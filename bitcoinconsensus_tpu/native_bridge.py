"""ctypes bridge to the native host core (`native/libnat.so`).

SURVEY §7 prescribes a native host consensus core around the TPU crypto
backend; this module is its loader + typed surface. The library is built
on demand from the checked-in C++ sources (single `g++ -shared` call, a
few seconds, cached by mtime) so the repo never carries a binary.

Native components surfaced here:
- `prep_lanes`: batched verify-lane preparation (structural pubkey parse,
  lax-DER, high-S normalize, batched s^-1 mod n, BIP340 challenge hash,
  GLV split, byte packing) — the TpuSecpVerifier host_prep/pack phases in
  one C call.
- `verify_ecdsa` / `verify_schnorr` / `tweak_add_check`: host-exact
  scalar verifies (fast fallback path; the pure-Python
  `crypto/secp_host.py` stays the executable spec they are tested
  against).
- `sha256` / `sha256d` / `tagged_hash` utilities.

Set BITCOINCONSENSUS_TPU_NATIVE=0 to disable (pure-Python paths remain
fully functional and consensus-exact).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["available", "lib", "prep_pack", "NativeSecp"]

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native"
)
_SO_PATH = os.path.join(_NATIVE_DIR, "libnat.so")
# Installed-package location: setup.py compiles the core into the wheel
# as bitcoinconsensus_tpu/_native/libnat.so (no source tree at runtime).
_PACKAGED_SO = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "_native", "libnat.so"
)
_SOURCES = ("nat.cpp", "secp.hpp", "sha256.hpp", "hash_extra.hpp", "interp.hpp", "eval.hpp", "block.hpp")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    srcs = [os.path.join(_NATIVE_DIR, s) for s in _SOURCES]
    if not all(os.path.exists(s) for s in srcs):
        return False
    if os.path.exists(_SO_PATH) and all(
        os.path.getmtime(_SO_PATH) >= os.path.getmtime(s) for s in srcs
    ):
        return True
    try:
        subprocess.run(
            [
                os.environ.get("CXX", "g++"),
                "-O3",
                "-std=c++17",
                "-fPIC",
                "-shared",
                os.path.join(_NATIVE_DIR, "nat.cpp"),
                "-o",
                _SO_PATH,
            ],
            check=True,
            capture_output=True,
            timeout=300,
        )
        return True
    except Exception:
        return False


def lib() -> Optional[ctypes.CDLL]:
    """The loaded library, or None (unbuildable / disabled)."""
    global _lib, _tried
    if os.environ.get("BITCOINCONSENSUS_TPU_NATIVE", "") in ("0", "off"):
        return None
    with _lock:
        if _tried:
            return _lib
        _tried = True
        # Explicit override first (the sanitizer gate points this at
        # libnat_san.so); then source checkout: (re)build from the
        # checked-in sources; then wheel install: the .so setup.py
        # compiled into the package.
        override = os.environ.get("BITCOINCONSENSUS_NAT_SO", "")
        if override:
            so = override
        elif _build():
            so = _SO_PATH
        elif os.path.exists(_PACKAGED_SO):
            so = _PACKAGED_SO
        else:
            return None
        try:
            L = ctypes.CDLL(so)
        except OSError:
            return None
        # ABI gate: a stale override/packaged .so with an older exported
        # surface (e.g. the pre-v4 recidx_data signature) must not load —
        # the typed prototypes below would mis-call it. Fall back to the
        # pure-Python paths instead.
        L.nat_version.restype = ctypes.c_int
        if L.nat_version() < 4:
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        L.nat_version.restype = ctypes.c_int
        L.nat_prep_lanes.argtypes = [
            u8p, i64p, i32p, ctypes.c_int32,
            u8p, i32p, i32p, i32p, i32p, i32p, i32p,
        ]
        L.nat_prep_lanes.restype = None
        L.nat_verify_ecdsa.argtypes = [u8p, ctypes.c_int64, u8p, ctypes.c_int64, u8p]
        L.nat_verify_ecdsa.restype = ctypes.c_int
        L.nat_verify_schnorr.argtypes = [u8p, u8p, u8p]
        L.nat_verify_schnorr.restype = ctypes.c_int
        L.nat_tweak_add_check.argtypes = [u8p, ctypes.c_int32, u8p, u8p]
        L.nat_tweak_add_check.restype = ctypes.c_int
        L.nat_murmur3_32.argtypes = [ctypes.c_uint32, u8p, ctypes.c_int64]
        L.nat_murmur3_32.restype = ctypes.c_uint32
        L.nat_sha256.argtypes = [u8p, ctypes.c_int64, u8p]
        L.nat_sha256d.argtypes = [u8p, ctypes.c_int64, u8p]
        L.nat_tagged_hash.argtypes = [u8p, ctypes.c_int64, u8p, ctypes.c_int64, u8p]
        # interpreter surface
        vp = ctypes.c_void_p
        L.nat_session_new.restype = vp
        L.nat_session_free.argtypes = [vp]
        L.nat_session_add_known.argtypes = [
            vp, ctypes.c_int32, ctypes.c_int32,
            u8p, ctypes.c_int64, u8p, ctypes.c_int64, u8p, ctypes.c_int64,
            ctypes.c_int32,
        ]
        L.nat_session_records_count.argtypes = [vp]
        L.nat_session_records_count.restype = ctypes.c_int32
        L.nat_session_records_meta.argtypes = [vp, i32p, i32p, i64p]
        L.nat_session_records_data.argtypes = [vp, u8p]
        L.nat_session_records_bytes.argtypes = [vp]
        L.nat_session_records_bytes.restype = ctypes.c_int64
        L.nat_tx_parse.argtypes = [u8p, ctypes.c_int64]
        L.nat_tx_parse.restype = vp
        L.nat_tx_free.argtypes = [vp]
        L.nat_tx_ser_size.argtypes = [vp]
        L.nat_tx_ser_size.restype = ctypes.c_int64
        L.nat_tx_n_inputs.argtypes = [vp]
        L.nat_tx_n_inputs.restype = ctypes.c_int32
        L.nat_tx_wtxid.argtypes = [vp, u8p]
        L.nat_tx_set_spent_outputs.argtypes = [vp, i64p, u8p, i64p, ctypes.c_int32]
        L.nat_tx_precompute.argtypes = [vp]
        L.nat_verify_input.argtypes = [
            vp, vp, ctypes.c_int32, ctypes.c_int64, u8p, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_int32, i32p, i32p,
        ]
        L.nat_verify_input.restype = ctypes.c_int32
        # batched surfaces (one C call per phase, not per input/check)
        L.nat_verify_inputs.argtypes = [
            vp, ctypes.POINTER(ctypes.c_void_p), i32p, i64p, u8p, i64p, i32p,
            ctypes.c_int32, ctypes.c_int32, i32p, i32p, i32p, i64p,
        ]
        L.nat_session_spec_count.argtypes = [vp]
        L.nat_session_spec_count.restype = ctypes.c_int32
        L.nat_session_spec_meta.argtypes = [vp, i32p, i32p, i64p]
        L.nat_session_spec_bytes.argtypes = [vp]
        L.nat_session_spec_bytes.restype = ctypes.c_int64
        L.nat_session_spec_data.argtypes = [vp, u8p]
        L.nat_session_add_known_batch.argtypes = [
            vp, ctypes.c_int32, i32p, u8p, i64p, i32p,
        ]
        L.nat_digest_checks.argtypes = [
            u8p, ctypes.c_int64, ctypes.c_int32, i32p, u8p, i64p, u8p,
        ]
        L.nat_digest_streams.argtypes = [
            u8p, ctypes.c_int64, ctypes.c_int32, i64p, i64p, u8p, u8p,
        ]
        # index-mode surface (session-resident uniq protocol)
        L.nat_verify_inputs_idx.argtypes = [
            vp, ctypes.POINTER(ctypes.c_void_p), i32p, i64p, u8p, i64p, i32p,
            ctypes.c_int32, ctypes.c_int32, i32p, i32p, i32p, i64p,
        ]
        L.nat_session_uniq_count.argtypes = [vp]
        L.nat_session_uniq_count.restype = ctypes.c_int32
        L.nat_session_recidx_data.argtypes = [vp, i32p, ctypes.c_int64]
        L.nat_session_recidx_data.restype = ctypes.c_int64
        L.nat_session_uniq_lanes.argtypes = [
            vp, i32p, ctypes.c_int32,
            u8p, i32p, i32p, i32p, i32p, i32p, i32p,
        ]
        L.nat_session_uniq_digests.argtypes = [
            vp, u8p, ctypes.c_int64, i32p, ctypes.c_int32, u8p,
        ]
        L.nat_session_publish_uniq.argtypes = [vp, i32p, ctypes.c_int32, i32p]
        L.nat_session_uniq_host_verify.argtypes = [vp, ctypes.c_int32]
        L.nat_session_uniq_host_verify.restype = ctypes.c_int32
        # block layer (native/block.hpp)
        L.nat_block_parse.argtypes = [u8p, ctypes.c_int64]
        L.nat_block_parse.restype = vp
        L.nat_block_free.argtypes = [vp]
        L.nat_block_n_tx.argtypes = [vp]
        L.nat_block_n_tx.restype = ctypes.c_int32
        L.nat_block_n_inputs.argtypes = [vp]
        L.nat_block_n_inputs.restype = ctypes.c_int32
        L.nat_block_tx.argtypes = [vp, ctypes.c_int32]
        L.nat_block_tx.restype = vp
        L.nat_block_txid.argtypes = [vp, ctypes.c_int32, u8p]
        L.nat_block_wtxid.argtypes = [vp, ctypes.c_int32, u8p]
        L.nat_block_check.argtypes = [vp, ctypes.c_int32, u8p, ctypes.c_int32]
        L.nat_block_check.restype = ctypes.c_int32
        L.nat_block_check_witness.argtypes = [vp]
        L.nat_block_check_witness.restype = ctypes.c_int32
        L.nat_block_accounting.argtypes = [vp, vp, ctypes.c_int64, ctypes.c_int32]
        L.nat_block_accounting.restype = ctypes.c_int32
        L.nat_block_acct_meta.argtypes = [vp, i64p, i64p, i64p, i64p]
        L.nat_block_acct_data.argtypes = [vp, i32p, i32p, i64p, i64p, u8p]
        L.nat_block_spent_digests.argtypes = [vp, u8p]
        L.nat_block_script_keys.argtypes = [
            vp, u8p, ctypes.c_int64, ctypes.c_int32, u8p,
        ]
        L.nat_view_new.restype = vp
        L.nat_view_free.argtypes = [vp]
        L.nat_view_clone.argtypes = [vp]
        L.nat_view_clone.restype = vp
        L.nat_view_len.argtypes = [vp]
        L.nat_view_len.restype = ctypes.c_int64
        L.nat_view_add_coins.argtypes = [
            vp, ctypes.c_int32, u8p, i32p, i64p, i32p, i32p, u8p, i64p,
        ]
        L.nat_view_get.argtypes = [vp, u8p, ctypes.c_int32, i64p, i32p, i32p, i64p]
        L.nat_view_get.restype = ctypes.c_int32
        L.nat_view_get_spk.argtypes = [vp, u8p, ctypes.c_int32, u8p]
        L.nat_view_spend.argtypes = [vp, u8p, ctypes.c_int32]
        L.nat_view_spend.restype = ctypes.c_int32
        L.nat_view_apply_block.argtypes = [vp, vp, ctypes.c_int64]
        _lib = L
        return _lib


def available() -> bool:
    return lib() is not None


def _u8p(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _i32p(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


_KIND_CODE = {"ecdsa": 0, "schnorr": 1, "tweak": 2}


def prep_pack(checks: Sequence, size: int):
    """Native _prep_lanes + _pack_lanes: returns the 7-tuple of padded
    arrays TpuSecpVerifier feeds the kernel, bit-identical to the Python
    packers (asserted by tests/test_native.py).

    `checks` are SigCheck-shaped (kind, data); `size >= len(checks)` is
    the padded batch size.
    """
    L = lib()
    assert L is not None
    n = len(checks)
    assert size >= n
    parts: List[bytes] = []
    offs = np.empty(3 * n + 1, dtype=np.int64)
    kinds = np.empty(n, dtype=np.int32)
    pos = 0
    for i, chk in enumerate(checks):
        d = chk.data
        if chk.kind == "tweak":
            # (tweaked32, parity, internal32, tweak32) ->
            # internal | tweak | tweaked, parity in the kind code
            p0, p1, p2 = d[2], d[3], d[0]
            kinds[i] = 2 | ((d[1] & 1) << 8)
        else:
            p0, p1, p2 = d[0], d[1], d[2]
            kinds[i] = _KIND_CODE[chk.kind]
        offs[3 * i] = pos
        offs[3 * i + 1] = pos + len(p0)
        offs[3 * i + 2] = pos + len(p0) + len(p1)
        pos += len(p0) + len(p1) + len(p2)
        parts.append(p0)
        parts.append(p1)
        parts.append(p2)
    offs[3 * n] = pos
    blob = np.frombuffer(b"".join(parts), dtype=np.uint8) if pos else np.zeros(
        1, dtype=np.uint8
    )

    fields = np.zeros((size, 4, 32), dtype=np.uint8)
    want_odd = np.zeros(size, dtype=np.int32)
    parity = np.full(size, -1, dtype=np.int32)
    has_t2 = np.zeros(size, dtype=np.int32)
    neg1 = np.zeros(size, dtype=np.int32)
    neg2 = np.zeros(size, dtype=np.int32)
    valid_i = np.zeros(size, dtype=np.int32)
    if n:
        L.nat_prep_lanes(
            _u8p(blob),
            offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            _i32p(kinds),
            n,
            _u8p(fields),
            _i32p(want_odd),
            _i32p(parity),
            _i32p(has_t2),
            _i32p(neg1),
            _i32p(neg2),
            _i32p(valid_i),
        )
    return fields, want_odd, parity, has_t2, neg1, neg2, valid_i != 0


_KIND_NAME = {0: "ecdsa", 1: "schnorr", 2: "tweak"}


def _pack_check_parts(checks: Sequence[Tuple[str, Tuple]]):
    """Flatten (kind, data) pairs into the (kinds, blob, offs) wire shape
    shared by add_known_batch / digest_checks: Record part order (ecdsa
    pubkey|sig|msg, schnorr pk32|sig64|msg, tweak q32|internal32|tweak32),
    tweak parity in kind bit 8."""
    n = len(checks)
    kinds = np.empty(n, dtype=np.int32)
    offs = np.empty(3 * n + 1, dtype=np.int64)
    parts: List[bytes] = []
    pos = 0
    for i, (kind, data) in enumerate(checks):
        if kind == "tweak":
            p0, p1, p2 = data[0], data[2], data[3]
            kinds[i] = 2 | ((int(data[1]) & 1) << 8)
        else:
            p0, p1, p2 = data
            kinds[i] = _KIND_CODE[kind]
        offs[3 * i] = pos
        offs[3 * i + 1] = pos + len(p0)
        offs[3 * i + 2] = pos + len(p0) + len(p1)
        pos += len(p0) + len(p1) + len(p2)
        parts.append(p0)
        parts.append(p1)
        parts.append(p2)
    offs[3 * n] = pos
    blob = (
        np.frombuffer(b"".join(parts), dtype=np.uint8)
        if pos
        else np.zeros(1, dtype=np.uint8)
    )
    return kinds, blob, offs


def digest_checks(salt: bytes, checks: Sequence[Tuple[str, Tuple]]) -> List[bytes]:
    """Batched salted cache-key digests, byte-identical to
    models/sigcache.py `_key(_parts(...))` (asserted by tests)."""
    L = lib()
    assert L is not None
    n = len(checks)
    if n == 0:
        return []
    kinds, blob, offs = _pack_check_parts(checks)
    salt_a = np.frombuffer(salt, dtype=np.uint8) if salt else np.zeros(1, np.uint8)
    out = np.zeros(32 * n, dtype=np.uint8)
    L.nat_digest_checks(
        _u8p(salt_a), len(salt), n, _i32p(kinds), _u8p(blob),
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), _u8p(out),
    )
    raw = out.tobytes()
    return [raw[32 * i : 32 * i + 32] for i in range(n)]


def digest_streams(salt: bytes, items: Sequence[Tuple[bytes, ...]]) -> List[bytes]:
    """Batched salted digests over arbitrary part lists, byte-identical to
    models/sigcache.py `_SaltedLRU._key` (asserted by tests)."""
    L = lib()
    assert L is not None
    n = len(items)
    if n == 0:
        return []
    bounds = np.empty(n + 1, dtype=np.int64)
    bounds[0] = 0
    parts: List[bytes] = []
    for i, it in enumerate(items):
        parts.extend(it)
        bounds[i + 1] = len(parts)
    offs = np.empty(len(parts) + 1, dtype=np.int64)
    offs[0] = 0
    pos = 0
    for j, p in enumerate(parts):
        pos += len(p)
        offs[j + 1] = pos
    blob = (
        np.frombuffer(b"".join(parts), dtype=np.uint8)
        if pos
        else np.zeros(1, dtype=np.uint8)
    )
    salt_a = np.frombuffer(salt, dtype=np.uint8) if salt else np.zeros(1, np.uint8)
    out = np.zeros(32 * n, dtype=np.uint8)
    L.nat_digest_streams(
        _u8p(salt_a), len(salt), n,
        bounds.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        _u8p(blob), _u8p(out),
    )
    raw = out.tobytes()
    return [raw[32 * i : 32 * i + 32] for i in range(n)]


class NativeTx:
    """Parsed-transaction handle (native/interp.hpp NTx). Holds the wire
    parse and the tx-wide precomputed hash aggregates on the C++ side."""

    __slots__ = ("_ptr", "n_inputs", "ser_size", "_wtxid")

    def __init__(self, raw: bytes):
        L = lib()
        assert L is not None
        arr = np.frombuffer(raw, dtype=np.uint8) if raw else np.zeros(1, np.uint8)
        ptr = L.nat_tx_parse(_u8p(arr), len(raw))
        if not ptr:
            raise ValueError("tx deserialize failed")
        self._ptr = ptr
        self.n_inputs = int(L.nat_tx_n_inputs(ptr))
        self.ser_size = int(L.nat_tx_ser_size(ptr))
        self._wtxid: Optional[bytes] = None

    @property
    def wtxid(self) -> bytes:
        if self._wtxid is None:
            out = np.zeros(32, dtype=np.uint8)
            lib().nat_tx_wtxid(self._ptr, _u8p(out))
            self._wtxid = out.tobytes()
        return self._wtxid

    def __del__(self):
        try:
            L = lib()
        except TypeError:  # interpreter shutdown tore down module globals
            return
        if L is not None and getattr(self, "_ptr", None):
            L.nat_tx_free(self._ptr)
            self._ptr = None

    def set_spent_outputs(self, spent: Sequence[Tuple[int, bytes]]) -> None:
        L = lib()
        amounts = np.asarray([a for a, _ in spent], dtype=np.int64)
        offs = np.zeros(len(spent) + 1, dtype=np.int64)
        for i, (_, spk) in enumerate(spent):
            offs[i + 1] = offs[i] + len(spk)
        blob_b = b"".join(spk for _, spk in spent)
        blob = np.frombuffer(blob_b, dtype=np.uint8) if blob_b else np.zeros(
            1, np.uint8
        )
        L.nat_tx_set_spent_outputs(
            self._ptr,
            amounts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            _u8p(blob),
            offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(spent),
        )

    def precompute(self) -> None:
        lib().nat_tx_precompute(self._ptr)


class NativeSession:
    """Deferral session (oracle map + per-call check records)."""

    __slots__ = ("_ptr",)

    MODE_DEFER = 0
    MODE_EXACT = 1

    def __init__(self):
        L = lib()
        assert L is not None
        self._ptr = L.nat_session_new()

    def __del__(self):
        try:
            L = lib()
        except TypeError:  # interpreter shutdown tore down module globals
            return
        if L is not None and getattr(self, "_ptr", None):
            L.nat_session_free(self._ptr)
            self._ptr = None

    def add_known(self, kind: str, data: Tuple, result: bool) -> None:
        """Publish one resolved check into the native oracle; key layout
        matches models/batch.py's `known` dict keys."""
        L = lib()
        if kind == "tweak":
            p0, parity, p1, p2 = data[0], int(data[1]), data[2], data[3]
            kcode = 2
        else:
            p0, p1, p2 = data
            parity = 0
            kcode = 0 if kind == "ecdsa" else 1
        a = np.frombuffer(p0, np.uint8) if p0 else np.zeros(1, np.uint8)
        b = np.frombuffer(p1, np.uint8) if p1 else np.zeros(1, np.uint8)
        c = np.frombuffer(p2, np.uint8) if p2 else np.zeros(1, np.uint8)
        L.nat_session_add_known(
            self._ptr, kcode, parity & 1,
            _u8p(a), len(p0), _u8p(b), len(p1), _u8p(c), len(p2),
            1 if result else 0,
        )

    def _drain(self, count_fn, meta_fn, bytes_fn, data_fn) -> List[Tuple[str, Tuple]]:
        n = int(count_fn(self._ptr))
        if n == 0:
            return []
        kinds = np.zeros(n, dtype=np.int32)
        parities = np.zeros(n, dtype=np.int32)
        lens = np.zeros(3 * n, dtype=np.int64)
        meta_fn(
            self._ptr, _i32p(kinds), _i32p(parities),
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        total = int(bytes_fn(self._ptr))
        blob = np.zeros(max(total, 1), dtype=np.uint8)
        data_fn(self._ptr, _u8p(blob))
        raw = blob.tobytes()
        out: List[Tuple[str, Tuple]] = []
        pos = 0
        for i in range(n):
            l0, l1, l2 = int(lens[3 * i]), int(lens[3 * i + 1]), int(lens[3 * i + 2])
            p0 = raw[pos : pos + l0]
            p1 = raw[pos + l0 : pos + l0 + l1]
            p2 = raw[pos + l0 + l1 : pos + l0 + l1 + l2]
            pos += l0 + l1 + l2
            kind = _KIND_NAME[int(kinds[i])]
            if kind == "tweak":
                out.append((kind, (p0, int(parities[i]), p1, p2)))
            else:
                out.append((kind, (p0, p1, p2)))
        return out

    def take_records(self) -> List[Tuple[str, Tuple]]:
        """Drain the records of the last verify_input(s) call as
        (kind, data) tuples shaped exactly like SigCheck.data."""
        L = lib()
        return self._drain(
            L.nat_session_records_count, L.nat_session_records_meta,
            L.nat_session_records_bytes, L.nat_session_records_data,
        )

    def take_spec(self) -> List[Tuple[str, Tuple]]:
        """Drain the speculative CHECKMULTISIG pairings accumulated by
        deferring verifies (cleared on drain; the seen-set persists so a
        later re-interpretation never re-emits one)."""
        L = lib()
        return self._drain(
            L.nat_session_spec_count, L.nat_session_spec_meta,
            L.nat_session_spec_bytes, L.nat_session_spec_data,
        )

    def add_known_batch(
        self, entries: Sequence[Tuple[str, Tuple, bool]]
    ) -> None:
        """Publish many resolved checks in one C call."""
        L = lib()
        n = len(entries)
        if n == 0:
            return
        kinds, blob, offs = _pack_check_parts([(k, d) for k, d, _ in entries])
        results = np.fromiter(
            (1 if r else 0 for _, _, r in entries), dtype=np.int32, count=n
        )
        L.nat_session_add_known_batch(
            self._ptr, n, _i32p(kinds), _u8p(blob),
            offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), _i32p(results),
        )

    def verify_inputs(
        self,
        ntxs: Sequence[NativeTx],
        n_ins: Sequence[int],
        amounts: Sequence[int],
        script_pubkeys: Sequence[bytes],
        flags: Sequence[int],
        mode: int = MODE_DEFER,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, List[List[Tuple[str, Tuple]]]]:
        """Verify many inputs in ONE C call. Returns (ok, err, unknown)
        int32 arrays plus each input's recorded checks (SigCheck-shaped).
        Speculative records accumulate on the session; drain via take_spec."""
        L = lib()
        n = len(ntxs)
        assert n == len(n_ins) == len(amounts) == len(script_pubkeys) == len(flags)
        if n == 0:
            return (
                np.zeros(0, np.int32), np.zeros(0, np.int32),
                np.zeros(0, np.int32), [],
            )
        tx_ptrs = (ctypes.c_void_p * n)(*[t._ptr for t in ntxs])
        nin_a = np.asarray(n_ins, dtype=np.int32)
        amt_a = np.asarray(amounts, dtype=np.int64)
        flg_a = np.asarray(flags, dtype=np.int32)
        spk_offs = np.zeros(n + 1, dtype=np.int64)
        for i, spk in enumerate(script_pubkeys):
            spk_offs[i + 1] = spk_offs[i] + len(spk)
        blob_b = b"".join(script_pubkeys)
        blob = (
            np.frombuffer(blob_b, dtype=np.uint8)
            if blob_b
            else np.zeros(1, np.uint8)
        )
        ok = np.zeros(n, dtype=np.int32)
        err = np.zeros(n, dtype=np.int32)
        unk = np.zeros(n, dtype=np.int32)
        bounds = np.zeros(n + 1, dtype=np.int64)
        L.nat_verify_inputs(
            self._ptr, tx_ptrs, _i32p(nin_a),
            amt_a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), _u8p(blob),
            spk_offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            _i32p(flg_a), mode, n, _i32p(ok), _i32p(err), _i32p(unk),
            bounds.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        flat = self.take_records()
        per_input = [
            flat[int(bounds[i]) : int(bounds[i + 1])] for i in range(n)
        ]
        return ok, err, unk, per_input

    # --- Index-mode protocol (session-resident uniq checks) -----------
    # The fast batch driver: check bytes stay in C++; Python sees int32
    # indices into the session's deduped `uniq` list plus, on demand,
    # packed kernel lanes / salted digests computed in place.

    def verify_inputs_idx(
        self,
        ntxs: Sequence[NativeTx],
        n_ins: Sequence[int],
        amounts: Sequence[int],
        script_pubkeys: Sequence[bytes],
        flags: Sequence[int],
        n_threads: int = 1,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Deferring interpretation of many inputs in ONE C call,
        optionally sharded across `n_threads` worker threads (the
        checkqueue.h:29-163 fan-out axis; the GIL is released for the
        duration). Returns (ok, err, unknown, rec_idx, rec_bounds):
        input i's oracle misses are uniq indices
        rec_idx[rec_bounds[i]:rec_bounds[i+1]]."""
        L = lib()
        n = len(ntxs)
        if n == 0:
            z32 = np.zeros(0, np.int32)
            return z32, z32, z32, z32, np.zeros(1, np.int64)
        tx_ptrs = (ctypes.c_void_p * n)(*[t._ptr for t in ntxs])
        nin_a = np.asarray(n_ins, dtype=np.int32)
        amt_a = np.asarray(amounts, dtype=np.int64)
        flg_a = np.asarray(flags, dtype=np.int32)
        spk_offs = np.zeros(n + 1, dtype=np.int64)
        for i, spk in enumerate(script_pubkeys):
            spk_offs[i + 1] = spk_offs[i] + len(spk)
        blob_b = b"".join(script_pubkeys)
        blob = (
            np.frombuffer(blob_b, dtype=np.uint8)
            if blob_b
            else np.zeros(1, np.uint8)
        )
        return self._run_idx(tx_ptrs, nin_a, amt_a, blob, spk_offs, flg_a,
                             n, n_threads)

    def verify_inputs_idx_raw(
        self,
        tx_ptrs: Sequence,
        n_ins: np.ndarray,
        amounts: np.ndarray,
        spk_blob: np.ndarray,
        spk_offs: np.ndarray,
        flags: np.ndarray,
        n_threads: int = 1,
    ):
        """Array-native variant of verify_inputs_idx: the scriptPubKeys
        arrive as one (blob, offs) pair — zero copies when the caller
        already holds the block accounting's arrays (models/validate.py
        _connect_block_native). `tx_ptrs` are raw NTx pointers."""
        n = len(tx_ptrs)
        if n == 0:
            z32 = np.zeros(0, np.int32)
            return z32, z32, z32, z32, np.zeros(1, np.int64)
        ptrs = (ctypes.c_void_p * n)(*tx_ptrs)
        nin_a = np.ascontiguousarray(n_ins, dtype=np.int32)
        amt_a = np.ascontiguousarray(amounts, dtype=np.int64)
        flg_a = np.ascontiguousarray(flags, dtype=np.int32)
        offs_a = np.ascontiguousarray(spk_offs, dtype=np.int64)
        blob = (
            np.ascontiguousarray(spk_blob, dtype=np.uint8)
            if len(spk_blob)
            else np.zeros(1, np.uint8)
        )
        return self._run_idx(ptrs, nin_a, amt_a, blob, offs_a, flg_a, n,
                             n_threads)

    def _run_idx(self, tx_ptrs, nin_a, amt_a, blob, spk_offs, flg_a, n,
                 n_threads):
        L = lib()
        ok = np.zeros(n, dtype=np.int32)
        err = np.zeros(n, dtype=np.int32)
        unk = np.zeros(n, dtype=np.int32)
        bounds = np.zeros(n + 1, dtype=np.int64)
        L.nat_verify_inputs_idx(
            self._ptr, tx_ptrs, _i32p(nin_a),
            amt_a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), _u8p(blob),
            spk_offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            _i32p(flg_a), n, int(n_threads), _i32p(ok), _i32p(err),
            _i32p(unk),
            bounds.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        n_idx = int(bounds[n])
        rec_idx = np.zeros(max(n_idx, 1), dtype=np.int32)
        if n_idx:
            got = int(L.nat_session_recidx_data(self._ptr, _i32p(rec_idx), n_idx))
            if got != n_idx:  # concurrent session mutation or ABI skew
                raise RuntimeError(f"recidx_data short copy: {got} != {n_idx}")
        return ok, err, unk, rec_idx[:n_idx], bounds

    def uniq_count(self) -> int:
        return int(lib().nat_session_uniq_count(self._ptr))

    def uniq_lanes(self, idxs: np.ndarray, size: int):
        """Packed kernel lanes for the uniq entries `idxs`, padded to
        `size` — the session-resident twin of prep_pack."""
        L = lib()
        n = len(idxs)
        assert size >= n
        idx_a = np.ascontiguousarray(idxs, dtype=np.int32)
        fields = np.zeros((size, 4, 32), dtype=np.uint8)
        want_odd = np.zeros(size, dtype=np.int32)
        parity = np.full(size, -1, dtype=np.int32)
        has_t2 = np.zeros(size, dtype=np.int32)
        neg1 = np.zeros(size, dtype=np.int32)
        neg2 = np.zeros(size, dtype=np.int32)
        valid_i = np.zeros(size, dtype=np.int32)
        if n:
            L.nat_session_uniq_lanes(
                self._ptr, _i32p(idx_a), n, _u8p(fields), _i32p(want_odd),
                _i32p(parity), _i32p(has_t2), _i32p(neg1), _i32p(neg2),
                _i32p(valid_i),
            )
        return fields, want_odd, parity, has_t2, neg1, neg2, valid_i != 0

    def uniq_digests(self, salt: bytes, idxs: np.ndarray) -> np.ndarray:
        """(n, 32) uint8 salted cache-key digests for uniq entries
        `idxs`, computed in place (no check bytes cross the bridge)."""
        L = lib()
        n = len(idxs)
        out = np.zeros((max(n, 1), 32), dtype=np.uint8)
        if n:
            idx_a = np.ascontiguousarray(idxs, dtype=np.int32)
            salt_a = (
                np.frombuffer(salt, dtype=np.uint8)
                if salt
                else np.zeros(1, np.uint8)
            )
            L.nat_session_uniq_digests(
                self._ptr, _u8p(salt_a), len(salt), _i32p(idx_a), n, _u8p(out)
            )
        return out[:n]

    def publish_uniq(self, idxs: np.ndarray, results: np.ndarray) -> None:
        """Publish verdicts for uniq entries `idxs` into the native
        oracle (known map) without round-tripping check bytes."""
        L = lib()
        n = len(idxs)
        if n == 0:
            return
        idx_a = np.ascontiguousarray(idxs, dtype=np.int32)
        res_a = np.ascontiguousarray(results, dtype=np.int32)
        L.nat_session_publish_uniq(self._ptr, _i32p(idx_a), n, _i32p(res_a))

    def uniq_host_verify(self, idx: int) -> bool:
        """Exact native verdict for one uniq entry (exceptional-lane
        fixup)."""
        return bool(lib().nat_session_uniq_host_verify(self._ptr, int(idx)))

    def verify_input(
        self,
        ntx: NativeTx,
        n_in: int,
        amount: int,
        script_pubkey: bytes,
        flags: int,
        mode: int = MODE_DEFER,
    ) -> Tuple[bool, int, int]:
        """(ok, script_error_code, unknown_count); records via take_records."""
        L = lib()
        spk = (
            np.frombuffer(script_pubkey, np.uint8)
            if script_pubkey
            else np.zeros(1, np.uint8)
        )
        serr = np.zeros(1, dtype=np.int32)
        unk = np.zeros(1, dtype=np.int32)
        ok = L.nat_verify_input(
            self._ptr, ntx._ptr, n_in, amount, _u8p(spk), len(script_pubkey),
            flags, mode, _i32p(serr), _i32p(unk),
        )
        return bool(ok), int(serr[0]), int(unk[0])


# BlkReason code -> reference reject-reason string (native/block.hpp
# BlkReason order is part of the ABI; index = code).
BLOCK_REASONS = (
    None,
    "high-hash",
    "bad-txnmrklroot",
    "bad-txns-duplicate",
    "bad-blk-length",
    "bad-cb-missing",
    "bad-cb-multiple",
    "bad-txns-vin-empty",
    "bad-txns-vout-empty",
    "bad-txns-oversize",
    "bad-txns-vout-negative",
    "bad-txns-vout-toolarge",
    "bad-txns-txouttotal-toolarge",
    "bad-txns-inputs-duplicate",
    "bad-cb-length",
    "bad-txns-prevout-null",
    "bad-blk-sigops",
    "bad-witness-nonce-size",
    "bad-witness-merkle-match",
    "unexpected-witness",
    "bad-txns-BIP30",
    "bad-txns-inputs-missingorspent",
    "bad-txns-premature-spend-of-coinbase",
    "bad-txns-inputvalues-outofrange",
    "bad-txns-in-belowout",
    "bad-txns-fee-outofrange",
    "bad-cb-amount",
    "block-deserialize-failed",
)


class NativeBlockTx:
    """Borrowed tx handle inside a NativeBlock (NOT freed on __del__ —
    the block owns it; the `_blk` backref keeps the owner alive for the
    handle's lifetime). Duck-compatible with NativeTx where the batch
    drivers need it (._ptr, .n_inputs, .ser_size, .wtxid)."""

    __slots__ = ("_ptr", "_blk", "n_inputs", "ser_size", "_wtxid", "_index",
                 "__weakref__")

    def __init__(self, blk: "NativeBlock", index: int, ptr):
        L = lib()
        self._blk = blk  # keeps the owning block alive
        self._index = index
        self._ptr = ptr
        self.n_inputs = int(L.nat_tx_n_inputs(ptr))
        self.ser_size = int(L.nat_tx_ser_size(ptr))
        self._wtxid: Optional[bytes] = None

    @property
    def wtxid(self) -> bytes:
        if self._wtxid is None:
            out = np.zeros(32, dtype=np.uint8)
            lib().nat_block_wtxid(self._blk._ptr, self._index, _u8p(out))
            self._wtxid = out.tobytes()
        return self._wtxid


class NativeBlock:
    """Parsed-block handle (native/block.hpp NBlock): header, txs, txids,
    and (after `accounting`) the per-input script-phase data."""

    __slots__ = ("_ptr", "n_tx", "n_inputs", "_txs")

    def __init__(self, raw: bytes):
        L = lib()
        assert L is not None
        arr = np.frombuffer(raw, dtype=np.uint8) if raw else np.zeros(1, np.uint8)
        ptr = L.nat_block_parse(_u8p(arr), len(raw))
        if not ptr:
            raise ValueError("block deserialize failed")
        self._ptr = ptr
        self.n_tx = int(L.nat_block_n_tx(ptr))
        self.n_inputs = int(L.nat_block_n_inputs(ptr))
        # weak values: a NativeBlockTx strongly refs its block, so a
        # strong cache here would form a cycle only cycle-GC could free —
        # and the block pipeline runs under gc_paused(). Weak entries die
        # with their last external ref; recreation is two C calls.
        import weakref

        self._txs = weakref.WeakValueDictionary()

    def __del__(self):
        try:
            L = lib()
        except TypeError:  # interpreter shutdown tore down module globals
            return
        if L is not None and getattr(self, "_ptr", None):
            L.nat_block_free(self._ptr)
            self._ptr = None

    def __deepcopy__(self, memo):
        # A deep copy would duplicate the raw C++ pointer and double-free;
        # the handle is a drop-on-copy cache (models/validate.py re-parses).
        return None

    def __reduce__(self):
        raise TypeError("NativeBlock handles are not picklable")

    def tx(self, i: int) -> NativeBlockTx:
        t = self._txs.get(i)
        if t is None:
            ptr = lib().nat_block_tx(self._ptr, i)
            assert ptr, i
            t = self._txs[i] = NativeBlockTx(self, i, ptr)
        return t

    def txid(self, i: int) -> bytes:
        out = np.zeros(32, dtype=np.uint8)
        lib().nat_block_txid(self._ptr, i, _u8p(out))
        return out.tobytes()

    def wtxid(self, i: int) -> bytes:
        out = np.zeros(32, dtype=np.uint8)
        lib().nat_block_wtxid(self._ptr, i, _u8p(out))
        return out.tobytes()

    def check(self, check_pow: bool, pow_limit: int, check_merkle: bool = True
              ) -> Optional[str]:
        """Context-free CheckBlock; returns a reject reason or None."""
        limit = np.frombuffer(pow_limit.to_bytes(32, "big"), dtype=np.uint8)
        code = lib().nat_block_check(
            self._ptr, 1 if check_pow else 0, _u8p(limit),
            1 if check_merkle else 0,
        )
        return BLOCK_REASONS[code]

    def check_witness_commitment(self) -> Optional[str]:
        return BLOCK_REASONS[lib().nat_block_check_witness(self._ptr)]

    def accounting(self, view: "NativeCoinsView", height: int, flags: int):
        """ConnectBlock accounting (BIP30, existence/maturity/values, fees,
        sigop budget) + per-input script-phase data + per-tx hash
        precompute. Returns (reason|None, fees, sigop_cost, tx_index,
        n_in, amounts, spk_offs, spk_blob) — arrays one entry per
        non-coinbase input, in block order."""
        L = lib()
        code = L.nat_block_accounting(self._ptr, view._ptr, height, flags)
        fees = np.zeros(1, np.int64)
        sigops = np.zeros(1, np.int64)
        n_in_total = np.zeros(1, np.int64)
        spk_bytes = np.zeros(1, np.int64)
        i64c = ctypes.POINTER(ctypes.c_int64)
        L.nat_block_acct_meta(
            self._ptr, fees.ctypes.data_as(i64c), sigops.ctypes.data_as(i64c),
            n_in_total.ctypes.data_as(i64c), spk_bytes.ctypes.data_as(i64c),
        )
        if code != 0:
            return (BLOCK_REASONS[code], int(fees[0]), int(sigops[0])) + (None,) * 5
        n = int(n_in_total[0])
        tx_index = np.zeros(max(n, 1), np.int32)
        n_in = np.zeros(max(n, 1), np.int32)
        amounts = np.zeros(max(n, 1), np.int64)
        spk_offs = np.zeros(n + 1, np.int64)
        spk_blob = np.zeros(max(int(spk_bytes[0]), 1), np.uint8)
        L.nat_block_acct_data(
            self._ptr, _i32p(tx_index), _i32p(n_in),
            amounts.ctypes.data_as(i64c), spk_offs.ctypes.data_as(i64c),
            _u8p(spk_blob),
        )
        return (None, int(fees[0]), int(sigops[0]), tx_index[:n], n_in[:n],
                amounts[:n], spk_offs, spk_blob)

    def spent_digests(self) -> np.ndarray:
        """(n_tx, 32) per-tx spent-output digests (coinbase rows zero);
        valid after a successful accounting() call."""
        out = np.zeros((self.n_tx, 32), dtype=np.uint8)
        lib().nat_block_spent_digests(self._ptr, _u8p(out))
        return out

    def script_keys(self, salt: bytes, flags: int) -> np.ndarray:
        """(n_inputs, 32) script-execution-cache keys for every
        non-coinbase input (byte-identical to ScriptExecutionCache
        `_key(_parts(...))`; valid after a successful accounting())."""
        out = np.zeros((self.n_inputs, 32), dtype=np.uint8)
        salt_a = (
            np.frombuffer(salt, dtype=np.uint8) if salt else np.zeros(1, np.uint8)
        )
        lib().nat_block_script_keys(
            self._ptr, _u8p(salt_a), len(salt), flags, _u8p(out)
        )
        return out


class NativeCoinsView:
    """Native UTXO set (native/block.hpp NView) with the models/validate.py
    CoinsView duck API plus batch insert and O(1) clone."""

    __slots__ = ("_ptr",)

    def __init__(self, _ptr=None):
        if _ptr is None:
            L = lib()
            assert L is not None
            _ptr = L.nat_view_new()
        self._ptr = _ptr

    def __del__(self):
        try:
            L = lib()
        except TypeError:
            return
        if L is not None and getattr(self, "_ptr", None):
            L.nat_view_free(self._ptr)
            self._ptr = None

    def clone(self) -> "NativeCoinsView":
        return NativeCoinsView(lib().nat_view_clone(self._ptr))

    def __deepcopy__(self, memo) -> "NativeCoinsView":
        return self.clone()

    def __len__(self) -> int:
        return int(lib().nat_view_len(self._ptr))

    def add_coins_batch(self, coins) -> None:
        """coins: sequence of (txid32, n, value, height, coinbase, spk)."""
        L = lib()
        n = len(coins)
        if n == 0:
            return
        txids = np.frombuffer(
            b"".join(c[0] for c in coins), dtype=np.uint8
        )
        ns = np.asarray([c[1] for c in coins], dtype=np.int32)
        values = np.asarray([c[2] for c in coins], dtype=np.int64)
        heights = np.asarray([c[3] for c in coins], dtype=np.int32)
        cbs = np.asarray([1 if c[4] else 0 for c in coins], dtype=np.int32)
        offs = np.zeros(n + 1, dtype=np.int64)
        for i, c in enumerate(coins):
            offs[i + 1] = offs[i] + len(c[5])
        blob_b = b"".join(c[5] for c in coins)
        blob = (
            np.frombuffer(blob_b, dtype=np.uint8)
            if blob_b
            else np.zeros(1, np.uint8)
        )
        L.nat_view_add_coins(
            self._ptr, n, _u8p(txids), _i32p(ns),
            values.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            _i32p(heights), _i32p(cbs), _u8p(blob),
            offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )

    # CoinsView duck API (models/validate.py) -------------------------
    def add(self, outpoint, coin) -> None:
        self.add_coins_batch(
            [(outpoint.hash, outpoint.n, coin.out.value, coin.height,
              coin.coinbase, coin.out.script_pubkey)]
        )

    def add_tx(self, tx, height: int) -> None:
        cb = tx.is_coinbase()
        self.add_coins_batch(
            [(tx.txid, n, out.value, height, cb, out.script_pubkey)
             for n, out in enumerate(tx.vout)]
        )

    def get(self, outpoint):
        L = lib()
        txid = np.frombuffer(outpoint.hash, dtype=np.uint8)
        value = np.zeros(1, np.int64)
        height = np.zeros(1, np.int32)
        cb = np.zeros(1, np.int32)
        spk_len = np.zeros(1, np.int64)
        i64c = ctypes.POINTER(ctypes.c_int64)
        found = L.nat_view_get(
            self._ptr, _u8p(txid), outpoint.n, value.ctypes.data_as(i64c),
            _i32p(height), _i32p(cb), spk_len.ctypes.data_as(i64c),
        )
        if not found:
            return None
        spk = np.zeros(max(int(spk_len[0]), 1), np.uint8)
        L.nat_view_get_spk(self._ptr, _u8p(txid), outpoint.n, _u8p(spk))
        from .core.tx import TxOut
        from .models.validate import Coin

        return Coin(
            TxOut(int(value[0]), spk[: int(spk_len[0])].tobytes()),
            int(height[0]), bool(cb[0]),
        )

    def spend(self, outpoint):
        coin = self.get(outpoint)
        if coin is not None:
            txid = np.frombuffer(outpoint.hash, dtype=np.uint8)
            lib().nat_view_spend(self._ptr, _u8p(txid), outpoint.n)
        return coin

    def apply_block(self, blk: NativeBlock, height: int) -> None:
        lib().nat_view_apply_block(self._ptr, blk._ptr, height)


class NativeSecp:
    """Object surface over the native single-check verifies (drop-in for
    the secp_host functions where a fast host-exact answer is wanted)."""

    @staticmethod
    def verify_ecdsa(pubkey: bytes, sig_der: bytes, msg32: bytes) -> bool:
        L = lib()
        assert L is not None and len(msg32) == 32
        pk = np.frombuffer(pubkey, dtype=np.uint8) if pubkey else np.zeros(1, np.uint8)
        sg = np.frombuffer(sig_der, dtype=np.uint8) if sig_der else np.zeros(1, np.uint8)
        ms = np.frombuffer(msg32, dtype=np.uint8)
        return bool(
            L.nat_verify_ecdsa(_u8p(pk), len(pubkey), _u8p(sg), len(sig_der), _u8p(ms))
        )

    @staticmethod
    def verify_schnorr(pubkey32: bytes, sig64: bytes, msg32: bytes) -> bool:
        L = lib()
        assert L is not None
        if len(pubkey32) != 32 or len(sig64) != 64 or len(msg32) != 32:
            return False
        a = np.frombuffer(pubkey32, dtype=np.uint8)
        b = np.frombuffer(sig64, dtype=np.uint8)
        c = np.frombuffer(msg32, dtype=np.uint8)
        return bool(L.nat_verify_schnorr(_u8p(a), _u8p(b), _u8p(c)))

    @staticmethod
    def tweak_add_check(
        tweaked32: bytes, parity: int, internal32: bytes, tweak32: bytes
    ) -> bool:
        L = lib()
        assert L is not None
        if len(tweaked32) != 32 or len(internal32) != 32 or len(tweak32) != 32:
            return False
        a = np.frombuffer(tweaked32, dtype=np.uint8)
        b = np.frombuffer(internal32, dtype=np.uint8)
        c = np.frombuffer(tweak32, dtype=np.uint8)
        return bool(L.nat_tweak_add_check(_u8p(a), parity & 1, _u8p(b), _u8p(c)))
