"""Batched TPU signature verification: one kernel for ECDSA, Schnorr, taproot.

The reference verifies one signature per call on one core
(`secp256k1_ecdsa_verify`, `secp256k1/src/secp256k1.c:423`;
`secp256k1_schnorrsig_verify`, `modules/schnorrsig/main_impl.h:190`;
`secp256k1_xonly_pubkey_tweak_add_check`, `modules/extrakeys/main_impl.h:109`).
All three reduce to the same algebra — compute R = a·G + b·P and compare R
against a target — so this backend folds a *mixed* batch of all three check
kinds into ONE device dispatch of the `double_scalar_mult` kernel:

    kind      a        b      P            accept
    ECDSA     m/s      r/s    pubkey       R.x ≡ r (mod n)      [x==r or x==r+n]
    Schnorr   s        n-e    lift_x(pk)   R.x == r and even(R.y)
    tweak     t        1      lift_x(pki)  R.x == out_x and parity(R.y) matches

Host-side prep (byte parsing, lax-DER, batched modular inverse of s, BIP340
challenge hashes) is branchy and tiny; device-side is the uniform 256-bit
double-and-add — the split the SURVEY §7 architecture prescribes. Lanes that
fail host-side structural checks get a dummy point and a False mask; the
per-lane accept targets use a sentinel (p itself, never produced by a
canonical field element) to encode "no secondary target".

Batches are padded to the next power of two (>= 8) so jit caches a handful
of shapes. Results are bit-identical to the host oracle
(`crypto/secp_host.py`), which is itself differentially tested against the
consensus vectors.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..utils.hashes import tagged_hash
from ..ops.limbs import (
    NLIMB,
    P_INT,
    fe_add,
    fe_canon,
    fe_is_zero,
    fe_mul,
    fe_sqr,
    fe_sqrt,
    fe_sub,
    int_to_limbs,
    ints_to_limbs_batch,
)
from ..ops.curve import G_X, G_Y, double_scalar_mult, jacobian_to_affine
from .secp_host import N, parse_der_lax

__all__ = ["SigCheck", "TpuSecpVerifier", "default_verifier"]

# Persistent XLA compilation cache: the verify kernel is large (a 256-step
# double-and-add body); caching makes every process after the first fast.
_CACHE_DIR = os.environ.get(
    "BITCOINCONSENSUS_TPU_CACHE", os.path.expanduser("~/.cache/bitcoinconsensus_tpu_xla")
)
try:  # pragma: no cover - depends on jax version/platform
    jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:
    pass


class SigCheck:
    """One deferred signature-algebra check (host-parsed, device-verified).

    kind: 'ecdsa'   -> data = (pubkey_bytes, sig_der_no_hashtype, msg32)
          'schnorr' -> data = (pubkey32, sig64, msg32)
          'tweak'   -> data = (tweaked32, parity, internal32, tweak32)
    """

    __slots__ = ("kind", "data")

    def __init__(self, kind: str, data: Tuple):
        assert kind in ("ecdsa", "schnorr", "tweak")
        self.kind = kind
        self.data = data


def _batch_inv_mod_n(vals: List[int]) -> List[int]:
    """Montgomery batch inversion mod the group order n (one modexp total)."""
    prefix = []
    acc = 1
    for v in vals:
        acc = acc * v % N
        prefix.append(acc)
    inv = pow(acc, N - 2, N)
    out = [0] * len(vals)
    for i in range(len(vals) - 1, -1, -1):
        out[i] = inv * (prefix[i - 1] if i else 1) % N
        inv = inv * vals[i] % N
    return out


_SENTINEL = P_INT  # never equals a canonical field element (< p)


class _Lane:
    __slots__ = ("valid", "a", "b", "px", "py", "want_odd", "t1", "t2", "parity")

    def __init__(self):
        # Invalid-lane defaults: 0·G + 0·G, impossible targets.
        self.valid = False
        self.a = 0
        self.b = 0
        self.px = G_X
        self.py = G_Y
        self.want_odd = -1  # -1: py holds the full y; 0/1: lift on device
        self.t1 = _SENTINEL
        self.t2 = _SENTINEL
        self.parity = -1  # -1: don't care


def _host_parse_pubkey(lane: _Lane, pubkey: bytes) -> bool:
    """Structural half of secp256k1_ec_pubkey_parse (eckey_impl.h): length,
    prefix, range and (for uncompressed forms) on-curve/hybrid checks. The
    expensive decompression square root runs on device (fe_sqrt)."""
    if len(pubkey) == 33 and pubkey[0] in (2, 3):
        x = int.from_bytes(pubkey[1:], "big")
        if x >= P_INT:
            return False
        lane.px = x
        lane.py = 0
        lane.want_odd = 1 if pubkey[0] == 3 else 0
        return True
    if len(pubkey) == 65 and pubkey[0] in (4, 6, 7):
        x = int.from_bytes(pubkey[1:33], "big")
        y = int.from_bytes(pubkey[33:], "big")
        if x >= P_INT or y >= P_INT:
            return False
        if (y * y - (x * x % P_INT * x + 7)) % P_INT != 0:
            return False
        if pubkey[0] == 6 and (y & 1):
            return False
        if pubkey[0] == 7 and not (y & 1):
            return False
        lane.px, lane.py, lane.want_odd = x, y, -1
        return True
    return False


def _prep_ecdsa(lane: _Lane, pubkey: bytes, sig_der: bytes, msg32: bytes):
    """Mirror of CPubKey::Verify host half (pubkey.cpp:191-207): parse
    pubkey, lax-DER parse, normalize S; u1/u2 are filled in later after the
    batched inversion. Returns s for the inversion batch, or None."""
    if not _host_parse_pubkey(lane, pubkey):
        return None
    rs = parse_der_lax(sig_der)
    if rs is None:
        return None
    r, s = rs
    if s > N // 2:
        s = N - s  # normalize high-S (pubkey.cpp:204)
    if r == 0 or s == 0:
        lane.want_odd = -1  # lane stays invalid; restore defaults
        lane.px, lane.py = G_X, G_Y
        return None
    lane.t1 = r
    lane.t2 = r + N if r + N < P_INT else _SENTINEL
    lane.valid = True
    return r, s, int.from_bytes(msg32, "big") % N


def _prep_schnorr(lane: _Lane, pubkey32: bytes, sig64: bytes, msg32: bytes):
    """BIP340 verify host half (modules/schnorrsig/main_impl.h:190-237)."""
    if len(pubkey32) != 32 or len(sig64) != 64:
        return
    px = int.from_bytes(pubkey32, "big")
    if px >= P_INT:
        return
    r = int.from_bytes(sig64[:32], "big")
    s = int.from_bytes(sig64[32:], "big")
    if r >= P_INT or s >= N:
        return
    e = int.from_bytes(
        tagged_hash("BIP0340/challenge", sig64[:32] + pubkey32 + msg32), "big"
    ) % N
    lane.px, lane.py = px, 0
    lane.want_odd = 0  # BIP340 lift_x: even y; device checks existence
    lane.a = s
    lane.b = (N - e) % N  # (n-e)·P = -e·P
    lane.t1 = r
    lane.parity = 0  # require even y
    lane.valid = True


def _prep_tweak(lane: _Lane, tweaked32: bytes, parity: int, internal32: bytes,
                tweak32: bytes):
    """Taproot commitment check host half (extrakeys/main_impl.h:109-129):
    Q = P_internal + t·G must equal (tweaked_x, parity)."""
    px = int.from_bytes(internal32, "big")
    if px >= P_INT:
        return
    t = int.from_bytes(tweak32, "big")
    if t >= N:
        return
    tx = int.from_bytes(tweaked32, "big")
    lane.px, lane.py = px, 0
    lane.want_odd = 0  # x-only internal key: even-y lift, device-checked
    lane.a = t
    lane.b = 1
    lane.t1 = tx if tx < P_INT else _SENTINEL
    lane.parity = parity & 1
    lane.valid = True


_SEVEN_LIMBS = int_to_limbs(7)


def _verify_kernel(a, b, px, py, want_odd, t1, t2, parity_req, valid):
    """Device side: decompress P where needed (fe_sqrt; the host only does
    structural parsing), then R = a·G + b·P and per-lane acceptance."""
    import jax.numpy as _jnp

    seven = _jnp.broadcast_to(_jnp.asarray(_SEVEN_LIMBS), px.shape).astype(px.dtype)
    rhs = fe_add(fe_mul(fe_sqr(px), px), seven)  # x^3 + 7
    ycand = fe_canon(fe_sqrt(rhs))
    sq_ok = fe_is_zero(fe_sub(fe_mul(ycand, ycand), rhs))
    odd = (ycand[..., 0] & 1) == 1
    yneg = fe_canon(fe_sub(_jnp.zeros_like(ycand), ycand))
    flip = odd != (want_odd == 1)
    ylift = _jnp.where(flip[..., None], yneg, ycand)
    need = want_odd >= 0
    py_eff = _jnp.where(need[..., None], ylift, py)
    valid = valid & (~need | sq_ok)
    X, Y, Z = double_scalar_mult(a, b, px, py_eff)
    x, y, inf = jacobian_to_affine(X, Y, Z)
    ok_x = jnp.all(x == t1, axis=-1) | jnp.all(x == t2, axis=-1)
    y_odd = (y[..., 0] & 1) == 1
    par_ok = (parity_req < 0) | (y_odd == (parity_req == 1))
    return valid & ~inf & ok_x & par_ok


class TpuSecpVerifier:
    """Batched verifier; pads to power-of-two batch shapes and jits once per
    shape (persistent XLA cache across processes)."""

    def __init__(self, min_batch: int = 8, max_batch: int = 1 << 16):
        self._kernel = jax.jit(_verify_kernel)
        self._min_batch = min_batch
        self._max_batch = max_batch

    def _pad(self, n: int) -> int:
        size = self._min_batch
        while size < n:
            size *= 2
        return size

    def verify_checks(self, checks: Sequence[SigCheck]) -> np.ndarray:
        """Verify a mixed batch; returns bool array aligned with `checks`."""
        if not checks:
            return np.zeros(0, dtype=bool)
        lanes = [_Lane() for _ in checks]
        ecdsa_pending = []  # (lane, r, s, m)
        for lane, chk in zip(lanes, checks):
            if chk.kind == "ecdsa":
                got = _prep_ecdsa(lane, *chk.data)
                if got is not None:
                    ecdsa_pending.append((lane, *got))
            elif chk.kind == "schnorr":
                _prep_schnorr(lane, *chk.data)
            else:
                _prep_tweak(lane, *chk.data)
        if ecdsa_pending:
            sinvs = _batch_inv_mod_n([s for _, _, s, _ in ecdsa_pending])
            for (lane, r, _s, m), sinv in zip(ecdsa_pending, sinvs):
                lane.a = m * sinv % N  # u1
                lane.b = r * sinv % N  # u2
        out = np.zeros(len(checks), dtype=bool)
        todo = [i for i, lane in enumerate(lanes) if lane.valid]
        if not todo:
            return out
        # Device dispatch (chunked at max_batch to bound memory).
        for start in range(0, len(todo), self._max_batch):
            idx = todo[start : start + self._max_batch]
            out[idx] = self._dispatch([lanes[i] for i in idx])
        return out

    def _dispatch(self, lanes: List[_Lane]) -> np.ndarray:
        n = len(lanes)
        size = self._pad(n)
        pad = size - n

        def fill(get, pad_value):
            return ints_to_limbs_batch(
                [get(lane) for lane in lanes] + [pad_value] * pad
            )

        a = fill(lambda l: l.a, 0)
        b = fill(lambda l: l.b, 0)
        px = fill(lambda l: l.px, G_X)
        py = fill(lambda l: l.py, G_Y)
        t1 = fill(lambda l: l.t1, _SENTINEL)
        t2 = fill(lambda l: l.t2, _SENTINEL)
        want_odd = np.fromiter(
            (lane.want_odd for lane in lanes), dtype=np.int32, count=n
        )
        want_odd = np.concatenate([want_odd, np.full(pad, -1, np.int32)])
        parity = np.fromiter((lane.parity for lane in lanes), np.int32, count=n)
        parity = np.concatenate([parity, np.full(pad, -1, np.int32)])
        valid = np.zeros(size, dtype=bool)
        valid[:n] = [lane.valid for lane in lanes]
        res = self._kernel(a, b, px, py, want_odd, t1, t2, parity, valid)
        return np.asarray(res)[:n]

    # Convenience single-check wrappers (used by tests/differential fuzzing).
    def verify_ecdsa(self, pubkey: bytes, sig_der: bytes, msg32: bytes) -> bool:
        return bool(self.verify_checks([SigCheck("ecdsa", (pubkey, sig_der, msg32))])[0])

    def verify_schnorr(self, pubkey32: bytes, sig64: bytes, msg32: bytes) -> bool:
        return bool(
            self.verify_checks([SigCheck("schnorr", (pubkey32, sig64, msg32))])[0]
        )

    def tweak_add_check(
        self, tweaked32: bytes, parity: int, internal32: bytes, tweak32: bytes
    ) -> bool:
        return bool(
            self.verify_checks(
                [SigCheck("tweak", (tweaked32, parity, internal32, tweak32))]
            )[0]
        )


_default: Optional[TpuSecpVerifier] = None


def default_verifier() -> TpuSecpVerifier:
    """Process-wide verifier (compiled kernels are shared via jit cache)."""
    global _default
    if _default is None:
        _default = TpuSecpVerifier()
    return _default
