"""Batched TPU signature verification: one kernel for ECDSA, Schnorr, taproot.

The reference verifies one signature per call on one core
(`secp256k1_ecdsa_verify`, `secp256k1/src/secp256k1.c:423`;
`secp256k1_schnorrsig_verify`, `modules/schnorrsig/main_impl.h:190`;
`secp256k1_xonly_pubkey_tweak_add_check`, `modules/extrakeys/main_impl.h:109`).
All three reduce to the same algebra — compute R = a·G + b·P and compare R
against a target — so this backend folds a *mixed* batch of all three check
kinds into ONE device program over `double_scalar_mult`:

    kind      a        b      P            accept
    ECDSA     m/s      r/s    pubkey       R.x ∈ {r, r+n} (mod p)
    Schnorr   s        n-e    lift_x(pk)   R.x == r and even(R.y)
    tweak     t        1      lift_x(pki)  R.x == out_x and parity matches

The host→device link, not device compute, is the scarce resource (the
device sits behind a narrow tunnel; one mixed batch is ~4k field muls per
lane on a VPU that does them in microseconds). Hence:

- **Byte-packed transfers**: each check ships as 4 x 32-byte fields
  (a, GLV-split |b1|‖|b2|, pubkey-x, target) + 6 flag ints — ~150 B/lane
  instead of ~500 B of pre-split limbs. Limb splitting, window-digit
  extraction, y-lifting (fe_sqrt), and the r+n secondary target all
  happen on device.
- **Pipelined chunk dispatch**: large batches go out in chunks whose
  transfers/compute overlap the host-side prep of the next chunk (JAX
  async dispatch); the per-roundtrip sync cost is paid once.

Host-side prep (byte parsing, lax-DER, batched modular inverse of s, BIP340
challenge hashes) is branchy and tiny; device-side is the uniform 256-bit
double-and-add — the split the SURVEY §7 architecture prescribes. Lanes
that fail host-side structural checks get dummy field values and a False
mask. Batches are padded to the next power of two (>= min_batch) so jit
caches a handful of shapes. Results are bit-identical to the host oracle
(`crypto/secp_host.py`), which is itself differentially tested against the
consensus vectors.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..obs import counter as _obs_counter
from ..obs import gauge as _obs_gauge
from ..utils.hashes import tagged_hash
from ..utils.gcpause import gc_paused
from ..utils.profiling import Phases
from ..ops.limbs import (
    MASK,
    NLIMB,
    P_INT,
    bytes_to_limbs,
    fe_add,
    fe_canon,
    fe_is_zero,
    fe_mul,
    fe_sqr,
    fe_sqrt,
    fe_sub,
    int_to_limbs,
)
from ..ops.curve import (
    G_X,
    G_Y,
    _GX_LIMBS,
    _GY_LIMBS,
    _digits128,
    double_scalar_mult_glv,
    jacobian_to_affine,
)
from ..ops.regions import named_region, region_scope
from .glv import split_lambda
from .secp_host import N, parse_der_lax
from ..resilience import degrade as _degrade
from ..resilience import faults as _faults
from ..resilience import guards as _guards
from ..resilience import inflight as _inflight

__all__ = ["SigCheck", "TpuSecpVerifier", "default_verifier"]

_CONFIG_ERRORS = _obs_counter(
    "consensus_backend_config_errors_total",
    "backend/config setup steps that failed and were skipped",
    ("step",),
)

# Persistent XLA compilation cache: the verify kernel is a large traced
# program; caching makes every process after the first fast.
_CACHE_DIR = os.environ.get(
    "BITCOINCONSENSUS_TPU_CACHE", os.path.expanduser("~/.cache/bitcoinconsensus_tpu_xla")
)
try:  # pragma: no cover - depends on jax version/platform
    jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except (AttributeError, KeyError, ValueError, TypeError):
    # An old/new jax may not know these keys; running without the
    # persistent cache is slow-but-correct. Never silent, though: a
    # backend-selection fault must be visible in the telemetry.
    _CONFIG_ERRORS.inc(step="compilation_cache")

# Device-dispatch telemetry (README "Observability"). All host-side: these
# run in the driver around `jit` calls, never inside a traced program, so
# the analysis determinism gate sees identical kernel jaxprs.
_CHECKS_TOTAL = _obs_counter(
    "consensus_checks_total", "deferred curve checks by kind", ("kind",)
)
_DISPATCH_TOTAL = _obs_counter(
    "consensus_dispatch_total", "device dispatches by backend", ("backend",)
)
_DISPATCH_LANES = _obs_counter(
    "consensus_dispatch_lanes_total", "real (unpadded) lanes dispatched"
)
_DISPATCH_PADDED = _obs_counter(
    "consensus_dispatch_padded_lanes_total",
    "padded lanes dispatched (pad ladder fill)",
)
_DISPATCH_FILL = _obs_gauge(
    "consensus_dispatch_fill_ratio",
    "real/padded lane ratio of the most recent dispatch",
)
_NEW_SHAPES = _obs_counter(
    "consensus_dispatch_new_shapes_total",
    "distinct padded dispatch shapes this process (each is one jit "
    "compile or persistent-cache load)",
)
_HOST_FIXUPS = _obs_counter(
    "consensus_host_fixup_total",
    "exceptional device lanes resolved exactly on host",
)


class SigCheck:
    """One deferred signature-algebra check (host-parsed, device-verified).

    kind: 'ecdsa'   -> data = (pubkey_bytes, sig_der_no_hashtype, msg32)
          'schnorr' -> data = (pubkey32, sig64, msg32)
          'tweak'   -> data = (tweaked32, parity, internal32, tweak32)
    """

    __slots__ = ("kind", "data")

    def __init__(self, kind: str, data: Tuple):
        assert kind in ("ecdsa", "schnorr", "tweak")
        self.kind = kind
        self.data = data


def _batch_inv_mod_n(vals: List[int]) -> List[int]:
    """Montgomery batch inversion mod the group order n (one modexp total)."""
    prefix = []
    acc = 1
    for v in vals:
        acc = acc * v % N
        prefix.append(acc)
    inv = pow(acc, N - 2, N)
    out = [0] * len(vals)
    for i in range(len(vals) - 1, -1, -1):
        out[i] = inv * (prefix[i - 1] if i else 1) % N
        inv = inv * vals[i] % N
    return out


class _Lane:
    """Host-parsed check, ready for byte packing.

    a: fixed-base scalar (< n); the variable-base scalar b ships GLV-split
    as (|b1|, |b2|, neg1, neg2) with |bi| < 2^128 (`crypto/glv.py` —
    halves the doubling count on device). px: the point's x coordinate;
    want_odd: parity of the y lift (valid pubkeys always resolve to a
    parity — uncompressed keys are curve-checked on host, so y is
    recomputable from its parity); t1: the x-coordinate target; has_t2
    marks the ECDSA r+n secondary target (only when r + n < p);
    parity_req constrains R.y parity (-1 don't care / 0 even / 1 odd).
    """

    __slots__ = (
        "valid", "a", "b1", "b2", "neg1", "neg2", "px", "want_odd", "t1",
        "has_t2", "parity",
    )

    def __init__(self):
        self.valid = False
        self.a = 0
        self.b1 = 0
        self.b2 = 0
        self.neg1 = 0
        self.neg2 = 0
        self.px = G_X
        self.want_odd = 0
        self.t1 = 0
        self.has_t2 = 0
        self.parity = -1

    def set_b(self, b: int) -> None:
        self.b1, self.neg1, self.b2, self.neg2 = split_lambda(b)


def _host_parse_pubkey(lane: _Lane, pubkey: bytes) -> bool:
    """Structural half of secp256k1_ec_pubkey_parse (eckey_impl.h): length,
    prefix, range and (for uncompressed forms) on-curve/hybrid checks. The
    expensive decompression square root runs on device (fe_sqrt)."""
    if len(pubkey) == 33 and pubkey[0] in (2, 3):
        x = int.from_bytes(pubkey[1:], "big")
        if x >= P_INT:
            return False
        lane.px = x
        lane.want_odd = 1 if pubkey[0] == 3 else 0
        return True
    if len(pubkey) == 65 and pubkey[0] in (4, 6, 7):
        x = int.from_bytes(pubkey[1:33], "big")
        y = int.from_bytes(pubkey[33:], "big")
        if x >= P_INT or y >= P_INT:
            return False
        if (y * y - (x * x % P_INT * x + 7)) % P_INT != 0:
            return False
        if pubkey[0] == 6 and (y & 1):
            return False
        if pubkey[0] == 7 and not (y & 1):
            return False
        # y is on-curve, hence exactly the lift of its own parity: the
        # device recomputes it from (x, want_odd) — y itself never ships.
        lane.px, lane.want_odd = x, y & 1
        return True
    return False


def _prep_ecdsa(lane: _Lane, pubkey: bytes, sig_der: bytes, msg32: bytes):
    """Mirror of CPubKey::Verify host half (pubkey.cpp:191-207): parse
    pubkey, lax-DER parse, normalize S; u1/u2 are filled in later after the
    batched inversion. Returns (r, s, m) for the inversion batch, or None."""
    if not _host_parse_pubkey(lane, pubkey):
        return None
    rs = parse_der_lax(sig_der)
    if rs is None:
        return None
    r, s = rs
    if s > N // 2:
        s = N - s  # normalize high-S (pubkey.cpp:204)
    if r == 0 or s == 0:
        return None
    lane.t1 = r
    lane.has_t2 = 1 if r + N < P_INT else 0
    lane.valid = True
    return r, s, int.from_bytes(msg32, "big") % N


def _prep_schnorr(
    lane: _Lane, pubkey32: bytes, sig64: bytes, msg32: bytes,
    defer_challenge: bool = False,
):
    """BIP340 verify host half (modules/schnorrsig/main_impl.h:190-237).

    With `defer_challenge` the structural work happens here but the
    challenge hash is left to the caller (returns the (r32, px32, m32)
    triple to feed `ops/sha256.bip340_challenge` in one device batch;
    caller must then `lane.set_b((N - e) % N)`)."""
    if len(pubkey32) != 32 or len(sig64) != 64:
        return None
    px = int.from_bytes(pubkey32, "big")
    if px >= P_INT:
        return None
    r = int.from_bytes(sig64[:32], "big")
    s = int.from_bytes(sig64[32:], "big")
    if r >= P_INT or s >= N:
        return None
    lane.px = px
    lane.want_odd = 0  # BIP340 lift_x: even y; device checks existence
    lane.a = s
    lane.t1 = r
    lane.parity = 0  # require even R.y
    lane.valid = True
    if defer_challenge:
        return (sig64[:32], pubkey32, msg32)
    e = int.from_bytes(
        tagged_hash("BIP0340/challenge", sig64[:32] + pubkey32 + msg32), "big"
    ) % N
    lane.set_b((N - e) % N)  # (n-e)·P = -e·P
    return None


def _prep_tweak(lane: _Lane, tweaked32: bytes, parity: int, internal32: bytes,
                tweak32: bytes):
    """Taproot commitment check host half (extrakeys/main_impl.h:109-129):
    Q = P_internal + t·G must equal (tweaked_x, parity)."""
    px = int.from_bytes(internal32, "big")
    if px >= P_INT:
        return
    t = int.from_bytes(tweak32, "big")
    if t >= N:
        return
    tx = int.from_bytes(tweaked32, "big")
    lane.px = px
    lane.want_odd = 0  # x-only internal key: even-y lift, device-checked
    lane.a = t
    lane.set_b(1)
    # tx >= p can never equal a canonical x coordinate; the raw compare
    # below is False for such lanes with no sentinel machinery.
    lane.t1 = tx
    lane.parity = parity & 1
    lane.valid = True


_SEVEN_LIMBS = int_to_limbs(7)
_N_LIMBS = int_to_limbs(N)


def _verify_kernel(fields, want_odd, parity_req, has_t2, neg1, neg2, valid):
    """Device side of the mixed verify batch.

    fields: (B, 4, 32) uint8 — little-endian (a, |b1|‖|b2|, px, t1) per
    lane (the variable-base scalar arrives GLV-split: two 16-byte halves
    sharing field 1, signs in neg1/neg2). Unpacks to limb-major (20, B),
    lifts P's y from (px, want_odd) via fe_sqrt, runs
    R = a·G + (±b1 ± lambda·b2)·P with the GLV schedule, and accepts per
    lane: R.x == t1, or (has_t2) R.x == t1 + n, with optional R.y parity.

    Region scopes (`ops/regions.py`) split the program for device-time
    attribution: point_decode (unpack + y-lift + sanitize), scalar_mult
    (the GLV ladder, via its own decorator), verdict (affine + compare).
    They add zero ops — the provers see an identical jaxpr."""
    with region_scope("point_decode"):
        a = bytes_to_limbs(fields[:, 0])
        b1 = bytes_to_limbs(fields[:, 1, :16], nlimb=10)
        b2 = bytes_to_limbs(fields[:, 1, 16:], nlimb=10)
        px = bytes_to_limbs(fields[:, 2])
        t1 = bytes_to_limbs(fields[:, 3])

        seven = jnp.broadcast_to(
            jnp.asarray(_SEVEN_LIMBS).reshape(NLIMB, 1), px.shape
        ).astype(px.dtype)
        rhs = fe_add(fe_mul(fe_sqr(px), px), seven)  # x^3 + 7
        ycand = fe_canon(fe_sqrt(rhs))
        sq_ok = fe_is_zero(fe_sub(fe_mul(ycand, ycand), rhs))
        odd = (ycand[0] & 1) == 1
        yneg = fe_sub(jnp.zeros_like(ycand), ycand)  # weak rep is fine here
        flip = odd != (want_odd == 1)
        py = jnp.where(flip[None], yneg, ycand)
        valid = valid & sq_ok
        # Sanitize: invalid lanes (non-residue x — off-curve garbage) are
        # replaced by the generator so EVERY lane runs on-curve group math.
        # This keeps the explicitly-tracked infinity masks sound (off-curve
        # orbits obey no group law and could hit Z ≡ 0 unflagged, which
        # would zero the cross-lane batch-inversion product); the verdicts
        # of these lanes are masked by `valid` regardless.
        gxb = jnp.broadcast_to(
            jnp.asarray(_GX_LIMBS).reshape(NLIMB, 1), px.shape
        ).astype(px.dtype)
        gyb = jnp.broadcast_to(
            jnp.asarray(_GY_LIMBS).reshape(NLIMB, 1), px.shape
        ).astype(px.dtype)
        px = jnp.where(valid[None], px, gxb)
        py = jnp.where(valid[None], py, gyb)

    X, Y, Z, r_inf = double_scalar_mult_glv(
        a, _digits128(b1), _digits128(b2), neg1 == 1, neg2 == 1, px, py
    )

    with region_scope("verdict"):
        x, y, inf = jacobian_to_affine(X, Y, Z, inf=r_inf)

        nl = jnp.broadcast_to(
            jnp.asarray(_N_LIMBS).reshape(NLIMB, 1), t1.shape
        ).astype(t1.dtype)
        t1n = fe_canon(t1 + nl, bounds=[2 * MASK] * NLIMB)  # r+n (< p)
        ok_x = jnp.all(x == t1, axis=0) | (
            (has_t2 == 1) & jnp.all(x == t1n, axis=0)
        )
        y_odd = (y[0] & 1) == 1
        par_ok = (parity_req < 0) | (y_odd == (parity_req == 1))
        return valid & ~inf & ok_x & par_ok


@named_region("verdict_checksum")
def _verdict_checksum(ok):
    """Device-side verdict checksum: (count, position-weighted) int32 sums.

    Chained onto the still-async ok buffer as a *separate* tiny jitted
    program, so the proven verify kernels are untouched; the settle seam
    recomputes both sums host-side from the materialized buffer and any
    mismatch (a single-lane flip anywhere, a replayed buffer) demotes the
    ticket to the host oracle. Weights are i % 251 + 1, keeping the
    weighted sum < 252·B — int32-safe to ~8.5M lanes (registered with the
    interval prover as `jax_backend.verdict_checksum`).
    """
    v = ok.astype(jnp.int32)
    w = jnp.arange(v.shape[0], dtype=jnp.int32) % jnp.int32(
        _guards.CHECKSUM_MOD
    ) + jnp.int32(1)
    return jnp.sum(v), jnp.sum(v * w)


_checksum_jit = jax.jit(_verdict_checksum)


class TpuSecpVerifier:
    """Batched verifier; pads to power-of-two batch shapes and jits once per
    shape (persistent XLA cache across processes). Large batches are split
    into `chunk` -lane dispatches pipelined back-to-back.

    Two device backends, bit-identical results (tests/test_pallas_kernel.py):
    - XLA-traced kernel (`_verify_kernel`) — every platform; the only
      choice for small batches and the CPU mesh tests.
    - Pallas mega-kernel (`ops/pallas_kernel.verify_tiles`) — TPU batches
      of >= LANE_TILE lanes; the whole scalar-mult pipeline VMEM-resident.
    Selection is automatic (TPU + large batch); BITCOINCONSENSUS_TPU_PALLAS
    =0/1 forces it off/on.
    """

    def __init__(
        self,
        min_batch: int = 8,
        chunk: int = 1 << 13,
        pad_step: Optional[int] = None,
        device_challenge: Optional[bool] = None,
    ):
        """`pad_step`: cap the power-of-two pad ladder at the next multiple
        of this step (small batches still pad to the ladder). Every distinct
        padded shape compiles once (15-60 s for the pallas kernel), so a
        small step only pays off for a recurring batch size — e.g. a
        block-replay driver padding ~5.6k checks to 6144 (step 2048)
        instead of 8192 saves ~25% device time after the one-time compile.
        Must be a multiple of the 512-lane pallas tile (and min_batch a
        power of two times 512) or TPU dispatches silently fall back to the
        slower XLA kernel."""
        if pad_step is not None and (pad_step <= 0 or pad_step % 512 != 0):
            raise ValueError(
                "pad_step must be a positive multiple of the 512-lane tile"
            )
        # BIP340 challenges via the batched device SHA-256 (ops/sha256) in
        # the Python prep path; the native C++ prep hashes in-process (the
        # same midstate trick at memory speed), so this only matters when
        # the native core is absent — and pays when dispatch is cheap
        # (co-located chips / CPU backend), not across a high-RTT tunnel.
        if device_challenge is None:
            device_challenge = os.environ.get(
                "BITCOINCONSENSUS_TPU_DEVICE_SHA", ""
            ) in ("1", "on")
        self._device_challenge = bool(device_challenge)
        self._kernel = jax.jit(_verify_kernel)
        self._min_batch = min_batch
        self._chunk = chunk
        self._pad_step = pad_step
        env = os.environ.get("BITCOINCONSENSUS_TPU_PALLAS", "")
        if env in ("0", "off"):
            self._use_pallas = False
        elif env in ("1", "on"):
            self._use_pallas = True
        else:
            try:
                self._use_pallas = jax.default_backend() == "tpu"
            except Exception:  # pragma: no cover
                self._use_pallas = False
        # Native host core (SURVEY §7): lane prep + packing in one C call,
        # ~10x the Python packers. Bit-identical output (tests/test_native.py);
        # the Python path stays as spec and fallback.
        from .. import native_bridge

        self._native = native_bridge if native_bridge.available() else None
        # Set when a deferred exceptional-case lane (pallas fast-add flag)
        # resolved FALSE on the host — consumed by the sharded verdict.
        self._fixup_failed = False
        # Padded shapes this instance has dispatched: first sight of a
        # shape means one jit compile (or persistent-cache load).
        self._seen_shapes: set = set()
        self.phases = Phases()  # host_prep / pack / dispatch / sync
        # Fault containment (resilience/): retry budget + backend
        # quarantine ladder. `_dispatch_level` is the rung the in-flight
        # dispatch runs at (set around each _run_kernel call).
        self._resilience = _degrade.DispatchResilience(
            self._ladder_levels(), name=type(self).__name__
        )
        self._dispatch_level: Optional[str] = None
        # In-flight settlement queue (resilience/inflight.py): dispatch
        # returns tickets, settlement applies the guards/retry/ladder
        # policy. Depth bounds unsettled host state (backpressure);
        # deadline bounds how long a wedged ticket may retry before the
        # host oracle takes the lanes. The device-side verdict checksum
        # rides every dispatch unless explicitly disabled.
        self._checksum = os.environ.get(
            "BITCOINCONSENSUS_TPU_CHECKSUM", ""
        ) not in ("0", "off")
        self._inflight = _inflight.InflightQueue(
            self._resilience,
            self._SITE,
            launch=self._launch_ticket,
            materialize=self._materialize_guarded,
            prepare=self._prepare_ticket,
            on_device=self._on_device_settle,
            max_depth=int(os.environ.get(
                "BITCOINCONSENSUS_TPU_INFLIGHT_DEPTH", "4")),
            deadline_s=float(os.environ.get(
                "BITCOINCONSENSUS_TPU_SETTLE_DEADLINE_S", "8.0")),
        )

    @property
    def _resilience(self) -> _degrade.DispatchResilience:
        return self._resilience_obj

    @_resilience.setter
    def _resilience(self, value: _degrade.DispatchResilience) -> None:
        # Keep the in-flight queue on the same policy object: tests (and
        # operators) swap the resilience budget/ladder wholesale.
        self._resilience_obj = value
        queue = getattr(self, "_inflight", None)
        if queue is not None:
            queue._res = value

    def _pad(self, n: int) -> int:
        # `n + 1`, not `n`: every padded shape reserves at least one pad
        # lane for the rotating known-answer sentinel (containment floor).
        # Chunked drivers slice at `lane_capacity` (= chunk - 1) so full
        # chunks still land on the same power-of-two shape.
        size = self._min_batch
        while size < n + 1:
            size *= 2
        if self._pad_step is not None:
            # Whichever is smaller: the power-of-two ladder or the step
            # rounding — a 5.6k main dispatch pads to 6144 (not 8192) while
            # a 4-check oracle round still pads to min_batch, not a full step.
            step = self._pad_step
            return min(size, max(self._min_batch, ((n + step) // step) * step))
        return size

    def _prep_lanes(self, checks: Sequence[SigCheck]) -> List["_Lane"]:
        lanes = [_Lane() for _ in checks]
        ecdsa_pending = []  # (lane, r, s, m)
        schnorr_pending = []  # (lane, r32, px32, m32) — device-challenge mode
        for lane, chk in zip(lanes, checks, strict=True):
            if chk.kind == "ecdsa":
                got = _prep_ecdsa(lane, *chk.data)
                if got is not None:
                    ecdsa_pending.append((lane, *got))
            elif chk.kind == "schnorr":
                trip = _prep_schnorr(
                    lane, *chk.data, defer_challenge=self._device_challenge
                )
                if trip is not None:
                    schnorr_pending.append((lane, *trip))
            else:
                _prep_tweak(lane, *chk.data)
        if ecdsa_pending:
            sinvs = _batch_inv_mod_n([s for _, _, s, _ in ecdsa_pending])
            for (lane, r, _s, m), sinv in zip(ecdsa_pending, sinvs, strict=True):
                lane.a = m * sinv % N  # u1
                lane.set_b(r * sinv % N)  # u2
        if schnorr_pending:
            # ONE batched device dispatch for every BIP340 challenge
            # (ops/sha256 midstate path) instead of per-lane host hashing;
            # bit-identical (tests/test_ops_sha256.py) — the GLV split of
            # (n - e) still happens host-side where the wide-int math is.
            from ..ops.sha256 import bip340_challenge

            stack = np.stack(
                [
                    np.frombuffer(r + px + m, dtype=np.uint8)
                    for _, r, px, m in schnorr_pending
                ]
            )
            digests = _inflight.settle_array(
                bip340_challenge(stack[:, :32], stack[:, 32:64], stack[:, 64:])
            )
            for (lane, *_), d in zip(schnorr_pending, digests, strict=True):
                e = int.from_bytes(d.tobytes(), "big") % N
                lane.set_b((N - e) % N)  # (n-e)·P = -e·P
        return lanes

    def verify_checks(self, checks: Sequence[SigCheck]) -> np.ndarray:
        """Verify a mixed batch; returns bool array aligned with `checks`.

        Fully pipelined per chunk: while the device crunches chunk k, the
        host parses/packs chunk k+1 (JAX async dispatch); the roundtrip
        sync cost is paid once, at the end. Cycle collection is paused
        for the duration (utils/gcpause.py — full GC passes over the JAX
        heap otherwise dominate the host-side cost of large batches).
        Stream drivers split the two halves themselves
        (`verify_checks_begin` / `verify_checks_finish`) so host prep for
        batch N+1 overlaps batch N's wire time.
        """
        if not checks:
            return np.zeros(0, dtype=bool)
        with gc_paused():
            return self.verify_checks_finish(self.verify_checks_begin(checks))

    def verify_checks_begin(self, checks: Sequence[SigCheck]):
        """Async half of `verify_checks`: prep, pack and dispatch every
        chunk through the in-flight queue; returns a pending handle
        without synchronizing anything. The queue's bounded depth settles
        the oldest ticket first if a caller races too far ahead."""
        kinds: dict = {}
        for c in checks:
            kinds[c.kind] = kinds.get(c.kind, 0) + 1
        for k, cnt in kinds.items():
            _CHECKS_TOTAL.inc(cnt, kind=k)
        pending = []  # (ticket, start, count)
        cap = self.lane_capacity
        for start in range(0, len(checks), cap):
            sub_checks = checks[start : start + cap]
            if self._native is not None:
                with self.phases("host_prep"):
                    args = self._native.prep_pack(
                        sub_checks, self._pad(len(sub_checks))
                    )
            else:
                with self.phases("host_prep"):
                    sub = self._prep_lanes(sub_checks)
                with self.phases("pack"):
                    args = self._pack_lanes(sub)
            with self.phases("dispatch"):
                pending.append(
                    (self._dispatch_guarded(args, len(sub_checks)), start,
                     len(sub_checks))
                )
        return (checks, pending)

    def verify_checks_finish(self, handle) -> np.ndarray:
        """Settle a `verify_checks_begin` handle: every ticket resolves
        through the guards (or the host oracle) into the result array."""
        checks, pending = handle
        out = np.zeros(len(checks), dtype=bool)
        with self.phases("sync"):
            for ticket, start, count in pending:
                self._settle_guarded(ticket, checks, out, start, count)
        return out

    # --- fault containment (resilience/) --------------------------------
    #
    # Every dispatch flows through _dispatch_guarded (pick ladder rung,
    # seed sentinel lanes, catch dispatch-time faults) and settles through
    # _settle_device (validate the verdict buffer, retry within budget,
    # walk the quarantine ladder). A chunk no device rung could answer for
    # lands on the host-exact oracle — faults cost latency, never a wrong
    # ACCEPT, never a crash.

    _SITE = "jax_backend"

    def _ladder_levels(self) -> Tuple[str, ...]:
        if self._use_pallas:
            return ("pallas", "xla", _degrade.HOST_LEVEL)
        return ("xla", _degrade.HOST_LEVEL)

    def _run_level(self, args: Tuple, n: int, level: str):
        self._dispatch_level = level
        try:
            return self._run_kernel(args, n)
        finally:
            self._dispatch_level = None

    def _prepare_ticket(self, args: Tuple, n: int):
        """Dispatch-time prep (inflight queue callback): copy read-only
        native buffers, then seed the rotating known-answer lanes into
        the reserved pad region — every dispatch carries sentinels."""
        args, _copied = _guards.ensure_writable(args)
        return args, _guards.install_sentinels(args, n)

    def _launch_ticket(self, args: Tuple, n: int, level: str, sset=None):
        """Launch one chunk at `level` (inflight queue callback); chains
        the device-side verdict checksum onto the still-async ok buffer.
        `sset` is the prepare output (sentinel set; the sharded subclass
        passes its shard layout and routes on it). Returns (result, aux)
        with nothing synchronized."""
        result = self._run_level(args, n, level)
        aux = None
        if self._checksum:
            aux = _checksum_jit(result[0] if isinstance(result, tuple)
                                else result)
        return result, aux

    def _on_device_settle(self, ticket, ok, needs, all_ok) -> None:
        """Success hook (inflight queue callback): exactly once per
        cleanly settled ticket, so subclass verdict accounting can never
        double-count across retries."""
        self._note_device_verdict(all_ok, ok, needs, ticket.n)

    def _dispatch_guarded(self, args: Tuple, n: int) -> _inflight.Ticket:
        """Async-dispatch one packed chunk; returns its in-flight ticket
        (unsynchronized device arrays + settle context + deadline)."""
        return self._inflight.dispatch(args, n)

    def _materialize_guarded(self, ticket: _inflight.Ticket):
        """The settle seam — the ONE place in-flight verdict buffers
        become host memory. Materialize + validate one ticket: structural
        guards, sentinel recheck, device-vs-host checksum compare.
        Returns (ok, needs, all_ok) — padded bool arrays and the sharded
        step's replicated verdict scalar (None off-mesh). Raises
        VerdictAnomaly on a buffer the guards reject."""
        with region_scope("settle"):
            result = ticket.result
            padded = int(ticket.args[0].shape[0])
            all_ok = None
            needs_raw = None
            if isinstance(result, tuple):
                if len(result) == 3:
                    ok_raw, needs_raw, all_ok = result
                else:
                    ok_raw, needs_raw = result
            else:
                ok_raw = result
            ok_np = _faults.corrupt_verdict(
                "jax_backend.verdict", np.asarray(ok_raw)
            )
            ok = _guards.validate_verdict(ok_np, padded, self._SITE)
            needs = None
            if needs_raw is not None:
                needs = _guards.validate_verdict(
                    np.asarray(needs_raw), padded, self._SITE
                )
            _guards.check_sentinels(ticket.sset, ok, needs, self._SITE)
            if ticket.aux is not None:
                # Device sums were computed over the pristine in-flight
                # buffer; recomputing from the materialized (possibly
                # corrupted-in-transit) copy catches any single-lane flip —
                # real-lane region included.
                dev_sums = (int(np.asarray(ticket.aux[0])),
                            int(np.asarray(ticket.aux[1])))
                _guards.check_checksum(dev_sums, ok, self._SITE)
            if all_ok is not None:
                all_ok = bool(np.asarray(all_ok))
            return ok, needs, all_ok

    def _settle_device(self, ticket: _inflight.Ticket, count: int):
        """Settle one ticket through the in-flight queue's retry/
        degradation policy. Returns (ok, needs) padded arrays that passed
        every guard, or None when the chunk must resolve on the
        host-exact oracle (fail-closed terminal)."""
        return self._inflight.settle(ticket)

    def _settle_guarded(self, ticket: _inflight.Ticket,
                        checks: Sequence[SigCheck], out: np.ndarray,
                        start: int, count: int) -> None:
        settled = self._settle_device(ticket, count)
        if settled is None:
            host_res = np.fromiter(
                (self._host_check(checks[start + i]) for i in range(count)),
                dtype=bool, count=count,
            )
            out[start : start + count] = host_res
            self._note_host_lanes(host_res)
            return
        ok, needs = settled
        out[start : start + count] = ok[:count]
        if needs is not None:
            needs_np = needs[:count]
            if needs_np.any():
                # Exceptional group-law lanes (crafted scalar collisions):
                # the fast device adds deferred them; resolve exactly on
                # host (never hit by honest traffic —
                # tests/test_pallas_kernel.py crafts one).
                _HOST_FIXUPS.inc(int(needs_np.sum()))
                for i in np.nonzero(needs_np)[0]:
                    r = self._host_check(checks[start + int(i)])
                    out[start + int(i)] = r
                    if not r:
                        self._fixup_failed = True

    def _note_device_verdict(self, all_ok, ok, needs, count: int) -> None:
        """Settle-time hook: a device chunk passed every guard. The base
        verifier keeps no chunk-level verdict; the sharded subclass ANDs
        into its block verdict here — at settle, so retries and contained
        faults can never double- or mis-count."""

    def _note_host_lanes(self, results: np.ndarray) -> None:
        """Settle-time hook: a contained chunk resolved host-exact."""

    def pad(self, n: int) -> int:
        """Public pad-ladder size for `n` lanes (the index-mode batch
        driver packs lanes natively and needs the same padded shapes)."""
        return self._pad(n)

    @property
    def chunk(self) -> int:
        return self._chunk

    @property
    def lane_capacity(self) -> int:
        """Real lanes per chunk dispatch: one short of `chunk`, so the
        reserved known-answer lane never pushes a full chunk up a pad
        rung (8191 real lanes + 1 sentinel pad to 8192, not 16384)."""
        return self._chunk - 1

    def dispatch_lanes(self, args: Tuple, n: int):
        """Async-dispatch one packed lane batch (the prep_pack 7-tuple,
        already padded); returns an opaque pending handle for sync_lanes.
        The index-mode driver's seam: lanes are prepped in the native
        session (uniq_lanes) so no SigCheck objects exist on this side."""
        with self.phases("dispatch"):
            return self._dispatch_guarded(args, n)

    def sync_lanes(self, pending, n: int) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """Materialize a dispatch_lanes result: (ok[:n], needs_host[:n] or
        None). Lanes flagged needs_host hit an exceptional group-law case
        (crafted scalar collisions) OR a contained device fault; the
        caller must resolve them exactly (nat_session_uniq_host_verify) —
        they report ok=False here. A chunk no device rung could answer for
        comes back with EVERY lane flagged needs_host (fail-closed: the
        caller's exact oracle decides, a fault never yields an ACCEPT)."""
        with self.phases("sync"):
            settled = self._settle_device(pending, n)
            if settled is None:
                return np.zeros(n, dtype=bool), np.ones(n, dtype=bool)
            ok, needs = settled
            return ok[:n], (None if needs is None else needs[:n])

    def _host_check(self, chk: SigCheck) -> bool:
        """Host-exact resolution of one check (native core when present,
        pure-Python oracle otherwise)."""
        if self._native is not None:
            ns = self._native.NativeSecp
            if chk.kind == "ecdsa":
                return ns.verify_ecdsa(*chk.data)
            if chk.kind == "schnorr":
                return ns.verify_schnorr(*chk.data)
            return ns.tweak_add_check(*chk.data)
        from . import secp_host

        if chk.kind == "ecdsa":
            return secp_host.verify_ecdsa(*chk.data)
        if chk.kind == "schnorr":
            return secp_host.verify_schnorr(*chk.data)
        return secp_host.xonly_tweak_add_check(*chk.data)

    def _pack_lanes(self, lanes: List["_Lane"]):
        n = len(lanes)
        size = self._pad(n)
        raw = bytearray(size * 4 * 32)
        pos = 0
        for lane in lanes:
            raw[pos : pos + 32] = lane.a.to_bytes(32, "little")
            raw[pos + 32 : pos + 48] = lane.b1.to_bytes(16, "little")
            raw[pos + 48 : pos + 64] = lane.b2.to_bytes(16, "little")
            raw[pos + 64 : pos + 96] = lane.px.to_bytes(32, "little")
            raw[pos + 96 : pos + 128] = lane.t1.to_bytes(32, "little")
            pos += 128
        # View over the bytearray, not a bytes copy: the fields array must
        # stay writable so install_sentinels can seed the pad region.
        fields = np.frombuffer(raw, dtype=np.uint8).reshape(size, 4, 32)

        def flag(get, pad_value):
            arr = np.fromiter((get(l) for l in lanes), dtype=np.int32, count=n)
            return np.concatenate([arr, np.full(size - n, pad_value, np.int32)])

        want_odd = flag(lambda l: l.want_odd, 0)
        parity = flag(lambda l: l.parity, -1)
        has_t2 = flag(lambda l: l.has_t2, 0)
        neg1 = flag(lambda l: l.neg1, 0)
        neg2 = flag(lambda l: l.neg2, 0)
        valid = np.zeros(size, dtype=bool)
        valid[:n] = [lane.valid for lane in lanes]
        return fields, want_odd, parity, has_t2, neg1, neg2, valid

    def _note_dispatch(self, padded: int, n: int, backend: str) -> None:
        """Dispatch accounting — called around, never inside, the jit'd
        program, so kernel jaxprs are identical with telemetry on."""
        _DISPATCH_TOTAL.inc(backend=backend)
        _DISPATCH_LANES.inc(n)
        _DISPATCH_PADDED.inc(padded)
        if padded:
            _DISPATCH_FILL.set(n / padded)
        if padded not in self._seen_shapes:
            self._seen_shapes.add(padded)
            _NEW_SHAPES.inc()

    def _run_kernel(self, args: Tuple, n: int):
        """Dispatch seam: subclasses (mesh sharding) override to add a live
        mask / collective verdict. `n` is the count of real (unpadded)
        lanes. Returns the (async) device result — a plain ok array (XLA
        complete-add kernel) or an (ok, needs_host) tuple (pallas fast-add
        kernel; flagged lanes are resolved host-side in verify_checks)."""
        padded = int(args[0].shape[0])
        _faults.maybe_raise("jax_backend.dispatch")
        if self._use_pallas and self._dispatch_level != "xla":
            # Deferred import keeps CPU-only paths light; LANE_TILE is the
            # kernel's own tile so the guard cannot drift from its assert.
            # A ladder-quarantined pallas rung skips straight to XLA.
            from ..ops.pallas_kernel import LANE_TILE, verify_tiles

            if padded % LANE_TILE == 0:
                self._note_dispatch(padded, n, "pallas")
                return verify_tiles(*args)
        self._note_dispatch(padded, n, "xla")
        return self._kernel(*args)

    # Convenience single-check wrappers (used by tests/differential fuzzing).
    def verify_ecdsa(self, pubkey: bytes, sig_der: bytes, msg32: bytes) -> bool:
        return bool(self.verify_checks([SigCheck("ecdsa", (pubkey, sig_der, msg32))])[0])

    def verify_schnorr(self, pubkey32: bytes, sig64: bytes, msg32: bytes) -> bool:
        return bool(
            self.verify_checks([SigCheck("schnorr", (pubkey32, sig64, msg32))])[0]
        )

    def tweak_add_check(
        self, tweaked32: bytes, parity: int, internal32: bytes, tweak32: bytes
    ) -> bool:
        return bool(
            self.verify_checks(
                [SigCheck("tweak", (tweaked32, parity, internal32, tweak32))]
            )[0]
        )


_default: Optional[TpuSecpVerifier] = None


def default_verifier() -> TpuSecpVerifier:
    """Process-wide verifier (compiled kernels are shared via jit cache)."""
    global _default
    if _default is None:
        _default = TpuSecpVerifier()
    return _default
