"""Host-side secp256k1: pure-Python reference implementation.

This is the framework's scalar fallback path and the executable spec for the
batched JAX/Pallas backend (`bitcoinconsensus_tpu.crypto.jax_backend`). It
reproduces the verify-relevant behavior of the reference's vendored
libsecp256k1 + `pubkey.cpp` glue:

- pubkey parsing incl. hybrid keys (`secp256k1/src/eckey_impl.h` parse rules)
- the consensus-critical lax-DER ECDSA signature parser
  (`pubkey.cpp:28-168` ecdsa_signature_parse_der_lax)
- ECDSA verify with S-normalization (`pubkey.cpp:191-207` CPubKey::Verify)
- BIP340 Schnorr verify (`modules/schnorrsig/main_impl.h:190-237`)
- x-only tweak-add check for Taproot commitments
  (`modules/extrakeys/main_impl.h:109-129`, `pubkey.cpp:176-189`)
- strict-DER / low-S / hashtype encoding predicates used by the interpreter
  (`interpreter.cpp:107-227`)

Group math uses Jacobian coordinates over Python ints — the same formulas the
JAX backend vectorizes over 13-bit limb vectors.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..utils.hashes import tagged_hash

__all__ = [
    "P",
    "N",
    "G",
    "PointJ",
    "lift_x",
    "parse_pubkey",
    "parse_der_lax",
    "verify_ecdsa",
    "verify_schnorr",
    "xonly_tweak_add_check",
    "is_valid_signature_encoding",
    "is_low_der_signature",
    "is_compressed_or_uncompressed_pubkey",
    "is_compressed_pubkey",
]

# Curve constants: y^2 = x^3 + 7 over F_p, group order n.
P = 2**256 - 2**32 - 977
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
_B = 7
G_X = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
G_Y = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8


class PointJ:
    """Jacobian point (X, Y, Z); Z == 0 encodes infinity.

    Formulas follow the reference's `group_impl.h` (gej_double, gej_add_ge,
    gej_add_var) in their mathematical content; this Python form is the spec
    the limb-vectorized JAX backend is tested against.
    """

    __slots__ = ("X", "Y", "Z")

    def __init__(self, X: int, Y: int, Z: int):
        self.X, self.Y, self.Z = X, Y, Z

    @staticmethod
    def infinity() -> "PointJ":
        return PointJ(1, 1, 0)

    @staticmethod
    def from_affine(x: int, y: int) -> "PointJ":
        return PointJ(x, y, 1)

    def is_infinity(self) -> bool:
        return self.Z == 0

    def double(self) -> "PointJ":
        if self.Z == 0:
            return self
        X, Y, Z = self.X, self.Y, self.Z
        # dbl-2009-l (a=0): A=X^2, B=Y^2, C=B^2, D=2((X+B)^2-A-C), E=3A, F=E^2
        A = X * X % P
        Bv = Y * Y % P
        C = Bv * Bv % P
        D = 2 * ((X + Bv) * (X + Bv) - A - C) % P
        E = 3 * A % P
        F = E * E % P
        X3 = (F - 2 * D) % P
        Y3 = (E * (D - X3) - 8 * C) % P
        Z3 = 2 * Y * Z % P
        return PointJ(X3, Y3, Z3)

    def add(self, other: "PointJ") -> "PointJ":
        if self.Z == 0:
            return other
        if other.Z == 0:
            return self
        X1, Y1, Z1 = self.X, self.Y, self.Z
        X2, Y2, Z2 = other.X, other.Y, other.Z
        # add-2007-bl
        Z1Z1 = Z1 * Z1 % P
        Z2Z2 = Z2 * Z2 % P
        U1 = X1 * Z2Z2 % P
        U2 = X2 * Z1Z1 % P
        S1 = Y1 * Z2 * Z2Z2 % P
        S2 = Y2 * Z1 * Z1Z1 % P
        if U1 == U2:
            if S1 != S2:
                return PointJ.infinity()
            return self.double()
        H = (U2 - U1) % P
        I = 4 * H * H % P
        J = H * I % P
        r = 2 * (S2 - S1) % P
        V = U1 * I % P
        X3 = (r * r - J - 2 * V) % P
        Y3 = (r * (V - X3) - 2 * S1 * J) % P
        Z3 = ((Z1 + Z2) * (Z1 + Z2) - Z1Z1 - Z2Z2) * H % P
        return PointJ(X3, Y3, Z3)

    def add_affine(self, x: int, y: int) -> "PointJ":
        return self.add(PointJ.from_affine(x, y))

    def neg(self) -> "PointJ":
        return PointJ(self.X, (-self.Y) % P, self.Z)

    def mul(self, k: int) -> "PointJ":
        """Scalar multiplication (plain double-and-add; host oracle only)."""
        k %= N
        acc = PointJ.infinity()
        addend = self
        while k:
            if k & 1:
                acc = acc.add(addend)
            addend = addend.double()
            k >>= 1
        return acc

    def to_affine(self) -> Optional[Tuple[int, int]]:
        if self.Z == 0:
            return None
        zinv = pow(self.Z, P - 2, P)
        zinv2 = zinv * zinv % P
        return self.X * zinv2 % P, self.Y * zinv2 * zinv % P


G = PointJ.from_affine(G_X, G_Y)


def _sqrt_mod_p(a: int) -> Optional[int]:
    """Square root mod p (p ≡ 3 mod 4 → a^((p+1)/4)); None if non-residue."""
    r = pow(a, (P + 1) // 4, P)
    if r * r % P != a % P:
        return None
    return r


def lift_x(x: int, odd: Optional[bool] = None) -> Optional[Tuple[int, int]]:
    """Lift an x coordinate to a curve point.

    odd=None → even y (BIP340 lift_x); otherwise choose requested parity.
    """
    if x >= P:
        return None
    y = _sqrt_mod_p((x * x % P * x + _B) % P)
    if y is None:
        return None
    want_odd = bool(odd)
    if (y & 1) != want_odd:
        y = P - y
    return x, y


def parse_pubkey(data: bytes) -> Optional[Tuple[int, int]]:
    """secp256k1_ec_pubkey_parse semantics (eckey_impl.h), incl. hybrid keys."""
    if len(data) == 33 and data[0] in (2, 3):
        x = int.from_bytes(data[1:], "big")
        return lift_x(x, odd=(data[0] == 3))
    if len(data) == 65 and data[0] in (4, 6, 7):
        x = int.from_bytes(data[1:33], "big")
        y = int.from_bytes(data[33:], "big")
        if x >= P or y >= P:
            return None
        if (y * y - (x * x % P * x + _B)) % P != 0:
            return None
        # Hybrid: leading byte commits to y parity (eckey_impl.h parse).
        if data[0] == 6 and (y & 1):
            return None
        if data[0] == 7 and not (y & 1):
            return None
        return x, y
    return None


def parse_der_lax(sig: bytes) -> Optional[Tuple[int, int]]:
    """The consensus-critical lax-DER parser (pubkey.cpp:28-168).

    Returns (r, s) on structural success — with (0, 0) substituted when
    either integer overflows the group order, matching the reference's
    overflow → zeroed-signature behavior — or None on structural failure.
    """
    pos = 0
    inputlen = len(sig)

    def read_len() -> Optional[Tuple[int, int]]:
        """Parse a DER length at pos; returns (length, newpos) or None."""
        nonlocal pos
        if pos == inputlen:
            return None
        lenbyte = sig[pos]
        pos += 1
        if lenbyte & 0x80:
            lenbyte -= 0x80
            if lenbyte > inputlen - pos:
                return None
            # Skip leading zero length bytes.
            while lenbyte > 0 and sig[pos] == 0:
                pos += 1
                lenbyte -= 1
            if lenbyte >= 4:
                return None
            val = 0
            while lenbyte > 0:
                val = (val << 8) + sig[pos]
                pos += 1
                lenbyte -= 1
            return val, pos
        return lenbyte, pos

    # Sequence tag byte.
    if pos == inputlen or sig[pos] != 0x30:
        return None
    pos += 1
    # Sequence length bytes — value is *ignored* (lax), only skipped.
    if pos == inputlen:
        return None
    lenbyte = sig[pos]
    pos += 1
    if lenbyte & 0x80:
        lenbyte -= 0x80
        if lenbyte > inputlen - pos:
            return None
        pos += lenbyte

    def read_integer() -> Optional[Tuple[int, int]]:
        """Parse one INTEGER; returns (valpos, vallen) or None."""
        nonlocal pos
        if pos == inputlen or sig[pos] != 0x02:
            return None
        pos += 1
        r = read_len()
        if r is None:
            return None
        length, _ = r
        if length > inputlen - pos:
            return None
        valpos = pos
        pos += length
        return valpos, length

    ri = read_integer()
    if ri is None:
        return None
    si = read_integer()
    if si is None:
        return None

    def extract(valpos: int, vallen: int) -> Optional[int]:
        """Strip leading zeros; >32 significant bytes → overflow (None)."""
        while vallen > 0 and sig[valpos] == 0:
            valpos += 1
            vallen -= 1
        if vallen > 32:
            return None
        return int.from_bytes(sig[valpos : valpos + vallen], "big")

    r = extract(*ri)
    s = extract(*si)
    # Overflow of either value (or >= group order) zeroes the signature
    # rather than failing the parse (pubkey.cpp:150-160 + parse_compact).
    if r is None or s is None or r >= N or s >= N:
        return 0, 0
    return r, s


def verify_ecdsa(pubkey: bytes, sig_der: bytes, msg32: bytes) -> bool:
    """CPubKey::Verify (pubkey.cpp:191-207): parse → lax-DER → normalize-S →
    secp256k1_ecdsa_verify (ecdsa_impl.h:207-275)."""
    pt = parse_pubkey(pubkey)
    if pt is None:
        return False
    rs = parse_der_lax(sig_der)
    if rs is None:
        return False
    r, s = rs
    if s > N // 2:  # normalize high-S before verify (pubkey.cpp:204)
        s = N - s
    if r == 0 or s == 0 or r >= N or s >= N:
        return False
    m = int.from_bytes(msg32, "big") % N
    sinv = pow(s, N - 2, N)
    u1 = m * sinv % N
    u2 = r * sinv % N
    R = G.mul(u1).add(PointJ.from_affine(*pt).mul(u2))
    aff = R.to_affine()
    if aff is None:
        return False
    return aff[0] % N == r


def verify_schnorr(pubkey32: bytes, sig64: bytes, msg32: bytes) -> bool:
    """BIP340 verify (modules/schnorrsig/main_impl.h:190-237)."""
    if len(pubkey32) != 32 or len(sig64) != 64:
        return False
    px = int.from_bytes(pubkey32, "big")
    pt = lift_x(px)  # even-y lift; None for x >= p or non-residue
    if pt is None:
        return False
    r = int.from_bytes(sig64[:32], "big")
    s = int.from_bytes(sig64[32:], "big")
    if r >= P or s >= N:
        return False
    e = (
        int.from_bytes(
            tagged_hash("BIP0340/challenge", sig64[:32] + pubkey32 + msg32), "big"
        )
        % N
    )
    # R = s*G - e*P
    R = G.mul(s).add(PointJ.from_affine(*pt).mul(N - e))
    aff = R.to_affine()
    if aff is None:
        return False
    xR, yR = aff
    return (yR & 1) == 0 and xR == r


def xonly_tweak_add_check(
    tweaked_x32: bytes, parity: int, internal_x32: bytes, tweak32: bytes
) -> bool:
    """secp256k1_xonly_pubkey_tweak_add_check (extrakeys/main_impl.h:109-129):
    verify tweaked == internal + tweak·G with the stated y parity.

    This is the Taproot commitment equation used by
    XOnlyPubKey::CheckPayToContract (pubkey.cpp:184-189)."""
    base = lift_x(int.from_bytes(internal_x32, "big"))
    if base is None:
        return False
    t = int.from_bytes(tweak32, "big")
    if t >= N:
        return False
    Q = PointJ.from_affine(*base).add(G.mul(t))
    aff = Q.to_affine()
    if aff is None:
        return False
    qx, qy = aff
    return qx == int.from_bytes(tweaked_x32, "big") and (qy & 1) == parity


# ---------------------------------------------------------------------------
# Signature/pubkey *encoding* predicates used by the interpreter
# (interpreter.cpp:107-227). These are byte-level checks, no curve math.
# ---------------------------------------------------------------------------

def is_valid_signature_encoding(sig: bytes) -> bool:
    """Strict DER check (interpreter.cpp:107-170 IsValidSignatureEncoding).

    Format: 0x30 [total-length] 0x02 [R-length] [R] 0x02 [S-length] [S]
    [sighash], with minimal positive integers.
    """
    if len(sig) < 9 or len(sig) > 73:
        return False
    if sig[0] != 0x30:
        return False
    if sig[1] != len(sig) - 3:
        return False
    lenR = sig[3]
    if 5 + lenR >= len(sig):
        return False
    lenS = sig[5 + lenR]
    if lenR + lenS + 7 != len(sig):
        return False
    if sig[2] != 0x02:
        return False
    if lenR == 0:
        return False
    if sig[4] & 0x80:
        return False
    if lenR > 1 and sig[4] == 0x00 and not (sig[5] & 0x80):
        return False
    if sig[lenR + 4] != 0x02:
        return False
    if lenS == 0:
        return False
    if sig[lenR + 6] & 0x80:
        return False
    if lenS > 1 and sig[lenR + 6] == 0x00 and not (sig[lenR + 7] & 0x80):
        return False
    return True


def is_low_der_signature(sig: bytes) -> bool:
    """Low-S check on a strict-DER sig incl. hashtype byte
    (interpreter.cpp:172-182 + pubkey.cpp:301-308 CheckLowS)."""
    rs = parse_der_lax(sig[:-1])
    if rs is None:
        return False
    _, s = rs
    return s <= N // 2


def is_compressed_or_uncompressed_pubkey(pubkey: bytes) -> bool:
    """interpreter.cpp:58-82."""
    if len(pubkey) < 33:
        return False
    if pubkey[0] == 0x04:
        return len(pubkey) == 65
    if pubkey[0] in (0x02, 0x03):
        return len(pubkey) == 33
    return False


def is_compressed_pubkey(pubkey: bytes) -> bool:
    """interpreter.cpp:84-94."""
    return len(pubkey) == 33 and pubkey[0] in (0x02, 0x03)


# ---------------------------------------------------------------------------
# Test-support signing (NOT consensus; mirrors key.cpp's role: vector
# generation only).
# ---------------------------------------------------------------------------

def _der_encode_int(v: int) -> bytes:
    raw = v.to_bytes((v.bit_length() + 7) // 8 or 1, "big")
    if raw[0] & 0x80:
        raw = b"\x00" + raw
    return b"\x02" + bytes([len(raw)]) + raw


def sign_ecdsa(seckey: int, msg32: bytes, grind_low_r: bool = False) -> bytes:
    """Deterministic ECDSA sign → strict-DER (without hashtype byte)."""
    import hashlib as _h

    m = int.from_bytes(msg32, "big") % N
    counter = 0
    while True:
        k = (
            int.from_bytes(
                _h.sha256(
                    seckey.to_bytes(32, "big") + msg32 + counter.to_bytes(4, "big")
                ).digest(),
                "big",
            )
            % N
        )
        counter += 1
        if k == 0:
            continue
        Raff = G.mul(k).to_affine()
        assert Raff is not None
        r = Raff[0] % N
        if r == 0:
            continue
        if grind_low_r and r >> 255:
            continue
        s = pow(k, N - 2, N) * (m + r * seckey) % N
        if s == 0:
            continue
        if s > N // 2:
            s = N - s
        body = _der_encode_int(r) + _der_encode_int(s)
        return b"\x30" + bytes([len(body)]) + body


def sign_schnorr(seckey: int, msg32: bytes, aux: bytes = b"\x00" * 32) -> bytes:
    """BIP340 sign (test-support only)."""
    d0 = seckey % N
    assert d0 != 0
    Paff = G.mul(d0).to_affine()
    assert Paff is not None
    px, py = Paff
    d = d0 if (py & 1) == 0 else N - d0
    t = d ^ int.from_bytes(tagged_hash("BIP0340/aux", aux), "big")
    k0 = (
        int.from_bytes(
            tagged_hash("BIP0340/nonce", t.to_bytes(32, "big") + px.to_bytes(32, "big") + msg32),
            "big",
        )
        % N
    )
    assert k0 != 0
    Raff = G.mul(k0).to_affine()
    assert Raff is not None
    rx, ry = Raff
    k = k0 if (ry & 1) == 0 else N - k0
    e = (
        int.from_bytes(
            tagged_hash(
                "BIP0340/challenge", rx.to_bytes(32, "big") + px.to_bytes(32, "big") + msg32
            ),
            "big",
        )
        % N
    )
    s = (k + e * d) % N
    return rx.to_bytes(32, "big") + s.to_bytes(32, "big")


def pubkey_create(seckey: int, compressed: bool = True) -> bytes:
    """Derive the serialized pubkey for a secret key (test support)."""
    aff = G.mul(seckey).to_affine()
    assert aff is not None
    x, y = aff
    if compressed:
        return bytes([2 + (y & 1)]) + x.to_bytes(32, "big")
    return b"\x04" + x.to_bytes(32, "big") + y.to_bytes(32, "big")


def xonly_pubkey_create(seckey: int) -> Tuple[bytes, int]:
    """Derive (xonly pubkey, parity) for a secret key (test support)."""
    aff = G.mul(seckey).to_affine()
    assert aff is not None
    x, y = aff
    return x.to_bytes(32, "big"), y & 1
