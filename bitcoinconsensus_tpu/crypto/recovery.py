"""Compact-signature public-key recovery (`CPubKey::RecoverCompact`).

The reference crate compiles libsecp256k1's recovery module
(`/root/reference/build.rs:47`) solely for
`CPubKey::RecoverCompact` (`pubkey.cpp:209-232`), which backs message
signing — not consensus. It is a cold host path (never reached from
`verify()`), so the TPU framework implements it host-side over the same
Jacobian point algebra as the executable-spec verifier
(`crypto/secp_host.py`); the math mirrors
`secp256k1_ecdsa_sig_recover` (`modules/recovery/main_impl.h:87-121`):

    R = lift_x(r + (recid&2 ? n : 0), odd=recid&1)
    Q = r^-1 * (s*R - m*G)

Signature wire format (65 bytes): `[header || r32 || s32]` with
`header = 27 + recid + (compressed ? 4 : 0)` — `pubkey.cpp:211-213`.
"""

from __future__ import annotations

import hashlib
from typing import Optional

from . import secp_host as H

__all__ = ["recover_compact", "sign_compact"]

COMPACT_SIGNATURE_SIZE = 65  # pubkey.h COMPACT_SIGNATURE_SIZE


def recover_compact(msg32: bytes, sig65: bytes) -> Optional[bytes]:
    """Recover the serialized pubkey from a compact signature, or None.

    Returns the 33-byte compressed or 65-byte uncompressed key according
    to the header's compression bit, exactly like `RecoverCompact`
    populating a CPubKey. Parse rules follow
    `recoverable_signature_parse_compact` (overflowing r or s rejected)
    and `sig_recover` (zero r or s rejected; recid&2 requires r+n < p).
    """
    if len(msg32) != 32 or len(sig65) != COMPACT_SIGNATURE_SIZE:
        return None
    header = sig65[0]
    # RecoverCompact masks ANY header byte (pubkey.cpp:211-213): recid and
    # the compression bit are taken mod 8 with C int wraparound, which
    # Python's & on a negative int reproduces exactly (e.g. header 26 ->
    # recid 3 compressed; header 35 -> recid 0 uncompressed).
    recid = (header - 27) & 3
    compressed = ((header - 27) & 4) != 0
    r = int.from_bytes(sig65[1:33], "big")
    s = int.from_bytes(sig65[33:65], "big")
    if r >= H.N or s >= H.N:  # parse_compact: overflow rejected
        return None
    if r == 0 or s == 0:  # sig_recover: zero scalars rejected
        return None
    fx = r
    if recid & 2:
        # main_impl.h:104-109: x = r + n must still be a field element
        if r >= H.P - H.N:
            return None
        fx = r + H.N
    pt = H.lift_x(fx, odd=bool(recid & 1))
    if pt is None:
        return None
    rinv = pow(r, H.N - 2, H.N)
    m = int.from_bytes(msg32, "big") % H.N
    u1 = (-(rinv * m)) % H.N
    u2 = (rinv * s) % H.N
    # Q = u2*R + u1*G (ecmult in main_impl.h:118)
    Q = H.PointJ.from_affine(*pt).mul(u2).add(H.G.mul(u1))
    aff = Q.to_affine()
    if aff is None:  # infinity
        return None
    x, y = aff
    if compressed:
        return bytes([2 + (y & 1)]) + x.to_bytes(32, "big")
    return b"\x04" + x.to_bytes(32, "big") + y.to_bytes(32, "big")


def sign_compact(seckey: int, msg32: bytes, compressed: bool = True) -> bytes:
    """Produce a recoverable compact signature (test support — the
    reference's signing side lives in the uncompiled key.cpp)."""
    assert 0 < seckey < H.N and len(msg32) == 32
    m = int.from_bytes(msg32, "big") % H.N
    counter = 0
    while True:
        k = (
            int.from_bytes(
                hashlib.sha256(
                    b"compact" + seckey.to_bytes(32, "big") + msg32
                    + counter.to_bytes(4, "big")
                ).digest(),
                "big",
            )
            % H.N
        )
        counter += 1
        if k == 0:
            continue
        Raff = H.G.mul(k).to_affine()
        assert Raff is not None
        rx, ry = Raff
        r = rx % H.N
        if r == 0:
            continue
        s = pow(k, H.N - 2, H.N) * (m + r * seckey) % H.N
        if s == 0:
            continue
        recid = (2 if rx >= H.N else 0) | (ry & 1)
        if s > H.N // 2:
            s = H.N - s
            recid ^= 1  # negating s flips the recovered point's y parity
        header = 27 + recid + (4 if compressed else 0)
        return (
            bytes([header]) + r.to_bytes(32, "big") + s.to_bytes(32, "big")
        )
