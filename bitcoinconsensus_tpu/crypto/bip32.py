"""BIP32 public-key derivation (`CPubKey::Derive` / `CExtPubKey`).

The reference compiles the BIP32 public-derivation surface in
`pubkey.cpp:253-299` (`CPubKey::Derive` via
`secp256k1_ec_pubkey_tweak_add`, `CExtPubKey::{Encode,Decode,Derive}`,
HMAC-SHA512 `BIP32Hash` from `hash.cpp:72-80`) — wallet-facing, not
consensus, and pure host work; implemented here over the executable-spec
curve (`crypto/secp_host.py`). Only NON-hardened derivation exists for
public keys (`pubkey.cpp:255` asserts `(nChild >> 31) == 0`).
"""

from __future__ import annotations

import hmac
import hashlib
from typing import Optional, Tuple

from . import secp_host as H
from ..utils.hashes import hash160

__all__ = ["bip32_hash", "pubkey_derive", "ExtPubKey", "BIP32_EXTKEY_SIZE"]

BIP32_EXTKEY_SIZE = 74  # pubkey.h BIP32_EXTKEY_SIZE


def bip32_hash(chaincode: bytes, n_child: int, header: int, data32: bytes) -> bytes:
    """HMAC-SHA512(cc, header || data32 || ser32(n_child)) — hash.cpp:72-80."""
    assert len(chaincode) == 32 and len(data32) == 32
    msg = bytes([header]) + data32 + n_child.to_bytes(4, "big")
    return hmac.new(chaincode, msg, hashlib.sha512).digest()


def pubkey_derive(
    pubkey33: bytes, chaincode: bytes, n_child: int
) -> Optional[Tuple[bytes, bytes]]:
    """(child pubkey33, child chaincode) or None — CPubKey::Derive
    (pubkey.cpp:253-273): I = BIP32Hash(cc, n, key[0], key[1:]);
    child = point(parse(key)) + IL*G, compressed; cc_child = IR.
    None when the parent key fails to parse or the tweaked point is
    invalid (IL >= n or infinity), like `secp256k1_ec_pubkey_tweak_add`.
    """
    if n_child >> 31:
        raise ValueError("hardened derivation requires the private key")
    if len(pubkey33) != 33 or pubkey33[0] not in (2, 3):
        return None
    out = bip32_hash(chaincode, n_child, pubkey33[0], pubkey33[1:33])
    il, cc_child = out[:32], out[32:]
    x = int.from_bytes(pubkey33[1:33], "big")
    if x >= H.P:
        return None
    pt = H.lift_x(x, odd=pubkey33[0] == 3)
    if pt is None:
        return None
    t = int.from_bytes(il, "big")
    if t >= H.N:  # tweak overflow: tweak_add fails
        return None
    child = H.PointJ.from_affine(*pt).add(H.G.mul(t)).to_affine()
    if child is None:  # infinity: tweak_add fails
        return None
    cx, cy = child
    return bytes([2 + (cy & 1)]) + cx.to_bytes(32, "big"), cc_child


class ExtPubKey:
    """CExtPubKey: (depth, parent fingerprint, child number, chaincode,
    compressed pubkey) with the 74-byte Encode/Decode wire layout
    (pubkey.cpp:275-299)."""

    __slots__ = ("depth", "fingerprint", "n_child", "chaincode", "pubkey")

    def __init__(
        self,
        depth: int = 0,
        fingerprint: bytes = b"\x00" * 4,
        n_child: int = 0,
        chaincode: bytes = b"\x00" * 32,
        pubkey: bytes = b"",
    ):
        self.depth = depth
        self.fingerprint = fingerprint
        self.n_child = n_child
        self.chaincode = chaincode
        self.pubkey = pubkey

    def encode(self) -> bytes:
        assert len(self.pubkey) == 33
        return (
            bytes([self.depth])
            + self.fingerprint
            + self.n_child.to_bytes(4, "big")
            + self.chaincode
            + self.pubkey
        )

    @classmethod
    def decode(cls, code: bytes) -> "ExtPubKey":
        assert len(code) == BIP32_EXTKEY_SIZE
        return cls(
            depth=code[0],
            fingerprint=code[1:5],
            n_child=int.from_bytes(code[5:9], "big"),
            chaincode=code[9:41],
            pubkey=code[41:74],
        )

    def derive(self, n_child: int) -> Optional["ExtPubKey"]:
        """CExtPubKey::Derive (pubkey.cpp:293-299); None on tweak failure."""
        got = pubkey_derive(self.pubkey, self.chaincode, n_child)
        if got is None:
            return None
        child_pub, child_cc = got
        return ExtPubKey(
            # unsigned-char nDepth semantics (CExtPubKey::Derive stores
            # nDepth+1 into an unsigned char, wrapping at 256)
            depth=(self.depth + 1) & 0xFF,
            fingerprint=hash160(self.pubkey)[:4],  # CKeyID prefix
            n_child=n_child,
            chaincode=child_cc,
            pubkey=child_pub,
        )

    def __eq__(self, other) -> bool:
        return isinstance(other, ExtPubKey) and self.encode() == other.encode()

    def __hash__(self) -> int:
        return hash(self.encode())
