"""Host-side GLV scalar decomposition for the verify kernel.

Splits a scalar k (mod n) as k = s1·|k1| + lambda·s2·|k2| with
|k1|, |k2| < 2^128, following the lattice-basis construction the
reference documents and implements in
`secp256k1/src/scalar_impl.h:60-178` (secp256k1_scalar_split_lambda):
c1 = round(b2·k/n), c2 = round(-b1·k/n), k2 = -(c1·b1 + c2·b2),
k1 = k - k2·lambda. Host Python ints make the rounding exact, so the
g1/g2 384-bit estimate machinery of the reference is unnecessary.

The device half of the scheme lives in `ops/curve.double_scalar_mult_glv`.
"""

from __future__ import annotations

from typing import Tuple

from ..obs import counter
from ..ops.curve import LAMBDA
from .secp_host import N

__all__ = ["split_lambda", "SplitRangeError", "LAMBDA"]

_B1 = -0xE4437ED6010E88286F547FA90ABFE4C3
_B2 = 0x3086D221A7D46BCDE86C90E49284EB15

_SPLIT_RANGE = counter(
    "consensus_glv_split_range_total",
    "GLV split produced a half >= 2^128 — the lattice certificate "
    "(analysis/scalar_check.py glv.split_lambda) is violated at runtime",
    ("half",))


class SplitRangeError(ValueError):
    """A GLV half escaped the proven |k_i| < 2^128 bound.

    The scalar-schedule prover certifies this cannot happen for the
    shipped constants, so reaching it means the constants (or the
    arithmetic) were corrupted in-process.  Unlike the bare ``assert``
    this replaces, the check survives ``python -O`` — a wrong-size half
    silently truncates in the 128-bit device decomposition, which is a
    consensus fault, never an optimization."""

    def __init__(self, k: int, a1: int, a2: int):
        self.k, self.a1, self.a2 = k, a1, a2
        super().__init__(
            f"GLV split out of range: k={k:#x} -> |k1|={a1:#x}, "
            f"|k2|={a2:#x}; proven bound is 2^128")


def split_lambda(k: int) -> Tuple[int, int, int, int]:
    """k (mod n) -> (abs_k1, neg1, abs_k2, neg2) with abs_ki < 2^128 and
    s1·abs_k1 + lambda·s2·abs_k2 ≡ k (mod n), si = -1 if negi else 1."""
    k %= N
    c1 = (_B2 * k + N // 2) // N
    c2 = (-_B1 * k + N // 2) // N
    k2 = -(c1 * _B1 + c2 * _B2)
    k1 = k - k2 * LAMBDA
    k1 %= N
    k2 %= N
    neg1 = k1 > N - k1
    neg2 = k2 > N - k2
    a1 = N - k1 if neg1 else k1
    a2 = N - k2 if neg2 else k2
    if a1 >= 1 << 128 or a2 >= 1 << 128:
        if a1 >= 1 << 128:
            _SPLIT_RANGE.inc(half="k1")
        if a2 >= 1 << 128:
            _SPLIT_RANGE.inc(half="k2")
        raise SplitRangeError(k, a1, a2)
    return a1, int(neg1), a2, int(neg2)
