"""Consistent-hash sigstore tier: shard handoff on cell membership change.

`PersistentSigCache` (models/sigstore.py) made signature-cache shards
portable — fixed-width CRC-checked records, truncation-tolerant replay,
durable DEL tombstones. This module promotes those per-replica stores
into a cell-wide tier:

- **Shared salt.** Cache keys are salted digests; the tier holds the
  canonical 32-byte salt at ``root/salt`` and seeds it into every
  replica's store directory before the replica opens it, so a record
  written by one replica is addressable by every other. Without this a
  handed-off log would be meaningless bytes.
- **Shard ownership.** Shard index ``i`` (the key's leading digest
  byte modulo the shard count) maps to an owning replica via the same
  consistent ring the router uses for tenants, so ownership moves
  minimally under churn.
- **Handoff on departure.** When a replica is evicted, each of its
  shard logs streams to that shard's new owner: records are re-verified
  CRC-by-CRC on the way out (the stream stops at the first bad record —
  the same truncation-tolerant fail-closed rule as replay), written to
  a handoff file with the atomic tmp→fsync→rename idiom, and absorbed
  into the receiver's **live** store in original order, so an ADD
  followed by its tombstone DEL lands evicted — audit-convicted poison
  stays convicted across handoff.
- **Fail-closed reads.** A key whose shard is mid-handoff simply misses
  in the receiver and re-verifies on the device/host path — the tier
  can cost work, never serve an unverified cached verdict.

Swept by ``scripts/consensus_chaos.py --cell`` (shard-handoff-under-load
trial: >=90% warm hits and zero re-dispatch of clean persisted entries
after handoff, tombstones preserved).
"""

from __future__ import annotations

import os
import zlib
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

from ..models.sigstore import (
    _KEY_LEN,
    _OP_ADD,
    _OP_DEL,
    _REC_LEN,
    PersistentSigCache,
)
from ..obs import counter as _obs_counter
from ..obs import flight as _flight
from .hashring import HashRing

__all__ = [
    "SigTier",
    "absorb_handoff",
    "iter_shard_records",
    "write_handoff",
]

_C_HANDOFFS = _obs_counter(
    "consensus_cell_handoffs_total",
    "sigstore shard handoffs streamed to a new owner on membership change",
)
_C_HANDOFF_RECORDS = _obs_counter(
    "consensus_cell_handoff_records_total",
    "CRC-verified records streamed in sigstore shard handoffs",
)


def iter_shard_records(path: str) -> Iterator[Tuple[bytes, bytes]]:
    """Yield (op, key) for every intact record of one shard log.

    CRC-checked record-by-record with the store's truncation-tolerant
    rule: the stream stops at the first short, checksum-failing, or
    unknown-op record — everything past a corrupt byte is untrusted and
    losing it costs cache misses, never wrong hits. The source file is
    never modified (the departed owner may still be inspected
    post-mortem)."""
    if not os.path.exists(path):
        return
    with open(path, "rb") as fh:
        while True:
            rec = fh.read(_REC_LEN)
            if len(rec) < _REC_LEN:
                return  # clean end or torn tail: stop fail-closed
            body = rec[: 1 + _KEY_LEN]
            crc = int.from_bytes(rec[1 + _KEY_LEN :], "little")
            if zlib.crc32(body) != crc:
                return
            op, key = body[:1], body[1:]
            if op not in (_OP_ADD, _OP_DEL):
                return
            yield op, key


def write_handoff(src_paths: Sequence[str], out_path: str) -> int:
    """Stream the intact records of `src_paths` into one handoff file.

    Atomic (tmp + fsync + rename, the compaction idiom): the receiver
    either sees a complete CRC-clean handoff file or no file at all.
    Record order within each source log is preserved, so ADD/DEL
    sequences (tombstones) replay to the same final state. Returns the
    record count."""
    n = 0
    tmp = out_path + ".tmp"
    with open(tmp, "wb") as fh:
        for src in src_paths:
            for op, key in iter_shard_records(src):
                body = op + key
                fh.write(body + zlib.crc32(body).to_bytes(4, "little"))
                n += 1
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, out_path)
    return n


def absorb_handoff(store: PersistentSigCache, path: str) -> Dict[str, int]:
    """Apply a handoff file into a live store, in record order.

    ADD inserts through the normal `add_key` path (persisted into the
    receiver's own shard logs), DEL evicts-and-tombstones through
    `discard_key` — so a key the departed owner convicted (ADD … DEL)
    ends absent here even if this store had cached it independently.
    A kill -9 mid-absorb leaves the receiver's logs healing to the last
    good record boundary on the next open, exactly like any other
    interrupted append sequence."""
    adds = dels = 0
    for op, key in iter_shard_records(path):
        if op == _OP_ADD:
            store.add_key(key)
            adds += 1
        else:
            store.discard_key(key)
            dels += 1
    return {"records": adds + dels, "adds": adds, "dels": dels}


class SigTier:
    """Shard-ownership coordinator over the per-replica stores.

    Holds the canonical salt, the member ring, and the handoff
    procedure. The supervisor drives it: ``join`` before spawning a
    replica (seeds the salt into its store dir), ``leave`` +
    ``handoff_from`` when one is evicted. The `absorb` callable bridges
    process boundaries — in-process stubs call `absorb_handoff`
    directly, subprocess replicas take a control-channel command."""

    def __init__(self, root_dir: str, shards: int = 8, vnodes: int = 64):
        self.root_dir = root_dir
        self.shards = shards
        os.makedirs(root_dir, exist_ok=True)
        self._salt = self._load_salt()
        self.ring = HashRing(vnodes=vnodes)
        self._handoff_seq = 0

    def _load_salt(self) -> bytes:
        path = os.path.join(self.root_dir, "salt")
        try:
            with open(path, "rb") as fh:
                salt = fh.read()
            if len(salt) == _KEY_LEN:
                return salt
        except FileNotFoundError:
            pass
        salt = os.urandom(_KEY_LEN)
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(salt)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        return salt

    def store_dir(self, member: str) -> str:
        return os.path.join(self.root_dir, member)

    def join(self, member: str) -> str:
        """Add `member` to the ring; returns its store dir with the
        cell salt pre-seeded (PersistentSigCache honours an existing
        salt file, so the store opens onto the shared keyspace)."""
        d = self.store_dir(member)
        os.makedirs(d, exist_ok=True)
        salt_path = os.path.join(d, "salt")
        if not os.path.exists(salt_path):
            tmp = salt_path + ".tmp"
            with open(tmp, "wb") as fh:
                fh.write(self._salt)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, salt_path)
        self.ring.add(member)
        return d

    def leave(self, member: str) -> None:
        self.ring.remove(member)

    def shard_owner(self, shard_i: int) -> Optional[str]:
        return self.ring.lookup(f"shard-{shard_i:02d}")

    def owners(self) -> Dict[int, Optional[str]]:
        return {i: self.shard_owner(i) for i in range(self.shards)}

    def handoff_from(
        self,
        departed: str,
        absorb: Callable[[str, str], Optional[dict]],
    ) -> dict:
        """Stream every shard log of `departed` to the shards' current
        owners (call `leave(departed)` first so ownership has already
        moved). One handoff file per receiving member, written into the
        receiver's store dir and absorbed via the `absorb` callable;
        the file is removed after a successful absorb. Returns a
        per-receiver record-count report."""
        src_dir = self.store_dir(departed)
        by_dest: Dict[str, List[str]] = {}
        for i in range(self.shards):
            owner = self.shard_owner(i)
            if owner is None or owner == departed:
                continue
            path = os.path.join(src_dir, "shard-%02d.log" % i)
            if os.path.exists(path):
                by_dest.setdefault(owner, []).append(path)
        report: Dict[str, dict] = {}
        for dest, paths in sorted(by_dest.items()):
            self._handoff_seq += 1
            out = os.path.join(
                self.store_dir(dest),
                "handoff-%s-%03d.log" % (departed, self._handoff_seq),
            )
            n = write_handoff(paths, out)
            _C_HANDOFFS.inc()
            _C_HANDOFF_RECORDS.inc(n)
            _flight.record(
                "cell.handoff", src=departed, dst=dest, records=n,
                shards=len(paths),
            )
            absorbed = absorb(dest, out)
            report[dest] = {
                "records": n,
                "absorbed": absorbed,
                "path": out,
            }
            if absorbed is not None:
                try:
                    os.remove(out)
                except OSError:
                    pass
        return {"departed": departed, "receivers": report,
                "records": sum(r["records"] for r in report.values())}
