"""Multi-process serving cell: router, replicas, shared sigstore tier.

The step from "a fast server" to "a service": several
`IngressServer`+`VerifyServer` replicas (real subprocesses), a
consistent-hash tenant router with health-driven failover
(cell/router.py), a known-answer-probing supervisor with bounded
restart backoff (cell/replica.py), and the persistent sigstore shards
promoted to a consistent-hash tier with shard handoff on membership
change (cell/sigtier.py). `ServingCell` (cell/cell.py) wires the four
together. Chaos-gated by `scripts/consensus_chaos.py --cell`.

Import discipline: `hashring` and `sigtier` are dependency-light
(stdlib + obs + models.sigstore — no jax anywhere on the chain), so
subprocess tooling and the kill-9 handoff tests can import them in
bare children. The router/replica/cell layers pull in the serving
stack (and with it jax); they are exposed lazily.
"""

from .hashring import HashRing
from .sigtier import SigTier, absorb_handoff, iter_shard_records, write_handoff

__all__ = [
    "CellRouter",
    "HashRing",
    "ReplicaProcess",
    "ReplicaSupervisor",
    "ServingCell",
    "SigTier",
    "StubReplica",
    "absorb_handoff",
    "iter_shard_records",
    "make_probe_items",
    "probe_replica",
    "write_handoff",
]

_LAZY = {
    "CellRouter": ("router", "CellRouter"),
    "ReplicaProcess": ("replica", "ReplicaProcess"),
    "ReplicaSupervisor": ("replica", "ReplicaSupervisor"),
    "StubReplica": ("replica", "StubReplica"),
    "make_probe_items": ("replica", "make_probe_items"),
    "probe_replica": ("replica", "probe_replica"),
    "ServingCell": ("cell", "ServingCell"),
}


def __getattr__(name: str):
    try:
        mod_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(name) from None
    import importlib

    mod = importlib.import_module(f".{mod_name}", __name__)
    return getattr(mod, attr)
