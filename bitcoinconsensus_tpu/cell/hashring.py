"""Consistent-hash ring: stable key→member placement under churn.

The cell uses one ring twice — tenants→replicas in the router
(cell/router.py) and sigstore shards→owners in the tier
(cell/sigtier.py). Both need the same property: when a member leaves,
only the keys it owned move (to the next member clockwise), and when it
rejoins, exactly those keys come back. A modulo hash would reshuffle
nearly everything on every membership change, defeating both the warm
sigstore handoff and tenant session affinity.

Deterministic and dependency-free: ring points are the leading 8 bytes
of ``sha256(member '#' vnode)``, so every process in the cell (router,
supervisor, chaos harness, tests) derives the identical placement from
the member names alone — no coordination service, no shared state.

``vnodes`` virtual points per member smooth the key distribution; 64 is
plenty for single-digit member counts (the cell's regime) while keeping
ring rebuilds trivially cheap.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Iterable, List, Optional, Tuple

__all__ = ["HashRing"]


def _point(member: str, vnode: int) -> int:
    h = hashlib.sha256(f"{member}#{vnode}".encode("utf-8")).digest()
    return int.from_bytes(h[:8], "big")


class HashRing:
    """Sorted ring of (point, member) pairs with vnode smoothing.

    Not thread-safe: owners (router, tier) rebuild or mutate it under
    their own locks — membership changes are rare and member counts are
    small, so copy-and-swap is the cheap, safe idiom.
    """

    def __init__(self, members: Iterable[str] = (), vnodes: int = 64):
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        self.vnodes = vnodes
        self._members: List[str] = []
        self._points: List[Tuple[int, str]] = []
        for m in members:
            self.add(m)

    @property
    def members(self) -> List[str]:
        return list(self._members)

    def __len__(self) -> int:
        return len(self._members)

    def __contains__(self, member: str) -> bool:
        return member in self._members

    def add(self, member: str) -> None:
        if member in self._members:
            return
        self._members.append(member)
        for v in range(self.vnodes):
            bisect.insort(self._points, (_point(member, v), member))

    def remove(self, member: str) -> None:
        if member not in self._members:
            return
        self._members.remove(member)
        self._points = [p for p in self._points if p[1] != member]

    def _key_point(self, key: str) -> int:
        h = hashlib.sha256(key.encode("utf-8")).digest()
        return int.from_bytes(h[:8], "big")

    def lookup(self, key: str) -> Optional[str]:
        """Owner of `key`: the first member point clockwise of the key's
        point (wrapping); None on an empty ring."""
        if not self._points:
            return None
        i = bisect.bisect_right(self._points, (self._key_point(key), "￿"))
        if i == len(self._points):
            i = 0
        return self._points[i][1]

    def lookup_chain(self, key: str) -> List[str]:
        """Every member, in ring order starting from `key`'s owner —
        the failover preference list (distinct members, each once)."""
        if not self._points:
            return []
        start = bisect.bisect_right(
            self._points, (self._key_point(key), "￿")
        )
        chain: List[str] = []
        n = len(self._points)
        for off in range(n):
            m = self._points[(start + off) % n][1]
            if m not in chain:
                chain.append(m)
                if len(chain) == len(self._members):
                    break
        return chain
