"""`ServingCell`: replicas + router + supervisor + sigstore tier, wired.

The one-stop assembly the chaos sweep, the gauntlet cell leg, and the
mini-workload use: N replicas (subprocesses by default, in-process
stubs with ``stub=True``), each with its own `PersistentSigCache`
store under the tier root (shared salt), fronted by a `CellRouter`
and supervised by a `ReplicaSupervisor`.

The supervision hooks close the failure loop:

- **evict** — routing flips first (`router.set_healthy(name, False)`
  is synchronous: when it returns, no new frame reaches the member and
  its in-flight frames are on the retry-once/explicit-ERR path), then
  the member leaves the tier ring and its shard logs stream to the new
  owners (`SigTier.handoff_from`), absorbed through each survivor's
  control surface. Reads racing the handoff simply miss and recompute —
  fail-closed by construction.
- **promote** — only ever reached through a passing known-answer probe;
  the router learns the restarted replica's fresh port, the member
  rejoins the tier ring, and routing flips back. Its shards return
  cold (their keys now live on the survivors) and warm back up through
  normal traffic — the tier never hands cached verdicts to a member
  that hasn't re-earned them.

Drive it tick-by-tick (`cell.tick()`, deterministic — what the tests
and chaos trials do) or start the background supervisor loop with
``cell.start(supervise=True)``.
"""

from __future__ import annotations

import shutil
import tempfile
from typing import Dict, List, Optional

from .replica import ReplicaProcess, ReplicaSupervisor, StubReplica
from .router import CellRouter
from .sigtier import SigTier

__all__ = ["ServingCell"]


class ServingCell:
    def __init__(
        self,
        n_replicas: int = 2,
        root_dir: Optional[str] = None,
        stub: bool = False,
        shards: int = 8,
        server_kw: Optional[dict] = None,
        evict_after: Optional[int] = None,
        host_only: bool = True,
        probe_items=None,
        backoff_s: float = 0.25,
        max_backoff_s: float = 2.0,
        probe_timeout_s: Optional[float] = None,
    ):
        self.n_replicas = n_replicas
        self._own_root = root_dir is None
        self.root_dir = root_dir or tempfile.mkdtemp(prefix="cell-")
        self.stub = stub
        self.shards = shards
        self.server_kw = dict(server_kw or {})
        self.evict_after = evict_after
        self.host_only = host_only
        self.probe_items = probe_items
        self.backoff_s = backoff_s
        self.max_backoff_s = max_backoff_s
        self.probe_timeout_s = probe_timeout_s
        self.tier: Optional[SigTier] = None
        self.router: Optional[CellRouter] = None
        self.supervisor: Optional[ReplicaSupervisor] = None
        self.replicas: Dict[str, object] = {}
        self._started = False

    # -- lifecycle -----------------------------------------------------

    def start(self, supervise: bool = False) -> "ServingCell":
        if self._started:
            return self
        self._started = True
        self.tier = SigTier(self.root_dir, shards=self.shards)
        cls = StubReplica if self.stub else ReplicaProcess
        for i in range(self.n_replicas):
            name = f"r{i}"
            store_dir = self.tier.join(name)
            self.replicas[name] = cls(
                name,
                store_dir=store_dir,
                host_only=self.host_only,
                server_kw=self.server_kw,
            ).start()
        self.router = CellRouter(
            {n: r.addr for n, r in self.replicas.items()}
        ).start()
        self.supervisor = ReplicaSupervisor(
            self.replicas,
            probe_items=self.probe_items,
            evict_after=self.evict_after,
            probe_timeout_s=self.probe_timeout_s,
            backoff_s=self.backoff_s,
            max_backoff_s=self.max_backoff_s,
            on_evict=self._on_evict,
            on_promote=self._on_promote,
        )
        if supervise:
            self.supervisor.run_forever()
        return self

    def __enter__(self) -> "ServingCell":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()

    @property
    def port(self) -> int:
        if self.router is None or self.router.port is None:
            raise RuntimeError("cell not started")
        return self.router.port

    def tick(self) -> None:
        self.supervisor.tick()

    def healthy_names(self) -> List[str]:
        return self.supervisor.healthy_names()

    def close(self) -> None:
        if self.supervisor is not None:
            self.supervisor.stop()
        if self.router is not None:
            self.router.close(drain=True)
        for r in self.replicas.values():
            try:
                r.close()
            except Exception:
                pass
        if self._own_root:
            shutil.rmtree(self.root_dir, ignore_errors=True)

    # -- supervision hooks ---------------------------------------------

    def _absorb(self, dest: str, path: str) -> Optional[dict]:
        handle = self.replicas.get(dest)
        if handle is None:
            return None
        try:
            reply = handle.control({"cmd": "absorb", "path": path})
        except Exception:
            return None
        return reply if reply.get("ok") else None

    def _on_evict(self, name: str) -> None:
        self.router.set_healthy(name, False)
        if name in self.tier.ring:
            self.tier.leave(name)
            if len(self.tier.ring):
                self.tier.handoff_from(name, self._absorb)

    def _on_promote(self, name: str) -> None:
        self.router.set_addr(name, self.replicas[name].addr)
        self.tier.join(name)
        self.router.set_healthy(name, True)
