"""Cell replicas: supervised verify workers, each on its own port.

A replica is one `IngressServer`+`VerifyServer` stack with its own
`PersistentSigCache` store. Two implementations share one handle
contract (`addr`, `is_alive()`, `kill()`, `restart()`):

- `ReplicaProcess` — a real subprocess (`python -m
  bitcoinconsensus_tpu.cell.replica`), the production shape: a kill -9
  takes out the whole worker, and the chaos sweep does exactly that.
  Alongside the ingress port it opens a JSON-line control channel
  (stats / absorb / peek / flush) so the supervisor can drive sigstore
  handoff across the process boundary.
- `StubReplica` — the same stack in-process, for router-logic units
  and the mini-workload leg where subprocess spawn cost buys nothing.

`ReplicaSupervisor` health-checks replicas with known-answer probe
verifies, reusing the guards.py sentinel discipline: every probe
exercises BOTH verdict sides — one known-valid item must come back
accepted and one known-corrupt item rejected — so a replica that fails
open (accepts everything) is exactly as convicted as one that crashes.
Probe failures accumulate per replica; at
``BITCOINCONSENSUS_TPU_CELL_EVICT_AFTER`` consecutive failures
(mirroring `ShardLadder`'s count-based eviction) the replica is
evicted: flight-recorder conviction dump (carrying the failing probe
events), router re-route, sigstore handoff. Restart follows bounded
exponential backoff, and re-promotion only ever happens through a
passing known-answer probe — the same discipline `degrade.py` applies
to rungs.

The supervisor is deliberately tick-driven (`tick()` advances one
supervision round) so tests and the chaos sweep control time
explicitly; `run_forever` wraps it in a thread for live cells.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
from typing import Callable, Dict, List, Optional, Tuple

from ..obs import counter as _obs_counter
from ..obs import flight as _flight
from ..obs import gauge as _obs_gauge
from ..obs import monotonic as _monotonic
from ..resilience.degrade import HOST_LEVEL

__all__ = [
    "ReplicaProcess",
    "ReplicaSupervisor",
    "StubReplica",
    "make_probe_items",
    "probe_replica",
]

_G_HEALTHY = _obs_gauge(
    "consensus_cell_replicas_healthy",
    "replicas currently healthy (probe-passing) in the serving cell",
)
_C_EVICTIONS = _obs_counter(
    "consensus_cell_evictions_total",
    "replica evictions (crash or known-answer probe failure streak)",
)
_C_REPROMOTIONS = _obs_counter(
    "consensus_cell_repromotions_total",
    "replicas re-promoted to healthy after a passing known-answer probe",
)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, ""))
    except ValueError:
        return default


def _force_host(verifier) -> None:
    """Pin a verifier's degradation ladder to the terminal host rung.

    Replicas in CPU containers (tests, chaos) must never trigger a jit
    compile — `inflight.dispatch` contains host-level tickets before
    any device work, so parking the ladder on HOST_LEVEL (and pushing
    the probe cadence out of reach, lest a probe dispatch compile) makes
    a replica cost milliseconds instead of minutes while keeping the
    verdict path host-exact."""
    lad = verifier._resilience.ladder
    lad._idx = lad.levels.index(HOST_LEVEL)
    lad.probe_after = 1 << 30


def make_probe_items():
    """The known-answer probe pair: (must-accept item, must-reject item).

    Deterministic single-signature spends (guards.py sentinel
    discipline): the reject item's signature is well-formed but
    cryptographically false, so a replica answering it `ok` has a
    broken verify path, not a parse error."""
    from ..core.flags import VERIFY_ALL_EXTENDED
    from ..models.batch import BatchItem
    from ..utils import blockgen

    _, funded = blockgen.make_funded_view(
        2, kinds=("p2wpkh",), seed="cell-probe"
    )
    items = []
    for j, f in enumerate(funded):
        tx = blockgen.build_spend_tx([f], corrupt_input=(0 if j else None))
        items.append(
            BatchItem(
                tx.serialize(), 0, VERIFY_ALL_EXTENDED,
                spent_outputs=[(f.amount, f.wallet.spk)],
            )
        )
    return items[0], items[1]


def probe_replica(
    addr: Tuple[str, int], probe_items, timeout_s: float = 5.0
) -> bool:
    """One known-answer probe over the wire: accept item must verify
    True AND reject item must verify False. Any transport error, shed,
    or wrong-side verdict fails the probe — fail-closed."""
    from ..serving.client import IngressClient

    good, bad = probe_items
    try:
        with IngressClient(
            addr[0], port=addr[1], timeout_s=timeout_s
        ) as cli:
            if not cli.verify(good, tenant="_probe").ok:
                return False
            if cli.verify(bad, tenant="_probe").ok:
                return False
        return True
    except Exception:
        return False


# -- in-process stub ---------------------------------------------------


class StubReplica:
    """The replica stack in-process: real wire protocol, no subprocess.

    Uses its own `TpuSecpVerifier` instance (never the process-global
    default — stubs pin their ladder to the host rung and must not
    mutate shared state). `kill()` is abrupt (no drain), modelling a
    crash as closely as an in-process stub can; `force_sick` makes the
    supervisor's probes fail without tearing anything down, for
    deterministic eviction-threshold tests."""

    def __init__(
        self,
        name: str,
        store_dir: Optional[str] = None,
        host_only: bool = True,
        server_kw: Optional[dict] = None,
    ):
        self.name = name
        self.store_dir = store_dir
        self.host_only = host_only
        self.server_kw = dict(server_kw or {})
        self.force_sick = False
        self.store = None
        self._vs = None
        self._ing = None

    @property
    def addr(self) -> Tuple[str, int]:
        if self._ing is None:
            raise RuntimeError("stub replica not started")
        return ("127.0.0.1", self._ing.port)

    def start(self) -> "StubReplica":
        from ..crypto.jax_backend import TpuSecpVerifier
        from ..models.sigcache import ScriptExecutionCache
        from ..serving import IngressServer, VerifyServer

        if self.store_dir is not None:
            from ..models.sigstore import PersistentSigCache

            self.store = PersistentSigCache(self.store_dir)
        verifier = TpuSecpVerifier(min_batch=8)
        if self.host_only:
            _force_host(verifier)
        self._vs = VerifyServer(
            verifier=verifier,
            sig_cache=self.store,
            script_cache=ScriptExecutionCache(),
            **self.server_kw,
        ).start()
        self._ing = IngressServer(self._vs).start()
        return self

    def is_alive(self) -> bool:
        return self._ing is not None

    def kill(self) -> None:
        """Abrupt stop: no drain, in-flight sessions see a reset —
        the closest an in-process stub gets to kill -9. The store's
        appends are already on disk (one fsync'd record per mutation),
        so closing it loses nothing a crash wouldn't keep."""
        ing, vs, store = self._ing, self._vs, self.store
        self._ing = self._vs = self.store = None
        if ing is not None:
            ing.close(drain=False)
        if vs is not None:
            vs.close(drain=False)
        if store is not None:
            store.close()

    def restart(self) -> "StubReplica":
        if self.is_alive():
            self.kill()
        return self.start()

    def close(self) -> None:
        self.kill()

    # Control surface, mirroring the subprocess JSON protocol so cell
    # plumbing (handoff absorb, stats) is handle-agnostic.
    def control(self, obj: dict) -> dict:
        cmd = obj.get("cmd")
        if cmd == "ping":
            return {"ok": True}
        if self.store is None:
            return {"ok": False, "error": "no store"}
        if cmd == "stats":
            return {
                "ok": True,
                "entries": len(self.store),
                "probes": self.store._probes_since_open,
                "hits": self.store._hits_since_open,
            }
        if cmd == "absorb":
            from .sigtier import absorb_handoff

            return {"ok": True, **absorb_handoff(self.store, obj["path"])}
        if cmd == "peek":
            return {
                "ok": True,
                "present": self.store.peek_key(bytes.fromhex(obj["key"])),
            }
        if cmd == "flush":
            self.store.flush()
            return {"ok": True}
        return {"ok": False, "error": f"unknown cmd {cmd!r}"}


# -- subprocess replica ------------------------------------------------

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


class ReplicaProcess:
    """A replica as a real OS process; see the module docstring.

    The child prints ``READY <ingress_port> <ctrl_port>`` on stdout once
    both sockets are bound, then blocks until its stdin reaches EOF
    (closing our pipe end is the graceful-stop signal; `kill()` is
    SIGKILL). Restart spawns a fresh process on fresh ephemeral ports —
    the supervisor re-routes by name, so port churn is invisible above
    the handle."""

    def __init__(
        self,
        name: str,
        store_dir: Optional[str] = None,
        host_only: bool = True,
        server_kw: Optional[dict] = None,
        spawn_timeout_s: float = 120.0,
    ):
        self.name = name
        self.store_dir = store_dir
        self.host_only = host_only
        self.server_kw = dict(server_kw or {})
        self.spawn_timeout_s = spawn_timeout_s
        self.port: Optional[int] = None
        self.ctrl_port: Optional[int] = None
        self._proc: Optional[subprocess.Popen] = None

    @property
    def addr(self) -> Tuple[str, int]:
        if self.port is None:
            raise RuntimeError("replica process not started")
        return ("127.0.0.1", self.port)

    def start(self) -> "ReplicaProcess":
        cmd = [
            sys.executable, "-m", "bitcoinconsensus_tpu.cell.replica",
            "--name", self.name,
        ]
        if self.store_dir is not None:
            cmd += ["--store-dir", self.store_dir]
        if self.host_only:
            cmd.append("--host-only")
        for k, v in sorted(self.server_kw.items()):
            cmd += [f"--{k.replace('_', '-')}", str(v)]
        env = dict(os.environ)
        if self.host_only:
            # Must land before the child imports jax.
            env["JAX_PLATFORMS"] = "cpu"
        self._proc = subprocess.Popen(
            cmd,
            cwd=_REPO_ROOT,
            env=env,
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            text=True,
        )
        ready: List[str] = []
        evt = threading.Event()

        def _reader() -> None:
            for line in self._proc.stdout:
                if line.startswith("READY "):
                    ready.append(line.strip())
                    evt.set()
                    return
            evt.set()  # EOF before READY: child died during startup

        t = threading.Thread(target=_reader, daemon=True)
        t.start()
        if not evt.wait(self.spawn_timeout_s) or not ready:
            self.kill()
            raise RuntimeError(
                f"replica {self.name!r} did not come up "
                f"(rc={self._proc.poll()})"
            )
        _, port, ctrl = ready[0].split()
        self.port, self.ctrl_port = int(port), int(ctrl)
        return self

    def is_alive(self) -> bool:
        return self._proc is not None and self._proc.poll() is None

    def kill(self) -> None:
        if self._proc is not None:
            try:
                self._proc.kill()
            except OSError:
                pass
            self._proc.wait()

    def restart(self) -> "ReplicaProcess":
        if self.is_alive():
            self.kill()
        return self.start()

    def close(self) -> None:
        """Graceful stop: close the stdin pipe (the child's exit
        signal) and wait briefly; escalate to SIGKILL."""
        if self._proc is None:
            return
        try:
            self._proc.stdin.close()
        except OSError:
            pass
        try:
            self._proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            self.kill()

    def control(self, obj: dict, timeout_s: float = 30.0) -> dict:
        if self.ctrl_port is None:
            raise RuntimeError("replica process not started")
        with socket.create_connection(
            ("127.0.0.1", self.ctrl_port), timeout=timeout_s
        ) as sock:
            sock.sendall((json.dumps(obj) + "\n").encode("utf-8"))
            buf = bytearray()
            while not buf.endswith(b"\n"):
                chunk = sock.recv(65536)
                if not chunk:
                    break
                buf.extend(chunk)
        return json.loads(buf.decode("utf-8"))


# -- supervisor --------------------------------------------------------


class _ReplicaState:
    __slots__ = ("healthy", "fail_streak", "attempts", "next_retry_at")

    def __init__(self) -> None:
        self.healthy = True
        self.fail_streak = 0
        self.attempts = 0
        self.next_retry_at = 0.0


class ReplicaSupervisor:
    """Health-driven eviction/restart/re-promotion over replica handles.

    Tick-driven: each `tick()` probes every healthy replica
    (known-answer, both verdict sides) and advances restart backoff for
    evicted ones. `on_evict`/`on_promote` are the cell's hooks — the
    router flips routing health and the sigstore tier runs handoff
    there, so supervision stays policy-only."""

    def __init__(
        self,
        replicas: Dict[str, object],
        probe_items=None,
        evict_after: Optional[int] = None,
        probe_timeout_s: Optional[float] = None,
        backoff_s: float = 0.25,
        max_backoff_s: float = 2.0,
        on_evict: Optional[Callable[[str], None]] = None,
        on_promote: Optional[Callable[[str], None]] = None,
    ):
        self.replicas = dict(replicas)
        self.probe_items = (
            probe_items if probe_items is not None else make_probe_items()
        )
        self.evict_after = (
            evict_after
            if evict_after is not None
            else _env_int("BITCOINCONSENSUS_TPU_CELL_EVICT_AFTER", 3)
        )
        self.probe_timeout_s = (
            probe_timeout_s
            if probe_timeout_s is not None
            else _env_float("BITCOINCONSENSUS_TPU_CELL_PROBE_TIMEOUT_S", 5.0)
        )
        self.backoff_s = backoff_s
        self.max_backoff_s = max_backoff_s
        self.on_evict = on_evict
        self.on_promote = on_promote
        self._state = {name: _ReplicaState() for name in self.replicas}
        self.backoff_log: Dict[str, List[float]] = {
            name: [] for name in self.replicas
        }
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        _G_HEALTHY.set(len(self.replicas))

    # -- introspection -------------------------------------------------

    def healthy_names(self) -> List[str]:
        with self._lock:
            return [n for n, s in self._state.items() if s.healthy]

    def is_healthy(self, name: str) -> bool:
        with self._lock:
            return self._state[name].healthy

    def _set_gauge_locked(self) -> None:
        _G_HEALTHY.set(sum(1 for s in self._state.values() if s.healthy))

    # -- probing -------------------------------------------------------

    def _probe(self, name: str) -> bool:
        r = self.replicas[name]
        if getattr(r, "force_sick", False):
            return False
        return probe_replica(r.addr, self.probe_items, self.probe_timeout_s)

    # -- supervision round ---------------------------------------------

    def tick(self) -> None:
        """One supervision round over every replica. Serialized: probes
        and membership transitions must not interleave."""
        with self._lock:
            for name, r in self.replicas.items():
                st = self._state[name]
                if st.healthy:
                    self._tick_healthy_locked(name, r, st)
                else:
                    self._tick_evicted_locked(name, r, st)

    def _tick_healthy_locked(self, name, r, st) -> None:
        if not r.is_alive():
            _flight.record("cell.probe", replica=name, ok=False,
                           cause="dead")
            self._evict_locked(name, st, reason="dead")
            return
        ok = self._probe(name)
        _flight.record("cell.probe", replica=name, ok=ok)
        if ok:
            st.fail_streak = 0
            return
        st.fail_streak += 1
        if st.fail_streak >= self.evict_after:
            self._evict_locked(name, st, reason="probe")

    def _tick_evicted_locked(self, name, r, st) -> None:
        now = _monotonic()
        if now < st.next_retry_at:
            return
        if not r.is_alive():
            try:
                r.restart()
            except Exception:
                self._backoff_locked(name, st, now)
                return
        ok = self._probe(name)
        _flight.record("cell.probe", replica=name, ok=ok, phase="repromote")
        if ok:
            st.healthy = True
            st.fail_streak = 0
            st.attempts = 0
            self._set_gauge_locked()
            _C_REPROMOTIONS.inc()
            _flight.record("cell.promote", replica=name)
            if self.on_promote is not None:
                self.on_promote(name)
        else:
            self._backoff_locked(name, st, now)

    def _backoff_locked(self, name, st, now: float) -> None:
        delay = min(self.backoff_s * (2 ** st.attempts), self.max_backoff_s)
        st.attempts += 1
        st.next_retry_at = now + delay
        self.backoff_log[name].append(delay)

    def _evict_locked(self, name, st, reason: str) -> None:
        st.healthy = False
        st.attempts = 0
        st.next_retry_at = _monotonic() + self.backoff_s
        self.backoff_log[name].append(self.backoff_s)
        self._set_gauge_locked()
        _C_EVICTIONS.inc()
        # Record the conviction before triggering the dump so the dump
        # carries it alongside the failing probe events (the same
        # record-then-trigger order degrade.py uses).
        _flight.record(
            "cell.evict", replica=name, reason=reason,
            fail_streak=st.fail_streak, evict_after=self.evict_after,
        )
        _flight.trigger("cell_eviction", replica=name, cause=reason)
        if self.on_evict is not None:
            self.on_evict(name)

    # -- background loop -----------------------------------------------

    def run_forever(self, interval_s: float = 0.5) -> "ReplicaSupervisor":
        if self._thread is not None:
            return self

        def _loop() -> None:
            while not self._stop.wait(interval_s):
                self.tick()

        self._thread = threading.Thread(
            target=_loop, name="cell-supervisor", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(10)
            self._thread = None


# -- subprocess entry point --------------------------------------------


def _serve_control(store, sock: socket.socket) -> None:
    """JSON-line control loop: one command per connection."""
    from .sigtier import absorb_handoff

    while True:
        try:
            conn, _ = sock.accept()
        except OSError:
            return
        try:
            with conn:
                fh = conn.makefile("rw", encoding="utf-8")
                line = fh.readline()
                if not line:
                    continue
                obj = json.loads(line)
                cmd = obj.get("cmd")
                if cmd == "ping":
                    reply = {"ok": True}
                elif store is None:
                    reply = {"ok": False, "error": "no store"}
                elif cmd == "stats":
                    reply = {
                        "ok": True,
                        "entries": len(store),
                        "probes": store._probes_since_open,
                        "hits": store._hits_since_open,
                    }
                elif cmd == "absorb":
                    reply = {"ok": True,
                             **absorb_handoff(store, obj["path"])}
                elif cmd == "peek":
                    reply = {
                        "ok": True,
                        "present": store.peek_key(
                            bytes.fromhex(obj["key"])
                        ),
                    }
                elif cmd == "flush":
                    store.flush()
                    reply = {"ok": True}
                else:
                    reply = {"ok": False, "error": f"unknown cmd {cmd!r}"}
                fh.write(json.dumps(reply) + "\n")
                fh.flush()
        except Exception:
            continue  # a broken control exchange must not kill the replica


def replica_main(argv: Optional[List[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(description="serving-cell replica worker")
    p.add_argument("--name", required=True)
    p.add_argument("--store-dir", default=None)
    p.add_argument("--host-only", action="store_true")
    p.add_argument("--max-batch", type=int, default=None)
    p.add_argument("--flush-s", type=float, default=None)
    p.add_argument("--tenant-depth", type=int, default=None)
    args = p.parse_args(argv)

    from ..crypto.jax_backend import TpuSecpVerifier
    from ..models.sigcache import ScriptExecutionCache
    from ..serving import IngressServer, VerifyServer

    store = None
    if args.store_dir is not None:
        from ..models.sigstore import PersistentSigCache

        store = PersistentSigCache(args.store_dir)
    verifier = TpuSecpVerifier(min_batch=8)
    if args.host_only:
        _force_host(verifier)
    server_kw = {}
    if args.max_batch is not None:
        server_kw["max_batch"] = args.max_batch
    if args.flush_s is not None:
        server_kw["flush_s"] = args.flush_s
    if args.tenant_depth is not None:
        server_kw["tenant_depth"] = args.tenant_depth
    vs = VerifyServer(
        verifier=verifier,
        sig_cache=store,
        script_cache=ScriptExecutionCache(),
        **server_kw,
    ).start()
    ing = IngressServer(vs).start()
    ctrl = socket.create_server(("127.0.0.1", 0))
    threading.Thread(
        target=_serve_control, args=(store, ctrl), daemon=True
    ).start()
    print(f"READY {ing.port} {ctrl.getsockname()[1]}", flush=True)
    try:
        sys.stdin.read()  # EOF = parent closed our pipe: shut down
    except KeyboardInterrupt:
        pass
    ing.close(drain=True)
    vs.close(drain=True)
    try:
        ctrl.close()
    except OSError:
        pass
    if store is not None:
        store.close()
    return 0


if __name__ == "__main__":
    sys.exit(replica_main())
