"""Tenant-hash front door for the serving cell.

`CellRouter` speaks the exact ingress frame protocol (serving/ingress.py
codec, reused verbatim) on the client side and acts as an ingress
*client* toward replicas on the other — existing `IngressClient` /
`verify_with_retry` callers point at the router port and need no
changes. Forwarded frames are byte-identical, so `rid` correlation is
preserved end to end: a pipelined client sees the same rids it sent,
in whatever order replicas settle them.

Routing: tenant → replica by consistent hash (cell/hashring.py), one
upstream connection per (client session, replica) so rids from
different client sessions can never collide at a replica. Two rings:
the *home* ring over full membership (for accounting — serving a
tenant off its home replica counts `consensus_cell_reroutes_total`)
and the *healthy* ring the supervisor edits via `set_healthy`.

Failure semantics — every admitted frame ends in exactly one explicit
outcome, never silence:

- Replica sick/evicted: its tenants re-route to the next healthy
  member clockwise. Frames in flight to the dead upstream are retried
  **exactly once** on the new owner (verdicts are pure functions of the
  item, so the replay is idempotent; `consensus_cell_retried_frames_total`)
  or, if already retried or no survivor exists, answered with a typed
  `ERR_OVERLOADED` frame (`replica_lost`) the retry client may resend.
- No healthy replica for a tenant: explicit `ERR_OVERLOADED`
  (`no_replica`), session stays open.
- Oversized / malformed / bad-type client frames: typed protocol ERR
  (>= 0x100, never retried) then close — the ingress discipline.

Chaos site `cell.route` models a router-side partition: an injected
fault tears down one client session mid-read, exactly like
`ingress.read`, and `verify_with_retry` recovers by reconnecting.
Swept by `scripts/consensus_chaos.py --cell`.
"""

from __future__ import annotations

import asyncio
import socket
import threading
from typing import Dict, List, Optional, Tuple

from ..api import Error
from ..obs import counter as _obs_counter
from ..obs import flight as _flight
from ..obs import monotonic as _monotonic
from ..resilience import faults as _faults
from ..serving.ingress import (
    ERR_PROTO_BAD_TYPE,
    ERR_PROTO_MALFORMED,
    ERR_PROTO_OVERSIZED,
    FRAME_ERR,
    FRAME_REQ,
    FRAME_RESP,
    HEADER_LEN,
    decode_header,
    encode_error,
    encode_frame,
)
from .hashring import HashRing

__all__ = ["CellRouter"]

_C_REROUTES = _obs_counter(
    "consensus_cell_reroutes_total",
    "frames served off the tenant's home replica (health-driven failover)",
)
_C_RETRIED = _obs_counter(
    "consensus_cell_retried_frames_total",
    "in-flight frames replayed exactly once on a survivor after their "
    "upstream replica died",
)


class _Upstream:
    """One router→replica connection owned by one client session."""

    __slots__ = ("name", "reader", "writer", "inflight", "task")

    def __init__(self, name: str, reader, writer):
        self.name = name
        self.reader = reader
        self.writer = writer
        # rid -> [raw REQ frame, tenant, already-retried flag]
        self.inflight: Dict[int, list] = {}
        self.task: Optional[asyncio.Task] = None


class _RouterSession:
    __slots__ = ("reader", "writer", "wlock", "upstreams", "alive")

    def __init__(self, reader, writer):
        self.reader = reader
        self.writer = writer
        self.wlock = asyncio.Lock()
        self.upstreams: Dict[str, _Upstream] = {}
        self.alive = True


class CellRouter:
    """Consistent-hash tenant router over replica addresses.

    Lifecycle mirrors `IngressServer`: the listening socket binds
    synchronously in `start()`, sessions run on a dedicated asyncio
    loop in a daemon thread, and `close(drain=True)` waits for frames
    in flight to replicas to settle before tearing sessions down.
    `set_healthy`/`set_addr` are thread-safe (the supervisor calls them
    from its own thread) and synchronous — when `set_healthy(name,
    False)` returns, the routing flip has been applied and the dead
    member's upstream links are closing, so the caller may proceed to
    sigstore handoff knowing no new frame will reach it."""

    def __init__(
        self,
        replicas: Dict[str, Tuple[str, int]],
        host: str = "127.0.0.1",
        port: Optional[int] = None,
        idle_s: float = 30.0,
        max_frame: int = 1 << 20,
        drain_timeout_s: float = 30.0,
        connect_timeout_s: float = 5.0,
        vnodes: int = 64,
    ):
        self._addrs = dict(replicas)
        self.host = host
        self._want_port = port or 0
        self.idle_s = idle_s
        self.max_frame = max_frame
        self.drain_timeout_s = drain_timeout_s
        self.connect_timeout_s = connect_timeout_s
        members = sorted(self._addrs)
        self._home = HashRing(members, vnodes=vnodes)
        self._healthy = HashRing(members, vnodes=vnodes)
        self.port: Optional[int] = None
        self._sock: Optional[socket.socket] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._listener = None
        self._sessions: set = set()
        self._tasks: set = set()
        self._closed = False

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "CellRouter":
        if self._thread is not None:
            return self
        if self._closed:
            raise RuntimeError("router already closed")
        self._sock = socket.create_server(
            (self.host, self._want_port), reuse_port=False
        )
        self.port = self._sock.getsockname()[1]
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop.run_forever, name="cell-router", daemon=True
        )
        self._thread.start()
        fut = asyncio.run_coroutine_threadsafe(self._serve(), self._loop)
        fut.result(timeout=10)
        return self

    async def _serve(self) -> None:
        self._listener = await asyncio.start_server(
            self._handle, sock=self._sock
        )

    def __enter__(self) -> "CellRouter":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close(drain=exc_type is None)

    def close(self, drain: bool = True) -> None:
        if self._closed:
            return
        self._closed = True
        if self._loop is None:
            return
        fut = asyncio.run_coroutine_threadsafe(
            self._shutdown(drain), self._loop
        )
        fut.result(timeout=self.drain_timeout_s + 10)
        self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(10)
        self._loop.close()

    async def _shutdown(self, drain: bool) -> None:
        if self._listener is not None:
            self._listener.close()
            await self._listener.wait_closed()
        if drain:
            deadline = _monotonic() + self.drain_timeout_s
            while (
                any(
                    up.inflight
                    for s in self._sessions
                    for up in s.upstreams.values()
                )
                and _monotonic() < deadline
            ):
                await asyncio.sleep(0.01)
        for sess in list(self._sessions):
            self._teardown(sess)
        tasks = [t for t in self._tasks if not t.done()]
        if tasks:
            await asyncio.wait(tasks, timeout=5)

    def _teardown(self, sess: _RouterSession) -> None:
        sess.alive = False
        for up in list(sess.upstreams.values()):
            try:
                up.writer.close()
            except Exception:
                pass
        try:
            sess.writer.close()
        except Exception:
            pass

    # -- membership (supervisor-facing, thread-safe) ---------------------

    def members(self) -> List[str]:
        return sorted(self._addrs)

    def healthy_members(self) -> List[str]:
        return sorted(self._healthy.members)

    def set_addr(self, name: str, addr: Tuple[str, int]) -> None:
        """Update a member's address (replicas restart on fresh ports)."""
        self._run_on_loop(self._apply_addr(name, addr))

    def set_healthy(self, name: str, healthy: bool) -> None:
        """Flip routing health; synchronous (see class docstring)."""
        self._run_on_loop(self._apply_health(name, healthy))

    def _run_on_loop(self, coro) -> None:
        loop = self._loop
        if loop is None or loop.is_closed():
            coro.close()
            return
        asyncio.run_coroutine_threadsafe(coro, loop).result(timeout=10)

    async def _apply_addr(self, name: str, addr: Tuple[str, int]) -> None:
        self._addrs[name] = addr

    async def _apply_health(self, name: str, healthy: bool) -> None:
        if healthy:
            self._healthy.add(name)
            return
        self._healthy.remove(name)
        _flight.record("cell.route_sick", replica=name)
        # Close the sick member's upstream links; each pump observes the
        # close and runs the retry-once / explicit-ERR failover for its
        # in-flight frames.
        for sess in self._sessions:
            up = sess.upstreams.get(name)
            if up is not None:
                try:
                    up.writer.close()
                except Exception:
                    pass

    # -- client side ----------------------------------------------------

    async def _handle(self, reader, writer) -> None:
        sess = _RouterSession(reader, writer)
        self._sessions.add(sess)
        self._tasks.add(asyncio.current_task())
        try:
            await self._session_loop(sess)
        finally:
            self._tasks.discard(asyncio.current_task())
            self._sessions.discard(sess)
            self._teardown(sess)
            try:
                await writer.wait_closed()
            except Exception:
                pass

    async def _read_exactly(self, sess: _RouterSession, n: int) -> bytes:
        # `cell.route` models a router partition: the injected fault
        # tears down this one client session (the peer sees a reset and
        # `verify_with_retry` reconnects); routing state is untouched.
        _faults.maybe_raise("cell.route")
        return await asyncio.wait_for(
            sess.reader.readexactly(n), self.idle_s
        )

    async def _session_loop(self, sess: _RouterSession) -> None:
        while sess.alive:
            try:
                hdr = await self._read_exactly(sess, HEADER_LEN)
            except asyncio.IncompleteReadError:
                return
            except (asyncio.TimeoutError, TimeoutError):
                return
            except (_faults.InjectedFault, ConnectionError, OSError):
                return
            ftype, ln = decode_header(hdr)
            if ln > self.max_frame:
                await self._send_err(
                    sess, 0, ERR_PROTO_OVERSIZED,
                    f"frame of {ln} bytes exceeds max_frame={self.max_frame}",
                )
                return
            try:
                payload = await self._read_exactly(sess, ln)
            except (
                asyncio.IncompleteReadError,
                asyncio.TimeoutError,
                TimeoutError,
                _faults.InjectedFault,
                ConnectionError,
                OSError,
            ):
                return
            if not await self._route(sess, ftype, payload):
                return

    async def _route(
        self, sess: _RouterSession, ftype: int, payload: bytes
    ) -> bool:
        """Route one inbound frame; False closes the session."""
        if ftype != FRAME_REQ:
            await self._send_err(
                sess, 0, ERR_PROTO_BAD_TYPE, f"unexpected frame type {ftype}"
            )
            return False
        # Cheap peek: rid and tenant prefix the REQ payload by design —
        # the router never decodes the item it forwards.
        if len(payload) < 6:
            await self._send_err(
                sess, 0, ERR_PROTO_MALFORMED, "short request payload"
            )
            return False
        rid = int.from_bytes(payload[0:4], "big")
        tlen = int.from_bytes(payload[4:6], "big")
        if len(payload) < 6 + tlen:
            await self._send_err(
                sess, 0, ERR_PROTO_MALFORMED, "truncated tenant"
            )
            return False
        try:
            tenant = payload[6 : 6 + tlen].decode("utf-8")
        except UnicodeDecodeError:
            await self._send_err(
                sess, 0, ERR_PROTO_MALFORMED, "tenant not utf-8"
            )
            return False
        owner = self._healthy.lookup(tenant)
        if owner is None:
            # Explicit, typed, retryable — overload is the cell's state.
            return await self._send_err(
                sess, rid, int(Error.ERR_OVERLOADED), "no_replica"
            )
        if owner != self._home.lookup(tenant):
            _C_REROUTES.inc()
        frame = encode_frame(FRAME_REQ, payload)
        if not await self._forward(sess, owner, rid, frame, tenant, False):
            return await self._send_err(
                sess, rid, int(Error.ERR_OVERLOADED), "replica_connect"
            )
        return True

    # -- replica side ----------------------------------------------------

    async def _get_upstream(
        self, sess: _RouterSession, owner: str
    ) -> Optional[_Upstream]:
        up = sess.upstreams.get(owner)
        if up is not None:
            return up
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(*self._addrs[owner]),
                self.connect_timeout_s,
            )
        except (OSError, asyncio.TimeoutError, TimeoutError):
            return None
        up = _Upstream(owner, reader, writer)
        sess.upstreams[owner] = up
        up.task = asyncio.get_running_loop().create_task(
            self._pump(sess, up)
        )
        self._tasks.add(up.task)
        up.task.add_done_callback(self._tasks.discard)
        return up

    async def _forward(
        self,
        sess: _RouterSession,
        owner: str,
        rid: int,
        frame: bytes,
        tenant: str,
        retried: bool,
    ) -> bool:
        up = await self._get_upstream(sess, owner)
        if up is None:
            return False
        up.inflight[rid] = [frame, tenant, retried]
        try:
            up.writer.write(frame)
            await up.writer.drain()
        except (ConnectionError, OSError):
            # The pump's failover owns frames that made it into the
            # inflight table of a dying upstream — but this one never
            # left the router, so reclaim it and report failure.
            up.inflight.pop(rid, None)
            return False
        return True

    async def _pump(self, sess: _RouterSession, up: _Upstream) -> None:
        """Forward one upstream's RESP/ERR frames back to the client,
        verbatim (rid untouched); on upstream death run failover."""
        try:
            while True:
                hdr = await up.reader.readexactly(HEADER_LEN)
                ftype, ln = decode_header(hdr)
                if ftype not in (FRAME_RESP, FRAME_ERR) or ln > self.max_frame:
                    break
                payload = await up.reader.readexactly(ln)
                rid = int.from_bytes(payload[0:4], "big")
                if rid == 0:
                    # Session-level ERR from the replica (idle reap,
                    # drain): this link is done; in-flight frames take
                    # the failover path below.
                    break
                up.inflight.pop(rid, None)
                await self._send(sess, ftype, payload)
        except (
            asyncio.IncompleteReadError,
            ConnectionError,
            OSError,
        ):
            pass
        except asyncio.CancelledError:
            raise
        finally:
            await self._upstream_down(sess, up)

    async def _upstream_down(
        self, sess: _RouterSession, up: _Upstream
    ) -> None:
        if sess.upstreams.get(up.name) is up:
            del sess.upstreams[up.name]
        try:
            up.writer.close()
        except Exception:
            pass
        inflight, up.inflight = up.inflight, {}
        if not inflight:
            return
        _flight.record(
            "cell.upstream_down", replica=up.name, inflight=len(inflight)
        )
        for rid, (frame, tenant, retried) in sorted(inflight.items()):
            if not sess.alive:
                return
            owner = self._survivor_for(tenant, up.name)
            if owner is not None and not retried:
                if await self._forward(sess, owner, rid, frame, tenant, True):
                    _C_RETRIED.inc()
                    continue
            # Already retried once, or no survivor reachable: explicit
            # typed failure the retry client may resend — never silence.
            await self._send_err(
                sess, rid, int(Error.ERR_OVERLOADED), "replica_lost"
            )

    def _survivor_for(self, tenant: str, dead: str) -> Optional[str]:
        for m in self._healthy.lookup_chain(tenant):
            if m != dead:
                return m
        return None

    # -- client writes ---------------------------------------------------

    async def _send_err(
        self, sess: _RouterSession, rid: int, code: int, reason: str
    ) -> bool:
        return await self._send(
            sess, FRAME_ERR, encode_error(rid, code, reason)
        )

    async def _send(
        self, sess: _RouterSession, ftype: int, payload: bytes
    ) -> bool:
        frame = encode_frame(ftype, payload)
        try:
            async with sess.wlock:
                sess.writer.write(frame)
                await sess.writer.drain()
        except (ConnectionError, OSError):
            self._teardown(sess)
            return False
        return True
