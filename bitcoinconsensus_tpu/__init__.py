"""tpu-bitcoinconsensus: TPU-native Bitcoin consensus verification.

A brand-new framework with the capabilities of `rust-bitcoinconsensus`
(Bitcoin Core 0.21's libbitcoinconsensus): byte-exact script verification
across bare/P2SH/segwit-v0/Taproot spends, with every ECDSA/Schnorr
signature check batchable onto TPU via a JAX/Pallas secp256k1 backend.

Layout (see SURVEY.md for the reference layer map this covers):
- ``core``     — host consensus engine: tx codec, interpreter, sighash
- ``crypto``   — secp256k1: pure-Python host oracle + batched JAX backend
- ``ops``      — Pallas/XLA kernels (limb arithmetic, SHA-256)
- ``models``   — verification pipelines (single verify, deferred batch,
                 block replay)
- ``parallel`` — mesh sharding of batches over devices
- ``serving``  — overload-safe front end: coalescing, admission, shedding
- ``utils``    — hashing, helpers
"""

from .api import (
    ConsensusError,
    Error,
    VERIFY_ALL_EXTENDED,
    VERIFY_ALL_LIBCONSENSUS,
    height_to_flags,
    verify,
    verify_with_flags,
    verify_with_spent_outputs,
    version,
)
from .core import flags
from .core.script_error import ScriptError
from .crypto.bip32 import ExtPubKey, pubkey_derive
from .crypto.recovery import recover_compact

__version__ = "0.1.0"

__all__ = [
    "ConsensusError",
    "Error",
    "ExtPubKey",
    "ScriptError",
    "VERIFY_ALL_EXTENDED",
    "VERIFY_ALL_LIBCONSENSUS",
    "flags",
    "height_to_flags",
    "pubkey_derive",
    "recover_compact",
    "verify",
    "verify_with_flags",
    "verify_with_spent_outputs",
    "version",
]
