"""Deterministic, seed-driven fault injection for the dispatch path.

Chaos discipline (the `CCheckQueue` analogue of Jepsen-style fault
drills): every fault the containment layer claims to survive is
*injectable on demand*, reproducibly, so `scripts/consensus_chaos.py`
and CI can assert the claim instead of trusting it.

Design constraints:

- **Deterministic.** An injector is a (plan, seed) pair; the same pair
  fires the same faults at the same sites in the same order, and lane
  selection for verdict corruption comes from a seeded PRNG. A chaos
  failure in CI replays locally from its seed.
- **Bounded.** Every `FaultSpec` carries a `count`; once drained the
  site goes quiet, so retry/degradation logic can be tested both in the
  "transient fault, retry succeeds" and the "persistent fault, quarantine
  + host fallback" regimes by choosing `count`.
- **Free when idle.** Every hook's fast path is one module-global read
  (`_active is None`); production traffic pays nothing for the harness
  being linked in.

Sites registered by the pipeline (grep for the literal):

    jax_backend.dispatch    raise/timeout at device dispatch
    jax_backend.verdict     corrupt the materialized verdict buffer
    mesh.dispatch           raise at sharded dispatch (whole-mesh drop)
    mesh.shard.<i>          per-shard: raise/timeout/device-loss at shard
                            settle, corrupt that shard's verdict slice,
                            or straggle (delay) the shard past its
                            deadline
    mesh.probe              raise during an evicted-device re-promotion
                            probe (keeps the device quarantined)
    batch.dispatch          raise at the batch driver's resolve step
    sigcache.sig            poisoned hit on the signature cache
    ingress.read            raise on a socket-session frame read (the
                            session tears down; the listener survives)
    ingress.write           raise on a socket-session response write
    sigstore.load           raise during a persistent-store shard replay
                            (that shard starts cold; contained)
    sigstore.append         raise on a persistent-store log append (the
                            entry stays unpersisted; verdicts unaffected)
    cell.route              raise on a cell-router client-session frame
                            read (router partition: that session tears
                            down, routing state and replicas survive,
                            `verify_with_retry` reconnects)

This module is host-side policy, never consensus; it is linted with the
clock rule only (`analysis/host_lint.py`) and reads no clocks at all.
"""

from __future__ import annotations

import random
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..obs import counter as _obs_counter

__all__ = [
    "FaultPlan",
    "FaultSpec",
    "FaultInjector",
    "InjectedDeviceLoss",
    "InjectedFault",
    "InjectedTimeout",
    "active",
    "corrupt_verdict",
    "inject",
    "maybe_raise",
    "poison_hit",
    "shard_delay",
]

_FAULTS_FIRED = _obs_counter(
    "consensus_resilience_faults_injected_total",
    "chaos-harness faults fired, by site and kind",
    ("site", "kind"),
)

# Corruption kinds vs raise kinds: `corrupt_verdict` consumes the former,
# `maybe_raise` the latter, so one plan can arm both on one site.
# "straggle" is consumed only by `shard_delay` (per-shard deadline path).
_RAISE_KINDS = ("raise", "timeout", "device-loss")
_CORRUPT_KINDS = ("invert", "flip", "value", "nan", "garbage", "shape")
_STRAGGLE_KINDS = ("straggle",)


class InjectedFault(RuntimeError):
    """A fault fired by the chaos harness (site/kind in the message)."""

    def __init__(self, site: str, kind: str):
        super().__init__(f"injected {kind} fault at {site}")
        self.site = site
        self.kind = kind


class InjectedTimeout(InjectedFault):
    """Injected dispatch timeout (distinct type: deadline-path tests)."""


class InjectedDeviceLoss(InjectedFault):
    """Injected device loss (distinct type: the mesh settle seam treats
    it as a per-shard hardware failure feeding the eviction ladder)."""


@dataclass
class FaultSpec:
    """One armed fault: fire `kind` at `site` up to `count` times.

    kind: "raise" | "timeout"             -> maybe_raise sites
          "device-loss"                   -> maybe_raise sites (distinct
                                             exception type; mesh settle
                                             feeds it to the shard ladder)
          "straggle"                      -> shard_delay sites report
                                             `value` seconds of simulated
                                             shard lag
          "invert"                        -> logical NOT of the whole buffer
          "flip"                          -> flip `lanes` PRNG-chosen lanes
          "value"                         -> set `lanes` lanes to `value`
                                             (int32 cast: non-{0,1} verdict)
          "nan"                           -> set `lanes` lanes to NaN
                                             (float32 cast)
          "garbage"                       -> whole buffer PRNG int32 noise
          "shape"                         -> truncate the buffer by one lane
          "poison"                        -> poison_hit sites report a hit
    """

    site: str
    kind: str
    count: int = 1
    lanes: int = 1
    value: int = 7


class FaultPlan:
    """An ordered set of FaultSpecs; `inject(plan, seed)` arms it."""

    def __init__(self, specs: Sequence[FaultSpec]):
        self.specs = list(specs)

    def __iter__(self):
        return iter(self.specs)


class FaultInjector:
    """Armed plan + seeded PRNG; tracks per-spec remaining fire counts."""

    def __init__(self, plan: FaultPlan, seed: int = 0):
        self._rng = random.Random(seed)
        self._remaining: List[List] = [[spec, spec.count] for spec in plan]
        self.fired: Dict[tuple, int] = {}

    def _take(self, site: str, kinds) -> Optional[FaultSpec]:
        for ent in self._remaining:
            spec, left = ent
            if left > 0 and spec.site == site and spec.kind in kinds:
                ent[1] = left - 1
                key = (site, spec.kind)
                self.fired[key] = self.fired.get(key, 0) + 1
                _FAULTS_FIRED.inc(site=site, kind=spec.kind)
                return spec
        return None

    def total_fired(self) -> int:
        return sum(self.fired.values())


_active: Optional[FaultInjector] = None


def active() -> Optional[FaultInjector]:
    return _active


@contextmanager
def inject(plan: FaultPlan, seed: int = 0):
    """Arm `plan` for the dynamic extent of the block (not reentrant —
    chaos runs are single-plan by design)."""
    global _active
    if _active is not None:
        raise RuntimeError("a fault plan is already armed")
    inj = FaultInjector(plan, seed=seed)
    _active = inj
    try:
        yield inj
    finally:
        _active = None


def maybe_raise(site: str) -> None:
    """Dispatch-site hook: raises when a raise/timeout fault is armed."""
    inj = _active
    if inj is None:
        return
    spec = inj._take(site, _RAISE_KINDS)
    if spec is None:
        return
    if spec.kind == "timeout":
        raise InjectedTimeout(site, spec.kind)
    if spec.kind == "device-loss":
        raise InjectedDeviceLoss(site, spec.kind)
    raise InjectedFault(site, spec.kind)


def shard_delay(site: str) -> float:
    """Shard-settle hook: seconds of simulated lag for this shard.

    Returns 0.0 when disarmed (one module-global read). The mesh settle
    seam adds the returned value to the shard's observed elapsed time,
    so an armed "straggle" spec with `value` past the per-shard deadline
    drives the deadline/redispatch path without real sleeping."""
    inj = _active
    if inj is None:
        return 0.0
    spec = inj._take(site, _STRAGGLE_KINDS)
    if spec is None:
        return 0.0
    return float(spec.value)


def poison_hit(site: str) -> bool:
    """Cache-probe hook: True forces a fabricated hit (poisoned entry)."""
    inj = _active
    if inj is None:
        return False
    return inj._take(site, ("poison",)) is not None


def corrupt_verdict(site: str, arr: np.ndarray) -> np.ndarray:
    """Verdict-buffer hook: returns a corrupted COPY when armed, else the
    array untouched. Corruption happens before the guards see the buffer,
    so every injected class must be caught (or the chaos gate fails)."""
    inj = _active
    if inj is None:
        return arr
    spec = inj._take(site, _CORRUPT_KINDS)
    if spec is None:
        return arr
    rng = inj._rng
    if spec.kind == "invert":
        return ~np.asarray(arr, dtype=bool)
    if spec.kind == "shape":
        return np.asarray(arr)[:-1]
    if spec.kind == "garbage":
        return np.asarray(
            [rng.randrange(-(2**31), 2**31) for _ in range(len(arr))],
            dtype=np.int32,
        )
    out = np.array(arr)  # writable copy, original dtype
    idxs = [rng.randrange(len(out)) for _ in range(min(spec.lanes, len(out)))]
    if spec.kind == "flip":
        for i in idxs:
            out[i] = not bool(out[i])
        return out
    if spec.kind == "value":
        out = out.astype(np.int32)
        for i in idxs:
            out[i] = spec.value
        return out
    # nan
    out = out.astype(np.float32)
    for i in idxs:
        out[i] = np.nan
    return out
