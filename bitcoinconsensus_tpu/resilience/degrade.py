"""Degradation ladder: bounded retry, backend quarantine, re-promotion.

The dispatch path has an ordered ladder of backends, fastest first and
each strictly more trustworthy than the last:

    TpuSecpVerifier:      pallas -> xla -> host
    ShardedSecpVerifier:  mesh   -> xla -> host

A dispatch that keeps failing (exceptions out of the runtime, verdict
buffers the guards reject) *quarantines* its level: the ladder demotes
one rung after ``demote_after`` consecutive failures, and the bottom
rung — the host-exact oracle, the same code the reference semantics are
pinned to — cannot fail this way, so the pipeline always terminates with
correct verdicts. Faults cost latency, never correctness, never a crash.

Quarantine is not forever: after ``probe_after`` consecutive successful
settles at the demoted level, the next dispatch *probes* the level above;
a successful probe re-promotes, a failed one re-arms the count. Probes
are count-based, not time-based, so the whole state machine is
deterministic and unit-testable without sleeping.

Retry policy (`DispatchResilience`): a failed dispatch retries at most
``max_retries`` times within a ``retry_deadline_s`` wall-clock budget
(read through the sanctioned ``obs.monotonic`` clock — this module is
linted with the same clock rule as `crypto/`). Deadline exhaustion is a
failure like any other: the ladder demotes and the work lands on host.

State is per-verifier-instance and mutated only from the verifier's
driver thread (the same discipline as its `_seen_shapes`).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

from ..obs import counter as _obs_counter
from ..obs import flight as _flight
from ..obs import gauge as _obs_gauge
from ..obs import monotonic as _monotonic

__all__ = [
    "DispatchFailed",
    "DispatchResilience",
    "HOST_LEVEL",
    "Ladder",
    "ShardLadder",
]

# The ladder's terminal rung: dispatch layers compare against this marker
# and route straight to their host-exact oracle when quarantined this far.
HOST_LEVEL = "host"

_LEVEL = _obs_gauge(
    "consensus_resilience_level",
    "current ladder rung (0 = fastest backend, rising = quarantined)",
    ("ladder",),
)
_DEMOTIONS = _obs_counter(
    "consensus_resilience_demotions_total",
    "ladder demotions after repeated dispatch failures",
    ("ladder", "src", "dst"),
)
_REPROMOTIONS = _obs_counter(
    "consensus_resilience_repromotions_total",
    "ladder re-promotions after a successful probe",
    ("ladder", "src", "dst"),
)
_PROBES = _obs_counter(
    "consensus_resilience_probes_total",
    "re-promotion probe dispatches at a quarantined level",
    ("ladder", "level"),
)
_RETRIES = _obs_counter(
    "consensus_resilience_retries_total",
    "dispatch retries after a contained fault",
    ("site",),
)


class DispatchFailed(RuntimeError):
    """Every device rung failed within the retry budget (host takes over)."""


class Ladder:
    """Quarantine state machine over an ordered backend list."""

    def __init__(
        self,
        levels: Sequence[str],
        name: str,
        demote_after: int = 2,
        probe_after: int = 16,
    ):
        if not levels or levels[-1] != HOST_LEVEL:
            raise ValueError("a ladder must end at the host rung")
        self.levels: Tuple[str, ...] = tuple(levels)
        self.name = name
        self.demote_after = demote_after
        self.probe_after = probe_after
        self._idx = 0
        self._fail_streak = 0
        self._ok_streak = 0  # successes at the current (quarantined) rung
        _LEVEL.set(0, ladder=name)

    @property
    def current(self) -> str:
        return self.levels[self._idx]

    def pick_level(self) -> Tuple[str, bool]:
        """Level for the next dispatch, and whether it is a probe.

        While quarantined, every ``probe_after``-th consecutive success
        earns one dispatch at the rung above; its outcome (reported via
        `report`) decides re-promotion.
        """
        if self._idx > 0 and self._ok_streak >= self.probe_after:
            lvl = self.levels[self._idx - 1]
            _PROBES.inc(ladder=self.name, level=lvl)
            return lvl, True
        return self.current, False

    def report(self, level: str, ok: bool, probe: bool = False) -> None:
        """Record a settled dispatch outcome for `level`."""
        if probe:
            self._ok_streak = 0  # one probe per earned window either way
            if ok:
                src, self._idx = self.current, self.levels.index(level)
                _REPROMOTIONS.inc(ladder=self.name, src=src, dst=level)
                _LEVEL.set(self._idx, ladder=self.name)
                self._fail_streak = 0
                _flight.record("ladder.repromote", ladder=self.name,
                               src=src, dst=level)
            return
        if ok:
            self._fail_streak = 0
            if self._idx > 0:
                self._ok_streak += 1
            return
        self._fail_streak += 1
        self._ok_streak = 0
        if (
            self._fail_streak >= self.demote_after
            and self._idx < len(self.levels) - 1
        ):
            src = self.current
            self._idx += 1
            self._fail_streak = 0
            _DEMOTIONS.inc(ladder=self.name, src=src, dst=self.current)
            _LEVEL.set(self._idx, ladder=self.name)
            # Record the transition BEFORE triggering, so the dump's
            # event window contains the demotion it is about.
            _flight.record("ladder.demote", ladder=self.name,
                           src=src, dst=self.current)
            _flight.trigger("quarantine", ladder=self.name,
                            src=src, dst=self.current)


_SHARD_HEALTH = _obs_gauge(
    "consensus_mesh_healthy_devices",
    "devices currently in the active mesh (evicted devices excluded)",
    ("ladder",),
)


class ShardLadder:
    """Per-device health for an elastic mesh: evict sick, re-probe later.

    Where `Ladder` quarantines a whole *backend rung*, this tracks each
    device of a sharded dispatch independently: ``evict_after``
    consecutive shard failures (guard anomalies, checksum mismatches,
    straggler deadlines, device loss) on one device convicts that device
    alone — the mesh owner rebuilds over the survivors and the batch
    keeps flowing. Like the rung ladder, eviction is not forever: every
    ``reprobe_after``-th clean mesh dispatch nominates the
    longest-evicted device for a known-answer re-promotion probe.

    Count-based and clockless, so the whole state machine is
    deterministic and unit-testable; the mesh owner supplies the
    wall-clock policy (per-shard straggler deadline) separately.
    """

    def __init__(
        self,
        device_ids: Sequence[str],
        evict_after: Optional[int] = None,
        reprobe_after: int = 16,
        min_devices: int = 1,
    ):
        if evict_after is None:
            evict_after = int(
                os.environ.get("BITCOINCONSENSUS_TPU_MESH_EVICT_AFTER", "3")
            )
        if evict_after < 1:
            raise ValueError("evict_after must be >= 1")
        self.evict_after = evict_after
        self.reprobe_after = reprobe_after
        self.min_devices = min_devices
        self._all: Tuple[str, ...] = tuple(device_ids)
        self._fails: Dict[str, int] = {d: 0 for d in self._all}
        self._evicted: List[str] = []  # FIFO: longest-evicted re-probes first
        self._clean_streak = 0
        _SHARD_HEALTH.set(len(self._all), ladder="mesh")

    def healthy(self) -> List[str]:
        """Device ids currently in the mesh, in original order."""
        return [d for d in self._all if d not in self._evicted]

    def report_shard(self, device_id: str, ok: bool) -> bool:
        """Record one shard outcome; True means "evict this device now".

        Never asks for an eviction that would shrink the mesh below
        ``min_devices`` — a mesh-wide fault then stays a whole-ticket
        failure for the rung ladder rather than a cascade of evictions.
        """
        if device_id in self._evicted:
            return False
        if ok:
            self._fails[device_id] = 0
            return False
        self._clean_streak = 0
        self._fails[device_id] = self._fails.get(device_id, 0) + 1
        return (
            self._fails[device_id] >= self.evict_after
            and len(self.healthy()) > self.min_devices
        )

    def evict(self, device_id: str) -> None:
        if device_id not in self._evicted:
            self._evicted.append(device_id)
            self._fails[device_id] = 0
            _SHARD_HEALTH.set(len(self.healthy()), ladder="mesh")
            _flight.record("shard.evict", device=device_id,
                           healthy=len(self.healthy()))
            _flight.trigger("quarantine", shard=device_id)

    def note_clean_dispatch(self) -> Optional[str]:
        """Record a fully clean mesh settle; maybe nominate a re-probe.

        Every ``reprobe_after``-th consecutive clean dispatch returns the
        longest-evicted device id (the caller runs a known-answer probe
        on it and calls `repromote` on success); otherwise None.
        """
        if not self._evicted:
            return None
        self._clean_streak += 1
        if self._clean_streak >= self.reprobe_after:
            self._clean_streak = 0
            return self._evicted[0]
        return None

    def repromote(self, device_id: str) -> None:
        if device_id in self._evicted:
            self._evicted.remove(device_id)
            self._fails[device_id] = 0
            _SHARD_HEALTH.set(len(self.healthy()), ladder="mesh")
            _flight.record("shard.repromote", device=device_id,
                           healthy=len(self.healthy()))


class DispatchResilience:
    """Retry budget + ladder for one verifier instance."""

    def __init__(
        self,
        levels: Sequence[str],
        name: str,
        demote_after: int = 2,
        probe_after: int = 16,
        max_retries: int = 3,
        retry_deadline_s: float = 2.0,
    ):
        self.ladder = Ladder(
            levels, name, demote_after=demote_after, probe_after=probe_after
        )
        self.max_retries = max_retries
        self.retry_deadline_s = retry_deadline_s

    def deadline(self) -> float:
        """Absolute retry deadline for a dispatch starting now."""
        return _monotonic() + self.retry_deadline_s

    def may_retry(self, attempts: int, deadline: float, site: str) -> bool:
        """True (and counted) if another attempt fits the retry budget."""
        if attempts > self.max_retries or _monotonic() >= deadline:
            return False
        _RETRIES.inc(site=site)
        return True
