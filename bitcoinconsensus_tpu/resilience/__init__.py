"""Fault containment and graceful degradation for the dispatch path.

The reference verifier is fail-closed by construction: a single-threaded
pure function whose every anomaly is a REJECT (`src/lib.rs:103-139`,
SURVEY §1). Our device path has failure modes the reference never had —
a corrupted kernel verdict buffer, a dispatch exception out of the XLA
runtime, a dropped mesh device, a poisoned cache entry — and the
north-star ("heavy traffic from millions of users") demands those faults
cost *latency*, never *correctness*, and never take the pipeline down.

Three pieces, composed around `crypto/jax_backend.TpuSecpVerifier`'s
dispatch/settle seam:

- ``faults`` — a deterministic, seed-driven fault-injection harness.
  Injection points are registered in `crypto/jax_backend.py` (dispatch
  exceptions, verdict corruption), `parallel/mesh.py` (device drop),
  `models/batch.py` (driver-level dispatch failure) and
  `models/sigcache.py` (poisoned hits). With no injector armed every
  hook is one module-global read — chaos machinery costs nothing in
  production.
- ``guards`` — verdict validation on every device return: shape, dtype
  domain ({0,1}), finiteness, plus per-dispatch *sentinel lanes* —
  known-answer checks written into the pad region of each packed batch
  whose verdicts are recomputed against precomputed expectations. Any
  anomaly raises ``VerdictAnomaly`` and the affected lanes demote to the
  exact host oracle (`TpuSecpVerifier._host_check` /
  `nat_verify_inputs_idx` MODE_EXACT).
- ``degrade`` — the degradation ladder: bounded retry with a wall-clock
  deadline around dispatch, backend quarantine
  (mesh/Pallas → XLA → host-exact) after repeated failures, and
  automatic count-based re-promotion probes.
- ``inflight`` — the asynchronous settlement queue. ``_dispatch_guarded``
  returns a *ticket* (unsynchronized device arrays + fault-site context
  + wall-clock deadline) instead of blocking; host prep for batch N+1
  runs while batch N is on the wire, and every ticket still settles
  through the guards, the retry budget, and the ladder. Bounded queue
  depth gives backpressure (a stalled device degrades gracefully), and a
  ladder demotion re-dispatches still-queued tickets off the quarantined
  backend. ``inflight.settle_array`` is the one sanctioned host
  materialization point outside the settle seam (enforced by the
  `host_lint` sync rule).

Containment floor (closed): the sentinel design catches systematic
verdict corruption — whole-buffer inversion/garbage, encoding faults,
dead kernels — the domain guards catch anything non-boolean, and the
per-dispatch device-side verdict checksum (rotating known-answer lanes +
(count, weighted) sums recomputed at settle) catches single-lane flips
anywhere in the buffer, real-lane region included. `flip` is a hard pass
criterion in `scripts/consensus_chaos.py`, which asserts bit-identical
results against the host-exact oracle for every fault class.

Everything here is host-side policy, never consensus: no module in this
package is imported by traced kernel code, and timing flows through the
sanctioned ``obs`` clock (`analysis/host_lint.py` lints this package
with the clock rule).
"""

from .faults import FaultPlan, FaultSpec, InjectedFault, InjectedTimeout, inject

# `degrade`/`guards`/`inflight` pull in the jax stack; `faults` must not.
# The sigstore tier chain (cell/sigtier.py → models/sigstore.py →
# resilience/faults.py) is imported by bare subprocess workers that never
# touch a device, so the heavy members resolve lazily.
_LAZY = {
    "DispatchResilience": ("degrade", "DispatchResilience"),
    "Ladder": ("degrade", "Ladder"),
    "VerdictAnomaly": ("guards", "VerdictAnomaly"),
    "install_sentinels": ("guards", "install_sentinels"),
    "set_cache_audit": ("guards", "set_cache_audit"),
    "validate_verdict": ("guards", "validate_verdict"),
    "InflightQueue": ("inflight", "InflightQueue"),
    "Ticket": ("inflight", "Ticket"),
    "settle_array": ("inflight", "settle_array"),
}


def __getattr__(name: str):
    try:
        mod_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(name) from None
    import importlib

    mod = importlib.import_module(f".{mod_name}", __name__)
    return getattr(mod, attr)


__all__ = [
    "DispatchResilience",
    "FaultPlan",
    "FaultSpec",
    "InflightQueue",
    "InjectedFault",
    "InjectedTimeout",
    "Ladder",
    "Ticket",
    "VerdictAnomaly",
    "inject",
    "install_sentinels",
    "set_cache_audit",
    "settle_array",
    "validate_verdict",
]
