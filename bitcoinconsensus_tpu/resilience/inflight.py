"""In-flight dispatch tickets with fail-closed settlement.

JAX arrays are futures: a dispatched verify batch is already
asynchronous until something on the host materializes it. PR 5's
dispatch/settle seam blocked immediately after every launch, which made
containment easy but serialized the pipeline — block replay spent 208 ms
of 282.7 ms waiting on the device link. This module makes the seam
asynchronous *without* loosening it: every dispatch returns a
:class:`Ticket` and every ticket still settles through the verdict
guards, the bounded-retry budget, and the degradation ladder before any
verdict is believed.

Ticket lifecycle::

    dispatch(args, n)                      settle(ticket)
      │ backpressure: settle oldest         │ materialize → guards
      │   while depth ≥ max_depth           │   (validate / sentinels /
      │ pick ladder level                   │    checksum) on the host
      │ prepare(args, n)  → sentinels       │ ok → report(level, True),
      │ launch(args, n, level) → futures    │      latency observed, done
      │ deadline = now + deadline_s         │ fail → report(level, False);
      └ append to queue ──────────────────▶ │   deadline expired → host
                                            │   else retry/backoff,
                                            │   re-pick level, relaunch
                                            │ terminal → CONTAINED,
                                            │   host-exact lanes, None

A `None` outcome is the fail-closed signal: the caller must re-verify
the ticket's lanes on the exact host oracle. When a settle failure
demotes the ladder, every still-queued ticket sitting on a now-
quarantined level is *cancelled and re-dispatched* at the new level
(counted in ``consensus_inflight_redispatch_total``) so queued work
never settles against a backend the ladder has already convicted.

Backpressure: the queue holds at most ``max_depth`` unsettled tickets;
a dispatch beyond that settles the oldest first (counted). A stalled
device therefore degrades to synchronous-with-retries instead of
accumulating unbounded host state.

``settle_array`` is the one sanctioned host materialization outside the
settle seam — `analysis/host_lint.py`'s sync rule bans bare
``np.asarray`` / ``block_until_ready`` on the dispatch path everywhere
else, so overlap cannot silently rot back into blocking code.

Host-side policy only: nothing here is traced, and time is read through
the sanctioned ``obs`` clock.
"""

from __future__ import annotations

import time
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

from ..obs import counter as _obs_counter
from ..obs import flight as _flight
from ..obs import current_trace as _current_trace
from ..obs import gauge as _obs_gauge
from ..obs import histogram as _obs_histogram
from ..obs import monotonic as _monotonic
from ..obs import perf as _perf
from . import guards as _guards
from .degrade import HOST_LEVEL, DispatchResilience

__all__ = ["InflightQueue", "Ticket", "settle_array"]

_DEPTH = _obs_gauge(
    "consensus_inflight_depth",
    "unsettled tickets currently in the dispatch queue, by site",
    ("site",),
)
_TICKETS = _obs_counter(
    "consensus_inflight_tickets_total",
    "tickets dispatched through the in-flight queue, by site",
    ("site",),
)
_SETTLE_SECONDS = _obs_histogram(
    "consensus_inflight_settle_seconds",
    "wall-clock time from dispatch to settled verdict per ticket",
    buckets=(0.001, 0.005, 0.02, 0.1, 0.5, 2.0, 10.0),
)
_DEADLINE_EXPIRED = _obs_counter(
    "consensus_inflight_deadline_expired_total",
    "tickets whose wall-clock deadline expired before a clean settle "
    "(demoted straight to the host oracle), by site",
    ("site",),
)
_REDISPATCH = _obs_counter(
    "consensus_inflight_redispatch_total",
    "queued tickets cancelled and re-dispatched after a ladder "
    "quarantine invalidated their level, by site",
    ("site",),
)
_BACKPRESSURE = _obs_counter(
    "consensus_inflight_backpressure_total",
    "dispatches that had to settle the oldest ticket first because the "
    "queue was at max depth, by site",
    ("site",),
)


def settle_array(x) -> np.ndarray:
    """THE sanctioned device→host materialization outside the settle seam.

    Forces (and waits for) the value of an in-flight array. Every
    synchronization on the dispatch path must flow through here or
    through the settle seam itself (`_materialize_guarded`) — the
    host_lint sync rule keeps it that way. Centralizing the block point
    is what makes "the pipeline overlaps" a checkable property instead
    of a hope.
    """
    from ..ops.regions import region_scope

    with region_scope("settle"):
        return np.asarray(x)


class Ticket:
    """One in-flight dispatch: unsynchronized result + settle context."""

    __slots__ = (
        "args", "n", "level", "probe", "attempts", "born", "deadline",
        "sset", "result", "aux", "error", "settled", "outcome", "seq",
        "timeline",
    )

    def __init__(self, args, n: int, level: str, probe: bool,
                 deadline: float, born: float, seq: int,
                 timeline=None):
        self.args = args
        self.n = n                  # real (padded) lane count dispatched
        self.level = level          # ladder level the launch ran at
        self.probe = probe
        self.attempts = 1
        self.born = born
        self.deadline = deadline    # wall-clock settle deadline
        self.sset = None            # SentinelSet installed at prepare
        self.result = None          # unsynchronized device arrays
        self.aux = None             # in-flight (count, weighted) checksum
        self.error = None           # launch exception, if any
        self.settled = False
        self.outcome = None         # (ok, needs) after settle; None=host
        self.seq = seq
        # PhaseTimeline (or the disarmed no-op): per-ticket phase stamps
        # feeding consensus_pipeline_phase_seconds at settle.
        self.timeline = _perf.NULL_TIMELINE if timeline is None else timeline


class InflightQueue:
    """Bounded queue of in-flight tickets settling through the guards.

    The queue owns *policy* (deadlines, retries, backpressure, ladder
    bookkeeping, re-dispatch after quarantine); the verifier supplies
    *mechanism* via callbacks:

    - ``prepare(args, n) -> (args, sset)`` — runs once per ticket at
      dispatch time: copy read-only buffers, install sentinel lanes.
    - ``launch(args, n, level, sset) -> (result, aux)`` — start the
      device work; returns unsynchronized arrays plus the in-flight
      checksum pair (or None). `sset` is whatever `prepare` returned
      (sentinel set or the sharded verifier's shard layout), so a launch
      can route by how the batch was laid out. Must not block.
      Exceptions are captured on the ticket and handled at settle (a
      launch failure is a settle failure that costs zero wire time).
    - ``materialize(ticket) -> (ok, needs, all_ok)`` — the settle seam:
      synchronize, run fault hooks, validate, check sentinels and the
      checksum. Raises ``VerdictAnomaly`` (or anything) on a bad buffer.
    - ``on_device(ticket, ok, needs, all_ok)`` — success accounting hook
      (verdict metrics); runs exactly once per cleanly settled ticket.
    """

    def __init__(
        self,
        resilience: DispatchResilience,
        site: str,
        launch: Callable[[Any, int, str, Any], Tuple[Any, Any]],
        materialize: Callable[[Ticket], Tuple[np.ndarray, Optional[np.ndarray], bool]],
        prepare: Optional[Callable[[Any, int], Tuple[Any, Any]]] = None,
        on_device: Optional[Callable[..., None]] = None,
        max_depth: int = 4,
        deadline_s: float = 8.0,
        backoff_s: float = 0.002,
    ):
        self._res = resilience
        self.site = site
        self._launch_cb = launch
        self._materialize = materialize
        self._prepare = prepare
        self._on_device = on_device
        self.max_depth = max(1, int(max_depth))
        self.deadline_s = float(deadline_s)
        self.backoff_s = float(backoff_s)
        self._pending: List[Ticket] = []
        self._seq = 0

    # -- dispatch side -------------------------------------------------

    def dispatch(self, args, n: int) -> Ticket:
        """Launch one batch; return its ticket without synchronizing."""
        while len(self._pending) >= self.max_depth:
            _BACKPRESSURE.inc(site=self.site)
            self.settle(self._pending[0])
        # Timeline starts before prepare so host-side sentinel/copy work
        # is attributed; it adopts the submitting request's trace id so
        # the ticket stitches into the serving-side span tree.
        timeline = _perf.new_timeline(trace=_current_trace())
        timeline.stamp("submit")
        if self._prepare is not None:
            args, sset = self._prepare(args, n)
        else:
            sset = None
        timeline.stamp("prepare")
        level, probe = self._res.ladder.pick_level()
        now = _monotonic()
        ticket = Ticket(args, n, level, probe,
                        deadline=now + self.deadline_s, born=now,
                        seq=self._seq, timeline=timeline)
        self._seq += 1
        ticket.sset = sset
        _TICKETS.inc(site=self.site)
        self._launch(ticket)
        self._pending.append(ticket)
        _DEPTH.set(len(self._pending), site=self.site)
        return ticket

    def _launch(self, ticket: Ticket) -> None:
        """(Re)issue the device work for a ticket at its current level."""
        ticket.result = None
        ticket.aux = None
        ticket.error = None
        if ticket.level == HOST_LEVEL:
            ticket.timeline.stamp("launch")
            return
        try:
            ticket.result, ticket.aux = self._launch_cb(
                ticket.args, ticket.n, ticket.level, ticket.sset
            )
        except Exception as exc:  # settled as a dispatch failure
            ticket.error = exc
        # Re-stamped on relaunch: the settled attempt owns the edge.
        ticket.timeline.stamp("launch")

    # -- settle side ---------------------------------------------------

    def settle(self, ticket: Ticket):
        """Resolve a ticket to `(ok, needs)` or None (host containment).

        Idempotent and order-independent: settling out of queue order is
        fine, and re-settling returns the cached outcome without
        re-touching the ladder or the containment counters.
        """
        if ticket.settled:
            return ticket.outcome
        # First host touch after launch: everything between "launch" and
        # here is the overlap window — wire time the host did not wait on.
        ticket.timeline.stamp_once("first_poll")
        try:
            self._pending.remove(ticket)
        except ValueError:
            pass
        _DEPTH.set(len(self._pending), site=self.site)
        res = self._res
        ladder = res.ladder
        start_idx = ladder.levels.index(ladder.current)
        outcome = None
        while ticket.level != HOST_LEVEL:
            failure = ticket.error
            if failure is None:
                ticket.timeline.stamp("settle_start")
                try:
                    ok, needs, all_ok = self._materialize(ticket)
                except Exception as exc:
                    failure = exc
                else:
                    ladder.report(ticket.level, True, probe=ticket.probe)
                    _SETTLE_SECONDS.observe(_monotonic() - ticket.born)
                    if self._on_device is not None:
                        self._on_device(ticket, ok, needs, all_ok)
                    outcome = (ok, needs)
                    break
            ladder.report(ticket.level, False, probe=ticket.probe)
            if _monotonic() >= ticket.deadline:
                _DEADLINE_EXPIRED.inc(site=self.site)
                _flight.record("inflight.deadline_expired", site=self.site,
                               attempts=ticket.attempts, level=ticket.level)
                break
            if not res.may_retry(ticket.attempts, ticket.deadline, self.site):
                break
            ticket.attempts += 1
            if self.backoff_s > 0.0:
                time.sleep(min(self.backoff_s * (1 << min(ticket.attempts, 8)),
                               0.05))
            ticket.level, ticket.probe = ladder.pick_level()
            if ticket.level == HOST_LEVEL:
                break
            self._launch(ticket)
        if outcome is None:
            _guards.CONTAINED.inc(site=self.site)
            _guards.HOST_EXACT_LANES.inc(ticket.n)
            if ladder.current == HOST_LEVEL:
                ladder.report(HOST_LEVEL, True)
        ticket.settled = True
        ticket.outcome = outcome
        ticket.timeline.stamp("settle_end")
        ticket.timeline.finalize()
        if ladder.levels.index(ladder.current) > start_idx:
            self._requeue_stale()
        return outcome

    def _requeue_stale(self) -> None:
        """Cancel + re-dispatch queued tickets on quarantined levels.

        After a demotion, an unsettled ticket launched at a higher rung
        would settle against a backend the ladder just convicted — and a
        clean settle there would *re-promote* the ladder, fighting the
        quarantine. Re-issue them at the current rung instead.
        """
        ladder = self._res.ladder
        cur = ladder.levels.index(ladder.current)
        for ticket in self._pending:
            if ticket.level == HOST_LEVEL:
                continue
            try:
                idx = ladder.levels.index(ticket.level)
            except ValueError:
                idx = -1
            if idx < cur:
                _REDISPATCH.inc(site=self.site)
                ticket.level, ticket.probe = ladder.pick_level()
                self._launch(ticket)

    # -- introspection -------------------------------------------------

    @property
    def depth(self) -> int:
        return len(self._pending)

    def drain(self) -> None:
        """Settle everything still in flight (oldest first)."""
        while self._pending:
            self.settle(self._pending[0])
