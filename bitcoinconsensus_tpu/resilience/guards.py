"""Verdict guards: validate every device return, sentinel known-answer lanes.

The device's answer to "is this spend valid" is a buffer that crossed a
runtime, a compiler, and a wire. Before the pipeline treats it as a
consensus verdict it must survive:

1. **Structural validation** (`validate_verdict`): the buffer has exactly
   the dispatched lane count, every element is finite, and every element
   is in the verdict domain {0, 1}. A truncated buffer, a NaN, or a 7
   raises ``VerdictAnomaly`` — the dispatching layer then contains the
   fault by re-verifying the affected lanes on the exact host oracle.
2. **Sentinel lanes** (`install_sentinels` / `SentinelSet.check`):
   known-answer EC checks written into the *pad region* of the packed
   batch — the lanes the pad ladder was going to waste anyway, so
   sentinels cost zero extra device work. Each sentinel is an
   R = (a+b)·G identity with a precomputed expected verdict (half expect
   True, half expect a deliberately-wrong target → False). A dispatch
   whose sentinel verdicts disagree with expectation proves the kernel,
   the runtime, or the readback corrupted the buffer *systematically*,
   and the whole chunk demotes to host.

Containment floor (closed as of the in-flight dispatch PR): sentinels
catch whole-buffer corruption classes (inversion, garbage, encoding
faults, dead kernels), structural validation catches anything
non-boolean, and the **verdict checksum** (`check_checksum`) closes the
remaining gap: a device-side (count, position-weighted) sum over the
verdict buffer, dispatched with the batch and compared at settle against
the same sums recomputed from the materialized buffer. Any single-lane
flip — sentinel region or real-lane region — changes the count by ±1
and mismatches; `flip` is a hard pass criterion in the chaos sweep.
Sentinel templates additionally *rotate* across dispatches
(`install_sentinels`), so a replayed/stuck verdict buffer that answers
the previous dispatch's pattern is caught; the dispatch layer pads every
shape with at least one spare lane (`TpuSecpVerifier._pad`) and copies
read-only native buffers (`ensure_writable`) so no dispatch goes out
sentinel-less.

Cache audit mode (`set_cache_audit`): when armed, the batch driver
re-verifies cache hits against the host oracle and evicts proven-wrong
entries — the containment story for poisoned cache entries, priced as an
opt-in because it re-pays the work the cache exists to skip.

Everything here is host-side numpy on materialized buffers — nothing is
traced, no kernel jaxpr changes.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..crypto import secp_host
from ..crypto.glv import split_lambda
from ..obs import counter as _obs_counter

__all__ = [
    "CHECKSUM_MOD",
    "SentinelSet",
    "VerdictAnomaly",
    "audit_cache_hits",
    "check_checksum",
    "check_sentinels",
    "ensure_writable",
    "install_sentinels",
    "install_sentinels_at",
    "set_cache_audit",
    "validate_verdict",
    "verdict_checksum_host",
]

GUARD_ANOMALIES = _obs_counter(
    "consensus_resilience_guard_anomalies_total",
    "device verdict buffers rejected by the guards, by site and reason",
    ("site", "reason"),
)
_SENTINEL_LANES = _obs_counter(
    "consensus_resilience_sentinel_lanes_total",
    "known-answer sentinel lanes mixed into device dispatches",
)
_SENTINEL_SKIPPED = _obs_counter(
    "consensus_resilience_sentinel_skipped_total",
    "dispatches that could not carry sentinels (no pad room or "
    "read-only packed buffers), by reason",
    ("reason",),
)
CONTAINED = _obs_counter(
    "consensus_resilience_contained_total",
    "faults contained by demoting work to the host-exact oracle, by site",
    ("site",),
)
HOST_EXACT_LANES = _obs_counter(
    "consensus_resilience_host_exact_lanes_total",
    "lanes re-verified on the host-exact oracle due to fault containment",
)
CACHE_POISON_CAUGHT = _obs_counter(
    "consensus_resilience_cache_poison_caught_total",
    "cache hits whose audit re-verification disagreed (entry evicted)",
    ("cache",),
)
_WRITABLE_COPIES = _obs_counter(
    "consensus_resilience_writable_copies_total",
    "packed batches copied host-side so sentinels could be installed "
    "(native prep_pack hands back read-only views)",
)


class VerdictAnomaly(RuntimeError):
    """A device verdict buffer failed validation (reason in `.reason`)."""

    def __init__(self, site: str, reason: str, detail: str = ""):
        msg = f"verdict anomaly at {site}: {reason}"
        if detail:
            msg += f" ({detail})"
        super().__init__(msg)
        self.site = site
        self.reason = reason
        # Single choke point for guard convictions: every anomaly lands
        # in the flight ring; a checksum mismatch (verdict corruption in
        # transit) is dump-worthy on its own, before the ladder reacts.
        from ..obs import flight as _flight

        _flight.record("guard.anomaly", site=site, reason=reason,
                       detail=detail)
        if reason == "checksum":
            _flight.trigger("checksum", site=site, detail=detail)


def validate_verdict(arr, n: int, site: str) -> np.ndarray:
    """Validate a materialized device verdict buffer; return it as bool.

    `n` is the exact lane count the buffer must have (padded size at the
    dispatch layer). Raises ``VerdictAnomaly`` — after counting it in
    ``consensus_resilience_guard_anomalies_total`` — on wrong shape,
    non-finite values, or values outside {0, 1}. Bool input is the
    trusted fast path: one asarray, no value scan.
    """
    a = np.asarray(arr)
    if a.ndim != 1 or a.shape[0] != n:
        GUARD_ANOMALIES.inc(site=site, reason="shape")
        raise VerdictAnomaly(site, "shape", f"got {a.shape}, want ({n},)")
    if a.dtype == np.bool_:
        return a
    if np.issubdtype(a.dtype, np.floating):
        if not np.isfinite(a).all():
            GUARD_ANOMALIES.inc(site=site, reason="nonfinite")
            raise VerdictAnomaly(site, "nonfinite")
    elif not np.issubdtype(a.dtype, np.integer):
        GUARD_ANOMALIES.inc(site=site, reason="dtype")
        raise VerdictAnomaly(site, "dtype", str(a.dtype))
    in_domain = (a == 0) | (a == 1)
    if not in_domain.all():
        GUARD_ANOMALIES.inc(site=site, reason="domain")
        raise VerdictAnomaly(
            site, "domain", f"{int((~in_domain).sum())} lanes outside {{0,1}}"
        )
    return a != 0


# --- sentinel lanes ---------------------------------------------------------
#
# Each template is a fully packed lane (the 128-byte field block + flags)
# plus its precomputed expected verdict. The check is R = a·G + b·G against
# target t1: for expect-True lanes t1 = ((a+b)·G).x; for expect-False lanes
# t1 = that x plus one (never a valid x for this R — the curve has no two
# points sharing R's orbit at x and x+1 for our fixed scalars, and equality
# is exact integer compare). b ships GLV-split exactly like a real lane, so
# sentinels exercise the same split/digit/ladder path real traffic does.

_SENTINEL_SCALARS = ((2, 3, True), (5, 7, False), (11, 13, True), (17, 19, False))
_templates: Optional[List[Tuple[bytes, int, int, int, int, int, bool]]] = None


def _sentinel_templates():
    """Lazily build packed sentinel rows (host EC math runs once/process)."""
    global _templates
    if _templates is not None:
        return _templates
    rows = []
    for a, b, expect in _SENTINEL_SCALARS:
        aff = secp_host.G.mul((a + b) % secp_host.N).to_affine()
        rx = aff[0]
        t1 = rx if expect else (rx + 1) % secp_host.P
        b1, neg1, b2, neg2 = split_lambda(b)
        raw = (
            a.to_bytes(32, "little")
            + b1.to_bytes(16, "little")
            + b2.to_bytes(16, "little")
            + secp_host.G_X.to_bytes(32, "little")
            + t1.to_bytes(32, "little")
        )
        want_odd = secp_host.G_Y & 1
        rows.append((raw, want_odd, -1, 0, neg1, neg2, expect))
    _templates = rows
    return rows


class SentinelSet:
    """Positions + expected verdicts of the sentinels in one dispatch."""

    __slots__ = ("positions", "expected")

    def __init__(self, positions: List[int], expected: List[bool]):
        self.positions = np.asarray(positions, dtype=np.int64)
        self.expected = np.asarray(expected, dtype=bool)

    def check(self, ok: np.ndarray, needs: Optional[np.ndarray], site: str) -> None:
        """Compare sentinel verdicts against expectation; raise on mismatch.

        Lanes the fast-add kernel flagged `needs_host` report ok=False by
        design regardless of the true answer, so flagged sentinels are
        excluded rather than miscounted as corruption.
        """
        got = np.asarray(ok, dtype=bool)[self.positions]
        exp = self.expected
        if needs is not None:
            usable = ~np.asarray(needs, dtype=bool)[self.positions]
            got, exp = got[usable], exp[usable]
        if not np.array_equal(got, exp):
            GUARD_ANOMALIES.inc(site=site, reason="sentinel")
            raise VerdictAnomaly(
                site,
                "sentinel",
                f"expected {exp.tolist()}, got {got.tolist()}",
            )


_rotation = 0


def ensure_writable(args: Tuple) -> Tuple[Tuple, bool]:
    """Return `(args, copied)` with every packed buffer host-writable.

    The native bridge's ``prep_pack`` hands back read-only views over the
    C-owned arena; sentinels must be written in place, so those batches
    are copied once host-side (a memcpy of the packed lanes — counted in
    ``consensus_resilience_writable_copies_total``). Already-writable
    batches pass through untouched.
    """
    if all(getattr(a, "flags", None) is not None and a.flags.writeable
           for a in args):
        return args, False
    _WRITABLE_COPIES.inc()
    return tuple(np.array(a) for a in args), True


def install_sentinels(
    args: Tuple, n: int, rotation: Optional[int] = None
) -> Optional[SentinelSet]:
    """Write sentinel lanes into the pad region of a packed batch, in place.

    `args` is the verifier's packed 7-tuple (fields, want_odd, parity,
    has_t2, neg1, neg2, valid); `n` is the real lane count, so rows
    [n, size) are pad. Templates rotate across dispatches (a process-wide
    counter advances the starting template each call) so consecutive
    batches of the same shape carry *different* expected patterns — a
    stuck or replayed verdict buffer that answers the previous dispatch's
    pattern mismatches. Pass `rotation` to pin the phase (tests).

    Returns the SentinelSet to check at settle, or None (counted) when
    the batch has no pad room or the buffers are not writable — callers
    that must not dispatch sentinel-less copy first via
    ``ensure_writable``.
    """
    fields = args[0]
    size = int(fields.shape[0])
    room = size - n
    if room <= 0:
        _SENTINEL_SKIPPED.inc(reason="no_pad_room")
        return None
    k = min(room, len(_sentinel_templates()))
    return install_sentinels_at(args, list(range(n, n + k)), rotation)


def install_sentinels_at(
    args: Tuple, positions: Sequence[int], rotation: Optional[int] = None
) -> Optional[SentinelSet]:
    """Write sentinel lanes at explicit row positions, in place.

    The scatter-layout variant of ``install_sentinels``: the sharded
    verifier reserves the *last* lane of every device shard rather than
    a contiguous tail region, so each shard carries its own known-answer
    lane and a per-shard flip is localized to that shard. Template
    selection still rotates (one process-wide counter advance per call,
    templates cycle across `positions`), so consecutive dispatches carry
    different expected patterns per shard.

    Returns None (counted) when the buffers are not writable.
    """
    global _rotation
    fields, want_odd, parity, has_t2, neg1, neg2, valid = args
    arrs = (fields, want_odd, parity, has_t2, neg1, neg2, valid)
    if not all(getattr(a, "flags", None) is not None and a.flags.writeable
               for a in arrs):
        _SENTINEL_SKIPPED.inc(reason="readonly")
        return None
    templates = _sentinel_templates()
    if rotation is None:
        rotation = _rotation
        _rotation = (_rotation + 1) % len(templates)
    out_pos, expected = [], []
    for i, pos in enumerate(positions):
        raw, w, par, h2, n1, n2, exp = templates[(rotation + i) % len(templates)]
        fields[pos] = np.frombuffer(raw, dtype=np.uint8).reshape(4, 32)
        want_odd[pos] = w
        parity[pos] = par
        has_t2[pos] = h2
        neg1[pos] = n1
        neg2[pos] = n2
        valid[pos] = True
        out_pos.append(int(pos))
        expected.append(exp)
    _SENTINEL_LANES.inc(len(out_pos))
    return SentinelSet(out_pos, expected)


def check_sentinels(
    sset: Optional[SentinelSet],
    ok: np.ndarray,
    needs: Optional[np.ndarray],
    site: str,
) -> None:
    """Module-level convenience: no-op for sentinel-less dispatches."""
    if sset is not None:
        sset.check(ok, needs, site)


# --- verdict checksum -------------------------------------------------------
#
# The single-flip detector. The dispatch layer chains a tiny jitted
# reduction onto the in-flight verdict buffer: (sum of lanes, sum of
# lane·weight) with weight[i] = i % CHECKSUM_MOD + 1. At settle the same
# two sums are recomputed host-side from the materialized buffer and must
# match exactly. Any single-lane flip changes the count sum by ±1; the
# weighted sum localizes most multi-lane corruptions the count parity
# would miss. int32-safe on device: 252 · B < 2^31 for B up to ~8.5M
# lanes (the interval prover certifies the registered kernel).

CHECKSUM_MOD = 251


def verdict_checksum_host(ok: np.ndarray) -> Tuple[int, int]:
    """Host recomputation of the device verdict checksum (int64 math)."""
    v = np.asarray(ok).astype(np.int64)
    w = np.arange(v.shape[0], dtype=np.int64) % CHECKSUM_MOD + 1
    return int(v.sum()), int((v * w).sum())


def check_checksum(
    device_sums: Optional[Tuple[int, int]], ok: np.ndarray, site: str
) -> None:
    """Compare device-side verdict sums against the materialized buffer.

    `device_sums` is the materialized (count, weighted) pair the dispatch
    layer computed on-device over the same buffer; None means the
    dispatch carried no checksum (counted as a guard skip is not needed —
    the caller decides whether checksum-less dispatch is allowed). Raises
    ``VerdictAnomaly(reason="checksum")`` on mismatch.
    """
    if device_sums is None:
        return
    count, wsum = verdict_checksum_host(ok)
    dev = (int(device_sums[0]), int(device_sums[1]))
    if dev != (count, wsum):
        GUARD_ANOMALIES.inc(site=site, reason="checksum")
        raise VerdictAnomaly(
            site, "checksum", f"device {dev} vs host {(count, wsum)}"
        )


# --- cache audit mode -------------------------------------------------------

_audit_cache = False


def set_cache_audit(on: bool) -> None:
    """Arm/disarm cache-hit auditing (poisoned-entry containment).

    When armed, the batch driver re-verifies every signature-cache hit
    against the host-exact oracle and evicts entries that disagree
    (counted in ``consensus_resilience_cache_poison_caught_total``).
    Off by default: auditing re-pays exactly the work the cache skips.
    """
    global _audit_cache
    _audit_cache = bool(on)


def audit_cache_hits() -> bool:
    return _audit_cache
