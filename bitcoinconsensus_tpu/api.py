"""Public API — mirrors the reference crate's surface, plus batch/taproot.

Reference parity (`src/lib.rs:103-139`, `script/bitcoinconsensus.cpp:74-129`):
``verify``, ``verify_with_flags``, ``height_to_flags``, ``version``, the
transport-level error enum, the libconsensus flag subset restriction and the
exact check order of the C ABI shim (flags → deserialize → index → size).

Extensions beyond the reference (SURVEY.md §3.2, §5):
- ``verify_with_spent_outputs``: supplies all spent outputs, unlocking the
  BIP341 taproot path the reference's C ABI cannot reach.
- per-input `ScriptError` detail on failures (the reference swallows it).
- ``verify_batch`` lives in `bitcoinconsensus_tpu.models.batch` (TPU path).
"""

from __future__ import annotations

import enum
from typing import List, Optional, Sequence, Tuple

from .core.flags import (
    ALL_FLAG_BITS,
    LIBCONSENSUS_FLAGS,
    VERIFY_ALL_EXTENDED,
    VERIFY_ALL_LIBCONSENSUS,
    VERIFY_TAPROOT,
    VERIFY_WITNESS,
    height_to_flags,
)
from .core.interpreter import TransactionSignatureChecker, verify_script
from .core.script_error import ScriptError
from .core.serialize import SerializationError
from .core.sighash import PrecomputedTxData
from .core.tx import Tx, TxOut
from .obs import counter as _obs_counter
from .obs import span as _span

__all__ = [
    "Error",
    "ConsensusError",
    "verify",
    "verify_with_flags",
    "verify_with_spent_outputs",
    "version",
    "height_to_flags",
    "VERIFY_ALL_LIBCONSENSUS",
    "VERIFY_ALL_EXTENDED",
]

API_VERSION = 1  # bitcoinconsensus.h:36 BITCOINCONSENSUS_API_VER

# Telemetry (README "Observability"): per-entry call counters and
# reject-reason counters keyed by the transport Error code and, for script
# failures, the exact ScriptError — the observable the reference swallows.
_VERIFY_CALLS = _obs_counter(
    "consensus_verify_calls_total", "verify* entry-point calls", ("entry",)
)
_VERIFY_REJECTS = _obs_counter(
    "consensus_verify_reject_total",
    "verify rejections by transport Error code (api + batch paths)",
    ("code",),
)
_SCRIPT_REJECTS = _obs_counter(
    "consensus_script_reject_total",
    "script-level rejections by ScriptError code (api + batch paths)",
    ("script_error",),
)


def _record_reject(exc: "ConsensusError") -> None:
    _VERIFY_REJECTS.inc(code=exc.code.name)
    if exc.script_error is not None and exc.script_error != ScriptError.OK:
        _SCRIPT_REJECTS.inc(script_error=exc.script_error.name)


class Error(enum.IntEnum):
    """Transport-level errors (bitcoinconsensus.h:38-46 + lib.rs:172-185)."""

    ERR_OK = 0
    ERR_TX_INDEX = 1
    ERR_TX_SIZE_MISMATCH = 2
    ERR_TX_DESERIALIZE = 3
    ERR_AMOUNT_REQUIRED = 4
    ERR_INVALID_FLAGS = 5
    # Script-level failure (the Rust crate's ERR_SCRIPT, lib.rs:121).
    ERR_SCRIPT = 6
    # Serving-layer extension (bitcoinconsensus_tpu.serving): admission
    # control shed the request before any consensus evaluation ran. A
    # fail-closed reject — the caller may retry with backoff; the request
    # was never partially evaluated. Not part of the reference ABI.
    ERR_OVERLOADED = 7


class ConsensusError(Exception):
    """Raised by verify* on failure; carries the transport error and (as an
    improvement over the reference, which swallows it) the ScriptError."""

    def __init__(self, code: Error, script_error: Optional[ScriptError] = None):
        self.code = code
        self.script_error = script_error
        detail = f", script_error={script_error.name}" if script_error is not None else ""
        super().__init__(f"{code.name}{detail}")


def version() -> int:
    """bitcoinconsensus_version (bitcoinconsensus.cpp:125-129)."""
    return API_VERSION


def _verify_input(
    spent_output_script: bytes,
    amount: int,
    spending_transaction: bytes,
    input_index: int,
    flags: int,
    allowed_flags: int,
    spent_outputs: Optional[Sequence[TxOut]] = None,
) -> None:
    """Shared body of the verify entry points; mirrors
    bitcoinconsensus.cpp:79-101 verify_script check order. Runs on the
    native host core (native/eval.hpp) when available — same transport
    checks, same ScriptErrors (tests/test_native_interp.py) — with the
    Python engine as spec and fallback."""
    if flags & ~allowed_flags:
        raise ConsensusError(Error.ERR_INVALID_FLAGS)

    from . import native_bridge

    if native_bridge.available():
        try:
            ntx = native_bridge.NativeTx(spending_transaction)
        except ValueError:
            raise ConsensusError(Error.ERR_TX_DESERIALIZE) from None
        # nIn is unsigned in the reference ABI: negative indices are
        # out-of-range, never Python-style wraparound.
        if input_index < 0 or input_index >= ntx.n_inputs:
            raise ConsensusError(Error.ERR_TX_INDEX)
        if ntx.ser_size != len(spending_transaction):
            raise ConsensusError(Error.ERR_TX_SIZE_MISMATCH)
        if spent_outputs is not None:
            if len(spent_outputs) != ntx.n_inputs:
                raise ConsensusError(Error.ERR_TX_INDEX)
            ntx.set_spent_outputs(
                [(o.value, o.script_pubkey) for o in spent_outputs]
            )
        else:
            if flags & VERIFY_TAPROOT:
                raise ConsensusError(Error.ERR_AMOUNT_REQUIRED)
            ntx.precompute()
        sess = native_bridge.NativeSession()
        ok, err_code, _ = sess.verify_input(
            ntx, input_index, amount, spent_output_script, flags,
            mode=native_bridge.NativeSession.MODE_EXACT,
        )
        if not ok:
            raise ConsensusError(Error.ERR_SCRIPT, ScriptError(err_code))
        return

    try:
        tx = Tx.deserialize(spending_transaction)
        if input_index < 0 or input_index >= len(tx.vin):
            raise ConsensusError(Error.ERR_TX_INDEX)
        if len(tx.serialize()) != len(spending_transaction):
            raise ConsensusError(Error.ERR_TX_SIZE_MISMATCH)
    except SerializationError:
        raise ConsensusError(Error.ERR_TX_DESERIALIZE) from None

    if spent_outputs is not None:
        if len(spent_outputs) != len(tx.vin):
            raise ConsensusError(Error.ERR_TX_INDEX)
        txdata = PrecomputedTxData(tx, list(spent_outputs))
    else:
        if flags & VERIFY_TAPROOT:
            # BIP341 sighash needs all spent outputs (interpreter.cpp:1512);
            # reject instead of asserting.
            raise ConsensusError(Error.ERR_AMOUNT_REQUIRED)
        txdata = PrecomputedTxData(tx)

    checker = TransactionSignatureChecker(tx, input_index, amount, txdata)
    ok, script_err = verify_script(
        tx.vin[input_index].script_sig,
        spent_output_script,
        tx.vin[input_index].witness,
        flags,
        checker,
    )
    if not ok:
        raise ConsensusError(Error.ERR_SCRIPT, script_err)


def _verify_entry(
    entry: str,
    spent_output_script: bytes,
    amount: int,
    spending_transaction: bytes,
    input_index: int,
    flags: int,
    allowed_flags: int,
    spent_outputs: Optional[Sequence[TxOut]] = None,
) -> None:
    """Instrumented shared body of the public entry points: one span per
    call, reject-reason counters on failure (the counters are cumulative
    process totals; `scripts/consensus_stats.py` snapshots them)."""
    _VERIFY_CALLS.inc(entry=entry)
    with _span(f"api.{entry}"):
        try:
            _verify_input(
                spent_output_script,
                amount,
                spending_transaction,
                input_index,
                flags,
                allowed_flags=allowed_flags,
                spent_outputs=spent_outputs,
            )
        except ConsensusError as e:
            _record_reject(e)
            raise


def verify(
    spent_output: bytes,
    amount: int,
    spending_transaction: bytes,
    input_index: int,
) -> None:
    """verify() (src/lib.rs:103-111): VERIFY_ALL under libconsensus flags.

    Raises ConsensusError on failure; returns None on success.
    """
    _verify_entry(
        "verify",
        spent_output,
        amount,
        spending_transaction,
        input_index,
        VERIFY_ALL_LIBCONSENSUS,
        allowed_flags=LIBCONSENSUS_FLAGS,
    )


def verify_with_flags(
    spent_output_script: bytes,
    amount: int,
    spending_transaction: bytes,
    input_index: int,
    flags: int,
) -> None:
    """verify_with_flags (src/lib.rs:113-139): same flag restriction as the
    reference C ABI (only libconsensus bits accepted)."""
    _verify_entry(
        "verify_with_flags",
        spent_output_script,
        amount,
        spending_transaction,
        input_index,
        flags,
        allowed_flags=LIBCONSENSUS_FLAGS,
    )


def verify_with_spent_outputs(
    spending_transaction: bytes,
    input_index: int,
    spent_outputs: Sequence[Tuple[int, bytes]],
    flags: int = VERIFY_ALL_EXTENDED,
) -> None:
    """Extended entry point: all spent outputs supplied → taproot reachable.

    ``spent_outputs`` is a sequence of (amount, scriptPubKey), one per input
    of the spending transaction (the shape Core's later
    verify_script_with_spent_outputs ABI adopted).
    """
    outs = [TxOut(amt, spk) for amt, spk in spent_outputs]
    if input_index < 0 or input_index >= len(outs):
        _VERIFY_CALLS.inc(entry="verify_with_spent_outputs")
        exc = ConsensusError(Error.ERR_TX_INDEX)
        _record_reject(exc)
        raise exc
    _verify_entry(
        "verify_with_spent_outputs",
        outs[input_index].script_pubkey,
        outs[input_index].value,
        spending_transaction,
        input_index,
        flags,
        allowed_flags=ALL_FLAG_BITS,
        spent_outputs=outs,
    )
