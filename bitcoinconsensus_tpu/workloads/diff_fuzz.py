"""Continuous differential fuzzing across all three verdict engines.

Seed-driven mutation of adversarial corpus entries, every mutant run
through three genuinely independent implementations:

1. ``python_verdict`` — the pure-Python interpreter, driven directly
   (never touches the native bridge or the batch machinery). This is
   the host oracle: a line-for-line transcription of the spec.
2. ``native_verdict`` — the C++ core (`native/libnat.so`) through
   NativeTx/NativeSession in exact mode, mirroring the transport check
   order of `api._verify_input`'s native branch. ``None`` when the
   bridge is unavailable (CPU-only containers without a toolchain).
3. ``batch_verdicts`` — `verify_batch` with fresh caches: the deferred
   checker + device dispatch + cache pipeline that production traffic
   actually takes (itself backed by native *or* Python engines, plus
   all the driver plumbing either way).

The contract is fail-closed: any disagreement on the full verdict
triple ``(ok, Error, ScriptError)`` between any pair of engines is a
divergence, and one unexplained divergence fails the gauntlet. Fixed
seed sets for CI live in `fuzz/gauntlet_seeds.json` so failures
reproduce exactly.
"""

from __future__ import annotations

import dataclasses
import random
from typing import List, Optional, Sequence, Tuple

from ..api import Error
from ..core.flags import (
    ALL_FLAG_BITS,
    LIBCONSENSUS_FLAGS,
    VERIFY_CLEANSTACK,
    VERIFY_P2SH,
    VERIFY_TAPROOT,
    VERIFY_WITNESS,
)
from ..core.interpreter import TransactionSignatureChecker, verify_script
from ..core.script_error import ScriptError
from ..core.serialize import SerializationError
from ..core.sighash import PrecomputedTxData
from ..core.tx import Tx, TxOut
from ..models.batch import BatchItem, BatchResult, verify_batch
from ..models.sigcache import ScriptExecutionCache, SigCache

__all__ = [
    "Verdict",
    "python_verdict",
    "native_verdict",
    "batch_verdicts",
    "backend_verdicts",
    "mutate",
    "run_diff_fuzz",
]

# (ok, transport-error name, script-error name or None). Script error is
# normalised to None on success so engines that report OK/None/absent
# on the success path can never spuriously diverge.
Verdict = Tuple[bool, str, Optional[str]]

MUTATIONS = (
    "tx_flip",
    "tx_truncate",
    "tx_extend",
    "spk_flip",
    "amount_perturb",
    "flags_random",
    "flags_invalid",
    "index_perturb",
)


def _allowed(item: BatchItem) -> int:
    # Mirrors batch._prepare / the api entry points: the full 21-bit
    # space with spent outputs, the libconsensus subset without.
    return ALL_FLAG_BITS if item.spent_outputs is not None else LIBCONSENSUS_FLAGS


def _norm(ok: bool, err: Error, serr: Optional[ScriptError]) -> Verdict:
    name = None
    if not ok and serr is not None and serr != ScriptError.OK:
        name = serr.name
    return (ok, err.name, name)


def python_verdict(item: BatchItem) -> Verdict:
    """Pure-Python engine verdict; transport check order of
    bitcoinconsensus.cpp:79-101 (flags → deserialize → index → size →
    prevout availability → script eval)."""
    if item.flags & ~_allowed(item):
        return _norm(False, Error.ERR_INVALID_FLAGS, None)
    try:
        tx = Tx.deserialize(item.spending_tx)
    except SerializationError:
        return _norm(False, Error.ERR_TX_DESERIALIZE, None)
    if item.input_index < 0 or item.input_index >= len(tx.vin):
        return _norm(False, Error.ERR_TX_INDEX, None)
    try:
        size_ok = len(tx.serialize()) == len(item.spending_tx)
    except Exception:  # noqa: BLE001 — unserializable parse is a size lie
        size_ok = False
    if not size_ok:
        return _norm(False, Error.ERR_TX_SIZE_MISMATCH, None)

    if item.spent_outputs is not None:
        if len(item.spent_outputs) != len(tx.vin):
            return _norm(False, Error.ERR_TX_INDEX, None)
        prevouts = [TxOut(v, s) for v, s in item.spent_outputs]
        txdata = PrecomputedTxData(tx, prevouts)
        spk = prevouts[item.input_index].script_pubkey
        amount = prevouts[item.input_index].value
    else:
        if item.flags & VERIFY_TAPROOT:
            return _norm(False, Error.ERR_AMOUNT_REQUIRED, None)
        txdata = PrecomputedTxData(tx)
        spk = item.spent_output_script or b""
        amount = item.amount

    checker = TransactionSignatureChecker(tx, item.input_index, amount, txdata)
    ok, script_err = verify_script(
        tx.vin[item.input_index].script_sig,
        spk,
        tx.vin[item.input_index].witness,
        item.flags,
        checker,
    )
    if ok:
        return _norm(True, Error.ERR_OK, None)
    return _norm(False, Error.ERR_SCRIPT, script_err)


def native_verdict(item: BatchItem) -> Optional[Verdict]:
    """C++ core verdict in exact mode, or None when the bridge is
    unavailable. Same transport order as the api native branch."""
    from .. import native_bridge

    if not native_bridge.available():
        return None
    if item.flags & ~_allowed(item):
        return _norm(False, Error.ERR_INVALID_FLAGS, None)
    try:
        ntx = native_bridge.NativeTx(item.spending_tx)
    except ValueError:
        return _norm(False, Error.ERR_TX_DESERIALIZE, None)
    if item.input_index < 0 or item.input_index >= ntx.n_inputs:
        return _norm(False, Error.ERR_TX_INDEX, None)
    if ntx.ser_size != len(item.spending_tx):
        return _norm(False, Error.ERR_TX_SIZE_MISMATCH, None)
    if item.spent_outputs is not None:
        if len(item.spent_outputs) != ntx.n_inputs:
            return _norm(False, Error.ERR_TX_INDEX, None)
        ntx.set_spent_outputs(list(item.spent_outputs))
        spk = item.spent_outputs[item.input_index][1]
        amount = item.spent_outputs[item.input_index][0]
    else:
        if item.flags & VERIFY_TAPROOT:
            return _norm(False, Error.ERR_AMOUNT_REQUIRED, None)
        ntx.precompute()
        spk = item.spent_output_script or b""
        amount = item.amount
    sess = native_bridge.NativeSession()
    ok, err_code, _ = sess.verify_input(
        ntx, item.input_index, amount, spk, item.flags,
        mode=native_bridge.NativeSession.MODE_EXACT,
    )
    if ok:
        return _norm(True, Error.ERR_OK, None)
    return _norm(False, Error.ERR_SCRIPT, ScriptError(err_code))


def _result_verdict(r: BatchResult) -> Verdict:
    return _norm(r.ok, r.error, r.script_error)


def batch_verdicts(items: Sequence[BatchItem], chunk: int = 64) -> List[Verdict]:
    """Verdicts through the production batch driver, fresh caches (so a
    poisoned global cache can never mask a divergence)."""
    out: List[Verdict] = []
    for lo in range(0, len(items), chunk):
        res = verify_batch(
            list(items[lo : lo + chunk]),
            sig_cache=SigCache(),
            script_cache=ScriptExecutionCache(),
        )
        out.extend(_result_verdict(r) for r in res)
    return out


def backend_verdicts(item: BatchItem) -> dict:
    """All engines on one item — {'python': V, 'native': V|None,
    'batch': V}. Test/debug convenience; run_diff_fuzz batches instead."""
    return {
        "python": python_verdict(item),
        "native": native_verdict(item),
        "batch": batch_verdicts([item])[0],
    }


def mutate(item: BatchItem, rng: random.Random) -> Tuple[BatchItem, str]:
    """One seed-driven mutation of a corpus item. Every mutation keeps
    the item well-formed at the API level (bytes/ints of the right
    types) — malformedness lives in the *content*, which is the point."""
    kind = rng.choice(MUTATIONS)
    tx = bytearray(item.spending_tx)
    fields = dataclasses.asdict(item)  # shallow copies of primitives
    if kind == "tx_flip":
        pos = rng.randrange(len(tx))
        tx[pos] ^= 1 << rng.randrange(8)
        fields["spending_tx"] = bytes(tx)
    elif kind == "tx_truncate":
        fields["spending_tx"] = bytes(tx[: rng.randrange(len(tx))])
    elif kind == "tx_extend":
        fields["spending_tx"] = bytes(tx) + bytes(
            rng.getrandbits(8) for _ in range(rng.randint(1, 8))
        )
    elif kind == "spk_flip" and item.spent_outputs:
        outs = [list(o) for o in item.spent_outputs]
        tgt = rng.randrange(len(outs))
        spk = bytearray(outs[tgt][1])
        if spk:
            spk[rng.randrange(len(spk))] ^= 1 << rng.randrange(8)
        outs[tgt][1] = bytes(spk)
        fields["spent_outputs"] = [tuple(o) for o in outs]
    elif kind == "amount_perturb" and item.spent_outputs:
        outs = [list(o) for o in item.spent_outputs]
        tgt = rng.randrange(len(outs))
        outs[tgt][0] = max(0, outs[tgt][0] + rng.choice((-1, 1, 1000, -1000)))
        fields["spent_outputs"] = [tuple(o) for o in outs]
    elif kind == "flags_random":
        f = rng.getrandbits(21)
        # The interpreter inherits Core's caller contract
        # (interpreter.cpp:1990,2076): WITNESS requires P2SH, CLEANSTACK
        # requires both. Outside it behavior is asserted, not defined —
        # the fuzzer stays inside the defined space.
        if f & VERIFY_WITNESS:
            f |= VERIFY_P2SH
        if f & VERIFY_CLEANSTACK:
            f |= VERIFY_P2SH | VERIFY_WITNESS
        fields["flags"] = f
    elif kind == "flags_invalid":
        # A bit above the defined space: every engine must agree on
        # ERR_INVALID_FLAGS before touching the tx at all.
        fields["flags"] = item.flags | (1 << rng.randint(21, 31))
    elif kind == "index_perturb":
        fields["input_index"] = rng.choice(
            (-1, item.input_index + 1, item.input_index + 64, 2**31)
        )
    else:  # spk/amount mutation drawn for a no-prevouts item
        pos = rng.randrange(len(tx))
        tx[pos] ^= 1 << rng.randrange(8)
        fields["spending_tx"] = bytes(tx)
        kind = "tx_flip"
    if fields.get("spent_outputs") is not None:
        fields["spent_outputs"] = [tuple(o) for o in fields["spent_outputs"]]
    return BatchItem(**fields), kind


def run_diff_fuzz(
    seed: int = 0,
    n_cases: int = 500,
    chunk: int = 64,
    corpus=None,
) -> dict:
    """Mutate corpus entries and compare all engines; returns a report
    with per-case divergences (one unexplained divergence fails the
    gauntlet). Deterministic from (seed, n_cases, corpus order)."""
    from . import GAUNTLET_DIVERGENCE, GAUNTLET_FUZZ_CASES
    from .corpus import build_corpus

    if corpus is None:
        corpus = build_corpus()
    rng = random.Random(seed)
    base = [c.item for c in corpus]
    names = [c.name for c in corpus]

    items: List[BatchItem] = []
    meta: List[Tuple[str, str]] = []
    while len(items) < n_cases:
        i = rng.randrange(len(base))
        mutant, kind = mutate(base[i], rng)
        items.append(mutant)
        meta.append((names[i], kind))

    from .. import native_bridge

    have_native = native_bridge.available()
    py = [python_verdict(it) for it in items]
    nat = [native_verdict(it) for it in items] if have_native else [None] * len(items)
    bat = batch_verdicts(items, chunk=chunk)

    divergences: List[dict] = []
    for i, it in enumerate(items):
        engines = {"python": py[i], "batch": bat[i]}
        if nat[i] is not None:
            engines["native"] = nat[i]
        if len(set(engines.values())) > 1:
            divergences.append(
                {
                    "case": i,
                    "origin": meta[i][0],
                    "mutation": meta[i][1],
                    "flags": it.flags,
                    "input_index": it.input_index,
                    "spending_tx": it.spending_tx.hex(),
                    "verdicts": {k: list(v) for k, v in engines.items()},
                }
            )
    GAUNTLET_FUZZ_CASES.inc(len(items))
    GAUNTLET_DIVERGENCE.inc(len(divergences), leg="diff_fuzz")
    return {
        "seed": seed,
        "cases": len(items),
        "native_available": have_native,
        "engines": 3 if have_native else 2,
        "divergences": divergences,
        "bit_identical": not divergences,
    }
