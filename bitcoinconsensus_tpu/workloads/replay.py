"""Historical-replay harness: mainnet-shaped streams through the pipeline.

A deterministic, seed-driven generator of realistic multi-block
workloads — mixed script types at mainnet-like ratios, duplicate
signers, mempool→block re-verification (the cache-warm pattern
production replay actually exhibits), varying batch fill and a sprinkle
of invalid spends — plus drivers that push the stream end-to-end
through each serving surface and assert the fail-closed contract:

- `run_replay` — `verify_batch_stream` (the pipelined batch driver),
  every verdict compared bit-identically against the independent
  pure-Python host oracle, and the mempool→block overlap must actually
  warm the script/sig caches.
- `run_replay_serving` — the full path: per-tenant client threads in
  bursts through `VerifyServer` (mode="serve") or over a real socket
  through `IngressServer`/`IngressClient` (mode="ingress"). Every
  submission ends in exactly one explicit outcome: a settled verdict
  (oracle-checked) or an `OverloadError` shed; with `overload=True`
  the config is tightened until sheds actually happen — and they must
  all be explicit.

`scripts/consensus_gauntlet.py --replay` is the CLI;
`consensus_chaos.py --gauntlet` re-runs the stream leg under injected
flips/stragglers/poison. Never imported by the production verify path.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..core.flags import VERIFY_ALL_EXTENDED
from ..models.batch import BatchItem, verify_batch_stream
from ..models.sigcache import ScriptExecutionCache, SigCache
from ..utils import blockgen

__all__ = [
    "ReplayConfig",
    "ReplayBlock",
    "generate_stream",
    "run_replay",
    "run_replay_cell",
    "run_replay_serving",
]

# Mainnet-ish script-type ratios (input-count share, post-taproot era;
# coarse on purpose — the point is MIXED traffic, not census accuracy).
DEFAULT_MIX = (
    ("p2wpkh", 0.55),
    ("p2tr", 0.20),
    ("p2pkh", 0.15),
    ("p2wsh_multisig", 0.10),
)


@dataclass
class ReplayConfig:
    seed: int = 0
    n_blocks: int = 4
    txs_per_block: int = 6          # mean; actual fill varies ±50% per block
    max_inputs: int = 3
    mix: Tuple[Tuple[str, float], ...] = DEFAULT_MIX
    dup_signer_rate: float = 0.35   # P(reuse an already-seen wallet)
    mempool_fraction: float = 0.5   # share of a block pre-verified "in mempool"
    invalid_rate: float = 0.15      # P(one corrupted input in a tx)
    tenants: int = 3


@dataclass
class ReplayBlock:
    """One block's worth of verification traffic: the mempool batch
    (arrivals verified ahead of the block) and the block batch (every
    input re-verified at connect time — the overlap is the cache-warm
    pattern)."""

    height: int
    mempool_items: List[BatchItem]
    block_items: List[BatchItem]
    expected_ok: List[bool] = field(default_factory=list)  # per block item
    n_txs: int = 0


def _pick_kind(rng: random.Random, mix) -> str:
    r = rng.random() * sum(w for _, w in mix)
    for kind, w in mix:
        r -= w
        if r <= 0:
            return kind
    return mix[-1][0]


def generate_stream(cfg: ReplayConfig) -> List[ReplayBlock]:
    """Deterministic multi-block stream from `cfg.seed` (same seed, same
    bytes — the chaos sweep and CI replays depend on it)."""
    rng = random.Random(cfg.seed)
    pool: Dict[str, List[blockgen.Wallet]] = {k: [] for k, _ in cfg.mix}
    blocks: List[ReplayBlock] = []

    def wallet(kind: str) -> blockgen.Wallet:
        seen = pool[kind]
        if seen and rng.random() < cfg.dup_signer_rate:
            return rng.choice(seen)  # duplicate signer
        w = blockgen.Wallet(f"replay/{cfg.seed}/{kind}/{len(seen)}", kind)
        seen.append(w)
        return w

    for b in range(cfg.n_blocks):
        lo = max(1, cfg.txs_per_block // 2)
        n_txs = rng.randint(lo, cfg.txs_per_block + cfg.txs_per_block // 2)
        block_items: List[BatchItem] = []
        expected: List[bool] = []
        mempool_cut: List[int] = []
        for t in range(n_txs):
            n_in = rng.randint(1, cfg.max_inputs)
            funded = []
            for i in range(n_in):
                w = wallet(_pick_kind(rng, cfg.mix))
                op = blockgen.OutPoint(
                    blockgen.hashlib.sha256(
                        f"replay/{cfg.seed}/{b}/{t}/{i}".encode()
                    ).digest(),
                    i,
                )
                amount = rng.randrange(10_000, 1_000_000)
                funded.append(blockgen.FundedOutput(op, w, amount))
            corrupt = (
                rng.randrange(n_in) if rng.random() < cfg.invalid_rate else None
            )
            tx = blockgen.build_spend_tx(funded, corrupt_input=corrupt)
            raw = tx.serialize()
            outs = [(f.amount, f.wallet.spk) for f in funded]
            start = len(block_items)
            for i in range(n_in):
                block_items.append(
                    BatchItem(raw, i, VERIFY_ALL_EXTENDED, spent_outputs=outs)
                )
                expected.append(corrupt is None or i != corrupt)
            if rng.random() < cfg.mempool_fraction:
                mempool_cut.extend(range(start, len(block_items)))
        blocks.append(
            ReplayBlock(
                height=100 + b,
                mempool_items=[block_items[i] for i in mempool_cut],
                block_items=block_items,
                expected_ok=expected,
                n_txs=n_txs,
            )
        )
    return blocks


def _oracle(items: List[BatchItem]) -> List[Tuple[bool, str, Optional[str]]]:
    """Independent per-item host oracle (pure-Python engine; no caches,
    no device, no batching) — the bit-identity reference."""
    from .diff_fuzz import python_verdict

    return [python_verdict(it) for it in items]


def _triple(r) -> Tuple[bool, str, Optional[str]]:
    from ..core.script_error import ScriptError

    serr = (
        r.script_error.name
        if r.script_error is not None and r.script_error != ScriptError.OK
        else None
    )
    return (r.ok, r.error.name, serr if not r.ok else None)


def _norm(t: Tuple[bool, str, Optional[str]]) -> Tuple[bool, str, Optional[str]]:
    ok, err, serr = t
    return (ok, err, serr if not ok else None)


def run_replay(cfg: ReplayConfig, depth: int = 2) -> dict:
    """Drive the generated stream through `verify_batch_stream` against
    fresh caches; returns a report with divergence and cache-warm counts
    (both hard gauntlet criteria)."""
    from . import (
        GAUNTLET_DIVERGENCE,
        GAUNTLET_REPLAY_BLOCKS,
    )

    blocks = generate_stream(cfg)
    # Mempool validation runs ahead of block connection (as on mainnet);
    # the lag must exceed the stream pipeline depth or the block batch's
    # cache probe races the mempool batch's insert and the warm-up the
    # harness is asserting never materialises.
    lag = depth + 1
    batches: List[List[BatchItem]] = []
    expect_hits = 0
    for i in range(len(blocks) + lag):
        if i < len(blocks) and blocks[i].mempool_items:
            batches.append(blocks[i].mempool_items)
        if i >= lag:
            blk = blocks[i - lag]
            batches.append(blk.block_items)
            if blk.mempool_items:
                ok_idx = {
                    j for j, ok in enumerate(blk.expected_ok) if ok
                }
                expect_hits += sum(
                    1
                    for j, it in enumerate(blk.block_items)
                    if it in blk.mempool_items and j in ok_idx
                )

    sig_cache, script_cache = SigCache(), ScriptExecutionCache()
    results = list(
        verify_batch_stream(
            batches, sig_cache=sig_cache, script_cache=script_cache,
            depth=depth,
        )
    )

    divergences: List[dict] = []
    n_items = 0
    for batch, res in zip(batches, results, strict=True):
        oracle = _oracle(batch)
        n_items += len(batch)
        for j, (r, want) in enumerate(zip(res, oracle, strict=True)):
            if _triple(r) != _norm(want):
                divergences.append(
                    {"batch_item": j, "got": _triple(r), "want": _norm(want)}
                )
    # Unconditional (a zero sample is the "leg ran, no divergence" fact
    # the stats gate wants to see, not just the absence of a counter).
    GAUNTLET_DIVERGENCE.inc(len(divergences), leg="replay")
    GAUNTLET_REPLAY_BLOCKS.inc(len(blocks))

    # Cache warm-up: every VALID mempool item re-verifies inside its
    # block batch (the cache is success-only, so invalid overlap can
    # never hit), so the script cache MUST have taken at least that many
    # hits. Fewer means the mempool→block skip path silently died.
    return {
        "blocks": len(blocks),
        "batches": len(batches),
        "items": n_items,
        "txs": sum(b.n_txs for b in blocks),
        "mempool_overlap_items": sum(len(b.mempool_items) for b in blocks),
        "expected_warm_hits": expect_hits,
        "script_cache_hits": script_cache.hits,
        "sig_cache_hits": sig_cache.hits,
        "warmed": script_cache.hits >= expect_hits > 0,
        "divergences": divergences,
        "bit_identical": not divergences,
    }


def run_replay_serving(
    cfg: ReplayConfig,
    mode: str = "serve",
    overload: bool = False,
    timeout_s: float = 120.0,
) -> dict:
    """The full serving path: per-tenant threads submit the stream in
    bursts. Every submission must end settled-and-oracle-identical or
    explicitly shed — hangs, silent drops and mystery exceptions all
    count as failures. With `overload=True` the server is configured so
    sheds MUST happen (tiny tenant depth, no size flush)."""
    assert mode in ("serve", "ingress")
    from ..serving import (
        IngressClient,
        IngressServer,
        OverloadError,
        VerifyServer,
    )
    from . import GAUNTLET_DIVERGENCE

    blocks = generate_stream(cfg)
    items: List[BatchItem] = [
        it for blk in blocks for it in blk.block_items
    ]
    oracle = [_norm(t) for t in _oracle(items)]

    if overload:
        server_kw = dict(max_batch=256, flush_s=0.05, tenant_depth=1)
    else:
        server_kw = dict(max_batch=16, flush_s=0.005, tenant_depth=256)

    lanes = [(i, it) for i, it in enumerate(items)]
    per_tenant: List[List[Tuple[int, BatchItem]]] = [
        lanes[t :: cfg.tenants] for t in range(cfg.tenants)
    ]

    settled: Dict[int, Tuple[bool, str, Optional[str]]] = {}
    sheds: List[int] = []
    errors: List[str] = []
    lock = threading.Lock()

    def tenant_worker(t: int, submit) -> None:
        rng = random.Random((cfg.seed << 8) | t)
        work = per_tenant[t]
        pos = 0
        while pos < len(work):
            burst = work[pos : pos + rng.randint(1, 4)]  # bursty arrival
            pos += len(burst)
            pendings = []
            for idx, it in burst:
                try:
                    pendings.append((idx, submit(it, f"tenant{t}")))
                except OverloadError:
                    with lock:
                        sheds.append(idx)
                except Exception as e:  # noqa: BLE001 — trial accounting
                    with lock:
                        errors.append(f"submit[{idx}]: {e!r}")
            for idx, pend in pendings:
                try:
                    res = pend.result(timeout=timeout_s) if pend is not None else None
                    with lock:
                        settled[idx] = _triple(res)
                except Exception as e:  # noqa: BLE001
                    with lock:
                        errors.append(f"settle[{idx}]: {e!r}")

    srv = VerifyServer(
        sig_cache=SigCache(), script_cache=ScriptExecutionCache(),
        **server_kw,
    ).start()
    ingress = None
    clients: List[IngressClient] = []
    try:
        if mode == "ingress":
            ingress = IngressServer(srv, idle_s=timeout_s).start()

            def make_submit():
                cli = IngressClient(port=ingress.port, timeout_s=timeout_s)
                clients.append(cli)

                def submit(it, tenant):
                    # Socket path is synchronous: settle inline, return a
                    # pre-resolved pending so the worker's settle loop is
                    # uniform across modes.
                    res = cli.verify(it, tenant)

                    class _Done:
                        def result(self, timeout=None):
                            return res

                    return _Done()

                return submit

            submits = [make_submit() for _ in range(cfg.tenants)]
        else:
            submits = [srv.submit for _ in range(cfg.tenants)]

        threads = [
            threading.Thread(target=tenant_worker, args=(t, submits[t]))
            for t in range(cfg.tenants)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=timeout_s)
        hung = [th for th in threads if th.is_alive()]
    finally:
        for cli in clients:
            cli.close()
        if ingress is not None:
            ingress.close(drain=True)
        srv.close(drain=True)

    divergences = [
        {"item": idx, "got": got, "want": oracle[idx]}
        for idx, got in sorted(settled.items())
        if got != oracle[idx]
    ]
    GAUNTLET_DIVERGENCE.inc(len(divergences), leg="replay-serving")
    all_accounted = len(settled) + len(sheds) == len(items)
    return {
        "mode": mode,
        "items": len(items),
        "settled": len(settled),
        "sheds": len(sheds),
        "errors": errors,
        "hung_threads": len(hung),
        "divergences": divergences,
        "bit_identical": not divergences,
        "all_accounted": all_accounted and not errors and not hung,
        "sheds_expected": overload,
        "sheds_happened": (len(sheds) > 0) if overload else True,
        "sheds_explicit_only": all_accounted,
    }


def run_replay_cell(
    cfg: ReplayConfig,
    n_replicas: int = 2,
    timeout_s: float = 120.0,
) -> dict:
    """The cell path: the same bursty multi-tenant stream, but through
    the `CellRouter` fronting `n_replicas` replica stacks (in-process
    stubs — the gauntlet leg proves routing + protocol end-to-end; the
    chaos sweep owns the subprocess kill trials). The criteria are
    `run_replay_serving`'s: every submission settles oracle-identical
    or is explicitly shed; hangs and silent drops are failures."""
    from ..cell import ServingCell
    from ..serving import OverloadError
    from ..serving.client import IngressClient, verify_with_retry
    from . import GAUNTLET_DIVERGENCE

    blocks = generate_stream(cfg)
    items: List[BatchItem] = [
        it for blk in blocks for it in blk.block_items
    ]
    oracle = [_norm(t) for t in _oracle(items)]

    lanes = [(i, it) for i, it in enumerate(items)]
    per_tenant: List[List[Tuple[int, BatchItem]]] = [
        lanes[t :: cfg.tenants] for t in range(cfg.tenants)
    ]

    settled: Dict[int, Tuple[bool, str, Optional[str]]] = {}
    sheds: List[int] = []
    errors: List[str] = []
    lock = threading.Lock()

    def tenant_worker(t: int, port: int) -> None:
        rng = random.Random((cfg.seed << 8) | t)
        cli = IngressClient(port=port, timeout_s=timeout_s)
        try:
            for idx, it in per_tenant[t]:
                try:
                    res = verify_with_retry(
                        cli, it, tenant=f"tenant{t}", retries=4, rng=rng
                    )
                    with lock:
                        settled[idx] = _triple(res)
                except OverloadError:
                    with lock:
                        sheds.append(idx)
                except Exception as e:  # noqa: BLE001 — trial accounting
                    with lock:
                        errors.append(f"cell[{idx}]: {e!r}")
        finally:
            cli.close()

    cell = ServingCell(
        n_replicas=n_replicas,
        stub=True,
        server_kw=dict(max_batch=16, flush_s=0.005, tenant_depth=256),
    ).start()
    try:
        threads = [
            threading.Thread(target=tenant_worker, args=(t, cell.port))
            for t in range(cfg.tenants)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=timeout_s)
        hung = [th for th in threads if th.is_alive()]
    finally:
        cell.close()

    divergences = [
        {"item": idx, "got": got, "want": oracle[idx]}
        for idx, got in sorted(settled.items())
        if got != oracle[idx]
    ]
    GAUNTLET_DIVERGENCE.inc(len(divergences), leg="replay-cell")
    all_accounted = len(settled) + len(sheds) == len(items)
    return {
        "mode": "cell",
        "replicas": n_replicas,
        "items": len(items),
        "settled": len(settled),
        "sheds": len(sheds),
        "errors": errors,
        "hung_threads": len(hung),
        "divergences": divergences,
        "bit_identical": not divergences,
        "all_accounted": all_accounted and not errors and not hung,
        "sheds_explicit_only": all_accounted,
    }
