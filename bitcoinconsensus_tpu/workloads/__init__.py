"""Adversarial and mainnet-shaped workload generation (the gauntlet).

Three legs, all deterministic from explicit seeds (README "Adversarial
workloads & gauntlet"):

- ``corpus`` — constructed worst-case transactions (max-fan-out
  CHECKMULTISIG, max-size scripts, pre-BIP143 quadratic sighash,
  taproot script-path + annex, signature-malleation and boundary-flag
  cases), each with a pinned expected verdict. The shapes the reference
  names as the hard cases (SURVEY §7) and the ones where a batched
  verifier can silently diverge or fall off its latency cliff.
- ``replay`` — seed-driven realistic multi-block streams (mainnet-like
  script-type mix, duplicate signers, mempool→block re-verification
  for cache-warm patterns, varying batch fill, bursty tenant arrival)
  driven end-to-end through ingress → coalescing → the stream driver,
  asserted bit-identical against an independent host oracle.
- ``diff_fuzz`` — seed-driven mutation of corpus entries run through
  the pure-Python engine, the native C++ engine and the batch/device
  driver, fail-closed on any triple disagreement.

`scripts/consensus_gauntlet.py` is the CLI; `consensus_chaos.py
--gauntlet` runs every leg under the fault sweep. Never imported by the
production verify path.

Gauntlet telemetry lives here so every leg shares one set of
instruments (consensus_stats.py REQUIRED_METRICS carries them).
"""

from __future__ import annotations

from ..obs import counter as _counter
from ..obs import histogram as _histogram

GAUNTLET_CORPUS_CASES = _counter(
    "consensus_gauntlet_corpus_cases_total",
    "adversarial corpus cases run, by shape",
    ("shape",),
)
GAUNTLET_DIVERGENCE = _counter(
    "consensus_gauntlet_divergence_total",
    "gauntlet verdict divergences (corpus pin misses, replay oracle "
    "mismatches, diff-fuzz backend disagreements) — any increment is a "
    "consensus bug or a stale pin",
    ("leg",),
)
GAUNTLET_REPLAY_BLOCKS = _counter(
    "consensus_gauntlet_replay_blocks_total",
    "replay-harness blocks streamed through the pipeline",
)
GAUNTLET_FUZZ_CASES = _counter(
    "consensus_gauntlet_fuzz_cases_total",
    "differential-fuzz mutated cases compared across backends",
)
GAUNTLET_SHAPE_SECONDS = _histogram(
    "consensus_gauntlet_shape_seconds",
    "per-item verify latency by adversarial shape (worst-case p99 "
    "tracking; populated by the corpus/bench legs)",
    ("shape",),
    buckets=(1e-5, 1e-4, 1e-3, 1e-2, 1e-1, 1.0, 10.0),
)

from .corpus import CorpusCase, SHAPES, build_corpus, shape_batch  # noqa: E402
from .replay import (  # noqa: E402
    ReplayBlock,
    ReplayConfig,
    generate_stream,
    run_replay,
    run_replay_cell,
    run_replay_serving,
)
from .diff_fuzz import backend_verdicts, run_diff_fuzz  # noqa: E402

__all__ = [
    "CorpusCase",
    "SHAPES",
    "build_corpus",
    "shape_batch",
    "ReplayBlock",
    "ReplayConfig",
    "generate_stream",
    "run_replay",
    "run_replay_cell",
    "run_replay_serving",
    "backend_verdicts",
    "run_diff_fuzz",
    "GAUNTLET_CORPUS_CASES",
    "GAUNTLET_DIVERGENCE",
    "GAUNTLET_REPLAY_BLOCKS",
    "GAUNTLET_FUZZ_CASES",
    "GAUNTLET_SHAPE_SECONDS",
]
