"""Adversarial corpus: constructed worst-case inputs with pinned verdicts.

Each entry is one `BatchItem` plus the exact expected outcome
(`ok`, transport `Error`, `ScriptError`), pinned at construction time
and enforced three ways:

- tests/test_workloads.py pins every entry against the Python engine,
  the batch/device driver, and (when the bridge is up) the native C++
  engine — plus the reference `.so` differential where available;
- `scripts/consensus_gauntlet.py --corpus` re-checks the pins on every
  backend and is a CI gate (`consensus_chaos.py --gauntlet` runs it
  under the fault sweep too);
- `scripts/bench_gauntlet.py` benches `shape_batch()` scale-ups of the
  same constructors so worst-case throughput is tracked per shape.

The shapes are the reference's hard cases (SURVEY §7, ROADMAP
"Scenario diversity"): CHECKMULTISIG fan-out is the measured deferral
dead end (the optimistic first pass guesses a pairing the cursor walk
then falsifies key by key), quadratic sighash is the pre-BIP143 O(n²)
hashing cliff, max-size scripts stress the interpreter byte budget,
taproot script-path + annex exercises the longest sighash/commitment
chain, and the malleation/boundary-flag entries pin the exact flag
bits where a verdict legally flips.

Adding a shape: write a `_case_*` constructor returning `CorpusCase`
rows with pinned verdicts, register its shape tag in `SHAPES`, extend
`shape_batch()` if it should be benched, and land a baseline via
`scripts/bench_gauntlet.py --measure` (README "Adversarial workloads &
gauntlet"). A wrong pin fails the gauntlet — that is the point.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import List, Optional, Tuple

from ..api import Error
from ..core.flags import (
    VERIFY_ALL_EXTENDED,
    VERIFY_DERSIG,
    VERIFY_LOW_S,
    VERIFY_NULLFAIL,
    VERIFY_P2SH,
)
from ..core.script import (
    MAX_PUBKEYS_PER_MULTISIG,
    MAX_SCRIPT_ELEMENT_SIZE,
    MAX_SCRIPT_SIZE,
    OP_1,
    OP_CHECKMULTISIG,
    OP_CHECKSIG,
    OP_DROP,
    push_data,
)
from ..core.script_error import ScriptError
from ..core.serialize import ser_string
from ..core.sighash import (
    SIGHASH_ALL,
    SIGHASH_DEFAULT,
    PrecomputedTxData,
    SigVersion,
    bip143_sighash,
    bip341_sighash,
    legacy_sighash,
)
from ..core.tx import COIN, OutPoint, Tx, TxIn, TxOut
from ..crypto import secp_host as H
from ..models.batch import BatchItem
from ..utils.hashes import hash160, sha256, tagged_hash

__all__ = ["SHAPES", "CorpusCase", "build_corpus", "shape_batch"]

# Corpus taxonomy (README "Adversarial workloads & gauntlet"). The first
# four are the per-shape bench/baseline axes; the rest are
# verdict-pinning shapes (cheap, correctness-only).
SHAPES = (
    "multisig_fanout",
    "quadratic_sighash",
    "max_size_script",
    "taproot_annex",
    "sig_malleation",
    "boundary_flags",
    "scalar_edge",
)

AMOUNT = COIN // 100


@dataclass
class CorpusCase:
    """One pinned adversarial input."""

    name: str
    shape: str
    description: str
    item: BatchItem
    expect_ok: bool
    expect_error: Error
    expect_script_error: Optional[ScriptError]

    def expected(self) -> Tuple[bool, str, Optional[str]]:
        """(ok, Error name, ScriptError name) — the comparison triple the
        gauntlet and the differential backends all speak."""
        serr = None
        if (
            not self.expect_ok
            and self.expect_script_error is not None
            and self.expect_script_error != ScriptError.OK
        ):
            serr = self.expect_script_error.name
        return (self.expect_ok, self.expect_error.name, serr)


def _sk(tag: str) -> int:
    return int.from_bytes(hashlib.sha256(tag.encode()).digest(), "big") % (H.N - 1) + 1


def _prevout(tag: str) -> OutPoint:
    return OutPoint(hashlib.sha256(f"corpus/{tag}".encode()).digest(), 0)


def _spend_tx(tag: str, n_inputs: int = 1) -> Tx:
    """Unsigned 1-output spend of `n_inputs` synthetic prevouts."""
    return Tx(
        version=2,
        vin=[TxIn(_prevout(f"{tag}/{i}")) for i in range(n_inputs)],
        vout=[TxOut(AMOUNT * n_inputs - 1000, b"\x51")],
        locktime=0,
    )


def _item(tx: Tx, spk: bytes, flags: int = VERIFY_ALL_EXTENDED,
          input_index: int = 0, n_inputs: int = 1) -> BatchItem:
    return BatchItem(
        tx.serialize(),
        input_index,
        flags,
        spent_outputs=[(AMOUNT, spk)] * n_inputs,
    )


def _malleate_high_s(sig_with_type: bytes) -> bytes:
    """Re-encode a strict-DER signature with S -> N - S (still lax-DER
    valid; consensus-accepted without VERIFY_LOW_S, pubkey.cpp:204)."""
    sig, hashtype = sig_with_type[:-1], sig_with_type[-1:]
    r, s = H.parse_der_lax(sig)
    body = H._der_encode_int(r) + H._der_encode_int(H.N - s)
    return b"\x30" + bytes([len(body)]) + body + hashtype


def _pad_der(sig_with_type: bytes) -> bytes:
    """Re-encode with a gratuitous leading zero on R — BER-ish padding
    parse_der_lax tolerates but strict DER (BIP66) rejects."""
    sig, hashtype = sig_with_type[:-1], sig_with_type[-1:]
    r, s = H.parse_der_lax(sig)
    r_raw = r.to_bytes((r.bit_length() + 7) // 8 or 1, "big")
    if r_raw[0] & 0x80:
        r_raw = b"\x00" + r_raw
    r_enc = b"\x02" + bytes([len(r_raw) + 1]) + b"\x00" + r_raw
    s_enc = H._der_encode_int(s)
    body = r_enc + s_enc
    return b"\x30" + bytes([len(body)]) + body + hashtype


# --------------------------------------------------------------------------
# multisig_fanout — the deferral dead end. Core's CHECKMULTISIG cursor
# walks keys top-down (interpreter.cpp:1177-1205): a sig that belongs to
# the LAST of 20 keys costs 19 cryptographically-false curve checks
# before the true pairing, and the batch driver's optimistic pass guesses
# the first pairing — the worst case for oracle re-interpretation rounds.
# --------------------------------------------------------------------------

def _multisig_keys(tag: str, n: int = MAX_PUBKEYS_PER_MULTISIG):
    sks = [_sk(f"{tag}/k{i}") for i in range(n)]
    return sks, [H.pubkey_create(sk) for sk in sks]


def _opnum(n: int) -> bytes:
    """Script-number opcode for small n: OP_1..OP_16 direct, a minimal
    one-byte push above that (20 keys > OP_16 — 0x50+20 would be
    OP_NOTIF, which is how a hand-rolled multisig script quietly turns
    into an unbalanced conditional)."""
    assert 1 <= n <= 0x7F
    return bytes([0x50 + n]) if n <= 16 else push_data(bytes([n]))


def _p2wsh_multisig(tag: str, m: int, sign_with: List[int],
                    wrong_msg: bool = False,
                    key_tag: Optional[str] = None) -> Tuple[Tx, bytes]:
    """P2WSH m-of-20 spend signed by key indices `sign_with` (ascending —
    the order the cursor needs). Returns (signed tx, spk). `key_tag`
    shares one derived key set across many txs (bench scale-ups)."""
    sks, pubs = _multisig_keys(key_tag or tag)
    ws = (
        _opnum(m)
        + b"".join(push_data(p) for p in pubs)
        + _opnum(len(pubs))
        + bytes([OP_CHECKMULTISIG])
    )
    spk = b"\x00\x20" + sha256(ws)
    tx = _spend_tx(tag)
    sighash = bip143_sighash(ws, tx, 0, SIGHASH_ALL, AMOUNT)
    if wrong_msg:
        sighash = sha256(b"corpus/other-msg")
    sigs = [H.sign_ecdsa(sks[i], sighash) + bytes([SIGHASH_ALL]) for i in sign_with]
    tx.vin[0].witness = [b""] + sigs + [ws]
    tx.invalidate_caches()
    return tx, spk


def _cases_multisig_fanout() -> List[CorpusCase]:
    tx1, spk1 = _p2wsh_multisig("ms-last", 1, [19])
    tx2, spk2 = _p2wsh_multisig("ms-top2", 2, [18, 19])
    tx3, spk3 = _p2wsh_multisig("ms-none", 1, [19], wrong_msg=True)
    return [
        CorpusCase(
            "multisig-1of20-last-key", "multisig_fanout",
            "1-of-20 CHECKMULTISIG whose sig matches only the last key: "
            "19 false curve checks before the true pairing",
            _item(tx1, spk1), True, Error.ERR_OK, ScriptError.OK,
        ),
        CorpusCase(
            "multisig-2of20-top-keys", "multisig_fanout",
            "2-of-20 signed by the two highest keys — the cursor burns "
            "18 misses before the first hit",
            _item(tx2, spk2), True, Error.ERR_OK, ScriptError.OK,
        ),
        CorpusCase(
            "multisig-1of20-no-match", "multisig_fanout",
            "well-formed sig matching none of the 20 keys: full cursor "
            "walk, then false (NULLFAIL not in the extended flag set)",
            _item(tx3, spk3), False, Error.ERR_SCRIPT, ScriptError.EVAL_FALSE,
        ),
    ]


# --------------------------------------------------------------------------
# quadratic_sighash — pre-BIP143 legacy inputs: every input's SIGHASH_ALL
# serializes the ENTIRE transaction (interpreter.cpp:1577-1642), so a
# K-input legacy tx hashes O(K²) bytes. BIP143 killed this for segwit;
# legacy spends still pay it.
# --------------------------------------------------------------------------

def _quadratic_tx(tag: str, k: int) -> Tuple[Tx, List[Tuple[int, bytes]]]:
    sks = [_sk(f"{tag}/q{i}") for i in range(k)]
    pubs = [H.pubkey_create(sk) for sk in sks]
    spks = [
        b"\x76\xa9" + push_data(hash160(p)) + b"\x88\xac" for p in pubs
    ]
    tx = _spend_tx(tag, n_inputs=k)
    for i in range(k):
        sighash = legacy_sighash(spks[i], tx, i, SIGHASH_ALL)
        sig = H.sign_ecdsa(sks[i], sighash) + bytes([SIGHASH_ALL])
        tx.vin[i].script_sig = push_data(sig) + push_data(pubs[i])
    tx.invalidate_caches()
    return tx, [(AMOUNT, spk) for spk in spks]


def _cases_quadratic() -> List[CorpusCase]:
    k = 16
    tx, outs = _quadratic_tx("quad16", k)
    raw = tx.serialize()
    first = BatchItem(raw, 0, VERIFY_ALL_EXTENDED, spent_outputs=outs)
    last = BatchItem(raw, k - 1, VERIFY_ALL_EXTENDED, spent_outputs=outs)
    return [
        CorpusCase(
            "quadratic-16in-legacy-first", "quadratic_sighash",
            "input 0 of a 16-input all-legacy tx: each input re-hashes "
            "the whole tx (pre-BIP143 quadratic shape)",
            first, True, Error.ERR_OK, ScriptError.OK,
        ),
        CorpusCase(
            "quadratic-16in-legacy-last", "quadratic_sighash",
            "last input of the same 16-input legacy tx",
            last, True, Error.ERR_OK, ScriptError.OK,
        ),
    ]


# --------------------------------------------------------------------------
# max_size_script — scriptPubKeys at the 10,000-byte consensus limit:
# 18 × (520-byte push + OP_DROP) filler then a P2PK tail keeps the
# non-push op count at 19 (limit 201) while the byte budget nearly fills.
# --------------------------------------------------------------------------

def _max_size_spk(tag: str, oversize: bool = False) -> Tuple[bytes, int]:
    """(spk, signing key). ~9.5 kB valid; `oversize` pads one byte past
    MAX_SCRIPT_SIZE so execution must fail with SCRIPT_SIZE."""
    sk = _sk(f"{tag}/pk")
    pub = H.pubkey_create(sk)
    blob = hashlib.sha256(f"corpus/{tag}/blob".encode()).digest()
    blob = (blob * ((MAX_SCRIPT_ELEMENT_SIZE // 32) + 1))[:MAX_SCRIPT_ELEMENT_SIZE]
    unit = push_data(blob) + bytes([OP_DROP])
    spk = unit * 18 + push_data(pub) + bytes([OP_CHECKSIG])
    if oversize:
        spk += bytes([0x61]) * (MAX_SCRIPT_SIZE + 1 - len(spk))  # OP_NOP pad
    assert (len(spk) > MAX_SCRIPT_SIZE) == oversize
    return spk, sk


def _max_size_tx(tag: str, spk: bytes, sk: int) -> Tx:
    tx = _spend_tx(tag)
    sighash = legacy_sighash(spk, tx, 0, SIGHASH_ALL)
    sig = H.sign_ecdsa(sk, sighash) + bytes([SIGHASH_ALL])
    tx.vin[0].script_sig = push_data(sig)
    tx.invalidate_caches()
    return tx


def _cases_max_size() -> List[CorpusCase]:
    spk, sk = _max_size_spk("maxs")
    tx = _max_size_tx("maxs", spk, sk)
    spk_big, sk_big = _max_size_spk("maxs-over", oversize=True)
    tx_big = _max_size_tx("maxs-over", spk_big, sk_big)
    return [
        CorpusCase(
            "maxscript-9.5kb-p2pk", "max_size_script",
            f"{len(spk)}-byte scriptPubKey (520-byte pushes + OP_DROP "
            "filler, P2PK tail) just under MAX_SCRIPT_SIZE",
            _item(tx, spk), True, Error.ERR_OK, ScriptError.OK,
        ),
        CorpusCase(
            "maxscript-oversize-10001", "max_size_script",
            "one byte past MAX_SCRIPT_SIZE: must fail SCRIPT_SIZE before "
            "any execution",
            _item(tx_big, spk_big), False, Error.ERR_SCRIPT,
            ScriptError.SCRIPT_SIZE,
        ),
    ]


# --------------------------------------------------------------------------
# taproot_annex — BIP341 script-path spend with an annex: single tapleaf
# (`<xonly> OP_CHECKSIG`), control block committing the leaf into the
# output key, witness [sig, script, control, annex]. The annex rides the
# sighash (spend_type bit + annex hash, interpreter.cpp:1106-1108), so a
# signature that ignores it must fail.
# --------------------------------------------------------------------------

def _taproot_scriptpath(tag: str, sign_annex: bool = True) -> Tuple[Tx, bytes]:
    internal_sk = _sk(f"{tag}/internal")
    px, parity = H.xonly_pubkey_create(internal_sk)

    leaf_sk = _sk(f"{tag}/leaf")
    leaf_px, leaf_parity = H.xonly_pubkey_create(leaf_sk)
    leaf_sk_even = leaf_sk if leaf_parity == 0 else H.N - leaf_sk
    script = push_data(leaf_px) + bytes([OP_CHECKSIG])
    tapleaf_hash = tagged_hash("TapLeaf", bytes([0xC0]) + ser_string(script))

    t = int.from_bytes(tagged_hash("TapTweak", px + tapleaf_hash), "big") % H.N
    internal_even = internal_sk if parity == 0 else H.N - internal_sk
    out_sk = (internal_even + t) % H.N
    qx, q_parity = H.xonly_pubkey_create(out_sk)
    spk = b"\x51\x20" + qx
    control = bytes([0xC0 | q_parity]) + px

    annex = bytes([0x50]) + hashlib.sha256(f"corpus/{tag}/annex".encode()).digest()
    tx = _spend_tx(tag)
    txdata = PrecomputedTxData(tx, [TxOut(AMOUNT, spk)], force=True)
    sighash = bip341_sighash(
        tx, 0, SIGHASH_DEFAULT, SigVersion.TAPSCRIPT, txdata,
        annex_present=sign_annex,
        annex_hash=sha256(ser_string(annex)) if sign_annex else b"",
        tapleaf_hash=tapleaf_hash,
    )
    sig = H.sign_schnorr(leaf_sk_even, sighash)
    tx.vin[0].witness = [sig, script, control, annex]
    tx.invalidate_caches()
    return tx, spk


def _cases_taproot_annex() -> List[CorpusCase]:
    tx, spk = _taproot_scriptpath("tap-annex")
    tx_bad, spk_bad = _taproot_scriptpath("tap-annex-bad", sign_annex=False)
    return [
        CorpusCase(
            "taproot-scriptpath-annex", "taproot_annex",
            "taproot script-path spend (single CHECKSIG tapleaf) with a "
            "33-byte annex committed into the BIP341 sighash",
            _item(tx, spk), True, Error.ERR_OK, ScriptError.OK,
        ),
        CorpusCase(
            "taproot-scriptpath-annex-unsigned", "taproot_annex",
            "same spend but the signature did not commit to the annex — "
            "the sighash diverges and the Schnorr check must fail",
            _item(tx_bad, spk_bad), False, Error.ERR_SCRIPT,
            ScriptError.SCHNORR_SIG,
        ),
    ]


# --------------------------------------------------------------------------
# sig_malleation + boundary_flags — the exact flag bits where a verdict
# legally flips: high-S (LOW_S), BER padding (DERSIG), CHECKMULTISIG
# dummy (NULLDUMMY) and failed-sig cleanliness (NULLFAIL). Each pair pins
# BOTH sides so a flag-plumbing regression in any backend surfaces as a
# corpus divergence, not a silent policy drift.
# --------------------------------------------------------------------------

def _p2pkh_spend(tag: str, mangle=None) -> Tuple[Tx, bytes]:
    sk = _sk(f"{tag}/pk")
    pub = H.pubkey_create(sk)
    spk = b"\x76\xa9" + push_data(hash160(pub)) + b"\x88\xac"
    tx = _spend_tx(tag)
    sighash = legacy_sighash(spk, tx, 0, SIGHASH_ALL)
    sig = H.sign_ecdsa(sk, sighash) + bytes([SIGHASH_ALL])
    if mangle is not None:
        sig = mangle(sig)
    tx.vin[0].script_sig = push_data(sig) + push_data(pub)
    tx.invalidate_caches()
    return tx, spk


def _bare_1of1(tag: str, dummy: bytes, wrong_msg: bool = False) -> Tuple[Tx, bytes]:
    sk = _sk(f"{tag}/pk")
    pub = H.pubkey_create(sk)
    spk = bytes([OP_1]) + push_data(pub) + bytes([OP_1, OP_CHECKMULTISIG])
    tx = _spend_tx(tag)
    sighash = legacy_sighash(spk, tx, 0, SIGHASH_ALL)
    if wrong_msg:
        sighash = sha256(b"corpus/multisig-wrong")
    sig = H.sign_ecdsa(sk, sighash) + bytes([SIGHASH_ALL])
    tx.vin[0].script_sig = dummy + push_data(sig)
    tx.invalidate_caches()
    return tx, spk


def _cases_malleation_and_flags() -> List[CorpusCase]:
    hs_tx, hs_spk = _p2pkh_spend("mall-highs", mangle=_malleate_high_s)
    pad_tx, pad_spk = _p2pkh_spend("mall-pad", mangle=_pad_der)
    nd_tx, nd_spk = _bare_1of1("flag-nulldummy", bytes([OP_1]))
    nf_tx, nf_spk = _bare_1of1("flag-nullfail", b"\x00", wrong_msg=True)
    return [
        CorpusCase(
            "malleate-high-s-accepted", "sig_malleation",
            "S -> N-S malleated signature; consensus-valid while "
            "VERIFY_LOW_S is off (verify normalizes, pubkey.cpp:204)",
            _item(hs_tx, hs_spk), True, Error.ERR_OK, ScriptError.OK,
        ),
        CorpusCase(
            "malleate-high-s-low-s-flag", "sig_malleation",
            "same spend with VERIFY_LOW_S set: SIG_HIGH_S",
            _item(hs_tx, hs_spk, flags=VERIFY_ALL_EXTENDED | VERIFY_LOW_S),
            False, Error.ERR_SCRIPT, ScriptError.SIG_HIGH_S,
        ),
        CorpusCase(
            "malleate-der-padded-dersig", "sig_malleation",
            "BER-padded R integer under VERIFY_DERSIG (BIP66): SIG_DER",
            _item(pad_tx, pad_spk), False, Error.ERR_SCRIPT,
            ScriptError.SIG_DER,
        ),
        CorpusCase(
            "malleate-der-padded-pre-dersig", "sig_malleation",
            "same BER padding with only P2SH active (pre-BIP66 rules): "
            "parse_der_lax tolerates it",
            _item(pad_tx, pad_spk, flags=VERIFY_P2SH),
            True, Error.ERR_OK, ScriptError.OK,
        ),
        CorpusCase(
            "boundary-nulldummy-rejected", "boundary_flags",
            "bare 1-of-1 CHECKMULTISIG with an OP_1 dummy under "
            "VERIFY_NULLDUMMY (in the extended set): SIG_NULLDUMMY",
            _item(nd_tx, nd_spk), False, Error.ERR_SCRIPT,
            ScriptError.SIG_NULLDUMMY,
        ),
        CorpusCase(
            "boundary-nulldummy-accepted", "boundary_flags",
            "same dummy with only P2SH active: accepted",
            _item(nd_tx, nd_spk, flags=VERIFY_P2SH),
            True, Error.ERR_OK, ScriptError.OK,
        ),
        CorpusCase(
            "boundary-nullfail", "boundary_flags",
            "failed CHECKMULTISIG with a non-empty signature under "
            "VERIFY_NULLFAIL: SIG_NULLFAIL instead of plain false",
            _item(nf_tx, nf_spk, flags=VERIFY_ALL_EXTENDED | VERIFY_NULLFAIL),
            False, Error.ERR_SCRIPT, ScriptError.SIG_NULLFAIL,
        ),
        CorpusCase(
            "boundary-nullfail-off", "boundary_flags",
            "same failed CHECKMULTISIG without NULLFAIL: EVAL_FALSE",
            _item(nf_tx, nf_spk), False, Error.ERR_SCRIPT,
            ScriptError.EVAL_FALSE,
        ),
    ]


# --------------------------------------------------------------------------
# scalar_edge — verifications whose ECDSA scalars hit the GLV/recoder
# boundaries the scalar-schedule prover certifies (analysis/scalar_check):
# u2 = r·s⁻¹ mod n is what `split_lambda` decomposes and the windowed
# recoders digest, so each case *constructs* a signature with a pinned u2.
#
# Construction (bare OP_CHECKSIG spk, so the legacy sighash z is
# key-independent): pick a nonce k, r = x(k·G); set s = r·t⁻¹ so that
# u2 = r·s⁻¹ = t exactly; then the verification equation
# u1·G + u2·P = k·G fixes the secret key sk = (k − u1)·t⁻¹ mod n.
# Flags are VERIFY_P2SH only (no LOW_S: s is whatever t demands).
# --------------------------------------------------------------------------

def _u2_pinned_spend(tag: str, t: int, u1_one: bool = False,
                     break_sig: bool = False) -> Tuple[Tx, bytes]:
    """Spend of a bare OP_CHECKSIG output whose verification scalar
    u2 ≡ t (mod n) — or u1 == 1 when `u1_one` (t is then implied)."""
    spk = bytes([OP_CHECKSIG])
    tx = _spend_tx(tag)
    z = int.from_bytes(legacy_sighash(spk, tx, 0, SIGHASH_ALL), "big") % H.N
    ctr = 0
    while True:
        k = _sk(f"{tag}/nonce/{ctr}")
        ctr += 1
        raff = H.G.mul(k).to_affine()
        r = raff[0] % H.N
        if r == 0:
            continue
        if u1_one:
            s = z  # u1 = z·s⁻¹ = 1
            t = r * pow(s, H.N - 2, H.N) % H.N
        else:
            s = r * pow(t, H.N - 2, H.N) % H.N
        if s == 0 or t == 0:
            continue
        u1 = z * pow(s, H.N - 2, H.N) % H.N
        sk = (k - u1) * pow(t, H.N - 2, H.N) % H.N
        if sk == 0:
            continue
        break
    pub = H.pubkey_create(sk)
    if break_sig:
        s = s + 1 if s + 1 < H.N else s - 1
    body = H._der_encode_int(r) + H._der_encode_int(s)
    sig = b"\x30" + bytes([len(body)]) + body + bytes([SIGHASH_ALL])
    tx.vin[0].script_sig = push_data(sig) + push_data(pub)
    tx.invalidate_caches()
    return tx, spk


def _cases_scalar_edge() -> List[CorpusCase]:
    from ..crypto.glv import LAMBDA  # local: pulls in ops.curve (jax)

    # Every signed digit at the minimum -16 (the maximal 25-long carry
    # chain): window 0 holds 16, windows 1..24 hold 15 (+1 carry-in),
    # and the top window absorbs the final carry at its proven cap of 7.
    max_digits = 16 + 15 * sum(32 ** w for w in range(1, 25)) + 6 * 32 ** 25
    targets = [
        ("scalar-u2-one", 1,
         "u2 pinned to 1: the minimal nonzero scalar through the "
         "GLV split and both recoders"),
        ("scalar-u2-n-minus-1", H.N - 1,
         "u2 pinned to n-1: negation-heavy split, maximal reduction"),
        ("scalar-u2-lambda", LAMBDA,
         "u2 pinned to the endomorphism eigenvalue lambda: the split "
         "degenerates to (0, 1) up to sign"),
        ("scalar-u2-lambda-plus-1", (LAMBDA + 1) % H.N,
         "u2 pinned one past lambda: smallest perturbation off the "
         "lattice eigenvector"),
        ("scalar-u2-2p128-minus-1", (1 << 128) - 1,
         "u2 pinned to 2^128-1: a split half exactly at the proven "
         "|k_i| < 2^128 boundary when the split passes it through"),
        ("scalar-u2-2p128", 1 << 128,
         "u2 pinned to 2^128: first scalar the 128-bit half encoding "
         "cannot carry verbatim — the lattice must actually reduce"),
        ("scalar-u2-max-signed-digits", max_digits,
         "u2 whose signed recoding is all windows at -16 (maximal "
         "carry chain) with the top window at its carry-free cap of 7"),
    ]
    cases = [
        CorpusCase(
            name, "scalar_edge", desc,
            _item(_tx_spk[0], _tx_spk[1], flags=VERIFY_P2SH),
            True, Error.ERR_OK, ScriptError.OK,
        )
        for name, t, desc in targets
        for _tx_spk in [_u2_pinned_spend(name, t)]
    ]
    u1_tx, u1_spk = _u2_pinned_spend("scalar-u1-one", 0, u1_one=True)
    cases.append(CorpusCase(
        "scalar-u1-one", "scalar_edge",
        "u1 pinned to 1: the G-table multiplier at its minimal nonzero "
        "value",
        _item(u1_tx, u1_spk, flags=VERIFY_P2SH),
        True, Error.ERR_OK, ScriptError.OK,
    ))
    bad_tx, bad_spk = _u2_pinned_spend("scalar-u2-lambda-bad", LAMBDA,
                                       break_sig=True)
    cases.append(CorpusCase(
        "scalar-u2-lambda-badsig", "scalar_edge",
        "same lambda-pinned construction with s+1: CHECKSIG pushes "
        "false and the script fails EVAL_FALSE (no NULLFAIL in flags)",
        _item(bad_tx, bad_spk, flags=VERIFY_P2SH),
        False, Error.ERR_SCRIPT, ScriptError.EVAL_FALSE,
    ))
    return cases


def build_corpus() -> List[CorpusCase]:
    """The full pinned corpus, deterministic (no RNG anywhere above)."""
    return (
        _cases_multisig_fanout()
        + _cases_quadratic()
        + _cases_max_size()
        + _cases_taproot_annex()
        + _cases_malleation_and_flags()
        + _cases_scalar_edge()
    )


def shape_batch(shape: str, n: int, seed: int = 0) -> List[BatchItem]:
    """`n` all-valid items of one worst-case shape for benching (distinct
    prevouts/sighashes per item so nothing short-circuits through the
    sig/script caches on a cold run; key material is shared per shape —
    construction cost stays linear)."""
    tag = f"bench{seed}"
    items: List[BatchItem] = []
    if shape == "multisig_fanout":
        for i in range(n):
            tx, spk = _p2wsh_multisig(
                f"{tag}/ms{i}", 1, [19], key_tag=f"{tag}/ms-keys"
            )
            items.append(_item(tx, spk))
    elif shape == "quadratic_sighash":
        tx, outs = _quadratic_tx(f"{tag}/quad", n)
        raw = tx.serialize()
        items = [
            BatchItem(raw, i, VERIFY_ALL_EXTENDED, spent_outputs=outs)
            for i in range(n)
        ]
    elif shape == "max_size_script":
        spk, sk = _max_size_spk(f"{tag}/maxs")
        for i in range(n):
            tx = _max_size_tx(f"{tag}/maxs{i}", spk, sk)
            items.append(_item(tx, spk))
    elif shape == "taproot_annex":
        for i in range(n):
            tx, spk = _taproot_scriptpath(f"{tag}/tap{i}")
            items.append(_item(tx, spk))
    else:
        raise ValueError(f"no bench batch for shape {shape!r}")
    return items


def run_corpus_check(corpus: Optional[List[CorpusCase]] = None) -> dict:
    """Every corpus entry through every available engine, each verdict
    compared against its pin. One mismatch is either a consensus bug or
    a stale pin — both fail the gauntlet (fail-closed, no allowlist).
    Also feeds the per-shape telemetry the stats gate requires."""
    from time import perf_counter

    from . import (
        GAUNTLET_CORPUS_CASES,
        GAUNTLET_DIVERGENCE,
        GAUNTLET_SHAPE_SECONDS,
    )
    from .diff_fuzz import batch_verdicts, native_verdict, python_verdict

    cases = build_corpus() if corpus is None else corpus
    bat = batch_verdicts([c.item for c in cases])
    mismatches: List[dict] = []
    native_seen = False
    for c, b in zip(cases, bat):
        GAUNTLET_CORPUS_CASES.inc(shape=c.shape)
        t0 = perf_counter()
        got = {"batch": b, "python": python_verdict(c.item)}
        nat = native_verdict(c.item)
        GAUNTLET_SHAPE_SECONDS.observe(perf_counter() - t0, shape=c.shape)
        if nat is not None:
            native_seen = True
            got["native"] = nat
        want = c.expected()
        for engine, verdict in got.items():
            if verdict != want:
                mismatches.append(
                    {
                        "case": c.name,
                        "shape": c.shape,
                        "engine": engine,
                        "want": list(want),
                        "got": list(verdict),
                    }
                )
    GAUNTLET_DIVERGENCE.inc(len(mismatches), leg="corpus")
    return {
        "cases": len(cases),
        "shapes": sorted({c.shape for c in cases}),
        "native_available": native_seen,
        "mismatches": mismatches,
        "pinned": not mismatches,
    }
