"""Cross-batch signature & script-execution caches.

Production Bitcoin Core skips re-verification of signatures it already
checked at mempool acceptance when the same tx appears in a block: a
salted-SHA256-keyed cuckoo set for (sighash, pubkey, sig) triples
(`script/sigcache.cpp:22-122`) and a second one for whole-tx script
success keyed on wtxid+flags (`validation.cpp:1477-1495,1529-1536`). Both
store *successes only* — failure is never cached, so a cache bug can only
cost work, not consensus.

TPU-era equivalents, same contract:

- `SigCache`: batch-dispatch front-end — hits resolve without shipping the
  lane to the device; verified-true lanes are inserted after each
  dispatch.
- `ScriptExecutionCache`: per-(wtxid, input, flags, spent-outputs) script
  success, probed before interpretation. The spent-outputs digest is part
  of the key because our API (unlike Core's UTXO view) lets callers
  supply arbitrary prevouts for the same tx.

Keys are salted per process (`os.urandom`) exactly as the reference salts
its hashers (sigcache.cpp:22-30) — entries are never addressable across
processes, so a poisoned entry cannot be constructed offline. Storage is
a bounded LRU (OrderedDict) rather than a cuckoo table: the reference's
cuckoo design buys lock-free concurrent probes on 32 B entries; under the
GIL an LRU dict has the same asymptotics with far less machinery. All
methods hold a mutex, making concurrent `verify_batch` calls safe — the
thread contract the reference documents for its own globals
(`pubkey.h:257-258`) and SURVEY §5 requires of ours.
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict
from typing import Iterable, Optional, Tuple

from ..obs import counter as _obs_counter
from ..obs import gauge as _obs_gauge
from ..resilience import faults as _faults

__all__ = [
    "SigCache",
    "ScriptExecutionCache",
    "default_sig_cache",
    "default_script_cache",
]

# Cache telemetry, labeled by cache role ("sig" / "script"; tests pass
# their own labels to isolate). Invariants asserted by tests/test_sigcache:
# hits + misses == lookups; insertions - evictions - erases == entries.
_C_LOOKUPS = _obs_counter(
    "consensus_cache_lookups_total", "cache probes", ("cache",)
)
_C_HITS = _obs_counter("consensus_cache_hits_total", "cache hits", ("cache",))
_C_MISSES = _obs_counter(
    "consensus_cache_misses_total", "cache misses", ("cache",)
)
_C_INSERTS = _obs_counter(
    "consensus_cache_insertions_total", "cache insertions", ("cache",)
)
_C_EVICTS = _obs_counter(
    "consensus_cache_evictions_total", "LRU evictions past max_entries",
    ("cache",),
)
_C_ERASES = _obs_counter(
    "consensus_cache_erases_total",
    "erase-on-hit removals (Core's mempool->block pattern)", ("cache",),
)
_C_ENTRIES = _obs_gauge(
    "consensus_cache_entries", "current cache entry count", ("cache",)
)


class _SaltedLRU:
    """Bounded success-set with a per-process salted key digest."""

    def __init__(self, max_entries: int, cache_label: str = "cache"):
        assert max_entries > 0
        self._salt = os.urandom(32)
        self._max = max_entries
        # Chaos-harness injection site (resilience/faults.py): an armed
        # "poison" fault makes one probe report a fabricated hit, the
        # observable a genuinely poisoned entry would produce.
        self._poison_site = "sigcache." + cache_label
        self._set: OrderedDict[bytes, None] = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.erases = 0
        self.insertions = 0
        # Bound metric children: one dict lookup + label-key build at
        # construction, plain locked adds on the probe/insert hot paths.
        lbl = {"cache": cache_label}
        self._m_lookups = _C_LOOKUPS.labels(**lbl)
        self._m_hits = _C_HITS.labels(**lbl)
        self._m_misses = _C_MISSES.labels(**lbl)
        self._m_inserts = _C_INSERTS.labels(**lbl)
        self._m_evicts = _C_EVICTS.labels(**lbl)
        self._m_erases = _C_ERASES.labels(**lbl)
        self._m_entries = _C_ENTRIES.labels(**lbl)

    def _key(self, parts: Iterable[bytes]) -> bytes:
        h = hashlib.sha256(self._salt)
        for p in parts:
            h.update(len(p).to_bytes(4, "little"))
            h.update(p)
        return h.digest()

    def contains_key(self, k: bytes, erase: bool = False) -> bool:
        """Probe by a precomputed digest (see SigCache.keys_for_checks)."""
        poisoned = _faults.poison_hit(self._poison_site)
        with self._lock:
            present = k in self._set
            hit = present or poisoned
            if present:
                self.hits += 1
                if erase:
                    del self._set[k]
                    self.erases += 1
                else:
                    self._set.move_to_end(k)
            elif poisoned:
                # Fabricated hit, dict untouched: counted as a hit so the
                # hits+misses==lookups invariant holds under chaos.
                self.hits += 1
            else:
                self.misses += 1
            size = len(self._set)
        # Registry updates outside the cache lock: no nested-lock ordering
        # to reason about, and a slow metrics path can never stall probes.
        self._m_lookups.inc()
        if hit:
            self._m_hits.inc()
            if present and erase:
                self._m_erases.inc()
                self._m_entries.set(size)
        else:
            self._m_misses.inc()
        return hit

    def discard_key(self, k: bytes) -> None:
        """Drop a proven-wrong entry (resilience cache-audit containment).

        No-op when absent. Counted as an erase so the entry-count
        invariant (insertions - evictions - erases == entries) holds."""
        with self._lock:
            present = k in self._set
            if present:
                del self._set[k]
                self.erases += 1
            size = len(self._set)
        if present:
            self._m_erases.inc()
            self._m_entries.set(size)

    def add_key(self, k: bytes) -> None:
        with self._lock:
            # A re-add of a present key is a freshness touch, not an
            # insertion: counting it would break the entry-accounting
            # invariant (insertions - evictions - erases == entries)
            # that concurrent writers rely on to detect lost entries.
            new = k not in self._set
            self._set[k] = None
            self._set.move_to_end(k)
            evicted = 0
            while len(self._set) > self._max:
                self._set.popitem(last=False)
                evicted += 1
            self.evictions += evicted
            if new:
                self.insertions += 1
            size = len(self._set)
        if new:
            self._m_inserts.inc()
        if evicted:
            self._m_evicts.inc(evicted)
        self._m_entries.set(size)

    def contains(self, parts: Iterable[bytes], erase: bool = False) -> bool:
        return self.contains_key(self._key(parts), erase=erase)

    def add(self, parts: Iterable[bytes]) -> None:
        self.add_key(self._key(parts))

    def keys_for_parts(self, items) -> list:
        """Digests for many part-tuples in one native call (byte-identical
        to `_key`; Python fallback otherwise). Pair with
        `contains_key`/`add_key` to amortize hashing over a batch."""
        from .. import native_bridge

        if native_bridge.available():
            return native_bridge.digest_streams(self._salt, items)
        return [self._key(parts) for parts in items]

    def __len__(self) -> int:
        return len(self._set)


class SigCache(_SaltedLRU):
    """Valid-signature set over deferred curve checks (sigcache.cpp:22-122).

    A `SigCheck`'s (kind, data) tuple is flattened into the salted digest;
    `contains` on a hit refreshes recency (Core's mempool->block pattern
    uses erase-on-hit from the block path; pass erase=True to match)."""

    def __init__(self, max_entries: int = 1 << 16, cache_label: str = "sig"):
        super().__init__(max_entries, cache_label=cache_label)

    @staticmethod
    def _parts(kind: str, data: Tuple) -> Tuple[bytes, ...]:
        # Ints serialize at 8 bytes signed so a future check kind carrying
        # e.g. a satoshi amount can never overflow the key builder (the
        # length-prefixed digest keeps 4- and 8-byte encodings distinct).
        parts = [kind.encode()]
        for d in data:
            parts.append(
                d if isinstance(d, bytes) else int(d).to_bytes(8, "little", signed=True)
            )
        return tuple(parts)

    def contains_check(self, kind: str, data: Tuple, erase: bool = False) -> bool:
        return self.contains(self._parts(kind, data), erase=erase)

    def add_check(self, kind: str, data: Tuple) -> None:
        self.add(self._parts(kind, data))

    def keys_for_checks(self, checks) -> list:
        """Digests for many SigCheck-shaped (kind, data) checks in one
        native call (byte-identical to `_key(_parts(...))`, asserted by
        tests/test_sigcache.py); Python fallback otherwise. Use with
        `contains_key`/`add_key` to amortize hashing over a batch."""
        from .. import native_bridge

        pairs = [(c.kind, c.data) for c in checks]
        if native_bridge.available():
            return native_bridge.digest_checks(self._salt, pairs)
        return [self._key(self._parts(k, d)) for k, d in pairs]


class ScriptExecutionCache(_SaltedLRU):
    """Per-input script success keyed on (wtxid, input index, flags,
    spent-outputs digest) — validation.cpp:1529-1536 reshaped to the
    per-input batch API."""

    def __init__(self, max_entries: int = 1 << 15, cache_label: str = "script"):
        super().__init__(max_entries, cache_label=cache_label)

    @staticmethod
    def _parts(
        wtxid: bytes, n_in: int, flags: int, spent_digest: bytes
    ) -> Tuple[bytes, ...]:
        return (
            wtxid,
            n_in.to_bytes(4, "little"),
            flags.to_bytes(4, "little"),
            spent_digest,
        )

    @staticmethod
    def spent_digest(spent_outputs) -> bytes:
        """Digest of the (amount, scriptPubKey) list a caller supplied
        (empty-sentinel for the legacy single-prevout form)."""
        h = hashlib.sha256()
        if spent_outputs is None:
            return b"\x00" * 32
        for amt, spk in spent_outputs:
            h.update(int(amt).to_bytes(8, "little", signed=True))
            h.update(len(spk).to_bytes(4, "little"))
            h.update(spk)
        return h.digest()

    def contains_input(
        self, wtxid: bytes, n_in: int, flags: int, spent_digest: bytes
    ) -> bool:
        return self.contains(self._parts(wtxid, n_in, flags, spent_digest))

    def add_input(
        self, wtxid: bytes, n_in: int, flags: int, spent_digest: bytes
    ) -> None:
        self.add(self._parts(wtxid, n_in, flags, spent_digest))


_default_sig: Optional[SigCache] = None
_default_script: Optional[ScriptExecutionCache] = None
_default_lock = threading.Lock()


def default_sig_cache() -> SigCache:
    global _default_sig
    with _default_lock:
        if _default_sig is None:
            _default_sig = SigCache()
        return _default_sig


def default_script_cache() -> ScriptExecutionCache:
    global _default_script
    with _default_lock:
        if _default_script is None:
            _default_script = ScriptExecutionCache()
        return _default_script
