"""`verify_batch()` — the TPU-era equivalent of Core's per-input fan-out.

The reference parallelizes block validation by pushing one `CScriptCheck`
per input onto a thread-pool queue (`checkqueue.h:29-163`,
`validation.cpp:2190`). The TPU-native design replaces thread-level
parallelism with *signature-level batching* using the checker-override seam
the reference itself provides (`DeferringSignatureChecker`,
`interpreter.h:275-301`; `CachingTransactionSignatureChecker`,
`script/sigcache.cpp:101-122`):

1. Every input's script runs on host with a `DeferringSignatureChecker`
   that records each curve operation (ECDSA / Schnorr / taproot-tweak) and
   optimistically reports success (encoding checks still run inline).
2. All recorded checks from all inputs — deduplicated, the in-batch
   analogue of Core's salted sig cache (`script/sigcache.cpp:22-122`) —
   resolve in one mixed device dispatch (`crypto/jax_backend.py`).
3. Any input whose optimistic guesses were wrong is RE-interpreted with
   the device results as an oracle; checks discovered by the corrected
   control flow (CHECKMULTISIG's cursor advance depends on each result,
   interpreter.cpp:1177-1205; OP_CHECKSIG pushes the bool,
   interpreter.cpp:1097; NULLFAIL, interpreter.cpp:365-366) go out as
   further batched dispatches until a fixpoint — e.g. a 2-of-3 multisig
   whose sigs belong to the lower keys converges in two rounds, all on
   device. A round cap falls back to the exact host checker.

Batch results are bit-identical to per-input `verify_with_flags` /
`verify_with_spent_outputs`, including `Error` codes and `ScriptError`s
(asserted by tests/test_batch.py).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..api import ConsensusError, Error
from ..core.flags import ALL_FLAG_BITS, LIBCONSENSUS_FLAGS, VERIFY_TAPROOT
from ..core.interpreter import (
    ScriptExecutionData,
    TransactionSignatureChecker,
    verify_script,
)
from ..core.script_error import ScriptError
from ..core.serialize import SerializationError
from ..core.sighash import PrecomputedTxData
from ..core.tx import Tx, TxOut
from ..crypto.jax_backend import SigCheck, TpuSecpVerifier, default_verifier
from .. import native_bridge
from ..obs import counter as _obs_counter
from ..obs import gauge as _obs_gauge
from ..obs import histogram as _obs_histogram
from ..obs import span as _span
from ..resilience import faults as _faults
from ..resilience import guards as _guards
from ..utils.gcpause import gc_paused
from .sigcache import (
    ScriptExecutionCache,
    SigCache,
    default_script_cache,
    default_sig_cache,
)

__all__ = ["BatchItem", "BatchResult", "verify_batch", "verify_batch_stream"]

# Batch-driver telemetry (README "Observability"). All updates are host
# side and integer-valued — this module is under the host AST lint, which
# bans float literals and clock reads; timing flows through obs spans (the
# one sanctioned clock reader).
_BATCH_SIZE = _obs_histogram(
    "consensus_batch_size",
    "items per verify_batch call",
    buckets=(1, 8, 64, 512, 4096, 32768),
)
_BATCH_ITEMS = _obs_counter(
    "consensus_batch_items_total", "inputs submitted to verify_batch"
)
_BATCH_RESULTS = _obs_counter(
    "consensus_batch_results_total",
    "verify_batch results by outcome",
    ("outcome",),
)
_STREAM_WINDOW = _obs_gauge(
    "consensus_pipeline_stream_window",
    "stream handles concurrently in flight in verify_batch_stream "
    "(begun, not yet finished) — the pipeline's realized overlap depth",
)
_FIXPOINT_ROUNDS = _obs_histogram(
    "consensus_fixpoint_rounds",
    "oracle re-interpretation rounds needed per batch fixpoint",
    buckets=(1, 2, 3, 4, 6, 8, 12, 24),
)
_EXACT_FALLBACK = _obs_counter(
    "consensus_exact_fallback_total",
    "inputs resolved by the exact host checker at the round cap",
)
_UNIQ_CHECKS = _obs_counter(
    "consensus_uniq_checks_total",
    "deduplicated curve checks discovered (uniq-list growth, index mode)",
)
# Shared with crypto/jax_backend.py: exceptional device lanes resolved
# exactly on host, whichever driver flags them.
_HOST_FIXUPS = _obs_counter(
    "consensus_host_fixup_total",
    "exceptional device lanes resolved exactly on host",
)
# Reject-reason counters are shared with the per-input API entry points
# (same registry names -> one process-wide view across both paths).
_VERIFY_REJECTS = _obs_counter(
    "consensus_verify_reject_total",
    "verify rejections by transport Error code (api + batch paths)",
    ("code",),
)
_SCRIPT_REJECTS = _obs_counter(
    "consensus_script_reject_total",
    "script-level rejections by ScriptError code (api + batch paths)",
    ("script_error",),
)


def _record_batch_results(out: List["BatchResult"]) -> None:
    """Aggregate result counters locally, then publish once per batch —
    bounded lock traffic no matter the batch size."""
    ok_n = 0
    rejects: Dict[Tuple[str, Optional[str]], int] = {}
    for r in out:
        if r.ok:
            ok_n += 1
        else:
            serr = (
                r.script_error.name
                if r.script_error is not None
                and r.script_error != ScriptError.OK
                else None
            )
            key = (r.error.name, serr)
            rejects[key] = rejects.get(key, 0) + 1
    if ok_n:
        _BATCH_RESULTS.inc(ok_n, outcome="ok")
    for (code, serr), n in rejects.items():
        _BATCH_RESULTS.inc(n, outcome="reject")
        _VERIFY_REJECTS.inc(n, code=code)
        if serr is not None:
            _SCRIPT_REJECTS.inc(n, script_error=serr)


@dataclass
class BatchItem:
    """One input verification request.

    `spent_outputs` (all prevouts of the tx, in input order) unlocks the
    taproot path; with only `spent_output_script`+`amount` the item has the
    same reach as the reference C ABI (SURVEY §3.2).
    """

    spending_tx: bytes
    input_index: int
    flags: int
    spent_output_script: Optional[bytes] = None
    amount: int = 0
    spent_outputs: Optional[Sequence[Tuple[int, bytes]]] = None


@dataclass
class BatchResult:
    ok: bool
    error: Error
    script_error: Optional[ScriptError] = None

    @staticmethod
    def success() -> "BatchResult":
        return BatchResult(True, Error.ERR_OK, ScriptError.OK)


class DeferringSignatureChecker(TransactionSignatureChecker):
    """Records curve checks and answers from a known-results oracle,
    optimistically succeeding on unknowns; the sighash and all encoding
    checks still run inline (they are host work by design).

    With an empty oracle this is the plain optimistic first pass. With
    device results fed back in, re-interpretation resolves control flow
    exactly where earlier guesses were wrong — the CHECKMULTISIG cursor
    (interpreter.cpp:1177-1205) tries sig/key pairs in order, so a 2-of-3
    whose sigs belong to lower keys discovers the true pairing over a few
    oracle rounds, each a batched device dispatch instead of host EC math.
    `unknown` counts oracle misses: zero means the produced verdict is
    exact."""

    def __init__(self, tx, n_in, amount, txdata, known=None):
        super().__init__(tx, n_in, amount, txdata)
        self.recorded: List[SigCheck] = []
        self.known = known if known is not None else {}
        self.unknown = 0

    def _resolve(self, kind: str, data: Tuple) -> bool:
        res = self.known.get((kind, data))
        if res is None:
            self.unknown += 1
            self.recorded.append(SigCheck(kind, data))
            return True
        return res

    def verify_ecdsa(self, sig_der: bytes, pubkey: bytes, sighash: bytes) -> bool:
        return self._resolve("ecdsa", (pubkey, sig_der, sighash))

    def verify_schnorr(self, sig64: bytes, pubkey32: bytes, sighash: bytes) -> bool:
        return self._resolve("schnorr", (pubkey32, sig64, sighash))

    def verify_taproot_tweak(self, q: bytes, parity: int, p: bytes, t: bytes) -> bool:
        return self._resolve("tweak", (q, parity, p, t))


@dataclass
class _Prepared:
    result: Optional[BatchResult] = None  # set when failed before batching
    tx: Optional[Tx] = None
    txdata: Optional[PrecomputedTxData] = None
    script_pubkey: bytes = b""
    amount: int = 0
    optimistic: Optional[Tuple[bool, ScriptError]] = None
    checks: List[SigCheck] = field(default_factory=list)
    ntx: Optional[object] = None  # native_bridge.NativeTx when native is on
    wtxid: Optional[bytes] = None


def _spent_memo_entry(item: BatchItem, spent_memo: Dict[int, Tuple]):
    """(List[TxOut], digest) for item.spent_outputs, memoized by the
    sequence's identity: a 10k-input tx shares ONE conversion + digest
    across its 10k items instead of an O(n²) per-item pass. Identity
    keying is safe within one verify_batch call (items hold the refs)."""
    key = id(item.spent_outputs)
    ent = spent_memo.get(key)
    if ent is None:
        outs = [TxOut(a, s) for a, s in item.spent_outputs]
        ent = (outs, ScriptExecutionCache.spent_digest(item.spent_outputs))
        spent_memo[key] = ent
    return ent


def _prepare(
    item: BatchItem,
    tx_cache: Dict[bytes, Tuple[Tx, bool]],
    txdata_cache: Dict[Tuple, PrecomputedTxData],
    spent_memo: Dict[int, Tuple],
    ntx_cache: Optional[Dict] = None,
) -> _Prepared:
    """Transport-level validation; mirrors bitcoinconsensus.cpp:79-101 check
    order (flags -> deserialize -> index -> size). PrecomputedTxData is
    built once per (tx, prevouts-digest) — the validation.cpp:1538-1549
    one-hash-pass-per-tx shape — and the digest keying means conflicting
    prevout lists for the same tx can never share a cache entry. With the
    native core on (ntx_cache given), parse + transport checks + hash
    precompute all happen in C++ and the Python Tx/PrecomputedTxData are
    never built (they are only consumed by the Python fallback engine)."""
    prep = _Prepared()
    allowed = ALL_FLAG_BITS if item.spent_outputs is not None else LIBCONSENSUS_FLAGS
    if item.flags & ~allowed:
        prep.result = BatchResult(False, Error.ERR_INVALID_FLAGS)
        return prep

    if ntx_cache is not None:
        if item.spent_outputs is not None:
            spent_outputs, digest = _spent_memo_entry(item, spent_memo)
            key = (item.spending_tx, digest)
        else:
            spent_outputs = None
            key = (item.spending_tx, None)
        if key in ntx_cache:
            ntx = ntx_cache[key]
        else:
            try:
                ntx = native_bridge.NativeTx(item.spending_tx)
            except ValueError:
                ntx = None
            if ntx is not None:
                # Precompute only with a LENGTH-VALID prevout list (one per
                # input); a mismatched list is rejected below with
                # ERR_TX_INDEX and the handle stays un-precomputed (it is
                # never interpreted — same key means same mismatch).
                if item.spent_outputs is None:
                    ntx.precompute()
                elif len(spent_outputs) == ntx.n_inputs:
                    ntx.set_spent_outputs(list(item.spent_outputs))
            ntx_cache[key] = ntx
        if ntx is None:
            prep.result = BatchResult(False, Error.ERR_TX_DESERIALIZE)
            return prep
        if item.input_index < 0 or item.input_index >= ntx.n_inputs:
            prep.result = BatchResult(False, Error.ERR_TX_INDEX)
            return prep
        if ntx.ser_size != len(item.spending_tx):
            prep.result = BatchResult(False, Error.ERR_TX_SIZE_MISMATCH)
            return prep
        if spent_outputs is not None:
            if len(spent_outputs) != ntx.n_inputs:
                prep.result = BatchResult(False, Error.ERR_TX_INDEX)
                return prep
            prep.script_pubkey = spent_outputs[item.input_index].script_pubkey
            prep.amount = spent_outputs[item.input_index].value
        else:
            if item.flags & VERIFY_TAPROOT:
                prep.result = BatchResult(False, Error.ERR_AMOUNT_REQUIRED)
                return prep
            prep.script_pubkey = item.spent_output_script or b""
            prep.amount = item.amount
        prep.ntx = ntx
        prep.wtxid = ntx.wtxid
        return prep

    try:
        cached = tx_cache.get(item.spending_tx)
        if cached is None:
            tx = Tx.deserialize(item.spending_tx)
            size_ok = len(tx.serialize()) == len(item.spending_tx)
            tx_cache[item.spending_tx] = (tx, size_ok)
        else:
            tx, size_ok = cached
        # Index before size, matching api._verify_input and the reference
        # (bitcoinconsensus.cpp:89-92): a tx with both trailing bytes AND an
        # out-of-range index must report ERR_TX_INDEX from every entry point.
        # nIn is unsigned in the reference ABI: negative is out-of-range,
        # never Python wraparound.
        if item.input_index < 0 or item.input_index >= len(tx.vin):
            prep.result = BatchResult(False, Error.ERR_TX_INDEX)
            return prep
        if not size_ok:
            prep.result = BatchResult(False, Error.ERR_TX_SIZE_MISMATCH)
            return prep
    except SerializationError:
        prep.result = BatchResult(False, Error.ERR_TX_DESERIALIZE)
        return prep

    if item.spent_outputs is not None:
        spent_outputs, digest = _spent_memo_entry(item, spent_memo)
        if len(spent_outputs) != len(tx.vin):
            prep.result = BatchResult(False, Error.ERR_TX_INDEX)
            return prep
        tkey = (id(tx), digest)
        txdata = txdata_cache.get(tkey)
        if txdata is None:
            txdata = PrecomputedTxData(tx, spent_outputs)
            txdata_cache[tkey] = txdata
        prep.txdata = txdata
        prep.script_pubkey = spent_outputs[item.input_index].script_pubkey
        prep.amount = spent_outputs[item.input_index].value
    else:
        if item.flags & VERIFY_TAPROOT:
            prep.result = BatchResult(False, Error.ERR_AMOUNT_REQUIRED)
            return prep
        tkey = (id(tx), None)
        txdata = txdata_cache.get(tkey)
        if txdata is None:
            txdata = PrecomputedTxData(tx)
            txdata_cache[tkey] = txdata
        prep.txdata = txdata
        prep.script_pubkey = item.spent_output_script or b""
        prep.amount = item.amount
    prep.tx = tx
    prep.wtxid = tx.wtxid
    return prep


def _idx_threads() -> int:
    """Interpretation fan-out width for the native index-mode path (the
    checkqueue.h:29-163 axis; the C call releases the GIL). Overridable
    via BITCOINCONSENSUS_TPU_THREADS; single-core hosts stay serial."""
    env = os.environ.get("BITCOINCONSENSUS_TPU_THREADS", "")
    if env:
        return max(1, int(env))
    return max(1, os.cpu_count() or 1)


class _UniqState:
    """Per-fixpoint resolution state over the session's uniq list:
    `val[i]` is entry i's verdict (every entry resolves in the round that
    discovers it, so the array is complete up to its length)."""

    __slots__ = ("val",)

    def __init__(self):
        self.val = np.zeros(0, dtype=bool)


def _accept_mask(state: _UniqState, rec_idx: np.ndarray, bounds,
                 unk) -> np.ndarray:
    """Per-input acceptance after a resolve round: input k's verdict is
    exact when it had no oracle misses (unk == 0) or every miss resolved
    TRUE (the optimistic assumption matched reality).
    Vectorized over the rec_idx slices via one cumulative sum — the
    per-input Python loop this replaces was ~10% of block-replay host
    time."""
    unk = np.asarray(unk)
    out = unk == 0
    if len(rec_idx) and not out.all():
        have = state.val[rec_idx].astype(np.int64)
        b = np.asarray(bounds, dtype=np.int64)
        cs = np.concatenate([np.zeros(1, np.int64), np.cumsum(have)])
        out = out | ((cs[b[1:]] - cs[b[:-1]]) == (b[1:] - b[:-1]))
    return out


def _dispatch_uniq(nsess, verifier, sig_cache, state: _UniqState):
    """Async half of the uniq resolve round: salted sig-cache probe first
    (success-only skip, script/sigcache.cpp:22-122), then packed kernel
    lanes prepped IN the session (no check bytes cross the bridge) and
    one in-flight device dispatch per chunk. Returns an opaque round
    record for `_settle_uniq` — nothing is synchronized here, so the
    caller can run host work (the NEXT batch's interpretation) while the
    lanes are on the wire. Returns None when no new uniq entries exist.

    Dispatch policy note: every unresolved entry resolves each round —
    INCLUDING the speculative CHECKMULTISIG pairings no rec_idx
    references. Deferring the speculative entries to a contingent second
    dispatch was measured and rejected: Core's CHECKMULTISIG cursor walks
    keys top-down (interpreter.cpp:1177-1205), so even a consensus-
    ordered m-of-n spend guesses a FALSE pairing first and the
    re-interpretation needs the pre-recorded pairings known — they are
    the main verdict path, not insurance, and deferring them bought a
    second 10k-lane device round-trip on the multisig benchmark."""
    U = nsess.uniq_count()
    lo = len(state.val)
    if U == lo:
        return None
    _UNIQ_CHECKS.inc(U - lo)
    grow = np.arange(lo, U, dtype=np.int32)
    with verifier.phases("host_prep"):
        digs = nsess.uniq_digests(sig_cache._salt, grow)
    raw = digs.tobytes()
    keys = {int(i): raw[32 * j : 32 * j + 32] for j, i in enumerate(grow)}
    state.val = np.concatenate([state.val, np.zeros(U - lo, dtype=bool)])

    if len(sig_cache) == 0 and _faults.active() is None:
        miss = [int(i) for i in grow]  # cold cache: every probe misses
    else:
        audit = _guards.audit_cache_hits()
        miss = []
        for i in grow:
            if sig_cache.contains_key(keys[int(i)]):
                # Audit mode (resilience): a hit certifies a past success,
                # but a poisoned entry certifies nothing — re-verify on
                # the exact oracle and evict entries proven wrong.
                if audit and not nsess.uniq_host_verify(int(i)):
                    _guards.CACHE_POISON_CAUGHT.inc(cache="sig")
                    sig_cache.discard_key(keys[int(i)])
                    miss.append(int(i))
                else:
                    state.val[i] = True
            else:
                miss.append(int(i))
    pending = []
    if miss:
        cap = verifier.lane_capacity
        for s in range(0, len(miss), cap):
            sub = np.asarray(miss[s : s + cap], dtype=np.int32)
            with verifier.phases("host_prep"):
                lanes = nsess.uniq_lanes(sub, verifier.pad(len(sub)))
            pending.append((verifier.dispatch_lanes(lanes, len(sub)), sub))
    return grow, keys, pending


def _settle_uniq(nsess, verifier, sig_cache, state: _UniqState,
                 round_rec) -> None:
    """Settle half of the uniq resolve round: every in-flight ticket
    resolves through the verifier's guards (exceptional or contained
    lanes land on nat_session_uniq_host_verify), verdicts publish into
    the native oracle and successes into the salted sig cache."""
    if round_rec is None:
        return
    grow, keys, pending = round_rec
    for pend, sub in pending:
        okv, needs = verifier.sync_lanes(pend, len(sub))
        okv = np.array(okv, dtype=bool, copy=True)
        if needs is not None and needs.any():
            fix = np.nonzero(needs)[0]
            _HOST_FIXUPS.inc(len(fix))
            for t in fix:
                r = nsess.uniq_host_verify(int(sub[t]))
                okv[t] = r
                if not r:
                    verifier._fixup_failed = True
        state.val[sub] = okv
        for t in np.nonzero(okv)[0]:  # success-only, like the reference
            sig_cache.add_key(keys[int(sub[int(t)])])

    nsess.publish_uniq(grow, state.val[grow].astype(np.int32))


def _resolve_uniq(nsess, verifier, sig_cache, state: _UniqState) -> None:
    """One synchronous uniq resolve round (dispatch + settle back-to-back)."""
    _settle_uniq(nsess, verifier, sig_cache, state,
                 _dispatch_uniq(nsess, verifier, sig_cache, state))


class IdxFixpoint:
    """The deferral fixpoint both index-mode drivers share
    (`_verify_batch_idx` and models/validate.py `_connect_block_native` —
    ONE copy of the consensus-critical loop), split into an async `begin`
    and a settling `finish` so stream drivers can overlap batches.

    `begin()` interprets the pending inputs (`run_idx(pos) -> (ok, err,
    unk, rec_idx, bounds)`) and dispatches every newly-discovered uniq
    check, leaving the round's device lanes IN FLIGHT. `finish()` settles
    them, accepts inputs whose verdicts are exact (no misses, or every
    optimistic guess confirmed true), and runs any remaining rounds to
    the fixpoint; inputs still pending at the round cap go through
    `exact_fallback(idx) -> (ok, err_code)`. A stream driver calls batch
    N+1's `begin()` between batch N's `begin()` and `finish()`, so host
    interpretation runs while the previous batch is on the wire —
    `verify_batch_stream` is that driver."""

    def __init__(
        self,
        nsess,
        verifier: TpuSecpVerifier,
        sig_cache: SigCache,
        live: Sequence[int],
        run_idx,
        exact_fallback,
        max_rounds: int = 24,  # > MAX_PUBKEYS_PER_MULTISIG cursor retries
    ):
        self.nsess = nsess
        self.verifier = verifier
        self.sig_cache = sig_cache
        self.run_idx = run_idx
        self.exact_fallback = exact_fallback
        self.max_rounds = max_rounds
        self.final: Dict[int, Tuple[bool, int]] = {}
        self._state = _UniqState()
        self._pending = list(live)
        self._rounds = 0
        self._in_flight = None  # (interp tuple, uniq round record)

    def begin(self) -> None:
        """Start one round: interpret + dispatch, nothing synchronized."""
        if self._in_flight is not None or not self._pending:
            return
        if self._rounds >= self.max_rounds:
            return
        self._rounds += 1
        with _span("batch.interpret", n=len(self._pending)):
            interp = self.run_idx(self._pending)
        with _span("batch.resolve"):
            rec = _dispatch_uniq(self.nsess, self.verifier, self.sig_cache,
                                 self._state)
        self._in_flight = (interp, rec)

    def _settle_round(self) -> None:
        interp, rec = self._in_flight
        self._in_flight = None
        with _span("batch.resolve"):
            _settle_uniq(self.nsess, self.verifier, self.sig_cache,
                         self._state, rec)
        ok, err, unk, rec_idx, bounds = interp
        # exact verdict (unk == 0), or optimistic with every guess
        # confirmed true — equivalent to an exact pass
        accept = _accept_mask(self._state, rec_idx, bounds, unk)
        still: List[int] = []
        for k, idx in enumerate(self._pending):
            if accept[k]:
                self.final[idx] = (bool(ok[k]), int(err[k]))
            else:
                still.append(idx)
        self._pending = still

    def abandon(self) -> None:
        """Settle-and-discard the in-flight round without running the
        fixpoint (the stream driver's generator-close path). The round's
        device tickets hold buffers and a backpressure slot in the
        verifier's in-flight queue, so they must settle even when nobody
        wants the verdicts; settle failures are already contained by the
        guards and irrelevant to a dead run."""
        if self._in_flight is None:
            self._pending = []
            return
        _interp, rec = self._in_flight
        self._in_flight = None
        if rec is not None:
            _grow, _keys, pending = rec
            for pend, sub in pending:
                try:
                    self.verifier.sync_lanes(pend, len(sub))
                except Exception:
                    pass
        self._pending = []

    def finish(self) -> Dict[int, Tuple[bool, int]]:
        """Settle the in-flight round, then loop to the fixpoint."""
        if self._in_flight is not None:
            self._settle_round()
        while self._pending and self._rounds < self.max_rounds:
            self.begin()
            if self._in_flight is None:  # defensive: begin refused
                break
            self._settle_round()
        _FIXPOINT_ROUNDS.observe(self._rounds)
        if self._pending:  # round cap hit: exact host fallback
            _EXACT_FALLBACK.inc(len(self._pending))
        for idx in self._pending:
            self.final[idx] = self.exact_fallback(idx)
        return self.final


def run_idx_fixpoint(
    nsess,
    verifier: TpuSecpVerifier,
    sig_cache: SigCache,
    live: Sequence[int],
    run_idx,
    exact_fallback,
    max_rounds: int = 24,
) -> Dict[int, Tuple[bool, int]]:
    """Synchronous fixpoint (begin + finish back-to-back); the signature
    models/validate.py `_connect_block_native` drives."""
    run = IdxFixpoint(nsess, verifier, sig_cache, live, run_idx,
                      exact_fallback, max_rounds=max_rounds)
    run.begin()
    return run.finish()


def _verify_batch_idx(
    items: Sequence[BatchItem],
    preps: List[_Prepared],
    nsess,
    verifier: TpuSecpVerifier,
    sig_cache: SigCache,
    script_cache: ScriptExecutionCache,
    script_keys: List[Optional[bytes]],
) -> List[BatchResult]:
    """Index-mode batch driver (the fast path of `verify_batch`).

    Same three phases as the legacy wire driver — deferring
    interpretation, one deduplicated device dispatch, oracle
    re-interpretation to a fixpoint — but the session keeps the deduped
    check list (`uniq`) in C++ and Python only ever moves int32 indices
    and packed lane arrays (native/nat.cpp nat_verify_inputs_idx + the
    uniq trio). Interpretation shards across `_idx_threads()` workers
    (checkqueue.h:29-163 shape). Results are bit-identical to the wire
    driver and the per-input API (tests/test_batch.py runs both paths)."""
    run = _idx_fixpoint_for(items, preps, nsess, verifier, sig_cache)
    final: Dict[int, Tuple[bool, int]] = {}
    if run is not None:
        run.begin()
        final = run.finish()
    return _assemble_idx_results(preps, final, script_cache, script_keys)


def _idx_fixpoint_for(
    items: Sequence[BatchItem],
    preps: List[_Prepared],
    nsess,
    verifier: TpuSecpVerifier,
    sig_cache: SigCache,
) -> Optional[IdxFixpoint]:
    """Build the fixpoint runner for a prepared index-mode batch (None
    when every input already resolved via transport checks or the script
    cache). Shared by the synchronous driver and the stream driver."""
    live = [i for i, p in enumerate(preps) if p.result is None]
    if not live:
        return None
    n_threads = _idx_threads()

    def run_idx(pos: List[int]):
        with verifier.phases("interpret"):
            return nsess.verify_inputs_idx(
                [preps[i].ntx for i in pos],
                [items[i].input_index for i in pos],
                [preps[i].amount for i in pos],
                [preps[i].script_pubkey for i in pos],
                [items[i].flags for i in pos],
                n_threads=n_threads,
            )

    def exact_fallback(idx: int) -> Tuple[bool, int]:
        okx, err_code, _ = nsess.verify_input(
            preps[idx].ntx, items[idx].input_index, preps[idx].amount,
            preps[idx].script_pubkey, items[idx].flags,
            mode=native_bridge.NativeSession.MODE_EXACT,
        )
        return okx, err_code

    return IdxFixpoint(nsess, verifier, sig_cache, live, run_idx,
                       exact_fallback)


def _assemble_idx_results(
    preps: List[_Prepared],
    final: Dict[int, Tuple[bool, int]],
    script_cache: ScriptExecutionCache,
    script_keys: List[Optional[bytes]],
) -> List[BatchResult]:
    out: List[BatchResult] = []
    for idx, prep in enumerate(preps):
        if prep.result is not None:
            out.append(prep.result)
            continue
        ok, err = final[idx]
        if ok:
            if script_keys[idx] is not None:
                script_cache.add_key(script_keys[idx])
            out.append(BatchResult.success())
        else:
            out.append(BatchResult(False, Error.ERR_SCRIPT, ScriptError(err)))
    return out


def verify_batch(
    items: Sequence[BatchItem],
    verifier: Optional[TpuSecpVerifier] = None,
    sig_cache: Optional[SigCache] = None,
    script_cache: Optional[ScriptExecutionCache] = None,
) -> List[BatchResult]:
    """Verify many inputs with one TPU signature dispatch.

    Returns one `BatchResult` per item, bit-identical to the per-input API.
    The cross-batch caches (success-only, salted keys — the
    `script/sigcache.cpp` / `validation.cpp:1529-1536` production skip
    paths) default to the process-wide instances; pass fresh instances to
    isolate. Mempool→block replays skip interpretation and the device
    entirely on repeat batches.

    Cycle collection is paused for the duration (utils/gcpause.py): the
    driver's allocation churn otherwise triggers repeated full GC passes
    over the JAX runtime's heap — measured 12x on cached replays.
    """
    _BATCH_SIZE.observe(len(items))
    _BATCH_ITEMS.inc(len(items))
    with gc_paused(), _span("batch.verify_batch", n=len(items)):
        out = _verify_batch_impl(items, verifier, sig_cache, script_cache)
    _record_batch_results(out)
    return out


def verify_batch_stream(
    batches,
    verifier: Optional[TpuSecpVerifier] = None,
    sig_cache: Optional[SigCache] = None,
    script_cache: Optional[ScriptExecutionCache] = None,
    depth: int = 2,
):
    """Pipelined `verify_batch` over an iterable of item lists.

    Yields one result list per input batch, in order, bit-identical to
    calling `verify_batch` per batch — but with up to `depth` batches in
    flight: batch N+1's parse/probe/interpretation runs on the host while
    batch N's device lanes are on the wire, so a sustained stream pays
    the link latency once, not once per batch. The verifier's bounded
    in-flight queue still applies per dispatch (backpressure), and every
    ticket settles through the resilience guards — overlap never bypasses
    containment.

    Batches that cannot take the index-mode path (no native core, or a
    transport-failed parse without a native handle) fall back to a
    synchronous `verify_batch` for that batch; ordering is preserved.
    """
    if verifier is None:
        verifier = default_verifier()
    if sig_cache is None:
        sig_cache = default_sig_cache()
    if script_cache is None:
        script_cache = default_script_cache()
    depth = max(1, int(depth))
    window: List[tuple] = []

    def _begin(items):
        with gc_paused(), _span("batch.stream_begin", n=len(items)):
            if native_bridge.available() and _idx_mode_enabled():
                nsess, preps, script_keys, _ = _prepare_and_probe(
                    items, script_cache
                )
                if all(p.result is not None or p.ntx is not None
                       for p in preps):
                    _BATCH_SIZE.observe(len(items))
                    _BATCH_ITEMS.inc(len(items))
                    run = _idx_fixpoint_for(items, preps, nsess, verifier,
                                            sig_cache)
                    if run is not None:
                        run.begin()
                    return ("idx", run, preps, script_keys)
        # Synchronous fallback: full verify (its own metrics/spans).
        return ("done", verify_batch(items, verifier, sig_cache,
                                     script_cache))

    def _finish(handle):
        if handle[0] == "done":
            return handle[1]
        _tag, run, preps, script_keys = handle
        with gc_paused(), _span("batch.stream_finish", n=len(preps)):
            final = run.finish() if run is not None else {}
            out = _assemble_idx_results(preps, final, script_cache,
                                        script_keys)
        _record_batch_results(out)
        return out

    try:
        for items in batches:
            window.append(_begin(items))
            _STREAM_WINDOW.set(len(window))
            while len(window) >= depth:
                yield _finish(window.pop(0))
                _STREAM_WINDOW.set(len(window))
        while window:
            yield _finish(window.pop(0))
            _STREAM_WINDOW.set(len(window))
    finally:
        # Consumer closed the generator mid-stream (GeneratorExit lands
        # at a yield above): begun batches still hold in-flight device
        # tickets — settle and discard them so buffers and backpressure
        # slots in the verifier's queue are not leaked.
        _abandon_stream_window(window)


def _abandon_stream_window(window: List[tuple]) -> None:
    """Settle-and-discard every begun-but-unfinished stream handle."""
    while window:
        handle = window.pop(0)
        if handle[0] == "idx" and handle[1] is not None:
            handle[1].abandon()


def _prepare_and_probe(
    items: Sequence[BatchItem],
    script_cache: ScriptExecutionCache,
):
    """Front half shared by the batch drivers: parse/prepare every item
    (native session when available) and probe the script-execution cache.
    Returns (nsess, preps, script_keys, use_native)."""
    use_native = native_bridge.available()
    nsess = native_bridge.NativeSession() if use_native else None
    tx_cache: Dict[bytes, Tuple[Tx, bool]] = {}
    txdata_cache: Dict[Tuple, PrecomputedTxData] = {}
    spent_memo: Dict[int, Tuple] = {}
    ntx_cache: Optional[Dict] = {} if use_native else None
    with _span("batch.prepare", n=len(items)):
        preps = [
            _prepare(item, tx_cache, txdata_cache, spent_memo, ntx_cache)
            for item in items
        ]

    # Script-execution cache probe: a hit certifies this exact
    # (wtxid, input, flags, prevouts) succeeded before — skip the
    # interpreter and the device outright (validation.cpp:1529-1536).
    script_keys: List[Optional[bytes]] = [None] * len(items)
    with _span("batch.probe"):
        probe_idx: List[int] = []
        probe_parts: List[Tuple[bytes, ...]] = []
        for idx, (item, prep) in enumerate(zip(items, preps, strict=True)):
            if prep.result is not None or prep.wtxid is None:
                continue
            if item.spent_outputs is not None:
                digest = _spent_memo_entry(item, spent_memo)[1]
            else:
                digest = ScriptExecutionCache.spent_digest(
                    [(item.amount, item.spent_output_script or b"")]
                )
            probe_idx.append(idx)
            probe_parts.append(
                ScriptExecutionCache._parts(
                    prep.wtxid, item.input_index, item.flags, digest
                )
            )
        for idx, key in zip(probe_idx,
                            script_cache.keys_for_parts(probe_parts),
                            strict=True):
            script_keys[idx] = key
            if script_cache.contains_key(key):
                preps[idx].result = BatchResult.success()
    return nsess, preps, script_keys, use_native


def _idx_mode_enabled() -> bool:
    return os.environ.get("BITCOINCONSENSUS_TPU_IDX", "") not in ("0", "off")


def _verify_batch_impl(
    items: Sequence[BatchItem],
    verifier: Optional[TpuSecpVerifier],
    sig_cache: Optional[SigCache],
    script_cache: Optional[ScriptExecutionCache],
) -> List[BatchResult]:
    if verifier is None:
        verifier = default_verifier()
    if sig_cache is None:
        sig_cache = default_sig_cache()
    if script_cache is None:
        script_cache = default_script_cache()

    nsess, preps, script_keys, use_native = _prepare_and_probe(
        items, script_cache
    )

    # Fast path: with the native core on, every prep either failed
    # transport checks (result set) or holds a native tx handle — the
    # whole batch runs the index-mode protocol (check bytes never cross
    # the bridge; Python sees int32 uniq indices only).
    # BITCOINCONSENSUS_TPU_IDX=0 forces the legacy wire driver (kept as
    # the executable spec; tests run the corpus through both).
    if (
        use_native
        and _idx_mode_enabled()
        and all(p.result is not None or p.ntx is not None for p in preps)
    ):
        return _verify_batch_idx(
            items, preps, nsess, verifier, sig_cache, script_cache, script_keys
        )

    # Phase 1: optimistic interpretation, recording curve checks. Inputs
    # the native engine parsed run in ONE batched C call (native/eval.hpp,
    # deferring mode — same protocol at C++ speed); this Python-engine
    # closure is the fallback for the rest and the executable spec.
    def interpret_deferring(item, prep) -> Tuple[bool, ScriptError, int, List[SigCheck]]:
        checker = DeferringSignatureChecker(
            prep.tx, item.input_index, prep.amount, prep.txdata, known=known
        )
        ok, err = verify_script(
            prep.tx.vin[item.input_index].script_sig,
            prep.script_pubkey,
            prep.tx.vin[item.input_index].witness,
            item.flags,
            checker,
        )
        return ok, err, checker.unknown, checker.recorded

    known: Dict[Tuple, bool] = {}
    with _span("batch.interpret"):
        native_idx = [
            idx
            for idx, prep in enumerate(preps)
            if prep.result is None and prep.ntx is not None
        ]
        if native_idx:
            # ONE C call interprets every native-parsed input (the per-call
            # bridge overhead dominates a block-sized batch otherwise).
            ok_a, err_a, _unk_a, recs = nsess.verify_inputs(
                [preps[i].ntx for i in native_idx],
                [items[i].input_index for i in native_idx],
                [preps[i].amount for i in native_idx],
                [preps[i].script_pubkey for i in native_idx],
                [items[i].flags for i in native_idx],
                mode=native_bridge.NativeSession.MODE_DEFER,
            )
            for j, idx in enumerate(native_idx):
                preps[idx].optimistic = (
                    bool(ok_a[j]), ScriptError(int(err_a[j]))
                )
                preps[idx].checks = [SigCheck(k, d) for k, d in recs[j]]
        for item, prep in zip(items, preps, strict=True):
            if prep.result is not None or prep.ntx is not None:
                continue
            ok, err, _unk, checks = interpret_deferring(item, prep)
            prep.optimistic = (ok, err)
            prep.checks = checks

    # Speculative CHECKMULTISIG pairings recorded by the native engine ride
    # the same first dispatch (they are resolve-only: never part of any
    # prep.checks, so they cannot affect an optimistic verdict) — a
    # misaligned multisig then re-interprets against a fully-known oracle
    # instead of paying a second device round-trip.
    def drain_spec() -> List[SigCheck]:
        if nsess is None:
            return []
        return [SigCheck(k, d) for k, d in nsess.take_spec()]

    # Phase 2: sig-cache probe, then one deduplicated device dispatch for
    # every remaining recorded check (sigcache.cpp:101-122 seam). Results
    # are published into the native oracle session as they land.
    pushed: set = set()

    def publish_known() -> None:
        if nsess is None:
            return
        fresh_entries = [
            (key[0], key[1], val)
            for key, val in known.items()
            if key not in pushed
        ]
        if fresh_entries:
            nsess.add_known_batch(fresh_entries)
            pushed.update((k, d) for k, d, _ in fresh_entries)

    def resolve(checks: Sequence[SigCheck]) -> None:
        """Fill `known` for every check: sig-cache probe (keys digested in
        one native call), then ONE deduplicated device dispatch; successes
        feed the cache."""
        with _span("batch.resolve"):
            todo: List[SigCheck] = []
            for chk in checks:
                key = (chk.kind, chk.data)
                if key in known:
                    continue
                known[key] = False  # placeholder until probed/dispatched
                todo.append(chk)
            if todo:
                # Same observable as the index-mode uniq-list growth: how
                # many deduplicated checks this batch actually discovered.
                _UNIQ_CHECKS.inc(len(todo))
                cache_keys = sig_cache.keys_for_checks(todo)
                audit = _guards.audit_cache_hits()
                fresh: List[Tuple[SigCheck, bytes]] = []
                for chk, ck in zip(todo, cache_keys, strict=True):
                    if sig_cache.contains_key(ck):
                        # Audit mode (resilience): re-verify the hit on
                        # the exact oracle; evict entries proven wrong.
                        if audit and not verifier._host_check(chk):
                            _guards.CACHE_POISON_CAUGHT.inc(cache="sig")
                            sig_cache.discard_key(ck)
                            fresh.append((chk, ck))
                        else:
                            known[(chk.kind, chk.data)] = True
                    else:
                        fresh.append((chk, ck))
                if fresh:
                    fresh_checks = [c for c, _ in fresh]
                    try:
                        _faults.maybe_raise("batch.dispatch")
                        run_res = verifier.verify_checks(fresh_checks)
                    except Exception:
                        # Driver-level dispatch fault: contain by resolving
                        # every check on the host-exact oracle (fail-closed
                        # — latency, never correctness).
                        _guards.CONTAINED.inc(site="batch.dispatch")
                        _guards.HOST_EXACT_LANES.inc(len(fresh_checks))
                        run_res = [
                            verifier._host_check(c) for c in fresh_checks
                        ]
                    for (chk, ck), r in zip(fresh, run_res, strict=True):
                        known[(chk.kind, chk.data)] = bool(r)
                        if r:  # success-only insertion, like the reference
                            sig_cache.add_key(ck)
            publish_known()

    resolve([chk for prep in preps for chk in prep.checks] + drain_spec())

    # Phase 3: accept verdicts whose guesses all held; where any guess
    # failed, RE-interpret with the device results as an oracle —
    # newly-discovered checks (e.g. the true CHECKMULTISIG sig/key
    # pairing) go out as further batched dispatches until a fixpoint, so
    # control-flow-dependent scripts resolve without host EC math. A
    # round cap guards pathological scripts; the host checker is the
    # exact fallback.
    final: Dict[int, Tuple[bool, ScriptError]] = {}
    pending: List[int] = []
    for idx, prep in enumerate(preps):
        if prep.result is not None:
            continue
        if all(known[(c.kind, c.data)] for c in prep.checks):
            final[idx] = prep.optimistic
        else:
            pending.append(idx)

    max_rounds = 24  # > MAX_PUBKEYS_PER_MULTISIG cursor retries
    rounds = 1  # the optimistic pass above is round one
    for _round in range(max_rounds):
        if not pending:
            break
        rounds += 1
        new_checks: List[SigCheck] = []
        still: List[int] = []
        nat_pending = [i for i in pending if preps[i].ntx is not None]
        if nat_pending:
            ok_a, err_a, unk_a, recs = nsess.verify_inputs(
                [preps[i].ntx for i in nat_pending],
                [items[i].input_index for i in nat_pending],
                [preps[i].amount for i in nat_pending],
                [preps[i].script_pubkey for i in nat_pending],
                [items[i].flags for i in nat_pending],
                mode=native_bridge.NativeSession.MODE_DEFER,
            )
            for j, idx in enumerate(nat_pending):
                if int(unk_a[j]) == 0:
                    final[idx] = (bool(ok_a[j]), ScriptError(int(err_a[j])))
                else:
                    new_checks.extend(SigCheck(k, d) for k, d in recs[j])
                    still.append(idx)
        for idx in pending:
            if preps[idx].ntx is not None:
                continue
            item, prep = items[idx], preps[idx]
            ok, err, unknown, recorded = interpret_deferring(item, prep)
            if unknown == 0:
                final[idx] = (ok, err)  # every oracle read was exact
            else:
                new_checks.extend(recorded)
                still.append(idx)
        if not still:
            pending = []
            break
        resolve(new_checks + drain_spec())
        pending = still

    _FIXPOINT_ROUNDS.observe(rounds)
    if pending:  # round cap hit: exact host fallback
        _EXACT_FALLBACK.inc(len(pending))
    for idx in pending:
        item, prep = items[idx], preps[idx]
        if prep.ntx is not None:
            ok, err_code, _ = nsess.verify_input(
                prep.ntx, item.input_index, prep.amount, prep.script_pubkey,
                item.flags, mode=native_bridge.NativeSession.MODE_EXACT,
            )
            final[idx] = (ok, ScriptError(err_code))
            continue
        checker = TransactionSignatureChecker(
            prep.tx, item.input_index, prep.amount, prep.txdata
        )
        final[idx] = verify_script(
            prep.tx.vin[item.input_index].script_sig,
            prep.script_pubkey,
            prep.tx.vin[item.input_index].witness,
            item.flags,
            checker,
        )

    out: List[BatchResult] = []
    for idx, (_item, prep) in enumerate(zip(items, preps, strict=True)):
        if prep.result is not None:
            out.append(prep.result)
            continue
        ok, err = final[idx]
        if ok:
            if script_keys[idx] is not None:
                script_cache.add_key(script_keys[idx])
            out.append(BatchResult.success())
        else:
            out.append(BatchResult(False, Error.ERR_SCRIPT, err))
    return out
