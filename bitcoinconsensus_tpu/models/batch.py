"""`verify_batch()` — the TPU-era equivalent of Core's per-input fan-out.

The reference parallelizes block validation by pushing one `CScriptCheck`
per input onto a thread-pool queue (`checkqueue.h:29-163`,
`validation.cpp:2190`). The TPU-native design replaces thread-level
parallelism with *signature-level batching* using the checker-override seam
the reference itself provides (`DeferringSignatureChecker`,
`interpreter.h:275-301`; `CachingTransactionSignatureChecker`,
`script/sigcache.cpp:101-122`):

1. Every input's script runs on host with a `DeferringSignatureChecker`
   that records each curve operation (ECDSA / Schnorr / taproot-tweak) and
   optimistically reports success (encoding checks still run inline).
2. All recorded checks from all inputs — deduplicated, the in-batch
   analogue of Core's salted sig cache (`script/sigcache.cpp:22-122`) —
   resolve in one mixed device dispatch (`crypto/jax_backend.py`).
3. Any input whose optimistic guesses were wrong is re-run synchronously
   with the exact host checker. This is required because check results feed
   script control flow (`OP_CHECKSIG` pushes the bool, interpreter.cpp:1097;
   CHECKMULTISIG's cursor advance, interpreter.cpp:1177-1205; NULLFAIL,
   interpreter.cpp:365-366). Valid-signature batches — the mainnet common
   case — never take this path.

Batch results are bit-identical to per-input `verify_with_flags` /
`verify_with_spent_outputs`, including `Error` codes and `ScriptError`s
(asserted by tests/test_batch.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..api import ConsensusError, Error
from ..core.flags import ALL_FLAG_BITS, LIBCONSENSUS_FLAGS, VERIFY_TAPROOT
from ..core.interpreter import (
    ScriptExecutionData,
    TransactionSignatureChecker,
    verify_script,
)
from ..core.script_error import ScriptError
from ..core.serialize import SerializationError
from ..core.sighash import PrecomputedTxData
from ..core.tx import Tx, TxOut
from ..crypto.jax_backend import SigCheck, TpuSecpVerifier, default_verifier
from .sigcache import (
    ScriptExecutionCache,
    SigCache,
    default_script_cache,
    default_sig_cache,
)

__all__ = ["BatchItem", "BatchResult", "verify_batch"]


@dataclass
class BatchItem:
    """One input verification request.

    `spent_outputs` (all prevouts of the tx, in input order) unlocks the
    taproot path; with only `spent_output_script`+`amount` the item has the
    same reach as the reference C ABI (SURVEY §3.2).
    """

    spending_tx: bytes
    input_index: int
    flags: int
    spent_output_script: Optional[bytes] = None
    amount: int = 0
    spent_outputs: Optional[Sequence[Tuple[int, bytes]]] = None


@dataclass
class BatchResult:
    ok: bool
    error: Error
    script_error: Optional[ScriptError] = None

    @staticmethod
    def success() -> "BatchResult":
        return BatchResult(True, Error.ERR_OK, ScriptError.OK)


class DeferringSignatureChecker(TransactionSignatureChecker):
    """Records curve checks and optimistically succeeds; the sighash and all
    encoding checks still run inline (they are host work by design)."""

    def __init__(self, tx, n_in, amount, txdata):
        super().__init__(tx, n_in, amount, txdata)
        self.recorded: List[SigCheck] = []

    def verify_ecdsa(self, sig_der: bytes, pubkey: bytes, sighash: bytes) -> bool:
        self.recorded.append(SigCheck("ecdsa", (pubkey, sig_der, sighash)))
        return True

    def verify_schnorr(self, sig64: bytes, pubkey32: bytes, sighash: bytes) -> bool:
        self.recorded.append(SigCheck("schnorr", (pubkey32, sig64, sighash)))
        return True

    def verify_taproot_tweak(self, q: bytes, parity: int, p: bytes, t: bytes) -> bool:
        self.recorded.append(SigCheck("tweak", (q, parity, p, t)))
        return True


@dataclass
class _Prepared:
    result: Optional[BatchResult] = None  # set when failed before batching
    tx: Optional[Tx] = None
    txdata: Optional[PrecomputedTxData] = None
    script_pubkey: bytes = b""
    amount: int = 0
    optimistic: Optional[Tuple[bool, ScriptError]] = None
    checks: List[SigCheck] = field(default_factory=list)


def _prepare(item: BatchItem, tx_cache: Dict[bytes, Tx]) -> _Prepared:
    """Transport-level validation; mirrors bitcoinconsensus.cpp:79-101 check
    order (flags -> deserialize -> index -> size)."""
    prep = _Prepared()
    spent_outputs = None
    if item.spent_outputs is not None:
        allowed = ALL_FLAG_BITS
        spent_outputs = [TxOut(a, s) for a, s in item.spent_outputs]
    else:
        allowed = LIBCONSENSUS_FLAGS
    if item.flags & ~allowed:
        prep.result = BatchResult(False, Error.ERR_INVALID_FLAGS)
        return prep
    try:
        tx = tx_cache.get(item.spending_tx)
        if tx is None:
            tx = Tx.deserialize(item.spending_tx)
            if len(tx.serialize()) != len(item.spending_tx):
                prep.result = BatchResult(False, Error.ERR_TX_SIZE_MISMATCH)
                return prep
            tx_cache[item.spending_tx] = tx
        if item.input_index >= len(tx.vin):
            prep.result = BatchResult(False, Error.ERR_TX_INDEX)
            return prep
    except SerializationError:
        prep.result = BatchResult(False, Error.ERR_TX_DESERIALIZE)
        return prep

    if spent_outputs is not None:
        if len(spent_outputs) != len(tx.vin):
            prep.result = BatchResult(False, Error.ERR_TX_INDEX)
            return prep
        prep.txdata = PrecomputedTxData(tx, spent_outputs)
        prep.script_pubkey = spent_outputs[item.input_index].script_pubkey
        prep.amount = spent_outputs[item.input_index].value
    else:
        if item.flags & VERIFY_TAPROOT:
            prep.result = BatchResult(False, Error.ERR_AMOUNT_REQUIRED)
            return prep
        prep.txdata = PrecomputedTxData(tx)
        prep.script_pubkey = item.spent_output_script or b""
        prep.amount = item.amount
    prep.tx = tx
    return prep


def verify_batch(
    items: Sequence[BatchItem],
    verifier: Optional[TpuSecpVerifier] = None,
    sig_cache: Optional[SigCache] = None,
    script_cache: Optional[ScriptExecutionCache] = None,
) -> List[BatchResult]:
    """Verify many inputs with one TPU signature dispatch.

    Returns one `BatchResult` per item, bit-identical to the per-input API.
    The cross-batch caches (success-only, salted keys — the
    `script/sigcache.cpp` / `validation.cpp:1529-1536` production skip
    paths) default to the process-wide instances; pass fresh instances to
    isolate. Mempool→block replays skip interpretation and the device
    entirely on repeat batches.
    """
    if verifier is None:
        verifier = default_verifier()
    if sig_cache is None:
        sig_cache = default_sig_cache()
    if script_cache is None:
        script_cache = default_script_cache()

    tx_cache: Dict[bytes, Tx] = {}
    txdata_cache: Dict[int, PrecomputedTxData] = {}
    preps = [_prepare(item, tx_cache) for item in items]

    # Script-execution cache probe: a hit certifies this exact
    # (wtxid, input, flags, prevouts) succeeded before — skip the
    # interpreter and the device outright (validation.cpp:1529-1536).
    spent_digests: List[Optional[bytes]] = [None] * len(items)
    for idx, (item, prep) in enumerate(zip(items, preps)):
        if prep.result is not None or prep.tx is None:
            continue
        outs = (
            item.spent_outputs
            if item.spent_outputs is not None
            else [(item.amount, item.spent_output_script or b"")]
        )
        digest = ScriptExecutionCache.spent_digest(outs)
        spent_digests[idx] = digest
        if script_cache.contains_input(
            prep.tx.wtxid, item.input_index, item.flags, digest
        ):
            prep.result = BatchResult.success()
    # Share PrecomputedTxData between items of the same tx (one hash pass
    # per tx, as in validation.cpp:1538-1549).
    for prep in preps:
        if prep.tx is not None and prep.txdata is not None:
            key = id(prep.tx)
            cached = txdata_cache.get(key)
            if cached is not None and cached.spent_outputs_ready >= prep.txdata.spent_outputs_ready:
                prep.txdata = cached
            else:
                txdata_cache[key] = prep.txdata

    # Phase 1: optimistic interpretation, recording curve checks.
    for item, prep in zip(items, preps):
        if prep.result is not None:
            continue
        checker = DeferringSignatureChecker(
            prep.tx, item.input_index, prep.amount, prep.txdata
        )
        ok, err = verify_script(
            prep.tx.vin[item.input_index].script_sig,
            prep.script_pubkey,
            prep.tx.vin[item.input_index].witness,
            item.flags,
            checker,
        )
        prep.optimistic = (ok, err)
        prep.checks = checker.recorded

    # Phase 2: sig-cache probe, then one deduplicated device dispatch for
    # every remaining recorded check (sigcache.cpp:101-122 seam).
    unique: Dict[Tuple, int] = {}
    ordered: List[SigCheck] = []
    for prep in preps:
        for chk in prep.checks:
            key = (chk.kind, chk.data)
            if key not in unique:
                unique[key] = len(ordered)
                ordered.append(chk)
    known: List[Optional[bool]] = [
        True if sig_cache.contains_check(c.kind, c.data) else None for c in ordered
    ]
    to_run = [i for i, k in enumerate(known) if k is None]
    if to_run:
        run_res = verifier.verify_checks([ordered[i] for i in to_run])
        for i, r in zip(to_run, run_res):
            known[i] = bool(r)
            if r:  # success-only insertion, like the reference
                sig_cache.add_check(ordered[i].kind, ordered[i].data)
    results = known

    # Phase 3: accept optimistic verdicts; re-run exactly where any curve
    # check came back False (its result feeds control flow). Successful
    # inputs feed the script-execution cache for future batches.
    out: List[BatchResult] = []
    for idx, (item, prep) in enumerate(zip(items, preps)):
        if prep.result is not None:
            out.append(prep.result)
            continue
        all_true = all(
            results[unique[(chk.kind, chk.data)]] for chk in prep.checks
        )
        if all_true:
            ok, err = prep.optimistic
        else:
            checker = TransactionSignatureChecker(
                prep.tx, item.input_index, prep.amount, prep.txdata
            )
            ok, err = verify_script(
                prep.tx.vin[item.input_index].script_sig,
                prep.script_pubkey,
                prep.tx.vin[item.input_index].witness,
                item.flags,
                checker,
            )
        if ok:
            if spent_digests[idx] is not None:
                script_cache.add_input(
                    prep.tx.wtxid, item.input_index, item.flags, spent_digests[idx]
                )
            out.append(BatchResult.success())
        else:
            out.append(BatchResult(False, Error.ERR_SCRIPT, err))
    return out
