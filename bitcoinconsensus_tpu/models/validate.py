"""Block-connect pipeline: the north-star replay driver (SURVEY §3.5).

TPU-era reshaping of the reference's `ConnectBlock` stack
(`validation.cpp:1946` → `CheckInputScripts` `:1516-1599` →
`CScriptCheck::operator()` `:1464-1468`): where Core fans per-input script
checks onto a thread-pool queue (`checkqueue.h:29-163`), this driver runs
every input's script through the deferring interpreter and resolves the
whole block's signature algebra in batched TPU dispatches via
`verify_batch` — signature-level batching replaces thread-level
parallelism.

Scope: the consensus rules that are functions of (block, UTXO view,
height) — input existence, coinbase maturity, value conservation, sigop
cost, script validity, coinbase reward. Chain-context rules that need
headers/median-time (BIP34 height-in-coinbase, BIP68 sequence locks,
nLockTime finality, difficulty retarget) sit above this layer, exactly as
they sit above `CheckInputScripts` in the reference.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..api import Error
from ..core.block import (
    Block,
    MAX_BLOCK_SIGOPS_COST,
    POW_LIMIT_MAINNET,
    check_block,
    check_witness_commitment,
)
from ..core.flags import (
    VERIFY_P2SH,
    VERIFY_WITNESS,
    height_to_flags,
)
from ..core.script import (
    get_sig_op_count,
    is_p2sh,
    is_push_only,
    is_witness_program,
    iter_ops,
    witness_sig_ops,
)
from ..core.tx import COIN, MAX_MONEY, OutPoint, Tx, TxOut
from ..core.tx_check import WITNESS_SCALE_FACTOR
from ..crypto.jax_backend import TpuSecpVerifier
from ..obs import counter as _obs_counter
from ..obs import span as _span
from ..utils.gcpause import gc_paused
from .batch import BatchItem, BatchResult, verify_batch
from .sigcache import ScriptExecutionCache, SigCache

__all__ = [
    "Coin",
    "CoinsView",
    "ConnectResult",
    "connect_block",
    "count_witness_sigops",
    "get_transaction_sigop_cost",
    "get_block_subsidy",
    "COINBASE_MATURITY",
]

COINBASE_MATURITY = 100  # consensus/consensus.h:19
SUBSIDY_HALVING_INTERVAL = 210_000  # chainparams.cpp mainnet

# Block-level telemetry (README "Observability"). The reason label reuses
# the reference's reject strings ("bad-txns-in-belowout", ...) verbatim.
_BLOCKS = _obs_counter(
    "consensus_blocks_total", "connect_block calls by result", ("result",)
)
_BLOCK_REJECTS = _obs_counter(
    "consensus_block_reject_total",
    "connect_block rejections by reason string",
    ("reason",),
)


@dataclass
class Coin:
    """One unspent output + its creation metadata (coins.h Coin)."""

    out: TxOut
    height: int = 0
    coinbase: bool = False


class CoinsView:
    """Dict-backed UTXO set, the `CCoinsViewCache` role in ConnectBlock."""

    def __init__(self):
        self._map: Dict[Tuple[bytes, int], Coin] = {}

    def add(self, outpoint: OutPoint, coin: Coin) -> None:
        self._map[(outpoint.hash, outpoint.n)] = coin

    def add_tx(self, tx: Tx, height: int) -> None:
        cb = tx.is_coinbase()
        for n, out in enumerate(tx.vout):
            self._map[(tx.txid, n)] = Coin(out, height, cb)

    def get(self, outpoint: OutPoint) -> Optional[Coin]:
        return self._map.get((outpoint.hash, outpoint.n))

    def spend(self, outpoint: OutPoint) -> Optional[Coin]:
        return self._map.pop((outpoint.hash, outpoint.n), None)

    def __len__(self) -> int:
        return len(self._map)


def get_block_subsidy(height: int) -> int:
    """GetBlockSubsidy (validation.cpp:1246-1257)."""
    halvings = height // SUBSIDY_HALVING_INTERVAL
    if halvings >= 64:
        return 0
    return (50 * COIN) >> halvings


def count_witness_sigops(
    script_sig: bytes, script_pubkey: bytes, witness: List[bytes], flags: int
) -> int:
    """CountWitnessSigOps (interpreter.cpp:2074-2103)."""
    if not (flags & VERIFY_WITNESS):
        return 0
    assert flags & VERIFY_P2SH
    wp = is_witness_program(script_pubkey)
    if wp is not None:
        return witness_sig_ops(wp[0], wp[1], witness)
    if is_p2sh(script_pubkey) and is_push_only(script_sig):
        data = b""
        for _opcode, pushed in iter_ops(script_sig):
            data = pushed if pushed is not None else b""
        wp = is_witness_program(data)
        if wp is not None:
            return witness_sig_ops(wp[0], wp[1], witness)
    return 0


def get_transaction_sigop_cost(
    tx: Tx, spent_outputs: List[TxOut], flags: int
) -> int:
    """GetTransactionSigOpCost (consensus/tx_verify.cpp:125-147): legacy
    sigops ×4 + P2SH redeem sigops ×4 + witness sigops ×1."""
    cost = 0
    for txin in tx.vin:
        cost += get_sig_op_count(txin.script_sig, accurate=False)
    for txout in tx.vout:
        cost += get_sig_op_count(txout.script_pubkey, accurate=False)
    cost *= WITNESS_SCALE_FACTOR
    if tx.is_coinbase():
        return cost
    if flags & VERIFY_P2SH:
        p2sh = 0
        for txin, prevout in zip(tx.vin, spent_outputs, strict=True):
            if is_p2sh(prevout.script_pubkey) and is_push_only(txin.script_sig):
                data = b""
                for _opcode, pushed in iter_ops(txin.script_sig):
                    data = pushed if pushed is not None else b""
                p2sh += get_sig_op_count(data, accurate=True)
        cost += p2sh * WITNESS_SCALE_FACTOR
    for txin, prevout in zip(tx.vin, spent_outputs, strict=True):
        cost += count_witness_sigops(
            txin.script_sig, prevout.script_pubkey, txin.witness, flags
        )
    return cost


@dataclass
class ConnectResult:
    ok: bool
    reason: Optional[str] = None
    fees: int = 0
    sigop_cost: int = 0
    input_results: Optional[List[BatchResult]] = None

    @property
    def script_failures(self) -> List[int]:
        if not self.input_results:
            return []
        return [i for i, r in enumerate(self.input_results) if not r.ok]


def connect_block(
    block: Block,
    coins: CoinsView,
    height: int,
    flags: Optional[int] = None,
    verifier: Optional[TpuSecpVerifier] = None,
    check_pow: bool = True,
    check_scripts: bool = True,
    enforce_witness_commitment: Optional[bool] = None,
    pow_limit: int = POW_LIMIT_MAINNET,
    sig_cache: Optional[SigCache] = None,
    script_cache: Optional[ScriptExecutionCache] = None,
) -> ConnectResult:
    """Validate and apply one block against the UTXO view.

    Mirrors the consensus phases of `ConnectBlock` (validation.cpp:1946):

    1. context-free `CheckBlock` (+ witness commitment when the flag era
       includes WITNESS, matching IsWitnessEnabled gating);
    2. per tx: inputs present & mature, value conservation, accumulated
       sigop cost vs MAX_BLOCK_SIGOPS_COST (`validation.cpp:2155-2181`,
       `consensus/tx_verify.cpp:157-218` CheckTxInputs);
    3. all inputs' scripts through `verify_batch` — the signature-batched
       stand-in for the CCheckQueue fan-out (`validation.cpp:2190`);
    4. coinbase reward cap, then the view update (spend + add).

    The view is mutated only when every check passes. `flags` defaults to
    the mainnet `height_to_flags(height, extended=True)` schedule.

    Cycle collection is paused for the duration (utils/gcpause.py; see
    verify_batch) — the accounting loops over thousands of inputs
    otherwise pay repeated full GC passes over the JAX heap.

    With the native core on and a `NativeCoinsView`, the whole block
    layer (codec, merkle, CheckBlock, witness commitment, accounting,
    sigop costing, view update) runs in C++ and the script phase drives
    the index-mode session directly — the production replay path
    (`_connect_block_native`). Results are identical to the Python
    pipeline (tests/test_native_block.py replays both).
    """
    from .. import native_bridge

    with gc_paused(), _span("block.connect", height=height):
        if (
            isinstance(coins, native_bridge.NativeCoinsView)
            and native_bridge.available()
        ):
            res = _connect_block_native(
                block, coins, height, flags, verifier, check_pow,
                check_scripts, enforce_witness_commitment, pow_limit,
                sig_cache, script_cache,
            )
        else:
            res = _connect_block_impl(
                block, coins, height, flags, verifier, check_pow,
                check_scripts, enforce_witness_commitment, pow_limit,
                sig_cache, script_cache,
            )
    _BLOCKS.inc(result="ok" if res.ok else "reject")
    if not res.ok and res.reason:
        _BLOCK_REJECTS.inc(reason=res.reason)
    return res


def _connect_block_native(
    block, coins, height, flags, verifier, check_pow, check_scripts,
    enforce_witness_commitment, pow_limit, sig_cache, script_cache,
) -> ConnectResult:
    """`connect_block` with the block layer in C++ (native/block.hpp) and
    the script phase on the index-mode session protocol.

    Phase map (validation.cpp:1946-2228): CheckBlock + witness commitment
    + BIP30/maturity/value/sigop accounting + per-tx hash precompute all
    happen in three C calls; the script phase interprets every input in
    one nat_verify_inputs_idx call and resolves the deduped checks with
    one device dispatch (models/batch.py's driver, shared helpers); the
    view update is one C call. Verdicts and reject reasons are identical
    to `_connect_block_impl` (tests/test_native_block.py)."""
    import numpy as np

    from .. import native_bridge
    from .batch import _idx_threads

    if flags is None:
        flags = height_to_flags(height, extended=True)
    if isinstance(block, (bytes, bytearray)):
        nblk = native_bridge.NativeBlock(bytes(block))
    else:
        # The cached parse is keyed on a cheap content fingerprint (header
        # bytes + per-tx txid/wtxid) so a Block mutated between calls is
        # re-serialized instead of validated stale. Mutating a Tx without
        # tx.invalidate_caches() leaves stale txids — which misleads the
        # Python pipeline identically, so the two paths cannot diverge.
        fp = (
            block.header.serialize(),
            tuple(tx.txid for tx in block.vtx),
            tuple(tx.wtxid for tx in block.vtx),
        )
        cached = getattr(block, "_native", None)
        if cached is not None and cached[0] == fp:
            nblk = cached[1]
        else:
            nblk = native_bridge.NativeBlock(block.serialize())
            block._native = (fp, nblk)

    phases = verifier.phases if verifier is not None else None

    def phase(name):
        from contextlib import nullcontext

        return phases(name) if phases is not None else nullcontext()

    with phase("block_check"):
        reason = nblk.check(check_pow, pow_limit)
        if reason:
            return ConnectResult(False, reason)
        if enforce_witness_commitment is None:
            enforce_witness_commitment = bool(flags & VERIFY_WITNESS)
        if enforce_witness_commitment:
            reason = nblk.check_witness_commitment()
            if reason:
                return ConnectResult(False, reason)

    with phase("accounting"):
        (reason, fees, sigop_cost, tx_index, n_in, amounts, spk_offs,
         spk_blob) = nblk.accounting(coins, height, flags)
        if reason:
            return ConnectResult(False, reason)

    input_results: Optional[List[BatchResult]] = None
    if check_scripts:
        if verifier is None:
            from ..crypto.jax_backend import default_verifier

            verifier = default_verifier()
        from .sigcache import default_script_cache, default_sig_cache

        if sig_cache is None:
            sig_cache = default_sig_cache()
        if script_cache is None:
            script_cache = default_script_cache()

        n = len(tx_index)
        with phase("probe"):
            raw_keys = nblk.script_keys(script_cache._salt, flags).tobytes()
            keys = [raw_keys[32 * j : 32 * j + 32] for j in range(n)]
            if len(script_cache) == 0:  # cold cache: every probe misses
                hit = [False] * n
            else:
                hit = [script_cache.contains_key(k) for k in keys]

        nsess = native_bridge.NativeSession()
        live = [j for j in range(n) if not hit[j]]
        n_threads = _idx_threads()
        flags_a = np.full(n, flags, dtype=np.int32)

        # Raw per-tx pointers, resolved once: the NTx objects are owned by
        # the (live) nblk, so the pointers outlast any handle wrapper.
        ptr_by_tx = [nblk.tx(t)._ptr for t in range(nblk.n_tx)]

        def run_idx(pos):
            if len(pos) == n:  # common path: whole block, zero-copy
                tx_ptrs = [ptr_by_tx[t] for t in tx_index.tolist()]
                return nsess.verify_inputs_idx_raw(
                    tx_ptrs, n_in, amounts, spk_blob, spk_offs, flags_a,
                    n_threads,
                )
            sel = np.asarray(pos, dtype=np.int64)
            sub_offs = np.zeros(len(pos) + 1, dtype=np.int64)
            chunks = []
            for k, j in enumerate(pos):
                chunks.append(spk_blob[int(spk_offs[j]) : int(spk_offs[j + 1])])
                sub_offs[k + 1] = sub_offs[k] + len(chunks[-1])
            sub_blob = (
                np.concatenate(chunks) if chunks else np.zeros(1, np.uint8)
            )
            return nsess.verify_inputs_idx_raw(
                [ptr_by_tx[int(tx_index[j])] for j in pos],
                n_in[sel], amounts[sel], sub_blob, sub_offs, flags_a[sel],
                n_threads,
            )

        def timed_run_idx(pos):
            with phase("interpret"):
                return run_idx(pos)

        def exact_fallback(j: int) -> Tuple[bool, int]:
            t = int(tx_index[j])
            spk = spk_blob[int(spk_offs[j]) : int(spk_offs[j + 1])].tobytes()
            okx, err_code, _ = nsess.verify_input(
                nblk.tx(t), int(n_in[j]), int(amounts[j]), spk, flags,
                mode=native_bridge.NativeSession.MODE_EXACT,
            )
            return okx, err_code

        from .batch import run_idx_fixpoint

        final = run_idx_fixpoint(
            nsess, verifier, sig_cache, live, timed_run_idx, exact_fallback
        )

        from ..core.script_error import ScriptError

        input_results = []
        all_ok = True
        for j in range(n):
            if hit[j]:
                input_results.append(BatchResult.success())
                continue
            okj, errj = final[j]
            if okj:
                script_cache.add_key(keys[j])
                input_results.append(BatchResult.success())
            else:
                all_ok = False
                input_results.append(
                    BatchResult(False, Error.ERR_SCRIPT, ScriptError(errj))
                )
        if not all_ok:
            return ConnectResult(
                False, "block-validation-failed", fees, sigop_cost,
                input_results,
            )

    with phase("apply"):
        coins.apply_block(nblk, height)
    return ConnectResult(True, None, fees, sigop_cost, input_results)


def _connect_block_impl(
    block, coins, height, flags, verifier, check_pow, check_scripts,
    enforce_witness_commitment, pow_limit, sig_cache, script_cache,
) -> ConnectResult:
    if flags is None:
        flags = height_to_flags(height, extended=True)
    if verifier is None and check_scripts:
        from ..crypto.jax_backend import default_verifier

        verifier = default_verifier()

    ok, reason = check_block(block, check_pow=check_pow, pow_limit=pow_limit)
    if not ok:
        return ConnectResult(False, reason)
    if enforce_witness_commitment is None:
        enforce_witness_commitment = bool(flags & VERIFY_WITNESS)
    if enforce_witness_commitment:
        ok, reason = check_witness_commitment(block)
        if not ok:
            return ConnectResult(False, reason)

    # Phase 2: inputs exist, maturity, values, sigop budget; gather the
    # spent outputs each tx needs (validation.cpp:1538-1549) without
    # mutating the view yet. Outputs created earlier in this same block are
    # spendable by later txs (the in-block overlay below).
    overlay: Dict[Tuple[bytes, int], Coin] = {}
    spent: set = set()
    per_tx_spent_outputs: List[List[TxOut]] = []
    fees = 0
    sigop_cost = 0

    # BIP30 guard (validation.cpp ConnectBlock's HaveCoin scan, run against
    # the start-of-block view before any spends): a tx whose outputs would
    # overwrite a still-unspent coin is rejected instead of silently
    # destroying it. In-block txid duplicates can't arise (identical txid
    # implies an identical tx, caught by the CVE-2012-2459 merkle check).
    for tx in block.vtx:
        for n in range(len(tx.vout)):
            if coins.get(OutPoint(tx.txid, n)) is not None:
                return ConnectResult(False, "bad-txns-BIP30")

    for tx in block.vtx:
        if tx.is_coinbase():
            per_tx_spent_outputs.append([])
            sigop_cost += get_transaction_sigop_cost(tx, [], flags)
            if sigop_cost > MAX_BLOCK_SIGOPS_COST:
                return ConnectResult(False, "bad-blk-sigops")
            overlay_tx_outputs(overlay, tx, height)
            continue
        spent_outputs: List[TxOut] = []
        value_in = 0
        for txin in tx.vin:
            key = (txin.prevout.hash, txin.prevout.n)
            if key in spent:
                return ConnectResult(False, "bad-txns-inputs-missingorspent")
            coin = overlay.get(key) or coins.get(txin.prevout)
            if coin is None:
                return ConnectResult(False, "bad-txns-inputs-missingorspent")
            if coin.coinbase and height - coin.height < COINBASE_MATURITY:
                return ConnectResult(False, "bad-txns-premature-spend-of-coinbase")
            if not (0 <= coin.out.value <= MAX_MONEY):
                return ConnectResult(False, "bad-txns-inputvalues-outofrange")
            value_in += coin.out.value
            # Accumulated value must stay in range too (CheckTxInputs,
            # consensus/tx_verify.cpp:157-218 MoneyRange(nValueIn)).
            if value_in > MAX_MONEY:
                return ConnectResult(False, "bad-txns-inputvalues-outofrange")
            spent_outputs.append(coin.out)
            spent.add(key)
        value_out = sum(o.value for o in tx.vout)
        if value_in < value_out:
            return ConnectResult(False, "bad-txns-in-belowout")
        fee = value_in - value_out
        fees += fee
        if not (0 <= fees <= MAX_MONEY):
            return ConnectResult(False, "bad-txns-fee-outofrange")
        sigop_cost += get_transaction_sigop_cost(tx, spent_outputs, flags)
        if sigop_cost > MAX_BLOCK_SIGOPS_COST:
            return ConnectResult(False, "bad-blk-sigops")
        per_tx_spent_outputs.append(spent_outputs)
        overlay_tx_outputs(overlay, tx, height)

    # Coinbase reward cap (validation.cpp:2222-2228).
    coinbase_out = sum(o.value for o in block.vtx[0].vout)
    if coinbase_out > fees + get_block_subsidy(height):
        return ConnectResult(False, "bad-cb-amount")

    # Phase 3: every input's script, one batched dispatch
    # (CheckInputScripts + CCheckQueue → verify_batch).
    input_results: Optional[List[BatchResult]] = None
    if check_scripts:
        items: List[BatchItem] = []
        for tx, spent_outputs in zip(block.vtx, per_tx_spent_outputs, strict=True):
            if tx.is_coinbase():
                continue
            raw = tx.serialize()
            outs = [(o.value, o.script_pubkey) for o in spent_outputs]
            for i in range(len(tx.vin)):
                items.append(
                    BatchItem(
                        spending_tx=raw,
                        input_index=i,
                        flags=flags,
                        spent_outputs=outs,
                    )
                )
        input_results = verify_batch(
            items,
            verifier=verifier,
            sig_cache=sig_cache,
            script_cache=script_cache,
        )
        if not all(r.ok for r in input_results):
            return ConnectResult(
                False, "block-validation-failed", fees, sigop_cost, input_results
            )

    # Phase 4: apply to the view (UpdateCoins, coins.cpp).
    for tx in block.vtx:
        for txin in tx.vin:
            if not tx.is_coinbase():
                coins.spend(txin.prevout)
        coins.add_tx(tx, height)
    return ConnectResult(True, None, fees, sigop_cost, input_results)


def overlay_tx_outputs(
    overlay: Dict[Tuple[bytes, int], Coin], tx: Tx, height: int
) -> None:
    """Record a tx's outputs in the in-block overlay so later txs of the
    same block can spend them (Core applies UpdateCoins per tx in order)."""
    cb = tx.is_coinbase()
    for n, out in enumerate(tx.vout):
        overlay[(tx.txid, n)] = Coin(out, height, cb)
