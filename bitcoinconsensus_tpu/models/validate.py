"""Block-connect pipeline: the north-star replay driver (SURVEY §3.5).

TPU-era reshaping of the reference's `ConnectBlock` stack
(`validation.cpp:1946` → `CheckInputScripts` `:1516-1599` →
`CScriptCheck::operator()` `:1464-1468`): where Core fans per-input script
checks onto a thread-pool queue (`checkqueue.h:29-163`), this driver runs
every input's script through the deferring interpreter and resolves the
whole block's signature algebra in batched TPU dispatches via
`verify_batch` — signature-level batching replaces thread-level
parallelism.

Scope: the consensus rules that are functions of (block, UTXO view,
height) — input existence, coinbase maturity, value conservation, sigop
cost, script validity, coinbase reward. Chain-context rules that need
headers/median-time (BIP34 height-in-coinbase, BIP68 sequence locks,
nLockTime finality, difficulty retarget) sit above this layer, exactly as
they sit above `CheckInputScripts` in the reference.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..core.block import (
    Block,
    MAX_BLOCK_SIGOPS_COST,
    POW_LIMIT_MAINNET,
    check_block,
    check_witness_commitment,
)
from ..core.flags import (
    VERIFY_P2SH,
    VERIFY_WITNESS,
    height_to_flags,
)
from ..core.script import (
    get_sig_op_count,
    is_p2sh,
    is_push_only,
    is_witness_program,
    iter_ops,
    witness_sig_ops,
)
from ..core.tx import COIN, MAX_MONEY, OutPoint, Tx, TxOut
from ..core.tx_check import WITNESS_SCALE_FACTOR
from ..crypto.jax_backend import TpuSecpVerifier
from ..utils.gcpause import gc_paused
from .batch import BatchItem, BatchResult, verify_batch
from .sigcache import ScriptExecutionCache, SigCache

__all__ = [
    "Coin",
    "CoinsView",
    "ConnectResult",
    "connect_block",
    "count_witness_sigops",
    "get_transaction_sigop_cost",
    "get_block_subsidy",
    "COINBASE_MATURITY",
]

COINBASE_MATURITY = 100  # consensus/consensus.h:19
SUBSIDY_HALVING_INTERVAL = 210_000  # chainparams.cpp mainnet


@dataclass
class Coin:
    """One unspent output + its creation metadata (coins.h Coin)."""

    out: TxOut
    height: int = 0
    coinbase: bool = False


class CoinsView:
    """Dict-backed UTXO set, the `CCoinsViewCache` role in ConnectBlock."""

    def __init__(self):
        self._map: Dict[Tuple[bytes, int], Coin] = {}

    def add(self, outpoint: OutPoint, coin: Coin) -> None:
        self._map[(outpoint.hash, outpoint.n)] = coin

    def add_tx(self, tx: Tx, height: int) -> None:
        cb = tx.is_coinbase()
        for n, out in enumerate(tx.vout):
            self._map[(tx.txid, n)] = Coin(out, height, cb)

    def get(self, outpoint: OutPoint) -> Optional[Coin]:
        return self._map.get((outpoint.hash, outpoint.n))

    def spend(self, outpoint: OutPoint) -> Optional[Coin]:
        return self._map.pop((outpoint.hash, outpoint.n), None)

    def __len__(self) -> int:
        return len(self._map)


def get_block_subsidy(height: int) -> int:
    """GetBlockSubsidy (validation.cpp:1246-1257)."""
    halvings = height // SUBSIDY_HALVING_INTERVAL
    if halvings >= 64:
        return 0
    return (50 * COIN) >> halvings


def count_witness_sigops(
    script_sig: bytes, script_pubkey: bytes, witness: List[bytes], flags: int
) -> int:
    """CountWitnessSigOps (interpreter.cpp:2074-2103)."""
    if not (flags & VERIFY_WITNESS):
        return 0
    assert flags & VERIFY_P2SH
    wp = is_witness_program(script_pubkey)
    if wp is not None:
        return witness_sig_ops(wp[0], wp[1], witness)
    if is_p2sh(script_pubkey) and is_push_only(script_sig):
        data = b""
        for _opcode, pushed in iter_ops(script_sig):
            data = pushed if pushed is not None else b""
        wp = is_witness_program(data)
        if wp is not None:
            return witness_sig_ops(wp[0], wp[1], witness)
    return 0


def get_transaction_sigop_cost(
    tx: Tx, spent_outputs: List[TxOut], flags: int
) -> int:
    """GetTransactionSigOpCost (consensus/tx_verify.cpp:125-147): legacy
    sigops ×4 + P2SH redeem sigops ×4 + witness sigops ×1."""
    cost = 0
    for txin in tx.vin:
        cost += get_sig_op_count(txin.script_sig, accurate=False)
    for txout in tx.vout:
        cost += get_sig_op_count(txout.script_pubkey, accurate=False)
    cost *= WITNESS_SCALE_FACTOR
    if tx.is_coinbase():
        return cost
    if flags & VERIFY_P2SH:
        p2sh = 0
        for txin, prevout in zip(tx.vin, spent_outputs):
            if is_p2sh(prevout.script_pubkey) and is_push_only(txin.script_sig):
                data = b""
                for _opcode, pushed in iter_ops(txin.script_sig):
                    data = pushed if pushed is not None else b""
                p2sh += get_sig_op_count(data, accurate=True)
        cost += p2sh * WITNESS_SCALE_FACTOR
    for txin, prevout in zip(tx.vin, spent_outputs):
        cost += count_witness_sigops(
            txin.script_sig, prevout.script_pubkey, txin.witness, flags
        )
    return cost


@dataclass
class ConnectResult:
    ok: bool
    reason: Optional[str] = None
    fees: int = 0
    sigop_cost: int = 0
    input_results: Optional[List[BatchResult]] = None

    @property
    def script_failures(self) -> List[int]:
        if not self.input_results:
            return []
        return [i for i, r in enumerate(self.input_results) if not r.ok]


def connect_block(
    block: Block,
    coins: CoinsView,
    height: int,
    flags: Optional[int] = None,
    verifier: Optional[TpuSecpVerifier] = None,
    check_pow: bool = True,
    check_scripts: bool = True,
    enforce_witness_commitment: Optional[bool] = None,
    pow_limit: int = POW_LIMIT_MAINNET,
    sig_cache: Optional[SigCache] = None,
    script_cache: Optional[ScriptExecutionCache] = None,
) -> ConnectResult:
    """Validate and apply one block against the UTXO view.

    Mirrors the consensus phases of `ConnectBlock` (validation.cpp:1946):

    1. context-free `CheckBlock` (+ witness commitment when the flag era
       includes WITNESS, matching IsWitnessEnabled gating);
    2. per tx: inputs present & mature, value conservation, accumulated
       sigop cost vs MAX_BLOCK_SIGOPS_COST (`validation.cpp:2155-2181`,
       `consensus/tx_verify.cpp:157-218` CheckTxInputs);
    3. all inputs' scripts through `verify_batch` — the signature-batched
       stand-in for the CCheckQueue fan-out (`validation.cpp:2190`);
    4. coinbase reward cap, then the view update (spend + add).

    The view is mutated only when every check passes. `flags` defaults to
    the mainnet `height_to_flags(height, extended=True)` schedule.

    Cycle collection is paused for the duration (utils/gcpause.py; see
    verify_batch) — the accounting loops over thousands of inputs
    otherwise pay repeated full GC passes over the JAX heap.
    """
    with gc_paused():
        return _connect_block_impl(
            block, coins, height, flags, verifier, check_pow, check_scripts,
            enforce_witness_commitment, pow_limit, sig_cache, script_cache,
        )


def _connect_block_impl(
    block, coins, height, flags, verifier, check_pow, check_scripts,
    enforce_witness_commitment, pow_limit, sig_cache, script_cache,
) -> ConnectResult:
    if flags is None:
        flags = height_to_flags(height, extended=True)
    if verifier is None and check_scripts:
        from ..crypto.jax_backend import default_verifier

        verifier = default_verifier()

    ok, reason = check_block(block, check_pow=check_pow, pow_limit=pow_limit)
    if not ok:
        return ConnectResult(False, reason)
    if enforce_witness_commitment is None:
        enforce_witness_commitment = bool(flags & VERIFY_WITNESS)
    if enforce_witness_commitment:
        ok, reason = check_witness_commitment(block)
        if not ok:
            return ConnectResult(False, reason)

    # Phase 2: inputs exist, maturity, values, sigop budget; gather the
    # spent outputs each tx needs (validation.cpp:1538-1549) without
    # mutating the view yet. Outputs created earlier in this same block are
    # spendable by later txs (the in-block overlay below).
    overlay: Dict[Tuple[bytes, int], Coin] = {}
    spent: set = set()
    per_tx_spent_outputs: List[List[TxOut]] = []
    fees = 0
    sigop_cost = 0

    # BIP30 guard (validation.cpp ConnectBlock's HaveCoin scan, run against
    # the start-of-block view before any spends): a tx whose outputs would
    # overwrite a still-unspent coin is rejected instead of silently
    # destroying it. In-block txid duplicates can't arise (identical txid
    # implies an identical tx, caught by the CVE-2012-2459 merkle check).
    for tx in block.vtx:
        for n in range(len(tx.vout)):
            if coins.get(OutPoint(tx.txid, n)) is not None:
                return ConnectResult(False, "bad-txns-BIP30")

    for tx in block.vtx:
        if tx.is_coinbase():
            per_tx_spent_outputs.append([])
            sigop_cost += get_transaction_sigop_cost(tx, [], flags)
            if sigop_cost > MAX_BLOCK_SIGOPS_COST:
                return ConnectResult(False, "bad-blk-sigops")
            overlay_tx_outputs(overlay, tx, height)
            continue
        spent_outputs: List[TxOut] = []
        value_in = 0
        for txin in tx.vin:
            key = (txin.prevout.hash, txin.prevout.n)
            if key in spent:
                return ConnectResult(False, "bad-txns-inputs-missingorspent")
            coin = overlay.get(key) or coins.get(txin.prevout)
            if coin is None:
                return ConnectResult(False, "bad-txns-inputs-missingorspent")
            if coin.coinbase and height - coin.height < COINBASE_MATURITY:
                return ConnectResult(False, "bad-txns-premature-spend-of-coinbase")
            if not (0 <= coin.out.value <= MAX_MONEY):
                return ConnectResult(False, "bad-txns-inputvalues-outofrange")
            value_in += coin.out.value
            # Accumulated value must stay in range too (CheckTxInputs,
            # consensus/tx_verify.cpp:157-218 MoneyRange(nValueIn)).
            if value_in > MAX_MONEY:
                return ConnectResult(False, "bad-txns-inputvalues-outofrange")
            spent_outputs.append(coin.out)
            spent.add(key)
        value_out = sum(o.value for o in tx.vout)
        if value_in < value_out:
            return ConnectResult(False, "bad-txns-in-belowout")
        fee = value_in - value_out
        fees += fee
        if not (0 <= fees <= MAX_MONEY):
            return ConnectResult(False, "bad-txns-fee-outofrange")
        sigop_cost += get_transaction_sigop_cost(tx, spent_outputs, flags)
        if sigop_cost > MAX_BLOCK_SIGOPS_COST:
            return ConnectResult(False, "bad-blk-sigops")
        per_tx_spent_outputs.append(spent_outputs)
        overlay_tx_outputs(overlay, tx, height)

    # Coinbase reward cap (validation.cpp:2222-2228).
    coinbase_out = sum(o.value for o in block.vtx[0].vout)
    if coinbase_out > fees + get_block_subsidy(height):
        return ConnectResult(False, "bad-cb-amount")

    # Phase 3: every input's script, one batched dispatch
    # (CheckInputScripts + CCheckQueue → verify_batch).
    input_results: Optional[List[BatchResult]] = None
    if check_scripts:
        items: List[BatchItem] = []
        for tx, spent_outputs in zip(block.vtx, per_tx_spent_outputs):
            if tx.is_coinbase():
                continue
            raw = tx.serialize()
            outs = [(o.value, o.script_pubkey) for o in spent_outputs]
            for i in range(len(tx.vin)):
                items.append(
                    BatchItem(
                        spending_tx=raw,
                        input_index=i,
                        flags=flags,
                        spent_outputs=outs,
                    )
                )
        input_results = verify_batch(
            items,
            verifier=verifier,
            sig_cache=sig_cache,
            script_cache=script_cache,
        )
        if not all(r.ok for r in input_results):
            return ConnectResult(
                False, "block-validation-failed", fees, sigop_cost, input_results
            )

    # Phase 4: apply to the view (UpdateCoins, coins.cpp).
    for tx in block.vtx:
        for txin in tx.vin:
            if not tx.is_coinbase():
                coins.spend(txin.prevout)
        coins.add_tx(tx, height)
    return ConnectResult(True, None, fees, sigop_cost, input_results)


def overlay_tx_outputs(
    overlay: Dict[Tuple[bytes, int], Coin], tx: Tx, height: int
) -> None:
    """Record a tx's outputs in the in-block overlay so later txs of the
    same block can spend them (Core applies UpdateCoins per tx in order)."""
    cb = tx.is_coinbase()
    for n, out in enumerate(tx.vout):
        overlay[(tx.txid, n)] = Coin(out, height, cb)
