"""Crash-safe persistent signature cache: hot RAM tier over shard logs.

`SigCache` (models/sigcache.py) is the product for repeat mainnet
traffic — the cached-replay bench configs run 104-130k verifies/s
because most real-world inputs re-verify previously-seen signatures —
but it evaporates on every restart, forcing a cold device warm-up
exactly when a recovering server is most fragile. `PersistentSigCache`
promotes it to a sharded two-tier store:

- **Hot tier**: the inherited bounded LRU (`_SaltedLRU`), sized by
  `hot_entries` — recency-ordered, probe-first.
- **Disk tier**: per-shard append-only record logs under `store_dir`,
  replayed (mmap) into an in-memory key index at open. Shard affinity
  is the key's leading digest byte, so concurrent appends from sharded
  servers never contend on one file and compaction is per-shard.

Durability contract (the crash-safety story, mirrored from WAL
recovery): every record is fixed-width and CRC-checksummed
(`op ‖ key ‖ crc32(op ‖ key)`), appends are flushed to the OS per
record (kill -9 loses nothing already flushed; only the torn tail of
an in-progress append is at risk), and replay is truncation-tolerant —
it stops at the first short or checksum-failing record, truncates the
log back to the last good boundary, and counts what it skipped. A
corrupt byte can therefore cost cache *misses*, never a wrong hit from
a mangled key.

Integrity contract (fail-closed, PR 5's audit mode): the salt is
persisted with the store, so persisted entries stay addressable across
restarts — and a *poisoned* persisted entry (wrong key on disk, however
it got there) is exactly what `resilience.set_cache_audit(True)` exists
for: the batch driver re-verifies cache hits on the host-exact oracle
and calls `discard_key` on disagreement, which here also appends a
tombstone record so the poison cannot resurrect on the next restart.
The store itself never turns a miss into a hit: all it can fabricate
is extra work.

Chaos sites (resilience/faults.py): `sigstore.load` (a replay fault
leaves that shard cold — contained, counted) and `sigstore.append` (a
failed append costs persistence of one entry, never correctness).
Swept by `scripts/consensus_chaos.py --ingress`.

Env knobs: ``BITCOINCONSENSUS_TPU_SIGSTORE_DIR`` (store directory for
`sig_store_from_env`), ``BITCOINCONSENSUS_TPU_SIGSTORE_HOT_ENTRIES``
(hot-tier LRU bound, default 65536).

This module is consensus-adjacent host code (models/): the host AST
lint applies in full — integer arithmetic only, no entropy imports, and
the one sanctioned clock is `obs.monotonic` (warm-up gauge).
"""

from __future__ import annotations

import mmap
import os
import zlib
from typing import Dict, List, Optional, Tuple

from ..obs import counter as _obs_counter
from ..obs import gauge as _obs_gauge
from ..obs import monotonic as _monotonic
from ..resilience import faults as _faults
from .sigcache import SigCache

__all__ = ["PersistentSigCache", "ShardLog", "sig_store_from_env"]

# Record layout: 1-byte op + 32-byte key + 4-byte little-endian CRC32
# over (op ‖ key). Fixed width makes torn-tail detection a length check.
_OP_ADD = b"A"
_OP_DEL = b"D"
_KEY_LEN = 32
_CRC_LEN = 4
_REC_LEN = 1 + _KEY_LEN + _CRC_LEN

# Compaction: rewrite a shard once its log carries this many dead
# records (duplicates + tombstones) beyond the live set — amortized
# O(1) appends, bounded disk growth.
_COMPACT_SLACK = 64

_S_HITS = _obs_counter(
    "consensus_sigstore_hits_total",
    "persistent sigstore hits, by serving tier",
    ("tier",),
)
_S_MISSES = _obs_counter(
    "consensus_sigstore_misses_total", "persistent sigstore misses"
)
_S_TIER = _obs_gauge(
    "consensus_sigstore_tier_entries",
    "current persistent-sigstore entry count, by tier",
    ("tier",),
)
_S_WARMUP = _obs_gauge(
    "consensus_sigstore_warmup_seconds",
    "time from store open to a 90% rolling hit rate (restart warm-up)",
)
_S_REPLAY = _obs_counter(
    "consensus_sigstore_replay_records_total",
    "records applied from shard logs at store open",
)
_S_REPLAY_SKIP = _obs_counter(
    "consensus_sigstore_replay_skipped_total",
    "replay records skipped fail-closed, by reason",
    ("reason",),
)
_S_APPENDS = _obs_counter(
    "consensus_sigstore_appends_total", "records appended to shard logs"
)
_S_APPEND_ERRORS = _obs_counter(
    "consensus_sigstore_append_errors_total",
    "failed shard-log appends (entry stays unpersisted; contained)",
)
_S_COMPACTIONS = _obs_counter(
    "consensus_sigstore_compactions_total", "shard-log compaction rewrites"
)
_S_SHARD_MOVED = _obs_counter(
    "consensus_sigstore_shard_moved_total",
    "shard backing files found missing mid-run (ownership moved away); "
    "the shard restarts cold, the verify path never sees an error",
)


def _rec(op: bytes, key: bytes) -> bytes:
    body = op + key
    return body + zlib.crc32(body).to_bytes(_CRC_LEN, "little")


class ShardLog:
    """One shard's append-only record log (crash-safe, compactable).

    Not thread-safe on its own: `PersistentSigCache` serializes every
    call under its store lock."""

    def __init__(self, path: str):
        self.path = path
        self._fh = None  # append handle, opened lazily

    def _handle(self):
        if self._fh is None:
            self._fh = open(self.path, "ab")
        return self._fh

    def append(self, op: bytes, key: bytes) -> None:
        """Append one record and flush to the OS: a kill -9 after this
        returns loses nothing (only power loss can — by design we never
        fsync per record; compaction fsyncs its rewrite)."""
        fh = self._handle()
        fh.write(_rec(op, key))
        fh.flush()

    def replay_into(self, out: Dict[bytes, None]) -> Tuple[int, int]:
        """Apply every intact record to `out`; returns (applied, skipped).

        Truncation-tolerant, fail-closed: replay stops at the first
        short, checksum-failing, or unknown-op record and truncates the
        file back to the last good boundary — everything past a corrupt
        byte is untrusted (it may be a torn write), and losing it costs
        misses, never wrong hits."""
        if not os.path.exists(self.path):
            return 0, 0
        size = os.path.getsize(self.path)
        if size == 0:
            return 0, 0
        applied = 0
        skipped = 0
        pos = 0
        with open(self.path, "rb") as fh:
            mm = mmap.mmap(fh.fileno(), 0, access=mmap.ACCESS_READ)
            try:
                while pos + _REC_LEN <= size:
                    rec = mm[pos : pos + _REC_LEN]
                    body = rec[: 1 + _KEY_LEN]
                    crc = int.from_bytes(rec[1 + _KEY_LEN :], "little")
                    if zlib.crc32(body) != crc:
                        skipped += 1
                        _S_REPLAY_SKIP.inc(reason="checksum")
                        break
                    op, key = body[:1], body[1:]
                    if op == _OP_ADD:
                        out[key] = None
                    elif op == _OP_DEL:
                        out.pop(key, None)
                    else:
                        skipped += 1
                        _S_REPLAY_SKIP.inc(reason="bad_op")
                        break
                    applied += 1
                    pos += _REC_LEN
            finally:
                mm.close()
        if pos < size:
            if skipped == 0:  # clean prefix + short tail = torn append
                skipped += 1
                _S_REPLAY_SKIP.inc(reason="torn_tail")
            os.truncate(self.path, pos)
        return applied, skipped

    def compact(self, live: Dict[bytes, None]) -> None:
        """Atomically rewrite the log as one ADD record per live key:
        tmp file, fsync, rename — a crash at any point leaves either
        the old log or the new one, never a mix."""
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as fh:
            for key in live:
                fh.write(_rec(_OP_ADD, key))
            fh.flush()
            os.fsync(fh.fileno())
        self.close()
        os.replace(tmp, self.path)

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


class PersistentSigCache(SigCache):
    """Two-tier `SigCache`: hot LRU over replayed per-shard disk logs.

    Drop-in for `SigCache` anywhere the batch driver takes one —
    `contains_key` / `add_key` / `discard_key` / `keys_for_checks` all
    keep their contracts, including the audit-mode poison-eviction path
    (`discard_key` additionally appends a tombstone so an evicted entry
    stays evicted across restarts). The salt is persisted with the
    store; entries remain non-addressable without the store directory.
    """

    def __init__(
        self,
        store_dir: str,
        hot_entries: Optional[int] = None,
        shards: int = 8,
        cache_label: str = "sig",
        warmup_min_probes: int = 16,
    ):
        if hot_entries is None:
            raw = os.environ.get(
                "BITCOINCONSENSUS_TPU_SIGSTORE_HOT_ENTRIES", ""
            )
            hot_entries = int(raw) if raw else 1 << 16
        assert shards >= 1
        super().__init__(max_entries=hot_entries, cache_label=cache_label)
        self.store_dir = store_dir
        self._shards = shards
        os.makedirs(store_dir, exist_ok=True)
        self._salt = self._load_salt()
        self._logs: List[ShardLog] = [
            ShardLog(os.path.join(store_dir, "shard-%02d.log" % i))
            for i in range(shards)
        ]
        # Disk-tier index: every persisted key, by shard. The hot tier
        # (inherited `_set`) is a bounded recency view over this.
        self._cold: List[Dict[bytes, None]] = [{} for _ in range(shards)]
        # Records currently in each shard file, live or dead — drives
        # the compaction trigger.
        self._records: List[int] = [0] * shards
        self._entries = 0
        self._closed = False
        self.replay_applied = 0
        self.replay_skipped = 0
        self._replay()
        # Warm-up clock: time from open until the rolling hit rate over
        # this instance's probes reaches 90% (integer cross-multiply; the
        # probe floor keeps one lucky hit from declaring warmth).
        self._warm_floor = warmup_min_probes
        self._opened = _monotonic()
        self._probes_since_open = 0
        self._hits_since_open = 0
        self.warmup_s: Optional[object] = None
        self._m_hit_hot = _S_HITS.labels(tier="hot")
        self._m_hit_cold = _S_HITS.labels(tier="cold")
        self._set_tier_gauges()

    # -- persistence ---------------------------------------------------

    def _load_salt(self) -> bytes:
        """Load (or atomically create) the store's persisted salt —
        the property that makes persisted digests meaningful across
        restarts while keeping entries non-addressable offline."""
        path = os.path.join(self.store_dir, "salt")
        try:
            with open(path, "rb") as fh:
                salt = fh.read()
            if len(salt) == _KEY_LEN:
                return salt
        except FileNotFoundError:
            pass
        salt = os.urandom(_KEY_LEN)
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(salt)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        return salt

    def _replay(self) -> None:
        """Warm the disk-tier index from the shard logs. A shard whose
        replay faults (`sigstore.load` site, or real I/O failure) starts
        cold — contained and counted, never propagated: a cache that
        cannot load is an empty cache, not a broken verifier."""
        for i, log in enumerate(self._logs):
            try:
                _faults.maybe_raise("sigstore.load")
                applied, skipped = log.replay_into(self._cold[i])
            except (OSError, _faults.InjectedFault):
                self._cold[i].clear()
                _S_REPLAY_SKIP.inc(reason="load_error")
                self.replay_skipped += 1
                continue
            self._records[i] = applied
            self.replay_applied += applied
            self.replay_skipped += skipped
            if applied:
                _S_REPLAY.inc(applied)
        self._entries = sum(len(c) for c in self._cold)
        self.insertions = self._entries  # replayed entries count as inserted

    def _shard_of(self, k: bytes) -> int:
        return k[0] % self._shards

    def _append(self, shard_i: int, op: bytes, key: bytes) -> None:
        """Fault-guarded log append: a failure (injected or real) costs
        persistence of this one record, never the in-RAM verdict path."""
        try:
            _faults.maybe_raise("sigstore.append")
            self._logs[shard_i].append(op, key)
        except FileNotFoundError:
            # The shard's backing directory vanished: ownership moved
            # away under the cell's handoff. Restart the shard cold —
            # reads miss and recompute (fail-closed), nothing raises
            # into the verify path.
            self._shard_moved_locked(shard_i)
            return
        except (OSError, _faults.InjectedFault):
            _S_APPEND_ERRORS.inc()
            return
        self._records[shard_i] += 1
        _S_APPENDS.inc()
        live = len(self._cold[shard_i])
        if self._records[shard_i] > 2 * live + _COMPACT_SLACK:
            try:
                self._logs[shard_i].compact(self._cold[shard_i])
            except FileNotFoundError:
                self._shard_moved_locked(shard_i)
                return
            except OSError:
                _S_APPEND_ERRORS.inc()
                return
            self._records[shard_i] = live
            _S_COMPACTIONS.inc()

    def _shard_moved_locked(self, shard_i: int) -> None:
        """Treat one shard as moved-away: drop its entries from both
        tiers (it must not keep answering hits for keys whose records
        now live elsewhere), close the stale handle, count it."""
        _S_SHARD_MOVED.inc()
        self._logs[shard_i].close()
        gone = self._cold[shard_i]
        self._cold[shard_i] = {}
        self._entries -= len(gone)
        self._records[shard_i] = 0
        for k in gone:
            self._set.pop(k, None)
        self._set_tier_gauges()

    def _set_tier_gauges(self) -> None:
        _S_TIER.set(len(self._set), tier="hot")
        _S_TIER.set(self._entries, tier="cold")

    # -- cache contract ------------------------------------------------

    def contains_key(self, k: bytes, erase: bool = False) -> bool:
        poisoned = _faults.poison_hit(self._poison_site)
        with self._lock:
            tier = None
            if k in self._set:
                tier = "hot"
                if not erase:
                    self._set.move_to_end(k)
            elif k in self._cold[self._shard_of(k)]:
                tier = "cold"
                if not erase:  # promote: recency now lives in the hot LRU
                    self._set[k] = None
                    while len(self._set) > self._max:
                        self._set.popitem(last=False)
            present = tier is not None
            hit = present or poisoned
            if present and erase:
                self._evict_locked(k)
                self.erases += 1
            if hit:
                self.hits += 1
            else:
                self.misses += 1
            self._probes_since_open += 1
            if hit:
                self._hits_since_open += 1
            warm = (
                self.warmup_s is None
                and self._probes_since_open >= self._warm_floor
                and 10 * self._hits_since_open
                >= 9 * self._probes_since_open
            )
            if warm:
                self.warmup_s = _monotonic() - self._opened
            if present and erase:
                self._append(self._shard_of(k), _OP_DEL, k)
            self._set_tier_gauges()
        # Registry updates outside the store lock, like the base class.
        self._m_lookups.inc()
        if hit:
            self._m_hits.inc()
            if tier == "cold":
                self._m_hit_cold.inc()
            elif tier == "hot":
                self._m_hit_hot.inc()
            if present and erase:
                self._m_erases.inc()
                self._m_entries.set(self._entries)
        else:
            self._m_misses.inc()
            _S_MISSES.inc()
        if warm:
            _S_WARMUP.set(self.warmup_s)
        return hit

    def add_key(self, k: bytes) -> None:
        with self._lock:
            shard_i = self._shard_of(k)
            shard = self._cold[shard_i]
            new = k not in shard
            self._set[k] = None
            self._set.move_to_end(k)
            while len(self._set) > self._max:
                # Hot-tier overflow only demotes recency: the key stays
                # in the disk tier, so this is NOT an entry eviction.
                self._set.popitem(last=False)
            if new:
                shard[k] = None
                self.insertions += 1
                self._entries += 1
                self._append(shard_i, _OP_ADD, k)
            self._set_tier_gauges()
        if new:
            self._m_inserts.inc()
        self._m_entries.set(self._entries)

    def discard_key(self, k: bytes) -> None:
        """Drop a proven-wrong entry from BOTH tiers and tombstone it on
        disk — the audit-mode containment path (resilience/guards.py):
        a poisoned persisted entry must stay evicted across restarts."""
        with self._lock:
            present = self._evict_locked(k)
            if present:
                self.erases += 1
                self._append(self._shard_of(k), _OP_DEL, k)
            self._set_tier_gauges()
        if present:
            self._m_erases.inc()
            self._m_entries.set(self._entries)

    def peek_key(self, k: bytes) -> bool:
        """Presence check with NO side effects: no probe/hit accounting,
        no LRU promotion, no metrics. For measurement surfaces (the cell
        control channel's tombstone audit) that must not pollute the
        warm-rate statistics they are trying to read."""
        with self._lock:
            return k in self._set or k in self._cold[self._shard_of(k)]

    def _evict_locked(self, k: bytes) -> bool:
        """Remove `k` from both in-RAM tiers; True when it was present."""
        self._set.pop(k, None)
        shard = self._cold[self._shard_of(k)]
        if k in shard:
            del shard[k]
            self._entries -= 1
            return True
        return False

    def __len__(self) -> int:
        # The store's size is the disk tier (hot is a subset view); the
        # batch driver's cold-cache shortcut keys off this.
        return self._entries

    # -- lifecycle -----------------------------------------------------

    def flush(self) -> None:
        """fsync every shard log (tests / checkpoint barriers)."""
        with self._lock:
            for i, log in enumerate(self._logs):
                if log._fh is None:
                    continue
                try:
                    log._fh.flush()
                    os.fsync(log._fh.fileno())
                except FileNotFoundError:
                    self._shard_moved_locked(i)
                except OSError:
                    _S_APPEND_ERRORS.inc()

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            for log in self._logs:
                log.close()

    def __enter__(self) -> "PersistentSigCache":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.close()


def sig_store_from_env(**kw) -> Optional[PersistentSigCache]:
    """Open the persistent store named by
    ``BITCOINCONSENSUS_TPU_SIGSTORE_DIR``; None when unset (callers fall
    back to the in-RAM `SigCache`)."""
    store_dir = os.environ.get("BITCOINCONSENSUS_TPU_SIGSTORE_DIR", "")
    if not store_dir:
        return None
    return PersistentSigCache(store_dir, **kw)
