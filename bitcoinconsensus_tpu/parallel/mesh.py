"""Multi-chip scaling: batch sharding over a device mesh + XLA collectives.

The reference has no distributed backend at all — its parallel axis is a
thread pool draining per-input checks (`checkqueue.h:29-163`). The TPU-native
equivalent (SURVEY §2.2) shards the *signature-check batch* across chips:

- a 1-D ``Mesh`` over a ``batch`` axis (data parallelism is the only axis
  with meaning here: lanes are independent; there is no gradient/activation
  traffic analogue),
- ``jax.jit`` with ``NamedSharding`` in/out specs so XLA partitions the
  verify kernel SPMD across the mesh (collective-free: embarrassingly
  parallel compute),
- a ``shard_map`` reduction step that AND-reduces per-lane verdicts into a
  block-level verdict with ``psum`` over ICI — the analogue of
  `CCheckQueueControl::Wait()`'s all-inputs-valid barrier
  (`checkqueue.h:139-142,188-195`).

Where `CCheckQueueControl::Wait()` assumes every worker answers, a mesh
must not: this module gives every device shard its own **fault domain**.
Each shard reserves the *last* lane of its slice for a rotating
known-answer sentinel, the sharded step returns a per-shard verdict
checksum pair (lane count + mod-251 position-weighted sum, computed
inside `shard_map` and recomputed host-side at settle), and the settle
seam validates shards *independently*: a flip on chip 3 is localized to
chip 3, whose lanes alone re-dispatch (surviving mesh → single-device
XLA → host-exact) while the other seven shards' verdicts stand. A
persistently sick device is *evicted* — the mesh is rebuilt and the
sharded step re-jitted over the survivors (`ShardLadder` in
`resilience/degrade.py`) — and later re-probed with a known-answer batch
for re-promotion. Per-shard stragglers have their own deadline
(`BITCOINCONSENSUS_TPU_SHARD_DEADLINE_S`), distinct from the whole-ticket
deadline of the in-flight queue.

Multi-host: the same mesh spec over `jax.devices()` spanning hosts rides
ICI/DCN transparently through pjit — no NCCL/MPI translation layer exists or
is needed.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

try:  # jax >= 0.4.35: the supported spelling
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map
# The varying-axes checker kwarg was renamed check_rep -> check_vma; key on
# the actual signature, not the import location (mid-range jax exposes
# jax.shard_map but still spells it check_rep).
import inspect as _inspect

_SHARD_MAP_KW = (
    {"check_vma": False}
    if "check_vma" in _inspect.signature(shard_map).parameters
    else {"check_rep": False}
)
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..crypto import jax_backend as _jb
from ..crypto.jax_backend import (
    SigCheck,
    TpuSecpVerifier,
    _verdict_checksum,
    _verify_kernel,
)
from ..obs import counter as _obs_counter
from ..obs import gauge as _obs_gauge
from ..obs import histogram as _obs_histogram
from ..obs import monotonic as _monotonic
from ..obs import span as _obs_span
from ..resilience import degrade as _degrade
from ..resilience import faults as _faults
from ..resilience import guards as _guards
from ..ops.regions import region_scope
from ..resilience.inflight import settle_array

__all__ = ["make_mesh", "ShardedSecpVerifier", "make_sharded_step"]

# Mesh telemetry — host-side driver accounting only; `local_step` below is
# traced and must stay instrumentation-free.
_MESH_DEVICES = _obs_gauge(
    "consensus_mesh_devices", "devices in the sharded verifier's mesh"
)
_MESH_DISPATCH = _obs_counter(
    "consensus_mesh_dispatch_total", "sharded (multi-chip) dispatches"
)
_MESH_SHARD_LANES = _obs_histogram(
    "consensus_mesh_shard_lanes",
    "live (real, non-sentinel/pad) lanes per device shard per dispatch",
    buckets=(8, 64, 512, 4096, 32768),
)
_MESH_SHARD_FAILURES = _obs_counter(
    "consensus_mesh_shard_failures_total",
    "per-shard settle failures (guard anomaly, checksum mismatch, "
    "straggler deadline, device loss), by device and reason",
    ("device", "reason"),
)
_MESH_EVICTIONS = _obs_counter(
    "consensus_mesh_evictions_total",
    "devices evicted from the mesh after repeated shard failures",
    ("device",),
)
_MESH_REPROMOTIONS = _obs_counter(
    "consensus_mesh_repromotions_total",
    "evicted devices re-promoted into the mesh after a clean probe",
    ("device",),
)
_MESH_REDISPATCH_LANES = _obs_counter(
    "consensus_mesh_redispatch_lanes_total",
    "lanes re-dispatched after their shard failed settle, by the level "
    "that answered (mesh = surviving shards, xla = single device, "
    "host = exact oracle)",
    ("level",),
)


def make_mesh(
    n_devices: Optional[int] = None,
    axis: str = "batch",
    devices: Optional[Sequence] = None,
) -> Mesh:
    """1-D device mesh over the batch axis.

    Pass `devices` to build over an explicit device list (the elastic
    verifier rebuilds over eviction survivors this way). Asking for more
    devices than the platform has is an error, not a silent truncation —
    a deployment that believes it runs 8-wide must not quietly run 1-wide.
    """
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if n_devices > len(devices):
                raise ValueError(
                    f"make_mesh: requested {n_devices} devices but only "
                    f"{len(devices)} are available "
                    f"(platform {devices[0].platform})"
                )
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis,))


def _pick_backend(use_pallas: bool):
    """Per-shard kernel: the SAME backend selection as
    TpuSecpVerifier._run_kernel, applied to the shard-local batch (so a
    multi-chip deployment dispatches the Pallas production kernel on each
    chip; CPU meshes and tile-indivisible shards fall back to XLA)."""

    def local_kernel(fields, want_odd, parity_req, has_t2, neg1, neg2, valid):
        if use_pallas:
            from ..ops.pallas_kernel import LANE_TILE, verify_tiles

            # Shard-local shapes are static at trace time inside shard_map.
            if fields.shape[0] % LANE_TILE == 0:
                return verify_tiles(
                    fields, want_odd, parity_req, has_t2, neg1, neg2, valid
                )
        ok = _verify_kernel(
            fields, want_odd, parity_req, has_t2, neg1, neg2, valid
        )
        return ok, jnp.zeros_like(ok)  # complete-add kernel: no deferrals

    return local_kernel


def make_sharded_step(mesh: Mesh, use_pallas: Optional[bool] = None):
    """The full multichip verify step, jitted over `mesh`.

    Returns ``step(fields, want_odd, parity_req, has_t2, neg1, neg2,
    valid, live) -> (per_lane, needs_host, all_ok, counts, wsums)`` where
    inputs are batch-sharded, `per_lane`/`needs_host` come back
    batch-sharded, `all_ok` is a replicated scalar produced by a psum
    AND-reduction inside shard_map (the cross-chip collective — the
    `CCheckQueueControl::Wait` analogue, checkqueue.h:139-142), and
    `counts`/`wsums` are length-``n_devices`` arrays carrying each
    shard's verdict checksum pair, computed on-device over the
    shard-local verdict slice (`jax_backend._verdict_checksum`, so the
    interval prover's coverage rides along). The settle seam recomputes
    both sums host-side per shard; a mismatch convicts exactly that
    shard. `live` marks real lanes: padding added to reach the batch
    shape is not counted as a failure, while structurally-invalid real
    lanes are. `needs_host` lanes (exceptional group-law deferrals of the
    pallas fast adds) are excluded from the device verdict — the host
    resolves them exactly and adjusts. Each shard runs the production
    backend selection (Pallas on TPU when the local tile divides; XLA
    otherwise).
    """
    axis = mesh.axis_names[0]
    fields_sharding = NamedSharding(mesh, P(axis, None, None))
    flat_sharding = NamedSharding(mesh, P(axis))
    replicated = NamedSharding(mesh, P())
    if use_pallas is None:
        use_pallas = all(d.platform == "tpu" for d in mesh.devices.flat)
    local_kernel = _pick_backend(use_pallas)

    def local_step(fields, want_odd, parity_req, has_t2, neg1, neg2, valid, live):
        # region scope only — metadata for device-time attribution
        # (obs/xprof); the traced program is unchanged.
        with region_scope("shard_step"):
            per_lane, needs = local_kernel(
                fields, want_odd, parity_req, has_t2, neg1, neg2, valid
            )
            # all-valid <=> no live lane DEFINITELY failed, on any shard
            # (deferred lanes stay out; the host fixup ANDs their
            # verdicts in).
            failures = jnp.sum(jnp.where(live & ~per_lane & ~needs, 1, 0))
            cnt, wsum = _verdict_checksum(per_lane)
            return (
                per_lane,
                needs,
                jax.lax.psum(failures, axis) == 0,
                jnp.reshape(cnt, (1,)),
                jnp.reshape(wsum, (1,)),
            )

    # Varying-axes checking is off: the verify kernel's scan carries start
    # as mesh-wide constants (infinity masks, G-table selects) and become
    # shard-varying inside the loop — correct SPMD, but the strict
    # varying-axes tracker rejects the carry-type mismatch.
    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(axis, None, None),) + (P(axis),) * 7,
        out_specs=(P(axis), P(axis), P(), P(axis), P(axis)),
        **_SHARD_MAP_KW,
    )
    return jax.jit(
        sharded,
        in_shardings=(fields_sharding,) + (flat_sharding,) * 7,
        out_shardings=(
            flat_sharding, flat_sharding, replicated,
            flat_sharding, flat_sharding,
        ),
    )


def _shard_positions(n: int, shard_size: int) -> np.ndarray:
    """Global row index of real lane `i` under the scatter layout.

    Each shard of `shard_size` rows holds `shard_size - 1` real lanes
    followed by its reserved sentinel row, so lane i lands at
    ``(i // (S-1)) * S + (i % (S-1))``.
    """
    cap = shard_size - 1
    idx = np.arange(n, dtype=np.int64)
    return (idx // cap) * shard_size + (idx % cap)


class _ShardLayout:
    """Settle context of one scattered mesh dispatch (rides ticket.sset).

    `positions` maps real-lane order to global rows; `ssets` holds one
    single-lane SentinelSet per shard (local position S-1) for per-shard
    checking, and `flat_sset` the same sentinels as one global set for
    the quarantined single-device fallback path. `epoch` pins the mesh
    generation the layout was built for: after an eviction rebuilds the
    mesh, stale layouts are no longer shard-aligned and relaunch on the
    single-device rung instead. `deadline_armed` is False for
    first-compile shapes, so the per-shard straggler deadline never fires
    on XLA compilation time.
    """

    __slots__ = (
        "n", "padded", "n_shards", "shard_size", "positions", "ssets",
        "flat_sset", "epoch", "deadline_armed",
    )

    def __init__(self, n, padded, n_shards, shard_size, positions, ssets,
                 flat_sset, epoch, deadline_armed):
        self.n = n
        self.padded = padded
        self.n_shards = n_shards
        self.shard_size = shard_size
        self.positions = positions
        self.ssets = ssets
        self.flat_sset = flat_sset
        self.epoch = epoch
        self.deadline_armed = deadline_armed


# Pad row values per packed array (mirrors _pack_lanes): fields 0,
# want_odd 0, parity -1 (don't-care), has_t2/neg1/neg2 0, valid False.
_PAD_VALUES = (0, 0, -1, 0, 0, 0, 0)


class ShardedSecpVerifier(TpuSecpVerifier):
    """Drop-in TpuSecpVerifier that spreads each dispatch over a mesh,
    with per-device fault domains: per-shard sentinels + checksums at
    settle, shard-granular re-dispatch, and elastic device eviction."""

    def __init__(self, mesh: Optional[Mesh] = None, min_batch: int = 8,
                 chunk: int = 1 << 13, evict_after: Optional[int] = None):
        super().__init__(min_batch=min_batch, chunk=chunk)
        mesh = mesh if mesh is not None else make_mesh()
        self._axis = mesh.axis_names[0]
        self._all_devices = list(mesh.devices.flat)
        self._base_min_batch = min_batch
        self._mesh_epoch = 0
        self._shard_ladder = _degrade.ShardLadder(
            [str(d.id) for d in self._all_devices], evict_after=evict_after
        )
        self._shard_deadline_s = float(os.environ.get(
            "BITCOINCONSENSUS_TPU_SHARD_DEADLINE_S", "4.0"
        ))
        self._verdict_acc = True
        self._dispatched = 0
        self._install_mesh(mesh)

    _SITE = "mesh"

    def _install_mesh(self, mesh: Mesh) -> None:
        """(Re)build the sharded step over `mesh`; logs the effective
        mesh size via obs (gauge + a traced `mesh.build` span) — also the
        eviction/re-promotion rebuild path, where re-jitting over the
        survivors is the dominant cost and worth a span of its own."""
        n = int(mesh.devices.size)
        self.mesh = mesh
        self._shard_device_ids = [str(d.id) for d in mesh.devices.flat]
        # Batch sizes must divide evenly across the mesh: round min_batch
        # up to a multiple of n (doubling in _pad preserves divisibility).
        self._min_batch = -(-self._base_min_batch // n) * n
        tpu_mesh = all(d.platform == "tpu" for d in mesh.devices.flat)
        with _obs_span("mesh.build", devices=n, epoch=self._mesh_epoch):
            self._step = make_sharded_step(
                mesh, use_pallas=self._use_pallas and tpu_mesh
            )
        _MESH_DEVICES.set(n)

    def _ladder_levels(self):
        # Quarantined mesh dispatch falls back to the single-device base
        # kernel before host: a sick collective/device drop does not force
        # host EC math while one chip still answers correctly.
        return ("mesh", "xla", _degrade.HOST_LEVEL)

    # --- layout ---------------------------------------------------------

    def _pad(self, n: int) -> int:
        # Reserve one sentinel lane PER SHARD (not one per dispatch): the
        # padded size must fit n real lanes plus n_devices sentinels, and
        # every shard must be >= 2 rows so its sentinel never crowds out
        # real work. min_batch is a multiple of n_devices, so doubling
        # preserves divisibility.
        d = int(self.mesh.devices.size)
        size = self._min_batch
        while size < n + d or size // max(d, 1) < 2:
            size *= 2
        return size

    @property
    def lane_capacity(self) -> int:
        """Real lanes per chunk dispatch: one short PER SHARD of `chunk`,
        so the per-shard sentinel rows never push a full chunk up a pad
        rung."""
        return self._chunk - int(self.mesh.devices.size)

    def _blank_args(self, like, padded: int):
        """Fresh all-pad packed buffers shaped like `like` at `padded`."""
        out = []
        for a, pv in zip(like, _PAD_VALUES):
            buf = np.zeros((padded,) + a.shape[1:], dtype=a.dtype)
            if pv:
                buf[...] = pv
            out.append(buf)
        return tuple(out)

    def _build_layout(self, args, n: int) -> Optional[_ShardLayout]:
        """Scatter real lanes across shards in place + install per-shard
        sentinels; None when the buffer cannot carry the layout (caller
        falls back to the contiguous single-sentinel prep)."""
        d = int(self.mesh.devices.size)
        padded = int(args[0].shape[0])
        if d < 2 or padded % d or padded < n + d:
            return None
        shard = padded // d
        if shard < 2 or n > d * (shard - 1):
            return None
        positions = _shard_positions(n, shard)
        for a, pv in zip(args, _PAD_VALUES):
            real = a[:n].copy()
            a[...] = pv
            a[positions] = real
        sent_rows = [s * shard + shard - 1 for s in range(d)]
        flat = _guards.install_sentinels_at(args, sent_rows)
        if flat is None:
            return None
        ssets = [
            _guards.SentinelSet([shard - 1], [bool(flat.expected[s])])
            for s in range(d)
        ]
        return _ShardLayout(
            n, padded, d, shard, positions, ssets, flat,
            self._mesh_epoch, padded in self._seen_shapes,
        )

    def _prepare_ticket(self, args, n: int):
        """Dispatch-time prep (inflight queue callback): copy read-only
        buffers, then lay the batch out shard-major with one rotating
        known-answer sentinel per device shard. Falls back to the base
        contiguous sentinel prep when the batch cannot shard."""
        args, _copied = _guards.ensure_writable(args)
        layout = self._build_layout(args, n)
        if layout is None:
            return args, _guards.install_sentinels(args, n)
        return args, layout

    # --- launch ---------------------------------------------------------

    def _launch_ticket(self, args, n: int, level: str, sset=None):
        """Launch one chunk (inflight queue callback). Mesh-level launches
        need a current-epoch shard layout; anything else (quarantined
        rung, stale layout after an eviction rebuild, unshardable batch)
        runs the single-device base dispatch, whose settle is guarded by
        the flat sentinel set + global checksum."""
        layout = sset if isinstance(sset, _ShardLayout) else None
        if (
            level != "mesh"
            or layout is None
            or layout.epoch != self._mesh_epoch
        ):
            if level == "mesh":
                level = "xla"
            return TpuSecpVerifier._launch_ticket(self, args, n, level, sset)
        _faults.maybe_raise("mesh.dispatch")
        live = np.zeros(layout.padded, dtype=bool)
        live[layout.positions] = True  # sentinel/pad lanes stay out of psum
        self._note_dispatch(layout.padded, n, "mesh")
        _MESH_DISPATCH.inc()
        cap = layout.shard_size - 1
        for s in range(layout.n_shards):
            _MESH_SHARD_LANES.observe(min(max(n - s * cap, 0), cap))
        # Per-shard checksums ride inside the 5-tuple result; no extra aux.
        return self._step(*args, live), None

    # --- settle ---------------------------------------------------------

    def _materialize_guarded(self, ticket):
        result = ticket.result
        layout = ticket.sset if isinstance(ticket.sset, _ShardLayout) else None
        if layout is None:
            # Contiguous prep (unshardable batch): base settle seam.
            return TpuSecpVerifier._materialize_guarded(self, ticket)
        if not (isinstance(result, tuple) and len(result) == 5):
            return self._materialize_flat(ticket, layout)
        return self._materialize_sharded(ticket, layout)

    def _materialize_flat(self, ticket, layout: _ShardLayout):
        """Settle a scattered buffer answered by the single-device rung:
        whole-buffer guards (flat sentinels + global checksum), then
        gather real lanes back to caller order."""
        result = ticket.result
        needs_raw = None
        if isinstance(result, tuple):
            ok_raw, needs_raw = result[0], result[1]
        else:
            ok_raw = result
        ok_np = _faults.corrupt_verdict(
            "jax_backend.verdict", settle_array(ok_raw)
        )
        ok = _guards.validate_verdict(ok_np, layout.padded, self._SITE)
        needs = None
        if needs_raw is not None:
            needs = _guards.validate_verdict(
                settle_array(needs_raw), layout.padded, self._SITE
            )
        _guards.check_sentinels(layout.flat_sset, ok, needs, self._SITE)
        if ticket.aux is not None:
            dev_sums = (int(settle_array(ticket.aux[0])),
                        int(settle_array(ticket.aux[1])))
            _guards.check_checksum(dev_sums, ok, self._SITE)
        ok_r = ok[layout.positions]
        needs_r = None if needs is None else needs[layout.positions]
        return ok_r, needs_r, None

    def _materialize_sharded(self, ticket, layout: _ShardLayout):
        """The per-shard settle seam: validate every device shard
        independently (structural guards, per-shard checksum FIRST — the
        single-flip detector — then the shard's sentinel), feed per-device
        health, and re-dispatch only the failed shards' lanes."""
        per_lane, needs, all_ok, cnts, wsums = ticket.result
        ok_np = settle_array(per_lane)
        needs_np = settle_array(needs)
        cnts_np = settle_array(cnts)
        wsums_np = settle_array(wsums)
        if (
            ok_np.ndim != 1
            or ok_np.shape[0] != layout.padded
            or needs_np.shape != ok_np.shape
            or cnts_np.shape[0] != layout.n_shards
            or wsums_np.shape[0] != layout.n_shards
        ):
            _guards.GUARD_ANOMALIES.inc(site=self._SITE, reason="shape")
            raise _guards.VerdictAnomaly(
                self._SITE, "shape",
                f"got {ok_np.shape}/{cnts_np.shape}, "
                f"want ({layout.padded},)/({layout.n_shards},)",
            )
        elapsed = _monotonic() - ticket.born
        ok_v, needs_v, bad = self._check_shards(
            ok_np, needs_np, cnts_np, wsums_np, layout, elapsed,
            timeline=ticket.timeline,
        )
        # Per-device health feeds the eviction ladder at the PRIMARY
        # settle only (re-dispatch retries must not double-convict).
        # Evictions apply after the loop: each one rebuilds the mesh and
        # shrinks _shard_device_ids, which this loop still indexes by the
        # layout's (pre-eviction) shard count.
        devs = list(self._shard_device_ids)
        to_evict = []
        for s in range(layout.n_shards):
            dev = devs[s]
            if s in bad:
                _MESH_SHARD_FAILURES.inc(device=dev, reason=bad[s])
            if self._shard_ladder.report_shard(dev, s not in bad):
                to_evict.append(dev)
        for dev in to_evict:
            self._evict_device(dev)
        if len(bad) == layout.n_shards:
            # Nothing survived: whole-mesh fault — let the ticket's
            # retry/ladder policy decide (same as the pre-shard-domain
            # behavior).
            raise _guards.VerdictAnomaly(
                self._SITE, "all-shards", ",".join(sorted(set(bad.values())))
            )
        if not bad:
            probe_dev = self._shard_ladder.note_clean_dispatch()
            if probe_dev is not None:
                self._probe_evicted(probe_dev)
            return (
                ok_v[layout.positions],
                needs_v[layout.positions],
                bool(settle_array(all_ok)),
            )
        # Partial settlement: keep the good shards' verdicts, re-dispatch
        # only the failed shards' real lanes. all_ok=None tells the
        # verdict accounting to recompute from the assembled lanes (the
        # psum scalar saw the faulted shards).
        cap = layout.shard_size - 1
        lane_shard = np.arange(layout.n, dtype=np.int64) // cap
        bad_keys = np.fromiter(bad.keys(), dtype=np.int64, count=len(bad))
        bad_mask = np.isin(lane_shard, bad_keys)
        ok_r = np.zeros(layout.n, dtype=bool)
        needs_r = np.zeros(layout.n, dtype=bool)
        good = ~bad_mask
        ok_r[good] = ok_v[layout.positions[good]]
        needs_r[good] = needs_v[layout.positions[good]]
        k = int(bad_mask.sum())
        if k:
            rows = layout.positions[bad_mask]
            sub = tuple(a[rows] for a in ticket.args)
            ok_b, needs_b = self._redispatch_lanes(sub, k)
            ok_r[bad_mask] = ok_b
            needs_r[bad_mask] = needs_b
        return ok_r, needs_r, None

    def _check_shards(self, ok_np, needs_np, cnts_np, wsums_np,
                      layout: _ShardLayout, elapsed: float,
                      timeline=None):
        """Validate each shard's verdict slice independently.

        Returns `(ok, needs, bad)` where ok/needs are padded bool buffers
        holding the surviving shards' validated slices and `bad` maps
        shard index -> failure reason. Check order is deliberate:
        structural validation, then the per-shard checksum (so a
        single-lane flip always convicts as "checksum" — the chaos
        sweep's hard criterion), then the shard's rotating sentinel.
        `timeline` (the settling ticket's PhaseTimeline, when present)
        receives one stamp per shard so the perf observatory can
        attribute settle time shard-by-shard.
        """
        shard = layout.shard_size
        ok_v = np.zeros(layout.padded, dtype=bool)
        needs_v = np.zeros(layout.padded, dtype=bool)
        bad = {}
        for s in range(layout.n_shards):
            site = f"mesh.shard.{s}"
            sl = slice(s * shard, (s + 1) * shard)
            try:
                _faults.maybe_raise(site)
                delay = _faults.shard_delay(site)
                # Convict on per-SHARD lag only (today the harness's
                # simulated delay; device completion events on real
                # hardware). Whole-dispatch slowness — compile stalls, a
                # loaded host — is the in-flight ticket deadline's job:
                # folding it in here would convict all shards at once on
                # a slow machine with no fault present.
                if (
                    layout.deadline_armed
                    and delay > 0.0
                    and elapsed + delay > self._shard_deadline_s
                ):
                    _guards.GUARD_ANOMALIES.inc(site=site, reason="deadline")
                    bad[s] = "deadline"
                    continue
                ok_s = _guards.validate_verdict(
                    _faults.corrupt_verdict(site, ok_np[sl]), shard, site
                )
                needs_s = _guards.validate_verdict(needs_np[sl], shard, site)
                _guards.check_checksum(
                    (int(cnts_np[s]), int(wsums_np[s])), ok_s, site
                )
                layout.ssets[s].check(ok_s, needs_s, site)
            except _guards.VerdictAnomaly as exc:
                bad[s] = exc.reason
            except _faults.InjectedDeviceLoss:
                bad[s] = "device-loss"
            except _faults.InjectedTimeout:
                bad[s] = "timeout"
            except Exception:
                bad[s] = "dispatch"
            else:
                ok_v[sl] = ok_s
                needs_v[sl] = needs_s
            finally:
                # Completion stamp: consecutive deltas (from settle_start)
                # are this shard's check duration.
                if timeline is not None:
                    timeline.stamp_shard(s)
        return ok_v, needs_v, bad

    # --- shard re-dispatch ---------------------------------------------

    def _redispatch_lanes(self, sub, k: int):
        """Re-answer `k` lanes whose shard failed settle: surviving mesh
        first, then the single-device XLA rung, then fail closed to the
        host oracle (lanes come back needs_host=True, so the settle layer
        resolves them exactly — a shard fault never yields an ACCEPT)."""
        for target in ("mesh", "xla"):
            try:
                if target == "mesh":
                    out = self._redispatch_mesh(sub, k)
                else:
                    out = self._redispatch_xla(sub, k)
            except Exception:
                out = None
            if out is not None:
                _MESH_REDISPATCH_LANES.inc(k, level=target)
                return out
        _MESH_REDISPATCH_LANES.inc(k, level="host")
        _guards.CONTAINED.inc(site=self._SITE)
        _guards.HOST_EXACT_LANES.inc(k)
        return np.zeros(k, dtype=bool), np.ones(k, dtype=bool)

    def _redispatch_mesh(self, sub, k: int):
        """One synchronous dispatch of the failed lanes over the current
        (possibly rebuilt) mesh, re-guarded shard-by-shard; None when the
        mesh cannot answer cleanly (caller falls to the next rung)."""
        args = self._blank_args(sub, self._pad(k))
        for a, r in zip(args, sub):
            a[:k] = r
        layout = self._build_layout(args, k)
        if layout is None:
            return None
        live = np.zeros(layout.padded, dtype=bool)
        live[layout.positions] = True
        self._note_dispatch(layout.padded, k, "mesh")
        _MESH_DISPATCH.inc()
        per_lane, needs, _all_ok, cnts, wsums = self._step(*args, live)
        ok_v, needs_v, bad = self._check_shards(
            settle_array(per_lane), settle_array(needs),
            settle_array(cnts), settle_array(wsums), layout, 0.0,
        )
        if bad:
            return None
        return ok_v[layout.positions], needs_v[layout.positions]

    def _redispatch_xla(self, sub, k: int):
        """Single-device re-answer of the failed lanes, guarded by a
        fresh contiguous sentinel set + the global verdict checksum."""
        args = self._blank_args(sub, self._pad(k))
        for a, r in zip(args, sub):
            a[:k] = r
        sset = _guards.install_sentinels(args, k)
        padded = int(args[0].shape[0])
        result = self._run_level(args, k, "xla")
        ok_raw = result[0] if isinstance(result, tuple) else result
        aux = _jb._checksum_jit(ok_raw) if self._checksum else None
        ok = _guards.validate_verdict(
            settle_array(ok_raw), padded, self._SITE
        )
        _guards.check_sentinels(sset, ok, None, self._SITE)
        if aux is not None:
            dev_sums = (int(settle_array(aux[0])),
                        int(settle_array(aux[1])))
            _guards.check_checksum(dev_sums, ok, self._SITE)
        return ok[:k], np.zeros(k, dtype=bool)

    # --- elastic mesh: eviction + re-promotion -------------------------

    def _evict_device(self, dev_id: str) -> None:
        """Convict one device: shrink the mesh to the survivors and
        re-jit the sharded step. In-flight layouts from the old epoch
        settle on the single-device rung (epoch check at relaunch)."""
        self._shard_ladder.evict(dev_id)
        _MESH_EVICTIONS.inc(device=dev_id)
        self._rebuild_mesh()

    def _rebuild_mesh(self) -> None:
        healthy = set(self._shard_ladder.healthy())
        devs = [d for d in self._all_devices if str(d.id) in healthy]
        self._mesh_epoch += 1
        self._install_mesh(make_mesh(axis=self._axis, devices=devs))

    def _probe_evicted(self, dev_id: str) -> None:
        """Known-answer re-promotion probe for an evicted device; a clean
        probe re-admits it (and re-jits the step over the grown mesh), a
        failed one leaves it quarantined for the next nomination."""
        try:
            ok = self._probe_device(dev_id)
        except Exception:
            ok = False
        if ok:
            self._shard_ladder.repromote(dev_id)
            _MESH_REPROMOTIONS.inc(device=dev_id)
            self._rebuild_mesh()

    def _probe_device(self, dev_id: str) -> bool:
        """Run an all-sentinel batch pinned to `dev_id`; True iff every
        known answer comes back right (the mesh analogue of the rung
        ladder's re-promotion probe — same idea, device-targeted)."""
        _faults.maybe_raise("mesh.probe")
        dev = next(
            (d for d in self._all_devices if str(d.id) == dev_id), None
        )
        if dev is None:
            return False
        size = 8
        args = self._blank_args(
            (np.zeros((1, 4, 32), dtype=np.uint8),) + tuple(
                np.zeros(1, dtype=np.int32) for _ in range(5)
            ) + (np.zeros(1, dtype=bool),),
            size,
        )
        sset = _guards.install_sentinels_at(args, [0, 1, 2, 3], rotation=0)
        if sset is None:
            return False
        put = tuple(jax.device_put(a, dev) for a in args)
        ok = _guards.validate_verdict(
            settle_array(self._kernel(*put)), size, "mesh.probe"
        )
        try:
            sset.check(ok, None, "mesh.probe")
        except _guards.VerdictAnomaly:
            return False
        return True

    # --- verdict accounting --------------------------------------------

    def _note_device_verdict(self, all_ok, ok, needs, count: int) -> None:
        """AND a settled chunk into the block verdict. `all_ok` is the
        psum collective's replicated scalar for fully-clean mesh
        dispatches; for partially-settled or quarantined (single-device)
        dispatches it is recomputed from the per-lane buffer with the
        same semantics (deferred lanes excluded — the host fixup ANDs
        their verdicts in via `_fixup_failed`). Accounting happens at
        settle, never dispatch, so retried or contained chunks cannot
        double-count."""
        if all_ok is None:
            lanes_ok = ok[:count]
            if needs is not None:
                lanes_ok = lanes_ok | needs[:count]
            all_ok = bool(np.all(lanes_ok))
        self._verdict_acc = self._verdict_acc and bool(all_ok)
        self._dispatched += count

    def _note_host_lanes(self, results: np.ndarray) -> None:
        self._verdict_acc = self._verdict_acc and bool(np.all(results))
        self._dispatched += len(results)

    def verify_checks_with_verdict(self, checks: Sequence[SigCheck]):
        """(per-check results, block-level all-ok).

        The all-ok verdict of device-dispatched lanes comes from the psum
        AND-reduction inside the sharded step (the collective barrier), not
        a host re-reduction; lanes rejected host-side before dispatch
        (structural parse failures) AND into the verdict via the dispatched
        count, and host-resolved exceptional deferrals AND in via
        `_fixup_failed`.
        """
        self._verdict_acc = True
        self._dispatched = 0
        self._fixup_failed = False
        try:
            res = self.verify_checks(checks)
            return res, (
                self._verdict_acc
                and self._dispatched == len(checks)
                and not self._fixup_failed
            )
        finally:
            # A raising verify_checks must not poison the NEXT verdict:
            # settle whatever is still in flight (those tickets' verdict
            # callbacks land in the accumulators being reset) and clear
            # the accounting either way.
            self._inflight.drain()
            self._verdict_acc = True
            self._dispatched = 0
            self._fixup_failed = False
