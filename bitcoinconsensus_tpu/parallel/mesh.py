"""Multi-chip scaling: batch sharding over a device mesh + XLA collectives.

The reference has no distributed backend at all — its parallel axis is a
thread pool draining per-input checks (`checkqueue.h:29-163`). The TPU-native
equivalent (SURVEY §2.2) shards the *signature-check batch* across chips:

- a 1-D ``Mesh`` over a ``batch`` axis (data parallelism is the only axis
  with meaning here: lanes are independent; there is no gradient/activation
  traffic analogue),
- ``jax.jit`` with ``NamedSharding`` in/out specs so XLA partitions the
  verify kernel SPMD across the mesh (collective-free: embarrassingly
  parallel compute),
- a ``shard_map`` reduction step that AND-reduces per-lane verdicts into a
  block-level verdict with ``psum`` over ICI — the analogue of
  `CCheckQueueControl::Wait()`'s all-inputs-valid barrier
  (`checkqueue.h:139-142,188-195`).

Multi-host: the same mesh spec over `jax.devices()` spanning hosts rides
ICI/DCN transparently through pjit — no NCCL/MPI translation layer exists or
is needed.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

try:  # jax >= 0.4.35: the supported spelling
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map
# The varying-axes checker kwarg was renamed check_rep -> check_vma; key on
# the actual signature, not the import location (mid-range jax exposes
# jax.shard_map but still spells it check_rep).
import inspect as _inspect

_SHARD_MAP_KW = (
    {"check_vma": False}
    if "check_vma" in _inspect.signature(shard_map).parameters
    else {"check_rep": False}
)
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..crypto.jax_backend import SigCheck, TpuSecpVerifier, _verify_kernel
from ..obs import counter as _obs_counter
from ..obs import gauge as _obs_gauge
from ..obs import histogram as _obs_histogram
from ..resilience import degrade as _degrade
from ..resilience import faults as _faults

__all__ = ["make_mesh", "ShardedSecpVerifier", "make_sharded_step"]

# Mesh telemetry — host-side driver accounting only; `local_step` below is
# traced and must stay instrumentation-free.
_MESH_DEVICES = _obs_gauge(
    "consensus_mesh_devices", "devices in the sharded verifier's mesh"
)
_MESH_DISPATCH = _obs_counter(
    "consensus_mesh_dispatch_total", "sharded (multi-chip) dispatches"
)
_MESH_SHARD_LANES = _obs_histogram(
    "consensus_mesh_shard_lanes",
    "per-device shard size (lanes) of each sharded dispatch",
    buckets=(8, 64, 512, 4096, 32768),
)


def make_mesh(n_devices: Optional[int] = None, axis: str = "batch") -> Mesh:
    """1-D device mesh over the batch axis."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def _pick_backend(use_pallas: bool):
    """Per-shard kernel: the SAME backend selection as
    TpuSecpVerifier._run_kernel, applied to the shard-local batch (so a
    multi-chip deployment dispatches the Pallas production kernel on each
    chip; CPU meshes and tile-indivisible shards fall back to XLA)."""

    def local_kernel(fields, want_odd, parity_req, has_t2, neg1, neg2, valid):
        if use_pallas:
            from ..ops.pallas_kernel import LANE_TILE, verify_tiles

            # Shard-local shapes are static at trace time inside shard_map.
            if fields.shape[0] % LANE_TILE == 0:
                return verify_tiles(
                    fields, want_odd, parity_req, has_t2, neg1, neg2, valid
                )
        ok = _verify_kernel(
            fields, want_odd, parity_req, has_t2, neg1, neg2, valid
        )
        return ok, jnp.zeros_like(ok)  # complete-add kernel: no deferrals

    return local_kernel


def make_sharded_step(mesh: Mesh, use_pallas: Optional[bool] = None):
    """The full multichip verify step, jitted over `mesh`.

    Returns ``step(fields, want_odd, parity_req, has_t2, neg1, neg2,
    valid, live) -> (per_lane, needs_host, all_ok)`` where inputs are
    batch-sharded, `per_lane`/`needs_host` come back batch-sharded, and
    `all_ok` is a replicated scalar produced by a psum AND-reduction inside
    shard_map (the cross-chip collective — the `CCheckQueueControl::Wait`
    analogue, checkqueue.h:139-142). `live` marks real lanes: padding added
    to reach the batch shape is not counted as a failure, while
    structurally-invalid real lanes are. `needs_host` lanes (exceptional
    group-law deferrals of the pallas fast adds) are excluded from the
    device verdict — the host resolves them exactly and adjusts. Each shard
    runs the production backend selection (Pallas on TPU when the local
    tile divides; XLA otherwise).
    """
    axis = mesh.axis_names[0]
    fields_sharding = NamedSharding(mesh, P(axis, None, None))
    flat_sharding = NamedSharding(mesh, P(axis))
    replicated = NamedSharding(mesh, P())
    if use_pallas is None:
        use_pallas = all(d.platform == "tpu" for d in mesh.devices.flat)
    local_kernel = _pick_backend(use_pallas)

    def local_step(fields, want_odd, parity_req, has_t2, neg1, neg2, valid, live):
        per_lane, needs = local_kernel(
            fields, want_odd, parity_req, has_t2, neg1, neg2, valid
        )
        # all-valid <=> no live lane DEFINITELY failed, on any shard
        # (deferred lanes stay out; the host fixup ANDs their verdicts in).
        failures = jnp.sum(jnp.where(live & ~per_lane & ~needs, 1, 0))
        return per_lane, needs, jax.lax.psum(failures, axis) == 0

    # Varying-axes checking is off: the verify kernel's scan carries start
    # as mesh-wide constants (infinity masks, G-table selects) and become
    # shard-varying inside the loop — correct SPMD, but the strict
    # varying-axes tracker rejects the carry-type mismatch.
    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(axis, None, None),) + (P(axis),) * 7,
        out_specs=(P(axis), P(axis), P()),
        **_SHARD_MAP_KW,
    )
    return jax.jit(
        sharded,
        in_shardings=(fields_sharding,) + (flat_sharding,) * 7,
        out_shardings=(flat_sharding, flat_sharding, replicated),
    )


class ShardedSecpVerifier(TpuSecpVerifier):
    """Drop-in TpuSecpVerifier that spreads each dispatch over a mesh."""

    def __init__(self, mesh: Optional[Mesh] = None, min_batch: int = 8,
                 chunk: int = 1 << 13):
        super().__init__(min_batch=min_batch, chunk=chunk)
        self.mesh = mesh if mesh is not None else make_mesh()
        n = self.mesh.devices.size
        # Batch sizes must divide evenly across the mesh: round min_batch up
        # to a multiple of n (doubling in _pad preserves divisibility).
        self._min_batch = -(-self._min_batch // n) * n
        tpu_mesh = all(d.platform == "tpu" for d in self.mesh.devices.flat)
        self._step = make_sharded_step(
            self.mesh, use_pallas=self._use_pallas and tpu_mesh
        )
        self._verdict_acc = True
        self._dispatched = 0
        _MESH_DEVICES.set(n)

    _SITE = "mesh"

    def _ladder_levels(self):
        # Quarantined mesh dispatch falls back to the single-device base
        # kernel before host: a sick collective/device drop does not force
        # host EC math while one chip still answers correctly.
        return ("mesh", "xla", _degrade.HOST_LEVEL)

    def _run_kernel(self, args, n: int):
        if self._dispatch_level == "xla":
            # Ladder-quarantined mesh rung: single-device base dispatch.
            return TpuSecpVerifier._run_kernel(self, args, n)
        _faults.maybe_raise("mesh.dispatch")
        padded = int(args[-1].shape[0])
        live = np.zeros(padded, dtype=bool)
        live[:n] = True  # sentinel/pad lanes stay out of the psum verdict
        self._note_dispatch(padded, n, "mesh")
        _MESH_DISPATCH.inc()
        _MESH_SHARD_LANES.observe(padded // self.mesh.devices.size)
        return self._step(*args, live)

    def _note_device_verdict(self, all_ok, ok, needs, count: int) -> None:
        """AND a settled chunk into the block verdict. `all_ok` is the
        psum collective's replicated scalar for mesh dispatches; for
        quarantined (single-device) dispatches it is recomputed from the
        per-lane buffer with the same semantics (deferred lanes excluded —
        the host fixup ANDs their verdicts in via `_fixup_failed`).
        Accounting happens at settle, never dispatch, so retried or
        contained chunks cannot double-count."""
        if all_ok is None:
            lanes_ok = ok[:count]
            if needs is not None:
                lanes_ok = lanes_ok | needs[:count]
            all_ok = bool(np.all(lanes_ok))
        self._verdict_acc = self._verdict_acc and bool(all_ok)
        self._dispatched += count

    def _note_host_lanes(self, results: np.ndarray) -> None:
        self._verdict_acc = self._verdict_acc and bool(np.all(results))
        self._dispatched += len(results)

    def verify_checks_with_verdict(self, checks: Sequence[SigCheck]):
        """(per-check results, block-level all-ok).

        The all-ok verdict of device-dispatched lanes comes from the psum
        AND-reduction inside the sharded step (the collective barrier), not
        a host re-reduction; lanes rejected host-side before dispatch
        (structural parse failures) AND into the verdict via the dispatched
        count, and host-resolved exceptional deferrals AND in via
        `_fixup_failed`.
        """
        self._verdict_acc = True
        self._dispatched = 0
        self._fixup_failed = False
        res = self.verify_checks(checks)
        verdict = (
            self._verdict_acc
            and self._dispatched == len(checks)
            and not self._fixup_failed
        )
        return res, verdict
