"""Multi-chip scaling: batch sharding over a device mesh + XLA collectives.

The reference has no distributed backend at all — its parallel axis is a
thread pool draining per-input checks (`checkqueue.h:29-163`). The TPU-native
equivalent (SURVEY §2.2) shards the *signature-check batch* across chips:

- a 1-D ``Mesh`` over a ``batch`` axis (data parallelism is the only axis
  with meaning here: lanes are independent; there is no gradient/activation
  traffic analogue),
- ``jax.jit`` with ``NamedSharding`` in/out specs so XLA partitions the
  verify kernel SPMD across the mesh (collective-free: embarrassingly
  parallel compute),
- a ``shard_map`` reduction step that AND-reduces per-lane verdicts into a
  block-level verdict with ``psum`` over ICI — the analogue of
  `CCheckQueueControl::Wait()`'s all-inputs-valid barrier
  (`checkqueue.h:139-142,188-195`).

Multi-host: the same mesh spec over `jax.devices()` spanning hosts rides
ICI/DCN transparently through pjit — no NCCL/MPI translation layer exists or
is needed.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..crypto.jax_backend import SigCheck, TpuSecpVerifier, _verify_kernel

__all__ = ["make_mesh", "ShardedSecpVerifier", "make_sharded_step"]


def make_mesh(n_devices: Optional[int] = None, axis: str = "batch") -> Mesh:
    """1-D device mesh over the batch axis."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def make_sharded_step(mesh: Mesh):
    """The full multichip verify step, jitted over `mesh`.

    Returns ``step(fields, want_odd, parity_req, has_t2, neg1, neg2,
    valid, live) -> (per_lane, all_ok)`` where inputs are batch-sharded,
    `per_lane`
    comes back batch-sharded, and `all_ok` is a replicated scalar produced
    by a psum AND-reduction inside shard_map (the cross-chip collective —
    the `CCheckQueueControl::Wait` analogue, checkqueue.h:139-142).
    `live` marks real lanes: padding added to reach the batch shape is not
    counted as a failure, while structurally-invalid real lanes are.
    """
    axis = mesh.axis_names[0]
    fields_sharding = NamedSharding(mesh, P(axis, None, None))
    flat_sharding = NamedSharding(mesh, P(axis))
    replicated = NamedSharding(mesh, P())

    def reduce_all(ok_local, live_local):
        # all-valid <=> no live lane failed, on any shard.
        failures = jnp.sum(jnp.where(live_local & ~ok_local, 1, 0))
        return jax.lax.psum(failures, axis) == 0

    reduce_sharded = shard_map(
        reduce_all, mesh=mesh, in_specs=(P(axis), P(axis)), out_specs=P()
    )

    def step(fields, want_odd, parity_req, has_t2, neg1, neg2, valid, live):
        per_lane = _verify_kernel(
            fields, want_odd, parity_req, has_t2, neg1, neg2, valid
        )
        return per_lane, reduce_sharded(per_lane, live)

    return jax.jit(
        step,
        in_shardings=(fields_sharding,) + (flat_sharding,) * 7,
        out_shardings=(flat_sharding, replicated),
    )


class ShardedSecpVerifier(TpuSecpVerifier):
    """Drop-in TpuSecpVerifier that spreads each dispatch over a mesh."""

    def __init__(self, mesh: Optional[Mesh] = None, min_batch: int = 8,
                 chunk: int = 1 << 13):
        super().__init__(min_batch=min_batch, chunk=chunk)
        self.mesh = mesh if mesh is not None else make_mesh()
        n = self.mesh.devices.size
        # Batch sizes must divide evenly across the mesh: round min_batch up
        # to a multiple of n (doubling in _pad preserves divisibility).
        self._min_batch = -(-self._min_batch // n) * n
        self._step = make_sharded_step(self.mesh)
        self._verdict_acc = True
        self._dispatched = 0

    def _run_kernel(self, args, n: int) -> np.ndarray:
        live = np.zeros(args[-1].shape[0], dtype=bool)
        live[:n] = True
        per_lane, all_ok = self._step(*args, live)
        self._verdict_acc = self._verdict_acc and bool(all_ok)
        self._dispatched += n
        return per_lane

    def verify_checks_with_verdict(self, checks: Sequence[SigCheck]):
        """(per-check results, block-level all-ok).

        The all-ok verdict of device-dispatched lanes comes from the psum
        AND-reduction inside the sharded step (the collective barrier), not
        a host re-reduction; lanes rejected host-side before dispatch
        (structural parse failures) AND into the verdict via the dispatched
        count.
        """
        self._verdict_acc = True
        self._dispatched = 0
        res = self.verify_checks(checks)
        verdict = self._verdict_acc and self._dispatched == len(checks)
        return res, verdict
