"""Multi-chip scaling: batch sharding over a device mesh + XLA collectives.

The reference has no distributed backend at all — its parallel axis is a
thread pool draining per-input checks (`checkqueue.h:29-163`). The TPU-native
equivalent (SURVEY §2.2) shards the *signature-check batch* across chips:

- a 1-D ``Mesh`` over a ``batch`` axis (data parallelism is the only axis
  with meaning here: lanes are independent; there is no gradient/activation
  traffic analogue),
- ``jax.jit`` with ``NamedSharding`` in/out specs so XLA partitions the
  verify kernel SPMD across the mesh (collective-free: embarrassingly
  parallel compute),
- a ``shard_map`` reduction step that AND-reduces per-lane verdicts into a
  block-level verdict with ``psum`` over ICI — the analogue of
  `CCheckQueueControl::Wait()`'s all-inputs-valid barrier
  (`checkqueue.h:139-142,188-195`).

Multi-host: the same mesh spec over `jax.devices()` spanning hosts rides
ICI/DCN transparently through pjit — no NCCL/MPI translation layer exists or
is needed.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..crypto.jax_backend import SigCheck, TpuSecpVerifier, _verify_kernel

__all__ = ["make_mesh", "ShardedSecpVerifier", "make_sharded_step"]


def make_mesh(n_devices: Optional[int] = None, axis: str = "batch") -> Mesh:
    """1-D device mesh over the batch axis."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def make_sharded_step(mesh: Mesh):
    """The full multichip verify step, jitted over `mesh`.

    Returns ``step(a, b, px, py, t1, t2, parity, valid) -> (per_lane, all_ok)``
    where inputs are batch-sharded, `per_lane` comes back batch-sharded, and
    `all_ok` is a replicated scalar produced by a psum AND-reduction inside
    shard_map (the cross-chip collective).
    """
    axis = mesh.axis_names[0]
    lane_sharding = NamedSharding(mesh, P(axis, None))
    flat_sharding = NamedSharding(mesh, P(axis))
    replicated = NamedSharding(mesh, P())

    def reduce_all(ok_local):
        # ok_local: this shard's verdicts. all-valid <=> no failures anywhere.
        failures = jnp.sum(jnp.where(ok_local, 0, 1))
        return jax.lax.psum(failures, axis) == 0

    reduce_sharded = shard_map(
        reduce_all, mesh=mesh, in_specs=P(axis), out_specs=P()
    )

    def step(a, b, px, py, want_odd, t1, t2, parity, valid):
        per_lane = _verify_kernel(a, b, px, py, want_odd, t1, t2, parity, valid)
        return per_lane, reduce_sharded(per_lane)

    return jax.jit(
        step,
        in_shardings=(lane_sharding,) * 4
        + (flat_sharding,)
        + (lane_sharding,) * 2
        + (flat_sharding, flat_sharding),
        out_shardings=(flat_sharding, replicated),
    )


class ShardedSecpVerifier(TpuSecpVerifier):
    """Drop-in TpuSecpVerifier that spreads each dispatch over a mesh."""

    def __init__(self, mesh: Optional[Mesh] = None, min_batch: int = 8,
                 max_batch: int = 1 << 16):
        super().__init__(min_batch=min_batch, max_batch=max_batch)
        self.mesh = mesh if mesh is not None else make_mesh()
        n = self.mesh.devices.size
        # Batch sizes must divide evenly across the mesh.
        while self._min_batch % n:
            self._min_batch *= 2
        self._step = make_sharded_step(self.mesh)
        self._kernel = lambda *args: self._step(*args)[0]

    def verify_checks_with_verdict(self, checks: Sequence[SigCheck]):
        """(per-check results, block-level all-ok) in one sharded dispatch."""
        res = self.verify_checks(checks)
        return res, bool(res.all())
