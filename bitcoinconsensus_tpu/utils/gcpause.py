"""Pause the cyclic GC across a bounded, allocation-heavy driver section.

A verify_batch/connect_block pass allocates hundreds of thousands of
short-lived objects (prep records, check tuples, cache keys), which
drives CPython's generational GC into repeated full collections — and a
full collection scans the ENTIRE heap, including the multi-gigabyte
object graph a loaded JAX/jaxlib runtime keeps alive. Measured on the
cached-replay bench: a 5000-input pass runs at ~8.6k inputs/s with the
collector on and ~110k inputs/s with it paused; the pause is also worth
~100 ms on a block replay.

The pause is bounded and state-restoring: reference counting still frees
the (acyclic) bulk of the churn immediately; only cycle collection is
deferred, and a young-generation sweep runs at exit so any cyclic
garbage from the section is reclaimed promptly. Nested pauses are safe
(the inner one is a no-op), and a caller who already disabled GC keeps
it disabled. BITCOINCONSENSUS_TPU_GC_PAUSE=0 turns the whole mechanism
off.
"""

from __future__ import annotations

import gc
import os
import threading
from contextlib import contextmanager

__all__ = ["gc_paused"]

_lock = threading.Lock()
_depth = 0
_reenable = False


@contextmanager
def gc_paused():
    """Depth-counted across threads: concurrent verify_batch calls are a
    supported pattern (models/sigcache.py mutex contract), so the
    collector re-enables only when the LAST paused section exits."""
    global _depth, _reenable
    if os.environ.get("BITCOINCONSENSUS_TPU_GC_PAUSE", "") in ("0", "off"):
        yield
        return
    with _lock:
        if _depth == 0:
            _reenable = gc.isenabled()
            gc.disable()
        _depth += 1
    try:
        yield
    finally:
        with _lock:
            _depth -= 1
            sweep = _depth == 0 and _reenable
            if sweep:
                gc.enable()
        if sweep:
            gc.collect(0)  # sweep the sections' young garbage promptly
