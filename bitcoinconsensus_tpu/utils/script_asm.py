"""Script-ASM parser for the consensus test-vector format.

Equivalent of the reference's ParseScript (`core_read.cpp`): the stringified
script dialect used by `script_tests.json` / `tx_valid.json` /
`tx_invalid.json` — decimal numbers (CScriptNum-encoded pushes with the
OP_0/OP_1..16/OP_1NEGATE folding of CScript::operator<<(int64_t)), raw
``0x``-hex inserted verbatim, single-quoted strings pushed as data, and
opcode names with or without the ``OP_`` prefix (only opcodes ≥ OP_NOP plus
OP_RESERVED are named, exactly like the reference's name map).
"""

from __future__ import annotations

import re

from ..core import script as S
from ..core.script import push_data, script_num_encode

__all__ = ["parse_asm", "ScriptParseError"]

INT64_MAX = 2**63 - 1
INT64_MIN = -(2**63)


class ScriptParseError(ValueError):
    pass


def _build_op_names() -> dict:
    names = {}
    for name in dir(S):
        if not name.startswith("OP_"):
            continue
        value = getattr(S, name)
        if not isinstance(value, int):
            continue
        # Only OP_RESERVED (0x50) and opcodes >= OP_NOP are nameable
        # (core_read.cpp skips the rest). Aliases like OP_NOP2/OP_TRUE are
        # attribute aliases of the same value; the reference resolves each
        # value to its canonical GetOpName string, but accepting the alias
        # spellings here is harmless for the vector corpus (which only uses
        # canonical names) and convenient for hand-written tests.
        if value == S.OP_RESERVED or S.OP_NOP <= value <= S.OP_CHECKSIGADD:
            names[name] = value
            names[name[3:]] = value
    names.pop("INVALIDOPCODE", None)
    names.pop("OP_INVALIDOPCODE", None)
    return names


_OP_NAMES = _build_op_names()
_HEX_RE = re.compile(r"^[0-9a-fA-F]+$")


def _push_int64(n: int) -> bytes:
    """CScript::operator<<(int64_t) (script.h:425-434)."""
    if n == -1 or 1 <= n <= 16:
        return bytes([n + (S.OP_1 - 1)])
    if n == 0:
        return bytes([S.OP_0])
    return push_data(script_num_encode(n))


def parse_asm(text: str) -> bytes:
    result = bytearray()
    for word in text.split():
        if not word:
            continue
        if word.isdigit() or (word[0] == "-" and len(word) > 1 and word[1:].isdigit()):
            n = int(word)
            # atoi64 clamps to the int64 range on overflow.
            n = max(INT64_MIN, min(INT64_MAX, n))
            result += _push_int64(n)
        elif word.startswith("0x") and len(word) > 2 and _HEX_RE.match(word[2:]):
            # Raw hex: inserted verbatim, NOT pushed.
            if len(word) % 2 != 0:
                raise ScriptParseError(f"odd-length hex: {word}")
            result += bytes.fromhex(word[2:])
        elif len(word) >= 2 and word[0] == "'" and word[-1] == "'":
            result += push_data(word[1:-1].encode("latin-1"))
        elif word in _OP_NAMES:
            result.append(_OP_NAMES[word])
        else:
            raise ScriptParseError(f"script parse error: {word!r}")
    return bytes(result)
