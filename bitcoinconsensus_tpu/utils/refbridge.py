"""ctypes bridge to the reference consensus library (dev/bench only).

Loads the shared object produced by `scripts/build_reference.sh` and exposes
the exact C ABI the reference crate binds (`src/lib.rs:141-162`,
`script/bitcoinconsensus.h:67-75`): per-input script verification with
amount. Used for (a) the measured CPU baseline BASELINE.md mandates and
(b) differential fuzzing (the `HAVE_CONSENSUS_LIB` round-trip precedent,
`script_tests.cpp:22-24`). Never imported by the production verify path.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

__all__ = ["ReferenceLib", "load_reference_lib"]

_DEFAULT_SO = os.path.join(
    os.path.dirname(__file__), "..", "..", ".baseline", "libbitcoinconsensus.so"
)


class ReferenceLib:
    """bitcoinconsensus_verify_script_with_amount + _version via ctypes."""

    def __init__(self, path: str):
        self._lib = ctypes.CDLL(path)
        fn = self._lib.bitcoinconsensus_verify_script_with_amount
        fn.restype = ctypes.c_int
        fn.argtypes = [
            ctypes.c_char_p,     # scriptPubKey
            ctypes.c_uint,       # scriptPubKeyLen
            ctypes.c_int64,      # amount
            ctypes.c_char_p,     # txTo
            ctypes.c_uint,       # txToLen
            ctypes.c_uint,       # nIn
            ctypes.c_uint,       # flags
            ctypes.POINTER(ctypes.c_int),  # err
        ]
        self._verify = fn
        fn2 = self._lib.bitcoinconsensus_verify_script
        fn2.restype = ctypes.c_int
        fn2.argtypes = [
            ctypes.c_char_p,     # scriptPubKey
            ctypes.c_uint,       # scriptPubKeyLen
            ctypes.c_char_p,     # txTo
            ctypes.c_uint,       # txToLen
            ctypes.c_uint,       # nIn
            ctypes.c_uint,       # flags
            ctypes.POINTER(ctypes.c_int),  # err
        ]
        self._verify_no_amount = fn2
        ver = self._lib.bitcoinconsensus_version
        ver.restype = ctypes.c_uint
        self._version = ver

    def version(self) -> int:
        return int(self._version())

    def verify_with_flags(
        self,
        spent_output_script: bytes,
        amount: int,
        spending_tx: bytes,
        input_index: int,
        flags: int,
    ) -> tuple:
        """Returns (ok, err_code) — err_code is bitcoinconsensus_error
        (0 = ERR_OK; script failures return ok=0 with err 0, matching the
        reference's swallowed ScriptError, src/lib.rs:133-137)."""
        err = ctypes.c_int(0)
        ok = self._verify(
            spent_output_script,
            len(spent_output_script),
            amount,
            spending_tx,
            len(spending_tx),
            input_index,
            flags,
            ctypes.byref(err),
        )
        return bool(ok), int(err.value)

    def verify_no_amount(
        self,
        spent_output_script: bytes,
        spending_tx: bytes,
        input_index: int,
        flags: int,
    ) -> tuple:
        """bitcoinconsensus_verify_script (bitcoinconsensus.h:67-69): the
        amount-less legacy entry; WITNESS flag yields ERR_AMOUNT_REQUIRED."""
        err = ctypes.c_int(0)
        ok = self._verify_no_amount(
            spent_output_script,
            len(spent_output_script),
            spending_tx,
            len(spending_tx),
            input_index,
            flags,
            ctypes.byref(err),
        )
        return bool(ok), int(err.value)


def load_reference_lib(path: Optional[str] = None) -> Optional[ReferenceLib]:
    """Load the built reference lib, or None when it isn't built (callers
    must skip, not fail: CI machines may lack the reference checkout)."""
    p = os.path.abspath(path or os.environ.get("BITCOINCONSENSUS_REF_SO", _DEFAULT_SO))
    if not os.path.exists(p):
        return None
    try:
        return ReferenceLib(p)
    except OSError:
        return None
