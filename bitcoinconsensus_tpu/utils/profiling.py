"""Per-phase wall-clock timers — a thin adapter over the obs telemetry.

Historically `Phases` owned its own perf_counter pairs and bare dicts;
the dict read-modify-writes raced under the `_idx_threads()` worker pool
in `models/batch.py` (two threads could each read `_calls["x"] == 3` and
both write 4). It is now a facade over `bitcoinconsensus_tpu.obs`:

- each phase runs inside an obs span named ``<scope>.<name>`` — so every
  `Phases` user feeds the global metrics registry
  (`consensus_span_duration_seconds{span="verifier.dispatch"}` etc.) and
  any attached JSONL sink for free;
- the per-instance accumulation that `report()`/`total()` serve is kept,
  but under a lock (regression-tested by tests/test_obs.py hammering one
  instance from many threads).

Usage is unchanged:
    ph = Phases()
    with ph("prep"):
        ...
    ph.report()  # {"prep": {"secs": ..., "calls": ...}, ...}

`Phases(enabled=False)` turns them into no-ops. `reset()` clears only the
instance's dicts — the cumulative registry metrics are process-global by
design (reset those via obs.get_registry().reset()).
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Dict

from ..obs import spans as _spans

__all__ = ["Phases", "xla_trace"]


class Phases:
    def __init__(self, enabled: bool = True, scope: str = "verifier"):
        self.enabled = enabled
        self.scope = scope
        self._lock = threading.Lock()
        self._secs: Dict[str, float] = {}
        self._calls: Dict[str, int] = {}

    @contextmanager
    def __call__(self, name: str):
        if not self.enabled:
            yield
            return
        sp = None
        try:
            with _spans.span(f"{self.scope}.{name}") as sp:
                yield
        finally:
            if sp is not None and sp.duration_s is not None:
                with self._lock:
                    self._secs[name] = self._secs.get(name, 0.0) + sp.duration_s
                    self._calls[name] = self._calls.get(name, 0) + 1

    def reset(self) -> None:
        with self._lock:
            self._secs.clear()
            self._calls.clear()

    def report(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                k: {"secs": round(self._secs[k], 6), "calls": self._calls[k]}
                for k in self._secs
            }

    def total(self) -> float:
        with self._lock:
            return sum(self._secs.values())


@contextmanager
def xla_trace(log_dir: str = "/tmp/bitcoinconsensus_tpu_trace"):
    """XLA/TPU profiler hook (LOCKED thin adapter — same CLI surface as
    always, used by `scripts/profile_verify.py --xla-trace`).

    The actual capture session lives in `obs/xprof.trace_session`, the
    device-truth observatory that also parses these traces into
    per-region attribution; this wrapper only keeps the historical
    entry point and its print. New profiling code should call
    `obs.xprof` directly."""
    from ..obs.xprof import trace_session

    with trace_session(log_dir):
        yield
    print(f"xla trace written to {log_dir}")
