"""Per-phase wall-clock timers for the verify pipeline.

The reference ships no tracing at all (SURVEY §5); its bench layer is
nanobench harnesses. Our pipeline crosses a host→device boundary, so the
first profiling question is always attribution: host parse vs limb pack vs
device dispatch vs readback. A `Phases` object accumulates seconds per
named phase across calls; `TpuSecpVerifier` keeps one (see
`crypto/jax_backend.py`) and `report()` summarises it.

Usage:
    ph = Phases()
    with ph("prep"):
        ...
    ph.report()  # {"prep": {"secs": ..., "calls": ...}, ...}

Timers are cheap (two perf_counter calls) but not free; they are on by
default because one batch is thousands of signatures — the per-batch
overhead is noise. `Phases(enabled=False)` turns them into no-ops.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Dict

__all__ = ["Phases", "xla_trace"]


class Phases:
    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._secs: Dict[str, float] = {}
        self._calls: Dict[str, int] = {}

    @contextmanager
    def __call__(self, name: str):
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self._secs[name] = self._secs.get(name, 0.0) + dt
            self._calls[name] = self._calls.get(name, 0) + 1

    def reset(self) -> None:
        self._secs.clear()
        self._calls.clear()

    def report(self) -> Dict[str, Dict[str, float]]:
        return {
            k: {"secs": round(self._secs[k], 6), "calls": self._calls[k]}
            for k in self._secs
        }

    def total(self) -> float:
        return sum(self._secs.values())


@contextmanager
def xla_trace(log_dir: str = "/tmp/bitcoinconsensus_tpu_trace"):
    """XLA/TPU profiler hook: wraps a region in `jax.profiler.trace` so
    device-side timing (kernel occupancy, transfers) lands in a
    TensorBoard-readable trace under `log_dir`. Complements the host-side
    `Phases` attribution; used by `scripts/profile_verify.py --xla-trace`."""
    import jax

    with jax.profiler.trace(log_dir):
        yield
    print(f"xla trace written to {log_dir}")
