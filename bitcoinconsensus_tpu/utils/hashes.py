"""Hash primitives for the consensus engine (host side).

Covers the reference's hash layer (`depend/bitcoin/src/hash.{h,cpp}`,
`crypto/sha256.cpp`, `crypto/ripemd160.cpp`): double-SHA256, SHA256+RIPEMD160,
single SHA256 and the BIP340 tagged-hash construction
(`hash.cpp:89-96` TaggedHash, `hash.h:24` CHash256, `hash.h:49` CHash160).

Host hashing uses hashlib (OpenSSL-backed, C speed). A pure-Python RIPEMD-160
fallback is provided for environments whose OpenSSL build disables the legacy
provider. The batched on-device SHA-256 lives in
``bitcoinconsensus_tpu.ops.sha256`` — this module is the scalar host path.
"""

from __future__ import annotations

import hashlib
import struct

__all__ = [
    "murmur3_32",
    "sha256",
    "sha256d",
    "hash160",
    "ripemd160",
    "sha1",
    "tagged_hash",
    "tagged_hash_midstate_engine",
]


def murmur3_32(seed: int, data: bytes) -> int:
    """MurmurHash3 x86_32 (hash.cpp:16-78) — the last compiled-surface
    hash of the reference crate (Core's bloom filters); vectors from
    src/test/hash_tests.cpp asserted in tests/test_core_basics.py."""
    c1, c2 = 0xCC9E2D51, 0x1B873593
    M = 0xFFFFFFFF
    h1 = seed & M
    nblocks = len(data) // 4
    for i in range(nblocks):
        k1 = int.from_bytes(data[4 * i : 4 * i + 4], "little")
        k1 = k1 * c1 & M
        k1 = (k1 << 15 | k1 >> 17) & M
        k1 = k1 * c2 & M
        h1 ^= k1
        h1 = (h1 << 13 | h1 >> 19) & M
        h1 = (h1 * 5 + 0xE6546B64) & M
    tail = data[nblocks * 4 :]
    k1 = 0
    if len(tail) >= 3:
        k1 ^= tail[2] << 16
    if len(tail) >= 2:
        k1 ^= tail[1] << 8
    if len(tail) >= 1:
        k1 ^= tail[0]
        k1 = k1 * c1 & M
        k1 = (k1 << 15 | k1 >> 17) & M
        k1 = k1 * c2 & M
        h1 ^= k1
    h1 ^= len(data)
    h1 ^= h1 >> 16
    h1 = h1 * 0x85EBCA6B & M
    h1 ^= h1 >> 13
    h1 = h1 * 0xC2B2AE35 & M
    h1 ^= h1 >> 16
    return h1


def sha256(data: bytes) -> bytes:
    """Single SHA-256."""
    return hashlib.sha256(data).digest()


def sha256d(data: bytes) -> bytes:
    """Double SHA-256 (Bitcoin's Hash(); reference hash.h:24 CHash256)."""
    return hashlib.sha256(hashlib.sha256(data).digest()).digest()


def sha1(data: bytes) -> bytes:
    """SHA-1, needed by OP_SHA1 (reference crypto/sha1.cpp)."""
    return hashlib.sha1(data).digest()


# ---------------------------------------------------------------------------
# RIPEMD-160 — hashlib when available, pure-Python otherwise.
# ---------------------------------------------------------------------------

try:
    hashlib.new("ripemd160", b"")
    _HAVE_OPENSSL_RIPEMD = True
except (ValueError, TypeError):  # pragma: no cover - depends on OpenSSL build
    _HAVE_OPENSSL_RIPEMD = False


def _ripemd160_pure(data: bytes) -> bytes:
    """Pure-Python RIPEMD-160 (ISO/IEC 10118-3 spec implementation)."""
    # Message schedule permutations and rotation amounts from the RIPEMD-160
    # specification (Dobbertin, Bosselaers, Preneel 1996).
    rl = [
        0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15,
        7, 4, 13, 1, 10, 6, 15, 3, 12, 0, 9, 5, 2, 14, 11, 8,
        3, 10, 14, 4, 9, 15, 8, 1, 2, 7, 0, 6, 13, 11, 5, 12,
        1, 9, 11, 10, 0, 8, 12, 4, 13, 3, 7, 15, 14, 5, 6, 2,
        4, 0, 5, 9, 7, 12, 2, 10, 14, 1, 3, 8, 11, 6, 15, 13,
    ]
    rr = [
        5, 14, 7, 0, 9, 2, 11, 4, 13, 6, 15, 8, 1, 10, 3, 12,
        6, 11, 3, 7, 0, 13, 5, 10, 14, 15, 8, 12, 4, 9, 1, 2,
        15, 5, 1, 3, 7, 14, 6, 9, 11, 8, 12, 2, 10, 0, 4, 13,
        8, 6, 4, 1, 3, 11, 15, 0, 5, 12, 2, 13, 9, 7, 10, 14,
        12, 15, 10, 4, 1, 5, 8, 7, 6, 2, 13, 14, 0, 3, 9, 11,
    ]
    sl = [
        11, 14, 15, 12, 5, 8, 7, 9, 11, 13, 14, 15, 6, 7, 9, 8,
        7, 6, 8, 13, 11, 9, 7, 15, 7, 12, 15, 9, 11, 7, 13, 12,
        11, 13, 6, 7, 14, 9, 13, 15, 14, 8, 13, 6, 5, 12, 7, 5,
        11, 12, 14, 15, 14, 15, 9, 8, 9, 14, 5, 6, 8, 6, 5, 12,
        9, 15, 5, 11, 6, 8, 13, 12, 5, 12, 13, 14, 11, 8, 5, 6,
    ]
    sr = [
        8, 9, 9, 11, 13, 15, 15, 5, 7, 7, 8, 11, 14, 14, 12, 6,
        9, 13, 15, 7, 12, 8, 9, 11, 7, 7, 12, 7, 6, 15, 13, 11,
        9, 7, 15, 11, 8, 6, 6, 14, 12, 13, 5, 14, 13, 13, 7, 5,
        15, 5, 8, 11, 14, 14, 6, 14, 6, 9, 12, 9, 12, 5, 15, 8,
        8, 5, 12, 9, 12, 5, 14, 6, 8, 13, 6, 5, 15, 13, 11, 11,
    ]
    kl = [0x00000000, 0x5A827999, 0x6ED9EBA1, 0x8F1BBCDC, 0xA953FD4E]
    kr = [0x50A28BE6, 0x5C4DD124, 0x6D703EF3, 0x7A6D76E9, 0x00000000]

    def rol(x: int, n: int) -> int:
        return ((x << n) | (x >> (32 - n))) & 0xFFFFFFFF

    def f(j: int, x: int, y: int, z: int) -> int:
        if j < 16:
            return x ^ y ^ z
        if j < 32:
            return (x & y) | (~x & z) & 0xFFFFFFFF
        if j < 48:
            return (x | ~y & 0xFFFFFFFF) ^ z
        if j < 64:
            return (x & z) | (y & ~z & 0xFFFFFFFF)
        return x ^ (y | ~z & 0xFFFFFFFF)

    h = [0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476, 0xC3D2E1F0]
    msg = data + b"\x80"
    msg += b"\x00" * ((56 - len(msg) % 64) % 64)
    msg += struct.pack("<Q", len(data) * 8)

    for off in range(0, len(msg), 64):
        x = struct.unpack("<16I", msg[off : off + 64])
        al, bl, cl, dl, el = h
        ar, br, cr, dr, er = h
        for j in range(80):
            t = rol((al + f(j, bl, cl, dl) + x[rl[j]] + kl[j // 16]) & 0xFFFFFFFF, sl[j])
            t = (t + el) & 0xFFFFFFFF
            al, el, dl, cl, bl = el, dl, rol(cl, 10), bl, t
            t = rol((ar + f(79 - j, br, cr, dr) + x[rr[j]] + kr[j // 16]) & 0xFFFFFFFF, sr[j])
            t = (t + er) & 0xFFFFFFFF
            ar, er, dr, cr, br = er, dr, rol(cr, 10), br, t
        h = [
            (h[1] + cl + dr) & 0xFFFFFFFF,
            (h[2] + dl + er) & 0xFFFFFFFF,
            (h[3] + el + ar) & 0xFFFFFFFF,
            (h[4] + al + br) & 0xFFFFFFFF,
            (h[0] + bl + cr) & 0xFFFFFFFF,
        ]
    return struct.pack("<5I", *h)


def ripemd160(data: bytes) -> bytes:
    """RIPEMD-160, needed by OP_RIPEMD160 / OP_HASH160."""
    if _HAVE_OPENSSL_RIPEMD:
        return hashlib.new("ripemd160", data).digest()
    return _ripemd160_pure(data)


def hash160(data: bytes) -> bytes:
    """RIPEMD160(SHA256(x)) (reference hash.h:49 CHash160)."""
    return ripemd160(sha256(data))


# ---------------------------------------------------------------------------
# BIP340 tagged hashes (reference hash.cpp:89-96, hash.h:164-184).
# ---------------------------------------------------------------------------

_TAG_MIDSTATES: dict[str, "hashlib._Hash"] = {}


def tagged_hash_midstate_engine(tag: str) -> "hashlib._Hash":
    """A SHA256 engine pre-fed with SHA256(tag)||SHA256(tag).

    Mirrors the reference's hard-coded tag midstates
    (`secp256k1/src/modules/schnorrsig/main_impl.h:16-44`): computing the
    64-byte prefix once and reusing it via ``.copy()`` amortizes the tag
    blocks across every tagged hash with the same tag.
    """
    eng = _TAG_MIDSTATES.get(tag)
    if eng is None:
        taghash = hashlib.sha256(tag.encode()).digest()
        eng = hashlib.sha256(taghash + taghash)
        _TAG_MIDSTATES[tag] = eng
    return eng.copy()


def tagged_hash(tag: str, data: bytes) -> bytes:
    """SHA256(SHA256(tag) || SHA256(tag) || data) per BIP340."""
    eng = tagged_hash_midstate_engine(tag)
    eng.update(data)
    return eng.digest()
