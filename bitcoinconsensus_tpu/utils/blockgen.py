"""Synthetic funded-UTXO and block generators (tests + benchmarks only).

The reference's bench layer drives `VerifyScript` with a hand-built P2WPKH
spend (`depend/bitcoin/src/bench/verify_script.cpp:19-76`) and its block
bench replays a fixed mainnet block (`bench/checkblock.cpp:17-45`). This
module generalizes that: deterministic keys, funded `CoinsView`s, signed
spends across the script families the BASELINE configs name (P2PKH,
P2WPKH, P2WSH 2-of-3 CHECKMULTISIG, P2TR key path), and fully valid blocks
(merkle root, witness commitment, regtest-grade proof of work) for the
block-replay north star. Never imported by the production verify path.
"""

from __future__ import annotations

import hashlib
import struct
from typing import List, Optional, Sequence, Tuple

from ..core.block import Block, BlockHeader, block_merkle_root, check_proof_of_work
from ..core.script import OP_CHECKMULTISIG, OP_RETURN, push_data
from ..core.sighash import (
    SIGHASH_ALL,
    SIGHASH_DEFAULT,
    PrecomputedTxData,
    SigVersion,
    bip143_sighash,
    bip341_sighash,
    legacy_sighash,
)
from ..core.tx import COIN, OutPoint, Tx, TxIn, TxOut
from ..crypto import secp_host as H
from ..models.validate import Coin, CoinsView, get_block_subsidy
from .hashes import hash160, sha256d, tagged_hash

__all__ = [
    "KINDS",
    "Wallet",
    "FundedOutput",
    "make_funded_view",
    "build_spend_tx",
    "build_block",
    "REGTEST_POW_LIMIT",
    "REGTEST_BITS",
]

# Regtest-grade PoW so test/bench blocks mine in a handful of nonce tries
# (chainparams.cpp regtest powLimit / genesis nBits).
REGTEST_POW_LIMIT = 0x7FFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFF
REGTEST_BITS = 0x207FFFFF

KINDS = ("p2pkh", "p2wpkh", "p2wsh_multisig", "p2tr")


def _sk(seed: str) -> int:
    return int.from_bytes(hashlib.sha256(seed.encode()).digest(), "big") % (H.N - 1) + 1


class Wallet:
    """Deterministic per-seed key material for one output `kind`."""

    def __init__(self, seed: str, kind: str):
        assert kind in KINDS
        self.kind = kind
        self.seed = seed
        if kind == "p2wsh_multisig":
            self.sks = [_sk(f"{seed}/k{i}") for i in range(3)]
            self.pubs = [H.pubkey_create(sk) for sk in self.sks]
            # 2-of-3: OP_2 <pk0> <pk1> <pk2> OP_3 OP_CHECKMULTISIG
            self.witness_script = (
                b"\x52"
                + b"".join(push_data(p) for p in self.pubs)
                + b"\x53"
                + bytes([OP_CHECKMULTISIG])
            )
            self.spk = b"\x00\x20" + hashlib.sha256(self.witness_script).digest()
        elif kind == "p2tr":
            d = _sk(seed)
            px, parity = H.xonly_pubkey_create(d)
            d_even = d if parity == 0 else H.N - d
            t = int.from_bytes(tagged_hash("TapTweak", px), "big") % H.N
            self.out_sk = (d_even + t) % H.N
            qx, _ = H.xonly_pubkey_create(self.out_sk)
            self.spk = b"\x51\x20" + qx
        else:
            self.sk = _sk(seed)
            self.pub = H.pubkey_create(self.sk)
            h = hash160(self.pub)
            if kind == "p2pkh":
                self.spk = b"\x76\xa9" + push_data(h) + b"\x88\xac"
            else:  # p2wpkh
                self.spk = b"\x00\x14" + h

    def sign_input(
        self,
        tx: Tx,
        n_in: int,
        amount: int,
        txdata: Optional[PrecomputedTxData] = None,
        corrupt: bool = False,
    ) -> None:
        """Fill scriptSig/witness of tx.vin[n_in] spending this wallet's spk."""
        if self.kind == "p2pkh":
            sighash = legacy_sighash(self.spk, tx, n_in, SIGHASH_ALL)
            sig = H.sign_ecdsa(self.sk, sighash) + bytes([SIGHASH_ALL])
            if corrupt:
                sig = _flip(sig, 9)
            tx.vin[n_in].script_sig = push_data(sig) + push_data(self.pub)
        elif self.kind == "p2wpkh":
            code = b"\x76\xa9" + push_data(hash160(self.pub)) + b"\x88\xac"
            sighash = bip143_sighash(code, tx, n_in, SIGHASH_ALL, amount)
            sig = H.sign_ecdsa(self.sk, sighash) + bytes([SIGHASH_ALL])
            if corrupt:
                sig = _flip(sig, 9)
            tx.vin[n_in].witness = [sig, self.pub]
        elif self.kind == "p2wsh_multisig":
            sighash = bip143_sighash(
                self.witness_script, tx, n_in, SIGHASH_ALL, amount
            )
            sigs = [
                H.sign_ecdsa(sk, sighash) + bytes([SIGHASH_ALL])
                for sk in self.sks[:2]
            ]
            if corrupt:
                sigs[0] = _flip(sigs[0], 9)
            tx.vin[n_in].witness = [b""] + sigs + [self.witness_script]
        else:  # p2tr key path
            assert txdata is not None, "taproot signing needs PrecomputedTxData"
            sighash = bip341_sighash(
                tx, n_in, SIGHASH_DEFAULT, SigVersion.TAPROOT, txdata, False, b""
            )
            sig = H.sign_schnorr(self.out_sk, sighash)
            if corrupt:
                sig = _flip(sig, 40)
            tx.vin[n_in].witness = [sig]
        # Construct-then-sign mutates the tx: any memoized id/serialization
        # captured before signing would be stale (core/tx.py contract).
        tx.invalidate_caches()


def _flip(b: bytes, i: int) -> bytes:
    return b[:i] + bytes([b[i] ^ 1]) + b[i + 1 :]


class FundedOutput:
    __slots__ = ("outpoint", "wallet", "amount")

    def __init__(self, outpoint: OutPoint, wallet: Wallet, amount: int):
        self.outpoint = outpoint
        self.wallet = wallet
        self.amount = amount


def make_funded_view(
    n: int,
    kinds: Sequence[str] = KINDS,
    amount: int = COIN // 100,
    height: int = 1,
    seed: str = "fund",
) -> Tuple[CoinsView, List[FundedOutput]]:
    """A CoinsView holding n outputs cycling through `kinds`."""
    coins = CoinsView()
    funded: List[FundedOutput] = []
    for i in range(n):
        kind = kinds[i % len(kinds)]
        w = Wallet(f"{seed}/{i}", kind)
        op = OutPoint(hashlib.sha256(f"{seed}/op/{i}".encode()).digest(), i & 0xFFFF)
        coins.add(op, Coin(TxOut(amount, w.spk), height=height, coinbase=False))
        funded.append(FundedOutput(op, w, amount))
    return coins, funded


def build_spend_tx(
    inputs: Sequence[FundedOutput],
    fee: int = 1000,
    corrupt_input: Optional[int] = None,
) -> Tx:
    """One signed tx spending `inputs` to an anyone-can-spend output."""
    total = sum(f.amount for f in inputs)
    tx = Tx(
        version=2,
        vin=[TxIn(f.outpoint) for f in inputs],
        vout=[TxOut(total - fee, b"\x51")],
        locktime=0,
    )
    spent = [TxOut(f.amount, f.wallet.spk) for f in inputs]
    # force=True: BIP341 readiness is normally inferred from witnesses,
    # which are only attached below as each input signs.
    txdata = (
        PrecomputedTxData(tx, spent, force=True)
        if any(f.wallet.kind == "p2tr" for f in inputs)
        else None
    )
    for i, f in enumerate(inputs):
        f.wallet.sign_input(
            tx, i, f.amount, txdata=txdata, corrupt=(i == corrupt_input)
        )
    return tx


def _make_coinbase(height: int, reward: int, with_witness_commitment: bool) -> Tx:
    """Coinbase paying `reward`; BIP34 height push + optional BIP141
    commitment placeholder (patched by build_block after the txs settle)."""
    script_sig = push_data(struct.pack("<I", height).rstrip(b"\x00") or b"\x00") + b"\x00"
    vout = [TxOut(reward, b"\x51")]
    if with_witness_commitment:
        vout.append(TxOut(0, bytes([OP_RETURN, 0x24]) + b"\xaa\x21\xa9\xed" + b"\x00" * 32))
    tx = Tx(
        version=1,
        vin=[TxIn(OutPoint(b"\x00" * 32, 0xFFFFFFFF), script_sig, 0xFFFFFFFF)],
        vout=vout,
        locktime=0,
    )
    if with_witness_commitment:
        tx.vin[0].witness = [b"\x00" * 32]
    return tx


def build_block(
    txs: List[Tx],
    height: int,
    prev_hash: bytes = b"\x00" * 32,
    fees: int = 0,
    time: int = 1_600_000_000,
    bits: int = REGTEST_BITS,
    witness_commitment: bool = True,
) -> Block:
    """Assemble + mine a structurally valid block over `txs`.

    Coinbase reward = subsidy(height) + fees; witness commitment recomputed
    over the final tx list; nonce ground until the header clears the
    regtest target (a few tries at REGTEST_BITS).
    """
    coinbase = _make_coinbase(
        height, get_block_subsidy(height) + fees, witness_commitment
    )
    vtx = [coinbase] + txs
    header = BlockHeader(
        version=0x20000000,
        prev_hash=prev_hash,
        merkle_root=b"\x00" * 32,
        time=time,
        bits=bits,
        nonce=0,
    )
    block = Block(header, vtx)
    if witness_commitment:
        from ..core.block import block_witness_merkle_root, witness_commitment_index

        root, _ = block_witness_merkle_root(block)
        commit = sha256d(root + coinbase.vin[0].witness[0])
        idx = witness_commitment_index(block)
        spk = coinbase.vout[idx].script_pubkey
        coinbase.vout[idx] = TxOut(0, spk[:6] + commit)
        # Coinbase mutated after caching: drop ids AND serializations.
        coinbase.invalidate_caches()
    header.merkle_root = block_merkle_root(block)[0]
    while not check_proof_of_work(block.hash, bits, REGTEST_POW_LIMIT):
        header.nonce += 1
    return block
