"""Batched SHA-256 on device — fixed-layout messages, lane-parallel.

The reference computes every hash serially on the CPU (`crypto/sha256.cpp`
generic transform; the SIMD multiway variants exist but are not compiled,
SURVEY §2.1). The TPU-native reshaping: one compression function traced
over a batch axis, whole-array uint32 ops on the VPU — every lane advances
through the 64 rounds in lockstep. Schedules are fixed at trace time by
the (static) message length, which is exactly the shape of the consensus
workloads:

- BIP340 tagged hashes: 64-byte tag prefix collapses into a precomputed
  midstate (the reference hardcodes the same midstates,
  `modules/schnorrsig/main_impl.h:16-44,96-109`), then a fixed 96-byte
  payload (challenge: r.x ‖ pk.x ‖ msg).
- BIP143/BIP341 sighash preimages: fixed layout per (script_code length)
  bucket; double SHA-256.

`sha256_fixed` handles any static length ≥ 0 with optional midstate;
`sha256d_fixed` is the double-SHA convenience; `bip340_challenge` is the
batched challenge hash the Schnorr verify path uses. All return big-endian
byte arrays, bit-identical to hashlib (asserted by tests/test_ops_sha256.py).
"""

from __future__ import annotations

import hashlib
import struct

import numpy as np

import jax.numpy as jnp

from .regions import named_region

__all__ = [
    "sha256_compress",
    "sha256_fixed",
    "sha256d_fixed",
    "tag_midstate",
    "bip340_challenge",
    "CHALLENGE_MIDSTATE",
]

_H0 = np.array(
    [
        0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
        0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
    ],
    dtype=np.uint32,
)

_K = np.array(
    [
        0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5, 0x3956C25B, 0x59F111F1,
        0x923F82A4, 0xAB1C5ED5, 0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
        0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174, 0xE49B69C1, 0xEFBE4786,
        0x0FC19DC6, 0x240CA1CC, 0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
        0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7, 0xC6E00BF3, 0xD5A79147,
        0x06CA6351, 0x14292967, 0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
        0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85, 0xA2BFE8A1, 0xA81A664B,
        0xC24B8B70, 0xC76C51A3, 0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
        0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5, 0x391C0CB3, 0x4ED8AA4A,
        0x5B9CCA4F, 0x682E6FF3, 0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
        0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
    ],
    dtype=np.uint32,
)


def _rotr(x, n: int):
    # x is uint32: >> is a logical shift for unsigned dtypes.
    return (x >> n) | (x << (32 - n))


def _shr(x, n: int):
    return x >> n


@named_region("sha256_compress")
def sha256_compress(state, block):
    """One SHA-256 compression: state (8, ...) uint32, block (16, ...)
    uint32 big-endian words. Returns the new (8, ...) state. Whole-array
    ops only; the batch rides the trailing axes."""
    w = [block[i] for i in range(16)]
    for i in range(16, 64):
        s0 = _rotr(w[i - 15], 7) ^ _rotr(w[i - 15], 18) ^ _shr(w[i - 15], 3)
        s1 = _rotr(w[i - 2], 17) ^ _rotr(w[i - 2], 19) ^ _shr(w[i - 2], 10)
        w.append(w[i - 16] + s0 + w[i - 7] + s1)

    a, b, c, d, e, f, g, h = (state[i] for i in range(8))
    for i in range(64):
        S1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + S1 + ch + jnp.uint32(int(_K[i])) + w[i]
        S0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = S0 + maj
        h, g, f, e, d, c, b, a = g, f, e, d + t1, c, b, a, t1 + t2
    out = jnp.stack([a, b, c, d, e, f, g, h], axis=0)
    return out + state


def _words_from_bytes(data):
    """(..., 4k) uint8 -> (k, ...) big-endian uint32 words (word-major)."""
    u = data.astype(jnp.uint32)
    w = (
        (u[..., 0::4] << 24)
        | (u[..., 1::4] << 16)
        | (u[..., 2::4] << 8)
        | u[..., 3::4]
    )
    return jnp.moveaxis(w, -1, 0)


def _bytes_from_words(words):
    """(8, ...) uint32 -> (..., 32) uint8 big-endian digest bytes."""
    w = jnp.moveaxis(words, 0, -1).astype(jnp.uint32)  # (..., 8)
    shifts = jnp.asarray([24, 16, 8, 0], dtype=jnp.uint32)
    b = (w[..., :, None] >> shifts) & jnp.uint32(0xFF)
    return b.reshape(b.shape[:-2] + (32,)).astype(jnp.uint8)


def _padding(total_len: int) -> bytes:
    """Static SHA-256 padding for a hashed stream (incl. any
    midstate-consumed prefix) totalling `total_len` bytes."""
    pad = b"\x80" + b"\x00" * ((55 - total_len) % 64)
    return pad + struct.pack(">Q", total_len * 8)


def sha256_fixed(data, midstate=None, prefix_len: int = 0):
    """Batched SHA-256 of fixed-length messages.

    data: (..., L) uint8 with static L. midstate: optional (8,) or (8, ...)
    uint32 chaining state that already consumed `prefix_len` bytes (must be
    a multiple of 64). Returns (..., 32) uint8 digests.
    """
    L = data.shape[-1]
    assert prefix_len % 64 == 0
    pad = _padding(prefix_len + L)
    batch_shape = data.shape[:-1]
    padv = jnp.broadcast_to(
        jnp.asarray(np.frombuffer(pad, dtype=np.uint8)), batch_shape + (len(pad),)
    )
    stream = jnp.concatenate([data, padv], axis=-1)
    n_blocks = stream.shape[-1] // 64
    assert stream.shape[-1] % 64 == 0

    if midstate is None:
        state = jnp.broadcast_to(
            jnp.asarray(_H0).reshape((8,) + (1,) * len(batch_shape)),
            (8,) + batch_shape,
        )
    else:
        ms = jnp.asarray(midstate, dtype=jnp.uint32)
        if ms.ndim == 1:
            ms = ms.reshape((8,) + (1,) * len(batch_shape))
        state = jnp.broadcast_to(ms, (8,) + batch_shape)
    for i in range(n_blocks):
        block = _words_from_bytes(stream[..., i * 64 : (i + 1) * 64])
        state = sha256_compress(state, block)
    return _bytes_from_words(state)


def sha256d_fixed(data, midstate=None, prefix_len: int = 0):
    """Double SHA-256 (CHash256, hash.h:24) of fixed-length messages."""
    return sha256_fixed(sha256_fixed(data, midstate, prefix_len))


def tag_midstate(tag: str) -> np.ndarray:
    """(8,) uint32 chaining state after SHA256(tag)‖SHA256(tag) — the
    64-byte prefix every BIP340 tagged hash starts with (hash.cpp:89-96;
    hardcoded equivalents at schnorrsig/main_impl.h:16-44)."""
    th = hashlib.sha256(tag.encode()).digest()
    state = _H0.copy()
    block = np.frombuffer(th + th, dtype=np.uint8)
    # One host-side compression over the doubled tag hash.
    s = [int(x) for x in state]
    w = list(struct.unpack(">16I", block.tobytes()))
    for i in range(16, 64):
        s0 = _py_rotr(w[i - 15], 7) ^ _py_rotr(w[i - 15], 18) ^ (w[i - 15] >> 3)
        s1 = _py_rotr(w[i - 2], 17) ^ _py_rotr(w[i - 2], 19) ^ (w[i - 2] >> 10)
        w.append((w[i - 16] + s0 + w[i - 7] + s1) & 0xFFFFFFFF)
    a, b, c, d, e, f, g, h = s
    for i in range(64):
        S1 = _py_rotr(e, 6) ^ _py_rotr(e, 11) ^ _py_rotr(e, 25)
        ch = (e & f) ^ (~e & g) & 0xFFFFFFFF
        t1 = (h + S1 + (ch & 0xFFFFFFFF) + int(_K[i]) + w[i]) & 0xFFFFFFFF
        S0 = _py_rotr(a, 2) ^ _py_rotr(a, 13) ^ _py_rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = (S0 + maj) & 0xFFFFFFFF
        h, g, f, e, d, c, b, a = g, f, e, (d + t1) & 0xFFFFFFFF, c, b, a, (t1 + t2) & 0xFFFFFFFF
    return np.array(
        [(x + y) & 0xFFFFFFFF for x, y in zip([a, b, c, d, e, f, g, h], s, strict=True)],
        dtype=np.uint32,
    )


def _py_rotr(x: int, n: int) -> int:
    return ((x >> n) | (x << (32 - n))) & 0xFFFFFFFF


CHALLENGE_MIDSTATE = tag_midstate("BIP0340/challenge")


@named_region("sighash_prep")
def bip340_challenge(r32, px32, m32):
    """Batched BIP340 challenge e = tagged(r.x ‖ pk.x ‖ m): (..., 32) uint8
    triples -> (..., 32) uint8 digests. Midstate skips the tag block; two
    compressions per lane (schnorrsig/main_impl.h:111-125)."""
    payload = jnp.concatenate([r32, px32, m32], axis=-1)
    return sha256_fixed(payload, midstate=CHALLENGE_MIDSTATE, prefix_len=64)
