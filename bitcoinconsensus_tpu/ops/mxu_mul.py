"""MXU one-hot fe_mul candidate: the limb convolution as f32 dot_generals.

The ROADMAP's kernel arc moves `fe_mul` off the VPU by phrasing the
radix-2^13 schoolbook convolution as systolic-array work: limb digits
ride through `dot_general` at `Precision.HIGHEST` against a one-hot
selector, so the MXU does the column gather-and-accumulate that today
costs 39 shifted adds per multiply. This module is the *reference-shaped
candidate* for that lowering — bit-identical to `limbs.fe_mul` (the
tests diff them across >= 10k seeded operand pairs) and, more
importantly, **provably** bit-identical: `analysis/interval.py`'s
carried exact-float domain certifies every f32 value in here
integer-valued with an accumulated magnitude bound
Sigma|products| <= 2^24, and `scripts/consensus_lint.py --exactness`
emits the per-value theorem trace. Registered as
`mxu.fe_mul_onehot` in `analysis/registry.py`.

Shape of the proof (all bounds static, derived independently by the
analyzer — a mismatch in either direction is a finding):

- Weak limbs are <= max(W2) = 15631 < 2^14, too wide for an exact f32
  product chain, so each operand splits into 7-bit digits
  `a = a0 + 2^7 * a1` with `a0 <= 127` and `a1 <= 122`.
- One digit convolution runs as two HIGHEST-precision dots against the
  traced one-hot selector S3[j, k, i] = [i + j == k] (built from
  `broadcasted_iota` equality, so the analyzer *derives* its
  one-hot-along-axis-0 structure instead of trusting a constant):
  U[b, k, i] = sum_j y[j, b] * S3[j, k, i] = y[k - i, b], then
  V[b, k] = sum_i U[b, k, i] * x[i, b] = sum_{i+j=k} x[i,b] * y[j,b].
  The accumulated sum bound is NLIMB * 127 * 127 = 322,580 <= 2^24,
  so every partial sum is an exactly-representable f32 integer.
- The four digit convolutions recombine in int32
  (2^14 = 2 * 2^13 moves the high-high term one column up), every
  column staying < 2^31, and `_settle` drives the 40 columns into the
  same W2 weak form `limbs.fe_mul` produces.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .limbs import NLIMB, W2, _pad_rows, _settle
from .regions import named_region

_NCOL = 2 * NLIMB - 1  # schoolbook columns of an NLIMB x NLIMB product

# 7-bit digit split of the <= 14-bit weak limbs.
_DIGIT_BITS = 7
_D0 = (1 << _DIGIT_BITS) - 1   # low-digit bound  (a & 127)
_D1 = max(W2) >> _DIGIT_BITS   # high-digit bound (15631 >> 7 = 122)

# Accumulated-sum bounds of the four digit convolutions: at most NLIMB
# products land in one column. Each must sit inside the 2^24 f32
# exact-integer window — these are the theorem obligations the analyzer
# re-derives per value.
_B00 = NLIMB * _D0 * _D0       # 322,580
_B01 = NLIMB * _D0 * _D1       # 309,880 (t01 and t10 alike)
_B11 = NLIMB * _D1 * _D1       # 297,680
assert max(_B00, _B01, _B11) <= 1 << 24

# Recombination bounds (int32): col = t00 + (t01 + t10) * 2^7, and the
# high-high term shifts one column up via 2^14 = 2 * 2^13.
_COLB = _B00 + 2 * _B01 * (1 << _DIGIT_BITS)
_COL40_BOUNDS = [_COLB] + [_COLB + 2 * _B11] * (_NCOL - 1) + [2 * _B11]
for _b in _COL40_BOUNDS:
    assert _b < 2 ** 31, _b


def _onehot_selector():
    """S3[j, k, i] = 1.0 iff i + j == k, traced from iota equality.

    Building it in-graph (rather than a captured numpy constant) lets
    the interval analyzer derive nz0-along-axis-0 — at most one j hits
    any (k, i) cell — which is what makes the first dot a pure gather
    with contraction multiplicity 1.
    """
    shape = (NLIMB, _NCOL, NLIMB)
    jj = lax.broadcasted_iota(jnp.int32, shape, 0)
    kk = lax.broadcasted_iota(jnp.int32, shape, 1)
    ii = lax.broadcasted_iota(jnp.int32, shape, 2)
    return (jj == (kk - ii)).astype(jnp.float32)


def _conv_mxu(x, y):
    """One digit convolution: (NLIMB, B) x (NLIMB, B) -> (2*NLIMB-1, B).

    out[k, b] = sum_{i+j=k} x[i, b] * y[j, b], computed as two
    HIGHEST-precision f32 dots (gather via the one-hot selector, then
    the per-lane contraction on the MXU).
    """
    s3 = _onehot_selector()
    xf = x.astype(jnp.float32)
    yf = y.astype(jnp.float32)
    # U[b, k, i] = y[k - i, b] (zero outside the band): one-hot gather.
    u = lax.dot_general(yf, s3, (((0,), (0,)), ((), ())),
                        precision=lax.Precision.HIGHEST)
    # V[b, k] = sum_i U[b, k, i] * x[i, b]: the column accumulation.
    v = lax.dot_general(u, xf, (((2,), (0,)), ((0,), (1,))),
                        precision=lax.Precision.HIGHEST)
    return v.astype(jnp.int32).T


@named_region("fe_mul_onehot")
def fe_mul_onehot(a, b):
    """a * b mod p via one-hot f32 MXU dots (weak in, weak out).

    Bit-identical to `limbs.fe_mul` after `fe_canon` (the two produce
    different — equally valid — weak representatives of the same
    residue; canonical form is where consensus identity is defined).
    """
    a0, a1 = a & _D0, a >> _DIGIT_BITS
    b0, b1 = b & _D0, b >> _DIGIT_BITS
    t00 = _conv_mxu(a0, b0)
    t01 = _conv_mxu(a0, b1)
    t10 = _conv_mxu(a1, b0)
    t11 = _conv_mxu(a1, b1)
    col = t00 + (t01 + t10) * (1 << _DIGIT_BITS)
    col40 = _pad_rows(col, 0, 1) + _pad_rows(2 * t11, 1, 0)
    return _settle(col40, list(_COL40_BOUNDS))
