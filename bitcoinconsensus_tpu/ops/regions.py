"""Named kernel-region annotation for device-time attribution.

Every consensus kernel executes under a ``jax.named_scope`` whose name
carries the ``region:`` prefix.  The scope is pure metadata: it adds no
ops to the traced program, so the interval prover, the exactness
prover, and the A/B bit-identity harness see byte-identical jaxprs.
What it *does* do is stamp every equation's ``source_info.name_stack``
(and, on real hardware, every XLA op's metadata) with the region name,
which is what lets `obs/xprof.py` attribute measured device time to
kernel regions — and what the host-lint annotation-coverage rule
checks so new kernels can't land unattributable.

This module deliberately lives in ``ops/`` (not ``obs/``): kernel code
must never import the observability layer, but the observability layer
may import this.  It has no dependencies beyond a lazy ``jax`` import.

Region names are stable identifiers — `XPROF_r{N}.json` artifacts and
the CI drift gate compare shares per region name across runs, so
renaming one is a breaking change to the perf-gate contract.
"""

from __future__ import annotations

import functools
from contextlib import contextmanager

# Prefix distinguishing consensus kernel regions from incidental
# jit/scan scope frames in a name stack or an XLA trace event.
REGION_PREFIX = "region:"


def region_name(name: str) -> str:
    """The fully-qualified scope name for a region."""
    return REGION_PREFIX + name


@contextmanager
def region_scope(name: str):
    """Inline form: ``with region_scope("point_decode"): ...``.

    Legal both under trace and eagerly, so host seams like settle can
    use it unconditionally: under trace it extends the name stack; a
    profiler ``TraceAnnotation`` additionally marks the region on the
    host track of a capture (nanoseconds of overhead when no profiler
    session is active), which is how eager seams stay attributable.
    """
    import jax

    qual = region_name(name)
    try:
        ann = jax.profiler.TraceAnnotation(qual)
    except Exception:  # pragma: no cover - profiler-less builds
        with jax.named_scope(qual):
            yield
        return
    with jax.named_scope(qual), ann:
        yield


def named_region(name: str):
    """Decorator: run the wrapped callable under a kernel region scope.

    >>> @named_region("fe_mul")
    ... def fe_mul(a, b): ...
    """

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            import jax

            with jax.named_scope(region_name(name)):
                return fn(*args, **kwargs)

        wrapper.__consensus_region__ = name
        return wrapper

    return deco


def extract_regions(scope_name: str) -> list:
    """Every region frame in a scope/name-stack string, outermost first.

    A name stack renders as ``/``-joined frames, e.g.
    ``jit(step)/region:point_decode/region:fe_mul`` -> the op belongs to
    leaf region ``fe_mul`` within phase ``point_decode``.
    """
    out = []
    idx = scope_name.find(REGION_PREFIX)
    while idx >= 0:
        tail = scope_name[idx + len(REGION_PREFIX):]
        for sep in ("/", '"', "'", ";", ",", " "):
            cut = tail.find(sep)
            if cut >= 0:
                tail = tail[:cut]
        if tail:
            out.append(tail)
        idx = scope_name.find(REGION_PREFIX, idx + len(REGION_PREFIX))
    return out


def extract_region(scope_name: str) -> str | None:
    """The region in a scope/name-stack string, or None.

    Name stacks render as ``/``-joined frames (``jit(f)/region:fe_mul``)
    and trace-event names may embed the scope arbitrarily; the *last*
    region frame wins so the innermost annotation is the one charged —
    which is what makes ``fe_mul`` vs ``fe_mul_onehot`` A/B-attributable
    inside a larger ``scalar_mult`` region.
    """
    idx = scope_name.rfind(REGION_PREFIX)
    if idx < 0:
        return None
    tail = scope_name[idx + len(REGION_PREFIX):]
    for sep in ("/", '"', "'", ";", ",", " "):
        cut = tail.find(sep)
        if cut >= 0:
            tail = tail[:cut]
    return tail or None
