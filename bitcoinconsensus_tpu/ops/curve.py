"""Batched secp256k1 group ops and double-scalar multiplication for TPU.

Points are Jacobian triples ``(X, Y, Z)`` of weak field elements (see
`limbs.py`), batched over leading axes; ``Z ≡ 0`` encodes infinity. All
control flow is branchless: exceptional cases of the addition law (equal
points, negated points, infinity operands) are computed alongside the generic
formula and chosen with masks, so one traced program is consensus-exact for
*every* lane — the TPU-native replacement for the reference's per-case
branches in `secp256k1/src/group_impl.h` (gej_double, gej_add_ge_var).

The verify workload is R = a·G + b·P per lane (`secp256k1_ecmult`,
`secp256k1/src/ecmult_impl.h:561-580`). The reference runs Strauss-wNAF per
call on one core; here every lane walks the same 256 MSB-first bit steps
(double, conditionally add G, conditionally add P) under `lax.fori_loop`, so
thousands of verifications advance in lockstep on the VPU. No secret data is
involved on the verify path, so uniform (non-constant-time) schedules are
fine — same stance as the reference's variable-time verify routines.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from .limbs import (
    MASK,
    NLIMB,
    RADIX,
    fe_add,
    fe_canon,
    fe_inv,
    fe_is_zero,
    fe_mul,
    fe_mul_small,
    fe_sqr,
    fe_sub,
    int_to_limbs,
)

__all__ = [
    "G_X",
    "G_Y",
    "jacobian_double",
    "jacobian_madd_complete",
    "double_scalar_mult",
    "jacobian_to_affine",
    "scalar_bits",
]

G_X = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
G_Y = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8

_GX_LIMBS = int_to_limbs(G_X)
_GY_LIMBS = int_to_limbs(G_Y)
_ONE = int_to_limbs(1)

NBITS = NLIMB * RADIX  # 260 bit positions per scalar (top 4 always zero)


def jacobian_double(X, Y, Z):
    """Point doubling, dbl-2009-l for a=0; maps infinity to infinity."""
    A = fe_sqr(X)
    B = fe_sqr(Y)
    C = fe_sqr(B)
    D = fe_mul_small(fe_sub(fe_sqr(fe_add(X, B)), fe_add(A, C)), 2)
    E = fe_mul_small(A, 3)
    F = fe_sqr(E)
    X3 = fe_sub(F, fe_mul_small(D, 2))
    Y3 = fe_sub(fe_mul(E, fe_sub(D, X3)), fe_mul_small(C, 8))
    Z3 = fe_mul_small(fe_mul(Y, Z), 2)  # Z=0 -> Z3=0: infinity is preserved
    return X3, Y3, Z3


def _select(mask, a3, b3):
    """Per-lane select between two point triples; mask shape (...,)."""
    m = mask[..., None]
    return tuple(jnp.where(m, x, y) for x, y in zip(a3, b3))


def jacobian_madd_complete(X1, Y1, Z1, x2, y2):
    """Complete mixed addition (X1,Y1,Z1) + (x2,y2) with (x2,y2) affine,
    never infinity. Handles all exceptional cases branchlessly:

    - (X1,Y1,Z1) infinite  -> (x2, y2, 1)
    - equal points         -> doubling result
    - negated points       -> infinity

    Generic path is madd-2007-bl (the same math as the reference's
    `secp256k1_gej_add_ge_var`, `group_impl.h`, vectorized and de-branched).
    """
    Z1Z1 = fe_sqr(Z1)
    U2 = fe_mul(x2, Z1Z1)
    S2 = fe_mul(y2, fe_mul(Z1, Z1Z1))
    H = fe_sub(U2, X1)
    Rsub = fe_sub(S2, Y1)
    h_zero = fe_is_zero(H)
    r_zero = fe_is_zero(Rsub)

    HH = fe_sqr(H)
    I = fe_mul_small(HH, 4)
    J = fe_mul(H, I)
    r = fe_mul_small(Rsub, 2)
    V = fe_mul(X1, I)
    X3 = fe_sub(fe_sqr(r), fe_add(J, fe_mul_small(V, 2)))
    Y3 = fe_sub(fe_mul(r, fe_sub(V, X3)), fe_mul_small(fe_mul(Y1, J), 2))
    Z3 = fe_sub(fe_sqr(fe_add(Z1, H)), fe_add(Z1Z1, HH))
    out = (X3, Y3, Z3)

    dbl = jacobian_double(X1, Y1, Z1)
    zeros = jnp.zeros_like(X1)
    ones = jnp.broadcast_to(jnp.asarray(_ONE), X1.shape).astype(X1.dtype)
    inf = (ones, ones, zeros)
    lift = (jnp.broadcast_to(x2, X1.shape).astype(X1.dtype),
            jnp.broadcast_to(y2, X1.shape).astype(X1.dtype), ones)

    out = _select(h_zero & r_zero, dbl, out)
    out = _select(h_zero & ~r_zero, inf, out)
    out = _select(fe_is_zero(Z1), lift, out)
    return out


def scalar_bits(limbs):
    """(..., 20) scalar limbs -> (..., 260) bits, LSB first."""
    shifts = jnp.arange(RADIX, dtype=jnp.int32)
    bits = (limbs[..., :, None] >> shifts) & 1
    return bits.reshape(bits.shape[:-2] + (NBITS,))


def double_scalar_mult(a, b, px, py):
    """R = a·G + b·P per lane (the ECDSA/Schnorr verify hot kernel).

    `a`, `b`: (..., 20) scalar limb vectors (values < 2^256, i.e. bit
    positions 256..259 zero). `px`, `py`: (..., 20) affine point (never
    infinity; host substitutes a dummy and masks invalid lanes).
    Returns a Jacobian triple. 256 iterations of double + 2 conditional
    complete additions, identical schedule in every lane.
    """
    bits_a = scalar_bits(a)
    bits_b = scalar_bits(b)
    gx = jnp.broadcast_to(jnp.asarray(_GX_LIMBS), px.shape).astype(px.dtype)
    gy = jnp.broadcast_to(jnp.asarray(_GY_LIMBS), py.shape).astype(py.dtype)
    zeros = jnp.zeros_like(px)
    ones = jnp.broadcast_to(jnp.asarray(_ONE), px.shape).astype(px.dtype)
    init = (ones, ones, zeros)  # infinity

    def body(i, R):
        t = 255 - i
        R = jacobian_double(*R)
        ba = lax.dynamic_index_in_dim(bits_a, t, axis=-1, keepdims=False)
        Ra = jacobian_madd_complete(*R, gx, gy)
        R = _select(ba == 1, Ra, R)
        bb = lax.dynamic_index_in_dim(bits_b, t, axis=-1, keepdims=False)
        Rb = jacobian_madd_complete(*R, px, py)
        R = _select(bb == 1, Rb, R)
        return R

    return lax.fori_loop(0, 256, body, init)


def jacobian_to_affine(X, Y, Z):
    """(X, Y, Z) -> (x, y, is_infinity) with x, y canonical in [0, p).

    Uses one Fermat inversion per lane (~500 muls — <5% of a 256-bit
    double-and-add). Infinity lanes return x = y = 0 and the mask.
    """
    zi = fe_inv(Z)
    zi2 = fe_sqr(zi)
    x = fe_canon(fe_mul(X, zi2))
    y = fe_canon(fe_mul(Y, fe_mul(zi2, zi)))
    return x, y, fe_is_zero(Z)
