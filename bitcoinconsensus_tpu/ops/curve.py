"""Batched secp256k1 group ops and double-scalar multiplication for TPU.

Points are Jacobian triples ``(X, Y, Z)`` of weak field elements in the
limb-major layout of `limbs.py` — shape ``(20, B)`` with the batch in the
lane axis; ``Z ≡ 0`` encodes infinity. All control flow is branchless:
exceptional cases of the addition law (equal points, negated points,
infinity operands) are computed alongside the generic formula and chosen
with masks, so one traced program is consensus-exact for *every* lane —
the TPU-native replacement for the reference's per-case branches in
`secp256k1/src/group_impl.h`.

The verify workload is R = a·G + b·P per lane (`secp256k1_ecmult`,
`secp256k1/src/ecmult_impl.h:561-580`). The reference runs Strauss-wNAF
per call on one core; here every lane advances in lockstep on the VPU:

- fixed-base half a·G: 32 8-bit windows against a device-resident table
  of affine multiples k·256^w·G (the ecmult_context_build analogue,
  `gen_gtable.py`) — 32 complete mixed additions, zero doublings; the
  one-hot row select runs as an exact f32 matmul on the MXU;
- variable-base half b·P: per-lane Jacobian table {0..15}·P built by a
  14-step `lax.scan`, then 64 windows of 4 doublings + one complete
  Jacobian addition with a one-hot table select;
- one final complete addition joins the halves.

No secret data is involved on the verify path, so uniform (non-constant-
time) schedules are fine — same stance as the reference's variable-time
verify routines.
"""

from __future__ import annotations

import os

import numpy as np

import jax.numpy as jnp
from jax import lax

from .regions import named_region
from .limbs import (
    MASK,
    NLIMB,
    RADIX,
    fe_add,
    fe_batch_inv,
    fe_canon,
    fe_inv,
    fe_is_zero,
    fe_is_zero_many,
    fe_mul,
    fe_mul_small,
    fe_sqr,
    fe_sub,
    int_to_limbs,
)

__all__ = [
    "G_X",
    "G_Y",
    "BETA",
    "LAMBDA",
    "GLV_WINDOWS",
    "jacobian_double",
    "jacobian_madd_complete",
    "jacobian_add_complete",
    "double_scalar_mult",
    "double_scalar_mult_glv",
    "double_scalar_mult_bits",
    "jacobian_to_affine",
    "scalar_bits",
]

G_X = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
G_Y = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8

# GLV endomorphism: beta^3 = 1 mod p, lambda^3 = 1 mod n, and
# lambda*(x, y) = (beta*x, y) (secp256k1/src/scalar_impl.h:60-112,
# field beta at secp256k1.c / util docs). The verify kernel splits the
# variable-base scalar b = b1 + lambda*b2 with |b1|,|b2| < 2^128
# (host-side, `crypto/glv.py`) and runs 32 4-bit windows instead of 64 —
# halving the doubling count, the dominant cost of the scalar mult.
BETA = 0x7AE96A2B657C07106E64479EAC3434E99CF0497512F58995C1396C28719501EE
LAMBDA = 0x5363AD4CC05C30E0A5261C028812645A122E22EA20816678DF02967C1B23BD72

_GX_LIMBS = int_to_limbs(G_X)
_GY_LIMBS = int_to_limbs(G_Y)
_BETA_LIMBS = int_to_limbs(BETA)
_ONE = int_to_limbs(1)

NBITS = NLIMB * RADIX  # 260 bit positions per scalar (top 4 always zero)
P_WINDOWS = 64
P_WINDOW_BITS = 4
G_WINDOWS = 32
G_WINDOW_BITS = 8


def _col(vec: np.ndarray, like):
    """Constant limb vector -> (20, 1, ..., 1) broadcastable column.

    Routed through `limb_const` so pallas kernels resolve it to a
    constant-table input instead of a captured jnp constant."""
    from .limbs import limb_const

    return limb_const(vec).reshape((NLIMB,) + (1,) * (like.ndim - 1))


@named_region("jacobian_double")
def jacobian_double(X, Y, Z):
    """Point doubling, dbl-2009-l for a=0; maps infinity to infinity."""
    A = fe_sqr(X)
    B = fe_sqr(Y)
    C = fe_sqr(B)
    D = fe_mul_small(fe_sub(fe_sqr(fe_add(X, B)), fe_add(A, C)), 2)
    E = fe_mul_small(A, 3)
    F = fe_sqr(E)
    X3 = fe_sub(F, fe_mul_small(D, 2))
    Y3 = fe_sub(fe_mul(E, fe_sub(D, X3)), fe_mul_small(C, 8))
    Z3 = fe_mul_small(fe_mul(Y, Z), 2)  # Z=0 -> Z3=0: infinity preserved
    return X3, Y3, Z3


def _select(mask, a3, b3):
    """Per-lane select between two point triples; mask shape (...,)."""
    m = mask[None]
    return tuple(jnp.where(m, x, y) for x, y in zip(a3, b3, strict=True))


def _inf_like(X):
    zeros = jnp.zeros_like(X)
    ones = jnp.broadcast_to(_col(_ONE, X), X.shape).astype(X.dtype)
    return ones, ones, zeros


def _madd_core(X1, Y1, Z1, x2, y2, inf1):
    """Generic madd-2007-bl formula + exceptional-case masks (the shared
    math of the complete and flagged mixed-add variants; one source so the
    two kernels cannot diverge). Returns (generic_triple, h_zero, r_zero,
    z1_zero, H) where z1_zero follows the inf1 convention (None ->
    computed, False -> statically finite, mask -> as given); H = U2 - X1
    satisfies Z3 = 2*Z1*H (the global-Z ratio callers may record)."""
    Z1Z1 = fe_sqr(Z1)
    U2 = fe_mul(x2, Z1Z1)
    S2 = fe_mul(y2, fe_mul(Z1, Z1Z1))
    H = fe_sub(U2, X1)
    Rsub = fe_sub(S2, Y1)
    if inf1 is None:
        h_zero, r_zero, z1_zero = fe_is_zero_many((H, Rsub, Z1))
    else:
        h_zero, r_zero = fe_is_zero_many((H, Rsub))
        z1_zero = inf1

    HH = fe_sqr(H)
    I = fe_mul_small(HH, 4)
    J = fe_mul(H, I)
    r = fe_mul_small(Rsub, 2)
    V = fe_mul(X1, I)
    X3 = fe_sub(fe_sqr(r), fe_add(J, fe_mul_small(V, 2)))
    Y3 = fe_sub(fe_mul(r, fe_sub(V, X3)), fe_mul_small(fe_mul(Y1, J), 2))
    Z3 = fe_sub(fe_sqr(fe_add(Z1, H)), fe_add(Z1Z1, HH))
    return (X3, Y3, Z3), h_zero, r_zero, z1_zero, H


def _madd_lift(out, X1, x2, y2, z1_zero):
    """Infinite-left-operand case: result is the lifted affine operand."""
    ones = jnp.broadcast_to(_col(_ONE, X1), X1.shape).astype(X1.dtype)
    lift = (jnp.broadcast_to(x2, X1.shape).astype(X1.dtype),
            jnp.broadcast_to(y2, X1.shape).astype(X1.dtype), ones)
    return _select(z1_zero, lift, out)


@named_region("jacobian_madd")
def jacobian_madd_complete(X1, Y1, Z1, x2, y2, inf1=None):
    """Complete mixed addition (X1,Y1,Z1) + (x2,y2), (x2,y2) affine and
    never infinity. Branchless handling of every exceptional case; the
    generic path is madd-2007-bl (the math of `secp256k1_gej_add_ge_var`,
    vectorized and de-branched).

    `inf1`: caller-known infinity status of the left operand — None
    computes the Z1 ≡ 0 field test (legacy), False asserts the operand is
    finite on every live lane, a mask uses it directly. Loop callers that
    track infinity explicitly skip one of the three exact-zero chains.
    """
    out, h_zero, r_zero, z1_zero, _H = _madd_core(X1, Y1, Z1, x2, y2, inf1)
    dbl = jacobian_double(X1, Y1, Z1)
    out = _select(h_zero & r_zero, dbl, out)
    out = _select(h_zero & ~r_zero, _inf_like(X1), out)
    if z1_zero is False:
        # Known-finite left operand: result is infinite only via P+(-P).
        return out + (h_zero & ~r_zero,)
    out = _madd_lift(out, X1, x2, y2, z1_zero)
    if inf1 is None:
        return out
    # inf1 given: also report the result's infinity (affine op is finite).
    return out + (~z1_zero & h_zero & ~r_zero,)


def _add_core(X1, Y1, Z1, X2, Y2, Z2, inf1):
    """Generic add-2007-bl formula + exceptional-case masks (shared by the
    complete and flagged Jacobian-add variants)."""
    Z1Z1 = fe_sqr(Z1)
    Z2Z2 = fe_sqr(Z2)
    U1 = fe_mul(X1, Z2Z2)
    U2 = fe_mul(X2, Z1Z1)
    S1 = fe_mul(Y1, fe_mul(Z2, Z2Z2))
    S2 = fe_mul(Y2, fe_mul(Z1, Z1Z1))
    H = fe_sub(U2, U1)
    Rsub = fe_sub(S2, S1)
    if inf1 is None:
        h_zero, r_zero, z1_zero = fe_is_zero_many((H, Rsub, Z1))
    else:
        h_zero, r_zero = fe_is_zero_many((H, Rsub))
        z1_zero = inf1

    I = fe_sqr(fe_mul_small(H, 2))
    J = fe_mul(H, I)
    r = fe_mul_small(Rsub, 2)
    V = fe_mul(U1, I)
    X3 = fe_sub(fe_sqr(r), fe_add(J, fe_mul_small(V, 2)))
    Y3 = fe_sub(fe_mul(r, fe_sub(V, X3)), fe_mul_small(fe_mul(S1, J), 2))
    Z3 = fe_mul(
        fe_sub(fe_sqr(fe_add(Z1, Z2)), fe_add(Z1Z1, Z2Z2)), H
    )
    return (X3, Y3, Z3), h_zero, r_zero, z1_zero


@named_region("jacobian_add")
def jacobian_add_complete(X1, Y1, Z1, X2, Y2, Z2, inf2, inf1=None):
    """Complete Jacobian+Jacobian addition (add-2007-bl), branchless.

    `inf2` is the caller-known infinity mask for the second operand (table
    entry 0), avoiding a field-level zero test on Z2. `inf1` (optional)
    does the same for the first operand — None computes the Z1 ≡ 0 test."""
    out, h_zero, r_zero, z1_zero = _add_core(X1, Y1, Z1, X2, Y2, Z2, inf1)
    dbl = jacobian_double(X1, Y1, Z1)
    out = _select(h_zero & r_zero, dbl, out)
    out = _select(h_zero & ~r_zero, _inf_like(X1), out)
    out = _select(z1_zero, (X2, Y2, Z2), out)
    out = _select(inf2, (X1, Y1, Z1), out)
    if inf1 is None:
        return out
    # Result infinity: both operands infinite, or finite cancellation.
    out_inf = (z1_zero & inf2) | (~z1_zero & ~inf2 & h_zero & ~r_zero)
    return out + (out_inf,)


def jacobian_madd_flagged(X1, Y1, Z1, x2, y2, inf1):
    """Mixed addition WITHOUT the embedded doubling fallback: the
    equal-points case (h ≡ 0, r ≡ 0) is only FLAGGED (`needs_dbl`), not
    computed — callers defer flagged lanes to the exact host path. Saves
    the jacobian_double (+selects) that `jacobian_madd_complete` pays on
    every call for a case honest traffic never hits (R == ±table point
    requires a crafted scalar collision). Same `_madd_core` math as the
    complete variant. `inf1` is the caller-tracked infinity mask of the
    left operand (or False when statically finite). Returns
    (X, Y, Z, out_inf, needs_dbl)."""
    out, h_zero, r_zero, z1_zero, _H = _madd_core(X1, Y1, Z1, x2, y2, inf1)
    out = _select(h_zero & ~r_zero, _inf_like(X1), out)
    if z1_zero is False:
        # Caller-asserted finite left operand: no lift select needed.
        return out + (h_zero & ~r_zero, h_zero & r_zero)
    out = _madd_lift(out, X1, x2, y2, z1_zero)
    out_inf = ~z1_zero & h_zero & ~r_zero
    needs_dbl = ~z1_zero & h_zero & r_zero
    return out + (out_inf, needs_dbl)


def jacobian_madd_flagged_ratio(X1, Y1, Z1, x2, y2, inf1=False):
    """`jacobian_madd_flagged` that also returns the Z-ratio
    ``Z3/Z1 = 2H`` (madd-2007-bl: Z3 = (Z1+H)^2 - Z1Z1 - HH = 2*Z1*H).
    The per-lane table build records these ratios so the whole table can
    be renormalized to the LAST entry's Z with multiplications only — the
    reference's effective-affine/global-Z trick
    (`secp256k1/src/ecmult_impl.h:61-136` odd-multiples table +
    `secp256k1_ge_table_set_globalz`) — no field inversion. Exceptional
    lanes (h ≡ 0) produce a meaningless ratio; callers defer those lanes
    to the host via the needs flag, so the garbage never reaches a
    verdict. Returns (X, Y, Z, out_inf, needs_dbl, ratio)."""
    out, h_zero, r_zero, z1_zero, H = _madd_core(X1, Y1, Z1, x2, y2, inf1)
    ratio = fe_mul_small(H, 2)
    out = _select(h_zero & ~r_zero, _inf_like(X1), out)
    if z1_zero is False:
        return out + (h_zero & ~r_zero, h_zero & r_zero, ratio)
    out = _madd_lift(out, X1, x2, y2, z1_zero)
    out_inf = ~z1_zero & h_zero & ~r_zero
    needs_dbl = ~z1_zero & h_zero & r_zero
    return out + (out_inf, needs_dbl, ratio)


def jacobian_add_flagged(X1, Y1, Z1, X2, Y2, Z2, inf2, inf1):
    """Jacobian+Jacobian addition without the doubling fallback (see
    jacobian_madd_flagged); same `_add_core` math as the complete variant.
    `inf2`/`inf1`: caller-tracked infinity masks. Returns
    (X, Y, Z, out_inf, needs_dbl)."""
    out, h_zero, r_zero, z1_zero = _add_core(X1, Y1, Z1, X2, Y2, Z2, inf1)
    out = _select(h_zero & ~r_zero, _inf_like(X1), out)
    out = _select(z1_zero, (X2, Y2, Z2), out)
    out = _select(inf2, (X1, Y1, Z1), out)
    out_inf = (z1_zero & inf2) | (~z1_zero & ~inf2 & h_zero & ~r_zero)
    needs_dbl = ~z1_zero & ~inf2 & h_zero & r_zero
    return out + (out_inf, needs_dbl)


def scalar_bits(limbs):
    """(20, ...) scalar limbs -> (260, ...) bits, LSB first."""
    shifts = jnp.arange(RADIX, dtype=jnp.int32).reshape(
        (1, RADIX) + (1,) * (limbs.ndim - 1)
    )
    bits = (limbs[:, None] >> shifts) & 1
    return bits.reshape((NBITS,) + limbs.shape[1:])


def _digits(limbs, width: int, count: int):
    """(20, ...) scalar limbs -> (count, ...) window digits, LSB first."""
    bits = scalar_bits(limbs)[:256]
    b = bits.reshape((count, width) + limbs.shape[1:])
    weights = jnp.asarray([1 << i for i in range(width)], dtype=jnp.int32)
    weights = weights.reshape((1, width) + (1,) * (limbs.ndim - 1))
    return jnp.sum(b * weights, axis=1)


_GTABLE = None


def _g_table():
    """(32, 255, 20) x2 affine G window table. Cached as numpy (host) so no
    traced value ever leaks into the cache; jnp conversion happens at the
    use site inside whatever trace is active."""
    global _GTABLE
    if _GTABLE is None:
        path = os.path.join(os.path.dirname(__file__), "_gtable8.npz")
        if os.path.exists(path):
            data = np.load(path)
            gx, gy = data["gx"], data["gy"]
        else:  # slow fallback: regenerate (deterministic)
            from .gen_gtable import build_tables

            gx, gy = build_tables()
        _GTABLE = (np.asarray(gx), np.asarray(gy))
    return jnp.asarray(_GTABLE[0]), jnp.asarray(_GTABLE[1])


def _fixed_base_mult(a_digits):
    """RG = a·G from 8-bit window digits (32, B): 32 complete madds, no
    doublings. The per-window row select is an exact f32 matmul
    (one-hot (255, B) against the (255, 20) window table): 13-bit limbs
    are exact in f32, and the contraction feeds the MXU instead of
    per-lane gathers."""
    gx_t, gy_t = _g_table()
    gx_f = gx_t.astype(jnp.float32)  # (32, 255, 20)
    gy_f = gy_t.astype(jnp.float32)
    k255 = jnp.arange(1, 256, dtype=jnp.int32)[:, None]  # (255, 1)

    def body(i, carry):
        X, Y, Z, rg_inf = carry
        da = a_digits[i]  # (B,)
        oh = (da[None, :] == k255).astype(jnp.float32)  # (255, B)
        gxw = lax.dynamic_index_in_dim(gx_f, i, axis=0, keepdims=False)
        gyw = lax.dynamic_index_in_dim(gy_f, i, axis=0, keepdims=False)
        # Precision.HIGHEST is load-bearing: the TPU MXU lowers default-
        # precision f32 dots to bfloat16 passes (8-bit mantissa), which
        # silently truncates 13-bit limbs.
        selx = jnp.dot(gxw.T, oh, preferred_element_type=jnp.float32,
                       precision=lax.Precision.HIGHEST)
        sely = jnp.dot(gyw.T, oh, preferred_element_type=jnp.float32,
                       precision=lax.Precision.HIGHEST)
        selx = selx.astype(jnp.int32)  # (20, B), exact
        sely = sely.astype(jnp.int32)
        Xa, Ya, Za, inf_a = jacobian_madd_complete(
            X, Y, Z, selx, sely, inf1=rg_inf
        )
        app = da > 0
        out = _select(app, (Xa, Ya, Za), (X, Y, Z))
        return out + (jnp.where(app, inf_a, rg_inf),)

    zeros = jnp.zeros_like(a_digits[0])
    inf = _inf_like(zeros[None].repeat(NLIMB, axis=0))
    all_inf = jnp.ones(a_digits.shape[1:], dtype=bool)
    X, Y, Z, rg_inf = lax.fori_loop(0, G_WINDOWS, body, inf + (all_inf,))
    return (X, Y, Z), rg_inf


def _p_table(px, py):
    """Per-lane Jacobian table T[k] = k·P, k = 0..15, via a 14-step scan
    (T[0] = infinity, T[1] = P). Returns (16, 20, B) coord stacks."""
    ones = jnp.broadcast_to(_col(_ONE, px), px.shape).astype(px.dtype)
    inf = _inf_like(px)

    def step(carry, _):
        # carry = k·P, k >= 1 — never infinity for on-curve P (order n
        # >> 16), so the Z1 exact test is skipped (inf1=False).
        *nxt, _cancel = jacobian_madd_complete(*carry, px, py, inf1=False)
        nxt = tuple(nxt)
        return nxt, nxt

    _, tail = lax.scan(step, (px, py, ones), None, length=14)
    TX = jnp.concatenate([inf[0][None], px[None], tail[0]], axis=0)
    TY = jnp.concatenate([inf[1][None], py[None], tail[1]], axis=0)
    TZ = jnp.concatenate([inf[2][None], ones[None], tail[2]], axis=0)
    return TX, TY, TZ


@named_region("scalar_mult")
def double_scalar_mult(a, b, px, py):
    """R = a·G + b·P per lane (the ECDSA/Schnorr verify hot kernel).

    `a`, `b`: (20, ...) scalar limb vectors, **reduced mod n** (the group
    order; the final join assumes a·G is infinite iff a ≡ 0). `px`, `py`:
    (20, ...) affine point, never infinity (the host substitutes a dummy
    for invalid lanes and masks them). Returns a Jacobian triple.

    Schedule per lane: 14 madds (P table, lax.scan) + 64x(4 doublings +
    1 complete J-add) + 32 G madds (MXU-select) + 1 final join.
    """
    digits_b = _digits(b, P_WINDOW_BITS, P_WINDOWS)  # (64, B)
    digits_a = _digits(a, G_WINDOW_BITS, G_WINDOWS)  # (32, B)

    TX, TY, TZ = _p_table(px, py)
    k16 = jnp.arange(16, dtype=jnp.int32).reshape((16,) + (1,) * px.ndim)

    def body(i, R):
        w = P_WINDOWS - 1 - i
        R = jacobian_double(*R)
        R = jacobian_double(*R)
        R = jacobian_double(*R)
        R = jacobian_double(*R)
        db = digits_b[w]  # (B,)
        oh = (db[None] == k16).astype(jnp.int32)  # (16, 1, B)
        selx = jnp.sum(TX * oh, axis=0)
        sely = jnp.sum(TY * oh, axis=0)
        selz = jnp.sum(TZ * oh, axis=0)
        return jacobian_add_complete(*R, selx, sely, selz, db == 0)

    R = lax.fori_loop(0, P_WINDOWS, body, _inf_like(px))
    RG, rg_inf = _fixed_base_mult(digits_a)
    return jacobian_add_complete(*R, *RG, rg_inf)


GLV_WINDOWS = 32  # 4-bit windows over the 128-bit split halves


def _digits128(limbs10, count: int = GLV_WINDOWS, width: int = P_WINDOW_BITS):
    """(10, ...) limb vector of a < 2^130 value -> (count, ...) 4-bit
    window digits, LSB first (only bits 0..count*width-1 are consumed)."""
    shifts = jnp.arange(RADIX, dtype=jnp.int32).reshape(
        (1, RADIX) + (1,) * (limbs10.ndim - 1)
    )
    bits = ((limbs10[:, None] >> shifts) & 1).reshape(
        (10 * RADIX,) + limbs10.shape[1:]
    )[: count * width]
    b = bits.reshape((count, width) + limbs10.shape[1:])
    weights = jnp.asarray([1 << i for i in range(width)], dtype=jnp.int32)
    weights = weights.reshape((1, width) + (1,) * (limbs10.ndim - 1))
    return jnp.sum(b * weights, axis=1)


@named_region("scalar_mult")
def double_scalar_mult_glv(a, db1, db2, neg1, neg2, px, py):
    """R = a·G + (±b1 + lambda·(±b2))·P with the GLV-split schedule.

    `a`: (20, ...) scalar limbs (reduced mod n). `db1`, `db2`:
    (32, ...) 4-bit window digits of |b1|, |b2| < 2^128. `neg1`, `neg2`:
    (...,) bool — negate the respective half (the split yields signed
    halves; -P = (x, -y)). `px`, `py`: affine P, never infinity.

    Schedule per lane: 14 madds (shared table) + 32x(4 doublings + 2
    complete adds + 1 beta-mul + y-negates) + 32 G madds + join — the
    endomorphism halves the 256 doublings of the non-GLV ladder
    (reference precedent: secp256k1_scalar_split_lambda + ecmult's
    wnaf_lam track, ecmult_impl.h:446-559 with USE_ENDOMORPHISM).
    """
    digits_a = _digits(a, G_WINDOW_BITS, G_WINDOWS)

    TX, TY, TZ = _p_table(px, py)
    beta = jnp.broadcast_to(_col(_BETA_LIMBS, px), px.shape).astype(px.dtype)
    k16 = jnp.arange(16, dtype=jnp.int32).reshape((16,) + (1,) * px.ndim)
    n1 = neg1[None]
    n2 = neg2[None]

    def body(i, carry):
        # R's infinity is tracked explicitly across the loop: the adds
        # skip the Z1 ≡ 0 exact test and report the result's status.
        X, Y, Z, r_inf = carry
        R = (X, Y, Z)
        w = GLV_WINDOWS - 1 - i
        R = jacobian_double(*R)  # doublings preserve infinity
        R = jacobian_double(*R)
        R = jacobian_double(*R)
        R = jacobian_double(*R)
        d1 = db1[w]
        oh = (d1[None] == k16).astype(jnp.int32)
        sx = jnp.sum(TX * oh, axis=0)
        sy = jnp.sum(TY * oh, axis=0)
        sz = jnp.sum(TZ * oh, axis=0)
        sy = jnp.where(n1, fe_sub(jnp.zeros_like(sy), sy), sy)
        *R, r_inf = jacobian_add_complete(*R, sx, sy, sz, d1 == 0, inf1=r_inf)
        d2 = db2[w]
        oh = (d2[None] == k16).astype(jnp.int32)
        sx = fe_mul(jnp.sum(TX * oh, axis=0), beta)  # lambda*(x,y)=(bx,y)
        sy = jnp.sum(TY * oh, axis=0)
        sz = jnp.sum(TZ * oh, axis=0)
        sy = jnp.where(n2, fe_sub(jnp.zeros_like(sy), sy), sy)
        X, Y, Z, r_inf = jacobian_add_complete(*R, sx, sy, sz, d2 == 0, inf1=r_inf)
        return X, Y, Z, r_inf

    all_inf = jnp.ones(px.shape[1:], dtype=bool)
    R = lax.fori_loop(0, GLV_WINDOWS, body, _inf_like(px) + (all_inf,))
    X, Y, Z, r_inf = R
    RG, rg_inf = _fixed_base_mult(digits_a)
    X, Y, Z, out_inf = jacobian_add_complete(
        X, Y, Z, *RG, rg_inf, inf1=r_inf
    )
    return X, Y, Z, out_inf


def double_scalar_mult_bits(a, b, px, py):
    """Naive 256-step bitwise ladder; kept as an independent reference
    schedule for differential tests against the windowed kernel."""
    bits_a = scalar_bits(a)
    bits_b = scalar_bits(b)
    gx = jnp.broadcast_to(_col(_GX_LIMBS, px), px.shape).astype(px.dtype)
    gy = jnp.broadcast_to(_col(_GY_LIMBS, py), py.shape).astype(py.dtype)

    def body(i, R):
        t = 255 - i
        R = jacobian_double(*R)
        Ra = jacobian_madd_complete(*R, gx, gy)
        R = _select(bits_a[t] == 1, Ra, R)
        Rb = jacobian_madd_complete(*R, px, py)
        R = _select(bits_b[t] == 1, Rb, R)
        return R

    return lax.fori_loop(0, 256, body, _inf_like(px))


@named_region("to_affine")
def jacobian_to_affine(X, Y, Z, inf=None):
    """(X, Y, Z) -> (x, y, is_infinity) with x, y canonical in [0, p).

    (20, B) batches share one Montgomery-trick inversion across the batch
    (fe_batch_inv, ~4 muls/lane); other shapes fall back to per-lane
    Fermat. Infinity lanes return x = y = 0. `inf` (optional) is a
    caller-tracked infinity mask, replacing the Z ≡ 0 exact test."""
    if inf is None:
        inf = fe_is_zero(Z)
    if Z.ndim == 2:
        zi = fe_batch_inv(Z, inf)
    else:
        zi = fe_inv(Z)
    zi2 = fe_sqr(zi)
    x = fe_canon(fe_mul(X, zi2))
    y = fe_canon(fe_mul(Y, fe_mul(zi2, zi)))
    return x, y, inf
