"""Batched 256-bit field arithmetic mod p (secp256k1) for TPU — limb-major.

Design (TPU-first, not a port). A field element is 20 little-endian limbs
in radix 2^13, dtype int32, **limb axis first**: shape ``(20, ...)`` with
the batch in the trailing (lane) axes. Two hardware facts drive the layout
and the carry scheme:

- The VPU operates on (8, 128) tiles with the *last* dimension mapped to
  128 lanes. Batch-last means every elementwise op runs at full lane
  occupancy; the tiny 20-limb axis lives in the sublane dimension. (The
  transposed layout — limbs last — wastes 108/128 lanes on every op.)
- There is no 64-bit multiplier. A 13x13-bit product is < 2^26 and a
  20-term schoolbook column sums to < 2^31, so every intermediate of a
  256-bit multiply fits a signed int32 lane. The reference proves the
  same idea at different widths (its 32-bit build uses 10x26 field limbs,
  `secp256k1/src/field_10x26_impl.h`); we shrink the radix so whole
  products fit one lane and vectorize over the batch instead of time.

Carry handling is *parallel only* — there are no sequential per-limb
chains anywhere in the hot path:

- `_pass` ships every limb's carry one position up simultaneously and
  wraps the carry out of limb 19 back into limbs 0..2 via
  2^260 ≡ 16C (mod p), C = 2^32 + 977 (16C = 2^36 + 15632, the 3-limb
  constant [7440, 1, 1024] in radix 2^13) — the pseudo-Mersenne
  wrap-around pass.
- Exactness (canonicalization, zero tests) uses a Kogge-Stone
  carry-lookahead: generate/propagate per limb, log2(20) combine steps,
  all whole-array ops.

Alongside the traced arrays every routine tracks static Python-int
per-limb upper bounds, so pass counts and fold rounds are fixed at trace
time and int32 overflow-freedom is checked by construction.

Representation invariant ("weak"): per-limb bounds `W2` (the fixpoint of
the wrap-around pass): limb 0 ≤ 2^13-1+7440, limb 1 ≤ 2^13+1,
limb 2 ≤ 2^13+1024, limbs 3..19 ≤ 2^13. All public ops accept and return
weak elements; `fe_canon` produces the unique representative in [0, p).

Spec source: the reference's field semantics (`secp256k1/src/field_*_impl.h`)
— behavior only; layout and algorithms here are TPU designs.
"""

from __future__ import annotations

import threading
from typing import List, Sequence, Tuple

import numpy as np

import jax.numpy as jnp

from .regions import named_region

__all__ = [
    "NLIMB",
    "RADIX",
    "MASK",
    "P_INT",
    "W2",
    "int_to_limbs",
    "limbs_to_int",
    "fe_add",
    "fe_sub",
    "fe_mul",
    "fe_sqr",
    "fe_mul_small",
    "fe_canon",
    "fe_is_zero",
    "fe_is_zero_many",
    "fe_eq",
    "fe_inv",
    "fe_batch_inv",
    "fe_pow_const",
    "fe_sqrt",
    "ints_to_limbs_batch",
]

NLIMB = 20
RADIX = 13
MASK = (1 << RADIX) - 1

P_INT = 2**256 - 2**32 - 977
_C = 2**32 + 977  # 2^256 mod p
# 2^260 mod p = 16C = 2^36 + 15632 -> radix-2^13 limbs [7440, 1, 1024].
_FOLD260 = (7440, 1, 1024)

# Weak bounds: fixpoint of the wrap-around pass (see _pass). With carries
# <= 1 in steady state: limb0 <= MASK + 1*7440, limb1 <= MASK + 1 + 1,
# limb2 <= MASK + 1 + 1*1024, others <= MASK + 1.
W2 = [MASK + 7440, MASK + 2, MASK + 1025] + [MASK + 1] * (NLIMB - 3)

# Mul safety: every schoolbook column sum must fit int32.
for _k in range(2 * NLIMB - 1):
    _col = sum(
        W2[_i] * W2[_k - _i]
        for _i in range(max(0, _k - NLIMB + 1), min(NLIMB, _k + 1))
    )
    assert _col < 2**31, (_k, _col)
# Value bound: weak values are < 2^261 (single-carry wrap in _exact260).
assert sum(w << (RADIX * i) for i, w in enumerate(W2)) < 2**261


def int_to_limbs(x: int, n: int = NLIMB) -> np.ndarray:
    """Host helper: Python int -> little-endian radix-2^13 limb vector."""
    out = np.zeros(n, dtype=np.int32)
    for i in range(n):
        out[i] = x & MASK
        x >>= RADIX
    if x:
        raise ValueError("value does not fit limb vector")
    return out


def limbs_to_int(limbs) -> int:
    """Host helper: limb vector (FIRST axis) -> Python int."""
    arr = np.asarray(limbs, dtype=np.int64)
    return sum(int(v) << (RADIX * i) for i, v in enumerate(arr))


def ints_to_limbs_batch(vals) -> np.ndarray:
    """Vectorized host packing: list of ints (< 2^257) -> (n, 20) int32.

    Row-major (one row per value) because that is the natural host order;
    the device kernel transposes once at entry to the limb-major layout.
    """
    raw = b"".join(v.to_bytes(33, "little") for v in vals)
    nb = np.frombuffer(raw, dtype=np.uint8).reshape(-1, 33).astype(np.int64)
    limbs = np.empty((len(vals), NLIMB), dtype=np.int32)
    for i in range(NLIMB):
        bitpos = RADIX * i
        k, sh = bitpos >> 3, bitpos & 7
        window = nb[:, k] | (nb[:, k + 1] << 8) | (nb[:, k + 2] << 16)
        limbs[:, i] = (window >> sh) & MASK
    return limbs


_P_LIMBS = int_to_limbs(P_INT)

Bounds = List[int]

# Constant provider hook. Pallas kernels cannot capture array constants
# (they must arrive as kernel inputs), so the pallas wrapper installs a
# provider that resolves well-known (20,) limb vectors to rows of a
# constant-table input; the default inlines them as jnp constants (XLA).
# Thread-LOCAL: tracing runs on the calling thread, and a concurrent
# XLA trace on another thread must not see a Pallas trace's provider
# (concurrent verify_batch is part of the documented thread contract).
_CONST_PROVIDER = threading.local()


def limb_const(arr: np.ndarray):
    provider = getattr(_CONST_PROVIDER, "fn", None)
    if provider is not None:
        out = provider(arr)
        if out is not None:
            return out
    return jnp.asarray(arr)


def set_const_provider(fn):
    """Install (or clear, with None) this thread's provider; returns the
    previous one so callers can restore it (used by ops/pallas_kernel.py)."""
    prev = getattr(_CONST_PROVIDER, "fn", None)
    _CONST_PROVIDER.fn = fn
    return prev


def bytes_to_limbs(u8, nlimb: int = NLIMB):
    """Device-side unpack: (..., K) uint8 little-endian values -> limb-major
    (nlimb, ...) int32 (K*8 <= nlimb*RADIX; default 32 bytes -> 20 limbs).

    Transfers over the host->device link are the scarce resource (32 bytes
    per field instead of 80 bytes of pre-split limbs); the unpack is a
    handful of static gathers + shifts, so it runs where compute is cheap.
    """
    nbytes = u8.shape[-1]
    assert nbytes * 8 <= nlimb * RADIX
    x = u8.astype(jnp.int32)
    # Top limb windows may span past the last byte: zero-pad.
    pad_n = (RADIX * (nlimb - 1) >> 3) + 3 - nbytes
    if pad_n > 0:
        pad = jnp.zeros(x.shape[:-1] + (pad_n,), dtype=x.dtype)
        x = jnp.concatenate([x, pad], axis=-1)
    limbs = []
    for i in range(nlimb):
        bitpos = RADIX * i
        k, sh = bitpos >> 3, bitpos & 7
        window = x[..., k] | (x[..., k + 1] << 8) | (x[..., k + 2] << 16)
        limbs.append((window >> sh) & MASK)
    return jnp.stack(limbs, axis=0)


def _zeros_rows(x, n: int):
    return jnp.zeros((n,) + x.shape[1:], dtype=x.dtype)


def _cat_rows(parts):
    """Concatenate along the limb axis, dropping zero-row operands —
    Mosaic (pallas) rejects zero-sized vectors that XLA tolerates."""
    parts = [p for p in parts if p.shape[0] != 0]
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)


def _pad_rows(x, lo: int, hi: int):
    """x padded with `lo` zero rows below and `hi` above, as ONE lax.pad
    op — the convolutions pad every row product into the output width,
    and materializing the zeros as separate arrays + concatenate doubled
    the kernel's data-movement op count (see scripts/kernel_roofline.py
    `move_ops_per_lane`)."""
    if lo == 0 and hi == 0:
        return x
    from jax import lax

    cfg = [(lo, hi, 0)] + [(0, 0, 0)] * (x.ndim - 1)
    return lax.pad(x, jnp.zeros((), dtype=x.dtype), cfg)


def _pass(x, bounds: Bounds) -> Tuple[jnp.ndarray, Bounds]:
    """One parallel carry pass along the limb axis.

    At exactly NLIMB limbs the carry out of limb 19 wraps into limbs 0..2
    via 16C (value changes by a multiple of p only). With more limbs the
    top carry appends a column (folded later by _fold_high).
    """
    assert all(0 <= b < 2**31 for b in bounds)
    n = x.shape[0]
    c = x >> RADIX
    kept = x & MASK
    out = kept + _pad_rows(c[:-1], 1, 0)
    cb = [b >> RADIX for b in bounds]
    b2 = [min(bounds[0], MASK)] + [
        min(bounds[i], MASK) + cb[i - 1] for i in range(1, n)
    ]
    top = c[n - 1]
    if cb[-1] == 0:
        return out, b2
    if n == NLIMB:
        wrap = jnp.stack(
            [top * _FOLD260[0], top * _FOLD260[1], top * _FOLD260[2]], axis=0
        )
        out = out + _pad_rows(wrap, 0, NLIMB - 3)
        for j, f in enumerate(_FOLD260):
            b2[j] += cb[-1] * f
            assert b2[j] < 2**31
        return out, b2
    out = jnp.concatenate([out, top[None]], axis=0)
    b2.append(cb[-1])
    return out, b2


def _fold_high(x, bounds: Bounds) -> Tuple[jnp.ndarray, Bounds]:
    """Fold limbs at positions >= NLIMB down via 2^260 ≡ 16C."""
    n_hi = x.shape[0] - NLIMB
    assert n_hi > 0
    out_len = max(NLIMB, n_hi + len(_FOLD260) - 1)
    lo, hi = x[:NLIMB], x[NLIMB:]
    pad = out_len - NLIMB
    acc = _pad_rows(lo, 0, pad) if pad else lo
    b2 = bounds[:NLIMB] + [0] * pad
    for j, f in enumerate(_FOLD260):
        acc = acc + _pad_rows(hi * f, j, out_len - j - n_hi)
        for i in range(n_hi):
            b2[i + j] += bounds[NLIMB + i] * f
            assert b2[i + j] < 2**31
    return acc, b2


def _settled(bounds: Bounds) -> bool:
    return len(bounds) == NLIMB and all(b <= w for b, w in zip(bounds, W2, strict=True))


def _settle(x, bounds: Bounds):
    """Drive any nonnegative limb vector into weak (W2-bounded) form.

    Control flow depends only on the static bounds: the emitted op
    sequence is fixed at trace time. Pure parallel passes + folds — no
    sequential per-limb chains.
    """
    assert x.shape[0] == len(bounds)
    guard = 0
    while not _settled(bounds):
        guard += 1
        assert guard < 24, "settle failed to converge (static bounds bug)"
        if len(bounds) > NLIMB and all(
            b * _FOLD260[0] < 2**30 for b in bounds[NLIMB:]
        ):
            x, bounds = _fold_high(x, bounds)
        else:
            x, bounds = _pass(x, bounds)
    return x


@named_region("fe_add")
def fe_add(a, b):
    """a + b mod p (weak in, weak out)."""
    return _settle(a + b, [2 * w for w in W2])


_SUB_K = 32  # bias = 32p, encoded with every limb >= W2 (see below)


def _sub_bias_limbs() -> np.ndarray:
    """Encode 32p in 20 limbs with limb i >= W2[i], so a + bias - b is
    nonnegative per limb for any weak a, b (bias value ≡ 0 mod p)."""
    d = [int(v) for v in int_to_limbs(_SUB_K * P_INT, 21)]
    # Merge the top limb down (32p < 2^261 so limb 20 is tiny).
    d[19] += d[20] << RADIX
    d = d[:20]
    for i in range(NLIMB - 1):
        while d[i] < W2[i]:
            d[i] += 1 << RADIX
            d[i + 1] -= 1
    assert all(d[i] >= W2[i] for i in range(NLIMB)), d
    assert all(d[i] + W2[i] < 2**31 for i in range(NLIMB))
    assert sum(v << (RADIX * i) for i, v in enumerate(d)) == _SUB_K * P_INT
    return np.asarray(d, dtype=np.int32)


_SUB_BIAS = _sub_bias_limbs()
_SUB_BOUNDS = [int(d) + w for d, w in zip(_SUB_BIAS, W2, strict=True)]


@named_region("fe_sub")
def fe_sub(a, b):
    """a - b mod p (weak in/out): a + 32p(in >=W2-limb form) - b >= 0."""
    bias = limb_const(_SUB_BIAS).reshape((NLIMB,) + (1,) * (a.ndim - 1))
    return _settle(a + bias - b, list(_SUB_BOUNDS))


def fe_mul_small(a, k: int):
    """a * k mod p for a small static k (k * W2[0] must fit int32)."""
    assert 0 < k and k * W2[0] < 2**31
    return _settle(a * k, [w * k for w in W2])


def _conv_rows(a, b, bw: Bounds, aw: Bounds, nl: int = NLIMB):
    """Schoolbook convolution: out[k] = sum_{i+j=k} a[i]*b[j] over nl-limb
    operands."""
    out_len = 2 * nl - 1
    acc = None
    bounds = [0] * out_len
    for i in range(nl):
        row = a[i] * b  # (nl, ...) scaled by one limb of a
        padded = _pad_rows(row, i, out_len - i - nl)
        acc = padded if acc is None else acc + padded
        for j in range(nl):
            bounds[i + j] += aw[i] * bw[j]
    assert all(bv < 2**31 for bv in bounds)
    return acc, bounds


def _conv_rows_kara(a, b, aw: Bounds, bw: Bounds, nl: int):
    """One Karatsuba level over an nl-limb convolution whose TRUE weights
    are aw/bw (all columns of all three sub-convolutions provably below
    2^31 — only usable for real-weight operands, not for the wrapping
    sum-convolution of the outer level). nl must be even."""
    h = nl // 2
    alo, ahi = a[:h], a[h:nl]
    blo, bhi = b[:h], b[h:nl]
    z0, b0 = _conv_rows(alo, blo, bw[:h], aw[:h], nl=h)
    z2, b2 = _conv_rows(ahi, bhi, bw[h:nl], aw[h:nl], nl=h)
    S = None
    asum, bsum = alo + ahi, blo + bhi
    for i in range(h):
        row = asum[i] * bsum
        padded = _pad_rows(row, i, h - 1 - i)
        S = padded if S is None else S + padded
    z1b = _cross_bounds(aw, bw, h)
    return _kara_combine(z0, b0, z2, b2, S, z1b, h, 2 * nl - 1)


def _sqr_rows(a, aw: Bounds, nl: int):
    """Squaring convolution over nl limbs: diagonal once + doubled cross
    terms — ~45% fewer multiplies than the generic convolution."""
    out_len = 2 * nl - 1
    acc = None
    bounds = [0] * out_len
    a2 = a * 2
    for i in range(nl):
        hi = nl - i - 1
        diag = a[i : i + 1] * a[i : i + 1]
        # hi == 0 (last limb): the cross-term slice would be zero-size,
        # which Mosaic rejects — emit the diagonal alone.
        row = _cat_rows([diag, a[i] * a2[i + 1 : nl]]) if hi else diag
        padded = _pad_rows(row, 2 * i, out_len - 2 * i - 1 - hi)
        acc = padded if acc is None else acc + padded
        bounds[2 * i] += aw[i] * aw[i]
        for j in range(i + 1, nl):
            bounds[i + j] += 2 * aw[i] * aw[j]
    assert all(bv < 2**31 for bv in bounds)
    return acc, bounds


# Karatsuba split: 20 = 10 + 10. One level replaces the 400-product
# schoolbook convolution with three 100-product half-convolutions plus
# O(n) combines (~25% fewer per-lane ops where the kernel spends most of
# its time). Exactness under int32 WRAPPING: XLA int32 add/mul are
# two's-complement (exact mod 2^32); the sum-convolution S may exceed
# 2^31 and wrap, but z1 = S - z0 - z2 is computed mod 2^32 and its TRUE
# value (the cross convolution, statically bounded below 2^31 by the
# asserted bounds) is therefore recovered exactly. The assembled columns
# are sums of sub-convolution TAILS with HEADS, so their true bounds stay
# below 2^31 (asserted), keeping _settle's nonnegative-value semantics.
_KARA_LO = 10


def _cross_bounds(wa: Bounds, wb: Bounds, h: int) -> Bounds:
    """True per-column bounds of the CROSS convolution lo*hi + hi*lo —
    what z1 = S - z0 - z2 recovers exactly despite S wrapping."""
    z1b = [0] * (2 * h - 1)
    for i in range(h):
        for j in range(h):
            z1b[i + j] += wa[i] * wb[h + j] + wa[h + i] * wb[j]
    return z1b


def _kara_combine(z0, b0, z2, b2, S, z1_true_bounds, h: int, out_len: int):
    """Assemble z0 + (S - z0 - z2)<<(RADIX*h) + z2<<(RADIX*2h) with static
    bounds; returns (acc, bounds) shaped like an out_len-column
    convolution. Shared by both Karatsuba levels (fe_mul/fe_sqr outer,
    _conv_rows_kara inner) so the overflow bookkeeping lives once."""
    z1 = S - z0 - z2  # exact mod 2^32; true value bounded by z1_true_bounds
    for tb in z1_true_bounds:
        assert 0 <= tb < 2**31
    acc = _pad_rows(z0, 0, out_len - (2 * h - 1))
    acc = acc + _pad_rows(z1, h, out_len - h - (2 * h - 1))
    acc = acc + _pad_rows(z2, 2 * h, out_len - 2 * h - (2 * h - 1))
    bounds = [0] * out_len
    for k in range(2 * h - 1):
        bounds[k] += b0[k]
        bounds[k + h] += z1_true_bounds[k]
        bounds[k + 2 * h] += b2[k]
    assert all(bv < 2**31 for bv in bounds)
    return acc, bounds


@named_region("fe_mul")
def fe_mul(a, b):
    """a * b mod p (weak in, weak out): one-level Karatsuba over the limb
    convolution + parallel carry passes — the per-lane unit the whole
    verify kernel reduces to."""
    h = _KARA_LO
    alo, ahi = a[:h], a[h:]
    blo, bhi = b[:h], b[h:]
    wlo, whi = W2[:h], W2[h:]
    # The real-weight halves take a second Karatsuba level (their columns
    # stay provably below 2^31); the wrapping sum-convolution cannot.
    z0, b0 = _conv_rows_kara(alo, blo, wlo, wlo, nl=h)
    z2, b2 = _conv_rows_kara(ahi, bhi, whi, whi, nl=h)
    asum, bsum = alo + ahi, blo + bhi
    # The sum-convolution is inlined (NOT via _conv_rows) because its
    # columns may exceed 2^31 and wrap — which is exact mod 2^32, but
    # would trip _conv_rows's nonnegative static-bound assertion.
    S = None
    for i in range(h):
        padded = _pad_rows(asum[i] * bsum, i, h - 1 - i)
        S = padded if S is None else S + padded
    z1b = _cross_bounds(W2, W2, h)
    acc, bounds = _kara_combine(z0, b0, z2, b2, S, z1b, h, 2 * NLIMB - 1)
    return _settle(acc, bounds)


@named_region("fe_sqr")
def fe_sqr(a):
    """a^2 mod p: Karatsuba over the squaring convolution (three half
    squares; diagonals once, cross terms doubled)."""
    h = _KARA_LO
    alo, ahi = a[:h], a[h:]
    wlo, whi = W2[:h], W2[h:]
    z0, b0 = _sqr_rows(alo, wlo, h)
    z2, b2 = _sqr_rows(ahi, whi, h)
    asum = alo + ahi
    S = None
    a2 = asum * 2
    for i in range(h):
        hi = h - i - 1
        diag = asum[i : i + 1] * asum[i : i + 1]
        row = _cat_rows([diag, asum[i] * a2[i + 1 : h]]) if hi else diag
        padded = _pad_rows(row, 2 * i, 2 * h - 1 - 2 * i - 1 - hi)
        S = padded if S is None else S + padded
    z1b = _cross_bounds(W2, W2, h)
    acc, bounds = _kara_combine(z0, b0, z2, b2, S, z1b, h, 2 * NLIMB - 1)
    return _settle(acc, bounds)


# ---------------------------------------------------------------------------
# Exactness: Kogge-Stone carry lookahead (all whole-array ops).

_KS_MAX = (1 << (RADIX + 1)) - 2  # per-limb cap for single-bit carries


def _ks_exact(x):
    """Exact carry propagation for limbs <= _KS_MAX: returns (exact 13-bit
    limbs, carry-out of limb 19 in {0,1}). Kogge-Stone over the limb axis:
    g=generate, pr=propagate, log2(20)=5 combine steps."""
    g = (x > MASK).astype(jnp.int32)
    pr = (x == MASK).astype(jnp.int32)
    d = 1
    while d < NLIMB:
        gs = jnp.concatenate([_zeros_rows(g, d), g[:-d]], axis=0)
        ps = jnp.concatenate([_zeros_rows(pr, d), pr[:-d]], axis=0)
        g = g | (pr & gs)
        pr = pr & ps
        d *= 2
    cin = jnp.concatenate([_zeros_rows(g, 1), g[:-1]], axis=0)
    exact = (x + cin) & MASK
    return exact, g[NLIMB - 1]


def _exact_lt_2p(x, bounds: Bounds):
    """Weak-ish x -> exact 13-bit limbs of a value v ≡ x (mod p), v < 2p.

    Steps: settle into KS range -> KS (value < 2^261 so carry-out <= 1)
    -> fold carry-out and bits 256..259 via C multiples -> second KS.
    """
    while len(bounds) > NLIMB or any(b > _KS_MAX for b in bounds):
        if len(bounds) > NLIMB:
            x, bounds = _fold_high(x, bounds)
        else:
            x, bounds = _pass(x, bounds)
    assert sum(b << (RADIX * i) for i, b in enumerate(bounds)) < 2**261
    e, cout = _ks_exact(x)
    # v1 = e + cout*2^260; fold cout*2^260 ≡ cout*16C and the top 4 bits
    # of limb 19 (2^256..2^259) ≡ hi4*C = hi4*(977 + 64*2^26).
    hi4 = e[NLIMB - 1] >> 9
    top = e[NLIMB - 1] & 0x1FF
    f0 = e[0] + cout * _FOLD260[0] + hi4 * 977
    f1 = e[1] + cout * _FOLD260[1]
    f2 = e[2] + cout * _FOLD260[2] + hi4 * 64
    # f0 <= MASK+7440+14655, beyond the single-bit-carry KS range: absorb
    # its carry into f1 locally (one shift+add, still fully parallel).
    f1 = f1 + (f0 >> RADIX)
    f0 = f0 & MASK
    x2 = jnp.concatenate(
        [jnp.stack([f0, f1, f2], axis=0), e[3 : NLIMB - 1], top[None]], axis=0
    )
    # Bounds after absorb: f0<=MASK, f1<=MASK+1+3, f2<=MASK+1024+960.
    assert MASK + _FOLD260[1] + (MASK + _FOLD260[0] + 15 * 977) // (MASK + 1) <= _KS_MAX
    assert MASK + _FOLD260[2] + 15 * 64 <= _KS_MAX
    e2, cout2 = _ks_exact(x2)
    # v2 = (e - hi4*2^256) + hi4*C + cout*16C < 2^256 + 31C < 2p, and
    # < 2^260, so cout2 is structurally 0; e2 is exact.
    del cout2
    return e2


@named_region("fe_canon")
def fe_canon(a, bounds: Bounds = None):
    """Weak -> canonical representative in [0, p), exact 13-bit limbs."""
    e = _exact_lt_2p(a, list(W2) if bounds is None else list(bounds))
    # One conditional subtract-p via borrow lookahead: d = e - p limbwise;
    # borrow-in b satisfies the same prefix recurrence with
    # g = (d < 0), pr = (d == 0) on the negated difference domain.
    p = limb_const(_P_LIMBS).reshape((NLIMB,) + (1,) * (a.ndim - 1))
    d = e - p
    g = (d < 0).astype(jnp.int32)
    pr = (d == 0).astype(jnp.int32)  # zero diff propagates an incoming borrow
    dd = 1
    gg, pp = g, pr
    while dd < NLIMB:
        gs = jnp.concatenate([_zeros_rows(gg, dd), gg[:-dd]], axis=0)
        ps = jnp.concatenate([_zeros_rows(pp, dd), pp[:-dd]], axis=0)
        gg = gg | (pp & gs)
        pp = pp & ps
        dd *= 2
    bin_ = jnp.concatenate([_zeros_rows(gg, 1), gg[:-1]], axis=0)
    sub = (d - bin_) & MASK
    ge = gg[NLIMB - 1] == 0  # no net borrow -> e >= p
    return jnp.where(ge[None], sub, e)


@named_region("fe_is_zero")
def fe_is_zero(a, bounds: Bounds = None):
    """a ≡ 0 mod p? Returns (...,) bool (batch shape without limb axis)."""
    e = _exact_lt_2p(a, list(W2) if bounds is None else list(bounds))
    p = limb_const(_P_LIMBS).reshape((NLIMB,) + (1,) * (a.ndim - 1))
    return jnp.all(e == 0, axis=0) | jnp.all(e == p, axis=0)


def fe_is_zero_many(vals: Sequence):
    """Zero tests for k same-shape elements via one widened dispatch: the
    operands are concatenated along the lane axis so the lookahead runs
    once at k-fold width (cheaper than k narrow chains)."""
    k = len(vals)
    cat = jnp.concatenate(list(vals), axis=-1)
    z = fe_is_zero(cat)
    n = z.shape[-1] // k
    return tuple(z[..., i * n : (i + 1) * n] for i in range(k))


def fe_eq(a, b):
    """a ≡ b mod p? (weak inputs)"""
    return fe_is_zero(fe_sub(a, b))


def fe_pow_const(a, e: int):
    """a^e mod p for a static exponent (square-and-multiply under
    lax.scan; schedule fixed at trace time, graph stays tiny)."""
    from jax import lax

    bits = jnp.asarray([int(c) for c in bin(e)[2:]], dtype=jnp.int32)

    def body(acc, bit):
        acc = fe_sqr(acc)
        return jnp.where(bit == 1, fe_mul(acc, a), acc), None

    acc, _ = lax.scan(body, a, bits[1:])
    return acc


def _sqr_n(x, n: int):
    """n repeated squarings under fori_loop (body compiled once per call
    site — Mosaic-lowerable, unlike a scan with stacked outputs)."""
    from jax import lax

    if n == 0:
        return x
    if n == 1:
        return fe_sqr(x)
    return lax.fori_loop(0, n, lambda i, acc: fe_sqr(acc), x)


def fe_pow_runs(x, e: int):
    """x^e for a static exponent whose binary form has long 1-runs (both
    secp256k1 field exponents do: (p+1)/4 and p-2 are runs of 223 and 22
    ones plus a short tail). Addition-chain over run blocks: the same
    ~log2(e) squarings as the bit ladder but ~18 multiplies instead of
    popcount(e) ~ 223/239 — the multiply count is what the bit ladder
    wastes (`secp256k1/src/field_*_impl.h` uses the same structure; chain
    derived independently). Exponent bookkeeping is asserted at trace
    time, so a wrong chain cannot trace, let alone compile."""
    assert e > 0
    # rep[k] holds (value, exponent) with exponent == 2^k - 1.
    rep = {1: (x, 1)}

    def get_rep(k: int):
        if k not in rep:
            a = k // 2
            b = k - a
            va, ea = get_rep(a)
            vb, eb = get_rep(b)
            val = fe_mul(_sqr_n(va, b), vb)
            ee = (ea << b) + eb
            assert ee == (1 << k) - 1
            rep[k] = (val, ee)
        return rep[k]

    runs = []  # (bit, length), MSB-first
    for ch in bin(e)[2:]:
        bit = int(ch)
        if runs and runs[-1][0] == bit:
            runs[-1][1] += 1
        else:
            runs.append([bit, 1])
    assert runs[0][0] == 1
    acc, e_acc = get_rep(runs[0][1])
    pending = 0
    for bit, length in runs[1:]:
        if bit == 0:
            pending += length
            continue
        blk, eb = get_rep(length)
        acc = fe_mul(_sqr_n(acc, pending + length), blk)
        e_acc = (e_acc << (pending + length)) + eb
        pending = 0
    acc = _sqr_n(acc, pending)
    e_acc <<= pending
    assert e_acc == e, "power chain bookkeeping broke"
    return acc


@named_region("fe_inv")
def fe_inv(a):
    """a^(p-2) mod p (Fermat inverse; 0 -> 0).

    Scan-based ladder: ONE compiled body — the XLA-path form (CPU test
    compiles stay fast). The Pallas kernel uses `fe_inv_chain` instead
    (Mosaic cannot lower the scan, and compiles the chain's fori_loop
    bodies cheaply)."""
    return fe_pow_const(a, P_INT - 2)


def fe_inv_chain(a):
    """Addition-chain Fermat inverse (~18 muls instead of ~239): the
    Pallas-kernel form of fe_inv. Bit-identical results."""
    return fe_pow_runs(a, P_INT - 2)


def fe_batch_inv(a, zero_mask):
    """Per-lane inverse over a (20, B) batch via Montgomery's trick.

    Two associative scans of fe_mul along the batch axis (prefix and
    suffix products) plus ONE tiny Fermat inversion of the grand product:
    ~4 field muls per lane instead of ~500 (`inv_i = pre_{i-1} * suf_{i+1}
    * inv(total)`). This is the batch-axis analogue of the reference's
    batch-inverse pattern — the lanes already advance in lockstep, so the
    scan tree is log-depth whole-array work.

    `zero_mask` (B,) marks lanes whose input is ≡ 0 (they would zero the
    whole product); such lanes contribute 1 to the scans and return 0,
    preserving the fe_inv(0) = 0 convention.
    """
    from jax import lax

    one = jnp.zeros_like(a).at[0].set(1)
    aa = jnp.where(zero_mask[None], one, a)
    pre = lax.associative_scan(fe_mul, aa, axis=1)
    suf = jnp.flip(lax.associative_scan(fe_mul, jnp.flip(aa, 1), axis=1), 1)
    tinv = fe_inv(pre[:, -1:])  # (20, 1): one narrow Fermat chain
    left = jnp.concatenate([one[:, :1], pre[:, :-1]], axis=1)
    right = jnp.concatenate([suf[:, 1:], one[:, :1]], axis=1)
    out = fe_mul(fe_mul(left, right), jnp.broadcast_to(tinv, a.shape))
    return jnp.where(zero_mask[None], jnp.zeros_like(a), out)


def fe_sqrt(a):
    """Candidate square root a^((p+1)/4) (p ≡ 3 mod 4). The caller must
    check candidate^2 == a; for non-residues the candidate is garbage.
    Scan-based (XLA path); the Pallas kernel uses `fe_sqrt_chain`."""
    return fe_pow_const(a, (P_INT + 1) // 4)


def fe_sqrt_chain(a):
    """Addition-chain sqrt candidate (~18 muls instead of ~223): the
    Pallas-kernel form of fe_sqrt. Bit-identical results."""
    return fe_pow_runs(a, (P_INT + 1) // 4)
