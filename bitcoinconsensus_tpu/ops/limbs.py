"""Batched 256-bit field arithmetic mod p (secp256k1) for TPU.

Design (TPU-first, not a port): a field element is a vector of 20
little-endian limbs in radix 2^13, dtype int32, batched over arbitrary
leading axes — shape ``(..., 20)``. Why 13-bit limbs in int32:

- a 13x13-bit product is < 2^26 and a 20-term schoolbook convolution sums to
  < 20 * 2^26 < 2^31, so every intermediate of a full 256-bit multiply fits a
  *signed int32 lane* — int32 is the TPU VPU's native element type (TPU has
  no int64 multiplier; XLA would emulate it slowly).
- the reference proves the same idea at different widths: its 32-bit build
  uses 10x26 field limbs / 8x32 scalars (`secp256k1/src/field_10x26_impl.h`,
  `scalar_8x32_impl.h`); we shrink the radix further so whole products fit a
  single lane, and vectorize over the *batch* axis instead of over time.

Reduction uses p = 2^256 - C with C = 2^32 + 977, hence
2^260 ≡ 16C = 2^36 + 15632, which in radix 2^13 is the 3-limb constant
[7440, 1, 1024] — folding high limbs back down is a tiny convolution.

Carry handling is *parallel*: each pass ships every limb's carry one
position up simultaneously (a handful of whole-array ops), instead of a
sequential 20-step scan. Alongside the traced arrays every routine tracks
static Python-int per-limb upper bounds, so the number of passes, fold
rounds, and appended carry columns are all decided at trace time and int32
overflow-freedom is checked by construction (asserts on the bounds).

Representation invariant ("weak"): limbs 0..18 in [0, 2^13] (inclusive —
the parallel passes settle at <= 2^13, which still keeps convolutions
int32-safe), limb 19 in [0, 2^10], value < 3p, congruent to the element
mod p. All public ops accept and return weak elements; `fe_canon` produces
the unique representative in [0, p) with exact 13-bit limbs.

Spec source: the reference's field semantics (`secp256k1/src/field_*_impl.h`)
— behavior only; the layout and algorithms here are vectorized-TPU designs.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

import jax.numpy as jnp

__all__ = [
    "NLIMB",
    "RADIX",
    "MASK",
    "P_INT",
    "int_to_limbs",
    "limbs_to_int",
    "fe_add",
    "fe_sub",
    "fe_mul",
    "fe_sqr",
    "fe_mul_small",
    "fe_canon",
    "fe_is_zero",
    "fe_is_zero_pair",
    "fe_eq",
    "fe_inv",
    "fe_pow_const",
    "fe_sqrt",
    "ints_to_limbs_batch",
]

NLIMB = 20
RADIX = 13
MASK = (1 << RADIX) - 1
LIMB_SETTLE = MASK + 1  # parallel passes settle limbs at <= 2^13 (inclusive)

P_INT = 2**256 - 2**32 - 977
_C = 2**32 + 977  # 2^256 mod p
_16C = 16 * _C  # 2^260 mod p = 2^36 + 15632
# 16C as radix-2^13 limbs: 15632 = 1*8192 + 7440; 2^36 = 1024 * 2^26.
_FOLD260 = (7440, 1, 1024)
# Weak-form bounds (see _settle): limbs 0..18 <= 2^13, limb 19 <= 2^10.
_WEAK_BOUNDS = [LIMB_SETTLE] * (NLIMB - 1) + [1 << 10]


def int_to_limbs(x: int, n: int = NLIMB) -> np.ndarray:
    """Host helper: Python int -> little-endian radix-2^13 limb vector."""
    out = np.zeros(n, dtype=np.int32)
    for i in range(n):
        out[i] = x & MASK
        x >>= RADIX
    if x:
        raise ValueError("value does not fit limb vector")
    return out


def limbs_to_int(limbs) -> int:
    """Host helper: limb vector (last axis) -> Python int."""
    arr = np.asarray(limbs, dtype=np.int64)
    return sum(int(v) << (RADIX * i) for i, v in enumerate(arr))


_P_LIMBS = int_to_limbs(P_INT)


def _sub_bias_limbs() -> np.ndarray:
    """A 21-limb encoding of 32p whose limbs 0..19 are all >= 2^13.

    Used as the additive bias in fe_sub so every per-limb difference
    a_i + bias_i - b_i stays nonnegative (b_i <= 2^13 by the weak invariant),
    which keeps all carry passes nonnegative.
    """
    d = [int(v) for v in int_to_limbs(32 * P_INT, 21)]
    for i in range(NLIMB):
        if d[i] < LIMB_SETTLE:
            d[i] += 1 << RADIX
            d[i + 1] -= 1
    assert all(d[i] >= LIMB_SETTLE for i in range(NLIMB)) and d[20] >= 0
    assert sum(v << (RADIX * i) for i, v in enumerate(d)) == 32 * P_INT
    return np.asarray(d, dtype=np.int32)


_SUB_BIAS = _sub_bias_limbs()

Bounds = List[int]


def _total(bounds: Bounds) -> int:
    return sum(b << (RADIX * i) for i, b in enumerate(bounds))


def _pass(x, bounds: Bounds):
    """One parallel carry pass; may append one carry column."""
    assert all(0 <= b < 2**31 for b in bounds)
    c = x >> RADIX
    kept = x & MASK
    cb = [b >> RADIX for b in bounds]
    zero = jnp.zeros_like(c[..., :1])
    x2 = kept + jnp.concatenate([zero, c[..., :-1]], axis=-1)
    b2 = [min(bounds[0], MASK)] + [
        min(bounds[i], MASK) + cb[i - 1] for i in range(1, len(bounds))
    ]
    if cb[-1] > 0:
        x2 = jnp.concatenate([x2, c[..., -1:]], axis=-1)
        b2.append(cb[-1])
    return x2, b2


def _fold_high(x, bounds: Bounds):
    """Fold limbs >= position 20 via 2^260 ≡ 16C (3-limb convolution)."""
    n_hi = x.shape[-1] - NLIMB
    out_len = max(NLIMB, n_hi + len(_FOLD260) - 1)
    lo, hi = x[..., :NLIMB], x[..., NLIMB:]
    pad = out_len - NLIMB
    acc = jnp.concatenate([lo, jnp.zeros_like(x[..., :pad])], axis=-1) if pad else lo
    b2 = bounds[:NLIMB] + [0] * pad
    for j, c in enumerate(_FOLD260):
        zl = jnp.zeros_like(x[..., :j])
        zr = jnp.zeros_like(x[..., : out_len - j - n_hi])
        acc = acc + jnp.concatenate([zl, hi * c, zr], axis=-1)
        for i in range(n_hi):
            b2[i + j] += bounds[NLIMB + i] * c
    return acc, b2


_LOOSE = 1 << 15  # phase-A settling threshold; breaks the 2^13 carry fixpoint


def _settle(x, bounds: Bounds):
    """Drive any nonnegative limb vector into weak 20-limb form.

    All control flow depends only on the static bounds, so the op sequence is
    fixed at trace time. Phase A (parallel passes + 16C folds) shrinks to 20
    loosely-bounded limbs; phase B (short sequential chains) produces exact
    13-bit limbs and folds bits >= 256, restoring the weak invariant.
    """
    # Phase A: parallel. Loose threshold avoids the fixpoint where an
    # all-2^13 bound vector keeps regenerating a phantom carry column.
    guard = 0
    while x.shape[-1] > NLIMB or any(b > _LOOSE for b in bounds):
        guard += 1
        assert guard < 64, "settle failed to converge (static bounds bug)"
        if any(b > _LOOSE for b in bounds):
            x, bounds = _pass(x, bounds)
        else:
            x, bounds = _fold_high(x, bounds)
    # Phase B: one sequential exact carry over the 20 limbs (the only exact
    # absorber the parallel bound domain cannot replace), then fold the two
    # kinds of overflow — bits 256..259 of limb 19 via 2^256 ≡ C, and the
    # carry past limb 19 via 2^260 ≡ 16C — and absorb with a 5-step chain.
    # The top-fold runs *before* the carry-fold so the value stays < 3p
    # (2^256 + 15C + c*16C) with no second wrap.
    total = _total(bounds)
    c_max = total >> (RADIX * NLIMB)  # bound on the carry past limb 19
    assert c_max * 7440 < 2**31
    cols = []
    carry = None
    for i in range(NLIMB):
        v = x[..., i] if carry is None else x[..., i] + carry
        cols.append(v & MASK)
        carry = v >> RADIX
    hi4 = cols[19] >> 9
    cols[19] = cols[19] & 0x1FF
    cols[0] = cols[0] + hi4 * 977
    cols[2] = cols[2] + hi4 * 64
    if c_max > 0:
        for j, f in enumerate(_FOLD260):
            cols[j] = cols[j] + carry * f
    # Short chain: limbs 0..4; remaining carry <= 1 lands in limb 5, which
    # stays <= 2^13 (the weak invariant allows it).
    carry = None
    for i in range(5):
        v = cols[i] if carry is None else cols[i] + carry
        cols[i] = v & MASK
        carry = v >> RADIX
    cols[5] = cols[5] + carry
    return jnp.stack(cols, axis=-1)


def fe_add(a, b):
    """a + b mod p (weak in, weak out)."""
    return _settle(a + b, [2 * w for w in _WEAK_BOUNDS])


def fe_sub(a, b):
    """a - b mod p (weak in/out): a + (32p in >=2^13-limb form) - b >= 0."""
    bias = jnp.asarray(_SUB_BIAS)
    pad = jnp.zeros_like(a[..., :1])
    x = jnp.concatenate([a, pad], axis=-1) + bias - jnp.concatenate([b, pad], axis=-1)
    bounds = [w + int(d) for w, d in zip(_WEAK_BOUNDS + [0], _SUB_BIAS)]
    return _settle(x, bounds)


def fe_mul_small(a, k: int):
    """a * k mod p for a small static k (k * 2^13 must fit int32)."""
    assert 0 < k < 2**17
    return _settle(a * k, [w * k for w in _WEAK_BOUNDS])


def fe_mul(a, b):
    """a * b mod p (weak in, weak out). ~400 int32 MACs/lane + carries."""
    out_len = 2 * NLIMB - 1
    acc = None
    bounds = [0] * out_len
    for i in range(NLIMB):
        zl = jnp.zeros_like(a[..., :i])
        zr = jnp.zeros_like(a[..., : out_len - i - NLIMB])
        row = jnp.concatenate([zl, a[..., i : i + 1] * b, zr], axis=-1)
        acc = row if acc is None else acc + row
        for j in range(NLIMB):
            bounds[i + j] += _WEAK_BOUNDS[i] * _WEAK_BOUNDS[j]
    assert all(bv < 2**31 for bv in bounds)  # 20 * 2^26 < 2^31
    return _settle(acc, bounds)


def fe_sqr(a):
    """a^2 mod p."""
    return fe_mul(a, a)


def _exact_pass(x):
    """Sequential exact carry: weak input -> exact 13-bit limbs, same value.

    Weak values are < 2^260 so there is no carry out of limb 19.
    """
    cols = []
    carry = None
    for i in range(NLIMB):
        v = x[..., i] if carry is None else x[..., i] + carry
        cols.append(v & MASK)
        carry = v >> RADIX
    return jnp.stack(cols, axis=-1)


def _cond_sub_p(x):
    """One conditional subtract-p on exact-13-bit-limbed x."""
    p = jnp.asarray(_P_LIMBS)
    d = x - p
    cols = []
    borrow = None
    for i in range(NLIMB):
        v = d[..., i] if borrow is None else d[..., i] + borrow
        cols.append(v & MASK)
        borrow = v >> RADIX  # 0 or -1 (arithmetic shift)
    ge = borrow == 0  # no net borrow -> x >= p
    sub = jnp.stack(cols, axis=-1)
    return jnp.where(ge[..., None], sub, x)


def fe_canon(a):
    """Weak -> canonical representative in [0, p), exact 13-bit limbs.

    Weak values are < 3p, so two conditional subtractions suffice.
    """
    x = _exact_pass(a)
    x = _cond_sub_p(x)
    return _cond_sub_p(x)


_2P_LIMBS = int_to_limbs(2 * P_INT)


def _is_zero_exact(z):
    """Exact-13-bit-limbed z (value < 3p): is z ≡ 0 mod p?

    The exact representation is unique per value, so z ≡ 0 iff its limbs
    match 0, p, or 2p — no conditional subtractions needed.
    """
    p1 = jnp.asarray(_P_LIMBS)
    p2 = jnp.asarray(_2P_LIMBS)
    return (
        jnp.all(z == 0, axis=-1)
        | jnp.all(z == p1, axis=-1)
        | jnp.all(z == p2, axis=-1)
    )


def fe_is_zero(a):
    """a ≡ 0 mod p? Returns (...,) bool."""
    return _is_zero_exact(_exact_pass(a))


def fe_is_zero_pair(u, v):
    """(u ≡ 0, v ≡ 0) sharing one carry chain (group-op hot path)."""
    z = _is_zero_exact(_exact_pass(jnp.stack([u, v], axis=0)))
    return z[0], z[1]


def fe_is_zero_many(vals):
    """Zero tests for a sequence of elements, one shared carry chain."""
    z = _is_zero_exact(_exact_pass(jnp.stack(list(vals), axis=0)))
    return tuple(z[i] for i in range(len(vals)))


def fe_eq(a, b):
    """a ≡ b mod p? (weak inputs)"""
    return jnp.all(fe_canon(a) == fe_canon(b), axis=-1)


def fe_pow_const(a, e: int):
    """a^e mod p for a static exponent (square-and-multiply under lax.scan;
    the schedule is fixed at trace time and the graph stays tiny)."""
    from jax import lax

    bits = jnp.asarray([int(c) for c in bin(e)[2:]], dtype=jnp.int32)

    def body(acc, bit):
        acc = fe_sqr(acc)
        return jnp.where(bit == 1, fe_mul(acc, a), acc), None

    acc, _ = lax.scan(body, a, bits[1:])
    return acc


def fe_inv(a):
    """a^(p-2) mod p (Fermat inverse; 0 -> 0)."""
    return fe_pow_const(a, P_INT - 2)


def fe_sqrt(a):
    """Candidate square root a^((p+1)/4) (p ≡ 3 mod 4). The caller must
    check candidate^2 == a; for non-residues the candidate is garbage."""
    return fe_pow_const(a, (P_INT + 1) // 4)


def ints_to_limbs_batch(vals) -> np.ndarray:
    """Vectorized host packing: list of ints (< 2^257) -> (n, 20) int32."""
    raw = b"".join(v.to_bytes(33, "little") for v in vals)
    nb = np.frombuffer(raw, dtype=np.uint8).reshape(-1, 33).astype(np.int64)
    limbs = np.empty((len(vals), NLIMB), dtype=np.int32)
    for i in range(NLIMB):
        bitpos = RADIX * i
        k, sh = bitpos >> 3, bitpos & 7
        window = nb[:, k] | (nb[:, k + 1] << 8) | (nb[:, k + 2] << 16)
        limbs[:, i] = (window >> sh) & MASK
    return limbs
