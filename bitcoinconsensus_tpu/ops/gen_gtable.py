"""Generate the fixed-base G window table artifact (_gtable8.npz).

32 windows of 8 bits: window w holds the 255 affine multiples
k * (256^w * G), k = 1..255, as radix-2^13 limb vectors. This is the
TPU-era analogue of the reference's ecmult precomputation
(`secp256k1_ecmult_context_build`, `secp256k1/src/ecmult_impl.h:312-350`;
the reference's WINDOW_G=15 table is ~1 MiB for the same reason):
device-resident multiples of G so the fixed-base half of u1*G + u2*P
needs no doublings and only 32 table adds per lane. The per-window
one-hot select is an exact f32 matmul (limbs are 13-bit, well inside the
f32 mantissa) — MXU work, not VPU work.

Size: 2 x 32 x 255 x 20 int32 ≈ 1.3 MiB. Deterministic; regenerate with
`python -m bitcoinconsensus_tpu.ops.gen_gtable` (validated by tests).
"""

from __future__ import annotations

import os

import numpy as np

from ..crypto.secp_host import G, P, PointJ
from .limbs import NLIMB, int_to_limbs

WINDOWS = 32
WINDOW_BITS = 8
ENTRIES = (1 << WINDOW_BITS) - 1  # 255 (entry 0 = infinity, never stored)

ARTIFACT = os.path.join(os.path.dirname(__file__), "_gtable8.npz")


def _batch_to_affine(points):
    """Jacobian points -> affine via one Montgomery-trick inversion."""
    zs = [pt.Z for pt in points]
    prefix = []
    acc = 1
    for z in zs:
        acc = acc * z % P
        prefix.append(acc)
    inv = pow(acc, P - 2, P)
    out = [None] * len(points)
    for i in range(len(points) - 1, -1, -1):
        zi = inv * (prefix[i - 1] if i else 1) % P
        inv = inv * zs[i] % P
        zi2 = zi * zi % P
        out[i] = (points[i].X * zi2 % P, points[i].Y * zi2 * zi % P)
    return out


def build_tables():
    """Returns (gx, gy): (32, 255, 20) int32 limb arrays."""
    gx = np.zeros((WINDOWS, ENTRIES, NLIMB), dtype=np.int32)
    gy = np.zeros((WINDOWS, ENTRIES, NLIMB), dtype=np.int32)
    base = G
    for w in range(WINDOWS):
        jac = []
        acc = PointJ.infinity()
        for _ in range(ENTRIES):
            acc = acc.add(base)
            jac.append(acc)
        for k, (x, y) in enumerate(_batch_to_affine(jac)):
            gx[w, k] = int_to_limbs(x)
            gy[w, k] = int_to_limbs(y)
        base = acc.add(base)  # 256^{w+1}*G = 255*256^w*G + 256^w*G
    return gx, gy


def main() -> None:
    gx, gy = build_tables()
    np.savez_compressed(ARTIFACT, gx=gx, gy=gy)
    print(f"wrote {ARTIFACT} ({os.path.getsize(ARTIFACT)} bytes)")


if __name__ == "__main__":
    main()
