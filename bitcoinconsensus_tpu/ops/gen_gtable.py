"""Generate the fixed-base G window table artifact (_gtable.npz).

64 windows of 4 bits: window w holds the 15 affine multiples
k * (16^w * G), k = 1..15, as radix-2^13 limb vectors. This is the TPU-era
analogue of the reference's ecmult precomputation
(`secp256k1_ecmult_context_build`, `secp256k1/src/ecmult_impl.h:312-350`):
device-resident multiples of G so the fixed-base half of
u1*G + u2*P needs no doublings at all — 64 table adds per lane.

Size: 2 x 64 x 15 x 20 int32 ≈ 153 KiB. Deterministic; regenerate with
`python -m bitcoinconsensus_tpu.ops.gen_gtable` (validated by tests).
"""

from __future__ import annotations

import os

import numpy as np

from ..crypto.secp_host import G, PointJ
from .limbs import NLIMB, int_to_limbs

WINDOWS = 64
WINDOW_BITS = 4
ENTRIES = (1 << WINDOW_BITS) - 1  # 15 (entry 0 = infinity, never stored)

ARTIFACT = os.path.join(os.path.dirname(__file__), "_gtable.npz")


def build_tables():
    """Returns (gx, gy): (64, 15, 20) int32 limb arrays."""
    gx = np.zeros((WINDOWS, ENTRIES, NLIMB), dtype=np.int32)
    gy = np.zeros((WINDOWS, ENTRIES, NLIMB), dtype=np.int32)
    base = G
    for w in range(WINDOWS):
        acc = PointJ.infinity()
        for k in range(ENTRIES):
            acc = acc.add(base)
            aff = acc.to_affine()
            assert aff is not None  # k*16^w*G is never infinity (k < n)
            gx[w, k] = int_to_limbs(aff[0])
            gy[w, k] = int_to_limbs(aff[1])
        base = acc.add(base)  # 16^{w+1} * G = 15*16^w*G + 16^w*G
    return gx, gy


def main() -> None:
    gx, gy = build_tables()
    np.savez_compressed(ARTIFACT, gx=gx, gy=gy)
    print(f"wrote {ARTIFACT} ({os.path.getsize(ARTIFACT)} bytes)")


if __name__ == "__main__":
    main()
