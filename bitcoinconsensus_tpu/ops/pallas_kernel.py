"""Pallas TPU kernel for the batched a·G + b·P verify hot path.

Why this exists: the XLA lowering of the limb-arithmetic graph
(`ops/limbs.py` + `ops/curve.py`) leaves the ~4k field operations per lane
as many small HBM-roundtripping fused kernels — profiling attributes ~65%
of verify wall time to device compute that should be VPU-bound by two
orders of magnitude less. This kernel runs the ENTIRE scalar-mult +
accept-logic pipeline for a tile of lanes inside one `pallas_call`:
every intermediate lives in VMEM (a (20, TILE) field element is 40 KB;
the live set is a few MB against ~16 MB of VMEM), HBM traffic is exactly
the kernel inputs/outputs, and Mosaic compiles the loops without
unrolling (the 315 s XLA warmup problem).

The math is literally the same code — `fe_mul`, `jacobian_double`,
`jacobian_add_complete`, ... are pure jnp functions over (20, B) int32
arrays and are called here on VMEM-resident values. Differences from the
XLA path (`curve.double_scalar_mult` + `jax_backend._verify_kernel`):

- The final x-compare uses the reference's z²-scaled trick where
  possible, but lanes may also need R.y parity (Schnorr/taproot), so a
  per-lane Fermat inverse (all-lanes SPMD, ~10% of the scalar-mult cost)
  produces true affine coordinates — replacing the XLA path's
  cross-lane `fe_batch_inv` scan, which does not belong inside a tiled
  kernel.
- Window digits and the r+n secondary target are precomputed in the XLA
  preamble (`verify_tiles` below) — cheap fused gathers there, scalar
  noise here.

Spec: `secp256k1_ecmult` (`secp256k1/src/ecmult_impl.h:446-580`),
`secp256k1_ecdsa_sig_verify` x-compare (`ecdsa_impl.h:207-275`), BIP340
even-y rule (`modules/schnorrsig/main_impl.h:190-237`).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .regions import named_region
from .curve import (
    G_WINDOWS,
    G_WINDOW_BITS,
    _digits,
    _g_table,
    _inf_like,
    _select,
    jacobian_add_flagged,
    jacobian_double,
    jacobian_madd_flagged,
    jacobian_madd_flagged_ratio,
)
from .curve import _BETA_LIMBS, _GX_LIMBS, _GY_LIMBS, _ONE, _digits128
from .limbs import (
    MASK,
    NLIMB,
    P_INT,
    _P_LIMBS,
    _SUB_BIAS,
    bytes_to_limbs,
    fe_add,
    fe_canon,
    fe_inv_chain,
    fe_is_zero,
    fe_mul,
    fe_mul_small,
    fe_sqr,
    fe_sqrt_chain,
    fe_sub,
    int_to_limbs,
    set_const_provider,
)

__all__ = ["verify_tiles", "LANE_TILE", "FLAG_BOUNDS", "OK_BOUNDS"]

LANE_TILE = 512  # lanes per kernel instance (4 VPU lane groups)

# Input/output contract of `verify_tiles`, single-sourced here and
# consumed by analysis/registry (the prover assumes exactly this much of
# the flag operands and must re-derive the verdict bounds below). Keys
# are positional argument indices of `verify_tiles`.
FLAG_BOUNDS = {
    1: (0, 1),    # want_odd
    2: (-1, 1),   # parity_req: -1 = don't care, else required parity
    3: (0, 1),    # has_t2 (r+n secondary target exists)
    4: (0, 1),    # neg1
    5: (0, 1),    # neg2
}
OK_BOUNDS = (0, 1)  # both verdict vectors are 0/1 masks per lane

# Signed 5-bit windows over the 128-bit GLV halves: 26 windows of
# (5 doublings + 2 complete adds) instead of the XLA path's 32 x (4 + 2) —
# twelve fewer complete adds per lane for two extra doublings. Digits are
# recoded to [-16, 15] in the XLA preamble (_signed_digits128); the table
# holds {1..16}·P and signs negate the selected y.
SGLV_WINDOWS = 26
SGLV_WIDTH = 5


def _signed_digits128(limbs10):
    """(10, B) limbs of a value < 2^128 -> ((26, B) |digit|, (26, B) sign)
    with digit ∈ [-16, 15] and sum digit_i·32^i equal to the value. The
    top window never carries out (bits 125..127 + carry <= 8 < 16)."""
    raw = _digits128(limbs10, count=SGLV_WINDOWS, width=SGLV_WIDTH)

    def step(carry, w):
        t = w + carry
        neg = t >= 16
        return neg.astype(jnp.int32), jnp.where(neg, t - 32, t)

    _, ds = lax.scan(step, jnp.zeros_like(raw[0]), raw)
    return jnp.abs(ds), (ds < 0).astype(jnp.int32)

from ..crypto.secp_host import N as _N_INT  # noqa: E402 (cycle-free)

_SEVEN = int_to_limbs(7)
_N_LIMBS = int_to_limbs(_N_INT)

# Rows of the constant-table kernel input (pallas kernels cannot capture
# array constants; see limbs.set_const_provider).
_CONST_TABLE = np.stack(
    [_SEVEN, _ONE, _SUB_BIAS, _P_LIMBS, _BETA_LIMBS, _GX_LIMBS, _GY_LIMBS]
).astype(np.int32)
_CONST_ROWS = {
    _SEVEN.tobytes(): 0,
    _ONE.tobytes(): 1,
    np.asarray(_SUB_BIAS).tobytes(): 2,
    np.asarray(_P_LIMBS).tobytes(): 3,
    np.asarray(_BETA_LIMBS).tobytes(): 4,
    np.asarray(_GX_LIMBS).tobytes(): 5,
    np.asarray(_GY_LIMBS).tobytes(): 6,
}

def _const_col(vec, like):
    from .limbs import limb_const

    return jnp.broadcast_to(
        limb_const(vec).reshape((NLIMB,) + (1,) * (like.ndim - 1)), like.shape
    ).astype(like.dtype)


def _tile_batch_inv(Z, inf_mask, ones):
    """Montgomery batch inverse across the tile's lane axis.

    Hillis-Steele prefix/suffix fe_mul trees (log2(tile) whole-tile muls
    each, lanes shifted with jnp.roll) + ONE Fermat chain on the (20, 1)
    grand product + 2 muls per lane — replaces a 255-step per-lane chain
    with ~21 tile-wide muls. The in-kernel analogue of `fe_batch_inv`
    (whose lax.associative_scan does not lower in Mosaic). Infinity lanes
    contribute 1 and return garbage, masked by the caller.
    """
    T = Z.shape[-1]
    zz = jnp.where(inf_mask[None], ones, Z)
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, T), 1)
    pre = zz
    d = 1
    while d < T:
        pre = jnp.where(
            lane >= d, fe_mul(pre, jnp.roll(pre, d, axis=1)), pre
        )
        d *= 2
    suf = zz
    d = 1
    while d < T:
        suf = jnp.where(
            lane < T - d, fe_mul(suf, jnp.roll(suf, -d, axis=1)), suf
        )
        d *= 2
    # Fermat chain (addition-chain fe_inv) on the grand product at width
    # 128 (Mosaic mis-lowers field ops on width-1 vectors); only the last
    # lane is the real total.
    w = min(128, T)
    tinv_w = fe_inv_chain(pre[:, T - w :])
    tinv = tinv_w[:, w - 1 :]  # (20, 1)
    left = jnp.where(lane == 0, ones, jnp.roll(pre, 1, axis=1))
    right = jnp.where(lane == T - 1, ones, jnp.roll(suf, -1, axis=1))
    return fe_mul(fe_mul(left, right), jnp.broadcast_to(tinv, Z.shape))


def _kernel(
    px_ref,
    t1_ref,
    t1n_ref,
    da_ref,
    db1_ref,
    ds1_ref,
    db2_ref,
    ds2_ref,
    flags_ref,
    consts_ref,
    gx_ref,
    gy_ref,
    ok_ref,
    tx_ref,
    ty_ref,
):
    """One LANE_TILE-wide verify tile, entirely in VMEM.

    flags rows: 0=want_odd, 1=parity_req, 2=has_t2, 3=valid, 4=neg1,
    5=neg2. db/ds: signed-window digit magnitudes/signs (26, tile).
    tx/ty: (16, 20, tile) VMEM scratch for the global-Z-affine
    {1..16}·P table.
    """

    def provider(arr):
        a = np.asarray(arr)
        if a.shape != (NLIMB,):
            return None
        row = _CONST_ROWS.get(a.tobytes())
        return None if row is None else consts_ref[row]

    prev = set_const_provider(provider)
    try:
        _kernel_body(
            px_ref, t1_ref, t1n_ref, da_ref, db1_ref, ds1_ref, db2_ref,
            ds2_ref, flags_ref, gx_ref, gy_ref, ok_ref, tx_ref, ty_ref,
        )
    finally:
        set_const_provider(prev)


def _kernel_body(
    px_ref,
    t1_ref,
    t1n_ref,
    da_ref,
    db1_ref,
    ds1_ref,
    db2_ref,
    ds2_ref,
    flags_ref,
    gx_ref,
    gy_ref,
    ok_ref,
    tx_ref,
    ty_ref,
):
    px = px_ref[:]
    want_odd = flags_ref[0, :]
    parity_req = flags_ref[1, :]
    has_t2 = flags_ref[2, :]
    valid = flags_ref[3, :] != 0
    neg1i = flags_ref[4, :]
    neg2i = flags_ref[5, :]

    # -- lift P's y from (x, parity): y = sqrt(x^3 + 7), flip to parity --
    seven = _const_col(_SEVEN, px)
    rhs = fe_add(fe_mul(fe_sqr(px), px), seven)
    ycand = fe_canon(fe_sqrt_chain(rhs))
    sq_ok = fe_is_zero(fe_sub(fe_mul(ycand, ycand), rhs))
    odd = (ycand[0] & 1) == 1
    yneg = fe_sub(jnp.zeros_like(ycand), ycand)
    flip = odd != (want_odd == 1)
    py = jnp.where(flip[None], yneg, ycand)
    valid = valid & sq_ok
    # Sanitize invalid (off-curve) lanes to the generator: keeps the
    # explicitly-tracked infinity masks sound for every lane (see the
    # XLA kernel's matching comment); verdicts stay masked by `valid`.
    gxb = jnp.broadcast_to(_const_col(_GX_LIMBS, px), px.shape).astype(px.dtype)
    gyb = jnp.broadcast_to(_const_col(_GY_LIMBS, px), px.shape).astype(px.dtype)
    px = jnp.where(valid[None], px, gxb)
    py = jnp.where(valid[None], py, gyb)

    # -- per-lane table {1..16}·P, renormalized to a GLOBAL Z -----------
    # Row r holds (r+1)·P. Build is Jacobian (row 1 = explicit doubling,
    # rows 2..15 = FLAGGED mixed adds — kP == ±P is impossible for
    # 2 <= k <= 16, the flag is folded defensively), recording each
    # step's Z-ratio (Z_k = Z_{k-1} * ratio_k) in registers. A
    # multiplication-only walk then rescales every row to
    # the LAST row's Z — the reference's effective-affine/global-Z trick
    # (`ecmult_impl.h:61-136` + `secp256k1_ge_table_set_globalz`): the
    # whole window loop below runs on the isomorphic curve where the
    # table is AFFINE (mixed adds, no Z selects), and the result returns
    # to the true curve with ONE multiplication of its Z by global-Z.
    # (The a=0 double/add formulas never reference the curve constant, so
    # they are valid verbatim on the isomorphic curve.)
    ones = _const_col(_ONE, px)
    zero_i = jnp.zeros(px.shape[1:], dtype=jnp.int32)
    needs32 = zero_i
    # Statically-unrolled build (no dynamic VMEM indexing — Mosaic lowers
    # it poorly): rows go straight to scratch; only the 15 Z-ratios ride
    # registers.
    tx_ref[0], ty_ref[0] = px, py
    ratios = [None, fe_mul_small(py, 2)]  # Z_1 = 2*py*1 (Z_0 = 1)
    X, Y, Z = jacobian_double(px, py, ones)
    tx_ref[1], ty_ref[1] = X, Y
    for k in range(2, 16):
        X, Y, Z, _inf, ndbl, ratio = jacobian_madd_flagged_ratio(
            X, Y, Z, px, py, inf1=False
        )
        tx_ref[k], ty_ref[k] = X, Y
        ratios.append(ratio)
        needs32 = needs32 | ndbl.astype(jnp.int32)

    # Rescale rows 14..0 to row 15's Z: c_k = prod_{j=k+1..15} ratio_j;
    # global-Z = c after the walk absorbs ratio_1 (= Z_15).
    c = None
    for k in range(14, -1, -1):
        c = ratios[k + 1] if c is None else fe_mul(c, ratios[k + 1])
        c2 = fe_sqr(c)
        tx_ref[k] = fe_mul(tx_ref[k], c2)
        ty_ref[k] = fe_mul(ty_ref[k], fe_mul(c2, c))
    global_z = c
    TX, TY = tx_ref[:], ty_ref[:]

    # -- (±b1 ± lambda·b2)·P: 26 signed 5-bit windows of 5 doublings + 2
    # mixed adds against the global-Z-affine table (lambda*(x,y) =
    # (beta*x, y); digit signs xor the GLV half signs and negate the
    # selected y; zero digits keep R via the same select pattern as the
    # G loop).
    k16 = jax.lax.broadcasted_iota(jnp.int32, (16, 1, 1), 0) + 1
    beta = jnp.broadcast_to(
        _const_col(_BETA_LIMBS, px)[:, :1], px.shape
    ).astype(px.dtype)

    # Infinity and needs-host masks ride the fori_loop carries as int32
    # 0/1 — Mosaic cannot lower i1 vectors through loop boundaries.
    def madd_step(R, r_inf32, nh, d, sign, selx, sely):
        sely = jnp.where(
            sign == 1, fe_sub(jnp.zeros_like(sely), sely), sely
        )
        Xa, Ya, Za, inf_a, nd = jacobian_madd_flagged(
            *R, selx, sely, inf1=r_inf32 == 1
        )
        app = d > 0
        out = _select(app, (Xa, Ya, Za), R)
        r_inf32 = jnp.where(app, inf_a.astype(jnp.int32), r_inf32)
        nh = nh | jnp.where(app, nd.astype(jnp.int32), 0)
        return out, r_inf32, nh

    def wbody(i, carry):
        X, Y, Z, r_inf32, nh = carry
        R = (X, Y, Z)
        w = SGLV_WINDOWS - 1 - i
        R = jacobian_double(*R)  # doublings preserve infinity
        R = jacobian_double(*R)
        R = jacobian_double(*R)
        R = jacobian_double(*R)
        R = jacobian_double(*R)
        d1 = db1_ref[w]  # ref-indexed dynamic VMEM load, (tile,)
        s1 = (ds1_ref[w] ^ neg1i)[None]
        oh = (d1[None, None, :] == k16).astype(jnp.int32)  # (16, 1, T)
        selx = jnp.sum(TX * oh, axis=0)
        sely = jnp.sum(TY * oh, axis=0)
        R, r_inf32, nh = madd_step(R, r_inf32, nh, d1, s1, selx, sely)
        d2 = db2_ref[w]
        s2 = (ds2_ref[w] ^ neg2i)[None]
        oh = (d2[None, None, :] == k16).astype(jnp.int32)
        selx = fe_mul(jnp.sum(TX * oh, axis=0), beta)
        sely = jnp.sum(TY * oh, axis=0)
        R, r_inf32, nh = madd_step(R, r_inf32, nh, d2, s2, selx, sely)
        return R + (r_inf32, nh)

    all_inf = jnp.ones(px.shape[1:], dtype=jnp.int32)
    X, Y, Z, r_inf32, needs32 = lax.fori_loop(
        0, SGLV_WINDOWS, wbody, _inf_like(px) + (all_inf, needs32)
    )
    r_inf = r_inf32 == 1
    # Leave the isomorphic frame: true Z = Z * global-Z (infinity lanes
    # stay Z = 0; flagged lanes carry garbage that the needs mask hides).
    Z = fe_mul(Z, global_z)
    R = (X, Y, Z)

    # -- a·G: 32 windows, MXU one-hot row select against the VMEM table -
    # Table row j holds (j+1)·256^w·G: the one-hot compares against 1..255.
    k255 = jax.lax.broadcasted_iota(jnp.int32, (255, 1), 0) + 1

    def gbody(i, carry):
        Xg, Yg, Zg, rg_inf32, nh = carry
        rg_inf = rg_inf32 == 1
        da = da_ref[i]  # ref-indexed dynamic VMEM load, (tile,)
        oh = (da[None, :] == k255).astype(jnp.float32)  # (255, T)
        gxw = gx_ref[i]  # (255, 20) f32
        gyw = gy_ref[i]
        selx = jax.lax.dot_general(
            gxw, oh, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=lax.Precision.HIGHEST,
        ).astype(jnp.int32)  # (20, T); 13-bit limbs are exact in f32
        sely = jax.lax.dot_general(
            gyw, oh, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=lax.Precision.HIGHEST,
        ).astype(jnp.int32)
        Xa, Ya, Za, inf_a, nd = jacobian_madd_flagged(
            Xg, Yg, Zg, selx, sely, inf1=rg_inf
        )
        app = da > 0
        out = _select(app, (Xa, Ya, Za), (Xg, Yg, Zg))
        # int32 branch values: Mosaic cannot lower selects over i1 vectors.
        return out + (
            jnp.where(app, inf_a.astype(jnp.int32), rg_inf32),
            nh | jnp.where(app, nd.astype(jnp.int32), 0),
        )

    Xg, Yg, Zg, rg_inf32, needs32 = lax.fori_loop(
        0, G_WINDOWS, gbody, _inf_like(px) + (all_inf, needs32)
    )
    X, Y, Z, inf_mask, nd_join = jacobian_add_flagged(
        *R, Xg, Yg, Zg, rg_inf32 == 1, inf1=r_inf
    )
    needs = (needs32 | nd_join.astype(jnp.int32)) == 1
    needs = needs & valid  # invalid lanes never defer (sanitized to G)

    # -- affine + accept -------------------------------------------------
    # Deferred lanes carry garbage (often Z ≡ 0 from the skipped doubling
    # case) — they must contribute 1 to the cross-lane inversion product
    # exactly like infinity lanes, or they would zero EVERY lane's affine
    # coordinates (pinned by test_exceptional_case_deferred_to_host).
    zi = _tile_batch_inv(Z, inf_mask | needs, ones)
    zi2 = fe_sqr(zi)
    x = fe_canon(fe_mul(X, zi2))
    y = fe_canon(fe_mul(Y, fe_mul(zi2, zi)))

    ok_x = jnp.all(x == t1_ref[:], axis=0) | (
        (has_t2 == 1) & jnp.all(x == t1n_ref[:], axis=0)
    )
    y_odd = (y[0] & 1) == 1
    par_ok = (parity_req < 0) | (y_odd == (parity_req == 1))
    ok = valid & ~inf_mask & ok_x & par_ok & ~needs
    ok_ref[0, :] = ok.astype(jnp.int32)
    ok_ref[1, :] = needs.astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("tile", "interpret"))
@named_region("verify_tiles")
def verify_tiles(
    fields, want_odd, parity_req, has_t2, neg1, neg2, valid,
    tile=LANE_TILE, interpret=False,
):
    """Replacement for `jax_backend._verify_kernel` running the heavy math
    as a Pallas grid over lane tiles.

    fields: (B, 4, 32) uint8 LE (a, |b1|‖|b2|, px, t1); flag vectors (B,)
    int32 / bool. B must be a multiple of `tile`. Returns
    ``(ok, needs_host)`` — both (B,) bool. ``needs_host`` marks lanes that
    hit an exceptional group-law case the fast adds defer (crafted scalar
    collisions only; such lanes report ok=False and MUST be re-checked by
    the exact host path, which TpuSecpVerifier.verify_checks does).
    """
    B = fields.shape[0]
    assert B % tile == 0, (B, tile)

    # XLA preamble: byte unpack, window digits (signed 5-bit for the GLV
    # halves), r+n secondary target.
    a = bytes_to_limbs(fields[:, 0])
    b1 = bytes_to_limbs(fields[:, 1, :16], nlimb=10)  # GLV halves
    b2 = bytes_to_limbs(fields[:, 1, 16:], nlimb=10)
    px = bytes_to_limbs(fields[:, 2])
    t1 = bytes_to_limbs(fields[:, 3])
    da = _digits(a, G_WINDOW_BITS, G_WINDOWS)  # (32, B)
    db1, ds1 = _signed_digits128(b1)  # (26, B) each
    db2, ds2 = _signed_digits128(b2)
    nl = _const_col(_N_LIMBS, t1)
    # t1 ships RAW (exact 13-bit limbs from bytes): a target >= p must
    # never equal a canonical x, so it is NOT reduced. t1+n is only used
    # when has_t2 certifies r + n < p, where the canon is exact.
    t1n = fe_canon(t1 + nl, bounds=[2 * MASK] * NLIMB)
    flags = jnp.stack(
        [
            want_odd.astype(jnp.int32),
            parity_req.astype(jnp.int32),
            has_t2.astype(jnp.int32),
            valid.astype(jnp.int32),
            neg1.astype(jnp.int32),
            neg2.astype(jnp.int32),
        ],
        axis=0,
    )  # (6, B)

    gx, gy = _g_table()
    gx = gx.astype(jnp.float32)
    gy = gy.astype(jnp.float32)

    lane_block = lambda rows: pl.BlockSpec(  # noqa: E731
        (rows, tile), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    shared = lambda shape: pl.BlockSpec(  # noqa: E731
        shape, lambda i: (0,) * len(shape), memory_space=pltpu.VMEM
    )

    consts = jnp.asarray(_CONST_TABLE)

    ok = pl.pallas_call(
        _kernel,
        grid=(B // tile,),
        in_specs=[
            lane_block(NLIMB),  # px
            lane_block(NLIMB),  # t1 (raw)
            lane_block(NLIMB),  # t1 + n (canonical)
            lane_block(G_WINDOWS),  # da
            lane_block(SGLV_WINDOWS),  # db1 magnitudes
            lane_block(SGLV_WINDOWS),  # ds1 signs
            lane_block(SGLV_WINDOWS),  # db2 magnitudes
            lane_block(SGLV_WINDOWS),  # ds2 signs
            lane_block(6),  # flags
            shared(consts.shape),  # limb constant table
            shared(gx.shape),  # G window table x
            shared(gy.shape),  # G window table y
        ],
        out_specs=pl.BlockSpec((2, tile), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((2, B), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((16, NLIMB, tile), jnp.int32),  # P-table x
            pltpu.VMEM((16, NLIMB, tile), jnp.int32),  # P-table y
        ],
        interpret=interpret,
    )(px, t1, t1n, da, db1, ds1, db2, ds2, flags, consts, gx, gy)
    return ok[0] != 0, ok[1] != 0
