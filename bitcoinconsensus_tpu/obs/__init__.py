"""Consensus telemetry: metrics registry + span tracing + exposition.

The reference crate ships no tracing at all (SURVEY §5); its only
instrument here was the ad-hoc `Phases` wall-clock timer. This package is
the production observability layer the ROADMAP north-star requires:
attribution across the host→device boundary (host parse vs limb pack vs
XLA dispatch vs readback, sigcache hits vs deferred TPU resolves) with
zero external dependencies.

Three pieces:

- ``metrics`` — a process-global, thread-safe registry of counters,
  gauges and fixed-bucket histograms, all label-aware. Every layer of the
  verify pipeline registers its metrics at import time; `snapshot()` is a
  plain dict, cheap to diff across runs.
- ``spans`` — nestable context-manager spans with monotonic timestamps.
  Every span aggregates into the registry
  (`consensus_span_duration_seconds{span=...}`); when a JSONL sink is
  attached each span additionally emits one JSON line (trace mode). With
  no sink attached the cost is two `perf_counter` reads plus one locked
  histogram update — cheap enough to leave on by default.
- ``exposition`` — Prometheus-text and JSON renderings of a snapshot,
  plus snapshot validation/diff helpers for the CLI
  (`scripts/consensus_stats.py`) and the CI `obs-smoke` artifact.
- ``perf`` — the performance observatory: `PhaseTimeline` phase
  attribution riding every in-flight dispatch ticket
  (`consensus_pipeline_phase_seconds{phase=...}` + the
  overlap-efficiency gauge), the reusable roofline/cost walk shared by
  the perf scripts, and provenance-stamped report comparison for the CI
  `perf-smoke` regression gate (`scripts/consensus_perf.py`).
- ``xprof`` — the device-truth kernel observatory: programmatic
  profiler capture sessions attributing device time to the named
  kernel regions threaded through the kernels via `ops/regions.py`
  (`consensus_kernel_region_seconds{region=...}` + MXU/VPU
  busy-fraction gauges, `XPROF_r{N}.json` artifacts, the
  `consensus_xprof.py --check` drift gate). Degrades to the op-walk
  estimate on CPU containers under the same `comparable()` discipline.
- ``flight`` — the black-box flight recorder: a bounded ring of recent
  resilience events/spans/metric deltas, dumped redacted +
  provenance-stamped on conviction (quarantine, checksum mismatch,
  chaos conviction, explicit CLI flag). Disarmed by default; the hot
  path costs one global read.

Design constraint (hard): nothing in this package is ever imported by —
or traced into — device kernel code. Instrumentation is host-side only,
so the jaxpr determinism gate (`analysis/`) and every registered kernel
jaxpr are untouched by telemetry. (`ops/regions.py` — imported by
``xprof`` — is the one sanctioned kernel-adjacent dependency: pure
naming metadata, importable both ways.) Conversely this is the ONE
place in the tree allowed to read clocks: the host AST lint rejects
direct `time.perf_counter()` timing in `models/` and `crypto/` so all
timing flows through spans.

Metric name catalogue and span taxonomy: README "Observability".
"""

from .metrics import (
    MetricsRegistry,
    counter,
    gauge,
    get_registry,
    histogram,
)
from .spans import (
    JsonlSink,
    Span,
    add_sink,
    current_span_id,
    current_trace,
    monotonic,
    remove_sink,
    span,
    trace_context,
)
from . import flight
from . import perf
from . import xprof

__all__ = [
    "JsonlSink",
    "flight",
    "xprof",
    "MetricsRegistry",
    "Span",
    "add_sink",
    "counter",
    "current_span_id",
    "current_trace",
    "gauge",
    "get_registry",
    "histogram",
    "monotonic",
    "perf",
    "remove_sink",
    "span",
    "trace_context",
]
