"""Exposition: render a metrics snapshot as Prometheus text or JSON.

Works on the plain-dict output of `MetricsRegistry.snapshot()` so it can
also render snapshots loaded back from disk (the CI `obs-smoke` artifact
and `scripts/consensus_stats.py --diff` path).
"""

from __future__ import annotations

import json
import math
from typing import Dict, List, Sequence, Tuple

__all__ = [
    "to_prometheus_text",
    "snapshot_to_json",
    "validate_snapshot",
    "diff_snapshots",
]


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_labels(labels: Dict[str, str], extra: Tuple[str, str] = None) -> str:
    items = sorted(labels.items())
    if extra is not None:
        items.append(extra)
    if not items:
        return ""
    body = ",".join(f'{k}="{_escape_label_value(str(v))}"' for k, v in items)
    return "{" + body + "}"


def _fmt_value(v) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def to_prometheus_text(snapshot: Dict[str, dict]) -> str:
    """Prometheus exposition-format text for a registry snapshot."""
    out: List[str] = []
    for name in sorted(snapshot):
        m = snapshot[name]
        if m["help"]:
            out.append(f"# HELP {name} {m['help']}")
        out.append(f"# TYPE {name} {m['kind']}")
        for s in m["samples"]:
            if m["kind"] == "histogram":
                for le, cum in s["buckets"]:
                    le_s = le if le == "+Inf" else _fmt_value(le)
                    out.append(
                        f"{name}_bucket"
                        f"{_fmt_labels(s['labels'], ('le', le_s))} {cum}"
                    )
                out.append(
                    f"{name}_sum{_fmt_labels(s['labels'])} "
                    f"{_fmt_value(s['sum'])}"
                )
                out.append(
                    f"{name}_count{_fmt_labels(s['labels'])} {s['count']}"
                )
            else:
                out.append(
                    f"{name}{_fmt_labels(s['labels'])} {_fmt_value(s['value'])}"
                )
    return "\n".join(out) + "\n"


def snapshot_to_json(snapshot: Dict[str, dict], **meta) -> str:
    """Pretty JSON document: {"meta": ..., "metrics": snapshot}."""
    return json.dumps(
        {"meta": meta, "metrics": snapshot}, indent=2, sort_keys=True
    )


def _iter_values(m: dict):
    for s in m["samples"]:
        if m["kind"] == "histogram":
            yield s["sum"]
            yield s["count"]
            for _le, cum in s["buckets"]:
                yield cum
        else:
            yield s["value"]


def validate_snapshot(
    snapshot: Dict[str, dict], required_names: Sequence[str] = ()
) -> List[str]:
    """Problems with a snapshot: required metrics missing or without
    samples, any non-finite (NaN/inf) value. Empty list == healthy."""
    problems: List[str] = []
    for name in required_names:
        m = snapshot.get(name)
        if m is None:
            problems.append(f"required metric missing: {name}")
        elif not m["samples"]:
            problems.append(f"required metric has no samples: {name}")
    for name in sorted(snapshot):
        for v in _iter_values(snapshot[name]):
            if not math.isfinite(float(v)):
                problems.append(f"non-finite value in {name}: {v!r}")
                break
    return problems


def _sample_map(m: dict) -> Dict[Tuple[Tuple[str, str], ...], dict]:
    return {
        tuple(sorted((k, str(v)) for k, v in s["labels"].items())): s
        for s in m["samples"]
    }


def diff_snapshots(
    old: Dict[str, dict], new: Dict[str, dict]
) -> List[str]:
    """Human-readable per-sample deltas between two snapshots.

    Counters/histogram counts report `+delta`; gauges report `old -> new`.
    Metrics or samples present on one side only are called out.
    """
    lines: List[str] = []
    for name in sorted(set(old) | set(new)):
        if name not in old:
            lines.append(f"+ {name} (new metric)")
            continue
        if name not in new:
            lines.append(f"- {name} (metric gone)")
            continue
        om, nm = _sample_map(old[name]), _sample_map(new[name])
        kind = new[name]["kind"]
        for key in sorted(set(om) | set(nm)):
            lbl = "{" + ",".join(f"{k}={v}" for k, v in key) + "}" if key else ""
            osamp, nsamp = om.get(key), nm.get(key)
            if osamp is None or nsamp is None:
                side = "new" if osamp is None else "gone"
                lines.append(f"  {name}{lbl} ({side} sample)")
                continue
            if kind == "histogram":
                dc = nsamp["count"] - osamp["count"]
                ds = nsamp["sum"] - osamp["sum"]
                if dc or ds:
                    lines.append(
                        f"  {name}{lbl} count +{dc} sum +{round(ds, 6)}"
                    )
            elif kind == "counter":
                d = nsamp["value"] - osamp["value"]
                if d:
                    lines.append(f"  {name}{lbl} +{_fmt(d)}")
            else:
                if nsamp["value"] != osamp["value"]:
                    lines.append(
                        f"  {name}{lbl} {_fmt(osamp['value'])} -> "
                        f"{_fmt(nsamp['value'])}"
                    )
    return lines


def _fmt(v) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) else str(round(f, 6))
