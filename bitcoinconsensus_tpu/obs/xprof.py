"""Device-truth kernel observatory: per-region device-time attribution.

PR 9's phase timelines say where *wall* time goes between host seams;
this module says which *kernel region* burns the device time inside a
dispatch. Every consensus kernel executes under a ``region:<name>``
``jax.named_scope`` (`ops/regions.py`), so the attribution needs no
cooperation from the kernels themselves — the region names ride the
jaxpr name stacks and, on real hardware, the XLA op metadata of every
profiler trace event.

Two capture modes, one artifact schema:

- ``trace`` (TPU/GPU): a programmatic ``jax.profiler.trace`` session
  around the workload; the chrome-trace events on the device tracks are
  parsed and their durations charged to the innermost region in the op
  name (`parse_trace_events`). This is measured device truth.
- ``opwalk`` (CPU containers): the PR 9 op-walk estimate — each
  program's jaxpr is walked (`walk_jaxpr_regions`: while×trips,
  scan×length, sub-jaxpr recursion with region inheritance) and its
  measured `timed_best` wall is split across regions by element-op
  share, so region shares still sum to ~100% of captured time and the
  same drift gate applies. The artifact's provenance stamps the mode
  and hardware; `check_reports` follows `perf.comparable()` — a
  container run never gates a TPU baseline, so CI never flaps.

Both produce per-region ``consensus_kernel_region_seconds`` gauges,
derived MXU/VPU busy-fraction gauges
(``consensus_xprof_busy_fraction{unit=mxu|vpu}``), and a
provenance-stamped ``XPROF_r{N}.json`` via `scripts/consensus_xprof.py`.

Like everything in ``obs/``, nothing here is imported by kernel code;
the one kernel-adjacent dependency is ``ops/regions.py``, which is
dependency-free metadata by design.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .metrics import counter, gauge
from . import perf as _perf
from ..ops.regions import extract_regions

__all__ = [
    "UNATTRIBUTED",
    "capture_report",
    "check_reports",
    "parse_trace_events",
    "parse_trace_dir",
    "standard_programs",
    "light_programs",
    "trace_session",
    "walk_jaxpr_regions",
    "write_report",
]

SCHEMA = "consensus-xprof-v1"

# Bucket for device time/ops outside every region scope — kept explicit
# (not silently dropped) so "shares sum to ~100%" is a checkable claim
# and annotation erosion shows up as a growing unattributed share.
UNATTRIBUTED = "unattributed"

_REGION_SECONDS = gauge(
    "consensus_kernel_region_seconds",
    "device seconds attributed to each named kernel region by the last "
    "xprof capture (trace mode: measured; opwalk mode: op-share estimate)",
    ("region",),
)
_BUSY_FRACTION = gauge(
    "consensus_xprof_busy_fraction",
    "derived busy fraction of the MXU (dot/conv work) and VPU "
    "(elementwise work) over the last capture's device time",
    ("unit",),
)
_CAPTURES = counter(
    "consensus_xprof_captures_total",
    "xprof capture sessions, by mode",
    ("mode",),
)

# Op names whose device time is systolic-array (MXU) work.
_MXU_PRIMS = ("dot_general", "dot", "conv")


# ---------------------------------------------------------------------------
# opwalk mode: region-attributed jaxpr walk
# ---------------------------------------------------------------------------


def _eqn_regions(eqn) -> Tuple[str, ...]:
    try:
        stack = str(eqn.source_info.name_stack)
    except Exception:  # pragma: no cover - jax internal move
        return ()
    return tuple(extract_regions(stack))


def walk_jaxpr_regions(
    jaxpr, inherited: Tuple[str, ...] = (), acc: Optional[dict] = None,
    mult: int = 1,
) -> Dict[Tuple[str, ...], Dict[str, int]]:
    """Attribute a jaxpr's element ops to kernel-region stacks.

    Returns ``{region_stack: {"ops": N, "mxu_flops": F}}`` where
    ``region_stack`` is the tuple of region frames (outermost first; the
    last entry is the innermost region the op is charged to — empty
    tuple = unattributed). The op accounting mirrors `perf.walk_jaxpr`
    (ARITH/MOVE element counts, while×trips, scan×length, recursion
    into any param carrying a jaxpr) with one addition: sub-jaxprs
    inherit the parent equation's region stack, because scan/while
    bodies are re-traced without the caller's name stack.
    """
    import numpy as np

    if acc is None:
        acc = {}

    def bucket(regions: Tuple[str, ...]) -> Dict[str, int]:
        b = acc.get(regions)
        if b is None:
            b = acc[regions] = {"ops": 0, "mxu_ops": 0, "mxu_flops": 0}
        return b

    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        regions = _eqn_regions(eqn) or inherited
        if prim == "while":
            walk_jaxpr_regions(
                eqn.params["body_jaxpr"].jaxpr, regions, acc,
                mult * _perf.while_trips(eqn),
            )
            continue
        if prim == "scan":
            walk_jaxpr_regions(
                eqn.params["jaxpr"].jaxpr, regions, acc,
                mult * eqn.params["length"],
            )
            continue
        recursed = False
        for p in eqn.params.values():
            sub = getattr(p, "jaxpr", p if hasattr(p, "eqns") else None)
            if sub is not None:
                walk_jaxpr_regions(sub, regions, acc, mult)
                recursed = True
        if recursed:
            continue
        outs = sum(int(np.prod(v.aval.shape)) for v in eqn.outvars)
        b = bucket(regions)
        if prim == "dot_general":
            lhs = eqn.invars[0].aval.shape
            ((lc, _rc), _batch) = eqn.params["dimension_numbers"]
            k = 1
            for d in lc:
                k *= int(lhs[d])
            b["mxu_flops"] += 2 * k * outs * mult
            b["mxu_ops"] += outs * mult
            b["ops"] += outs * mult
        elif prim in _perf.ARITH or prim in _perf.MOVE:
            b["ops"] += outs * mult
    return acc


def _opwalk_program(name: str, fn: Callable, args: tuple, reps: int):
    """One program's opwalk attribution: (region_acc, best_wall_s)."""
    import jax

    closed = jax.make_jaxpr(fn)(*args)
    acc = walk_jaxpr_regions(closed.jaxpr)
    jfn = jax.jit(fn)
    jfn(*args)  # compile outside the timed window
    best, _median, _walls = _perf.timed_best(lambda: jfn(*args), reps=reps)
    return acc, best


# ---------------------------------------------------------------------------
# trace mode: chrome-trace event parsing
# ---------------------------------------------------------------------------


def _device_pids(events: Sequence[dict]) -> set:
    """pids of device tracks in a chrome trace (process_name metadata
    mentioning a device; XLA emits '/device:TPU:0' style names)."""
    pids = set()
    for ev in events:
        if ev.get("ph") != "M" or ev.get("name") != "process_name":
            continue
        pname = str((ev.get("args") or {}).get("name", ""))
        low = pname.lower()
        if "/device:" in pname or "tpu" in low or "gpu" in low \
                or "xla" in low:
            pids.add(ev.get("pid"))
    return pids


def parse_trace_events(events: Sequence[dict]) -> dict:
    """Attribute device-track complete events to kernel regions.

    Returns ``{"regions": {leaf: seconds}, "phases": {outer: seconds},
    "total_s": float, "mxu_s": float}``. Only ``ph == "X"`` events on
    device-track pids count (durations are chrome-trace microseconds);
    the region is the innermost ``region:`` frame in the event name or
    its args (XLA op names carry the jaxpr name stack as a prefix).
    Events with no region frame are charged to `UNATTRIBUTED`.
    """
    pids = _device_pids(events)
    regions: Dict[str, float] = {}
    phases: Dict[str, float] = {}
    total = mxu = 0.0
    for ev in events:
        if ev.get("ph") != "X" or (pids and ev.get("pid") not in pids):
            continue
        dur = float(ev.get("dur", 0)) / 1e6
        if dur <= 0:
            continue
        name = str(ev.get("name", ""))
        hay = name
        args = ev.get("args")
        if isinstance(args, dict):
            hay += " " + " ".join(str(v) for v in args.values())
        frames = extract_regions(hay)
        leaf = frames[-1] if frames else UNATTRIBUTED
        outer = frames[0] if frames else UNATTRIBUTED
        regions[leaf] = regions.get(leaf, 0.0) + dur
        phases[outer] = phases.get(outer, 0.0) + dur
        total += dur
        low = name.lower()
        if any(m in low for m in _MXU_PRIMS):
            mxu += dur
    return {"regions": regions, "phases": phases,
            "total_s": total, "mxu_s": mxu}


def parse_trace_dir(log_dir: str) -> dict:
    """Parse every ``*.trace.json(.gz)`` under a profiler log dir and
    merge the per-file `parse_trace_events` attributions."""
    merged = {"regions": {}, "phases": {}, "total_s": 0.0, "mxu_s": 0.0}
    paths = sorted(
        glob.glob(os.path.join(log_dir, "**", "*.trace.json.gz"),
                  recursive=True)
        + glob.glob(os.path.join(log_dir, "**", "*.trace.json"),
                    recursive=True)
    )
    for path in paths:
        opener = gzip.open if path.endswith(".gz") else open
        try:
            with opener(path, "rt") as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            continue
        part = parse_trace_events(doc.get("traceEvents", []))
        for key in ("regions", "phases"):
            for k, v in part[key].items():
                merged[key][k] = merged[key].get(k, 0.0) + v
        merged["total_s"] += part["total_s"]
        merged["mxu_s"] += part["mxu_s"]
    return merged


@contextmanager
def trace_session(log_dir: str):
    """A programmatic ``jax.profiler.trace`` session (the one sanctioned
    wrapper — `utils/profiling.xla_trace` is a locked thin adapter over
    this). Usable on every platform; on CPU the capture holds host
    tracks only, which is why `capture_report` degrades to opwalk there.
    """
    import jax

    with jax.profiler.trace(log_dir):
        yield


# ---------------------------------------------------------------------------
# workload program sets
# ---------------------------------------------------------------------------


def light_programs(batch: int = 256) -> List[Tuple[str, Callable, tuple]]:
    """Cheap-to-compile region workload: the fe_mul A/B pair, the BIP340
    challenge kernel, and the verdict checksum. This is the mini-workload
    leg (`consensus_stats.py`) and the unit-test set — no verify-kernel
    compile."""
    import numpy as np
    import jax.numpy as jnp
    from ..ops import limbs, mxu_mul, sha256

    rng = np.random.default_rng(0xB17C015)
    fe = lambda: jnp.asarray(rng.integers(  # noqa: E731
        0, limbs.MASK + 1, size=(limbs.NLIMB, batch), dtype=np.int32))
    a, b = fe(), fe()
    u8 = lambda: jnp.asarray(rng.integers(  # noqa: E731
        0, 256, size=(batch, 32), dtype=np.uint8))
    ok = jnp.asarray(rng.integers(0, 2, size=(batch,)) == 1)
    from ..crypto import jax_backend as _jb

    return [
        ("fe_mul", limbs.fe_mul, (a, b)),
        ("fe_mul_onehot", mxu_mul.fe_mul_onehot, (a, b)),
        ("bip340_challenge", sha256.bip340_challenge, (u8(), u8(), u8())),
        ("verdict_checksum", _jb._verdict_checksum, (ok,)),
    ]


def standard_programs(batch: int = 256) -> List[Tuple[str, Callable, tuple]]:
    """The full capture workload: `light_programs` plus the XLA verify
    kernel itself (sighash prep -> point decode -> scalar mult ->
    verdict chain). All-zero fields parse as off-curve and sanitize to
    the generator, so every lane runs the full on-curve group math —
    the kernel is data-independent by construction."""
    import jax.numpy as jnp

    from ..crypto import jax_backend as _jb

    progs = light_programs(batch)
    fields = jnp.zeros((batch, 4, 32), dtype=jnp.uint8)
    z = jnp.zeros((batch,), dtype=jnp.int32)
    progs.append((
        "verify_kernel",
        _jb._verify_kernel,
        (fields, z, z, z, z, z, z.astype(bool)),
    ))
    return progs


# ---------------------------------------------------------------------------
# capture -> report
# ---------------------------------------------------------------------------


def _finalize(regions: Dict[str, float], phases: Dict[str, float],
              total: float, mxu_s: float, mode: str,
              programs: Dict[str, float], cmd: Optional[str]) -> dict:
    unattr = regions.get(UNATTRIBUTED, 0.0)
    named = {k: v for k, v in regions.items() if k != UNATTRIBUTED}
    share = (lambda s: s / total if total > 0 else 0.0)
    doc = {
        "schema": SCHEMA,
        "mode": mode,
        "provenance": _perf.provenance(cmd=cmd),
        "device_total_s": total,
        "regions": {
            k: {"seconds": v, "share": share(v)}
            for k, v in sorted(named.items())
        },
        "phases": {
            k: {"seconds": v, "share": share(v)}
            for k, v in sorted(phases.items()) if k != UNATTRIBUTED
        },
        "unattributed_s": unattr,
        "named_share": share(sum(named.values())),
        "mxu_busy_fraction": share(mxu_s),
        "vpu_busy_fraction": share(total - mxu_s),
        "programs": {k: {"seconds": v} for k, v in sorted(programs.items())},
    }
    for k, v in named.items():
        _REGION_SECONDS.set(v, region=k)
    _REGION_SECONDS.set(unattr, region=UNATTRIBUTED)
    _BUSY_FRACTION.set(doc["mxu_busy_fraction"], unit="mxu")
    _BUSY_FRACTION.set(doc["vpu_busy_fraction"], unit="vpu")
    _CAPTURES.inc(mode=mode)
    return doc


def capture_report(
    programs: Optional[Sequence[Tuple[str, Callable, tuple]]] = None,
    reps: int = 3,
    mode: Optional[str] = None,
    log_dir: Optional[str] = None,
    cmd: Optional[str] = None,
) -> dict:
    """Run the workload under the active capture mode and return the
    XPROF report dict (not yet written to disk — see `write_report`).

    ``mode`` is ``"trace"`` on real accelerators and ``"opwalk"`` on CPU
    unless forced. In trace mode the programs run inside one profiler
    session and the device tracks are parsed; in opwalk mode each
    program's jaxpr op counts split its measured wall time, so the
    artifact never claims measured device truth a CPU container cannot
    produce (the provenance + mode fields make the difference explicit,
    and `check_reports` refuses cross-mode comparison).
    """
    import jax

    if programs is None:
        programs = standard_programs()
    if mode is None:
        mode = "opwalk" if jax.default_backend() == "cpu" else "trace"

    regions: Dict[str, float] = {}
    phases: Dict[str, float] = {}
    prog_walls: Dict[str, float] = {}
    total = mxu_s = 0.0

    if mode == "trace":
        import tempfile

        own = log_dir is None
        log_dir = log_dir or tempfile.mkdtemp(prefix="consensus_xprof_")
        jitted = [(n, jax.jit(fn), args) for n, fn, args in programs]
        for _n, jfn, args in jitted:  # compile outside the session
            _perf.timed_best(lambda: jfn(*args), reps=1)
        with trace_session(log_dir):
            for name, jfn, args in jitted:
                best, _m, _w = _perf.timed_best(
                    lambda: jfn(*args), reps=reps)
                prog_walls[name] = best
        parsed = parse_trace_dir(log_dir)
        regions, phases = parsed["regions"], parsed["phases"]
        total, mxu_s = parsed["total_s"], parsed["mxu_s"]
        if own:
            import shutil

            shutil.rmtree(log_dir, ignore_errors=True)
    else:
        for name, fn, args in programs:
            acc, wall = _opwalk_program(name, fn, args, reps)
            prog_walls[name] = wall
            ops_total = sum(b["ops"] for b in acc.values()) or 1
            for stack, b in acc.items():
                sec = wall * (b["ops"] / ops_total)
                leaf = stack[-1] if stack else UNATTRIBUTED
                outer = stack[0] if stack else UNATTRIBUTED
                regions[leaf] = regions.get(leaf, 0.0) + sec
                phases[outer] = phases.get(outer, 0.0) + sec
                mxu_s += wall * (b["mxu_ops"] / ops_total)
            total += wall
    return _finalize(regions, phases, total, mxu_s, mode, prog_walls, cmd)


def write_report(doc: dict, path: str) -> None:
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")


# Minimum share a region must hold before drift in it can gate, and the
# maximum absolute share drift tolerated between same-provenance runs.
SHARE_FLOOR = 0.01
SHARE_TOLERANCE = 0.15


def check_reports(
    baseline: dict, report: dict,
    tolerance: float = SHARE_TOLERANCE, floor: float = SHARE_FLOOR,
) -> Optional[List[str]]:
    """Region-share drift gate between two XPROF artifacts.

    Returns None when the runs are not comparable (provenance mismatch
    or different capture modes — same skip-not-fail discipline as
    `perf.compare_reports`), else the list of drift findings (empty =
    pass). A region drifts when its device-time share moved by more
    than `tolerance` absolute points and at least one side holds more
    than `floor` share — so a region appearing from or collapsing to
    ~nothing is also a finding.
    """
    ok, _why = _perf.comparable(
        baseline.get("provenance", {}), report.get("provenance", {}))
    if not ok:
        return None
    if baseline.get("mode") != report.get("mode"):
        return None
    problems: List[str] = []
    old = {k: v.get("share", 0.0)
           for k, v in (baseline.get("regions") or {}).items()}
    new = {k: v.get("share", 0.0)
           for k, v in (report.get("regions") or {}).items()}
    for k in sorted(set(old) | set(new)):
        a, b = old.get(k, 0.0), new.get(k, 0.0)
        if max(a, b) < floor:
            continue
        if abs(b - a) > tolerance:
            problems.append(
                f"region {k}: share {a:.1%} -> {b:.1%} "
                f"(drift {abs(b - a):.1%} > {tolerance:.0%})"
            )
    old_named = baseline.get("named_share")
    new_named = report.get("named_share")
    if (isinstance(old_named, (int, float))
            and isinstance(new_named, (int, float))
            and new_named < old_named - tolerance):
        problems.append(
            f"named-region coverage dropped {old_named:.1%} -> "
            f"{new_named:.1%} (annotations eroding)"
        )
    return problems
