"""Black-box flight recorder: bounded ring buffer + dump-on-conviction.

When the resilience machinery convicts something — a guard rejects a
verdict buffer, the degradation ladder demotes a backend, a shard gets
evicted — the interesting evidence is what happened in the seconds
*before*. This module keeps that evidence: a bounded, thread-safe ring
of recent events (resilience decisions, span completions, anything the
hook sites `record()`), plus the metric registry delta since arming.
On a trigger (quarantine, checksum mismatch, chaos conviction, explicit
CLI flag) the ring is dumped — redacted and provenance-stamped — to a
``flight_dump_<reason>_*.json`` the chaos harness and operators can
read post-mortem.

Disarmed-by-default discipline (same as `perf.set_enabled`): the fast
path of `record()` is a single module-global read, so the recorder
costs nothing measurable inside the <1% resilience overhead budget
until armed via ``BITCOINCONSENSUS_TPU_FLIGHT=1`` or `set_enabled()`.
Span subscription attaches a sink only while armed, so the span hot
path is untouched when disarmed.

Redaction: consensus inputs (scripts, signatures, pubkeys, message
bytes) never belong in a dump that may leave the machine. Any event
field whose key smells sensitive is replaced by ``<redacted:N bytes>``
recursively before serialization.

Dumps are count-capped per process (`MAX_DUMPS`), deliberately NOT
time-rate-limited: a chaos sweep convicting on back-to-back trials must
get a complete dump for each conviction, and a production incident
rarely needs more than the first few dumps anyway.
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque
from typing import Any, Dict, List, Optional

from .metrics import counter, gauge, get_registry
from . import exposition as _exposition
from . import perf as _perf
from . import spans as _spans

__all__ = [
    "CAPACITY",
    "MAX_DUMPS",
    "enabled",
    "events",
    "record",
    "reset",
    "set_enabled",
    "trigger",
]

SCHEMA = "consensus-flight-v1"

# Ring capacity: large enough to hold the span/decision window around a
# conviction (a verify batch emits a handful of spans), small enough to
# bound memory and dump size.
CAPACITY = 512

# Dumps written per process before the recorder goes quiet (count cap,
# not a rate limit — see module docstring).
MAX_DUMPS = 16

_EVENTS = counter(
    "consensus_flight_events_total",
    "events accepted by the flight ring while armed, by kind",
    ("kind",),
)
_DUMPS = counter(
    "consensus_flight_dumps_total",
    "flight dumps written, by trigger reason",
    ("trigger",),
)
_ARMED_GAUGE = gauge(
    "consensus_flight_armed",
    "1 while the flight recorder is armed, else 0",
)
_ARMED_GAUGE.set(0)

# Event-field keys whose values are redacted from dumps. Substring
# match, case-insensitive: "pubkey_x", "script_sig", "msg32" all hit.
REDACT_KEYS = (
    "payload", "data", "sig", "pubkey", "pub_key", "msg", "message",
    "raw", "script", "secret", "privkey", "key_bytes", "witness",
)

_lock = threading.Lock()
_armed = os.environ.get("BITCOINCONSENSUS_TPU_FLIGHT", "0") not in (
    "0", "", "false", "no")
_ring: deque = deque(maxlen=CAPACITY)
_appended = 0  # lifetime accepted count; - len(ring) = evicted
_dumps_written = 0
_dump_seq = 0
_armed_snapshot: Optional[dict] = None
_span_sink = None


class _FlightSpanSink:
    """Span sink feeding completed spans into the ring (attached only
    while armed; `spans.add_sink` errors are already counted there)."""

    def write(self, rec: dict) -> None:
        record("span", **rec)


def enabled() -> bool:
    return _armed


def set_enabled(flag: bool) -> None:
    """Arm or disarm the recorder (idempotent).

    Arming snapshots the metric registry (dumps carry the delta since
    arming) and subscribes the span sink; disarming detaches the sink so
    the span path returns to its unobserved cost.
    """
    global _armed, _armed_snapshot, _span_sink
    with _lock:
        if flag and not _armed:
            _armed_snapshot = get_registry().snapshot()
            _span_sink = _FlightSpanSink()
            _spans.add_sink(_span_sink)
            _armed = True
            _ARMED_GAUGE.set(1)
        elif not flag and _armed:
            _armed = False
            if _span_sink is not None:
                _spans.remove_sink(_span_sink)
                _span_sink = None
            _ARMED_GAUGE.set(0)


def reset() -> None:
    """Clear ring + dump counters (test isolation helper)."""
    global _appended, _dumps_written, _dump_seq, _armed_snapshot
    with _lock:
        _ring.clear()
        _appended = 0
        _dumps_written = 0
        _dump_seq = 0
        if _armed:
            _armed_snapshot = get_registry().snapshot()


def record(kind: str, **fields) -> None:
    """Append one event to the ring. Disarmed cost: one global read."""
    if not _armed:
        return
    global _appended
    ev = {"t": _spans.monotonic(), "kind": kind}
    ev.update(fields)
    with _lock:
        _ring.append(ev)
        _appended += 1
    _EVENTS.inc(kind=kind)


def events() -> List[dict]:
    """Current ring contents, oldest first (copy)."""
    with _lock:
        return list(_ring)


def dropped() -> int:
    """Events evicted from the ring since arming/reset."""
    with _lock:
        return max(0, _appended - len(_ring))


def _redact(value: Any, key: str = "") -> Any:
    low = key.lower()
    if any(tok in low for tok in REDACT_KEYS):
        try:
            size = len(value)  # type: ignore[arg-type]
        except TypeError:
            size = 0
        return f"<redacted:{size}>"
    if isinstance(value, dict):
        return {k: _redact(v, str(k)) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_redact(v) for v in value]
    if isinstance(value, (bytes, bytearray)):
        return f"<bytes:{len(value)}>"
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    return repr(value)


def _dump_dir() -> str:
    return os.environ.get("BITCOINCONSENSUS_TPU_FLIGHT_DIR", "/tmp")


def trigger(reason: str, out_dir: Optional[str] = None,
            **attrs) -> Optional[str]:
    """Dump the flight ring; returns the written path (None when
    disarmed or the per-process dump cap is exhausted).

    The dump holds: the trigger reason + attrs (redacted), the full
    event window oldest-first, the count of ring-evicted events, the
    metric deltas since arming, and a provenance stamp — everything a
    post-mortem needs without re-running the workload.
    """
    global _dumps_written, _dump_seq
    if not _armed:
        return None
    with _lock:
        if _dumps_written >= MAX_DUMPS:
            return None
        _dumps_written += 1
        _dump_seq += 1
        seq = _dump_seq
        window = list(_ring)
        evicted = max(0, _appended - len(_ring))
        base_snap = _armed_snapshot or {}
    deltas = _exposition.diff_snapshots(base_snap, get_registry().snapshot())
    doc = {
        "schema": SCHEMA,
        "trigger": reason,
        "attrs": _redact(dict(attrs)),
        "provenance": _perf.provenance(),
        "events": [_redact(ev) for ev in window],
        "events_dropped": evicted,
        "metric_deltas": deltas,
    }
    out_dir = out_dir or _dump_dir()
    path = os.path.join(
        out_dir, f"flight_dump_{reason}_{os.getpid()}_{seq:03d}.json")
    try:
        with open(path, "w") as fh:
            json.dump(doc, fh, indent=2, sort_keys=True, default=repr)
            fh.write("\n")
    except OSError:
        return None
    _DUMPS.inc(trigger=reason)
    return path
