"""Performance observatory: ticket phase timelines, overlap efficiency,
roofline/cost accounting, and provenance-stamped perf reports.

The ROADMAP's perf arc (kill the 208 ms link wait, raise VPU utilization)
needs its numbers measured *continuously*, inside the async pipeline —
not reconstructed by hand from one-off scripts. Three layers live here:

- **PhaseTimeline** — rides every `resilience/inflight.py` Ticket.
  Monotonic stamps (through the sanctioned `obs` clock — the host AST
  lint's clock rule stays intact) at submit / prepare / launch /
  first-poll / settle-start / settle-end, plus per-shard stamps from the
  mesh settle seam. Finalizing a timeline feeds the
  `consensus_pipeline_phase_seconds{phase=…}` histograms and the derived
  `consensus_pipeline_overlap_efficiency` gauge: the fraction of a
  ticket's wire time (launch → settled) the host spent *not* waiting —
  the continuous successor to the one-off "208 ms of 282.7 ms is link
  wait" measurement. Dispatch-path hot code never touches more than a
  dict store per stamp; `BITCOINCONSENSUS_TPU_PERF_TIMELINE=0` disarms
  timelines entirely (a shared no-op instance — the A/B knob for the
  <1 % overhead budget).

- **Roofline/cost accounting** — the traced-jaxpr integer-op walk that
  `scripts/kernel_roofline.py` pioneered, as a reusable library
  (`walk_jaxpr`, `while_trips`, `kernel_report`), plus
  `Compiled.cost_analysis()` where the installed jax exposes it. Scripts
  stay thin wrappers.

- **Provenance + reports** — `provenance()` stamps every perf artifact
  with backend/device/versions/git-rev, `comparable()` decides whether
  two artifacts may be compared at all (the BENCH_r06 "CPU container
  numbers are NOT comparable to TPU v5e" footgun, closed structurally),
  and `compare_reports()` is the regression gate
  `scripts/consensus_perf.py --check` and CI's perf-smoke job run.

Nothing here is ever traced into a device kernel; jax/numpy imports are
lazy so the telemetry package stays dependency-light at import time.
"""

from __future__ import annotations

import os
import platform as _platform
import subprocess
import sys
import threading
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from .metrics import gauge, get_registry, histogram
from .spans import monotonic

__all__ = [
    "NULL_TIMELINE",
    "PEAK_INT_OPS_V5E",
    "PhaseTimeline",
    "compare_reports",
    "comparable",
    "cost_analysis",
    "kernel_report",
    "new_timeline",
    "overlap_efficiency",
    "phase_report",
    "provenance",
    "register_kernel",
    "reset_overlap_window",
    "registered_kernels",
    "set_enabled",
    "timed_best",
    "timeline_enabled",
    "walk_jaxpr",
    "while_trips",
]

_PHASE_SECONDS = histogram(
    "consensus_pipeline_phase_seconds",
    "per-ticket pipeline phase durations (README: Performance "
    "observatory phase taxonomy)",
    ("phase",),
)
_OVERLAP = gauge(
    "consensus_pipeline_overlap_efficiency",
    "fraction of recent tickets' wire time hidden by host-side work "
    "(1.0 = the link wait is fully overlapped, 0.0 = fully exposed)",
)

# (histogram phase label, start stamp, end stamp). "inflight" is the
# overlap window: the host came back to poll the ticket only after this
# long — time the device spent working while the host did something else.
_PHASE_EDGES: Tuple[Tuple[str, str, str], ...] = (
    ("prepare", "submit", "prepare"),
    ("launch", "prepare", "launch"),
    ("inflight", "launch", "first_poll"),
    ("settle", "settle_start", "settle_end"),
    ("total", "submit", "settle_end"),
)

# Overlap gauge window: recent (hidden, wire) second pairs; the gauge is
# sum(hidden)/sum(wire), so long tickets weigh proportionally.
_OVERLAP_WINDOW = 256
_overlap_lock = threading.Lock()
_overlap_win: deque = deque(maxlen=_OVERLAP_WINDOW)

_enabled = os.environ.get(
    "BITCOINCONSENSUS_TPU_PERF_TIMELINE", ""
) not in ("0", "off")


def set_enabled(flag: bool) -> None:
    """Arm/disarm phase timelines process-wide (the A/B overhead knob).
    Tickets already carrying a live timeline finish it; new dispatches
    get the shared no-op instance while disarmed."""
    global _enabled
    _enabled = bool(flag)


def timeline_enabled() -> bool:
    return _enabled


def reset_overlap_window() -> None:
    """Drop accumulated overlap samples (test isolation; the metrics
    registry's `reset()` does not reach this module-level window)."""
    with _overlap_lock:
        _overlap_win.clear()


def _note_overlap(hidden: float, wire: float) -> None:
    with _overlap_lock:
        _overlap_win.append((hidden, wire))
        h = sum(x for x, _ in _overlap_win)
        w = sum(y for _, y in _overlap_win)
    if w > 0.0:
        _OVERLAP.set(h / w)


class PhaseTimeline:
    """Monotonic stamp sheet for one in-flight dispatch ticket.

    The queue stamps the lifecycle edges; `finalize()` (idempotent, at
    settle) turns them into phase histogram observations and one overlap
    sample. `trace` carries the submitting request's trace id across the
    worker-thread boundary for post-hoc JSONL correlation.
    """

    __slots__ = ("stamps", "shards", "trace", "_done")

    def __init__(self, trace: Optional[int] = None):
        self.stamps: Dict[str, float] = {}
        self.shards: List[Tuple[int, float]] = []
        self.trace = trace
        self._done = False

    def stamp(self, name: str) -> None:
        """Record `name` at now; re-stamping overwrites (a relaunch after
        a retry moves the launch edge — the settled attempt is the one
        attributed)."""
        self.stamps[name] = monotonic()

    def stamp_once(self, name: str) -> None:
        """Record `name` only if unseen (first_poll must survive
        re-settles)."""
        if name not in self.stamps:
            self.stamps[name] = monotonic()

    def stamp_shard(self, idx: int) -> None:
        """Record completion of shard `idx`'s settle-side check."""
        self.shards.append((idx, monotonic()))

    def phase_seconds(self) -> Dict[str, float]:
        """Derived per-phase durations (only edges with both stamps)."""
        t = self.stamps
        out: Dict[str, float] = {}
        for phase, a, b in _PHASE_EDGES:
            if a in t and b in t and t[b] >= t[a]:
                out[phase] = t[b] - t[a]
        return out

    def finalize(self) -> None:
        """Feed the registry once: phase histograms, per-shard check
        durations, and the overlap-efficiency sample."""
        if self._done:
            return
        self._done = True
        for phase, dt in self.phase_seconds().items():
            _PHASE_SECONDS.observe(dt, phase=phase)
        t = self.stamps
        start = t.get("settle_start")
        if self.shards and start is not None:
            prev = start
            for _idx, ts in self.shards:
                if ts >= prev:
                    _PHASE_SECONDS.observe(ts - prev, phase="shard_check")
                prev = ts
        launch = t.get("launch")
        poll = t.get("first_poll")
        end = t.get("settle_end")
        if launch is not None and poll is not None and end is not None:
            wire = end - launch
            if wire > 0.0:
                _note_overlap(min(max(poll - launch, 0.0), wire), wire)


class _NullTimeline:
    """Shared disarmed timeline: every hook a no-op, zero per-ticket
    allocation. `trace` reads as None; there is nothing to set."""

    __slots__ = ()
    trace = None

    def stamp(self, name: str) -> None:
        pass

    def stamp_once(self, name: str) -> None:
        pass

    def stamp_shard(self, idx: int) -> None:
        pass

    def phase_seconds(self) -> Dict[str, float]:
        return {}

    def finalize(self) -> None:
        pass


NULL_TIMELINE = _NullTimeline()


def new_timeline(trace: Optional[int] = None):
    """A live PhaseTimeline, or the shared no-op when disarmed."""
    if not _enabled:
        return NULL_TIMELINE
    return PhaseTimeline(trace)


# ---------------------------------------------------------------------------
# Registry readbacks (report side).


def phase_report() -> Dict[str, dict]:
    """Per-phase {count, mean_s, total_s} from the pipeline histograms —
    the report block `scripts/consensus_perf.py` emits and gates on."""
    h = get_registry().get("consensus_pipeline_phase_seconds")
    out: Dict[str, dict] = {}
    if h is None:
        return out
    for s in h._samples():
        if s["count"]:
            out[s["labels"]["phase"]] = {
                "count": s["count"],
                "mean_s": s["sum"] / s["count"],
                "total_s": s["sum"],
            }
    return out


def overlap_efficiency() -> Optional[float]:
    """Current overlap-efficiency gauge value, or None before any
    settled ticket fed the window."""
    g = get_registry().get("consensus_pipeline_overlap_efficiency")
    if g is None or not g._samples():
        return None
    return float(g.value())


# ---------------------------------------------------------------------------
# Roofline / cost accounting (shared by kernel_roofline + consensus_perf).

# v5e VPU int32 ceiling: (8, 128) vector unit x 4 ALUs at ~0.94 GHz.
PEAK_INT_OPS_V5E = 3.85e12

ARITH = {
    "add", "sub", "mul", "and", "or", "xor", "shift_left",
    "shift_right_logical", "shift_right_arithmetic", "select_n", "eq", "ne",
    "lt", "le", "gt", "ge", "min", "max", "neg", "abs", "rem", "not",
    "convert_element_type", "broadcast_in_dim", "concatenate", "iota",
    "reduce_and", "reduce_or", "reduce_sum", "reduce_min", "reduce_max",
}
# Conservative split: data movement / shape ops are NOT compute but still
# occupy the VPU pipeline; counted separately.
MOVE = {"convert_element_type", "broadcast_in_dim", "concatenate", "iota"}


def while_trips(eqn) -> int:
    """Trip count of a lowered `fori_loop` (a `while` whose carry init
    holds the static upper bound as a scalar int literal — take the
    largest such literal; exact for every fori in the verify kernel:
    window loop, G loop, the _sqr_n chains)."""
    try:
        from jax._src.core import Literal
    except Exception:  # pragma: no cover - jax internal move
        from jax.core import Literal
    trips = 1
    for v in eqn.invars:
        if isinstance(v, Literal) and getattr(v.aval, "shape", None) == ():
            try:
                trips = max(trips, int(v.val))
            except (TypeError, ValueError):
                pass
    return trips


def walk_jaxpr(jaxpr) -> Tuple[int, int]:
    """Sum (compute_ops, move_ops) element counts over a jaxpr: every
    arithmetic/logic/select/compare primitive's output elements — the
    int32 work the VPU actually executes (loads/stores and MXU dots
    excluded). Recurses into pjit/call bodies, `while` (fori trip counts
    via `while_trips`), `scan` (`length`), and any param carrying a
    jaxpr (pallas_call bodies included)."""
    import numpy as np

    comp = move = 0
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if prim == "while":
            c, m = walk_jaxpr(eqn.params["body_jaxpr"].jaxpr)
            t = while_trips(eqn)
            comp += c * t
            move += m * t
            continue
        if prim == "scan":
            c, m = walk_jaxpr(eqn.params["jaxpr"].jaxpr)
            comp += c * eqn.params["length"]
            move += m * eqn.params["length"]
            continue
        recursed = False
        for p in eqn.params.values():
            # ClosedJaxpr (.jaxpr) or raw Jaxpr (.eqns) — pallas_call
            # carries the latter.
            sub = getattr(p, "jaxpr", p if hasattr(p, "eqns") else None)
            if sub is not None:
                c, m = walk_jaxpr(sub)
                comp += c
                move += m
                recursed = True
        if recursed:
            continue
        outs = sum(int(np.prod(vv.aval.shape)) for vv in eqn.outvars)
        if prim in MOVE:
            move += outs
        elif prim in ARITH:
            comp += outs
    return comp, move


def _block(x) -> None:
    """Wait for every array leaf of `x` (timing helper; report side only
    — dispatch-path code settles through resilience/inflight)."""
    import jax

    for leaf in jax.tree_util.tree_leaves(x):
        wait = getattr(leaf, "block_until_ready", None)
        if wait is not None:
            wait()


def timed_best(fn: Callable[[], Any], reps: int = 5):
    """(best_s, median_s, walls) over `reps` synchronized calls of `fn`
    — min-of-N approximates the uncontended kernel on a shared chip."""
    walls = []
    for _ in range(max(1, int(reps))):
        t0 = monotonic()
        _block(fn())
        walls.append(monotonic() - t0)
    return min(walls), sorted(walls)[len(walls) // 2], walls


def cost_analysis(fn: Callable, *args) -> Dict[str, float]:
    """XLA's own cost model for `fn(*args)` where the installed jax
    exposes `Compiled.cost_analysis()`; {} when unavailable. Numeric
    entries only (the raw dict carries non-JSON values on some
    backends)."""
    try:
        import jax

        compiled = jax.jit(fn).lower(*args).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        return {
            str(k): float(v)
            for k, v in dict(ca).items()
            if isinstance(v, (int, float))
        }
    except Exception:
        return {}


_KERNELS: Dict[str, Callable[[], tuple]] = {}


def register_kernel(name: str, make: Callable[[], tuple]) -> None:
    """Register a kernel for the perf report. `make()` -> (run, run_args)
    or (run, run_args, trace_fn, trace_args) — built lazily so
    registration never compiles anything."""
    _KERNELS[name] = make


def registered_kernels() -> Dict[str, Callable[[], tuple]]:
    return dict(_KERNELS)


def kernel_report(
    name: str,
    run: Callable,
    run_args: tuple,
    trace_fn: Optional[Callable] = None,
    trace_args: Optional[tuple] = None,
    reps: int = 5,
    peak: float = PEAK_INT_OPS_V5E,
    with_cost_analysis: bool = True,
) -> dict:
    """Machine-readable roofline for one kernel.

    Op count from the TRACED program (`trace_fn(*trace_args)`, defaults
    to the timed call — pass a one-tile interpret trace when the grid
    repeats one program), timing from min-of-`reps` synchronized calls
    of `run(*run_args)`, ceiling from `peak`. Lanes = leading dim of the
    first argument of each side.
    """
    import jax

    trace_fn = run if trace_fn is None else trace_fn
    trace_args = run_args if trace_args is None else trace_args
    closed = jax.make_jaxpr(trace_fn)(*trace_args)
    comp, move = walk_jaxpr(closed.jaxpr)
    trace_lanes = int(trace_args[0].shape[0])
    lanes = int(run_args[0].shape[0])
    ops_per_lane = comp / trace_lanes
    move_per_lane = move / trace_lanes
    _block(run(*run_args))  # warm the compile; timing below excludes it
    best, median, _walls = timed_best(lambda: run(*run_args), reps=reps)
    lanes_per_s = lanes / best
    achieved = ops_per_lane * lanes_per_s
    out = {
        "kernel": name,
        "lanes": lanes,
        "trace_lanes": trace_lanes,
        "reps": int(reps),
        "best_ms": round(best * 1000, 3),
        "median_ms": round(median * 1000, 3),
        "lanes_per_sec_best": round(lanes_per_s, 1),
        "int_ops_per_lane": round(ops_per_lane, 1),
        "move_ops_per_lane": round(move_per_lane, 1),
        "achieved_int_ops_per_sec": f"{achieved:.3e}",
        "vpu_peak_int_ops_per_sec": f"{peak:.3e}",
        "vpu_utilization_pct": round(100 * achieved / peak, 2),
    }
    if with_cost_analysis:
        ca = cost_analysis(trace_fn, *trace_args)
        if ca:
            out["xla_cost_analysis"] = ca
    return out


# ---------------------------------------------------------------------------
# Provenance + the regression gate.

# Provenance keys that must MATCH for two perf artifacts to be compared
# at all. git rev and versions are recorded but deliberately not part of
# the comparability key — the gate exists precisely to compare across
# revisions on the same hardware class.
COMPARABLE_KEYS = ("platform", "device_kind")


def _git_rev() -> str:
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=root, capture_output=True, text=True, timeout=5,
        )
        rev = out.stdout.strip()
        return rev if out.returncode == 0 and rev else "unknown"
    except Exception:
        return "unknown"


def provenance(cmd: Optional[str] = None) -> dict:
    """Where a perf number came from: backend platform + device kind,
    jax/jaxlib/python versions, git revision, and the producing command.
    Stamped into every artifact this repo's bench writers emit."""
    doc = {
        "platform": "unavailable",
        "device_kind": "unavailable",
        "device_count": 0,
        "jax": "unavailable",
        "jaxlib": "unavailable",
        "python": sys.version.split()[0],
        "git_rev": _git_rev(),
        "cmd": " ".join(sys.argv) if cmd is None else cmd,
    }
    try:
        import jax

        doc["jax"] = jax.__version__
        try:
            import jaxlib

            doc["jaxlib"] = jaxlib.__version__
        except Exception:
            pass
        doc["platform"] = jax.default_backend()
        devs = jax.devices()
        if devs:
            kind = devs[0].device_kind
            if doc["platform"] == "cpu":
                # A bare "cpu" would make every CPU box "comparable" and
                # flap the throughput gate across machines; qualify it so
                # the gate only bites on matched hardware.
                kind = (
                    f"{kind}/{_platform.machine()}"
                    f"-{os.cpu_count() or 0}c"
                )
            doc["device_kind"] = kind
            doc["device_count"] = len(devs)
    except Exception:
        pass
    return doc


def comparable(a: dict, b: dict) -> Tuple[bool, str]:
    """Whether two provenance blocks describe comparable hardware; the
    reason string names the first mismatched key when not."""
    for k in COMPARABLE_KEYS:
        if a.get(k) != b.get(k):
            return False, f"{k}: {a.get(k)!r} vs {b.get(k)!r}"
    return True, ""


def compare_reports(
    baseline: dict,
    report: dict,
    tolerance: float = 0.5,
    abs_floor_s: float = 1e-3,
) -> Optional[List[str]]:
    """Regression-gate a perf report against a checked-in baseline.

    Returns None when the two are not comparable (provenance mismatch —
    a container run never fails a TPU baseline), else the list of
    regression descriptions (empty = pass). A phase regresses when its
    mean grew BOTH by more than `tolerance` (relative) and by more than
    `abs_floor_s` (absolute) — microsecond-scale phases don't flap the
    gate on scheduler noise. Throughput regresses on relative drop alone.
    """
    ok, _why = comparable(
        baseline.get("provenance", {}), report.get("provenance", {})
    )
    if not ok:
        return None
    problems: List[str] = []
    old_tp = (baseline.get("workload") or {}).get("verifies_per_sec")
    new_tp = (report.get("workload") or {}).get("verifies_per_sec")
    if old_tp and new_tp and new_tp < old_tp * (1.0 - tolerance):
        problems.append(
            f"throughput regression: {new_tp:.1f} verifies/s vs baseline "
            f"{old_tp:.1f} (tolerance {tolerance:.0%})"
        )
    old_ph = baseline.get("phases") or {}
    new_ph = report.get("phases") or {}
    for phase, old in sorted(old_ph.items()):
        new = new_ph.get(phase)
        if new is None:
            continue
        o, n = old.get("mean_s"), new.get("mean_s")
        if o is None or n is None:
            continue
        if n > o * (1.0 + tolerance) and n - o > abs_floor_s:
            problems.append(
                f"phase '{phase}' regression: mean {n * 1e3:.2f} ms vs "
                f"baseline {o * 1e3:.2f} ms (tolerance {tolerance:.0%}, "
                f"floor {abs_floor_s * 1e3:.0f} ms)"
            )
    return problems
