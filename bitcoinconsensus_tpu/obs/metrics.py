"""Thread-safe metrics registry: counters, gauges, fixed-bucket histograms.

Prometheus-shaped data model, zero dependencies: a metric has a name, a
help string, a fixed tuple of label names, and one sample per observed
label-value combination. All mutation happens under a per-metric lock, so
the registry is safe under the `_idx_threads()` interpretation pool in
`models/batch.py` and any concurrent `verify_batch` callers — the thread
contract the old `Phases` dicts violated.

Hot-path cost model: one `inc()`/`observe()` is a tuple build + one lock
acquire + one dict update (sub-microsecond). For tight loops, bind a
child once with `.labels(...)` and call `.inc()` on the bound handle —
`models/sigcache.py` does this per cache instance.

The process-global registry (`get_registry()`) is what the pipeline
instruments and what `scripts/consensus_stats.py` exposes; fresh
`MetricsRegistry` instances exist for tests and golden-output checks.
"""

from __future__ import annotations

import bisect
import math
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "counter",
    "gauge",
    "get_registry",
    "histogram",
    "DEFAULT_DURATION_BUCKETS",
]

# Span/phase durations land here: 10 µs .. 30 s covers a single counter
# bump through a cold-compile device dispatch over the tunnel.
DEFAULT_DURATION_BUCKETS: Tuple[float, ...] = (
    1e-5, 1e-4, 1e-3, 1e-2, 0.1, 0.5, 1.0, 5.0, 30.0,
)


class _Metric:
    """Shared plumbing: label validation, per-metric lock, sample store."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str]):
        self.name = name
        self.help = help
        self.labelnames: Tuple[str, ...] = tuple(labelnames)
        self._lock = threading.Lock()

    def _key(self, labels: Dict[str, object]) -> Tuple[str, ...]:
        if len(labels) != len(self.labelnames) or any(
            k not in labels for k in self.labelnames
        ):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(sorted(labels))}"
            )
        return tuple(str(labels[k]) for k in self.labelnames)


class _BoundCounter:
    """A counter pre-bound to one label combination (hot-path handle)."""

    __slots__ = ("_metric", "_key")

    def __init__(self, metric: "Counter", key: Tuple[str, ...]):
        self._metric = metric
        self._key = key

    def inc(self, amount: int = 1) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        m = self._metric
        with m._lock:
            m._values[self._key] = m._values.get(self._key, 0) + amount


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name: str, help: str = "", labelnames: Sequence[str] = ()):
        super().__init__(name, help, labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}

    def labels(self, **labels) -> _BoundCounter:
        return _BoundCounter(self, self._key(labels))

    def inc(self, amount: int = 1, **labels) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + amount

    def value(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            return self._values.get(key, 0)

    def _samples(self) -> List[dict]:
        with self._lock:
            items = sorted(self._values.items())
        return [
            {"labels": dict(zip(self.labelnames, key, strict=True)), "value": v}
            for key, v in items
        ]

    def _reset(self) -> None:
        with self._lock:
            self._values.clear()


class _BoundGauge:
    __slots__ = ("_metric", "_key")

    def __init__(self, metric: "Gauge", key: Tuple[str, ...]):
        self._metric = metric
        self._key = key

    def set(self, value) -> None:
        m = self._metric
        with m._lock:
            m._values[self._key] = value

    def add(self, amount) -> None:
        m = self._metric
        with m._lock:
            m._values[self._key] = m._values.get(self._key, 0) + amount


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name: str, help: str = "", labelnames: Sequence[str] = ()):
        super().__init__(name, help, labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}

    def labels(self, **labels) -> _BoundGauge:
        return _BoundGauge(self, self._key(labels))

    def set(self, value, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = value

    def add(self, amount, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + amount

    def value(self, **labels) -> float:
        key = self._key(labels)
        with self._lock:
            return self._values.get(key, 0)

    _samples = Counter._samples
    _reset = Counter._reset


class _BoundHistogram:
    __slots__ = ("_metric", "_key")

    def __init__(self, metric: "Histogram", key: Tuple[str, ...]):
        self._metric = metric
        self._key = key

    def observe(self, value) -> None:
        self._metric._observe(self._key, value)


class Histogram(_Metric):
    """Fixed-bucket histogram; bucket `i` counts values <= buckets[i]
    (Prometheus `le` semantics), with an implicit +Inf overflow bucket."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str = "",
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_DURATION_BUCKETS,
    ):
        super().__init__(name, help, labelnames)
        b = tuple(float(x) for x in buckets)
        if not b or any(b[i] >= b[i + 1] for i in range(len(b) - 1)):
            raise ValueError("buckets must be non-empty and strictly increasing")
        if any(not math.isfinite(x) for x in b):
            raise ValueError("buckets must be finite (+Inf is implicit)")
        self.buckets = b
        # key -> [per-bucket counts (len(buckets)+1, last is +Inf), sum, count]
        self._values: Dict[Tuple[str, ...], list] = {}

    def labels(self, **labels) -> _BoundHistogram:
        return _BoundHistogram(self, self._key(labels))

    def observe(self, value, **labels) -> None:
        self._observe(self._key(labels), value)

    def _observe(self, key: Tuple[str, ...], value) -> None:
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            cell = self._values.get(key)
            if cell is None:
                cell = self._values[key] = [[0] * (len(self.buckets) + 1), 0.0, 0]
            cell[0][i] += 1
            cell[1] += value
            cell[2] += 1

    def quantile(self, q: float, **labels) -> Optional[float]:
        """Conservative quantile estimate from the fixed buckets.

        Returns the upper bound (``le``) of the first bucket whose
        cumulative count reaches ``q * count`` — an over-estimate, which
        is the safe direction for the admission control built on it
        (serving/shedding.py). Returns None with no observations and
        ``math.inf`` when the quantile lands in the implicit +Inf
        overflow bucket.
        """
        if not 0.0 < q <= 1.0:
            raise ValueError("q must be in (0, 1]")
        key = self._key(labels)
        with self._lock:
            cell = self._values.get(key)
            if cell is None or cell[2] == 0:
                return None
            counts, count = list(cell[0]), cell[2]
        rank = q * count
        cum = 0
        for i, c in enumerate(counts[:-1]):
            cum += c
            if cum >= rank:
                return self.buckets[i]
        return math.inf

    def _samples(self) -> List[dict]:
        with self._lock:
            items = [
                (key, [list(c[0]), c[1], c[2]])
                for key, c in sorted(self._values.items())
            ]
        out = []
        for key, (counts, total, count) in items:
            cum, cum_counts = 0, []
            for c in counts:
                cum += c
                cum_counts.append(cum)
            out.append(
                {
                    "labels": dict(zip(self.labelnames, key, strict=True)),
                    "buckets": [
                        [le, cum_counts[i]] for i, le in enumerate(self.buckets)
                    ]
                    + [["+Inf", cum_counts[-1]]],
                    "sum": total,
                    "count": count,
                }
            )
        return out

    def _reset(self) -> None:
        with self._lock:
            self._values.clear()


class MetricsRegistry:
    """Name -> metric map with get-or-create registration.

    Re-registering an existing name returns the existing metric when kind
    and labelnames match (so independent modules can share e.g. the
    reject-reason counters) and raises when they conflict — a conflict is
    always a programming error, never something to paper over.
    """

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name, help, labelnames, **kw) -> _Metric:
        labelnames = tuple(labelnames)
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if type(existing) is not cls or existing.labelnames != labelnames:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind}{existing.labelnames}, requested "
                        f"{cls.kind}{labelnames}"
                    )
                return existing
            m = cls(name, help, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(
        self, name: str, help: str = "", labelnames: Sequence[str] = ()
    ) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(
        self, name: str, help: str = "", labelnames: Sequence[str] = ()
    ) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(
        self,
        name: str,
        help: str = "",
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_DURATION_BUCKETS,
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labelnames, buckets=buckets
        )

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def snapshot(self) -> Dict[str, dict]:
        """JSON-able view of every registered metric and its samples."""
        with self._lock:
            metrics = sorted(self._metrics.items())
        return {
            name: {
                "kind": m.kind,
                "help": m.help,
                "labelnames": list(m.labelnames),
                "samples": m._samples(),
            }
            for name, m in metrics
        }

    def reset(self) -> None:
        """Zero every sample; registrations (and bound handles) survive."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            m._reset()


_DEFAULT = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-global registry the verify pipeline instruments."""
    return _DEFAULT


def counter(
    name: str, help: str = "", labelnames: Iterable[str] = ()
) -> Counter:
    return _DEFAULT.counter(name, help, tuple(labelnames))


def gauge(name: str, help: str = "", labelnames: Iterable[str] = ()) -> Gauge:
    return _DEFAULT.gauge(name, help, tuple(labelnames))


def histogram(
    name: str,
    help: str = "",
    labelnames: Iterable[str] = (),
    buckets: Sequence[float] = DEFAULT_DURATION_BUCKETS,
) -> Histogram:
    return _DEFAULT.histogram(name, help, tuple(labelnames), buckets=buckets)
