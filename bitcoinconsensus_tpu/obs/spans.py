"""Nestable tracing spans over the verify pipeline's host path.

A span is a context manager timing one named region with monotonic
timestamps (`time.perf_counter`). Spans nest per thread; each records its
parent, so a JSONL sink reconstructs the call tree of a verify:

    block.connect
      batch.verify_batch
        batch.prepare
        batch.interpret
        batch.resolve
          verifier.host_prep
          verifier.dispatch
          verifier.sync

Every span aggregates into the process-global metrics registry:
`consensus_span_duration_seconds{span=...}` (histogram — its `_count` is
the call count) and `consensus_span_errors_total{span=...}` when the body
raised. With no sink attached that aggregation is the ONLY exit-path work
— no dict/JSON construction — so instrumentation stays on by default.
Attach a `JsonlSink` (or anything with a `write(record: dict)` method) to
additionally stream one JSON line per span.

Traces cross threads explicitly: every span carries a `trace` id (the
root span's id, inherited down the per-thread stack), and
`trace_context(trace, parent_span_id)` adopts a trace begun elsewhere —
a worker thread wraps its work in the submitting request's context, so
the JSONL tree no longer breaks at the thread boundary (the serving
layer's submit→coalesce→burst-worker→settle path rides this).

This module is the one sanctioned clock reader of the pipeline: the host
AST lint (`analysis/host_lint.py`) rejects direct `time.perf_counter()`
timing in `models/` and `crypto/` so all timing flows through here, and
nothing in this module is ever traced into a device kernel.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from contextlib import contextmanager
from typing import IO, Optional, Tuple, Union

from .metrics import counter, histogram

__all__ = [
    "Span",
    "JsonlSink",
    "add_sink",
    "current_span_id",
    "current_trace",
    "monotonic",
    "remove_sink",
    "span",
    "trace_context",
]

_SPAN_SECONDS = histogram(
    "consensus_span_duration_seconds",
    "wall-clock duration of pipeline spans (see README span taxonomy)",
    ("span",),
)
_SPAN_ERRORS = counter(
    "consensus_span_errors_total",
    "spans whose body raised",
    ("span",),
)
_SINK_ERRORS = counter(
    "consensus_obs_sink_errors_total",
    "span records dropped because a sink's write() raised",
    ("sink",),
)


def monotonic() -> float:
    """Sanctioned monotonic clock for host-side *policy* code.

    The resilience layer needs wall-clock deadlines (bounded retry) but is
    linted with the clock rule like `crypto/` — direct `time.*` reads are
    banned outside this module so ad-hoc timing cannot drift in beside the
    telemetry. Policy deadlines read the clock through here; consensus
    code (`core/`, `models/`) still may not read it at all.
    """
    return time.perf_counter()

_ids = itertools.count(1)  # next() is atomic under the GIL
_tls = threading.local()

# Sinks are kept in an immutable tuple swapped under a lock: the span exit
# fast path reads one module global, no lock.
_sinks: Tuple[object, ...] = ()
_sinks_lock = threading.Lock()


class Span:
    """One timed region. `duration_s` is set when the region exits."""

    __slots__ = ("name", "span_id", "parent_id", "trace", "t0", "duration_s",
                 "attrs", "error")

    def __init__(self, name: str, span_id: int, parent_id: Optional[int],
                 attrs: Optional[dict], trace: Optional[int] = None):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        # Root spans define their own trace; children inherit it, and
        # trace_context() lets another thread adopt it.
        self.trace = span_id if trace is None else trace
        self.t0 = 0.0
        self.duration_s: Optional[float] = None
        self.attrs = attrs
        self.error: Optional[str] = None


class _TraceMarker:
    """Stack entry standing in for a parent span that lives on another
    thread: carries only the identity a child needs (parent id + trace).
    Never timed, never written to sinks."""

    __slots__ = ("span_id", "trace")

    def __init__(self, span_id: Optional[int], trace: Optional[int]):
        self.span_id = span_id
        self.trace = trace


class JsonlSink:
    """Append-mode JSON-lines span sink (one dict per line), thread-safe.

    Flush behavior is bounded: at most `flush_every` records are ever
    buffered (perf workloads stream tens of thousands of spans; an
    unbounded libc buffer loses an arbitrary tail on a crash). `close()`
    is idempotent; a `write()` after close raises — the span exit path
    counts it in `consensus_obs_sink_errors_total` instead of crashing
    the verify, so a sink removed late shows up in triage, not as data
    silently appended to a dead handle.
    """

    def __init__(self, path_or_file: Union[str, IO[str]],
                 flush_every: int = 512):
        if isinstance(path_or_file, str):
            self._fh = open(path_or_file, "a", encoding="utf-8")
            self._owns = True
        else:
            self._fh = path_or_file
            self._owns = False
        self._flush_every = max(1, int(flush_every))
        self._unflushed = 0
        self._closed = False
        self._lock = threading.Lock()

    def write(self, record: dict) -> None:
        line = json.dumps(record, separators=(",", ":"), sort_keys=True)
        with self._lock:
            if self._closed:
                raise ValueError("write() on a closed JsonlSink")
            self._fh.write(line + "\n")
            self._unflushed += 1
            if self._unflushed >= self._flush_every:
                self._fh.flush()
                self._unflushed = 0

    def flush(self) -> None:
        with self._lock:
            if not self._closed:
                self._fh.flush()
                self._unflushed = 0

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._fh.flush()
            if self._owns:
                self._fh.close()


def add_sink(sink) -> None:
    """Attach a span sink (any object with `write(record: dict)`)."""
    global _sinks
    with _sinks_lock:
        _sinks = _sinks + (sink,)


def remove_sink(sink) -> None:
    global _sinks
    with _sinks_lock:
        _sinks = tuple(s for s in _sinks if s is not sink)


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current_trace() -> Optional[int]:
    """Trace id of the innermost open span (or adopted context) on this
    thread; None outside any span. Hand this (plus the span id) to work
    you queue onto another thread, and re-enter it there with
    `trace_context` so the settle side stitches back to the submit side."""
    st = getattr(_tls, "stack", None)
    return st[-1].trace if st else None


def current_span_id() -> Optional[int]:
    """Span id of the innermost open span on this thread, or None."""
    st = getattr(_tls, "stack", None)
    return st[-1].span_id if st else None


@contextmanager
def trace_context(trace: Optional[int], parent_span_id: Optional[int] = None):
    """Adopt a trace begun on another thread.

    Spans opened inside the context inherit `trace` and (for top-level
    ones) parent to `parent_span_id` — the cross-thread stitch: capture
    `(span.trace, span.span_id)` where the request is submitted, then
    wrap the worker-side settle in `trace_context(trace, span_id)`.
    Nests freely with real spans and other contexts; the innermost wins.
    No timing, no sink record — identity only.
    """
    stack = _stack()
    marker = _TraceMarker(parent_span_id, trace)
    stack.append(marker)
    try:
        yield
    finally:
        stack.pop()


@contextmanager
def span(name: str, **attrs):
    """Time a region as `name`; nest freely; yields the live Span.

    Exceptions propagate untouched (recorded as `error` on the span and in
    `consensus_span_errors_total`). Extra keyword attrs ride along into
    sink records only — they never become metric labels, so attr
    cardinality cannot pollute the registry.
    """
    stack = _stack()
    parent = stack[-1] if stack else None
    sp = Span(
        name,
        next(_ids),
        parent.span_id if parent is not None else None,
        attrs or None,
        trace=parent.trace if parent is not None else None,
    )
    stack.append(sp)
    sp.t0 = time.perf_counter()
    try:
        yield sp
    except BaseException as e:
        sp.error = type(e).__name__
        raise
    finally:
        dt = time.perf_counter() - sp.t0
        sp.duration_s = dt
        stack.pop()
        _SPAN_SECONDS.observe(dt, span=name)
        if sp.error is not None:
            _SPAN_ERRORS.inc(span=name)
        sinks = _sinks
        if sinks:
            record = {
                "name": name,
                "span_id": sp.span_id,
                "parent_id": sp.parent_id,
                "trace": sp.trace,
                "thread": threading.get_ident(),
                "pid": os.getpid(),
                "t0": round(sp.t0, 9),
                "dur_s": round(dt, 9),
            }
            if sp.attrs:
                record["attrs"] = sp.attrs
            if sp.error is not None:
                record["error"] = sp.error
            for s in sinks:
                try:
                    s.write(record)
                except Exception:
                    # A broken sink must never take down a verify — but a
                    # sink dying mid-chaos-run must not vanish without
                    # trace either: every dropped record is counted.
                    _SINK_ERRORS.inc(sink=type(s).__name__)
