"""Registry of consensus kernels the static analyzer must prove.

Every traced program whose output feeds a consensus verdict is listed
here with the input bounds it is entitled to assume (the same contracts
`ops/limbs.py` documents: W2 weak-representation rows for field inputs,
canonical rows for unpacked coordinates, small windows for digits) and
the output bounds it promises (checked against the analyzer's derived
intervals — `out_within` failing means the hand bookkeeping understates
reality, which is a release blocker, not an analyzer bug).

To register a new kernel:

    KERNELS.append(KernelSpec(
        name="my_kernel",
        build=lambda B: (my_fn, (arg_specs...,)),
        in_bounds={0: w2_rows(), ...},   # flat arg index -> bounds
        out_within=[w2_rows(), ...],     # or None per output
        heavy=False,                     # True: skipped by --quick / tests
    ))

and `scripts/consensus_lint.py` picks it up on the next run. Bounds are
(lo, hi) tuples, or a per-axis-0-row list of them; None means the full
lane range of the dtype.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..ops import limbs as L
from ..ops import curve as C
from ..ops import sha256 as SH
from . import interval


DEFAULT_BATCH = 2  # two lanes: keeps batch-axis structure without cost


def w2_rows() -> List[Tuple[int, int]]:
    """Weak-representation input contract: per-limb [0, W2[i]]."""
    return [(0, int(b)) for b in L.W2]


def canon_rows() -> List[Tuple[int, int]]:
    """Canonical field element: every limb in [0, MASK]."""
    return [(0, L.MASK)] * L.NLIMB


@dataclass
class KernelSpec:
    name: str
    build: Callable  # B -> (fn, arg_specs)
    in_bounds: Optional[Dict[int, object]] = None
    out_within: Optional[Sequence[object]] = None
    heavy: bool = False
    note: str = ""

    def analyze(self, batch: int = DEFAULT_BATCH) -> "interval.Report":
        fn, args = self.build(batch)
        return interval.analyze(
            fn, args, self.name,
            in_bounds=self.in_bounds, out_within=self.out_within,
        )


def _fe(B):
    return jax.ShapeDtypeStruct((L.NLIMB, B), jnp.int32)


def _flags(B):
    return jax.ShapeDtypeStruct((B,), jnp.int32)


def _bools(B):
    return jax.ShapeDtypeStruct((B,), jnp.bool_)


def _u8(B, n):
    return jax.ShapeDtypeStruct((B, n), jnp.uint8)


_W2 = None  # built lazily so importing this module stays cheap


def _specs() -> List[KernelSpec]:
    w2 = w2_rows()
    canon = canon_rows()
    fe3 = [w2, w2, w2, None]
    specs = [
        KernelSpec(
            "limbs.fe_add", lambda B: (L.fe_add, (_fe(B), _fe(B))),
            in_bounds={0: w2, 1: w2}, out_within=[w2],
        ),
        KernelSpec(
            "limbs.fe_sub", lambda B: (L.fe_sub, (_fe(B), _fe(B))),
            in_bounds={0: w2, 1: w2}, out_within=[w2],
        ),
        KernelSpec(
            "limbs.fe_mul", lambda B: (L.fe_mul, (_fe(B), _fe(B))),
            in_bounds={0: w2, 1: w2}, out_within=[w2],
            note="Karatsuba; transient int32 wraps are expected and legal",
        ),
        KernelSpec(
            "limbs.fe_sqr", lambda B: (L.fe_sqr, (_fe(B),)),
            in_bounds={0: w2}, out_within=[w2],
        ),
        KernelSpec(
            "mxu.fe_mul_onehot",
            lambda B: (_mxu_mul_fn(), (_fe(B), _fe(B))),
            in_bounds={0: w2, 1: w2}, out_within=[w2],
            note="MXU one-hot fe_mul candidate: every f32 value carries "
                 "an exactness certificate (accumulated Sigma|products| "
                 "<= 2^24 at Precision.HIGHEST); see ops/mxu_mul.py",
        ),
        KernelSpec(
            "limbs.fe_canon", lambda B: (L.fe_canon, (_fe(B),)),
            in_bounds={0: w2}, out_within=[canon],
        ),
        KernelSpec(
            "limbs.fe_is_zero", lambda B: (L.fe_is_zero, (_fe(B),)),
            in_bounds={0: w2},
        ),
        KernelSpec(
            "limbs.fe_inv", lambda B: (L.fe_inv, (_fe(B),)),
            in_bounds={0: w2}, out_within=[w2],
        ),
        KernelSpec(
            "curve.jacobian_double",
            lambda B: (C.jacobian_double, (_fe(B),) * 3),
            in_bounds={0: w2, 1: w2, 2: w2}, out_within=[w2, w2, w2],
        ),
        KernelSpec(
            "curve.jacobian_add_complete",
            lambda B: (C.jacobian_add_complete, (_fe(B),) * 6 + (_bools(B),) * 2),
            in_bounds={i: w2 for i in range(6)}, out_within=fe3,
        ),
        KernelSpec(
            "curve.jacobian_madd_complete",
            lambda B: (C.jacobian_madd_complete,
                       (_fe(B),) * 5 + (_bools(B),)),
            in_bounds={i: w2 for i in range(5)}, out_within=fe3,
        ),
        KernelSpec(
            "sha256.compress",
            lambda B: (SH.sha256_compress,
                       (jax.ShapeDtypeStruct((8, B), jnp.uint32),
                        jax.ShapeDtypeStruct((16, B), jnp.uint32))),
            note="uint32 wrap-by-spec: every op is a residue function",
        ),
        KernelSpec(
            "sha256.bip340_challenge",
            lambda B: (SH.bip340_challenge,
                       (_u8(B, 32), _u8(B, 32), _u8(B, 32))),
        ),
        KernelSpec(
            "curve.double_scalar_mult_glv",
            lambda B: (C.double_scalar_mult_glv,
                       (_fe(B),
                        jax.ShapeDtypeStruct((32, B), jnp.int32),
                        jax.ShapeDtypeStruct((32, B), jnp.int32),
                        _bools(B), _bools(B), _fe(B), _fe(B))),
            in_bounds={0: canon, 1: (0, 15), 2: (0, 15),
                       5: canon, 6: canon},
            out_within=fe3,
            heavy=True,
            note="GLV ladder: scan fixpoint over 32 windows + f32 MXU "
                 "G-table select",
        ),
        KernelSpec(
            "jax_backend.verify_kernel",
            lambda B: (_verify_kernel_fn(),
                       (jax.ShapeDtypeStruct((B, 4, 32), jnp.uint8),
                        _flags(B), _flags(B), _flags(B), _flags(B),
                        _flags(B), _bools(B))),
            in_bounds={1: (0, 1), 2: (-1, 1), 3: (0, 1), 4: (0, 1),
                       5: (0, 1)},
            out_within=[[(0, 1)] * DEFAULT_BATCH],
            heavy=True,
            note="the full device-side verify batch (~70k eqns)",
        ),
        KernelSpec(
            "jax_backend.verdict_checksum",
            lambda B: (_verdict_checksum_fn(), (_bools(B),)),
            in_bounds={0: (0, 1)},
            # count sum <= B; weighted sum <= B * (max lane weight 251)
            out_within=[[(0, DEFAULT_BATCH)], [(0, DEFAULT_BATCH * 251)]],
            note="in-flight verdict checksum: any single-lane flip moves "
                 "the count sum, any count-preserving swap moves the "
                 "weighted sum (settle seam recomputes both on host)",
        ),
        KernelSpec(
            "pallas.verify_tiles",
            lambda B: _pallas_verify_build(),
            # Flag contract single-sourced from ops/pallas_kernel.py
            # (same shape as jax_backend.verify_kernel's); the limb
            # contracts live below the byte-unpack preamble and are
            # re-derived, not assumed.
            in_bounds=_pallas_flag_bounds(),
            # Two (B,) verdict vectors, each lane provably 0/1 — the same
            # pin the XLA verify kernel carries, independently re-derived
            # through the Mosaic kernel's Ref semantics.
            out_within=[[(0, 1)] * _PALLAS_B] * 2,
            heavy=True,
            note="the fused Mosaic kernel: Ref-semantics interval proof + "
                 "grid/BlockSpec + VMEM budget (analysis/pallas_check.py)",
        ),
    ]
    return specs


def _mxu_mul_fn():
    from ..ops import mxu_mul as M
    return M.fe_mul_onehot


def _verify_kernel_fn():
    from ..crypto import jax_backend as JB
    return JB._verify_kernel


def _verdict_checksum_fn():
    from ..crypto import jax_backend as JB
    return JB._verdict_checksum


# verify_tiles requires B % LANE_TILE == 0 and a multi-step grid is the
# interesting case, so the Pallas spec ignores the requested batch and
# proves two full lane tiles.
_PALLAS_B = 1024  # == 2 * ops.pallas_kernel.LANE_TILE


def _pallas_flag_bounds():
    from ..ops import pallas_kernel as PK
    return dict(PK.FLAG_BOUNDS)


def _pallas_verify_build():
    from . import pallas_check  # noqa: F401  registers the Ref rules
    from ..ops import pallas_kernel as PK

    assert _PALLAS_B == 2 * PK.LANE_TILE
    B = _PALLAS_B

    def fn(fields, want_odd, parity_req, has_t2, neg1, neg2, valid):
        return PK.verify_tiles(fields, want_odd, parity_req, has_t2,
                               neg1, neg2, valid)

    return fn, (jax.ShapeDtypeStruct((B, 4, 32), jnp.uint8),
                _flags(B), _flags(B), _flags(B), _flags(B),
                _flags(B), _bools(B))


@dataclass
class ScheduleSpec:
    """A scalar-schedule prover target (analysis/scalar_check.py): digit
    recoders, the GLV split, and the window ladders.  `certify` returns a
    CertResult whose status is THEOREM / VACUOUS / FAIL — fail-closed, the
    same discipline as the interval kernels above."""

    name: str
    heavy: bool = False  # heavy: eager ledger walk (~1-2 min on CPU)
    note: str = ""

    def certify(self, quick: bool = False):
        from . import scalar_check
        return scalar_check.certify(self.name, quick=quick)


def _schedule_specs() -> List[ScheduleSpec]:
    return [
        ScheduleSpec("scalar._digits",
                     note="4-bit window recoding: exact bit-slice theorem"),
        ScheduleSpec("scalar._digits128",
                     note="4-bit recoding of GLV halves + congruence planes"),
        ScheduleSpec("scalar.bytes_to_limbs",
                     note="byte->limb packing, 32B/20L and 16B/10L"),
        ScheduleSpec("sha256.bytes_from_words",
                     note="digest word->byte unpack, big-endian slices"),
        ScheduleSpec("scalar._signed_digits128",
                     note="signed window recoder: exhaustive carry automaton"),
        ScheduleSpec("glv.split_lambda",
                     note="lattice constants + |k1|,|k2| < 2^128 certificate"),
        ScheduleSpec("curve.double_scalar_mult", heavy=True,
                     note="Strauss ladder weight ledger + differential"),
        ScheduleSpec("curve.double_scalar_mult_glv", heavy=True,
                     note="GLV ladder weight ledger + differential"),
        ScheduleSpec("pallas.kernel_schedule", heavy=True,
                     note="Mosaic kernel: table object-flow + signed ledger"),
    ]


def all_schedules(include_heavy: bool = True) -> List[ScheduleSpec]:
    specs = _schedule_specs()
    if not include_heavy:
        specs = [s for s in specs if not s.heavy]
    return specs


def get_schedule(name: str) -> ScheduleSpec:
    for s in _schedule_specs():
        if s.name == name:
            return s
    raise KeyError(name)


def all_kernels(include_heavy: bool = True) -> List[KernelSpec]:
    specs = _specs()
    if not include_heavy:
        specs = [s for s in specs if not s.heavy]
    return specs


def get_kernel(name: str) -> KernelSpec:
    for s in _specs():
        if s.name == name:
            return s
    raise KeyError(name)
