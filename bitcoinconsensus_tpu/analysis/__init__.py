"""Static analysis of the consensus kernels.

`interval` — jaxpr-level interval abstract interpretation (the int32
overflow prover) fused with the determinism/op-allowlist gate.
`pallas_check` — the same engine pushed below the jaxpr into Pallas
kernels: abstract Ref semantics, grid/BlockSpec checks, VMEM budget,
ref-discipline lint (importing it registers the state-primitive rules).
`registry` — the kernels the prover must certify, with their input
contracts. `host_lint` — AST lint of the plain-Python consensus path.

Entry point: `scripts/consensus_lint.py` (also the CI `analysis` job).
"""

from .interval import (  # noqa: F401
    ALLOWED_PRIMITIVES,
    AbstractArray,
    Report,
    Violation,
    analyze,
    analyze_closed,
)
from .host_lint import LintFinding, lint_consensus_host, lint_paths  # noqa: F401
from .registry import KernelSpec, all_kernels, get_kernel  # noqa: F401
from .pallas_check import (  # noqa: F401
    NEGATIVES,
    RefAbstract,
    VMEM_BUDGET_BYTES,
    analyze_negative,
    analyze_positive_toy,
)
