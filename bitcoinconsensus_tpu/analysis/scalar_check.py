"""Scalar-schedule prover: machine-checked certificates for the scalar
pipeline that the kernel arc is about to rewrite.

The interval / Pallas / f32-exactness provers (PR 1/4/16) certify
*limb-level* arithmetic; this module certifies the *scalar-level*
semantics above it — the digit recoders, the GLV lattice split, and the
doubling/add window schedules — so a window-order swap or a carry
off-by-one is a static-analysis FAIL instead of a silent consensus bug.
Four legs, all fail-closed (an unproven or unevaluable claim is FAIL,
never VACUOUS):

1. **Bit-slice recombination theorems** (`_Sym`): each recoder
   (`scalar_bits`-based `_digits`, `_digits128`, the raw digits feeding
   `_signed_digits128`, and `bytes_to_limbs` packing) is abstractly
   interpreted over symbolic bit variables b_i.  Every window digit must
   equal Σ_{i<width} 2^i · b_{w·width+i} *exactly* — which makes the
   radix recombination Σ_w d_w · 2^(w·width) = Σ_i 2^i · b_i an identity,
   not a sampled test.  The interval domain's congruence facts
   (`interval.AbstractArray.cong`, added alongside this module) prove the
   same plane-divisibility/range structure inside the abstract
   interpreter for the windows whose weights fit int32.

2. **Carry-automaton proof** of `_signed_digits128`: the recoder is a
   2×32-state automaton (carry × window value).  We (a) enumerate every
   transition of the spec δ and check the telescoping invariant
   d + 32·c' = v + c with d ∈ [-16, 15], (b) check the traced function
   is literally one length-26 forward scan over the proven-exact raw
   digits, and (c) drive the *device* function through all 1584
   reachable (window, value, carry) configurations in one batched call
   and compare against an independent host recoder — including the
   claimed "top window never carries out (bits 125..127 + carry ≤ 8 <
   16)" fact at ops/pallas_kernel.py:109, which is discharged
   mechanically here instead of trusted.

3. **Exact GLV certificate** for `crypto/glv.py`: λ³ ≡ 1 (mod n),
   β³ ≡ 1 (mod p), λ·G = (β·x, y) on the actual generator, the lattice
   basis relation (adjugate rows A_i = minrep(-λ·B_i mod n) with
   determinant A1·B2 − A2·B1 = n), and the worst-case rounding bound
   |k1|, |k2| ≤ (|A1|+|A2|)//2 + 1 < 2^128 derived from exact integer
   arithmetic — plus a structured-k panel through the real
   `split_lambda`.  Corrupting any constant breaks the determinant or a
   cube identity, so the certificate is not refutable by re-deriving
   from the corrupted values.

4. **Schedule ledger**: the production ladders (`double_scalar_mult`,
   `double_scalar_mult_glv`, the Pallas `_kernel_body`) are executed
   eagerly under an instrumented `lax.fori_loop` that runs every window
   iteration with a concrete Python index while spies record each
   jacobian double/add and each digit-array read.  From the recording we
   build the weight ledger: accumulating R ← 2^D·R + d_{w(i)}·P over the
   loop gives digit w a final coefficient of 2^(D·(count−1−i)); the
   prover asserts coefficient(w) == 2^(width·w) for EVERY window — which
   is exactly "the ledger sum equals the recoder's radix decomposition"
   and catches swapped window order, dropped doublings, and
   doubling-count drift in one identity.  Table-entry multiples are
   certified separately (host differential for `_p_table` / `_g_table`;
   object-flow chain proof + `iota+1` index check for the Pallas VMEM
   table), and the XLA walks double as end-to-end differentials against
   the exact host implementation (all iterations really run, in order,
   on concrete values).

`NEGATIVES` holds planted-unsound variants (wrong carry fold, swapped
window order, dropped doubling, out-of-range digit weights, corrupted
GLV constant); `analyze_negative` must REJECT each one — the same
discipline as `pallas_check.NEGATIVES` and the f32 exactness toys.

Registering a new recoder or schedule: add the function name to
`REGISTERED_RECODERS` (host_lint's scalar-coverage rule requires it),
add a `_target_*` prover entry to `TARGETS`, and give it a planted
negative if it introduces a new failure mode.
"""

from __future__ import annotations

import ast
import inspect
import math
import textwrap
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import interval
from ..crypto import glv as glv_mod
from ..crypto import secp_host as host
from ..ops import curve as curve_mod
from ..ops import limbs as limbs_mod
from ..ops import pallas_kernel as pk_mod

RADIX = limbs_mod.RADIX
MASK = limbs_mod.MASK
NLIMB = limbs_mod.NLIMB


# --------------------------------------------------------------------------
# results
# --------------------------------------------------------------------------

@dataclass
class CertResult:
    """One certificate: THEOREM (proved, with facts), VACUOUS (ran but
    proved nothing), or FAIL (refuted or unevaluable — fail closed)."""

    name: str
    status: str                      # THEOREM | VACUOUS | FAIL
    facts: Dict[str, Any] = field(default_factory=dict)
    failures: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return self.status == "THEOREM"

    def to_dict(self) -> dict:
        return {"name": self.name, "status": self.status, "ok": self.ok,
                "facts": self.facts, "failures": self.failures}


def _finish(name: str, facts: Dict[str, Any],
            failures: List[str]) -> CertResult:
    if failures:
        return CertResult(name, "FAIL", facts, failures)
    if not facts:
        return CertResult(name, "VACUOUS", facts, ["no facts proven"])
    return CertResult(name, "THEOREM", facts, [])


# --------------------------------------------------------------------------
# Leg 1 — symbolic bit-slice evaluator
# --------------------------------------------------------------------------

class SymUnsupported(Exception):
    """A primitive or operand shape the bit-slice domain cannot model
    exactly.  Callers turn this into FAIL — never into a skip."""


class Lin:
    """Exact integer-linear form  const + Σ coeff_b · b  over bit
    variables b ∈ {0, 1}.  All arithmetic is exact Python-int; any
    operation that cannot be represented exactly raises SymUnsupported.

    The *packed* normal form (const == 0, every coefficient a distinct
    power of two, at most one term per bit) is what justifies the
    nonlinear ops: `x >> c` drops positions < c exactly (their sum is
    < 2^c), `x & (2^t - 1)` keeps positions < t, and `x | y` with
    disjoint position sets is addition."""

    __slots__ = ("terms", "const")

    def __init__(self, terms: Optional[Dict[int, int]] = None,
                 const: int = 0):
        self.terms = {b: c for b, c in (terms or {}).items() if c != 0}
        self.const = const

    # -- helpers ----------------------------------------------------------
    @property
    def is_const(self) -> bool:
        return not self.terms

    def value_bounds(self) -> Tuple[int, int]:
        lo = self.const + sum(c for c in self.terms.values() if c < 0)
        hi = self.const + sum(c for c in self.terms.values() if c > 0)
        return lo, hi

    def packed(self) -> Optional[Dict[int, int]]:
        """{bit-position: bit-id} if in packed normal form, else None."""
        if self.const != 0:
            return None
        pos: Dict[int, int] = {}
        for b, c in self.terms.items():
            if c <= 0 or (c & (c - 1)) != 0:
                return None
            p = c.bit_length() - 1
            if p in pos:
                return None
            pos[p] = b
        return pos

    def __eq__(self, other):
        if isinstance(other, int):
            return self.is_const and self.const == other
        if not isinstance(other, Lin):
            return NotImplemented
        return self.const == other.const and self.terms == other.terms

    def __hash__(self):
        return hash((self.const, tuple(sorted(self.terms.items()))))

    def __repr__(self):
        ts = " + ".join(f"{c}*b{b}" for b, c in sorted(self.terms.items()))
        return f"Lin({self.const}{' + ' + ts if ts else ''})"

    # -- exact ring ops ---------------------------------------------------
    @staticmethod
    def _coerce(x) -> "Lin":
        if isinstance(x, Lin):
            return x
        if isinstance(x, (int, np.integer)):
            return Lin(const=int(x))
        raise SymUnsupported(f"cannot coerce {type(x).__name__}")

    def __add__(self, other):
        o = Lin._coerce(other)
        t = dict(self.terms)
        for b, c in o.terms.items():
            t[b] = t.get(b, 0) + c
        return Lin(t, self.const + o.const)

    __radd__ = __add__

    def __neg__(self):
        return Lin({b: -c for b, c in self.terms.items()}, -self.const)

    def __sub__(self, other):
        return self + (-Lin._coerce(other))

    def __rsub__(self, other):
        return (-self) + Lin._coerce(other)

    def __mul__(self, other):
        o = Lin._coerce(other)
        if o.is_const:
            k = o.const
            return Lin({b: c * k for b, c in self.terms.items()},
                       self.const * k)
        if self.is_const:
            k = self.const
            return Lin({b: c * k for b, c in o.terms.items()}, o.const * k)
        raise SymUnsupported("nonlinear product of two symbolic forms")

    __rmul__ = __mul__

    def __lshift__(self, other):
        o = Lin._coerce(other)
        if not o.is_const or o.const < 0:
            raise SymUnsupported("symbolic/negative shift amount")
        return self * (1 << o.const)

    def __rshift__(self, other):
        o = Lin._coerce(other)
        if not o.is_const or o.const < 0:
            raise SymUnsupported("symbolic/negative shift amount")
        c = o.const
        if self.is_const:
            if self.const < 0:
                raise SymUnsupported("rshift of negative constant")
            return Lin(const=self.const >> c)
        pos = self.packed()
        if pos is None:
            raise SymUnsupported("rshift of non-packed form")
        return Lin({b: 1 << (p - c) for p, b in pos.items() if p >= c})

    def __and__(self, other):
        o = Lin._coerce(other)
        if self.is_const and o.is_const:
            if self.const < 0 or o.const < 0:
                raise SymUnsupported("bitand of negative constants")
            return Lin(const=self.const & o.const)
        if o.is_const:
            sym, mask = self, o.const
        elif self.is_const:
            sym, mask = o, self.const
        else:
            raise SymUnsupported("bitand of two symbolic forms")
        if mask < 0 or (mask & (mask + 1)) != 0:
            raise SymUnsupported(f"bitand with non-low-mask {mask:#x}")
        t = mask.bit_length()          # mask == 2^t - 1
        pos = sym.packed()
        if pos is None:
            raise SymUnsupported("bitand of non-packed form")
        return Lin({b: 1 << p for p, b in pos.items() if p < t})

    def __or__(self, other):
        o = Lin._coerce(other)
        if self.is_const and self.const == 0:
            return o
        if o.is_const and o.const == 0:
            return self
        if self.is_const and o.is_const:
            if self.const < 0 or o.const < 0:
                raise SymUnsupported("bitor of negative constants")
            return Lin(const=self.const | o.const)
        pa, pb = self.packed(), o.packed()
        if pa is None or pb is None or (set(pa) & set(pb)):
            raise SymUnsupported("bitor of overlapping/non-packed forms")
        return self + o

    __ror__ = __or__


def _sym_const(arr: np.ndarray) -> np.ndarray:
    out = np.empty(arr.shape, dtype=object)
    flat = out.reshape(-1)
    src = np.asarray(arr).reshape(-1)
    for i in range(flat.shape[0]):
        flat[i] = Lin(const=int(src[i]))
    return out


def _sym_eval(closed, args: List[np.ndarray]) -> List[np.ndarray]:
    """Interpret a ClosedJaxpr over numpy object arrays of `Lin`."""
    return _sym_eval_jaxpr(closed.jaxpr, closed.consts, args)


def _sym_eval_jaxpr(jaxpr, consts, args):
    env: Dict[Any, np.ndarray] = {}

    def read(v):
        if isinstance(v, jax.extend.core.Literal):
            return _sym_const(np.asarray(v.val))
        return env[v]

    def write(v, val):
        env[v] = val

    for v, c in zip(jaxpr.constvars, consts):
        write(v, _sym_const(np.asarray(c)))
    for v, a in zip(jaxpr.invars, args):
        write(v, np.asarray(a, dtype=object))

    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        ins = [read(v) for v in eqn.invars]
        p = eqn.params
        if prim in ("pjit", "closed_call", "core_call", "custom_jvp_call",
                    "custom_vjp_call", "remat", "checkpoint"):
            inner = p.get("jaxpr") or p.get("call_jaxpr")
            if hasattr(inner, "jaxpr"):        # ClosedJaxpr
                outs = _sym_eval_jaxpr(inner.jaxpr, inner.consts, ins)
            else:
                outs = _sym_eval_jaxpr(inner, (), ins)
        elif prim == "add":
            outs = [np.add(*np.broadcast_arrays(*ins))]
        elif prim == "sub":
            outs = [np.subtract(*np.broadcast_arrays(*ins))]
        elif prim == "mul":
            outs = [np.multiply(*np.broadcast_arrays(*ins))]
        elif prim == "neg":
            outs = [np.negative(ins[0])]
        elif prim == "and":
            outs = [np.bitwise_and(*np.broadcast_arrays(*ins))]
        elif prim == "or":
            outs = [np.bitwise_or(*np.broadcast_arrays(*ins))]
        elif prim in ("shift_right_logical", "shift_right_arithmetic"):
            # identical on our domain: packed forms are non-negative by
            # construction and constant operands are checked >= 0.
            outs = [np.right_shift(*np.broadcast_arrays(*ins))]
        elif prim == "shift_left":
            outs = [np.left_shift(*np.broadcast_arrays(*ins))]
        elif prim == "reduce_sum":
            outs = [np.sum(ins[0], axis=tuple(p["axes"]))]
        elif prim == "convert_element_type":
            nd = p["new_dtype"]
            if not jnp.issubdtype(nd, jnp.integer):
                raise SymUnsupported(f"convert to non-integer {nd}")
            outs = [ins[0]]            # exactness checked by the caller's
                                       # range facts; int->int is identity
                                       # whenever the value fits, and every
                                       # theorem also proves the range.
        elif prim == "reshape":
            outs = [np.reshape(ins[0], p["new_sizes"])]
        elif prim == "squeeze":
            outs = [np.squeeze(ins[0], axis=tuple(p["dimensions"]))]
        elif prim == "expand_dims":
            outs = [np.expand_dims(ins[0], axis=tuple(p["dimensions"]))]
        elif prim == "transpose":
            outs = [np.transpose(ins[0], p["permutation"])]
        elif prim == "rev":
            sl = tuple(slice(None, None, -1) if d in p["dimensions"]
                       else slice(None) for d in range(ins[0].ndim))
            outs = [ins[0][sl]]
        elif prim == "broadcast_in_dim":
            shape = p["shape"]
            newshape = [1] * len(shape)
            for i, d in enumerate(p["broadcast_dimensions"]):
                newshape[d] = ins[0].shape[i]
            outs = [np.broadcast_to(ins[0].reshape(newshape), shape)]
        elif prim == "slice":
            sl = tuple(slice(s, l, st) for s, l, st in
                       zip(p["start_indices"], p["limit_indices"],
                           p["strides"] or [1] * len(p["start_indices"])))
            outs = [ins[0][sl]]
        elif prim == "concatenate":
            outs = [np.concatenate(ins, axis=p["dimension"])]
        elif prim == "iota":
            idx = np.indices(p["shape"])[p["dimension"]]
            outs = [_sym_const(idx)]
        elif prim == "pad":
            x, pv = ins[0], ins[1].reshape(-1)[0]
            cfg = p["padding_config"]
            shape = tuple(lo + hi + max(0, x.shape[i] - 1) * it + x.shape[i]
                          for i, (lo, hi, it) in enumerate(cfg))
            out = np.empty(shape, dtype=object)
            out[...] = pv
            sl = tuple(slice(lo, lo + max(0, x.shape[i] - 1) * (it + 1) + 1,
                             it + 1)
                       for i, (lo, hi, it) in enumerate(cfg))
            out[sl] = x
            outs = [out]
        elif prim == "copy" or prim == "stop_gradient":
            outs = [ins[0]]
        else:
            raise SymUnsupported(f"primitive `{prim}` outside the "
                                 "bit-slice domain")
        for v, o in zip(eqn.outvars, outs):
            write(v, o)
    return [read(v) for v in jaxpr.outvars]


def _seed_limb_bits(nlimb: int) -> np.ndarray:
    """(nlimb, 1) object array: limb l = Σ_{i<RADIX} 2^i · b_{RADIX·l+i}.
    Bit id == absolute bit position of the packed integer."""
    out = np.empty((nlimb, 1), dtype=object)
    for l in range(nlimb):
        out[l, 0] = Lin({RADIX * l + i: 1 << i for i in range(RADIX)})
    return out


def _seed_byte_bits(nbytes: int) -> np.ndarray:
    """(1, nbytes) object array: byte k = Σ_{i<8} 2^i · b_{8k+i}."""
    out = np.empty((1, nbytes), dtype=object)
    for k in range(nbytes):
        out[0, k] = Lin({8 * k + i: 1 << i for i in range(8)})
    return out


def _expected_window(w: int, width: int) -> Lin:
    return Lin({w * width + i: 1 << i for i in range(width)})


def _prove_digit_slices(name: str, fn, seed: np.ndarray,
                        count: int, width: int,
                        facts: Dict[str, Any],
                        failures: List[str]) -> None:
    """Core recombination theorem: fn(seed)[w] == Σ 2^i b_{w·width+i}."""
    try:
        shape = tuple(int(d) for d in seed.shape)
        closed = jax.make_jaxpr(fn)(
            jax.ShapeDtypeStruct(shape, jnp.int32))
        (digits,) = _sym_eval(closed, [seed])
    except SymUnsupported as e:
        failures.append(f"{name}: symbolic evaluation failed: {e}")
        return
    except Exception as e:  # noqa: BLE001 — unevaluable is FAIL
        failures.append(f"{name}: {type(e).__name__}: {e}")
        return
    if digits.shape[0] != count:
        failures.append(f"{name}: expected {count} windows, traced "
                        f"{digits.shape[0]}")
        return
    max_digit = 0
    recomb = Lin()
    for w in range(count):
        d = digits[w].reshape(-1)[0]
        want = _expected_window(w, width)
        if d != want:
            failures.append(
                f"{name}: window {w} is {d!r}, not the exact bit slice "
                f"{want!r} — recombination broken")
            continue
        lo, hi = d.value_bounds()
        max_digit = max(max_digit, hi)
        if not (0 <= lo and hi <= (1 << width) - 1):
            failures.append(f"{name}: window {w} range [{lo},{hi}] "
                            f"outside [0, 2^{width}-1]")
        recomb = recomb + d * (1 << (w * width))
    want_total = Lin({i: 1 << i for i in range(count * width)})
    if recomb != want_total:
        failures.append(f"{name}: Σ d_w·2^(w·width) != Σ 2^i·b_i over the "
                        f"consumed {count * width} bits")
    if not failures:
        facts[name] = {
            "windows": count, "width": width,
            "bits_consumed": count * width,
            "max_digit": max_digit,
            "recombination": "sum(d_w * 2^(w*width)) == sum(2^i * b_i)",
        }


def _prove_bytes_to_limbs(nbytes: int, nlimb: int,
                          facts: Dict[str, Any],
                          failures: List[str]) -> None:
    name = f"bytes_to_limbs[{nbytes}B->{nlimb}L]"
    try:
        closed = jax.make_jaxpr(
            lambda u8: limbs_mod.bytes_to_limbs(u8, nlimb=nlimb))(
                jax.ShapeDtypeStruct((1, nbytes), jnp.uint8))
        (limbs,) = _sym_eval(closed, [_seed_byte_bits(nbytes)])
    except SymUnsupported as e:
        failures.append(f"{name}: symbolic evaluation failed: {e}")
        return
    except Exception as e:  # noqa: BLE001
        failures.append(f"{name}: {type(e).__name__}: {e}")
        return
    nbits = nbytes * 8
    recomb = Lin()
    for l in range(limbs.shape[0]):
        got = limbs[l].reshape(-1)[0]
        want = Lin({RADIX * l + i: 1 << i for i in range(RADIX)
                    if RADIX * l + i < nbits})
        if got != want:
            failures.append(f"{name}: limb {l} is {got!r}, expected the "
                            f"exact bit slice {want!r}")
            continue
        recomb = recomb + got * (1 << (RADIX * l))
    if recomb != Lin({i: 1 << i for i in range(nbits)}):
        failures.append(f"{name}: Σ limb_l·2^(13·l) != Σ 2^i·b_i")
    if not failures:
        facts[name] = {"bytes": nbytes, "limbs": nlimb,
                       "recombination":
                       "sum(limb_l * 2^(13*l)) == sum(2^i * b_i)"}


def _prove_cong_planes(facts: Dict[str, Any],
                       failures: List[str]) -> None:
    """Interval+congruence leg: run the weighted-plane recombiner through
    the abstract interpreter.  plane_w = d_w · 2^(4w) must carry the
    congruence fact ≡ 0 (mod 2^(4w)) and the interval [0, 2^(4w+4)-2^(4w)]
    — divisibility + range + disjoint support is the analyzer-level shape
    of the exact recombination (the full identity is leg 1's _Sym proof;
    int32 caps the planes at window 6)."""
    n_planes = 7                      # 4·6+4 = 28 bits < int32

    def planes(limbs):
        d = curve_mod._digits(limbs, 4, 64)
        return jnp.stack([d[w] << (4 * w) for w in range(n_planes)], axis=0)

    try:
        rep = interval.analyze(planes, [jnp.zeros((NLIMB, 2), jnp.int32)],
                               in_bounds={0: (0, MASK)},
                               name="scalar.digit_planes")
    except Exception as e:  # noqa: BLE001
        failures.append(f"cong-planes: {type(e).__name__}: {e}")
        return
    if not rep.ok:
        failures.append("cong-planes: interval prover found violations: "
                        + "; ".join(str(v) for v in rep.violations[:3]))
        return
    if not rep.out_cong or not rep.out_cong[0]:
        failures.append("cong-planes: analyzer derived no congruence "
                        "facts for the digit planes")
        return
    rows = rep.out_cong[0]
    if len(rows) == 1:
        rows = rows * n_planes
    proved = 0
    for w in range(n_planes):
        fact = rows[w]
        m = 1 << (4 * w)
        if w == 0:
            proved += 1               # ≡ 0 (mod 1) is trivially carried
            continue
        if fact is None or fact[0] % m != 0 and fact[0] != 0 or \
                fact[1] % m != 0:
            failures.append(
                f"cong-planes: plane {w} fact {fact} does not prove "
                f"≡ 0 (mod 2^{4 * w})")
            continue
        proved += 1
    lo_hi = rep.out_bounds[0] if rep.out_bounds else []
    if not failures:
        facts["cong_planes"] = {
            "planes": proved,
            "rule": "plane_w ≡ 0 (mod 2^(4w)), plane_w < 2^(4w+4)",
            "bounds_rows": len(lo_hi),
        }


def _target_digits() -> CertResult:
    facts: Dict[str, Any] = {}
    failures: List[str] = []
    _prove_digit_slices("_digits[w4,c64]",
                        lambda l: curve_mod._digits(l, 4, 64),
                        _seed_limb_bits(NLIMB), 64, 4, facts, failures)
    _prove_digit_slices("_digits[w8,c32]",
                        lambda l: curve_mod._digits(l, 8, 32),
                        _seed_limb_bits(NLIMB), 32, 8, facts, failures)
    _prove_cong_planes(facts, failures)
    return _finish("scalar._digits", facts, failures)


def _target_digits128() -> CertResult:
    facts: Dict[str, Any] = {}
    failures: List[str] = []
    _prove_digit_slices("_digits128[w4,c32]",
                        lambda l: curve_mod._digits128(l, 32, 4),
                        _seed_limb_bits(10), 32, 4, facts, failures)
    _prove_digit_slices("_digits128[w5,c26]",
                        lambda l: curve_mod._digits128(l, 26, 5),
                        _seed_limb_bits(10), 26, 5, facts, failures)
    # Composed with the device unpack of a 16-byte (< 2^128) value, the
    # top 5-bit window must touch only bits 125..127 — the premise of the
    # no-carry-out claim the automaton leg discharges.
    try:
        closed = jax.make_jaxpr(
            lambda u8: curve_mod._digits128(
                limbs_mod.bytes_to_limbs(u8, nlimb=10), 26, 5))(
                    jax.ShapeDtypeStruct((1, 16), jnp.uint8))
        (raw,) = _sym_eval(closed, [_seed_byte_bits(16)])
        top = raw[25].reshape(-1)[0]
        want = Lin({125: 1, 126: 2, 127: 4})
        if top != want:
            failures.append(f"top window of _digits128(bytes16) is "
                            f"{top!r}, expected bits 125..127 only")
        else:
            facts["top_window"] = {"bits": [125, 126, 127], "max": 7}
    except SymUnsupported as e:
        failures.append(f"top-window slice: {e}")
    except Exception as e:  # noqa: BLE001
        failures.append(f"top-window slice: {type(e).__name__}: {e}")
    return _finish("scalar._digits128", facts, failures)


def _target_bytes_to_limbs() -> CertResult:
    facts: Dict[str, Any] = {}
    failures: List[str] = []
    _prove_bytes_to_limbs(32, NLIMB, facts, failures)
    _prove_bytes_to_limbs(16, 10, facts, failures)
    return _finish("scalar.bytes_to_limbs", facts, failures)


def _target_bytes_from_words() -> CertResult:
    """Digest unpack `sha256._bytes_from_words`: byte j of the output is
    the exact big-endian 8-bit slice of word j//4 — same bit-slice domain
    as the limb packers (the host_lint scalar-coverage rule flags this
    function's `(w >> shifts) & 0xFF` extraction, so it is certified)."""
    from ..ops import sha256 as sha_mod

    facts: Dict[str, Any] = {}
    failures: List[str] = []
    name = "bytes_from_words[8W->32B]"
    seed = np.empty((8,), dtype=object)
    for w in range(8):
        seed[w] = Lin({32 * w + i: 1 << i for i in range(32)})
    try:
        closed = jax.make_jaxpr(sha_mod._bytes_from_words)(
            jax.ShapeDtypeStruct((8,), jnp.uint32))
        (out,) = _sym_eval(closed, [seed])
    except SymUnsupported as e:
        failures.append(f"{name}: symbolic evaluation failed: {e}")
        return _finish("sha256.bytes_from_words", facts, failures)
    except Exception as e:  # noqa: BLE001 — unevaluable is FAIL
        failures.append(f"{name}: {type(e).__name__}: {e}")
        return _finish("sha256.bytes_from_words", facts, failures)
    for j in range(32):
        word, pos = j // 4, j % 4
        sh = 8 * (3 - pos)  # big-endian byte order within each word
        want = Lin({32 * word + sh + i: 1 << i for i in range(8)})
        got = out.reshape(-1)[j]
        if got != want:
            failures.append(f"{name}: byte {j} is {got!r}, expected the "
                            f"big-endian slice {want!r}")
    if not failures:
        facts[name] = {"words": 8, "bytes": 32, "order": "big-endian",
                       "rule": "byte j == bits 8*(3-j%4)..+8 of word j//4"}
    return _finish("sha256.bytes_from_words", facts, failures)


# --------------------------------------------------------------------------
# Leg 2 — carry automaton for _signed_digits128
# --------------------------------------------------------------------------

def _ref_signed_recode(x: int, *, threshold: int = 16,
                       wrap: int = 32) -> List[int]:
    """Independent host recoder: 26 signed 5-bit windows, LSB first."""
    assert 0 <= x < 1 << 128
    digits = []
    carry = 0
    for w in range(pk_mod.SGLV_WINDOWS):
        t = ((x >> (5 * w)) & 31) + carry
        carry = 1 if t >= threshold else 0
        digits.append(t - wrap * carry)
    assert carry == 0, "top window carried out"
    return digits


def _count_scans(jaxpr, found: List[Any]) -> None:
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "scan":
            found.append(eqn)
        for v in eqn.params.values():
            inner = getattr(v, "jaxpr", v)
            if hasattr(inner, "eqns"):
                _count_scans(inner, found)


def prove_carry_automaton(step_fn=None) -> CertResult:
    """Exhaustive proof of the signed-digit recoder.

    `step_fn(t) -> (carry', digit)` defaults to the production fold
    (t >= 16 → t − 32); negatives pass a corrupted fold."""
    facts: Dict[str, Any] = {}
    failures: List[str] = []

    def default_step(t: int) -> Tuple[int, int]:
        c = 1 if t >= 16 else 0
        return c, t - 32 * c

    step = step_fn or default_step

    # (a) every transition of the 2 x 32 automaton
    for c in (0, 1):
        for v in range(32):
            cp, d = step(v + c)
            if d + 32 * cp != v + c:
                failures.append(
                    f"automaton: δ({c},{v}) = (c'={cp}, d={d}) breaks the "
                    f"telescoping invariant d + 32·c' = v + c")
            if not (-16 <= d <= 15) or cp not in (0, 1):
                failures.append(
                    f"automaton: δ({c},{v}) digit {d} / carry {cp} "
                    "outside [-16,15] x {0,1}")
    # top window: raw digit 25 ∈ [0,7] (proven by leg 1), so t = v+c <= 8
    for c in (0, 1):
        for v in range(8):
            cp, _ = step(v + c)
            if cp != 0:
                failures.append(
                    f"automaton: top window carries out at (c={c}, v={v}) "
                    "— ops/pallas_kernel.py:109 claim refuted")
    if not failures:
        facts["transitions"] = {"states": 2 * 32, "invariant":
                                "d + 32·c' = v + c, d ∈ [-16,15]",
                                "top_window_no_carry": "t = v+c <= 8 < 16"}

    # (b) the traced recoder is one forward length-26 scan
    try:
        closed = jax.make_jaxpr(pk_mod._signed_digits128)(
            jax.ShapeDtypeStruct((10, 1), jnp.int32))
        scans: List[Any] = []
        _count_scans(closed.jaxpr, scans)
        if len(scans) != 1:
            failures.append(f"structure: expected exactly 1 scan in "
                            f"_signed_digits128, found {len(scans)}")
        else:
            p = scans[0].params
            if p.get("length") != pk_mod.SGLV_WINDOWS:
                failures.append(f"structure: scan length {p.get('length')}"
                                f" != {pk_mod.SGLV_WINDOWS}")
            if p.get("num_carry") != 1:
                failures.append("structure: carry arity != 1")
            if p.get("reverse"):
                failures.append("structure: scan is reversed — carries "
                                "must propagate LSB-first")
            if not failures:
                facts["structure"] = {"scans": 1, "length": 26,
                                      "num_carry": 1, "reverse": False}
    except Exception as e:  # noqa: BLE001
        failures.append(f"structure: {type(e).__name__}: {e}")

    # (c) all 1584 reachable (window, value, carry-in) configurations in
    # one batched device call vs the independent host recoder.  The lane
    # x = v·32^w (+ 16·32^(w-1) to force carry-in 1) reaches window w
    # with value v and carry c: windows < w-1 hold 0, window w-1 holds 16
    # → digit -16, carry 1.
    lanes: List[Tuple[int, int, int, int]] = []   # (x, w, v, c)
    for w in range(pk_mod.SGLV_WINDOWS):
        vmax = 8 if w == pk_mod.SGLV_WINDOWS - 1 else 32
        for v in range(vmax):
            for c in (0, 1):
                if c == 1 and w == 0:
                    continue
                x = v * 32 ** w + (16 * 32 ** (w - 1) if c else 0)
                if x >= 1 << 128:
                    continue
                lanes.append((x, w, v, c))
    xs = [x for x, _, _, _ in lanes]
    arr = np.zeros((10, len(xs)), dtype=np.int32)
    for j, x in enumerate(xs):
        for l in range(10):
            arr[l, j] = (x >> (RADIX * l)) & MASK
    try:
        dev_abs, dev_sgn = jax.jit(pk_mod._signed_digits128)(
            jnp.asarray(arr))
        dev_abs = np.asarray(dev_abs)
        dev_sgn = np.asarray(dev_sgn)
    except Exception as e:  # noqa: BLE001
        failures.append(f"device: {type(e).__name__}: {e}")
        return _finish("scalar._signed_digits128", facts, failures)
    bad = 0
    for j, (x, w, v, c) in enumerate(lanes):
        ref = _ref_signed_recode(x)
        got = [int(dev_abs[i, j]) * (-1 if dev_sgn[i, j] else 1)
               for i in range(pk_mod.SGLV_WINDOWS)]
        if got != ref:
            bad += 1
            if bad <= 3:
                failures.append(
                    f"device: x=2^?·… (w={w}, v={v}, c={c}) recodes to "
                    f"{got[:4]}…, host reference {ref[:4]}…")
        recon = sum(d * 32 ** i for i, d in enumerate(got))
        if recon != x:
            bad += 1
            if bad <= 6:
                failures.append(
                    f"device: Σ d_i·32^i = {recon} != x = {x} "
                    f"(w={w}, v={v}, c={c})")
        if any(abs(d) > 16 for d in got):
            bad += 1
            if bad <= 9:
                failures.append(f"device: digit outside [-16,16] at "
                                f"(w={w}, v={v}, c={c})")
    if bad > 9:
        failures.append(f"device: …{bad - 9} more mismatching lanes")
    if not any(f.startswith("device") for f in failures):
        facts["device_enumeration"] = {
            "lanes": len(lanes),
            "checked": "device == host reference, Σ d·32^w == x, "
                       "|d| <= 16, all (window, value, carry) states",
        }
    return _finish("scalar._signed_digits128", facts, failures)


def _target_signed_digits128() -> CertResult:
    return prove_carry_automaton()


# --------------------------------------------------------------------------
# Leg 3 — exact GLV certificate
# --------------------------------------------------------------------------

def _minrep(x: int, n: int) -> int:
    """Minimal signed representative of x mod n (in (-n/2, n/2])."""
    x %= n
    return x - n if x > n // 2 else x


def prove_glv_constants(B1: Optional[int] = None,
                        B2: Optional[int] = None) -> CertResult:
    """Exact host-side certificate for crypto/glv.py's lattice split.

    With E1 = n·c1 − B2·k and E2 = n·c2 + B1·k (the exact rounding
    errors, |E_i| ≤ n/2 by the round-half-up in split_lambda), the split
    satisfies  n·k2 = −(B1·E1 + B2·E2)  and  n·k1' = −(A1·E1 + A2·E2)
    where A_i = minrep(−λ·B_i mod n) and k1' is the minimal
    representative of k − λ·k2.  Hence |k2| ≤ (|B1|+|B2|)/2 + 1 and
    |k1| ≤ (|A1|+|A2|)/2 + 1, both < 2^128 — derived, not asserted.
    The determinant A1·B2 − A2·B1 = n pins the basis to the curve order:
    corrupting any of B1/B2/λ/n breaks it (or a cube identity)."""
    facts: Dict[str, Any] = {}
    failures: List[str] = []
    n = host.N
    p = host.P
    lam = curve_mod.LAMBDA
    beta = curve_mod.BETA
    b1 = glv_mod._B1 if B1 is None else B1
    b2 = glv_mod._B2 if B2 is None else B2

    if pow(lam, 3, n) != 1 or lam in (1, n - 1):
        failures.append("λ is not a primitive cube root of 1 mod n")
    if pow(beta, 3, p) != 1 or beta in (1, p - 1):
        failures.append("β is not a primitive cube root of 1 mod p")
    if (lam * lam + lam + 1) % n != 0:
        failures.append("λ² + λ + 1 != 0 mod n")
    ep = host.G.mul(lam).to_affine()
    if ep != (beta * host.G_X % p, host.G_Y):
        failures.append("endomorphism λ·G != (β·x_G, y_G) on the "
                        "generator — β and λ are not paired")
    if not failures:
        facts["identities"] = {"lambda_cubed": 1, "beta_cubed": 1,
                               "endomorphism": "λ·G == (β·x_G, y_G)"}

    # basis relation: both rows must be short vectors of the lattice
    # {(a, b) : a + b·λ ≡ 0 mod n}, and the adjugate rows A_i close it
    # with determinant exactly n.
    if (b2 + b1 * lam) % n != 0:      # row (b2, b1): b2 ≡ -b1·λ
        failures.append("basis row (B2, B1) not in the GLV lattice: "
                        "B2 + B1·λ != 0 mod n")
    a1 = _minrep(-lam * b1, n)
    a2 = _minrep(-lam * b2, n)
    det = a1 * b2 - a2 * b1
    if det != n:
        failures.append(f"adjugate determinant A1·B2 − A2·B1 = {det} "
                        f"!= n — lattice constants corrupted")
    bound_k2 = (abs(b1) + abs(b2)) // 2 + 1
    bound_k1 = (abs(a1) + abs(a2)) // 2 + 1
    if bound_k2 >= 1 << 128:
        failures.append(f"|k2| worst case {bound_k2} >= 2^128")
    if bound_k1 >= 1 << 128:
        failures.append(f"|k1| worst case {bound_k1} >= 2^128")
    if not failures:
        facts["lattice"] = {
            "det": "A1·B2 − A2·B1 == n",
            "k1_bound_bits": bound_k1.bit_length(),
            "k2_bound_bits": bound_k2.bit_length(),
        }

    # structured-k panel through the real split, against the exact theory
    panel = [0, 1, 2, n - 1, lam, (n - lam) % n, (1 << 128) - 1, 1 << 128,
             n // 2, n // 2 + 1, lam - 1, lam + 1]
    for k in panel:
        try:
            s_a1, neg1, s_a2, neg2 = glv_mod.split_lambda(k)
        except Exception as e:  # noqa: BLE001
            failures.append(f"split_lambda({k}) raised "
                            f"{type(e).__name__}: {e}")
            continue
        k1 = -s_a1 if neg1 else s_a1
        k2 = -s_a2 if neg2 else s_a2
        if (k1 + lam * k2 - k) % n != 0:
            failures.append(f"split_lambda({k}): k1 + λ·k2 != k mod n")
        if s_a1 >= 1 << 128 or s_a2 >= 1 << 128:
            failures.append(f"split_lambda({k}): half >= 2^128")
        # exact formula re-derivation (independent of glv.py's code path)
        kk = k % n
        c1 = (b2 * kk + n // 2) // n
        c2 = (-b1 * kk + n // 2) // n
        e1 = n * c1 - b2 * kk
        e2 = n * c2 + b1 * kk
        if abs(e1) > n // 2 + 1 or abs(e2) > n // 2 + 1:
            failures.append(f"split_lambda({k}): rounding error exceeds "
                            "n/2 — round-half-up broken")
        want_k2 = -(c1 * glv_mod._B1 + c2 * glv_mod._B2) if B1 is None \
            else -(c1 * b1 + c2 * b2)
        if (k2 - want_k2) % n != 0:
            failures.append(f"split_lambda({k}): k2 disagrees with the "
                            "exact lattice formula")
    if not any("split_lambda" in f for f in failures):
        facts["panel"] = {"cases": len(panel),
                          "checked": "k1 + λ·k2 ≡ k (mod n), halves "
                                     "< 2^128, exact formula match"}
    return _finish("glv.split_lambda", facts, failures)


def _target_glv() -> CertResult:
    return prove_glv_constants()


# --------------------------------------------------------------------------
# Leg 4 — schedule ledger (instrumented eager walk)
# --------------------------------------------------------------------------

_FULL_RUN_CAP = 64   # fori loops at most this long run EVERY iteration
                     # (all window loops qualify: 64/32/26); longer loops
                     # (field-element chains) are sampled and carry no
                     # jacobian events, so the ledger never reads them.


class _Recorder:
    def __init__(self):
        self.loops: List[dict] = []
        self.preamble: List[tuple] = []   # events outside any loop
        self.cur: Optional[dict] = None   # current iteration record
        self.depth = 0

    def event(self, name: str, meta=None):
        if self.depth > 0:
            return
        rec = (name, meta)
        (self.cur["events"] if self.cur is not None
         else self.preamble).append(rec)

    def read(self, array_name: str, index: int):
        if self.depth > 0:
            return
        if self.cur is not None:
            self.cur["reads"].append((array_name, index))
        else:
            self.preamble.append((f"read:{array_name}", index))

    def write(self, array_name: str, index, value_id: int):
        if self.depth > 0:
            return
        rec = (f"write:{array_name}", (index, value_id))
        (self.cur["events"] if self.cur is not None
         else self.preamble).append(rec)


def _spy(rec: _Recorder, name: str, fn):
    def wrapper(*a, **k):
        target = slot = None
        if rec.depth == 0:      # nested jacobian calls are not re-counted
            target = (rec.cur["events"] if rec.cur is not None
                      else rec.preamble)
            target.append((name, {"in": tuple(id(x) for x in a)}))
            slot = len(target) - 1
        rec.depth += 1
        try:
            out = fn(*a, **k)
        finally:
            rec.depth -= 1
        if target is not None:
            outs = out if isinstance(out, tuple) else (out,)
            target[slot] = (name, {"in": target[slot][1]["in"],
                                   "out": tuple(id(x) for x in outs)})
        return out
    return wrapper


def _fake_fori(rec: _Recorder):
    def fori(lo, hi, body, init, **_kw):
        lo, hi = int(lo), int(hi)
        entry = {"lo": lo, "hi": hi, "iters": {}}
        rec.loops.append(entry)
        if hi - lo <= _FULL_RUN_CAP:
            samples = list(range(lo, hi))
        else:
            samples = sorted({lo, lo + 1, hi - 1})
        val = init
        for i in samples:
            it = {"events": [], "reads": []}
            entry["iters"][i] = it
            prev, rec.cur = rec.cur, it
            try:
                val = body(i, val)
            finally:
                rec.cur = prev
        entry["complete"] = (samples == list(range(lo, hi)))
        return val
    return fori


class _SpyArray:
    """Wraps a digit array; records integer row reads."""

    def __init__(self, arr, name: str, rec: _Recorder):
        self._a = arr
        self._name = name
        self._rec = rec

    @property
    def shape(self):
        return self._a.shape

    @property
    def ndim(self):
        return self._a.ndim

    @property
    def dtype(self):
        return self._a.dtype

    def __getitem__(self, idx):
        if isinstance(idx, (int, np.integer)):
            self._rec.read(self._name, int(idx))
        return self._a[idx]


class _FakeRef:
    """pallas Ref stand-in over a jnp array: `[...]` reads/writes with
    integer-index recording."""

    def __init__(self, arr, name: str, rec: _Recorder):
        self._a = jnp.asarray(arr)
        self._name = name
        self._rec = rec

    @property
    def shape(self):
        return self._a.shape

    def __getitem__(self, idx):
        if isinstance(idx, (int, np.integer)):
            self._rec.read(self._name, int(idx))
        return self._a[idx]

    def __setitem__(self, idx, val):
        key = int(idx) if isinstance(idx, (int, np.integer)) else idx
        self._rec.write(self._name,
                        key if isinstance(key, int) else "slice", id(val))
        self._a = self._a.at[idx].set(val)


class _Patched:
    """Context manager: swap module attributes, restore on exit."""

    def __init__(self, mapping: Dict[Tuple[Any, str], Any]):
        self.mapping = mapping
        self.saved: Dict[Tuple[Any, str], Any] = {}

    def __enter__(self):
        for (mod, attr), val in self.mapping.items():
            self.saved[(mod, attr)] = getattr(mod, attr)
            setattr(mod, attr, val)
        return self

    def __exit__(self, *exc):
        for (mod, attr), val in self.saved.items():
            setattr(mod, attr, val)
        return False


_FAST_CACHE: Dict[Any, Any] = {}


def _fast(fn):
    """Jit wrapper preserving the `inf1` static-sentinel contract (None /
    False select different formula variants at trace time; an array is a
    runtime mask).  One compile per (variant, shapes), cached across
    certify calls; the eager ledger walk then costs one dispatch per
    jacobian op instead of hundreds."""
    if fn in _FAST_CACHE:
        return _FAST_CACHE[fn]
    jit_plain = jax.jit(lambda *a: fn(*a))
    jit_inf_false = jax.jit(lambda *a: fn(*a, inf1=False))
    jit_inf_arr = jax.jit(lambda *a: fn(*a[:-1], inf1=a[-1]))

    def call(*a, **k):
        if not k:
            return jit_plain(*a)
        if set(k) != {"inf1"}:
            return fn(*a, **k)
        v = k["inf1"]
        if v is None:
            return jit_plain(*a)
        if v is False:
            return jit_inf_false(*a)
        return jit_inf_arr(*a, v)

    _FAST_CACHE[fn] = call
    return call


def _jacobian_spies(rec: _Recorder, mod) -> Dict[Tuple[Any, str], Any]:
    out: Dict[Tuple[Any, str], Any] = {}
    for name in ("jacobian_double", "jacobian_add_complete",
                 "jacobian_madd_complete", "jacobian_madd_flagged",
                 "jacobian_madd_flagged_ratio", "jacobian_add_flagged",
                 "fe_mul", "fe_sub"):
        if hasattr(mod, name):
            out[(mod, name)] = _spy(rec, name, _fast(getattr(mod, name)))
    return out


_JAC_EVENTS = {"jacobian_double", "jacobian_add_complete",
               "jacobian_madd_complete", "jacobian_madd_flagged",
               "jacobian_madd_flagged_ratio", "jacobian_add_flagged"}


def _window_loops(rec: _Recorder) -> List[dict]:
    """Loops whose iterations contain jacobian-level events (fe chains
    and other helper loops carry none)."""
    out = []
    for loop in rec.loops:
        if any(e[0] in _JAC_EVENTS for it in loop["iters"].values()
               for e in it["events"]):
            out.append(loop)
    return out


def _check_ladder_loop(loop: dict, *, count: int, width: int,
                       digit_arrays: List[str],
                       expect_events: List[str],
                       label: str,
                       failures: List[str]) -> Dict[str, Any]:
    """The core ledger identity for one window loop.

    Every iteration i must perform exactly `width` doublings before its
    adds (expect_events pins the full per-iteration schedule), and read
    window w(i) of each digit array.  Accumulating R ← 2^D·R + d_{w(i)}·P
    gives digit w(i) the final coefficient 2^(D·(count−1−i)); we require
    coefficient(w) == 2^(width·w) for every w — the ledger sum equals
    the radix decomposition Σ d_w·2^(width·w) proven by leg 1."""
    if (loop["lo"], loop["hi"]) != (0, count):
        failures.append(f"{label}: window loop bounds "
                        f"({loop['lo']}, {loop['hi']}) != (0, {count})")
        return {}
    if not loop.get("complete"):
        failures.append(f"{label}: window loop iterations were sampled, "
                        "not exhaustively executed")
        return {}
    doubles_seen = set()
    coeff: Dict[str, Dict[int, int]] = {a: {} for a in digit_arrays}
    for i in range(count):
        it = loop["iters"][i]
        names = [e[0] for e in it["events"]]
        if names != expect_events:
            failures.append(f"{label}: iteration {i} schedule {names} != "
                            f"expected {expect_events}")
            return {}
        doubles_seen.add(sum(1 for nm in names
                             if nm == "jacobian_double"))
        reads = {}
        for arr, idx in it["reads"]:
            if arr in coeff:
                reads.setdefault(arr, []).append(idx)
        for arr in digit_arrays:
            got = reads.get(arr, [])
            if len(got) != 1:
                failures.append(f"{label}: iteration {i} read {arr} "
                                f"{len(got)} times (want once)")
                return {}
            w = got[0]
            if w in coeff[arr]:
                failures.append(f"{label}: window {w} of {arr} read by "
                                "two iterations")
                return {}
            coeff[arr][w] = 1 << (width * (count - 1 - i))
    if doubles_seen != {width}:
        failures.append(f"{label}: doublings per window {doubles_seen} "
                        f"!= recoder width {width} — ledger weight "
                        "mismatch")
        return {}
    for arr in digit_arrays:
        for w in range(count):
            want = 1 << (width * w)
            got = coeff[arr].get(w)
            if got != want:
                failures.append(
                    f"{label}: ledger coefficient of {arr}[{w}] is "
                    f"{'absent' if got is None else hex(got)}, radix "
                    f"decomposition requires 2^{width * w} — window "
                    "order/doubling schedule broken")
                return {}
    return {"windows": count, "doubles_per_window": width,
            "order": "descending (w = count-1-i)",
            "ledger": "coeff(w) == 2^(width*w) for every window"}


def _affine_of(X, Y, Z) -> Optional[Tuple[int, int]]:
    z = limbs_mod.limbs_to_int(np.asarray(Z)[:, 0])
    if z % host.P == 0:
        return None
    x = limbs_mod.limbs_to_int(np.asarray(X)[:, 0])
    y = limbs_mod.limbs_to_int(np.asarray(Y)[:, 0])
    zi = pow(z, host.P - 2, host.P)
    return (x * zi * zi % host.P, y * zi * zi * zi % host.P)


def _limb_col(x: int, n: int = NLIMB) -> jnp.ndarray:
    return jnp.asarray(limbs_mod.int_to_limbs(x, n), jnp.int32)[:, None]


def certify_p_table() -> Tuple[Dict[str, Any], List[str]]:
    """Concrete differential: _p_table rows really hold k·P, k = 0..15."""
    facts: Dict[str, Any] = {}
    failures: List[str] = []
    px, py = _limb_col(host.G_X), _limb_col(host.G_Y)
    TX, TY, TZ = curve_mod._p_table(px, py)
    for k in range(16):
        got = _affine_of(TX[k], TY[k], TZ[k])
        want = host.G.mul(k).to_affine()
        if got != want:
            failures.append(f"_p_table row {k} != {k}·P")
    if not failures:
        facts["p_table"] = {"rows": 16, "rule": "T[k] == k·P"}
    return facts, failures


_GTABLE_CERT: Optional[List[str]] = None


def certify_g_table() -> Tuple[Dict[str, Any], List[str]]:
    """Host certificate: _g_table row (w, j) is affine((j+1)·256^w·G),
    verified incrementally with exact Jacobian point arithmetic (no
    inversions: compare x·Z² ≡ X, y·Z³ ≡ Y mod p)."""
    global _GTABLE_CERT
    if _GTABLE_CERT is not None:
        failures = list(_GTABLE_CERT)
        return ({} if failures else
                {"g_table": {"rows": 32 * 255,
                             "rule": "row (w,j) == (j+1)·256^w·G"}},
                failures)
    failures = []
    gx, gy = curve_mod._g_table()
    gx = np.asarray(gx)
    gy = np.asarray(gy)
    base = host.G                      # 256^w · G, advanced per window
    for w in range(curve_mod.G_WINDOWS):
        ba = base.to_affine()
        acc = host.PointJ.from_affine(*ba)     # (j+1)·base
        for j in range(255):
            a = acc.to_affine() if j else ba
            tx = limbs_mod.limbs_to_int(gx[w, j])
            ty = limbs_mod.limbs_to_int(gy[w, j])
            if (tx, ty) != a:
                failures.append(f"_g_table row ({w}, {j}) != "
                                f"({j + 1})·256^{w}·G")
                if len(failures) > 4:
                    _GTABLE_CERT = failures
                    return {}, failures
            acc = acc.add_affine(*ba)
        for _ in range(8):
            base = base.double()
    _GTABLE_CERT = failures
    if failures:
        return {}, failures
    return {"g_table": {"rows": 32 * 255,
                        "rule": "row (w,j) == (j+1)·256^w·G"}}, []


def _target_double_scalar_mult(quick: bool = False) -> CertResult:
    facts: Dict[str, Any] = {}
    failures: List[str] = []
    rec = _Recorder()

    a_int = 0x1234567890ABCDEF1234567890ABCDEF0DDBA11FEEDFACE8BADF00D5EED
    b_int = 0xC0FFEE0FF1CE0DDC0DE0FACADE0BEEFF00DBABB1E0CAFE0DEAF0D00DAD
    a = _limb_col(a_int)
    b = _limb_col(b_int)
    px, py = _limb_col(host.G_X), _limb_col(host.G_Y)

    digit_calls: List[Tuple[int, int, str]] = []
    orig_digits = curve_mod._digits

    def digits_spy(limbs, width, count):
        name = f"digits{len(digit_calls)}"
        digit_calls.append((width, count, name))
        return _SpyArray(orig_digits(limbs, width, count), name, rec)

    patches = _jacobian_spies(rec, curve_mod)
    patches[(curve_mod, "_digits")] = digits_spy
    patches[(jax.lax, "fori_loop")] = _fake_fori(rec)
    patches[(lax, "fori_loop")] = patches[(jax.lax, "fori_loop")]
    try:
        with _Patched(patches):
            R = curve_mod.double_scalar_mult(a, b, px, py)
    except Exception as e:  # noqa: BLE001
        failures.append(f"ledger walk: {type(e).__name__}: {e}")
        return _finish("curve.double_scalar_mult", facts, failures)

    if [(w, c) for w, c, _ in digit_calls] != [(4, 64), (8, 32)]:
        failures.append(f"recoder calls {digit_calls} != expected "
                        "[(4,64) P digits, (8,32) G digits]")
        return _finish("curve.double_scalar_mult", facts, failures)
    wloops = _window_loops(rec)
    if len(wloops) != 2:
        failures.append(f"found {len(wloops)} jacobian window loops, "
                        "expected 2 (P ladder + G madd loop)")
        return _finish("curve.double_scalar_mult", facts, failures)
    pl = _check_ladder_loop(
        wloops[0], count=64, width=4, digit_arrays=["digits0"],
        expect_events=["jacobian_double"] * 4 + ["jacobian_add_complete"],
        label="P ladder", failures=failures)
    if pl:
        facts["p_ladder"] = pl
    # G loop: no doublings — weights live in the table rows (j+1)·256^w·G
    gl = wloops[1]
    if (gl["lo"], gl["hi"]) != (0, 32) or not gl.get("complete"):
        failures.append("G loop bounds/completeness wrong")
    else:
        for i in range(32):
            it = gl["iters"][i]
            if [e[0] for e in it["events"]] != ["jacobian_madd_complete"]:
                failures.append(f"G loop iteration {i}: schedule "
                                f"{[e[0] for e in it['events']]}")
                break
            reads = [idx for arr, idx in it["reads"] if arr == "digits1"]
            if reads != [i]:
                failures.append(f"G loop iteration {i} reads digit "
                                f"window(s) {reads}, expected [{i}] "
                                "(ascending: weights are in the table)")
                break
        else:
            facts["g_loop"] = {"windows": 32, "doubles_per_window": 0,
                               "order": "ascending, table row (j+1)·256^w·G"}
    # final join: exactly one add after the loops
    post_jac = [e[0] for e in rec.preamble if e[0] in _JAC_EVENTS]
    if post_jac != ["jacobian_madd_complete", "jacobian_add_complete"]:
        failures.append(f"out-of-loop jacobian events {post_jac} != "
                        "[p-table scan madd, final join add]")
    else:
        facts["join"] = {"final_adds": 1}

    # every iteration really ran in order on concrete values, so the walk
    # doubles as an end-to-end differential against the exact host math.
    got = _affine_of(*R[:3]) if isinstance(R, tuple) else None
    want_pt = host.G.mul(a_int).add(host.G.mul(b_int))
    if got != want_pt.to_affine():
        failures.append("differential: eager ladder result != "
                        "a·G + b·P computed with exact host arithmetic")
    else:
        facts["differential"] = {"scalars": 2,
                                 "rule": "eager walk == a·G + b·P (host)"}

    f2, fail2 = certify_p_table()
    facts.update(f2)
    failures.extend(fail2)
    if not quick:
        f3, fail3 = certify_g_table()
        facts.update(f3)
        failures.extend(fail3)
    return _finish("curve.double_scalar_mult", facts, failures)


def _target_double_scalar_mult_glv(quick: bool = False) -> CertResult:
    facts: Dict[str, Any] = {}
    failures: List[str] = []
    rec = _Recorder()

    a_int = 0xFACE0FF1CE0DDBA11
    k_int = 0xD1CE0C0DE0BEEF0CAFE0F00D0BADD00D0FACADE0ACC01ADE0DECAF0FAD
    a1, neg1, a2, neg2 = glv_mod.split_lambda(k_int)
    a = _limb_col(a_int)
    db1 = _SpyArray(curve_mod._digits128(_limb_col(a1, 10), 32, 4),
                    "db1", rec)
    db2 = _SpyArray(curve_mod._digits128(_limb_col(a2, 10), 32, 4),
                    "db2", rec)
    n1 = jnp.asarray([neg1])
    n2 = jnp.asarray([neg2])
    px, py = _limb_col(host.G_X), _limb_col(host.G_Y)

    digit_calls: List[Tuple[int, int, str]] = []
    orig_digits = curve_mod._digits

    def digits_spy(limbs, width, count):
        name = f"digits{len(digit_calls)}"
        digit_calls.append((width, count, name))
        return _SpyArray(orig_digits(limbs, width, count), name, rec)

    patches = _jacobian_spies(rec, curve_mod)
    patches[(curve_mod, "_digits")] = digits_spy
    patches[(jax.lax, "fori_loop")] = _fake_fori(rec)
    patches[(lax, "fori_loop")] = patches[(jax.lax, "fori_loop")]
    try:
        with _Patched(patches):
            X, Y, Z, out_inf = curve_mod.double_scalar_mult_glv(
                a, db1, db2, n1, n2, px, py)
    except Exception as e:  # noqa: BLE001
        failures.append(f"ledger walk: {type(e).__name__}: {e}")
        return _finish("curve.double_scalar_mult_glv", facts, failures)

    wloops = _window_loops(rec)
    if len(wloops) != 2:
        failures.append(f"found {len(wloops)} jacobian window loops, "
                        "expected 2 (GLV ladder + G madd loop)")
        return _finish("curve.double_scalar_mult_glv", facts, failures)
    # per-iteration schedule pins β onto the SECOND (λ-half) add: the
    # lone top-level fe_mul between the two complete adds.
    gl = _check_ladder_loop(
        wloops[0], count=32, width=4, digit_arrays=["db1", "db2"],
        expect_events=["jacobian_double"] * 4
        + ["fe_sub", "jacobian_add_complete",
           "fe_mul", "fe_sub", "jacobian_add_complete"],
        label="GLV ladder", failures=failures)
    if gl:
        gl["beta"] = "fe_mul(Σ TX·onehot, β) precedes only the d2 add"
        facts["glv_ladder"] = gl

    # differential: ±a1 ± λ·a2 must reproduce k, and the eager walk must
    # equal the host's exact a·G + k·P.
    s1 = -a1 if neg1 else a1
    s2 = -a2 if neg2 else a2
    if (s1 + curve_mod.LAMBDA * s2 - k_int) % host.N != 0:
        failures.append("split halves do not recombine to k mod n")
    got = _affine_of(X, Y, Z)
    want = host.G.mul(a_int).add(host.G.mul(k_int)).to_affine()
    if got != want:
        failures.append("differential: eager GLV ladder != a·G + k·P "
                        "(exact host arithmetic)")
    else:
        facts["differential"] = {
            "rule": "eager walk == a·G + (±a1 ± λ·a2)·P == a·G + k·P"}
    f2, fail2 = certify_p_table()
    facts.update(f2)
    failures.extend(fail2)
    return _finish("curve.double_scalar_mult_glv", facts, failures)


def _pallas_source_checks(facts: Dict[str, Any],
                          failures: List[str]) -> None:
    """AST facts about _kernel_body that the eager walk cannot see:
    the one-hot comparands are iota+1 (table row k holds (k+1)·P /
    (j+1)·256^w·G — off-by-one here selects the wrong multiple), and the
    digit signs are XORed with the GLV half signs before negating y."""
    src = textwrap.dedent(inspect.getsource(pk_mod._kernel_body))
    tree = ast.parse(src)
    iota_plus_one = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add)
                and isinstance(node.right, ast.Constant)
                and node.right.value == 1
                and isinstance(node.left, ast.Call)
                and getattr(node.left.func, "attr", "")
                == "broadcasted_iota"):
            dims = [a.value for a in node.left.args[1].elts
                    if isinstance(a, ast.Constant)]
            iota_plus_one.append(tuple(dims))
    if (16, 1, 1) not in iota_plus_one:
        failures.append("pallas: k16 one-hot comparand is not "
                        "broadcasted_iota((16,1,1)) + 1 — P-table row k "
                        "holds (k+1)·P, the +1 is load-bearing")
    if (255, 1) not in iota_plus_one:
        failures.append("pallas: k255 comparand is not "
                        "broadcasted_iota((255,1)) + 1")
    sign_xor = any(
        isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitXor)
        for node in ast.walk(tree))
    if not sign_xor:
        failures.append("pallas: digit signs are not XORed with the GLV "
                        "half signs (ds ^ neg)")
    if not failures:
        facts["source"] = {"onehot_comparands": "iota + 1 (k16, k255)",
                           "sign_wiring": "ds_ref[w] ^ neg"}


def _target_pallas_schedule(quick: bool = False) -> CertResult:
    facts: Dict[str, Any] = {}
    failures: List[str] = []
    _pallas_source_checks(facts, failures)

    rec = _Recorder()
    T = 1
    k_int = 0xBADC0DE0DDF00D0D15EA5E0BEEFFACE0CADFACE0DEAD0FAB0FEED0ACE
    a1, neg1, a2, neg2 = glv_mod.split_lambda(k_int)
    ab1, sb1 = (np.asarray(v) for v in
                pk_mod._signed_digits128(_limb_col(a1, 10)))
    ab2, sb2 = (np.asarray(v) for v in
                pk_mod._signed_digits128(_limb_col(a2, 10)))
    px = _limb_col(host.G_X)
    flags = np.zeros((6, T), np.int32)
    flags[0, :] = host.G_Y & 1         # want_odd
    flags[1, :] = -1                   # no parity requirement
    flags[3, :] = 1                    # valid
    flags[4, :] = 1 if neg1 else 0
    flags[5, :] = 1 if neg2 else 0
    gx, gy = curve_mod._g_table()
    refs = {
        "px": _FakeRef(px, "px", rec),
        "t1": _FakeRef(jnp.zeros((NLIMB, T), jnp.int32), "t1", rec),
        "t1n": _FakeRef(jnp.zeros((NLIMB, T), jnp.int32), "t1n", rec),
        "da": _FakeRef(jnp.zeros((32, T), jnp.int32), "da", rec),
        "db1": _FakeRef(jnp.asarray(ab1), "db1", rec),
        "ds1": _FakeRef(jnp.asarray(sb1), "ds1", rec),
        "db2": _FakeRef(jnp.asarray(ab2), "db2", rec),
        "ds2": _FakeRef(jnp.asarray(sb2), "ds2", rec),
        "flags": _FakeRef(jnp.asarray(flags), "flags", rec),
        "gx": _FakeRef(gx.astype(jnp.float32), "gx", rec),
        "gy": _FakeRef(gy.astype(jnp.float32), "gy", rec),
        "ok": _FakeRef(jnp.zeros((2, T), jnp.int32), "ok", rec),
        "tx": _FakeRef(jnp.zeros((16, NLIMB, T), jnp.int32), "tx", rec),
        "ty": _FakeRef(jnp.zeros((16, NLIMB, T), jnp.int32), "ty", rec),
    }
    patches = _jacobian_spies(rec, pk_mod)
    patches[(jax.lax, "fori_loop")] = _fake_fori(rec)
    patches[(lax, "fori_loop")] = patches[(jax.lax, "fori_loop")]
    try:
        with _Patched(patches):
            pk_mod._kernel_body(
                refs["px"], refs["t1"], refs["t1n"], refs["da"],
                refs["db1"], refs["ds1"], refs["db2"], refs["ds2"],
                refs["flags"], refs["gx"], refs["gy"], refs["ok"],
                refs["tx"], refs["ty"])
    except Exception as e:  # noqa: BLE001
        failures.append(f"ledger walk: {type(e).__name__}: {e}")
        return _finish("pallas.kernel_schedule", facts, failures)

    # -- table build: object-flow chain proof ---------------------------
    # write tx[0] = P; row 1 = double(P); row k (2..15) = row k−1 + P via
    # flagged ratio-madds whose base args are the SAME objects every time
    # and whose (X,Y,Z) inputs are the previous call's outputs — so row k
    # holds the chain value (k+1)·P by induction.
    pre = rec.preamble
    jac = [(n, m) for n, m in pre if n in _JAC_EVENTS]
    names = [n for n, _ in jac]
    if names != (["jacobian_double"]
                 + ["jacobian_madd_flagged_ratio"] * 14
                 + ["jacobian_add_flagged"]):
        failures.append(f"pallas: out-of-loop jacobian events {names} != "
                        "[table double, 14 ratio madds, final join]")
    else:
        dbl_meta = jac[0][1]
        ratio_meta = [m for _, m in jac[1:15]]
        base = dbl_meta["in"][:2] if isinstance(dbl_meta, dict) else None
        chain_ok = base is not None
        prev_out = dbl_meta["out"][:3] if chain_ok else None
        for m in ratio_meta:
            if not isinstance(m, dict) or m["in"][3:5] != base or \
                    m["in"][:3] != prev_out:
                chain_ok = False
                break
            prev_out = m["out"][:3]
        writes = [(meta[0]) for n, meta in pre if n == "write:tx"
                  and isinstance(meta, tuple)]
        if writes[:16] != list(range(16)):
            failures.append(f"pallas: table rows written in order "
                            f"{writes[:16]}, expected 0..15")
        elif not chain_ok:
            failures.append("pallas: table build is not a single-base "
                            "madd chain — row k is not (k+1)·P")
        else:
            facts["table"] = {"rows": 16,
                              "rule": "row k == (k+1)·P (object-flow "
                                      "chain: double + 14 madds of the "
                                      "same base)"}

    wloops = _window_loops(rec)
    if len(wloops) != 2:
        failures.append(f"pallas: found {len(wloops)} jacobian window "
                        "loops, expected 2 (signed GLV + G loop)")
        return _finish("pallas.kernel_schedule", facts, failures)
    wl = _check_ladder_loop(
        wloops[0], count=pk_mod.SGLV_WINDOWS, width=pk_mod.SGLV_WIDTH,
        digit_arrays=["db1", "db2"],
        expect_events=["jacobian_double"] * 5
        + ["fe_sub", "jacobian_madd_flagged",
           "fe_mul", "fe_sub", "jacobian_madd_flagged"],
        label="pallas signed ladder", failures=failures)
    if wl:
        # signs must be read in lockstep with the digits
        for i in range(pk_mod.SGLV_WINDOWS):
            it = wloops[0]["iters"][i]
            w = pk_mod.SGLV_WINDOWS - 1 - i
            sreads = [idx for arr, idx in it["reads"]
                      if arr in ("ds1", "ds2")]
            if sreads != [w, w]:
                failures.append(f"pallas: iteration {i} sign reads "
                                f"{sreads} != [{w}, {w}]")
                wl = {}
                break
    if wl:
        wl["beta"] = "fe_mul(Σ TX·onehot, β) precedes only the d2 madd"
        facts["signed_ladder"] = wl
    gl = wloops[1]
    if (gl["lo"], gl["hi"]) != (0, 32) or not gl.get("complete"):
        failures.append("pallas: G loop bounds/completeness wrong")
    else:
        ok = True
        for i in range(32):
            it = gl["iters"][i]
            if [e[0] for e in it["events"]] != ["jacobian_madd_flagged"]:
                failures.append(f"pallas: G loop iteration {i} schedule "
                                f"{[e[0] for e in it['events']]}")
                ok = False
                break
            reads = [idx for arr, idx in it["reads"]
                     if arr in ("da", "gx", "gy")]
            if reads != [i, i, i]:
                failures.append(f"pallas: G loop iteration {i} reads "
                                f"{reads}, expected window {i} of "
                                "da/gx/gy")
                ok = False
                break
        if ok:
            facts["g_loop"] = {"windows": 32, "doubles_per_window": 0,
                               "order": "ascending, table row "
                                        "(j+1)·256^w·G"}
    if not quick:
        f3, fail3 = certify_g_table()
        facts.update(f3)
        failures.extend(fail3)
    return _finish("pallas.kernel_schedule", facts, failures)


# --------------------------------------------------------------------------
# target registry / public API
# --------------------------------------------------------------------------

TARGETS: Dict[str, Callable[..., CertResult]] = {
    "scalar._digits": lambda quick=False: _target_digits(),
    "scalar._digits128": lambda quick=False: _target_digits128(),
    "scalar.bytes_to_limbs": lambda quick=False: _target_bytes_to_limbs(),
    "sha256.bytes_from_words":
        lambda quick=False: _target_bytes_from_words(),
    "scalar._signed_digits128":
        lambda quick=False: _target_signed_digits128(),
    "glv.split_lambda": lambda quick=False: _target_glv(),
    "curve.double_scalar_mult": _target_double_scalar_mult,
    "curve.double_scalar_mult_glv": _target_double_scalar_mult_glv,
    "pallas.kernel_schedule": _target_pallas_schedule,
}

# Function names host_lint's scalar-coverage rule accepts as "registered
# with the schedule prover" (mapped to the target that certifies them).
REGISTERED_RECODERS: Dict[str, str] = {
    "scalar_bits": "scalar._digits",
    "_digits": "scalar._digits",
    "_digits128": "scalar._digits128",
    "_signed_digits128": "scalar._signed_digits128",
    "bytes_to_limbs": "scalar.bytes_to_limbs",
    "int_to_limbs": "scalar.bytes_to_limbs",
    "limbs_to_int": "scalar.bytes_to_limbs",
    "_bytes_from_words": "sha256.bytes_from_words",
    "ints_to_limbs_batch": "scalar._signed_digits128",
    "split_lambda": "glv.split_lambda",
    "double_scalar_mult": "curve.double_scalar_mult",
    "double_scalar_mult_glv": "curve.double_scalar_mult_glv",
    "double_scalar_mult_bits": "curve.double_scalar_mult",
    "_fixed_base_mult": "curve.double_scalar_mult",
    "_kernel_body": "pallas.kernel_schedule",
}


# Targets whose certificate needs an eager ledger walk (~1-2 min each on
# CPU); the stats mini-workload and test suite certify only the fast set,
# CI's --schedule leg runs everything.
HEAVY_TARGETS = {
    "curve.double_scalar_mult",
    "curve.double_scalar_mult_glv",
    "pallas.kernel_schedule",
}


def all_targets(include_heavy: bool = True) -> List[str]:
    names = list(TARGETS)
    if not include_heavy:
        names = [n for n in names if n not in HEAVY_TARGETS]
    return names


def certify(name: str, quick: bool = False) -> CertResult:
    try:
        return TARGETS[name](quick=quick)
    except Exception as e:  # noqa: BLE001 — unevaluable is FAIL
        return CertResult(name, "FAIL", {},
                          [f"{type(e).__name__}: {e}"])


_CERT_COUNTER = None


def certify_all(quick: bool = False,
                emit_metrics: bool = True,
                include_heavy: bool = True) -> List[CertResult]:
    global _CERT_COUNTER
    results = [certify(n, quick=quick)
               for n in all_targets(include_heavy=include_heavy)]
    if emit_metrics:
        if _CERT_COUNTER is None:
            from ..obs import counter
            _CERT_COUNTER = counter(
                "consensus_scalar_certificates",
                "Scalar-schedule prover certificates by target and status",
                ("target", "status"))
        for r in results:
            _CERT_COUNTER.inc(target=r.name, status=r.status)
    return results


# --------------------------------------------------------------------------
# planted-unsound negatives — the prover must REJECT every one
# --------------------------------------------------------------------------

def _toy_bad_weights_recoder() -> CertResult:
    """Out-of-range digit: weights [1, 2, 4, 9] instead of [1, 2, 4, 8]
    — windows can exceed 2^width − 1 and recombination is broken."""

    def bad_digits(limbs):
        bits = curve_mod.scalar_bits(limbs)[:256]
        b = bits.reshape((64, 4) + limbs.shape[1:])
        weights = jnp.asarray([1, 2, 4, 9], dtype=jnp.int32).reshape(
            (1, 4) + (1,) * (limbs.ndim - 1))
        return jnp.sum(b * weights, axis=1)

    facts: Dict[str, Any] = {}
    failures: List[str] = []
    _prove_digit_slices("toy_bad_weights", bad_digits,
                        _seed_limb_bits(NLIMB), 64, 4, facts, failures)
    return _finish("negative.scalar-digit-range", facts, failures)


def _toy_bad_carry() -> CertResult:
    """Wrong carry fold: digit = t − 31 on carry instead of t − 32 —
    the telescoping invariant (and hence reconstruction) breaks."""
    return prove_carry_automaton(
        step_fn=lambda t: ((1 if t >= 16 else 0),
                           t - 31 * (1 if t >= 16 else 0)))


def _toy_ladder(order_desc: bool, doubles: int) -> CertResult:
    """4-window width-2 ladder over an 8-bit scalar using the production
    jacobian ops and table; run through the SAME generic ledger check as
    the real ladders.  order_desc=True, doubles=2 is the sound schedule
    (checker self-test); ascending order or doubles != width must FAIL."""
    facts: Dict[str, Any] = {}
    failures: List[str] = []
    rec = _Recorder()
    scalar = 0b10110110
    px, py = _limb_col(host.G_X), _limb_col(host.G_Y)
    digits = _SpyArray(
        jnp.asarray([[(scalar >> (2 * w)) & 3] for w in range(4)],
                    jnp.int32), "digits0", rec)

    def ladder():
        TX, TY, TZ = curve_mod._p_table(px, py)
        k4 = jnp.arange(4, dtype=jnp.int32).reshape((4,) + (1,) * px.ndim)

        def body(i, R):
            w = (3 - i) if order_desc else i
            for _ in range(doubles):
                R = curve_mod.jacobian_double(*R)
            d = digits[w]
            oh = (d[None] == k4).astype(jnp.int32)
            selx = jnp.sum(TX[:4] * oh, axis=0)
            sely = jnp.sum(TY[:4] * oh, axis=0)
            selz = jnp.sum(TZ[:4] * oh, axis=0)
            return curve_mod.jacobian_add_complete(
                *R, selx, sely, selz, d == 0)

        return lax.fori_loop(0, 4, body, curve_mod._inf_like(px))

    patches = _jacobian_spies(rec, curve_mod)
    patches[(jax.lax, "fori_loop")] = _fake_fori(rec)
    patches[(lax, "fori_loop")] = patches[(jax.lax, "fori_loop")]
    try:
        with _Patched(patches):
            R = ladder()
    except Exception as e:  # noqa: BLE001
        failures.append(f"toy ladder walk: {type(e).__name__}: {e}")
        return _finish("negative.toy-ladder", facts, failures)
    wloops = _window_loops(rec)
    if len(wloops) != 1:
        failures.append(f"toy ladder: {len(wloops)} window loops")
        return _finish("negative.toy-ladder", facts, failures)
    led = _check_ladder_loop(
        wloops[0], count=4, width=2, digit_arrays=["digits0"],
        expect_events=["jacobian_double"] * doubles
        + ["jacobian_add_complete"],
        label="toy ladder", failures=failures)
    if led:
        facts["toy_ladder"] = led
    got = _affine_of(*R[:3])
    if got != host.G.mul(scalar).to_affine():
        failures.append("toy ladder differential: result != scalar·P")
    elif led:
        facts["differential"] = {"rule": "toy walk == scalar·P"}
    return _finish("negative.toy-ladder", facts, failures)


def _cert_to_report(name: str, cert: CertResult) -> interval.Report:
    rep = interval.Report(name=f"negative.{name}", ok=cert.ok)
    for f in cert.failures:
        rep.violations.append(
            interval.Violation(kind="schedule", where=cert.name, msg=f))
    rep.notes.append(f"scalar-schedule prover verdict: {cert.status}")
    return rep


NEGATIVES: Dict[str, Callable[[], CertResult]] = {
    "scalar-carry-fold": _toy_bad_carry,
    "scalar-window-order": lambda: _toy_ladder(order_desc=False,
                                               doubles=2),
    "scalar-dropped-doubling": lambda: _toy_ladder(order_desc=True,
                                                   doubles=1),
    "scalar-digit-range": _toy_bad_weights_recoder,
    "scalar-glv-constant": lambda: prove_glv_constants(
        B2=glv_mod._B2 + 2),
}


def toy_ladder_selftest() -> CertResult:
    """The sound toy schedule must PASS through the same checker the
    negatives fail — proves the gate is alive, not trivially rejecting."""
    return _toy_ladder(order_desc=True, doubles=2)


def analyze_negative(name: str) -> interval.Report:
    return _cert_to_report(name, NEGATIVES[name]())
