"""Interval abstract interpretation + determinism gate over closed jaxprs.

The whole TPU design rests on one claim: every intermediate of the
radix-2^13 field pipeline fits a signed int32 lane. `ops/limbs.py` tracks
that claim by hand — static Python-int `Bounds` lists threaded alongside
the traced arrays, asserted by the same code they audit. This module is
the *independent* auditor: it closes the jaxpr of a consensus kernel and
re-derives per-element integer intervals for every equation, with no
access to the hand bookkeeping.

The theorem proved per kernel (the "observation discipline"):

  XLA int32 add/sub/mul/shift-left are exact mod 2^32 (two's-complement
  wrap), i.e. ring homomorphisms on residues. So a signed value's TRUE
  (unbounded-integer) interval propagates exactly through ring ops even
  if the machine representation transiently wraps — the Karatsuba
  sum-convolution in `fe_mul` relies on exactly this. Wrapping only
  corrupts math at *observing* ops whose result is not a residue
  function: right shifts, comparisons, div/min/max, int<->float
  converts, and the kernel outputs. At every such observation the
  analyzer demands the operand's true interval fit the lane
  ([-2^31, 2^31) for int32); a kernel is overflow-free iff no
  observation fails. Unsigned dtypes (SHA-256) wrap by *spec*: every
  unsigned op is a residue function, so their intervals are reduced
  mod 2^w and never violate.

Precision machinery (needed to prove the real kernels, not toys):

- Intervals are tracked per-row along the first TWO axes (capped at
  `ROW_CAP`), collapsed elsewhere. Axis 0 is the limb axis in this
  codebase, so the derived rows are directly comparable to the
  hand-tracked `Bounds` lists (tests pin them equal).
- One-hot selects: `(digit == iota_rows)` yields an at-most-one-nonzero-
  along-axis-0 flag; `reduce_sum(table * onehot, axis=0)` then joins
  rows instead of summing them — without this the windowed scalar-mult
  table selects false-alarm by a factor of the table size.
- Exact-float discipline: every float32 value carries an exactness
  CERTIFICATE (exactf + a tracked magnitude bound <= 2^24, exact in an
  f32 mantissa), propagated end to end through converts, one-hot
  construction, select_n, mul/add, reductions and HIGHEST-precision
  dots — where the sound rule is the ACCUMULATED bound
  Sum|terms| <= 2^24, not the result hull. A primitive outside
  FLOAT_VETTED demotes the certificate with a sourced diagnostic
  (`fwhy`), and an inexact f32 reaching a use site or a kernel output
  is a violation. Every float equation is appended to the report's
  `exactness` trace — the machine-checkable theorem the MXU one-hot
  fe_mul candidate and the gtable selects are certified by.
- Loops: `scan` (what `fori_loop` lowers to) and fori-shaped `while`
  run to an abstract fixpoint with staged widening; `while` with a
  data-dependent trip count is rejected outright (determinism gate).

The determinism/allowlist gate piggybacks on the same walk: any
primitive without a registered transfer rule, any 64-bit dtype, any
non-exact float, and any non-fori `while` is reported. The allowlist IS
the transfer registry — a primitive we cannot bound is a primitive we
do not allow in consensus kernels.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.extend import core as jax_core

__all__ = [
    "AbstractArray",
    "Report",
    "Violation",
    "analyze",
    "analyze_closed",
    "ALLOWED_PRIMITIVES",
    "FLOAT_VETTED",
]

# Saturation sentinel: "unbounded" true value. Big enough that no real
# kernel bound reaches it; arithmetic on it stays exact Python-int math.
INF = 1 << 300
ROW_CAP = 64  # track per-row intervals along axes whose size is <= this
EXACT_F32 = 1 << 24  # integers up to 2^24 are exact in a float32 mantissa

# Dense power-of-two stages: each widening step jumps a carry bound to
# the next stage. The 2^14 stage matters: the W2 weak-representation rows
# (max 15631) live between 2^13 and 2^14, and a coarser ladder would
# overshoot point-coordinate carries past the region where the field ops
# are contracting, never to return.
_WIDEN_HI = [0, 1] + [(1 << k) - 1 for k in range(13, 32)] + [INF]
_WIDEN_LO = [0, -1] + [-(1 << k) for k in range(13, 32)] + [-INF]
_MAX_FIX_ITERS = 24


def _sat(v: int) -> int:
    return INF if v > INF else (-INF if v < -INF else v)


def _hull(a: Tuple[int, int], b: Tuple[int, int]) -> Tuple[int, int]:
    return (a[0] if a[0] < b[0] else b[0], a[1] if a[1] > b[1] else b[1])


def _widen_cell(old: Tuple[int, int], new: Tuple[int, int]) -> Tuple[int, int]:
    lo, hi = new
    if hi > old[1]:
        hi = next(t for t in _WIDEN_HI if t >= hi)
    if lo < old[0]:
        lo = next(t for t in _WIDEN_LO if t <= lo)
    return (lo, hi)


def _dkind(dtype) -> Tuple[str, int]:
    d = np.dtype(dtype)
    if d == np.bool_:
        return ("bool", 1)
    return ({"i": "int", "u": "uint", "f": "float"}.get(d.kind, "other"),
            d.itemsize * 8)


class AbstractArray:
    """Interval abstraction of one array: per-cell (lo, hi) true-value
    bounds over a (r0, r1) grid covering the first two axes (rX is 1 when
    that axis is collapsed/joined), plus relational flags.

    nz0: along axis 0, at most one element is nonzero (per fixed index of
         the remaining axes) — the one-hot/masked-select property.
    uni0: the value is constant along axis 0.
    dist0: every axis-0 row is a constant, and the row constants are
           pairwise distinct (an iota/table-key property that survives
           past ROW_CAP, where per-row cells can no longer express it).
    exactf: float dtype carrying exactly-representable integers
            (|v| <= 2^24); non-exact floats are violations at use sites.
    fwhy: for a float value with exactf=False, the sourced reason the
          exactness certificate was lost (the demoting equation). None
          for exact floats and non-floats. Carried so the eventual
          violation (at a use site or the kernel output) can name the
          equation that actually broke the chain, not just the symptom.
    """

    __slots__ = ("shape", "dtype", "cells", "nz0", "uni0", "dist0",
                 "exactf", "fwhy", "poly", "cong")

    def __init__(self, shape, dtype, cells, nz0=False, uni0=False,
                 exactf=False, dist0=False, poly=None, fwhy=None,
                 cong=None):
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self.cells = cells  # list[r0] of list[r1] of (lo, hi)
        self.nz0 = nz0
        self.uni0 = uni0
        self.dist0 = dist0
        self.exactf = exactf
        self.fwhy = fwhy
        # Optional sum-of-products refinement (see _poly_transfer): dict
        # monomial -> {row_or_None: int coeff}. Sound per-cell true-value
        # decomposition over interval atoms; used to recover correlations
        # interval arithmetic loses (the Karatsuba z1 = S - z0 - z2).
        self.poly = poly
        # Optional congruence facts (see _cong_transfer): a list of
        # per-axis-0-row facts, each None or a pair (m, r) meaning every
        # element of that row satisfies x ≡ r (mod m); m == 0 means the
        # row is EXACTLY r (the zero modulus is the whole-integer-kills-
        # everything convention: gcd(0, m) == m makes the join uniform).
        # Length is 1 (a fact uniform over all rows) or shape[0]. None
        # means no fact — always sound to drop, which is what widening
        # and every unsupported transfer do.
        self.cong = cong

    @property
    def r0(self) -> int:
        return len(self.cells)

    @property
    def r1(self) -> int:
        return len(self.cells[0])

    def cell(self, i: int, j: int) -> Tuple[int, int]:
        return self.cells[i if len(self.cells) > 1 else 0][
            j if len(self.cells[0]) > 1 else 0
        ]

    def joined(self) -> Tuple[int, int]:
        lo = min(c[0] for row in self.cells for c in row)
        hi = max(c[1] for row in self.cells for c in row)
        return (lo, hi)

    def rows0(self) -> List[Tuple[int, int]]:
        """Per-axis-0 row hulls, expanded to shape[0] entries."""
        n = self.shape[0] if self.shape else 1
        out = []
        for i in range(n):
            lo = min(self.cell(i, j)[0] for j in range(max(self.r1, 1)))
            hi = max(self.cell(i, j)[1] for j in range(max(self.r1, 1)))
            out.append((lo, hi))
        return out

    def same_as(self, other: "AbstractArray") -> bool:
        return (self.cells == other.cells and self.nz0 == other.nz0
                and self.uni0 == other.uni0 and self.exactf == other.exactf
                and self.dist0 == other.dist0)

    def __repr__(self):
        return (f"AbstractArray({self.shape}, {self.dtype.name}, "
                f"r=({self.r0},{self.r1}), hull={self.joined()})")


def _grid_dims(shape) -> Tuple[int, int]:
    g0 = shape[0] if len(shape) >= 1 and 1 < shape[0] <= ROW_CAP else 1
    g1 = shape[1] if len(shape) >= 2 and 1 < shape[1] <= ROW_CAP else 1
    return g0, g1


def _collapse_if_uniform(cells):
    if len(cells) > 1 and all(r == cells[0] for r in cells[1:]):
        cells = [cells[0]]
    if len(cells[0]) > 1 and all(
        all(c == row[0] for c in row[1:]) for row in cells
    ):
        cells = [[row[0]] for row in cells]
    return cells


def mk(shape, dtype, cells, nz0=False, uni0=False, exactf=False,
       dist0=False):
    """Normalize + build: saturate, reduce unsigned mod 2^w, clamp bool,
    collapse uniform grids (perf: most values are batch-uniform)."""
    kind, bits = _dkind(dtype)
    out = []
    for row in cells:
        r = []
        for lo, hi in row:
            lo, hi = _sat(lo), _sat(hi)
            if kind == "uint":
                m = 1 << bits
                if hi - lo >= m:
                    lo, hi = 0, m - 1
                else:
                    lo2 = lo % m
                    hi2 = lo2 + (hi - lo)
                    lo, hi = (0, m - 1) if hi2 >= m else (lo2, hi2)
            elif kind == "bool":
                lo, hi = max(lo, 0), min(hi, 1)
            r.append((lo, hi))
        out.append(r)
    out = _collapse_if_uniform(out)
    if len(shape) >= 1 and shape[0] == 1:
        uni0 = True
    return AbstractArray(shape, dtype, out, nz0=nz0, uni0=uni0,
                         exactf=exactf, dist0=dist0)


def full_range(shape, dtype) -> AbstractArray:
    kind, bits = _dkind(dtype)
    if kind == "int":
        c = (-(1 << (bits - 1)), (1 << (bits - 1)) - 1)
    elif kind == "uint":
        c = (0, (1 << bits) - 1)
    elif kind == "bool":
        c = (0, 1)
    else:
        c = (-INF, INF)
    return mk(shape, dtype, [[c]])


def top(shape, dtype) -> AbstractArray:
    """Unknown TRUE value (post-violation recovery): unbounded."""
    return AbstractArray(shape, dtype, [[(-INF, INF)]])


def from_concrete(arr) -> AbstractArray:
    """Abstract a concrete array (jaxpr consts / literals) exactly, with
    per-row mins/maxes along the tracked axes."""
    a = np.asarray(arr)
    kind, _ = _dkind(a.dtype)
    exactf = False
    if kind == "float":
        finite = bool(np.all(np.isfinite(a)))
        integral = finite and bool(np.all(a == np.trunc(a)))
        small = finite and (a.size == 0 or float(np.max(np.abs(a))) <= EXACT_F32)
        exactf = integral and small
        to_int = (lambda v: int(v)) if exactf else (lambda v: int(np.floor(v)))
    else:
        to_int = int
    if a.size == 0:
        return mk(a.shape, a.dtype, [[(0, 0)]], exactf=exactf)
    g0, g1 = _grid_dims(a.shape)
    cells = []
    for i in range(g0):
        sl0 = a[i] if g0 > 1 else a
        row = []
        for j in range(g1):
            sl = (sl0[j] if g0 > 1 else sl0[:, j]) if g1 > 1 else sl0
            row.append((to_int(np.min(sl)), to_int(np.max(sl))))
        cells.append(row)
    uni0 = bool(a.ndim >= 1 and a.shape[0] >= 1
                and np.all(a == a[:1]))
    dist0 = False
    if a.ndim >= 1 and a.shape[0] > 1 and kind != "float":
        flat = a.reshape(a.shape[0], -1)
        row_lo, row_hi = flat.min(axis=1), flat.max(axis=1)
        dist0 = bool(np.all(row_lo == row_hi)
                     and len(np.unique(row_lo)) == a.shape[0])
    av = mk(a.shape, a.dtype, cells, uni0=uni0, exactf=exactf,
            dist0=dist0)
    if kind != "float":
        # Congruence seeding: a constant row is exactly its value (m=0).
        rows = [((0, row[0][0]) if all(lo == hi and lo == row[0][0]
                                       for lo, hi in row) else None)
                for row in cells]
        if any(f is not None for f in rows):
            av.cong = rows
    return av


@dataclass
class Violation:
    kind: str      # overflow | float | allowlist | dtype64 | loop | internal
                   # | grid | ref | vmem (Pallas layer, pallas_check.py)
    where: str     # eqn path, e.g. "scan[3].body.eqn[17] mul"
    msg: str

    def __str__(self):
        return f"[{self.kind}] {self.where}: {self.msg}"


@dataclass
class Report:
    name: str
    ok: bool = True
    violations: List[Violation] = field(default_factory=list)
    prim_counts: Dict[str, int] = field(default_factory=dict)
    n_eqns: int = 0
    out_bounds: List[List[Tuple[int, int]]] = field(default_factory=list)
    wrap_eqns: int = 0      # signed ring ops whose interval left int32
    max_observed: int = 0   # largest |bound| proven at an observation
    notes: List[str] = field(default_factory=list)
    # Exact-float theorem trace: one entry per float-dtyped equation
    # output (unmuted passes), recording the primitive, the proven
    # magnitude bound, whether the exactness certificate survived, and —
    # for dot_general / reduce_sum — the accumulated sum-of-|terms|
    # bound actually checked against 2^24. This is the machine-checkable
    # per-value bound trace the report JSON exports.
    exactness: List[dict] = field(default_factory=list)
    # Congruence facts proven for each kernel output: one list per
    # output, one entry per axis-0 row, each None or (m, r) meaning
    # every element of that row is ≡ r (mod m) (m == 0: exactly r).
    out_cong: List[List[Optional[Tuple[int, int]]]] = field(
        default_factory=list)
    # Pallas-layer facts (analysis/pallas_check.py): peak VMEM live set
    # of the kernel (blocks + scratch + intermediates) and the grid shape.
    vmem_peak_bytes: Optional[int] = None
    grid: Optional[Tuple[int, ...]] = None

    def to_dict(self) -> dict:
        def b(v):  # saturated bounds -> JSON-safe
            return "unbounded" if abs(v) >= INF else int(v)

        d = {
            "kernel": self.name,
            "ok": self.ok,
            "violations": [
                {"kind": v.kind, "where": v.where, "msg": v.msg}
                for v in self.violations
            ],
            "n_eqns": self.n_eqns,
            "prim_counts": dict(sorted(self.prim_counts.items())),
            "wrap_eqns": self.wrap_eqns,
            "max_observed": b(self.max_observed),
            "out_bounds": [
                [[b(lo), b(hi)] for lo, hi in rows] for rows in self.out_bounds
            ],
            "notes": self.notes,
        }
        if self.exactness:
            d["exactness"] = self.exactness
        if any(any(f is not None for f in rows) for rows in self.out_cong):
            d["out_cong"] = [
                [None if f is None else [int(f[0]), int(f[1])]
                 for f in rows]
                for rows in self.out_cong
            ]
        if self.vmem_peak_bytes is not None:
            d["vmem_peak_bytes"] = int(self.vmem_peak_bytes)
        if self.grid is not None:
            d["grid"] = [int(g) for g in self.grid]
        return d


_TRACE_CAP = 4096  # exactness-trace entries per report (overflow noted)


class _Ctx:
    def __init__(self, report: Report):
        self.report = report
        self.mute = 0  # >0 during fixpoint warmup iterations
        # >0 while evaluating a loop body (any _fixpoint pass, including
        # the final unmuted one) or a multi-branch cond. Stateful rules
        # (the Ref writes of analysis/pallas_check.py) must downgrade
        # strong updates to hull-merges here: the body may abstract more
        # than one concrete execution.
        self.in_loop = 0
        # Scratchpad cleared before each equation: transfer rules drop
        # facts here (e.g. dot_general's accumulated sum bound) and the
        # float post-pass folds them into the exactness-trace entry.
        self.eqn_facts: Dict[str, object] = {}

    def violate(self, kind: str, where: str, msg: str):
        if self.mute:
            return
        self.report.ok = False
        self.report.violations.append(Violation(kind, where, msg))

    def trace_float(self, entry: dict):
        if self.mute:
            return
        tr = self.report.exactness
        if len(tr) >= _TRACE_CAP:
            if len(tr) == _TRACE_CAP:
                tr.append({"note": f"exactness trace capped at "
                                   f"{_TRACE_CAP} entries"})
            return
        tr.append(entry)

    def note_wrap(self):
        if not self.mute:
            self.report.wrap_eqns += 1

    def observe(self, av: AbstractArray, where: str, what: str) -> AbstractArray:
        """Demand av's true interval fit its (signed) lane; unsigned and
        bool are residue-defined and always pass. Returns a clamped value
        so one failure does not cascade into noise."""
        kind, bits = _dkind(av.dtype)
        if kind not in ("int",):
            return av
        lo_l, hi_l = -(1 << (bits - 1)), (1 << (bits - 1)) - 1
        bad = None
        for row in av.cells:
            for lo, hi in row:
                if lo < lo_l or hi > hi_l:
                    bad = (lo, hi)
                    break
                m = max(abs(lo), abs(hi))
                if not self.mute and m > self.report.max_observed:
                    self.report.max_observed = m
            if bad:
                break
        if bad is None:
            return av
        lo, hi = bad

        def s(v):
            return "unbounded" if abs(v) >= INF else str(v)

        self.violate(
            "overflow", where,
            f"{what}: derived interval [{s(lo)}, {s(hi)}] exceeds "
            f"int{bits} lane range [{lo_l}, {hi_l}]",
        )
        cells = [[(max(lo2, lo_l), min(hi2, hi_l)) for lo2, hi2 in row]
                 for row in av.cells]
        return AbstractArray(av.shape, av.dtype, cells, nz0=av.nz0,
                             uni0=av.uni0)


# ---------------------------------------------------------------------------
# Grid utilities.

def _aligned_cells(a: AbstractArray, b: AbstractArray):
    """Iterate aligned (r0, r1) cell grids of two same-result-shape values
    (operand grids may be 1 where the other tracks rows)."""
    r0 = max(a.r0, b.r0)
    r1 = max(a.r1, b.r1)
    return r0, r1


def _ewise(ctx, shape, dtype, ops: Sequence[AbstractArray],
           f: Callable[..., Tuple[int, int]], **flags) -> AbstractArray:
    r0 = max(o.r0 for o in ops)
    r1 = max(o.r1 for o in ops)
    cells = [
        [f(*(o.cell(i, j) for o in ops)) for j in range(r1)]
        for i in range(r0)
    ]
    return mk(shape, dtype, cells, **flags)


def take_axes(av: AbstractArray, shape, a0: Optional[int],
              a1: Optional[int], **flags) -> AbstractArray:
    """Rebuild a grid for a result whose axis 0 comes from operand axis
    `a0` and axis 1 from `a1` (None = no tracked source: join). Joins over
    whichever tracked operand axes are not referenced."""

    def src_rows(ax):
        if ax == 0 and av.r0 > 1:
            return [
                ( min(c[0] for c in row), max(c[1] for c in row) )
                for row in av.cells
            ], av.r0
        if ax == 1 and av.r1 > 1:
            return [
                (
                    min(av.cells[i][j][0] for i in range(av.r0)),
                    max(av.cells[i][j][1] for i in range(av.r0)),
                )
                for j in range(av.r1)
            ], av.r1
        return None, 1

    if (a0 == 0 and a1 == 1) and av.r0 >= 1:
        cells = av.cells
    elif (a0 == 1 and a1 == 0):
        cells = [
            [av.cells[i][j] for i in range(av.r0)] for j in range(av.r1)
        ]
    else:
        rows_a, _ = src_rows(a0)
        rows_b, _ = src_rows(a1) if a1 is not None else (None, 1)
        if rows_a is not None and rows_b is None:
            cells = [[c] for c in rows_a]
        elif rows_a is None and rows_b is not None:
            cells = [rows_b]
        elif rows_a is not None and rows_b is not None:
            # Both requested axes tracked but the cross-cells unknown:
            # every element of result cell (i, j) lies in BOTH source-row
            # hulls, so the intersection is sound (non-empty for any cell
            # that abstracts a real element; hull as a safe fallback).
            cells = [
                [
                    (max(ra[0], rb[0]), min(ra[1], rb[1]))
                    if max(ra[0], rb[0]) <= min(ra[1], rb[1])
                    else _hull(ra, rb)
                    for rb in rows_b
                ]
                for ra in rows_a
            ]
        else:
            cells = [[av.joined()]]
    flags.setdefault("exactf", av.exactf)
    return mk(shape, av.dtype, cells, **flags)


def join_values(a: AbstractArray, b: AbstractArray) -> AbstractArray:
    r0 = max(a.r0, b.r0)
    r1 = max(a.r1, b.r1)
    cells = [
        [_hull(a.cell(i, j), b.cell(i, j)) for j in range(r1)]
        for i in range(r0)
    ]
    out = AbstractArray(
        a.shape, a.dtype, _collapse_if_uniform(cells),
        nz0=a.nz0 and b.nz0, uni0=a.uni0 and b.uni0,
        exactf=a.exactf and b.exactf,
    )
    if a.cong is not None and b.cong is not None:
        n = max(len(a.cong), len(b.cong))
        ra, rb = _cong_expand(a.cong, n), _cong_expand(b.cong, n)
        rows = [_cong_join(fa, fb) for fa, fb in zip(ra, rb, strict=True)]
        if any(f is not None for f in rows):
            out.cong = rows
    return out


# ---------------------------------------------------------------------------
# Transfer rules. RULES maps primitive name -> fn(interp, eqn, ins, where)
# -> list of AbstractArray. The keys double as the op allowlist.

RULES: Dict[str, Callable] = {}


def _rule(*names):
    def deco(fn):
        for n in names:
            RULES[n] = fn
        return fn
    return deco


def _out_aval(eqn, i=0):
    return eqn.outvars[i].aval


def _is_signed(av: AbstractArray) -> bool:
    return _dkind(av.dtype)[0] == "int"


def _int32_ok(cell: Tuple[int, int], bits: int) -> bool:
    return cell[0] >= -(1 << (bits - 1)) and cell[1] <= (1 << (bits - 1)) - 1


def _check_float_exact(interp, where, ops, result_cells_hull):
    """Shared float-policy check for arithmetic combining floats."""
    bad = next((o for o in ops
                if _dkind(o.dtype)[0] == "float" and not o.exactf), None)
    if bad is not None:
        why = f" [{bad.fwhy}]" if bad.fwhy else ""
        interp.ctx.violate(
            "float", where,
            "float operand without exact-integer provenance "
            f"(only int->f32 converts of values |v| <= 2^24 are vetted){why}",
        )
        return False
    lo, hi = result_cells_hull
    if max(abs(lo), abs(hi)) > EXACT_F32:
        interp.ctx.violate(
            "float", where,
            f"float result interval [{lo}, {hi}] exceeds the 2^24 "
            "exact-integer range of a float32 mantissa",
        )
        return False
    return True


@_rule("add", "sub", "mul")
def _r_arith(interp, eqn, ins, where):
    a, b = ins
    out = _out_aval(eqn)
    name = eqn.primitive.name

    if name == "add":
        f = lambda x, y: (x[0] + y[0], x[1] + y[1])  # noqa: E731
    elif name == "sub":
        f = lambda x, y: (x[0] - y[1], x[1] - y[0])  # noqa: E731
    else:
        def f(x, y):
            ps = (x[0] * y[0], x[0] * y[1], x[1] * y[0], x[1] * y[1])
            return (min(ps), max(ps))

    nz0 = name == "mul" and (a.nz0 or b.nz0)
    # Adding/subtracting a single constant shifts every row by the same
    # amount: distinct constant rows stay distinct constant rows. (The
    # Pallas G-loop builds its one-hot key as `broadcasted_iota + 1`,
    # which must keep dist0 past ROW_CAP or the MXU select false-alarms.)
    dist0 = False
    if name in ("add", "sub"):
        ja, jb = a.joined(), b.joined()
        dist0 = ((a.dist0 and jb[0] == jb[1])
                 or (b.dist0 and ja[0] == ja[1]))
    res = _ewise(interp.ctx, out.shape, out.dtype, ins, f,
                 nz0=nz0, uni0=a.uni0 and b.uni0, dist0=dist0)
    kind, bits = _dkind(out.dtype)
    if kind == "float":
        ok = _check_float_exact(interp, where, ins, res.joined())
        res.exactf = ok
    elif kind == "int":
        if not all(_int32_ok(c, bits) for row in res.cells for c in row):
            interp.ctx.note_wrap()  # transient wrap: legal for ring ops
    return [res]


@_rule("neg")
def _r_neg(interp, eqn, ins, where):
    (a,) = ins
    out = _out_aval(eqn)
    res = _ewise(interp.ctx, out.shape, out.dtype, ins,
                 lambda x: (-x[1], -x[0]), uni0=a.uni0)
    if _dkind(out.dtype)[0] == "float":
        res.exactf = _check_float_exact(interp, where, ins, res.joined())
    return [res]


def _up2m1(v: int) -> int:
    """Smallest 2^k - 1 >= v (for nonneg v)."""
    return (1 << max(v, 0).bit_length()) - 1


@_rule("and", "or", "xor")
def _r_bitwise(interp, eqn, ins, where):
    a, b = ins
    out = _out_aval(eqn)
    kind, bits = _dkind(out.dtype)
    name = eqn.primitive.name
    if kind == "bool":
        if name == "and":
            f = lambda x, y: (min(x[0], y[0]), min(x[1], y[1]))  # noqa: E731
        elif name == "or":
            f = lambda x, y: (max(x[0], y[0]), max(x[1], y[1]))  # noqa: E731
        else:
            f = lambda x, y: (0 if x == y == (0, 0) else 0, 1)  # noqa: E731
        return [_ewise(interp.ctx, out.shape, out.dtype, ins, f,
                       uni0=a.uni0 and b.uni0)]

    def f(x, y):
        x_in = x[0] >= 0 and x[1] < (1 << (bits - 1 if kind == "int" else bits))
        y_in = y[0] >= 0 and y[1] < (1 << (bits - 1 if kind == "int" else bits))
        if name == "and":
            # x & y <= min(x, y) for any nonneg in-range operand; with one
            # wrapped operand the other nonneg bound still caps the result.
            if x_in and y_in:
                return (0, min(x[1], y[1]))
            if x_in:
                return (0, x[1])
            if y_in:
                return (0, y[1])
        elif x_in and y_in:  # or / xor
            return (0, _up2m1(max(x[1], y[1])))
        # Machine result is some in-range lane value; true == machine for
        # bitwise ops (they are residue functions), so full range is sound.
        if kind == "int":
            return (-(1 << (bits - 1)), (1 << (bits - 1)) - 1)
        return (0, (1 << bits) - 1)

    return [_ewise(interp.ctx, out.shape, out.dtype, ins, f,
                   uni0=a.uni0 and b.uni0)]


@_rule("not")
def _r_not(interp, eqn, ins, where):
    (a,) = ins
    out = _out_aval(eqn)
    kind, bits = _dkind(out.dtype)
    if kind == "bool":
        f = lambda x: (1 - x[1], 1 - x[0])  # noqa: E731
    else:
        f = lambda x: (-x[1] - 1, -x[0] - 1)  # ~x == -x - 1 (ring)  # noqa: E731
    return [_ewise(interp.ctx, out.shape, out.dtype, ins, f, uni0=a.uni0)]


@_rule("shift_left")
def _r_shl(interp, eqn, ins, where):
    a, s = ins
    out = _out_aval(eqn)
    s = interp.ctx.observe(s, where, "shift amount")

    def f(x, sh):
        slo, shi = max(sh[0], 0), max(sh[1], 0)
        cands = (x[0] << slo, x[0] << shi, x[1] << slo, x[1] << shi)
        return (min(cands), max(cands))

    # Ring-compatible: v << s is v * 2^s mod 2^w; no observation on v.
    return [_ewise(interp.ctx, out.shape, out.dtype, ins, f,
                   uni0=a.uni0 and s.uni0)]


@_rule("shift_right_arithmetic", "shift_right_logical")
def _r_shr(interp, eqn, ins, where):
    a, s = ins
    out = _out_aval(eqn)
    # OBSERVATION: a right shift reads the lane's bit pattern as a value;
    # a wrapped operand shifts garbage. The operand must be in-range.
    a = interp.ctx.observe(a, where, "right-shift operand")
    s = interp.ctx.observe(s, where, "shift amount")
    logical = eqn.primitive.name == "shift_right_logical"
    kind, bits = _dkind(a.dtype)
    if logical and kind == "int":
        a2 = a  # logical shift on signed: require nonneg or give machine range
        neg = any(c[0] < 0 for row in a2.cells for c in row)
        if neg:
            return [full_range(out.shape, out.dtype)]

    def f(x, sh):
        slo, shi = max(sh[0], 0), max(sh[1], 0)
        cands = (x[0] >> slo, x[0] >> shi, x[1] >> slo, x[1] >> shi)
        return (min(cands), max(cands))

    return [_ewise(interp.ctx, out.shape, out.dtype, ins, f,
                   uni0=a.uni0 and s.uni0)]


def _distinct_singleton_rows(av: AbstractArray) -> bool:
    if not av.shape or av.r0 != av.shape[0] or av.r0 <= 1:
        return False
    vals = []
    for i in range(av.r0):
        los = [av.cells[i][j] for j in range(av.r1)]
        lo = min(c[0] for c in los)
        hi = max(c[1] for c in los)
        if lo != hi:
            return False
        vals.append(lo)
    return len(set(vals)) == len(vals)


@_rule("eq", "ne", "lt", "le", "gt", "ge")
def _r_cmp(interp, eqn, ins, where):
    a, b = ins
    out = _out_aval(eqn)
    # OBSERVATION: comparisons read true values (signed lanes must hold
    # their true value; unsigned/bool compare residues by definition).
    a = interp.ctx.observe(a, where, "comparison lhs")
    b = interp.ctx.observe(b, where, "comparison rhs")
    name = eqn.primitive.name
    nz0 = False
    if name == "eq":
        # One-hot detection: distinct constant rows vs an axis-0-uniform
        # value -> at most one row can match. dist0 carries the same
        # distinctness promise for tables longer than ROW_CAP.
        def distinct(v):
            return v.dist0 or _distinct_singleton_rows(v)

        if (distinct(a) and b.uni0) or (distinct(b) and a.uni0):
            nz0 = True

    def f(x, y):
        lo, hi = 0, 1
        if name == "eq":
            if x[1] < y[0] or y[1] < x[0]:
                hi = 0
            elif x[0] == x[1] == y[0] == y[1]:
                lo = 1
        elif name == "ne":
            if x[1] < y[0] or y[1] < x[0]:
                lo = 1
            elif x[0] == x[1] == y[0] == y[1]:
                hi = 0
        elif name == "lt":
            if x[1] < y[0]:
                lo = 1
            if x[0] >= y[1]:
                hi = 0
        elif name == "le":
            if x[1] <= y[0]:
                lo = 1
            if x[0] > y[1]:
                hi = 0
        elif name == "gt":
            if x[0] > y[1]:
                lo = 1
            if x[1] <= y[0]:
                hi = 0
        elif name == "ge":
            if x[0] >= y[1]:
                lo = 1
            if x[1] < y[0]:
                hi = 0
        return (lo, hi)

    return [_ewise(interp.ctx, out.shape, out.dtype, ins, f, nz0=nz0,
                   uni0=a.uni0 and b.uni0)]


@_rule("min", "max", "clamp", "rem", "div", "abs", "sign")
def _r_order(interp, eqn, ins, where):
    out = _out_aval(eqn)
    name = eqn.primitive.name
    ins = [interp.ctx.observe(o, where, f"{name} operand") for o in ins]
    if any(_dkind(o.dtype)[0] == "float" for o in ins) \
            and name in ("div", "rem"):
        interp.ctx.violate("float", where,
                           f"float {name} is never exact-integer")
        return [top(out.shape, out.dtype)]
    if name == "min":
        f = lambda x, y: (min(x[0], y[0]), min(x[1], y[1]))  # noqa: E731
    elif name == "max":
        f = lambda x, y: (max(x[0], y[0]), max(x[1], y[1]))  # noqa: E731
    elif name == "clamp":
        f = lambda lo, x, hi: (  # noqa: E731
            min(max(x[0], lo[0]), hi[1]), max(min(x[1], hi[1]), lo[0]))
    elif name == "abs":
        f = lambda x: (  # noqa: E731
            0 if x[0] <= 0 <= x[1] else min(abs(x[0]), abs(x[1])),
            max(abs(x[0]), abs(x[1])))
    elif name == "sign":
        f = lambda x: (-1 if x[0] < 0 else (0 if x[0] == 0 else 1),  # noqa: E731
                       1 if x[1] > 0 else (0 if x[1] == 0 else -1))
    elif name == "rem":
        def f(x, y):
            m = max(abs(y[0]), abs(y[1]))
            return (-m + 1 if x[0] < 0 else 0, m - 1)
    else:  # div (integer)
        def f(x, y):
            if y[0] <= 0 <= y[1]:
                return (-max(abs(x[0]), abs(x[1])), max(abs(x[0]), abs(x[1])))
            cands = []
            for xv in x:
                for yv in y:
                    q = abs(xv) // abs(yv)
                    cands.append(q if (xv >= 0) == (yv > 0) else -q)
            return (min(cands) - 1, max(cands) + 1)

    res = _ewise(interp.ctx, out.shape, out.dtype, ins, f)
    if _dkind(out.dtype)[0] == "float":
        # min/max/clamp/abs/sign SELECT (or negate) one operand value:
        # exactness is preserved whenever every float operand carries the
        # certificate, and the result magnitude is within operand hulls.
        res.exactf = _check_float_exact(interp, where, ins, res.joined())
    return [res]


@_rule("integer_pow")
def _r_ipow(interp, eqn, ins, where):
    (a,) = ins
    out = _out_aval(eqn)
    y = eqn.params["y"]

    def f(x):
        cands = [x[0] ** y, x[1] ** y]
        if y % 2 == 0 and x[0] <= 0 <= x[1]:
            cands.append(0)
        return (min(cands), max(cands))

    return [_ewise(interp.ctx, out.shape, out.dtype, ins, f, uni0=a.uni0)]


@_rule("select_n")
def _r_select(interp, eqn, ins, where):
    out = _out_aval(eqn)
    pred, cases = ins[0], ins[1:]
    kind, _ = _dkind(out.dtype)
    if kind == "float" and not all(c.exactf for c in cases):
        interp.ctx.violate("float", where,
                           "select over non-exact float branches")
    r0 = max(c.r0 for c in cases)
    r1 = max(c.r1 for c in cases)
    plo, phi = pred.joined()
    if plo == phi and 0 <= plo < len(cases):
        chosen = [cases[plo]]
    else:
        chosen = cases
    cells = [
        [
            (min(c.cell(i, j)[0] for c in chosen),
             max(c.cell(i, j)[1] for c in chosen))
            for j in range(r1)
        ]
        for i in range(r0)
    ]
    return [mk(out.shape, out.dtype, cells,
               uni0=pred.uni0 and all(c.uni0 for c in chosen),
               exactf=all(c.exactf for c in chosen))]


@_rule("convert_element_type")
def _r_convert(interp, eqn, ins, where):
    (a,) = ins
    out = _out_aval(eqn)
    skind, _ = _dkind(a.dtype)
    dkind, dbits = _dkind(out.dtype)
    flags = dict(nz0=a.nz0, uni0=a.uni0)
    if dkind == "float":
        # int/bool -> float: exact iff |v| <= 2^24 and the source is true.
        a2 = interp.ctx.observe(a, where, "int->float convert source")
        lo, hi = a2.joined()
        if skind == "float":
            flags["exactf"] = a.exactf
        elif max(abs(lo), abs(hi)) <= EXACT_F32:
            flags["exactf"] = True
        else:
            interp.ctx.violate(
                "float", where,
                f"convert to float of interval [{lo}, {hi}] exceeds the "
                "2^24 exact-integer float32 range",
            )
        return [mk(out.shape, out.dtype, a2.cells, **flags)]
    if skind == "float":
        if not a.exactf:
            why = f" [{a.fwhy}]" if a.fwhy else ""
            interp.ctx.violate(
                "float", where,
                "float->int convert of a non-exact float (value may have "
                f"rounded; only exact-integer floats are vetted){why}",
            )
            return [full_range(out.shape, out.dtype)]
        a = interp.ctx.observe(
            AbstractArray(a.shape, np.dtype(np.int32), a.cells, nz0=a.nz0,
                          uni0=a.uni0),
            where, "float->int convert",
        )
        return [mk(out.shape, out.dtype, a.cells, **flags)]
    if dkind == "int":
        # Converting into a signed lane observes the true value unless the
        # source residue provably fits (mk reduces unsigned for us).
        if skind == "int":
            a = interp.ctx.observe(a, where, "int->int convert")
        return [mk(out.shape, out.dtype, a.cells, **flags)]
    # -> uint / bool: residue (mk normalizes), always defined.
    if dkind == "bool":
        cells = [[(0 if c == (0, 0) else (1 if c[0] > 0 or c[1] < 0 else 0),
                   0 if c == (0, 0) else 1)] for row in a.cells
                 for c in [row[0]]]
        # simpler: nonzero test per joined cells
        lo, hi = a.joined()
        nz_lo = 1 if (lo > 0 or hi < 0) else 0
        nz_hi = 0 if (lo == 0 and hi == 0) else 1
        return [mk(out.shape, out.dtype, [[(nz_lo, nz_hi)]], **flags)]
    return [mk(out.shape, out.dtype, a.cells, **flags)]


@_rule("device_put", "copy", "stop_gradient")
def _r_identity(interp, eqn, ins, where):
    return [ins[0]]


@_rule("broadcast_in_dim")
def _r_broadcast(interp, eqn, ins, where):
    (a,) = ins
    out = _out_aval(eqn)
    bdims = eqn.params["broadcast_dimensions"]
    # Which operand axis feeds result axes 0/1 (None: fresh broadcast dim)?
    src = {r: o for o, r in enumerate(bdims)}

    def src_axis(res_ax):
        o = src.get(res_ax)
        if o is None:
            return None, True  # fresh dim: uniform along it
        if a.shape[o] == 1 and len(out.shape) > res_ax and out.shape[res_ax] != 1:
            return None, True  # broadcast from size-1: uniform
        return o, False

    s0, fresh0 = src_axis(0)
    s1, _ = src_axis(1)
    uni0 = a.uni0 if s0 == 0 else (True if fresh0 else False)
    if s0 is not None and s0 not in (0, 1):
        s0 = None
    if s1 is not None and s1 not in (0, 1):
        s1 = None
    nz0 = a.nz0 and s0 == 0
    res = take_axes(a, out.shape, s0, s1, nz0=nz0)
    res.uni0 = uni0 or res.uni0
    res.exactf = a.exactf
    # Broadcasting only replicates: constant-distinct rows stay so as
    # long as result axis 0 is operand axis 0 unchanged.
    if a.dist0 and s0 == 0 and out.shape[0] == a.shape[0]:
        res.dist0 = True
    return [res]


@_rule("reshape")
def _r_reshape(interp, eqn, ins, where):
    (a,) = ins
    out = _out_aval(eqn)
    old, new = a.shape, out.shape
    flags = dict(exactf=a.exactf)
    if old and new and old[0] == new[0]:
        keep_r1 = len(old) > 1 and len(new) > 1 and old[1] == new[1]
        res = take_axes(a, new, 0, 1 if keep_r1 else None,
                        nz0=a.nz0, **flags)
        res.uni0 = a.uni0
        return [res]
    rows = a.rows0() if old and a.r0 > 1 else None
    if rows is not None and new and new[0] % old[0] == 0 and old[0] > 1:
        # leading-axis split of each old row into k new rows (C order)
        k = new[0] // old[0]
        if k * old[0] == new[0] and len(old) >= 2 and old[1] % k == 0:
            pass  # fallthrough to repeat expansion below
        rep = [r for r in rows for _ in range(k)]
        if new[0] <= ROW_CAP:
            return [mk(new, out.dtype, [[c] for c in rep], **flags)]
    if rows is not None and new and old[0] % max(new[0], 1) == 0 and new[0] >= 1:
        # leading-axis merge: groups of consecutive old rows join
        g = old[0] // new[0]
        grouped = []
        for i in range(new[0]):
            chunk = rows[i * g:(i + 1) * g]
            grouped.append((min(c[0] for c in chunk),
                            max(c[1] for c in chunk)))
        if new[0] <= ROW_CAP:
            return [mk(new, out.dtype, [[c] for c in grouped], **flags)]
    return [mk(new, out.dtype, [[a.joined()]], **flags)]


@_rule("squeeze")
def _r_squeeze(interp, eqn, ins, where):
    (a,) = ins
    out = _out_aval(eqn)
    dims = set(eqn.params["dimensions"])
    remaining = [i for i in range(len(a.shape)) if i not in dims]
    s0 = remaining[0] if len(remaining) >= 1 else None
    s1 = remaining[1] if len(remaining) >= 2 else None
    s0 = s0 if s0 in (0, 1) else None
    s1 = s1 if s1 in (0, 1) else None
    res = take_axes(a, out.shape, s0, s1, nz0=a.nz0 and s0 == 0)
    res.uni0 = a.uni0 if s0 == 0 else res.uni0
    return [res]


@_rule("transpose")
def _r_transpose(interp, eqn, ins, where):
    (a,) = ins
    out = _out_aval(eqn)
    perm = eqn.params["permutation"]
    s0 = perm[0] if len(perm) >= 1 and perm[0] in (0, 1) else None
    s1 = perm[1] if len(perm) >= 2 and perm[1] in (0, 1) else None
    res = take_axes(a, out.shape, s0, s1, nz0=a.nz0 and s0 == 0)
    res.uni0 = a.uni0 if s0 == 0 else res.uni0
    return [res]


@_rule("slice")
def _r_slice(interp, eqn, ins, where):
    (a,) = ins
    out = _out_aval(eqn)
    starts = eqn.params["start_indices"]
    strides = eqn.params.get("strides") or (1,) * len(starts)

    def rows_for(ax, get):
        n_out = out.shape[ax]
        return [get(starts[ax] + i * strides[ax]) for i in range(n_out)]

    cells = None
    if a.r0 > 1 and out.shape and out.shape[0] <= ROW_CAP:
        rows_idx = [starts[0] + i * strides[0] for i in range(out.shape[0])]
        if a.r1 > 1 and len(out.shape) > 1 and out.shape[1] <= ROW_CAP:
            cols_idx = [starts[1] + j * strides[1]
                        for j in range(out.shape[1])]
            cells = [[a.cells[i][j] for j in cols_idx] for i in rows_idx]
        else:
            cells = [
                [(min(c[0] for c in a.cells[i]),
                  max(c[1] for c in a.cells[i]))]
                for i in rows_idx
            ]
    elif a.r1 > 1 and len(out.shape) > 1 and out.shape[1] <= ROW_CAP and (
        not a.shape or a.shape[0] == out.shape[0] or a.r0 == 1
    ):
        cols_idx = [starts[1] + j * strides[1] for j in range(out.shape[1])]
        cells = [[a.cells[0][j] for j in cols_idx]]
    if cells is None:
        cells = [[a.joined()]]
    return [mk(out.shape, out.dtype, cells, nz0=False, uni0=a.uni0,
               exactf=a.exactf)]


@_rule("concatenate")
def _r_concat(interp, eqn, ins, where):
    out = _out_aval(eqn)
    dim = eqn.params["dimension"]
    if dim == 0 and out.shape[0] <= ROW_CAP:
        r1 = max(o.r1 for o in ins)
        cells = []
        for o in ins:
            n = o.shape[0]
            for i in range(n):
                cells.append([o.cell(i, j) for j in range(r1)])
        return [mk(out.shape, out.dtype, cells,
                   exactf=all(o.exactf for o in ins))]
    if dim == 1 and len(out.shape) > 1 and out.shape[1] <= ROW_CAP:
        r0 = max(o.r0 for o in ins)
        cells = [[] for _ in range(r0)]
        for o in ins:
            for j in range(o.shape[1]):
                for i in range(r0):
                    cells[i].append(o.cell(i, j))
        return [mk(out.shape, out.dtype, cells,
                   exactf=all(o.exactf for o in ins))]
    # concat along an untracked axis: rowwise join across operands
    r0 = max(o.r0 for o in ins)
    r1 = max(o.r1 for o in ins)
    cells = [
        [
            (min(o.cell(i, j)[0] for o in ins),
             max(o.cell(i, j)[1] for o in ins))
            for j in range(r1)
        ]
        for i in range(r0)
    ]
    return [mk(out.shape, out.dtype, cells,
               nz0=all(o.nz0 for o in ins),
               uni0=all(o.uni0 for o in ins),
               exactf=all(o.exactf for o in ins))]


@_rule("pad")
def _r_pad(interp, eqn, ins, where):
    a, pv = ins
    out = _out_aval(eqn)
    cfg = eqn.params["padding_config"]
    pcell = pv.joined()

    def pad_axis(rows, n_in, n_out, lo, hi, interior):
        res = []
        for i in range(n_out):
            src = i - lo
            if src < 0 or src > (n_in - 1) * (interior + 1):
                res.append(pcell)
            elif src % (interior + 1) == 0:
                res.append(rows[src // (interior + 1)])
            else:
                res.append(pcell)
        return res

    # Padding on axes >= 2 is untracked by the (r0, r1) grid: fold the pad
    # value into every kept cell so those positions stay covered.
    deep_pad = any(c != (0, 0, 0) for c in cfg[2:])

    def keep(c):
        return _hull(c, pcell) if deep_pad else c

    if (a.shape and out.shape and out.shape[0] <= ROW_CAP
            and a.shape[0] <= 4 * ROW_CAP):
        lo, hi, interior = cfg[0]
        if (len(out.shape) > 1 and 1 <= out.shape[1] <= ROW_CAP
                and a.shape[1] <= ROW_CAP):
            # Full per-cell grid on both tracked axes. Crucially this runs
            # even when a.r1 == 1 (e.g. a (20, 1) -> (20, 2) column pad in
            # an associative-scan interleave): the padded column must read
            # as the pad value, not the data hull, or the even/odd merge
            # add doubles every bound downstream.
            lo1, hi1, int1 = cfg[1]
            grid = [
                pad_axis([keep(a.cell(i, j)) for j in range(a.shape[1])],
                         a.shape[1], out.shape[1], lo1, hi1, int1)
                for i in range(a.shape[0])
            ]
            prow = [pcell] * out.shape[1]
            cells = pad_axis(grid, a.shape[0], out.shape[0], lo, hi, interior)
            cells = [(r if isinstance(r, list) else prow) for r in cells]
            return [mk(out.shape, out.dtype, cells, exactf=a.exactf)]
        arows = a.rows0()
        if len(out.shape) <= 1 or all(c == (0, 0, 0) for c in cfg[1:]):
            rows = [keep(c) for c in arows]
            cells = [[c] for c in pad_axis(rows, a.shape[0], out.shape[0],
                                           lo, hi, interior)]
            return [mk(out.shape, out.dtype, cells, exactf=a.exactf)]
        # Axis-1 padding on an untracked-width row: hull with the pad value.
        rows = [_hull(keep(c), pcell) for c in arows]
        cells = [[c] for c in pad_axis(rows, a.shape[0], out.shape[0],
                                       lo, hi, interior)]
        return [mk(out.shape, out.dtype, cells, exactf=a.exactf)]
    return [mk(out.shape, out.dtype, [[_hull(a.joined(), pcell)]],
               exactf=a.exactf)]


@_rule("iota")
def _r_iota(interp, eqn, ins, where):
    out = _out_aval(eqn)
    dim = eqn.params["dimension"]
    n = out.shape[dim]
    # An iota varies only along `dim`: every other axis is uniform, in
    # particular axis 0 whenever dim != 0. A float iota is exact iff its
    # largest value fits the f32 exact-integer window.
    exf = _dkind(out.dtype)[0] == "float" and max(n - 1, 0) <= EXACT_F32
    uni = dim != 0
    if dim == 0 and n <= ROW_CAP:
        return [mk(out.shape, out.dtype, [[(i, i)] for i in range(n)],
                   dist0=n > 1, exactf=exf)]
    if dim == 1 and len(out.shape) > 1 and n <= ROW_CAP:
        return [mk(out.shape, out.dtype, [[(i, i) for i in range(n)]],
                   uni0=uni, exactf=exf)]
    return [mk(out.shape, out.dtype, [[(0, max(n - 1, 0))]],
               dist0=dim == 0 and n > 1, uni0=uni, exactf=exf)]


@_rule("reduce_sum", "reduce_max", "reduce_min", "reduce_and", "reduce_or")
def _r_reduce(interp, eqn, ins, where):
    (a,) = ins
    out = _out_aval(eqn)
    axes = set(eqn.params["axes"])
    name = eqn.primitive.name
    if name in ("reduce_max", "reduce_min"):
        a = interp.ctx.observe(a, where, f"{name} operand")

    # Multiplicity of untracked reduced elements per surviving cell.
    mult = 1
    for ax in axes:
        if ax == 0 and a.r0 > 1:
            continue
        if ax == 1 and a.r1 > 1:
            continue
        mult *= a.shape[ax]

    red0 = 0 in axes and a.r0 > 1
    red1 = 1 in axes and a.r1 > 1

    def combine(cells_seq):
        if name == "reduce_sum":
            lo = sum(c[0] for c in cells_seq)
            hi = sum(c[1] for c in cells_seq)
        elif name == "reduce_max":
            lo = max(c[0] for c in cells_seq)
            hi = max(c[1] for c in cells_seq)
        elif name == "reduce_min":
            lo = min(c[0] for c in cells_seq)
            hi = min(c[1] for c in cells_seq)
        elif name == "reduce_and":
            lo = min(c[0] for c in cells_seq)
            hi = min(c[1] for c in cells_seq)
        else:  # reduce_or
            lo = max(c[0] for c in cells_seq)
            hi = max(c[1] for c in cells_seq)
        return (lo, hi)

    def apply_mult(c):
        if mult == 1 or name != "reduce_sum":
            return c
        return (c[0] * mult, c[1] * mult)

    if (a.nz0 and name == "reduce_sum" and 0 in axes
            and (1 not in axes or a.r1 == 1)):
        # Masked-select: at most one element nonzero along axis 0, so the
        # sum is one of the rows (or 0) — join, don't sum. This is what
        # keeps one-hot table selects at per-limb precision. Applies even
        # when the row grid is collapsed (r0 == 1: `mk` folds uniform
        # rows, e.g. a W2-bounded table read through a Pallas Ref) — the
        # single tracked cell covers every row, so the join is that cell
        # extended with 0; only the OTHER reduced axes still multiply.
        mult_no0 = 1
        for ax in axes:
            if ax == 0:
                continue
            mult_no0 *= a.shape[ax]
        red0_cells = [
            (min(0, min(a.cells[i][j][0] for i in range(a.r0))),
             max(0, max(a.cells[i][j][1] for i in range(a.r0))))
            for j in range(a.r1)
        ]
        new_cells = [[(c[0] * mult_no0, c[1] * mult_no0)]
                     for c in red0_cells]
        res = mk(out.shape, out.dtype, new_cells, exactf=a.exactf)
        if _dkind(out.dtype)[0] == "float":
            # At most one nonzero along axis 0, so the accumulated
            # |partial sum| over the remaining mult_no0 untracked terms
            # is exactly the derived cell bound — the hull IS the sound
            # sum bound here.
            res.exactf = _check_float_exact(interp, where, ins,
                                            res.joined())
        return [res]

    cells = a.cells
    if red0:
        cells = [[combine([cells[i][j] for i in range(len(cells))])
                  for j in range(len(cells[0]))]]
    if red1:
        cells = [[combine(row)] for row in cells]
    # remap: surviving tracked axes shift into result axes 0/1
    if red0 and not red1:
        new_cells = [[apply_mult(c)] for c in cells[0]]  # old axis1 -> axis0
    elif red1 and not red0:
        new_cells = [[apply_mult(row[0])] for row in cells]
    elif red0 and red1:
        new_cells = [[apply_mult(cells[0][0])]]
    else:
        new_cells = [[apply_mult(c) for c in row] for row in cells]
    res = mk(out.shape, out.dtype, new_cells, exactf=False)
    if _dkind(out.dtype)[0] == "float":
        if name == "reduce_sum":
            # SOUND rule: every partial sum of the reduction, under ANY
            # association order, is bounded by the ACCUMULATED sum of
            # per-element magnitude bounds — the result hull is not
            # enough (signs may cancel in the true sum while a partial
            # sum leaves the 2^24 window and rounds).
            def cabs(c):
                return max(abs(c[0]), abs(c[1]))

            if red0 and red1:
                accs = [mult * sum(cabs(a.cells[i][j])
                                   for i in range(a.r0)
                                   for j in range(a.r1))]
            elif red0:
                accs = [mult * sum(cabs(a.cells[i][j])
                                   for i in range(a.r0))
                        for j in range(a.r1)]
            elif red1:
                accs = [mult * sum(cabs(c) for c in row)
                        for row in a.cells]
            else:
                accs = [mult * cabs(c) for row in a.cells for c in row]
            acc_max = max(accs) if accs else 0
            k_terms = 1
            for ax in axes:
                k_terms *= a.shape[ax]
            interp.ctx.eqn_facts["sum_abs_bound"] = _sat(acc_max)
            interp.ctx.eqn_facts["k_terms"] = k_terms
            res.exactf = _check_float_exact(interp, where, ins,
                                            (-acc_max, acc_max))
        elif name in ("reduce_max", "reduce_min"):
            # Selection: the result is one of the operand elements.
            res.exactf = a.exactf
    return [res]


@_rule("gather")
def _r_gather(interp, eqn, ins, where):
    a, idx = ins
    out = _out_aval(eqn)
    idx = interp.ctx.observe(idx, where, "gather indices")
    return [mk(out.shape, out.dtype, [[a.joined()]], exactf=a.exactf)]


@_rule("dynamic_slice")
def _r_dynamic_slice(interp, eqn, ins, where):
    a = ins[0]
    out = _out_aval(eqn)
    for s in ins[1:]:
        interp.ctx.observe(s, where, "dynamic_slice start")
    # Unknown offset: join along sliced tracked axes; a tracked axis whose
    # full extent survives keeps its rows.
    keep0 = a.shape and out.shape and a.shape[0] == out.shape[0]
    keep1 = (len(a.shape) > 1 and len(out.shape) > 1
             and a.shape[1] == out.shape[1])
    res = take_axes(a, out.shape, 0 if keep0 else None, 1 if keep1 else None)
    res.exactf = a.exactf
    return [res]


@_rule("dynamic_update_slice")
def _r_dus(interp, eqn, ins, where):
    a, upd = ins[0], ins[1]
    for s in ins[2:]:
        interp.ctx.observe(s, where, "dynamic_update_slice start")
    out = _out_aval(eqn)
    u = upd.joined()
    cells = [[_hull(c, u) for c in row] for row in a.cells]
    return [mk(out.shape, out.dtype, cells, exactf=a.exactf and upd.exactf)]


@_rule("scatter")
def _r_scatter(interp, eqn, ins, where):
    a, _idx, upd = ins[0], ins[1], ins[2]
    out = _out_aval(eqn)
    u = upd.joined()
    cells = [[_hull(c, u) for c in row] for row in a.cells]
    return [mk(out.shape, out.dtype, cells, exactf=a.exactf and upd.exactf)]


@_rule("rev")
def _r_rev(interp, eqn, ins, where):
    (a,) = ins
    out = _out_aval(eqn)
    dims = set(eqn.params["dimensions"])
    cells = a.cells
    if 0 in dims and a.r0 > 1:
        cells = cells[::-1]
    if 1 in dims and a.r1 > 1:
        cells = [row[::-1] for row in cells]
    return [mk(out.shape, out.dtype, cells, uni0=a.uni0, exactf=a.exactf)]


@_rule("dot_general")
def _r_dot(interp, eqn, ins, where):
    a, b = ins
    out = _out_aval(eqn)
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    kind, _ = _dkind(out.dtype)
    K = 1
    for d in lc:
        K *= a.shape[d]
    # One-hot contraction: if either operand is nz0 along its (single)
    # contracted axis 0, at most one term of the sum is nonzero — the
    # result is one product, not K of them. This is what makes the f32
    # MXU table select (one-hot (255,B) against a (255,20) window table)
    # provably exact instead of 255x over-approximated.
    if (a.nz0 and tuple(lc) == (0,)) or (b.nz0 and tuple(rc) == (0,)):
        K = 1
    ah = a.joined()
    bh = b.joined()
    ps = (ah[0] * bh[0], ah[0] * bh[1], ah[1] * bh[0], ah[1] * bh[1])
    plo, phi = min(ps), max(ps)
    # Partial sums are bounded by K * max|product| regardless of order:
    # this is the ACCUMULATED sum bound Sum|products|, not the
    # per-element bound — the quantity that must stay <= 2^24 for the
    # f32 contraction to be bit-exact at Precision.HIGHEST.
    bound = K * max(abs(plo), abs(phi))
    exactf = False
    if kind == "float":
        interp.ctx.eqn_facts["sum_abs_bound"] = _sat(bound)
        interp.ctx.eqn_facts["k_terms"] = K
        ok = _check_float_exact(interp, where, ins, (-bound, bound))
        prec = eqn.params.get("precision")
        prec_ok = False
        if prec is not None:
            try:
                from jax import lax as _lax
                ps_ = prec if isinstance(prec, (tuple, list)) else (prec,)
                prec_ok = all(p == _lax.Precision.HIGHEST for p in ps_)
            except Exception:
                prec_ok = False
        if not prec_ok:
            interp.ctx.violate(
                "float", where,
                "float dot_general without Precision.HIGHEST: the TPU MXU "
                "lowers default-precision f32 dots through bfloat16 passes "
                "that truncate 13-bit limbs",
            )
            ok = False
        exactf = ok
    # Result axis 0 <- first lhs batch dim, else first free lhs dim.
    free_l = [d for d in range(len(a.shape)) if d not in lc and d not in lb]
    res_ax0_src = (lb[0] if lb else (free_l[0] if free_l else None))
    s0 = res_ax0_src if res_ax0_src in (0, 1) else None
    base = take_axes(a, out.shape, s0, None)
    cells = [
        [(K * min(c[0] * bh[0], c[0] * bh[1], c[1] * bh[0], c[1] * bh[1]),
          K * max(c[0] * bh[0], c[0] * bh[1], c[1] * bh[0], c[1] * bh[1]))
         for c in row]
        for row in base.cells
    ]
    return [mk(out.shape, out.dtype, cells, exactf=exactf)]



# ---------------------------------------------------------------------------
# Control flow.

def _scan_elem(x: AbstractArray) -> AbstractArray:
    """Abstract one scanned-over element of an xs input (strip the leading
    scan axis: element axis 0 <- xs axis 1, everything else joined)."""
    elem_shape = x.shape[1:]
    return take_axes(x, elem_shape, 1 if len(x.shape) > 1 else None, None,
                     exactf=x.exactf)


def _stack_ys(y: AbstractArray, length: int) -> AbstractArray:
    """Abstract the stacked ys output (new leading scan axis; body-output
    axis 0 moves to axis 1). The body value is a fixpoint over-approximation
    of every iteration, so broadcasting it along the scan axis is sound."""
    out_shape = (length,) + y.shape
    res = take_axes(y, out_shape, None, 0, exactf=y.exactf)
    res.uni0 = True
    return res


def _fixpoint(interp, closed, n_consts, consts_and_carry_init, extra_args,
              where, narrow=None, min_trips=0):
    """Run `closed`'s body to a carry fixpoint with staged widening.

    consts_and_carry_init: (const_avals, carry_avals); extra_args are the
    per-iteration xs elements (already element-shaped, loop-invariant
    abstractions). Returns the final (carry_out, other_outs) of a last
    *unmuted* pass evaluated at the fixpoint carry. With min_trips >= 1
    (statically known to iterate), the carry-out is the body output alone
    — the loop exit value is the LAST iteration's output, so the init
    need not be joined in (it matters for weak-rep inits the body
    immediately settles, e.g. the 2*W2 sum feeding fe_batch_inv's
    Fermat scan).
    """
    const_in, carry0 = consts_and_carry_init
    carry = list(carry0)
    interp.ctx.in_loop += 1
    interp.ctx.mute += 1
    try:
        for it in range(_MAX_FIX_ITERS):
            args = list(const_in) + list(carry) + list(extra_args)
            outs = interp.eval_closed(closed, args, where)
            new_carry = outs[: len(carry)]
            nxt = []
            stable = True
            for old, new in zip(carry, new_carry, strict=True):
                r0 = max(old.r0, new.r0)
                r1 = max(old.r1, new.r1)
                cells = []
                for i in range(r0):
                    rowc = []
                    for j in range(r1):
                        oc, nc = old.cell(i, j), new.cell(i, j)
                        h = _hull(oc, nc)
                        if h != oc and it >= 3:
                            h = _widen_cell(oc, h)
                        rowc.append(h)
                    cells.append(rowc)
                merged = AbstractArray(
                    old.shape, old.dtype, _collapse_if_uniform(cells),
                    nz0=old.nz0 and new.nz0, uni0=old.uni0 and new.uni0,
                    exactf=old.exactf and new.exactf,
                )
                if narrow is not None:
                    merged = narrow(len(nxt), merged)
                # Stability must be judged on the *narrowed* carry: a pinned
                # counter whose raw hull grows each pass (0,31)->(0,32) but
                # clamps back would otherwise never read as stable.
                if (merged.nz0, merged.uni0, merged.exactf) != (
                        old.nz0, old.uni0, old.exactf):
                    stable = False
                else:
                    for i in range(r0):
                        for j in range(r1):
                            if merged.cell(i, j) != old.cell(i, j):
                                stable = False
                                break
                        if not stable:
                            break
                nxt.append(merged)
            carry = nxt
            if stable:
                break
        else:
            carry = [top(c.shape, c.dtype) for c in carry]
        # Decreasing (narrowing) passes: staged widening can overshoot the
        # least fixpoint (e.g. jump a limb bound from 8191 past W2=15631 to
        # 16383, where mul chains stop being int32-safe). Re-evaluate the
        # body at the widened carry and shrink each cell to
        # hull(init, body_out) ∩ current. The final unmuted pass below
        # re-checks the body at the narrowed carry, so an unsound shrink
        # cannot escape silently.
        for _ in range(4):
            args = list(const_in) + list(carry) + list(extra_args)
            outs = interp.eval_closed(closed, args, where)
            shrunk = False
            nxt = []
            for idx, (init0, old, new) in enumerate(
                    zip(carry0, carry, outs[: len(carry)], strict=True)):
                r0 = max(old.r0, new.r0, init0.r0)
                r1 = max(old.r1, new.r1, init0.r1)
                cells = []
                for i in range(r0):
                    rowc = []
                    for j in range(r1):
                        oc = old.cell(i, j)
                        ic, nc = init0.cell(i, j), new.cell(i, j)
                        cand = (min(ic[0], nc[0]), max(ic[1], nc[1]))
                        h = (max(oc[0], cand[0]), min(oc[1], cand[1]))
                        if h[0] > h[1]:
                            h = oc
                        if h != oc:
                            shrunk = True
                        rowc.append(h)
                    cells.append(rowc)
                merged = AbstractArray(
                    old.shape, old.dtype, _collapse_if_uniform(cells),
                    nz0=old.nz0, uni0=old.uni0, exactf=old.exactf,
                )
                if narrow is not None:
                    merged = narrow(idx, merged)
                nxt.append(merged)
            carry = nxt
            if not shrunk:
                break
    finally:
        interp.ctx.mute -= 1
    try:
        args = list(const_in) + list(carry) + list(extra_args)
        outs = interp.eval_closed(closed, args, where)
    finally:
        interp.ctx.in_loop -= 1
    final_carry = []
    for old, new in zip(carry, outs[: len(carry)], strict=True):
        if min_trips >= 1:
            final_carry.append(new)
        else:
            final_carry.append(join_values(old, new)
                               if old.shape == new.shape else new)
    return final_carry, outs[len(carry):]


def _counter_carries(jaxpr, n_consts: int, n_carry: int):
    """Find carries that are pure counters: body output k is exactly
    `add(carry_k, literal)`. Their range over the whole loop is known
    statically from the trip count — pinning them keeps indexing and
    trip-count arithmetic (`w = N-1-i`, `db1[w]`) finitely bounded
    instead of widening to infinity."""
    out = {}
    Lit = jax_core.Literal
    for k in range(n_carry):
        ov = jaxpr.outvars[k]
        iv = jaxpr.invars[n_consts + k]
        for e in jaxpr.eqns:
            if e.outvars and e.outvars[0] is ov:
                if e.primitive.name == "add":
                    a, b = e.invars
                    if a is iv and isinstance(b, Lit):
                        out[k] = int(b.val)
                    elif b is iv and isinstance(a, Lit):
                        out[k] = int(a.val)
                break
    return out


@_rule("scan")
def _r_scan(interp, eqn, ins, where):
    p = eqn.params
    n_consts, n_carry = p["num_consts"], p["num_carry"]
    length = p["length"]
    closed = p["jaxpr"]
    consts = ins[:n_consts]
    carry0 = ins[n_consts:n_consts + n_carry]
    xs = ins[n_consts + n_carry:]
    elems = [_scan_elem(x) for x in xs]

    counters = _counter_carries(closed.jaxpr, n_consts, n_carry)
    pins = {}
    for k, step in counters.items():
        lo0, hi0 = carry0[k].joined()
        if abs(lo0) < INF and abs(hi0) < INF and length:
            span = step * (length - 1)
            pins[k] = (lo0 + min(span, 0), hi0 + max(span, 0))

    def narrow(k, av):
        pin = pins.get(k)
        if pin is None:
            return av
        cells = [[(max(lo, pin[0]), min(hi, pin[1])) for lo, hi in row]
                 for row in av.cells]
        return AbstractArray(av.shape, av.dtype, cells, nz0=av.nz0,
                             uni0=av.uni0, exactf=av.exactf)

    carry_out, y_body = _fixpoint(
        interp, closed, n_consts, (consts, carry0), elems, where,
        narrow=narrow, min_trips=1 if (length or 0) >= 1 else 0)
    ys = [_stack_ys(y, length) for y in y_body]
    return list(carry_out) + ys


def _fori_shaped(cond_closed):
    """Detect the fori_loop cond pattern: a single `lt` of one carry
    element against a literal/const. Returns (carry_index, bound) or
    None. Anything else is a data-dependent trip count."""
    jaxpr = cond_closed.jaxpr
    if len(jaxpr.eqns) != 1:
        return None
    eqn = jaxpr.eqns[0]
    if eqn.primitive.name != "lt" or len(jaxpr.outvars) != 1:
        return None
    if eqn.outvars[0] is not jaxpr.outvars[0]:
        return None
    lhs, rhs = eqn.invars
    Lit = jax_core.Literal
    if isinstance(lhs, Lit) or not isinstance(rhs, Lit):
        return None
    try:
        idx = list(jaxpr.invars).index(lhs)
    except ValueError:
        return None
    return idx, int(rhs.val)


@_rule("while")
def _r_while(interp, eqn, ins, where):
    p = eqn.params
    cn, bn = p["cond_nconsts"], p["body_nconsts"]
    cond_closed, body_closed = p["cond_jaxpr"], p["body_jaxpr"]
    cond_consts = ins[:cn]
    body_consts = ins[cn:cn + bn]
    carry0 = ins[cn + bn:]

    fori = _fori_shaped(cond_closed)
    narrow = None
    if fori is None:
        interp.ctx.violate(
            "loop", where,
            "data-dependent while_loop trip count: cond jaxpr is not the "
            "fori_loop pattern (single `lt counter const`); on TPU this "
            "re-dispatches per iteration and its timing/trip count depends "
            "on lane values — consensus kernels must use fori_loop or scan",
        )
    else:
        idx, bound = fori

        def narrow(i, av, _idx=idx - cn, _bound=bound):
            if i != _idx:
                return av
            cells = [[(min(lo, _bound), min(hi, _bound))
                      for lo, hi in row] for row in av.cells]
            return AbstractArray(av.shape, av.dtype, cells, nz0=av.nz0,
                                 uni0=av.uni0, exactf=av.exactf)

    carry_out, _ = _fixpoint(
        interp, body_closed, bn, (body_consts, carry0), [], where,
        narrow=narrow)
    # Evaluate the cond once (observation discipline on its operands).
    interp.ctx.mute += 1
    try:
        interp.eval_closed(cond_closed, list(cond_consts) + list(carry_out),
                           where + "/cond")
    finally:
        interp.ctx.mute -= 1
    return list(carry_out)


@_rule("cond")
def _r_cond(interp, eqn, ins, where):
    branches = eqn.params["branches"]
    pred, args = ins[0], ins[1:]
    interp.ctx.observe(pred, where, "cond predicate")
    outs = None
    plo, phi = pred.joined()
    idxs = range(len(branches))
    if plo == phi and 0 <= plo < len(branches):
        idxs = [plo]
    # With an unresolved predicate every branch is evaluated abstractly
    # but only one runs concretely — ref writes inside must stay weak.
    multi = len(list(idxs)) > 1
    if multi:
        interp.ctx.in_loop += 1
    try:
        for bi in idxs:
            bouts = interp.eval_closed(branches[bi], list(args),
                                       f"{where}/branch{bi}")
            if outs is None:
                outs = list(bouts)
            else:
                outs = [join_values(a, b) if a.shape == b.shape else b
                        for a, b in zip(outs, bouts, strict=True)]
    finally:
        if multi:
            interp.ctx.in_loop -= 1
    return outs


@_rule("pjit", "closed_call", "core_call", "remat", "checkpoint")
def _r_call(interp, eqn, ins, where):
    closed = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
    name = eqn.params.get("name", eqn.primitive.name)
    return interp.eval_closed(closed, list(ins), f"{where}/{name}")


@_rule("custom_jvp_call", "custom_vjp_call", "custom_jvp_call_jaxpr")
def _r_custom(interp, eqn, ins, where):
    closed = eqn.params.get("call_jaxpr") or eqn.params.get("fun_jaxpr")
    return interp.eval_closed(closed, list(ins), where)


ALLOWED_PRIMITIVES = frozenset(RULES)

# Primitives whose transfer rules implement the exact-float policy: they
# either preserve the exactness certificate soundly (structural moves,
# selections, the checked add/mul/dot/reduce arithmetic) or decide the
# float question themselves (div/rem always violate). Any float32 value
# produced by a primitive OUTSIDE this set is demoted to inexact by the
# interpreter post-pass with a sourced diagnostic — an unvetted op can
# round, so the certificate cannot survive it. A deliberately mutable
# set (unlike ALLOWED_PRIMITIVES): analysis/pallas_check.py extends it
# with the Ref primitives whose rules thread exactf through VMEM.
FLOAT_VETTED = {
    # checked arithmetic (each rule proves bound <= 2^24 or violates)
    "add", "sub", "mul", "neg", "dot_general",
    "reduce_sum", "reduce_max", "reduce_min",
    # selections / comparisons (result is one of the operand values)
    "min", "max", "clamp", "abs", "sign", "select_n",
    # rules that always violate on float themselves
    "div", "rem",
    # converts (rule checks the 2^24 window / certificate)
    "convert_element_type",
    # structural moves: values are copied, never recomputed
    "broadcast_in_dim", "reshape", "squeeze", "transpose", "slice",
    "concatenate", "pad", "rev", "gather", "dynamic_slice",
    "dynamic_update_slice", "scatter", "iota",
    "device_put", "copy", "stop_gradient",
    # control flow: certificates propagate through the recursive walk
    "scan", "while", "cond", "pjit", "closed_call", "core_call",
    "remat", "checkpoint", "custom_jvp_call", "custom_vjp_call",
    "custom_jvp_call_jaxpr",
}


# ---------------------------------------------------------------------------
# The interpreter.

_BANNED_64 = ("int64", "uint64", "float64")


class _Interp:
    def __init__(self, ctx: _Ctx):
        self.ctx = ctx

    def _read(self, env, v):
        if isinstance(v, jax_core.Literal):
            return from_concrete(np.asarray(v.val, dtype=v.aval.dtype))
        return env[v]

    def eval_closed(self, closed, args: List[AbstractArray],
                    where: str) -> List[AbstractArray]:
        jaxpr = closed.jaxpr if hasattr(closed, "jaxpr") else closed
        consts = closed.consts if hasattr(closed, "consts") else []
        env: Dict = {}
        for var, c in zip(jaxpr.constvars, consts, strict=True):
            env[var] = from_concrete(np.asarray(c))
        if len(args) != len(jaxpr.invars):
            raise ValueError(
                f"{where}: arity mismatch ({len(args)} args for "
                f"{len(jaxpr.invars)} invars)")
        for var, a in zip(jaxpr.invars, args, strict=True):
            env[var] = a
        for k, eqn in enumerate(jaxpr.eqns):
            name = eqn.primitive.name
            ew = f"{where}#{k}:{name}"
            if not self.ctx.mute:
                self.ctx.report.n_eqns += 1
                self.ctx.report.prim_counts[name] = (
                    self.ctx.report.prim_counts.get(name, 0) + 1)
            for v in list(eqn.invars) + list(eqn.outvars):
                aval = getattr(v, "aval", None)
                if aval is not None and str(aval.dtype) in _BANNED_64:
                    self.ctx.violate(
                        "dtype64", ew,
                        f"64-bit dtype {aval.dtype} in consensus kernel "
                        "(TPU lowers 64-bit integer ops as pairs; banned)",
                    )
            ins = [self._read(env, v) for v in eqn.invars]
            self.ctx.eqn_facts = {}
            rule = RULES.get(name)
            if rule is None:
                self.ctx.violate(
                    "allowlist", ew,
                    f"primitive `{name}` is not on the integer-deterministic "
                    "allowlist (no vetted transfer rule); add a rule to "
                    "analysis/interval.py RULES after review",
                )
                outs = [top(v.aval.shape, v.aval.dtype)
                        for v in eqn.outvars]
            else:
                try:
                    outs = rule(self, eqn, ins, ew)
                except Exception as e:  # analyzer bug, never silently pass
                    self.ctx.violate(
                        "internal", ew,
                        f"transfer rule for `{name}` raised "
                        f"{type(e).__name__}: {e}",
                    )
                    outs = [top(v.aval.shape, v.aval.dtype)
                            for v in eqn.outvars]
            _poly_transfer(eqn, ins, outs)
            _cong_transfer(eqn, ins, outs)
            self._float_post(name, ew, ins, outs)
            for var, o in zip(eqn.outvars, outs, strict=True):
                if type(var).__name__ != "DropVar":
                    env[var] = o
        return [self._read(env, v) for v in jaxpr.outvars]

    def _float_post(self, name, ew, ins, outs):
        """Exact-float post-pass, run on EVERY equation: demote float
        outputs of primitives without a vetted exact-float transfer
        (they may round), attach demotion provenance, and append the
        per-value entry to the exactness trace."""
        ctx = self.ctx
        facts = ctx.eqn_facts
        for oi, o in enumerate(outs):
            if _dkind(o.dtype)[0] != "float":
                continue
            if name not in FLOAT_VETTED:
                o.exactf = False
                o.fwhy = (f"certificate demoted at {ew}: `{name}` has no "
                          "vetted exact-float transfer")
                ctx.violate(
                    "float", ew,
                    f"float32 value produced by `{name}`, which is not on "
                    "the exact-float vetted list: the value may have "
                    "rounded, so the exactness certificate is demoted "
                    "to inexact here",
                )
            if o.exactf:
                o.fwhy = None
            elif o.fwhy is None:
                # Inherit the demotion source from the first inexact
                # float operand; otherwise this equation is the source.
                o.fwhy = next(
                    (i.fwhy for i in ins
                     if _dkind(i.dtype)[0] == "float" and i.fwhy),
                    f"certificate demoted at {ew}")
            if not ctx.mute:
                lo, hi = o.joined()
                m = max(abs(lo), abs(hi))
                entry = {
                    "where": ew, "prim": name, "out": oi,
                    "dtype": o.dtype.name, "exact": bool(o.exactf),
                    "bound": "unbounded" if m >= INF else int(m),
                }
                for k, v in facts.items():
                    entry[k] = ("unbounded"
                                if isinstance(v, int) and abs(v) >= INF
                                else v)
                if not o.exactf:
                    entry["reason"] = o.fwhy
                ctx.trace_float(entry)


# ---------------------------------------------------------------------------
# Public API.

def _abstract_inputs(closed, in_bounds, in_cong=None):
    """Build input AbstractArrays for a closed jaxpr. in_bounds maps the
    flat input position to either None (full lane range), a (lo, hi)
    tuple, or a per-axis0-row list of (lo, hi). in_cong maps the flat
    input position to a congruence fact: an (m, r) pair (uniform) or a
    per-axis0-row list of (m, r) / None."""
    avs = []
    for i, var in enumerate(closed.jaxpr.invars):
        aval = var.aval
        spec = in_bounds.get(i) if in_bounds else None
        if spec is None:
            av = full_range(aval.shape, aval.dtype)
        elif isinstance(spec, tuple):
            av = mk(aval.shape, aval.dtype, [[spec]])
        else:
            cells = [[(int(lo), int(hi))] for lo, hi in spec]
            av = mk(aval.shape, aval.dtype, cells)
        cspec = in_cong.get(i) if in_cong else None
        if cspec is not None:
            rows = [cspec] if isinstance(cspec, tuple) else list(cspec)
            av.cong = [None if f is None else _cong_norm(f[0], f[1])
                       for f in rows]
        avs.append(av)
    return avs


def analyze_closed(closed, name: str, in_bounds=None,
                   out_within=None, in_cong=None) -> Report:
    """Run both passes (interval prover + determinism/allowlist gate) over
    a ClosedJaxpr. Returns a Report; report.ok is the gate."""
    report = Report(name=name)
    ctx = _Ctx(report)
    interp = _Interp(ctx)
    args = _abstract_inputs(closed, in_bounds, in_cong=in_cong)
    try:
        outs = interp.eval_closed(closed, args, name)
    except Exception as e:
        ctx.violate("internal", name,
                    f"analysis aborted: {type(e).__name__}: {e}")
        return report
    for i, o in enumerate(outs):
        o2 = ctx.observe(o, f"{name}/out{i}", "kernel output")
        if _dkind(o.dtype)[0] == "float" and not o.exactf:
            why = f" [{o.fwhy}]" if o.fwhy else ""
            ctx.violate(
                "float", f"{name}/out{i}",
                "unproven f32 value reaches a consensus-visible "
                f"output{why}",
            )
        report.out_bounds.append(o.rows0() if o.shape else [o.joined()])
        n_rows = o.shape[0] if o.shape else 1
        report.out_cong.append(
            _cong_expand(o.cong, n_rows) if o.cong is not None
            and n_rows <= ROW_CAP else [None] * min(n_rows, ROW_CAP))
        if out_within is not None and i < len(out_within) \
                and out_within[i] is not None:
            hand = out_within[i]
            derived = o2.rows0() if o2.shape else [o2.joined()]
            if len(hand) == len(derived):
                for r, ((lo, hi), hb) in enumerate(zip(derived, hand, strict=True)):
                    if isinstance(hb, tuple):
                        hlo, hhi = hb
                    else:
                        hlo, hhi = 0, int(hb)
                    if lo < hlo or hi > hhi:
                        ctx.violate(
                            "overflow", f"{name}/out{i}[{r}]",
                            f"hand-tracked bound [{hlo}, {hhi}] understates "
                            f"derived interval [{lo}, {hi}]: the Bounds "
                            "bookkeeping in ops/limbs.py is wrong for this "
                            "op — fix the hand bound, not the analyzer",
                        )
            else:
                ctx.violate(
                    "internal", f"{name}/out{i}",
                    f"hand bound has {len(hand)} rows, derived has "
                    f"{len(derived)}")
    return report


def analyze(fn, args, name: str, in_bounds=None, out_within=None,
            static_argnums=(), in_cong=None) -> Report:
    """Trace `fn` at example `args` (concrete or ShapeDtypeStruct) and
    analyze the resulting jaxpr."""
    closed = jax.make_jaxpr(fn, static_argnums=static_argnums)(*args)
    return analyze_closed(closed, name, in_bounds=in_bounds,
                          out_within=out_within, in_cong=in_cong)


# ---------------------------------------------------------------------------
# Sum-of-products refinement.
#
# Pure interval arithmetic cannot prove the Karatsuba combine: in
# z1 = S - z0 - z2 the three operands are correlated (each is a sum of
# products of the SAME input limbs), and the interval of the difference
# explodes even though the true value is the small cross convolution.
# This layer tracks, alongside the interval cells, an optional exact
# decomposition of each integer array as
#
#     value[row, ...] = sum_m coeff_m(row) * monomial_m
#
# where a monomial is a product of at most two interval "atoms" (an atom
# is one limb-row of some earlier array, minted lazily the first time a
# value is sliced into or multiplied). add/sub merge coefficient dicts,
# so S - z0 - z2 cancels the square terms ALGEBRAICALLY and the derived
# bound of z1 is the true cross-term bound — the same argument
# `_kara_combine`'s hand bookkeeping makes, re-derived independently.
# Any op without an exact transfer (shifts, bitwise, compares, reduces)
# simply drops the decomposition; the interval cells always remain.

_ATOM_UID = [0]


class _Atom:
    __slots__ = ("uid", "lo", "hi")

    def __init__(self, lo: int, hi: int):
        _ATOM_UID[0] += 1
        self.uid = _ATOM_UID[0]
        self.lo = lo
        self.hi = hi


_POLY_MAX_TERMS = 6000


def _ensure_poly(av: AbstractArray):
    """Mint a degree-1 decomposition for an integer array that has none:
    one atom per axis-0 row (its interval = the row hull). Sound because
    every cell of row r genuinely lies in that row's interval, and all
    later elementwise ops act row-aligned."""
    if av.poly is not None:
        return av.poly
    if _dkind(av.dtype)[0] != "int":
        return None
    lo, hi = av.joined()
    if lo == hi:
        av.poly = {(): {None: lo}} if lo else {}
        return av.poly
    if av.shape and 1 <= av.shape[0] <= ROW_CAP:
        rows = av.rows0()
        poly: Dict = {}
        for r, (rlo, rhi) in enumerate(rows):
            if rlo == rhi:
                if rlo:
                    poly.setdefault((), {})[r] = rlo
            else:
                poly[(_Atom(rlo, rhi),)] = {r: 1}
    else:
        poly = {(_Atom(lo, hi),): {None: 1}}
    av.poly = poly
    return poly


def _poly_size(p) -> int:
    return sum(len(rows) for rows in p.values())


def _mono_bound(mono) -> Tuple[int, int]:
    lo, hi = 1, 1
    for a in mono:
        cands = (lo * a.lo, lo * a.hi, hi * a.lo, hi * a.hi)
        lo, hi = min(cands), max(cands)
    return lo, hi


def _poly_row_bound(p, r) -> Tuple[int, int]:
    lo = hi = 0
    for mono, rows in p.items():
        c = rows.get(None, 0) + (rows.get(r, 0) if r is not None else 0)
        if not c:
            continue
        mlo, mhi = _mono_bound(mono)
        if c > 0:
            lo += c * mlo
            hi += c * mhi
        else:
            lo += c * mhi
            hi += c * mlo
    return lo, hi


def _poly_addsub(pa, pb, sign: int):
    res = {m: dict(rows) for m, rows in pa.items()}
    for mono, rows in pb.items():
        dst = res.setdefault(mono, {})
        for r, c in rows.items():
            nc = dst.get(r, 0) + sign * c
            if nc:
                dst[r] = nc
            elif r in dst:
                del dst[r]
        if not dst:
            del res[mono]
    if _poly_size(res) > _POLY_MAX_TERMS:
        return None
    return res


def _poly_mul(pa, pb):
    res: Dict = {}
    for ma, ra in pa.items():
        for mb, rb in pb.items():
            if len(ma) + len(mb) > 2:
                return None  # degree > 2: out of the domain, drop exactly
            mono = tuple(sorted(ma + mb, key=lambda a: a.uid))
            dst = res.setdefault(mono, {})
            for r1, c1 in ra.items():
                for r2, c2 in rb.items():
                    if r1 is None:
                        r = r2
                    elif r2 is None or r1 == r2:
                        r = r1
                    else:
                        return None  # row-crossed product: not elementwise
                    nc = dst.get(r, 0) + c1 * c2
                    if nc:
                        dst[r] = nc
                    elif r in dst:
                        del dst[r]
            if not dst:
                del res[mono]
    if _poly_size(res) > _POLY_MAX_TERMS:
        return None
    return res


def _materialize_rows(p, n: int):
    """Expand row=None ('every row') entries to explicit rows 0..n-1 —
    required before pads/concats where 'every row' changes meaning."""
    res: Dict = {}
    for mono, rows in p.items():
        dst: Dict = {}
        for r, c in rows.items():
            if r is None:
                for i in range(n):
                    dst[i] = dst.get(i, 0) + c
            else:
                dst[r] = dst.get(r, 0) + c
        dst = {r: c for r, c in dst.items() if c}
        if dst:
            res[mono] = dst
    if _poly_size(res) > _POLY_MAX_TERMS:
        return None
    return res


def _refine_with_poly(av: AbstractArray):
    """Intersect av's interval cells with its poly-derived row bounds
    (both are sound, so the intersection is)."""
    p = av.poly
    if p is None:
        return
    n = av.shape[0] if av.shape else 1
    if av.shape and (n == 0 or n > ROW_CAP):
        return
    r1 = av.r1
    cells = []
    for i in range(n):
        plo, phi = _poly_row_bound(p, i if av.shape else None)
        row = []
        for j in range(r1):
            clo, chi = av.cell(i, j)
            lo, hi = max(clo, plo), min(chi, phi)
            if lo > hi:  # defensive: both sound => should not happen
                lo, hi = plo, phi
            row.append((_sat(lo), _sat(hi)))
        cells.append(row)
    av.cells = _collapse_if_uniform(cells)


def _rows_aligned(p, av, out):
    """Re-key an operand poly so its rows line up with the result of a
    (possibly broadcasting) elementwise op: a size-1 or absent leading
    axis becomes row=None ('every row'); otherwise the leading axes must
    match. Returns None when alignment can't be established."""
    if p is None:
        return None
    if not av.shape or av.shape[0] == 1:
        folded: Dict = {}
        for mono, rows in p.items():
            dst: Dict = {}
            for r, c in rows.items():
                if r in (None, 0):
                    dst[None] = dst.get(None, 0) + c
                else:
                    return None
            dst = {k: v for k, v in dst.items() if v}
            if dst:
                folded[mono] = dst
        return folded
    if out.shape and av.shape[0] == out.shape[0] \
            and len(av.shape) == len(out.shape):
        return p
    if all(r is None for rows in p.values() for r in rows):
        return p
    return None


def _complementary_support(x, y):
    """True when the two same-shaped arrays never overlap: every tracked
    cell is exactly (0, 0) on at least one side. This is the signature of
    an associative-scan interleave (even/odd positions padded with zeros
    and merged by one add)."""
    if x.shape != y.shape or not x.shape or x.shape[0] > ROW_CAP:
        return False
    ncols = min(x.shape[1], ROW_CAP) if len(x.shape) > 1 else 1
    for i in range(x.shape[0]):
        for j in range(ncols):
            if x.cell(i, j) != (0, 0) and y.cell(i, j) != (0, 0):
                return False
    return True


def _poly_transfer(eqn, ins, outs):
    """Attach exact decompositions to the outputs of structure-preserving
    integer ops; refine their interval cells. Pure precision layer — any
    unsupported case just leaves poly=None."""
    if len(outs) != 1:
        return
    out = outs[0]
    if _dkind(out.dtype)[0] != "int" or (out.shape and out.shape[0] > ROW_CAP
                                         and len(out.shape) != 1):
        return
    name = eqn.primitive.name
    p = None
    try:
        if name == "mul":
            pa = _rows_aligned(_ensure_poly(ins[0]), ins[0], out)
            pb = _rows_aligned(_ensure_poly(ins[1]), ins[1], out)
            if pa is not None and pb is not None:
                p = _poly_mul(pa, pb)
        elif name in ("add", "sub"):
            pa = _rows_aligned(_ensure_poly(ins[0]), ins[0], out)
            pb = _rows_aligned(_ensure_poly(ins[1]), ins[1], out)
            if pa is not None and pb is not None:
                p = _poly_addsub(pa, pb, 1 if name == "add" else -1)
        elif name == "neg":
            pa = _ensure_poly(ins[0])
            if pa is not None:
                p = _poly_addsub({}, pa, -1)
        elif name == "slice":
            starts = eqn.params["start_indices"]
            strides = eqn.params.get("strides") or (1,) * len(starts)
            pa = _ensure_poly(ins[0])
            if pa is not None and out.shape:
                s0, st0, n0 = starts[0], strides[0], out.shape[0]
                p = {}
                for mono, rows in pa.items():
                    dst = {}
                    for r, c in rows.items():
                        if r is None:
                            dst[None] = dst.get(None, 0) + c
                        elif (r - s0) % st0 == 0 and 0 <= (r - s0) // st0 < n0:
                            nr = (r - s0) // st0
                            dst[nr] = dst.get(nr, 0) + c
                    dst = {r: c for r, c in dst.items() if c}
                    if dst:
                        p[mono] = dst
        elif name == "squeeze":
            pa = ins[0].poly
            if pa is not None:
                dims = eqn.params["dimensions"]
                if 0 in dims:
                    p = {}
                    for mono, rows in pa.items():
                        c = rows.get(None, 0) + rows.get(0, 0)
                        if c:
                            p[mono] = {None: c}
                else:
                    p = pa
        elif name == "broadcast_in_dim":
            pa = ins[0].poly
            if pa is not None:
                bdims = eqn.params["broadcast_dimensions"]
                src = ins[0]
                if src.shape and src.shape[0] == 1:
                    pa = {m: {(None if r in (0, None) else r): c
                              for r, c in rows.items()}
                          for m, rows in pa.items()}
                if bdims and bdims[0] == 0 and src.shape \
                        and src.shape[0] == out.shape[0]:
                    p = pa
                elif all(r is None for rows in pa.values() for r in rows):
                    p = pa
        elif name == "pad":
            cfg = eqn.params["padding_config"]
            if (ins[1].joined() == (0, 0) and ins[0].shape
                    and all(c == (0, 0, 0) for c in cfg[1:])
                    and cfg[0][2] == 0 and ins[0].shape[0] <= ROW_CAP):
                pa = _ensure_poly(ins[0])
                if pa is not None:
                    pa = _materialize_rows(pa, ins[0].shape[0])
                    if pa is not None:
                        lo = cfg[0][0]
                        p = {}
                        for mono, rows in pa.items():
                            dst = {r + lo: c for r, c in rows.items()
                                   if 0 <= r + lo < out.shape[0]}
                            if dst:
                                p[mono] = dst
        elif name == "concatenate":
            if eqn.params["dimension"] == 0 and out.shape[0] <= ROW_CAP:
                p = {}
                off = 0
                for o in ins:
                    po = _ensure_poly(o)
                    po = (_materialize_rows(po, o.shape[0])
                          if po is not None else None)
                    if po is None:
                        p = None
                        break
                    for mono, rows in po.items():
                        dst = p.setdefault(mono, {})
                        for r, c in rows.items():
                            dst[r + off] = dst.get(r + off, 0) + c
                    off += o.shape[0]
    except Exception:
        p = None
    if p is not None:
        dominated = False
        if name == "add" and len(ins) == 2 and out.shape \
                and 1 <= out.shape[0] <= ROW_CAP \
                and _complementary_support(ins[0], ins[1]):
            # The associative-scan interleave: an add of two arrays padded
            # onto complementary positions, so every cell holds ONE operand
            # and the other side is exactly zero there. The per-cell grid
            # sees that (cell bound = the one live operand) but the
            # row-keyed poly cannot -- its row bound is the SUM of both
            # operands' row hulls, doubling every bound, and the loose poly
            # then poisons every downstream product. Drop the poly when it
            # is strictly wider than the interval cells on some row and
            # tighter nowhere; re-minted per-row atoms from the cells
            # dominate it for every use. The complementary-support guard is
            # load-bearing: an ordinary add (e.g. Karatsuba's a0 + a1, both
            # halves live in every cell) may also look row-dominated when
            # the operands have column structure, yet its poly carries the
            # atoms the later m - z0 - z1 cancellation needs.
            rows = out.rows0()
            for r in range(out.shape[0]):
                plo, phi = _poly_row_bound(p, r)
                clo, chi = rows[r]
                if plo > clo or phi < chi:
                    dominated = False
                    break
                if plo < clo or phi > chi:
                    dominated = True
        if not dominated:
            out.poly = p
            _refine_with_poly(out)


# ---------------------------------------------------------------------------
# Congruence refinement.
#
# Alongside each interval cell grid, an AbstractArray may carry per-row
# congruence facts x ≡ r (mod m) (m == 0: exactly r). The facts flow
# through the integer ops the scalar-recoding pipeline is built from —
# add/sub/neg, mul, shifts by exact amounts, masking, or-of-disjoint-
# support, reductions, and the structural ops — with gcd-based joins, so
# the analyzer can certify place-value structure (a weighted bit plane
# b_i * 2^i is ≡ 0 mod 2^i; a partial recombination sum of planes i >= t
# is ≡ 0 mod 2^t) that pure intervals cannot express. Any unsupported
# op drops the fact (always sound); widening constructs fresh
# AbstractArrays and so drops facts automatically. The exact-recombination
# theorems of analysis/scalar_check.py use this domain for the modular
# layer of the digit-recoding certificates.

def _cong_norm(m: int, r: int):
    """Normalize a fact: m >= 0; m == 1 carries no information (None);
    m == 0 means exactly r; otherwise reduce r mod m."""
    m = abs(int(m))
    r = int(r)
    if m == 1:
        return None
    if m == 0:
        return (0, r)
    return (m, r % m)


def _cong_join(fa, fb):
    """Weakest fact implied by both: gcd(m1, m2, r1 - r2)."""
    if fa is None or fb is None:
        return None
    (m1, r1), (m2, r2) = fa, fb
    return _cong_norm(math.gcd(math.gcd(m1, m2), abs(r1 - r2)), r1)


def _cong_expand(rows, n: int):
    """Expand a fact list to exactly n per-row entries (len-1 = uniform)."""
    if rows is None:
        return [None] * n
    if len(rows) == n:
        return list(rows)
    if len(rows) == 1:
        return [rows[0]] * n
    return [None] * n


def _cong_add(fa, fb, sign=1):
    if fa is None or fb is None:
        return None
    (m1, r1), (m2, r2) = fa, fb
    return _cong_norm(math.gcd(m1, m2), r1 + sign * r2)


def _cong_mul(fa, fb):
    """(r1 + a·m1)(r2 + b·m2) ≡ r1·r2 mod gcd(m1·m2, m1·r2, m2·r1).
    A factless operand is (1, 0) — any integer ≡ 0 (mod 1) — so a
    product with an exactly-known factor still yields x·c ≡ 0 (mod c)."""
    if fa is None:
        fa = (1, 0)
    if fb is None:
        fb = (1, 0)
    (m1, r1), (m2, r2) = fa, fb
    return _cong_norm(
        math.gcd(math.gcd(m1 * m2, abs(m1 * r2)), abs(m2 * r1)), r1 * r2)


def _cong_exact_rows(av: AbstractArray, n: int):
    """Per-row exactly-known values (from facts with m == 0), else None."""
    rows = _cong_expand(av.cong, n)
    return [r[1] if (r is not None and r[0] == 0) else None for r in rows]


def _cong_rows_for(av: AbstractArray, out: AbstractArray, n: int):
    """Operand facts aligned to the result's n axis-0 rows under
    elementwise broadcasting: a scalar / size-1-leading operand is
    uniform; a same-leading-length operand maps row to row."""
    if av.cong is None:
        return [None] * n
    if not av.shape or av.shape[0] == 1:
        return [av.cong[0]] * n
    if out.shape and av.shape[0] == out.shape[0] and len(av.cong) in (1, n):
        return _cong_expand(av.cong, n)
    if len(av.cong) == 1:
        return [av.cong[0]] * n
    return [None] * n


def _row_hull(av: AbstractArray, i: int):
    lo = min(av.cell(i, j)[0] for j in range(max(av.r1, 1)))
    hi = max(av.cell(i, j)[1] for j in range(max(av.r1, 1)))
    return lo, hi


def _cong_transfer(eqn, ins, outs):
    """Attach congruence facts to the output of supported integer ops.
    Pure precision layer: every unsupported case leaves cong=None."""
    if len(outs) != 1:
        return
    out = outs[0]
    if _dkind(out.dtype)[0] not in ("int", "uint", "bool"):
        return
    n = out.shape[0] if out.shape else 1
    if n == 0 or n > ROW_CAP:
        n = 1 if not out.shape else n
        if n > ROW_CAP:
            return
    name = eqn.primitive.name
    rows = None
    try:
        if name in ("add", "sub"):
            ra = _cong_rows_for(ins[0], out, n)
            rb = _cong_rows_for(ins[1], out, n)
            sign = 1 if name == "add" else -1
            rows = [_cong_add(a, b, sign) for a, b in zip(ra, rb)]
        elif name == "neg":
            ra = _cong_rows_for(ins[0], out, n)
            rows = [None if f is None else _cong_norm(f[0], -f[1])
                    for f in ra]
        elif name == "mul":
            ra = _cong_rows_for(ins[0], out, n)
            rb = _cong_rows_for(ins[1], out, n)
            rows = [None if (a is None and b is None) else _cong_mul(a, b)
                    for a, b in zip(ra, rb)]
        elif name == "shift_left":
            ra = _cong_rows_for(ins[0], out, n)
            sh = _cong_exact_rows(ins[1], n) if ins[1].cong is not None \
                else [None] * n
            rows = [
                None if (s is None or not 0 <= s < 64)
                else _cong_mul(a, (0, 1 << s))
                for a, s in zip(ra, sh)
            ]
        elif name in ("shift_right_logical", "shift_right_arithmetic"):
            # x >> c with 2^c | m and 2^c | r and x >= 0: then 2^c | x,
            # the shift is an exact division, and x/2^c ≡ r/2^c (m/2^c).
            ra = _cong_rows_for(ins[0], out, n)
            sh = _cong_exact_rows(ins[1], n) if ins[1].cong is not None \
                else [None] * n
            rows = []
            for i, (a, s) in enumerate(zip(ra, sh)):
                f = None
                if a is not None and s is not None and 0 <= s < 64:
                    m, r = a
                    lo, _ = _row_hull(ins[0], i if ins[0].r0 > 1 else 0)
                    if lo >= 0 and m % (1 << s) == 0 and r % (1 << s) == 0:
                        f = _cong_norm(m >> s, r >> s)
                rows.append(f)
        elif name == "and":
            # x & (2^t - 1) on x >= 0 is x mod 2^t; with 2^t | m that
            # residue is exactly r mod 2^t.
            for xi, mi in ((0, 1), (1, 0)):
                mask_rows = _cong_exact_rows(ins[mi], n) \
                    if ins[mi].cong is not None else [None] * n
                xa = _cong_rows_for(ins[xi], out, n)
                got = []
                for i, (f, msk) in enumerate(zip(xa, mask_rows)):
                    g = None
                    if (f is not None and msk is not None and msk >= 0
                            and (msk & (msk + 1)) == 0):
                        t = msk.bit_length()
                        m, r = f
                        lo, _ = _row_hull(ins[xi],
                                          i if ins[xi].r0 > 1 else 0)
                        if lo >= 0 and (m % (1 << t) == 0 or m == 0):
                            g = (0, r % (1 << t))
                    got.append(g)
                if any(g is not None for g in got):
                    rows = got
                    break
        elif name == "or":
            # Disjoint-support or is add: y's low t bits provably zero
            # (2^t | m and 2^t | r) and 0 <= x < 2^t (cells), or
            # symmetrically.
            for xi, yi in ((0, 1), (1, 0)):
                xa = _cong_rows_for(ins[xi], out, n)
                ya = _cong_rows_for(ins[yi], out, n)
                got = []
                for i, (fx, fy) in enumerate(zip(xa, ya)):
                    g = None
                    if fy is not None:
                        my, ry = fy
                        lo, hi = _row_hull(ins[xi],
                                           i if ins[xi].r0 > 1 else 0)
                        if lo >= 0 and hi >= 0:
                            t = hi.bit_length()
                            if (my % (1 << t) == 0 or my == 0) \
                                    and ry % (1 << t) == 0 \
                                    and (my != 0 or ry % (1 << t) == 0):
                                g = _cong_add(fx if fx is not None
                                              else (1, 0), fy)
                    got.append(g)
                if any(g is not None for g in got):
                    rows = got
                    break
        elif name == "convert_element_type":
            # Safe only when the conversion cannot wrap: the input
            # interval must fit the target lane.
            if _dkind(ins[0].dtype)[0] in ("int", "uint", "bool"):
                kind, bits = _dkind(out.dtype)
                lo_l = -(1 << (bits - 1)) if kind == "int" else 0
                hi_l = (1 << (bits - 1)) - 1 if kind == "int" \
                    else (1 << bits) - 1
                glo, ghi = ins[0].joined()
                if lo_l <= glo and ghi <= hi_l:
                    rows = _cong_rows_for(ins[0], out, n)
        elif name == "broadcast_in_dim":
            src = ins[0]
            if src.cong is not None:
                bdims = eqn.params["broadcast_dimensions"]
                if not src.shape or src.shape[0] == 1 or len(src.cong) == 1:
                    rows = [src.cong[0]] * n
                elif bdims and bdims[0] == 0 and out.shape \
                        and src.shape[0] == out.shape[0]:
                    rows = _cong_expand(src.cong, n)
                else:
                    # Every output element is some input element, so the
                    # join over all source rows is always sound.
                    acc = src.cong[0]
                    for f in src.cong[1:]:
                        acc = _cong_join(acc, f)
                    if acc is not None:
                        rows = [acc] * n
        elif name in ("reshape", "squeeze", "transpose", "rev",
                      "copy", "stop_gradient"):
            # Layout changes permute/forward elements: a uniform fact
            # survives as-is, a per-row fact survives as the rows' join.
            if ins[0].cong is not None:
                acc = ins[0].cong[0]
                for f in ins[0].cong[1:]:
                    acc = _cong_join(acc, f)
                if acc is not None:
                    rows = [acc] * n
        elif name == "slice":
            src = ins[0]
            if src.cong is not None and out.shape:
                if len(src.cong) == 1:
                    rows = [src.cong[0]] * n
                else:
                    starts = eqn.params["start_indices"]
                    strides = eqn.params.get("strides") \
                        or (1,) * len(starts)
                    s0, st0 = starts[0], strides[0]
                    full = _cong_expand(src.cong, src.shape[0])
                    rows = [full[s0 + k * st0] for k in range(out.shape[0])]
        elif name == "concatenate":
            if eqn.params["dimension"] == 0 and out.shape \
                    and out.shape[0] <= ROW_CAP:
                rows = []
                for o in ins:
                    rows.extend(_cong_expand(o.cong, o.shape[0]))
        elif name == "reduce_sum":
            axes = eqn.params["axes"]
            src = ins[0]
            if src.cong is not None and src.shape:
                k_other = 1
                for ax in axes:
                    if ax != 0:
                        k_other *= src.shape[ax]
                full = _cong_expand(src.cong, src.shape[0])
                # each row's sum: k_other elements per row index ≡ k·r
                per_row = [None if f is None
                           else _cong_mul(f, (0, k_other))
                           for f in full]
                if 0 in axes:
                    acc = per_row[0]
                    for f in per_row[1:]:
                        acc = _cong_add(acc, f)
                    rows = [acc] * n
                elif out.shape and out.shape[0] == src.shape[0]:
                    rows = _cong_expand(per_row, n)
    except Exception:
        rows = None
    if rows is not None and any(f is not None for f in rows):
        if len(rows) not in (1, n):
            return
        out.cong = rows
        _refine_with_cong(out)


def _refine_with_cong(av: AbstractArray):
    """Tighten interval cells to the nearest values satisfying the row's
    congruence fact (both layers are sound, so the intersection is)."""
    if av.cong is None:
        return
    n = av.shape[0] if av.shape else 1
    if av.shape and (n == 0 or n > ROW_CAP):
        return
    facts = _cong_expand(av.cong, max(av.r0, 1))
    if len(facts) != av.r0:
        return
    cells = []
    changed = False
    for i, row in enumerate(av.cells):
        f = facts[i]
        if f is None:
            cells.append(list(row))
            continue
        m, r = f
        new_row = []
        for lo, hi in row:
            if m == 0:
                if lo <= r <= hi:
                    nlo = nhi = r
                else:
                    nlo, nhi = lo, hi  # defensive; keep sound cells
            else:
                nlo = lo + ((r - lo) % m)
                nhi = hi - ((hi - r) % m)
                if nlo > nhi:
                    nlo, nhi = lo, hi
            changed = changed or (nlo, nhi) != (lo, hi)
            new_row.append((nlo, nhi))
        cells.append(new_row)
    if changed:
        av.cells = _collapse_if_uniform(cells)
