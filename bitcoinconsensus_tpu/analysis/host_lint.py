"""Host-side determinism lint for the consensus interpreter.

The jaxpr prover covers traced kernels; this covers the plain-Python
consensus path (`core/` — script interpreter, tx/block checks, sighash —
and `models/` — batch orchestration whose decisions feed verdicts).
Those modules must be bit-exact, replayable functions of their inputs:

- no float literals or float arithmetic (script semantics are integer;
  a float sneaking into, say, a fee or size comparison is a consensus
  fault that no test vector may cover),
- no `random` / `secrets` (verdicts must not depend on entropy),
- no reading clocks (`time.time`, `datetime.now`, `time.monotonic` —
  anything time-dependent belongs to policy, not consensus).

The clock rule also runs alone over `crypto/` (which legitimately uses
float literals for jax config and fill-ratio math): all host-side timing
flows through `bitcoinconsensus_tpu.obs` spans — the one sanctioned
clock reader — so ad-hoc `time.perf_counter()` pairs cannot drift in
beside the uniform telemetry.

The `precision` rule group runs alone over `ops/` and `crypto/`: every
`jnp.dot` / `lax.dot_general` there must pin
`precision=lax.Precision.HIGHEST` at the call site — the source-level
complement of the jaxpr prover's dot rule, catching the bug before
tracing and in paths no registered kernel reaches yet.

Pure-AST checks: no imports of the scanned modules, so a syntax-valid
file is lintable even when its dependencies are not importable.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass
from typing import FrozenSet, Iterator, List, Sequence

# Rule groups, selectable per scanned tree.
ALL_RULES = frozenset({"float", "nondeterminism", "time"})
TIMING_RULES = frozenset({"time"})
# Async-dispatch discipline: on the device-dispatch path, forcing an
# in-flight JAX array to host (`np.asarray`, `.block_until_ready()`,
# `jax.device_get`) is a hidden synchronization point that silently
# serializes the pipeline — and bypasses the settle seam's guards. The
# ONLY sanctioned block points are the settle seam itself and
# `resilience/inflight.settle_array` (SYNC_ALLOWED_FUNCS).
SYNC_RULES = frozenset({"sync"})
# Function bodies allowed to materialize device buffers.
SYNC_ALLOWED_FUNCS = {
    "_materialize_guarded",  # crypto/jax_backend.py — the settle seam
    "settle_array",          # resilience/inflight.py — sanctioned helper
    "make_mesh",             # parallel/mesh.py — host device-list shaping
}
# module.attr calls that force a device→host sync.
SYNC_BANNED_CALLS = {
    ("np", "asarray"), ("numpy", "asarray"),
    ("np", "array"), ("numpy", "array"),
    ("jax", "device_get"),
}
# MXU precision discipline: every dot in the traced consensus ops must
# pin `precision=lax.Precision.HIGHEST` explicitly — the TPU MXU lowers
# default-precision f32 dots through bfloat16 passes (8-bit mantissa)
# that silently truncate 13-bit limbs. The jaxpr prover catches this
# after tracing (interval._r_dot); this catches it at review time, and
# in code paths no registered kernel reaches yet.
PRECISION_RULES = frozenset({"precision"})
# module-path suffixes whose calls take a precision keyword.
DOT_CALLS = {"jnp.dot", "jax.numpy.dot", "numpy.dot",
             "lax.dot_general", "jax.lax.dot_general",
             "jnp.matmul", "jax.numpy.matmul"}

# Pallas kernel-body discipline: inside `_kernel_body`, every limb
# constant must come through the consts_ref row table installed by
# `_kernel`'s set_const_provider — materializing an ndarray there makes
# Mosaic bake it into the kernel as a captured constant, bypassing the
# one audited constant path (analysis/pallas_check.py flags the same
# thing at the jaxpr level; this catches it at review time, pre-trace).
PALLAS_RULES = frozenset({"pallas"})

# Function bodies subject to the `pallas` rule.
PALLAS_KERNEL_BODIES = {"_kernel_body"}
# np/jnp constructors that materialize array constants.
ARRAY_CONSTRUCTORS = {"asarray", "array", "frombuffer", "fromiter"}
ARRAY_MODULES = {"np", "numpy", "jnp"}

BANNED_IMPORTS = {"random", "secrets"}
# module.attr calls whose mere presence is a violation
BANNED_CALLS = {
    ("time", "time"), ("time", "time_ns"), ("time", "monotonic"),
    ("time", "monotonic_ns"), ("time", "perf_counter"),
    ("time", "perf_counter_ns"), ("time", "process_time"),
    ("datetime", "now"), ("datetime", "utcnow"), ("datetime", "today"),
    ("date", "today"),
}
FLOAT_CAST = {"float"}


@dataclass
class LintFinding:
    path: str
    line: int
    rule: str
    msg: str

    def __str__(self):
        return f"{self.path}:{self.line}: [{self.rule}] {self.msg}"


def _is_float_literal(node: ast.Constant) -> bool:
    return isinstance(node.value, float)


def _dotted_name(fn) -> str:
    """`a.b.c` attribute chain -> \"a.b.c\"; anything else -> \"\"."""
    parts = []
    while isinstance(fn, ast.Attribute):
        parts.append(fn.attr)
        fn = fn.value
    if isinstance(fn, ast.Name):
        parts.append(fn.id)
        return ".".join(reversed(parts))
    return ""


class _Visitor(ast.NodeVisitor):
    def __init__(self, path: str, rules: FrozenSet[str] = ALL_RULES):
        self.path = path
        self.rules = rules
        self.findings: List[LintFinding] = []
        self._fn_stack: List[str] = []

    def _flag(self, node, rule, msg):
        self.findings.append(
            LintFinding(self.path, getattr(node, "lineno", 0), rule, msg))

    def visit_Constant(self, node: ast.Constant):
        if "float" in self.rules and _is_float_literal(node):
            self._flag(node, "float-literal",
                       f"float literal {node.value!r} in consensus host "
                       "code (integer semantics only)")
        self.generic_visit(node)

    def visit_Import(self, node: ast.Import):
        if "nondeterminism" in self.rules:
            for alias in node.names:
                root = alias.name.split(".")[0]
                if root in BANNED_IMPORTS:
                    self._flag(node, "nondeterminism",
                               f"import of `{alias.name}` (entropy source)")
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom):
        root = (node.module or "").split(".")[0]
        if "nondeterminism" in self.rules and root in BANNED_IMPORTS:
            self._flag(node, "nondeterminism",
                       f"import from `{node.module}` (entropy source)")
        self.generic_visit(node)

    def visit_FunctionDef(self, node: ast.FunctionDef):
        self._fn_stack.append(node.name)
        try:
            self.generic_visit(node)
        finally:
            self._fn_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def _in_kernel_body(self) -> bool:
        return any(n in PALLAS_KERNEL_BODIES for n in self._fn_stack)

    def visit_Call(self, node: ast.Call):
        fn = node.func
        if "precision" in self.rules:
            name = _dotted_name(fn)
            if name in DOT_CALLS:
                kw = next((k.value for k in node.keywords
                           if k.arg == "precision"), None)
                if not (isinstance(kw, ast.Attribute)
                        and kw.attr == "HIGHEST"):
                    self._flag(
                        node, "dot-precision",
                        f"{name}() without an explicit "
                        "precision=lax.Precision.HIGHEST — the TPU MXU "
                        "lowers default-precision f32 dots through "
                        "bfloat16 passes that silently truncate 13-bit "
                        "limbs; the exactness theorem only holds at "
                        "HIGHEST")
        if "pallas" in self.rules and self._in_kernel_body():
            name = None
            if (isinstance(fn, ast.Attribute)
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id in ARRAY_MODULES
                    and fn.attr in ARRAY_CONSTRUCTORS):
                name = f"{fn.value.id}.{fn.attr}"
            elif isinstance(fn, ast.Name) and fn.id in ARRAY_CONSTRUCTORS:
                name = fn.id
            if name is not None:
                self._flag(
                    node, "pallas-consts",
                    f"{name}() inside a Pallas kernel body captures an "
                    "array constant — route limb constants through the "
                    "consts_ref row table (limbs.set_const_provider), the "
                    "one audited constant path into VMEM")
        if "sync" in self.rules and not any(
            n in SYNC_ALLOWED_FUNCS for n in self._fn_stack
        ):
            if isinstance(fn, ast.Attribute):
                if fn.attr == "block_until_ready":
                    self._flag(
                        node, "sync",
                        ".block_until_ready() outside the settle seam — "
                        "in-flight buffers settle through "
                        "resilience/inflight (settle_array or "
                        "_materialize_guarded), never ad-hoc blocking")
                elif (isinstance(fn.value, ast.Name)
                      and (fn.value.id, fn.attr) in SYNC_BANNED_CALLS):
                    self._flag(
                        node, "sync",
                        f"{fn.value.id}.{fn.attr}() on the dispatch path "
                        "forces a hidden device→host sync — route "
                        "materialization through inflight.settle_array "
                        "or the settle seam")
        if (
            "time" in self.rules
            and isinstance(fn, ast.Attribute)
            and isinstance(fn.value, ast.Name)
        ):
            key = (fn.value.id, fn.attr)
            if key in BANNED_CALLS:
                self._flag(node, "time-dependence",
                           f"call to {key[0]}.{key[1]}() — time flows "
                           "through obs spans only (consensus verdicts "
                           "must not read clocks, and ad-hoc timing "
                           "bypasses the telemetry registry)")
        if (
            "float" in self.rules
            and isinstance(fn, ast.Name)
            and fn.id in FLOAT_CAST
        ):
            self._flag(node, "float-op",
                       "float() cast in consensus host code")
        self.generic_visit(node)

    def visit_BinOp(self, node: ast.BinOp):
        if "float" in self.rules and isinstance(node.op, ast.Div):
            self._flag(node, "float-op",
                       "true division `/` yields float; use `//` for "
                       "integer consensus arithmetic")
        self.generic_visit(node)


def _iter_py(root: str) -> Iterator[str]:
    for dirpath, _dirnames, filenames in os.walk(root):
        for f in sorted(filenames):
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)


def lint_paths(
    paths: Sequence[str], rules: FrozenSet[str] = ALL_RULES
) -> List[LintFinding]:
    findings: List[LintFinding] = []
    for root in paths:
        files = _iter_py(root) if os.path.isdir(root) else [root]
        for path in files:
            with open(path, "r", encoding="utf-8") as fh:
                src = fh.read()
            try:
                tree = ast.parse(src, filename=path)
            except SyntaxError as e:
                findings.append(LintFinding(path, e.lineno or 0,
                                            "syntax", str(e)))
                continue
            v = _Visitor(path, rules)
            v.visit(tree)
            findings.extend(v.findings)
    return findings


def lint_consensus_host(repo_root: str) -> List[LintFinding]:
    """Full rules over core/ + models/; clock rule alone over crypto/
    (its device-dispatch driver may use floats but must route timing
    through obs spans, never raw perf_counter pairs); const-provider
    discipline over the Pallas kernel body."""
    pkg = os.path.join(repo_root, "bitcoinconsensus_tpu")
    findings = lint_paths([os.path.join(pkg, "core"),
                           os.path.join(pkg, "models")])
    # resilience/ and serving/ are host-side policy with wall-clock
    # deadlines: like crypto/ they may use floats but must read time
    # through obs.monotonic, never raw time.* pairs the telemetry
    # cannot see (sleeping is fine; reading a clock is not).
    findings += lint_paths([os.path.join(pkg, "crypto"),
                           os.path.join(pkg, "resilience"),
                           os.path.join(pkg, "serving")],
                          rules=TIMING_RULES)
    findings += lint_paths([os.path.join(pkg, "ops", "pallas_kernel.py")],
                           rules=PALLAS_RULES)
    # MXU precision discipline over the traced consensus ops: every dot
    # must pin Precision.HIGHEST at the call site (see PRECISION_RULES).
    findings += lint_paths([os.path.join(pkg, "ops"),
                            os.path.join(pkg, "crypto")],
                           rules=PRECISION_RULES)
    # Async-dispatch discipline over the in-flight pipeline: the dispatch
    # drivers and the queue itself must not force device buffers to host
    # outside the settle seam (see SYNC_ALLOWED_FUNCS).
    findings += lint_paths(
        [os.path.join(pkg, "crypto", "jax_backend.py"),
         os.path.join(pkg, "parallel", "mesh.py"),
         os.path.join(pkg, "resilience", "inflight.py"),
         # The network edge and the persistent store sit upstream of the
         # dispatch path: neither may ever force a device buffer to host.
         os.path.join(pkg, "serving", "ingress.py"),
         os.path.join(pkg, "models", "sigstore.py")],
        rules=SYNC_RULES)
    return findings


# -- scalar-recoder schedule coverage (PR 19) ----------------------------
#
# Any digit-recoding / scalar-split function in ops/ or crypto/glv.py
# must be registered with the scalar-schedule prover
# (analysis/scalar_check.REGISTERED_RECODERS), mirroring the PR 17
# region-coverage rule: a new recoder landing without a certificate
# would silently reopen the window-order / carry-fold hole the prover
# closed. Detection is AST-only: a function counts as a recoder when
# its name carries a scalar-decomposition hint, or its body extracts
# windowed digits — a `(x >> amt) & mask` where the shift amount is not
# a plain integer constant (fixed-shift carry propagation in the field
# ops is NOT a recoder; variable-shift extraction is).

SCALAR_RECODER_NAME_HINTS = (
    "digit", "window", "recode", "split_lambda", "scalar_bits",
    "to_limbs", "limbs_to",
)


def _is_var_shift_extract(node: ast.AST) -> bool:
    """`(expr >> amt) & mask` with a non-constant shift amount."""
    if not (isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitAnd)):
        return False
    for side in (node.left, node.right):
        if (isinstance(side, ast.BinOp)
                and isinstance(side.op, ast.RShift)
                and not isinstance(side.right, ast.Constant)):
            return True
    return False


def scalar_recoder_functions(paths: Sequence[str]):
    """All (path, line, name) recoder-shaped functions under `paths`."""
    hits = []
    for root in paths:
        files = _iter_py(root) if os.path.isdir(root) else [root]
        for path in files:
            with open(path, "r", encoding="utf-8") as fh:
                try:
                    tree = ast.parse(fh.read(), filename=path)
                except SyntaxError:
                    continue  # lint_paths reports syntax errors
            for node in ast.walk(tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                name = node.name.lower()
                named = any(h in name for h in SCALAR_RECODER_NAME_HINTS)
                extracts = any(_is_var_shift_extract(n)
                               for n in ast.walk(node))
                if named or extracts:
                    hits.append((path, node.lineno, node.name))
    return hits


def lint_scalar_recoders(
    repo_root: str = None,
    paths: Sequence[str] = None,
    registered=None,
) -> List[LintFinding]:
    """One finding per recoder-shaped function not registered with the
    scalar-schedule prover.

    `paths` / `registered` override the defaults (the negative-fixture
    tests feed a deliberately unregistered toy recoder through the same
    gate)."""
    if paths is None:
        pkg = os.path.join(repo_root, "bitcoinconsensus_tpu")
        paths = [os.path.join(pkg, "ops"),
                 os.path.join(pkg, "crypto", "glv.py")]
    if registered is None:
        from . import scalar_check
        registered = scalar_check.REGISTERED_RECODERS
    findings: List[LintFinding] = []
    for path, line, name in scalar_recoder_functions(paths):
        if name not in registered:
            findings.append(LintFinding(
                path, line, "scalar-coverage",
                f"`{name}` looks like a digit recoder / scalar split but "
                "is not registered with the scalar-schedule prover — add "
                "it to analysis/scalar_check.REGISTERED_RECODERS mapped "
                "to the target that certifies it (and extend the prover "
                "if no target covers it yet)"))
    return findings


# -- kernel region-annotation coverage (PR 17) ---------------------------
#
# Not an AST rule: this one traces. Every kernel registered in
# `analysis/registry` must execute under a `region:` named scope
# (`ops/regions.py`) so the xprof observatory can attribute its device
# time — a kernel landing without annotation would silently grow the
# `unattributed` share of every capture. Kept in this module because it
# is a lint (finding-shaped, wired into `scripts/consensus_lint.py`),
# with lazy imports so the pure-AST rules above stay dependency-free.

# A kernel passes when at least this fraction of its element ops sit
# under some region scope. Below 1.0 because trace plumbing (argument
# converts, output reshapes) legitimately sits outside the scopes.
REGION_MIN_COVERAGE = 0.90


def region_coverage(fn, args) -> float:
    """Fraction of a traced callable's element ops under region scopes."""
    import jax

    from ..obs import xprof

    closed = jax.make_jaxpr(fn)(*args)
    acc = xprof.walk_jaxpr_regions(closed.jaxpr)
    total = sum(b["ops"] for b in acc.values())
    if total <= 0:
        return 0.0
    named = sum(b["ops"] for stack, b in acc.items() if stack)
    return named / total


def lint_kernel_regions(
    include_heavy: bool = False,
    min_coverage: float = REGION_MIN_COVERAGE,
    specs=None,
) -> List[LintFinding]:
    """One finding per registry kernel not covered by named regions.

    `specs` overrides the registry list (the negative-fixture tests feed
    a deliberately unannotated toy through the same gate).
    """
    from . import registry

    if specs is None:
        specs = registry.all_kernels(include_heavy=include_heavy)
    findings: List[LintFinding] = []
    for spec in specs:
        try:
            fn, args = spec.build(registry.DEFAULT_BATCH)
            cov = region_coverage(fn, args)
        except Exception as e:  # an untraceable kernel is a finding too
            findings.append(LintFinding(
                spec.name, 0, "region",
                f"region-coverage trace failed: {type(e).__name__}: {e}"))
            continue
        if cov < min_coverage:
            findings.append(LintFinding(
                spec.name, 0, "region",
                f"only {cov:.0%} of element ops run under a region: "
                f"scope (< {min_coverage:.0%}) — annotate the kernel "
                f"with ops/regions.named_region so xprof can attribute "
                f"its device time"))
    return findings
