"""Pallas-level consensus prover: the interval engine below the jaxpr.

PR 1's analyzer certifies every XLA-path kernel, but the hand-fused
Mosaic kernel (`ops/pallas_kernel.py`) — the code that actually runs the
hot path on TPU — was vetted only by bit-equality spot tests. This
module closes that gap by teaching `analysis/interval.py` the Pallas
dialect, in three layers:

1. **Abstract Ref semantics.** A `pallas_call` equation is entered, its
   kernel jaxpr evaluated by the same interval interpreter, with every
   VMEM ref modeled as a `RefAbstract`: a mutable per-axis-0-row store
   of interval abstractions. `get`/`swap`/`addupdate` transfer rules
   thread per-row intervals through the `(16, NLIMB, tile)` scratch
   tables, so the signed-window selects and the batch-inverse
   prefix/suffix trees are proven int32-safe with per-limb precision —
   the same observation discipline as the jaxpr layer, re-derived with
   no access to the kernel's hand bookkeeping. Writes inside loop
   bodies / unresolved cond branches degrade to hull-merges
   (`ctx.in_loop`), keeping strong updates sound; a read of a scratch
   or output row that was never written is a gate failure
   (uninitialized VMEM must not feed a consensus verdict).

2. **Grid/BlockSpec program checks.** Every index map is evaluated
   concretely for every grid step: block windows must stay inside the
   array extent, array dims must divide by block dims (the
   `B % LANE_TILE` contract), and every OUTPUT block offset must be
   produced by exactly one grid step and the set must tile the array —
   "every output element written exactly once". The peak VMEM live set
   (pipelined blocks x double-buffering + scratch + a last-use liveness
   walk over the kernel's intermediates) is computed, attached to the
   `Report` (`vmem_peak_bytes`, `grid`), and budgeted against
   `VMEM_BUDGET_BYTES` (14 MB of the ~16 MB core limit — headroom for
   Mosaic's own spills).

3. **Ref-discipline lint.** Captured array constants in the kernel
   jaxpr are rejected (limb constants must arrive via the
   `set_const_provider` row table, `consts_ref`); i1 vectors and 64-bit
   dtypes through scan/while carries are rejected (Mosaic cannot lower
   vmasks across loop boundaries; the kernel carries int32 0/1 masks).

Scratch persists across grid steps on a real TPU, but the abstract body
is evaluated once per `pallas_call`: a kernel whose step N reads scratch
written by step N-1 is flagged by the read-before-write check. That is
deliberate — grid-step-order dependence is exactly the kind of schedule
coupling the consensus kernel must not have.

Importing this module registers the `get`/`swap`/`addupdate`/
`program_id`/`pallas_call` rules into `interval.RULES`, so a plain
`interval.analyze(verify_tiles, ...)` proves preamble, kernel body and
epilogue end to end. (`interval.ALLOWED_PRIMITIVES` is a frozen
import-time snapshot and intentionally does not grow: state primitives
are only legal inside a Pallas trace, where these rules vet them.)

The exact-float certificate of `interval.py` carries through unchanged:
ref reads/writes preserve `exactf`/`fwhy`, an inexact f32 value written
into VMEM is a gate failure at the write site, and the state primitives
are registered on `interval.FLOAT_VETTED` so the post-pass does not
demote values they merely move.

`NEGATIVES` holds deliberately broken toy kernels (out-of-bounds index
map, read-before-write scratch, an overflowing fe_mul-without-canon
chain, a double-written output block, plus three unsound f32 chains: a
default-precision dot, a 2^24-overflowing accumulation, and a float
round-trip through an unvetted op) used by the tests and
`scripts/consensus_lint.py --negative` to prove the gate actually fires.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import tree_util

from jax.extend import core as jax_core

from . import interval as IV

__all__ = [
    "RefAbstract",
    "VMEM_BYTES",
    "VMEM_BUDGET_BYTES",
    "NEGATIVES",
    "analyze_negative",
    "analyze_positive_toy",
]

VMEM_BYTES = 16 * 1024 * 1024        # per-core VMEM on current TPUs
VMEM_BUDGET_BYTES = 14 * 1024 * 1024  # gate margin: leave Mosaic headroom
MAX_GRID_STEPS = 4096                 # index-map enumeration cap
_DOUBLE_BUFFER = 2                    # Mosaic pipelines grid blocks


def _nbytes(shape, dtype) -> int:
    n = 1
    for s in shape or ():
        n *= int(s)
    return n * max(np.dtype(dtype).itemsize, 1)


def _is_ref_aval(aval) -> bool:
    return "Ref" in type(aval).__name__ or hasattr(aval, "inner_aval")


def _origin(bm, i) -> str:
    return str(getattr(bm, "origin", "") or f"operand{i}")


def _block_dim(b) -> int:
    if isinstance(b, (int, np.integer)):
        return int(b)
    try:
        return int(b)
    except Exception:
        return 1  # squeezed/mapped block dim


# ---------------------------------------------------------------------------
# RefAbstract: the abstract VMEM ref.


def _row_hull(v: "IV.AbstractArray", i: int) -> Tuple[int, int]:
    lo = min(v.cell(i, j)[0] for j in range(v.r1))
    hi = max(v.cell(i, j)[1] for j in range(v.r1))
    return (lo, hi)


class RefAbstract:
    """Mutable interval store for one VMEM ref.

    Rows along axis 0 (the table/limb/window axis of every consensus
    ref) are tracked individually while `shape[0] <= ROW_CAP`; each row
    holds an AbstractArray of the remainder shape (which itself tracks
    its own leading axis — so a (16, NLIMB, tile) table keeps a full
    (16, NLIMB) interval grid). `None` rows are bottom: never written.
    """

    __slots__ = ("name", "kind", "shape", "dtype", "rest", "n0", "gran",
                 "rows", "writes", "rbw")

    def __init__(self, name, kind, shape, dtype, init=None):
        self.name = name
        self.kind = kind  # "in" | "out" | "scratch"
        self.shape = tuple(int(s) for s in shape)
        self.dtype = np.dtype(dtype)
        self.n0 = self.shape[0] if self.shape else 1
        self.rest = self.shape[1:]
        self.gran = self.n0 if 1 <= self.n0 <= IV.ROW_CAP else 1
        self.rows: List[Optional[IV.AbstractArray]] = [None] * self.gran
        self.writes = [0] * self.gran
        self.rbw: Dict[int, str] = {}  # slot -> where of first bottom read
        if init is not None:
            for s in range(self.gran):
                r = s if self.gran == self.n0 else 0
                cells = [[init.cell(r, j)] for j in range(init.r1)]
                self.rows[s] = IV.mk(self.rest, self.dtype, cells,
                                     exactf=init.exactf)

    # -- indexing -----------------------------------------------------------

    def _slot(self, r: int) -> int:
        return r if self.gran == self.n0 else 0

    def _resolve(self, ctx, idx, where):
        """-> (rows, keeps_axis0, trailing_full, exact). `rows` is the
        (clamped) set of axis-0 indices possibly touched; `exact` means
        that set is known precisely (strong updates are legal)."""
        if idx is None or len(idx) != 1 or not self.shape:
            return list(range(self.n0)), True, not self.shape, False
        entries = list(idx[0].indices)
        if not entries:
            return list(range(self.n0)), True, True, True
        keeps, lo, hi, exact = _entry_range(entries[0], self.n0)
        if lo < 0 or hi > self.n0 - 1:
            ctx.violate(
                "ref", where,
                f"{self.kind} ref `{self.name}` axis-0 index interval "
                f"[{lo}, {hi}] out of bounds for {self.n0} rows",
            )
            lo, hi = max(lo, 0), min(hi, self.n0 - 1)
            if lo > hi:
                lo, hi = 0, self.n0 - 1
            exact = False
        trailing_full = all(
            _is_full_slice(e, n)
            for e, n in zip(entries[1:], self.shape[1:])
        ) and len(entries) - 1 <= len(self.shape) - 1
        return list(range(lo, hi + 1)), keeps, trailing_full, exact

    # -- read ---------------------------------------------------------------

    def read(self, ctx, idx, out_shape, out_dtype, where, check_rbw=True):
        rows, keeps, trailing_full, _ = self._resolve(ctx, idx, where)
        slots = sorted({self._slot(r) for r in rows})
        if check_rbw and self.kind in ("out", "scratch"):
            for s in slots:
                if self.rows[s] is None and s not in self.rbw:
                    self.rbw[s] = where
        vals = [self.rows[s] if self.rows[s] is not None
                else IV.full_range(self.rest, self.dtype) for s in slots]
        exactf = all(v.exactf for v in vals) and bool(vals)
        if keeps:
            full = (self.gran == self.n0 and trailing_full
                    and rows == list(range(self.n0))
                    and out_shape and out_shape[0] == self.n0)
            if full:
                rmax = max(v.r0 for v in vals)
                cells = []
                for v in vals:
                    if v.r0 == rmax:
                        cells.append([_row_hull(v, i) for i in range(rmax)])
                    else:
                        cells.append([v.joined()] * rmax)
                return IV.mk(out_shape, out_dtype, cells, exactf=exactf)
            hull = _join_list(vals).joined()
            return IV.mk(out_shape, out_dtype, [[hull]], exactf=exactf)
        joined = _join_list(vals)
        if trailing_full and tuple(out_shape) == tuple(self.rest):
            return joined
        return IV.mk(out_shape, out_dtype, [[joined.joined()]],
                     exactf=exactf)

    # -- write --------------------------------------------------------------

    def write(self, ctx, idx, val, where, weak):
        if self.dtype.kind == "f" and not val.exactf:
            why = f" [{val.fwhy}]" if getattr(val, "fwhy", None) else ""
            ctx.violate(
                "float", where,
                f"inexact float32 value written to {self.kind} ref "
                f"`{self.name}`: every VMEM-resident f32 table must carry "
                f"an exact-integer certificate{why}",
            )
        rows, keeps, trailing_full, exact = self._resolve(ctx, idx, where)
        slots = sorted({self._slot(r) for r in rows})
        full_slice = (keeps and self.gran == self.n0 and trailing_full
                      and rows == list(range(self.n0)))
        strong = (not weak) and exact and self.gran == self.n0 and (
            full_slice or len(rows) == 1)
        for s in slots:
            if full_slice:
                j_hi = max(val.r1, 1)
                cells = [[val.cell(min(s, max(val.r0 - 1, 0)), j)]
                         for j in range(j_hi)]
                rv = IV.mk(self.rest, self.dtype, cells, exactf=val.exactf)
            elif (not keeps and trailing_full
                  and tuple(val.shape) == tuple(self.rest)):
                rv = val
            else:
                rv = IV.mk(self.rest, self.dtype, [[val.joined()]],
                           exactf=val.exactf)
            if strong:
                self.rows[s] = rv
            else:
                cur = self.rows[s]
                self.rows[s] = rv if cur is None else IV.join_values(cur, rv)
            if not ctx.mute:
                self.writes[s] += 1

    # -- export -------------------------------------------------------------

    def to_array(self, shape, dtype) -> "IV.AbstractArray":
        vals = [r if r is not None
                else IV.full_range(self.rest, self.dtype)
                for r in self.rows]
        exactf = all(v.exactf for v in vals)
        if self.gran == self.n0 and shape and shape[0] == self.n0:
            rmax = max(v.r0 for v in vals)
            cells = []
            for v in vals:
                if v.r0 == rmax:
                    cells.append([_row_hull(v, i) for i in range(rmax)])
                else:
                    cells.append([v.joined()] * rmax)
            return IV.mk(shape, dtype, cells, exactf=exactf)
        hull = _join_list(vals).joined()
        return IV.mk(shape, dtype, [[hull]], exactf=exactf)

    def __repr__(self):
        written = sum(r is not None for r in self.rows)
        return (f"RefAbstract({self.name}, {self.kind}, {self.shape}, "
                f"{written}/{self.gran} rows written)")


def _join_list(vals: List["IV.AbstractArray"]) -> "IV.AbstractArray":
    out = vals[0]
    for v in vals[1:]:
        out = IV.join_values(out, v)
    return out


def _entry_range(e, n: int):
    """Classify one NDIndexer dim entry -> (keeps_axis, lo, hi, exact)."""
    if hasattr(e, "start") and hasattr(e, "size"):  # pl.Slice
        size = int(e.size)
        stride = int(getattr(e, "stride", 1) or 1)
        st = e.start
        if isinstance(st, IV.AbstractArray):
            slo, shi = st.joined()
            exact = slo == shi
        elif isinstance(st, (int, np.integer)):
            slo = shi = int(st)
            exact = True
        else:
            return True, 0, n - 1, False
        return True, slo, shi + (size - 1) * stride, exact and stride == 1
    if isinstance(e, IV.AbstractArray):
        lo, hi = e.joined()
        if e.shape:  # advanced integer-array index: keeps a dim, joins
            return True, lo, hi, False
        return False, lo, hi, lo == hi
    if isinstance(e, (int, np.integer)):
        return False, int(e), int(e), True
    return False, 0, n - 1, False


def _is_full_slice(e, n: int) -> bool:
    return (hasattr(e, "start") and hasattr(e, "size")
            and isinstance(e.start, (int, np.integer))
            and int(e.start) == 0 and int(e.size) == int(n)
            and int(getattr(e, "stride", 1) or 1) == 1)


def _indexer(eqn, ins, start: int):
    """Rebuild the NDIndexer list from the flattened dynamic index
    operands (abstract values stand in for the tracers)."""
    tree = eqn.params.get("tree")
    if tree is None:
        return None
    idx = tree_util.tree_unflatten(tree, list(ins[start:]))
    entries = [t for t in idx if hasattr(t, "indices")]
    return entries if entries else None


# ---------------------------------------------------------------------------
# State-primitive transfer rules.


def _r_get(interp, eqn, ins, where):
    out = eqn.outvars[0].aval
    ref = ins[0]
    if not isinstance(ref, RefAbstract):
        interp.ctx.violate("internal", where, "get on a non-ref operand")
        return [IV.top(out.shape, out.dtype)]
    for v in ins[1:]:
        interp.ctx.observe(v, where, "ref index")
    idx = _indexer(eqn, ins, 1)
    return [ref.read(interp.ctx, idx, out.shape, out.dtype, where)]


def _r_swap(interp, eqn, ins, where):
    out = eqn.outvars[0].aval
    ref, val = ins[0], ins[1]
    if not isinstance(ref, RefAbstract):
        interp.ctx.violate("internal", where, "swap on a non-ref operand")
        return [IV.top(out.shape, out.dtype)]
    for v in ins[2:]:
        interp.ctx.observe(v, where, "ref index")
    idx = _indexer(eqn, ins, 2)
    drop = type(eqn.outvars[0]).__name__ == "DropVar"
    old = (IV.top(out.shape, out.dtype) if drop
           else ref.read(interp.ctx, idx, out.shape, out.dtype, where))
    ref.write(interp.ctx, idx, val, where, weak=interp.ctx.in_loop > 0)
    return [old]


def _r_addupdate(interp, eqn, ins, where):
    ref, val = ins[0], ins[1]
    if not isinstance(ref, RefAbstract):
        interp.ctx.violate("internal", where, "addupdate on a non-ref")
        return []
    idx = _indexer(eqn, ins, 2)
    old = ref.read(interp.ctx, idx, val.shape, val.dtype, where)
    acc = IV._ewise(interp.ctx, val.shape, val.dtype, [old, val],
                    lambda x, y: (x[0] + y[0], x[1] + y[1]))
    ref.write(interp.ctx, idx, acc, where, weak=True)
    return []


_GRID_STACK: List[Tuple[int, ...]] = []


def _r_program_id(interp, eqn, ins, where):
    out = eqn.outvars[0].aval
    axis = int(eqn.params.get("axis", 0))
    hi = 0
    if _GRID_STACK and axis < len(_GRID_STACK[-1]):
        hi = max(int(_GRID_STACK[-1][axis]) - 1, 0)
    return [IV.mk(out.shape, out.dtype, [[(0, hi)]])]


# ---------------------------------------------------------------------------
# Grid / BlockSpec program checks.


def _grid_total(grid) -> int:
    t = 1
    for g in grid:
        t *= int(g)
    return t


def _check_grid(ctx, grid, bms, nin, nout, where):
    total = _grid_total(grid)
    steps = list(itertools.islice(
        np.ndindex(*grid) if grid else iter([()]), MAX_GRID_STEPS))
    truncated = total > MAX_GRID_STEPS
    if truncated and not ctx.mute:
        ctx.report.notes.append(
            f"grid has {total} steps; index maps checked for the first "
            f"{MAX_GRID_STEPS} only")
    seen_out: List[Dict[tuple, tuple]] = [dict() for _ in range(nout)]
    for bi, bm in enumerate(bms):
        name = _origin(bm, bi)
        bw = f"{where}/blockspec[{name}]"
        ashape = tuple(int(s) for s in bm.array_shape_dtype.shape)
        bshape = tuple(_block_dim(b) for b in bm.block_shape)
        for d, (adim, bdim) in enumerate(zip(ashape, bshape)):
            if bdim and adim % bdim:
                ctx.violate(
                    "grid", bw,
                    f"array dim {d} ({adim}) is not divisible by the block "
                    f"dim ({bdim}): partial tiles are outside the verified "
                    "contract (the B % LANE_TILE == 0 precondition)",
                )
        cj = bm.index_map_jaxpr
        if len(cj.jaxpr.invars) != len(grid):
            ctx.violate(
                "grid", bw,
                f"index map takes {len(cj.jaxpr.invars)} operands for a "
                f"{len(grid)}-d grid (dynamic index operands are not part "
                "of the verified contract)",
            )
            continue
        for step in steps:
            try:
                bidx = jax.core.eval_jaxpr(
                    cj.jaxpr, cj.consts, *[np.int32(v) for v in step])
            except Exception as e:  # index map must be total
                ctx.violate(
                    "grid", bw,
                    f"index map failed at grid step {tuple(step)}: "
                    f"{type(e).__name__}: {e}")
                break
            starts = tuple(int(b) * bd for b, bd in zip(bidx, bshape))
            for d, (st, bd, adim) in enumerate(zip(starts, bshape, ashape)):
                if st < 0 or st + bd > adim:
                    ctx.violate(
                        "grid", bw,
                        f"index map sends grid step {tuple(step)} to block "
                        f"start {st} on dim {d}: window [{st}, {st + bd}) "
                        f"escapes the array extent {adim}",
                    )
            if nin <= bi < nin + nout:
                j = bi - nin
                prev = seen_out[j].get(starts)
                if prev is not None:
                    ctx.violate(
                        "grid", bw,
                        f"output block at offset {starts} is written by grid "
                        f"steps {prev} and {tuple(step)} — every output "
                        "element must be written exactly once",
                    )
                else:
                    seen_out[j][starts] = tuple(step)
        if not truncated and nin <= bi < nin + nout:
            j = bi - nin
            blk = 1
            for b in bshape:
                blk *= max(b, 1)
            tot = 1
            for s in ashape:
                tot *= s
            if len(seen_out[j]) * blk != tot:
                ctx.violate(
                    "grid", bw,
                    f"grid writes {len(seen_out[j])} distinct blocks of "
                    f"{blk} elements but the output has {tot}: some "
                    "elements are never written",
                )


# ---------------------------------------------------------------------------
# VMEM live-set accounting.

_PEAK_CACHE: Dict[int, int] = {}


def _sub_jaxprs(e):
    for v in e.params.values():
        if hasattr(v, "jaxpr") and hasattr(v, "consts"):  # ClosedJaxpr
            yield v.jaxpr
        elif hasattr(v, "eqns") and hasattr(v, "invars"):  # raw Jaxpr
            yield v
        elif isinstance(v, (tuple, list)):
            for u in v:
                if hasattr(u, "jaxpr") and hasattr(u, "consts"):
                    yield u.jaxpr
                elif hasattr(u, "eqns") and hasattr(u, "invars"):
                    yield u


def _peak_live(jaxpr) -> int:
    """Peak bytes of simultaneously-live SSA intermediates, by a
    last-use liveness walk. Sub-jaxprs (scan/while/cond bodies)
    contribute their own internal peak at their call site. Refs are
    excluded (counted as blocks/scratch); a conservative model of what
    Mosaic must hold, not a simulation of its allocator."""
    key = id(jaxpr)
    if key in _PEAK_CACHE:
        return _PEAK_CACHE[key]
    eqns = jaxpr.eqns
    last = {}
    for t, e in enumerate(eqns):
        for v in e.invars:
            if not isinstance(v, jax_core.Literal):
                last[v] = t
    for v in jaxpr.outvars:
        if not isinstance(v, jax_core.Literal):
            last[v] = len(eqns)
    alive: Dict[object, int] = {}
    cur = 0
    for v in jaxpr.constvars:
        if v in last and not _is_ref_aval(v.aval):
            alive[v] = _nbytes(v.aval.shape, v.aval.dtype)
            cur += alive[v]
    peak = cur
    for t, e in enumerate(eqns):
        born_dead = 0
        for v in e.outvars:
            if _is_ref_aval(v.aval):
                continue
            sz = _nbytes(v.aval.shape, v.aval.dtype)
            if type(v).__name__ == "DropVar" or v not in last:
                born_dead += sz  # materialized for this eqn only
                continue
            if v not in alive:
                alive[v] = sz
                cur += sz
        sub = 0
        for sj in _sub_jaxprs(e):
            sub = max(sub, _peak_live(sj))
        if cur + sub + born_dead > peak:
            peak = cur + sub + born_dead
        for v in {v for v in list(e.invars) + list(e.outvars)
                  if not isinstance(v, jax_core.Literal)}:
            if v in alive and last.get(v, -1) <= t:
                cur -= alive.pop(v)
    _PEAK_CACHE[key] = peak
    return peak


def _vmem_peak(jaxpr, bms, grid, nin, nout) -> int:
    dbuf = _DOUBLE_BUFFER if _grid_total(grid) > 1 else 1
    blocks = 0
    for bm in bms:
        bshape = tuple(_block_dim(b) for b in bm.block_shape)
        blocks += _nbytes(bshape, bm.array_shape_dtype.dtype)
    scratch = 0
    for v in jaxpr.invars[nin + nout:]:
        aval = v.aval
        scratch += _nbytes(aval.shape, aval.dtype)
    return blocks * dbuf + scratch + _peak_live(jaxpr)


# ---------------------------------------------------------------------------
# Ref-discipline lint.


def _check_carry(ctx, aval, where, i):
    shape = tuple(getattr(aval, "shape", ()) or ())
    try:
        dt = np.dtype(aval.dtype)
    except Exception:
        return
    if dt == np.bool_ and shape:
        ctx.violate(
            "ref", where,
            f"loop carry {i} is an i1 vector {shape}: Mosaic cannot lower "
            "vmask values through loop boundaries — carry int32 0/1 masks "
            "instead (see ops/pallas_kernel.py wbody/gbody)",
        )
    elif dt.itemsize == 8:
        ctx.violate(
            "dtype64", where,
            f"loop carry {i} is 64-bit ({dt}) — banned in consensus kernels",
        )


def _carry_lint(ctx, jaxpr, where):
    for k, e in enumerate(jaxpr.eqns):
        nm = e.primitive.name
        ew = f"{where}#{k}:{nm}"
        if nm == "scan":
            cj = e.params["jaxpr"]
            nc, ncar = e.params["num_consts"], e.params["num_carry"]
            for i, v in enumerate(cj.jaxpr.invars[nc:nc + ncar]):
                _check_carry(ctx, v.aval, ew, i)
            _carry_lint(ctx, cj.jaxpr, ew)
        elif nm == "while":
            bj = e.params["body_jaxpr"]
            bn = e.params["body_nconsts"]
            for i, v in enumerate(bj.jaxpr.invars[bn:]):
                _check_carry(ctx, v.aval, ew, i)
            _carry_lint(ctx, bj.jaxpr, ew)
        else:
            for sj in _sub_jaxprs(e):
                _carry_lint(ctx, sj, ew)


def _ref_discipline(ctx, jaxpr, where):
    for cv in jaxpr.constvars:
        aval = cv.aval
        shape = tuple(getattr(aval, "shape", ()) or ())
        n = 1
        for s in shape:
            n *= int(s)
        if shape and n > 1:
            ctx.violate(
                "ref", f"{where}/constvars",
                f"kernel captured an array constant {shape} {aval.dtype}: "
                "Pallas consensus kernels must source every limb constant "
                "from the consts_ref row table (ops/limbs.set_const_provider"
                "), never closure capture",
            )
    _carry_lint(ctx, jaxpr, where)


# ---------------------------------------------------------------------------
# The pallas_call transfer rule.


def _r_pallas_call(interp, eqn, ins, where):
    ctx = interp.ctx
    p = eqn.params
    gm = p["grid_mapping"]
    kj = p["jaxpr"]
    jaxpr = kj.jaxpr if hasattr(kj, "jaxpr") else kj
    consts = list(getattr(kj, "consts", []) or [])
    grid = tuple(int(g) for g in gm.grid)
    nidx = int(getattr(gm, "num_index_operands", 0))
    nin, nout = int(gm.num_inputs), int(gm.num_outputs)
    nscr = int(gm.num_scratch_operands)
    bms = list(gm.block_mappings)

    for s in ins[:nidx]:
        ctx.observe(s, where, "pallas index operand")
    ops = ins[nidx:]

    _check_grid(ctx, grid, bms, nin, nout, where)
    vmem = _vmem_peak(jaxpr, bms, grid, nin, nout)
    if not ctx.mute:
        rep = ctx.report
        rep.vmem_peak_bytes = max(rep.vmem_peak_bytes or 0, vmem)
        if rep.grid is None:
            rep.grid = grid
    if vmem > VMEM_BUDGET_BYTES:
        ctx.violate(
            "vmem", where,
            f"peak VMEM live set {vmem} bytes (blocks x double-buffer + "
            f"scratch + intermediates) exceeds the {VMEM_BUDGET_BYTES}-byte "
            f"budget (core limit ~{VMEM_BYTES}; the margin is Mosaic "
            "spill headroom)",
        )
    _ref_discipline(ctx, jaxpr, where)

    tops = [IV.top(v.aval.shape, v.aval.dtype) for v in eqn.outvars]
    kin = list(jaxpr.invars)
    if len(kin) != nin + nout + nscr or len(ops) < nin:
        ctx.violate(
            "internal", where,
            f"kernel arity mismatch: {len(kin)} invars vs "
            f"{nin}+{nout}+{nscr} declared operands")
        return tops
    if jaxpr.constvars and len(consts) != len(jaxpr.constvars):
        # Already flagged by _ref_discipline; body cannot be evaluated
        # faithfully without the constants.
        return tops

    refs: List[RefAbstract] = []
    for i in range(nin):
        aval = kin[i].aval
        refs.append(RefAbstract(
            _origin(bms[i], i), "in", aval.shape, aval.dtype,
            init=_block_abs(ops[i], aval)))
    for j in range(nout):
        aval = kin[nin + j].aval
        refs.append(RefAbstract(
            _origin(bms[nin + j], nin + j), "out", aval.shape, aval.dtype))
    for s in range(nscr):
        aval = kin[nin + nout + s].aval
        refs.append(RefAbstract(
            f"scratch{s}", "scratch", aval.shape, aval.dtype))

    closed = jax_core.ClosedJaxpr(jaxpr, consts)
    _GRID_STACK.append(grid)
    try:
        interp.eval_closed(closed, list(refs), where + "/kernel")
    finally:
        _GRID_STACK.pop()

    # Read-before-write findings are recorded on first encounter (even
    # under fixpoint warmup, where ctx.violate is muted — program order
    # of the first abstract pass matches the first concrete iteration).
    for ref in refs:
        for slot, rw in sorted(ref.rbw.items()):
            ctx.violate(
                "ref", rw,
                f"read of {ref.kind} ref `{ref.name}` row {slot} before any "
                "write: uninitialized VMEM must not feed a consensus "
                "verdict",
            )

    outs = []
    for j in range(nout):
        ref = refs[nin + j]
        missing = [s for s in range(ref.gran) if ref.rows[s] is None]
        if missing:
            ctx.violate(
                "ref", f"{where}/kernel",
                f"output ref `{ref.name}` rows {missing} are never written",
            )
        out_aval = eqn.outvars[j].aval
        outs.append(ref.to_array(out_aval.shape, out_aval.dtype))
    return outs


def _block_abs(op: "IV.AbstractArray", aval) -> "IV.AbstractArray":
    """Slice an operand abstraction down to one block: axes the block
    spans fully keep their tracked rows, partial axes join (sound for
    every grid step, since the hull covers the whole operand)."""
    shape = tuple(int(s) for s in aval.shape)
    if op is None:
        return IV.full_range(shape, aval.dtype)
    keep0 = bool(op.shape and shape and op.shape[0] == shape[0])
    keep1 = bool(len(op.shape) > 1 and len(shape) > 1
                 and op.shape[1] == shape[1])
    return IV.take_axes(op, shape, 0 if keep0 else None,
                        1 if keep1 else None)


IV.RULES["get"] = _r_get
IV.RULES["swap"] = _r_swap
IV.RULES["addupdate"] = _r_addupdate
IV.RULES["program_id"] = _r_program_id
IV.RULES["pallas_call"] = _r_pallas_call

# The state primitives move values without float arithmetic (get/swap
# return the refs' own certificates; addupdate and pallas_call results
# are re-checked at the ref layer above), so they preserve the carried
# exact-float certificate rather than demoting it.
IV.FLOAT_VETTED.update({"get", "swap", "addupdate", "pallas_call",
                        "program_id"})


# ---------------------------------------------------------------------------
# Toy kernels: the gate must demonstrably fire. Each builder returns
# (fn, arg_specs, in_bounds); shapes are trace-only (never compiled).

_TOY_TILE = 128


def _toy_specs(rows, tile, index_map=None):
    from jax.experimental import pallas as pl

    return pl.BlockSpec((rows, tile), index_map or (lambda i: (0, i)))


def _build_positive():
    from jax.experimental import pallas as pl

    def kern(x_ref, o_ref):
        o_ref[:] = x_ref[:] + 1

    def fn(x):
        return pl.pallas_call(
            kern, grid=(2,),
            in_specs=[_toy_specs(8, _TOY_TILE)],
            out_specs=_toy_specs(8, _TOY_TILE),
            out_shape=jax.ShapeDtypeStruct((8, 2 * _TOY_TILE), jnp.int32),
        )(x)

    args = (jax.ShapeDtypeStruct((8, 2 * _TOY_TILE), jnp.int32),)
    return fn, args, {0: (0, 100)}


def _build_oob_index_map():
    from jax.experimental import pallas as pl

    def kern(x_ref, o_ref):
        o_ref[:] = x_ref[:]

    def fn(x):
        return pl.pallas_call(
            kern, grid=(2,),
            # Off-by-one block index: the last grid step's window escapes.
            in_specs=[_toy_specs(8, _TOY_TILE, lambda i: (0, i + 1))],
            out_specs=_toy_specs(8, _TOY_TILE),
            out_shape=jax.ShapeDtypeStruct((8, 2 * _TOY_TILE), jnp.int32),
        )(x)

    args = (jax.ShapeDtypeStruct((8, 2 * _TOY_TILE), jnp.int32),)
    return fn, args, {0: (0, 100)}


def _build_read_before_write():
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kern(x_ref, o_ref, s_ref):
        # s_ref row 0 is read but never written anywhere.
        o_ref[:] = x_ref[:] + s_ref[0][None, :]

    def fn(x):
        return pl.pallas_call(
            kern, grid=(2,),
            in_specs=[_toy_specs(8, _TOY_TILE)],
            out_specs=_toy_specs(8, _TOY_TILE),
            out_shape=jax.ShapeDtypeStruct((8, 2 * _TOY_TILE), jnp.int32),
            scratch_shapes=[pltpu.VMEM((4, _TOY_TILE), jnp.int32)],
        )(x)

    args = (jax.ShapeDtypeStruct((8, 2 * _TOY_TILE), jnp.int32),)
    return fn, args, {0: (0, 100)}


def _build_mul_overflow():
    from jax.experimental import pallas as pl
    from ..ops import limbs as L

    def kern(x_ref, o_ref):
        # fe_mul's convolution is int32-safe only under the 13-bit weak
        # contract; 14-bit inputs without a canon overflow it.
        o_ref[:] = L.fe_mul(x_ref[:], x_ref[:])

    def fn(x):
        return pl.pallas_call(
            kern, grid=(2,),
            in_specs=[_toy_specs(L.NLIMB, _TOY_TILE)],
            out_specs=_toy_specs(L.NLIMB, _TOY_TILE),
            out_shape=jax.ShapeDtypeStruct(
                (L.NLIMB, 2 * _TOY_TILE), jnp.int32),
        )(x)

    args = (jax.ShapeDtypeStruct((L.NLIMB, 2 * _TOY_TILE), jnp.int32),)
    return fn, args, {0: [(0, (1 << 14) - 1)] * L.NLIMB}


def _build_double_write():
    from jax.experimental import pallas as pl

    def kern(x_ref, o_ref):
        o_ref[:] = x_ref[:]

    def fn(x):
        return pl.pallas_call(
            kern, grid=(2,),
            in_specs=[_toy_specs(8, _TOY_TILE)],
            # Both grid steps write output block 0; block 1 never written.
            out_specs=_toy_specs(8, _TOY_TILE, lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((8, 2 * _TOY_TILE), jnp.int32),
        )(x)

    args = (jax.ShapeDtypeStruct((8, 2 * _TOY_TILE), jnp.int32),)
    return fn, args, {0: (0, 100)}


def _build_f32_default_precision_dot():
    from jax.experimental import pallas as pl

    def kern(x_ref, o_ref):
        xf = x_ref[:].astype(jnp.float32)
        # Missing precision=HIGHEST: the MXU's default f32 path goes
        # through bfloat16 passes, so the products may round.
        y = jax.lax.dot_general(xf, xf, (((1,), (1,)), ((), ())))
        o_ref[:] = jnp.broadcast_to(y.astype(jnp.int32)[:, :1],
                                    (8, _TOY_TILE))

    def fn(x):
        return pl.pallas_call(
            kern, grid=(2,),
            in_specs=[_toy_specs(8, _TOY_TILE)],
            out_specs=_toy_specs(8, _TOY_TILE),
            out_shape=jax.ShapeDtypeStruct((8, 2 * _TOY_TILE), jnp.int32),
        )(x)

    args = (jax.ShapeDtypeStruct((8, 2 * _TOY_TILE), jnp.int32),)
    # Sigma|products| = 128 * 100^2 well below 2^24: the ONLY defect is
    # the missing precision keyword.
    return fn, args, {0: (0, 100)}


def _build_f32_accum_overflow():
    from jax.experimental import pallas as pl

    def kern(x_ref, o_ref):
        xf = x_ref[:].astype(jnp.float32)
        # HIGHEST precision, every product exact (512^2 = 2^18), but the
        # accumulated sum 128 * 2^18 = 2^25 exceeds the f32 mantissa.
        y = jax.lax.dot_general(xf, xf, (((1,), (1,)), ((), ())),
                                precision=jax.lax.Precision.HIGHEST)
        o_ref[:] = jnp.broadcast_to(y.astype(jnp.int32)[:, :1],
                                    (8, _TOY_TILE))

    def fn(x):
        return pl.pallas_call(
            kern, grid=(2,),
            in_specs=[_toy_specs(8, _TOY_TILE)],
            out_specs=_toy_specs(8, _TOY_TILE),
            out_shape=jax.ShapeDtypeStruct((8, 2 * _TOY_TILE), jnp.int32),
        )(x)

    args = (jax.ShapeDtypeStruct((8, 2 * _TOY_TILE), jnp.int32),)
    return fn, args, {0: (0, 1 << 9)}


def _build_f32_unvetted_roundtrip():
    from jax.experimental import pallas as pl

    def kern(x_ref, o_ref):
        xf = x_ref[:].astype(jnp.float32)
        # integer_pow is on the determinism allowlist but has no vetted
        # exact-float transfer: the certificate must demote here and the
        # astype(int32) round-trip must fail with a sourced diagnostic.
        y = xf ** 2
        o_ref[:] = y.astype(jnp.int32)

    def fn(x):
        return pl.pallas_call(
            kern, grid=(2,),
            in_specs=[_toy_specs(8, _TOY_TILE)],
            out_specs=_toy_specs(8, _TOY_TILE),
            out_shape=jax.ShapeDtypeStruct((8, 2 * _TOY_TILE), jnp.int32),
        )(x)

    args = (jax.ShapeDtypeStruct((8, 2 * _TOY_TILE), jnp.int32),)
    return fn, args, {0: (0, 100)}


NEGATIVES = {
    "oob-index-map": _build_oob_index_map,
    "read-before-write": _build_read_before_write,
    "mul-overflow-no-canon": _build_mul_overflow,
    "double-write": _build_double_write,
    "f32-default-precision-dot": _build_f32_default_precision_dot,
    "f32-accum-overflow": _build_f32_accum_overflow,
    "f32-unvetted-roundtrip": _build_f32_unvetted_roundtrip,
}


def analyze_negative(name: str) -> "IV.Report":
    """Analyze one deliberately broken toy kernel; the report must come
    back not-ok or the gate is dead."""
    fn, args, in_bounds = NEGATIVES[name]()
    return IV.analyze(fn, args, f"pallas.negative.{name}",
                      in_bounds=in_bounds)


def analyze_positive_toy() -> "IV.Report":
    """A minimal clean Pallas kernel: proves the machinery end to end
    without paying for the real verify kernel."""
    fn, args, in_bounds = _build_positive()
    return IV.analyze(fn, args, "pallas.toy", in_bounds=in_bounds)
