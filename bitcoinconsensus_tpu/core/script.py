"""Script container: opcodes, decoding, pattern predicates, CScriptNum.

Host-side equivalent of the reference's `script/script.{h,cpp}`: the opcode
enum (`script.h:65-205`), consensus limits (`script.h:23-56`), push decoding
(`script.cpp:283-333` GetScriptOp), pattern tests (`script.cpp:201-256`),
OP_SUCCESSx classification (`script.cpp:335-341`), legacy sigop counting
(`script.cpp:153-199`) and the minimal-encoding int64 `CScriptNum`
(`script.h:218-391`).

Scripts are plain `bytes` here — the structure lives in the decoder, not in
a container class; this keeps the hot host loop allocation-light.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

__all__ = [
    "ScriptNumError",
    "script_num_decode",
    "script_num_encode",
    "decode_op",
    "iter_ops",
    "is_p2sh",
    "is_witness_program",
    "is_push_only",
    "is_op_success",
    "is_unspendable",
    "check_minimal_push",
    "get_sig_op_count",
    "witness_sig_ops",
    "find_and_delete",
    "push_data",
]

# --- consensus limits (script.h:23-56) -------------------------------------
MAX_SCRIPT_ELEMENT_SIZE = 520
MAX_OPS_PER_SCRIPT = 201
MAX_PUBKEYS_PER_MULTISIG = 20
MAX_SCRIPT_SIZE = 10000
MAX_STACK_SIZE = 1000
LOCKTIME_THRESHOLD = 500_000_000
ANNEX_TAG = 0x50
VALIDATION_WEIGHT_PER_SIGOP_PASSED = 50
VALIDATION_WEIGHT_OFFSET = 50

# --- opcodes (script.h:65-205) ---------------------------------------------
OP_0 = 0x00
OP_FALSE = OP_0
OP_PUSHDATA1 = 0x4C
OP_PUSHDATA2 = 0x4D
OP_PUSHDATA4 = 0x4E
OP_1NEGATE = 0x4F
OP_RESERVED = 0x50
OP_1 = 0x51
OP_TRUE = OP_1
OP_2 = 0x52
OP_3 = 0x53
OP_4 = 0x54
OP_5 = 0x55
OP_6 = 0x56
OP_7 = 0x57
OP_8 = 0x58
OP_9 = 0x59
OP_10 = 0x5A
OP_11 = 0x5B
OP_12 = 0x5C
OP_13 = 0x5D
OP_14 = 0x5E
OP_15 = 0x5F
OP_16 = 0x60

# control
OP_NOP = 0x61
OP_VER = 0x62
OP_IF = 0x63
OP_NOTIF = 0x64
OP_VERIF = 0x65
OP_VERNOTIF = 0x66
OP_ELSE = 0x67
OP_ENDIF = 0x68
OP_VERIFY = 0x69
OP_RETURN = 0x6A

# stack ops
OP_TOALTSTACK = 0x6B
OP_FROMALTSTACK = 0x6C
OP_2DROP = 0x6D
OP_2DUP = 0x6E
OP_3DUP = 0x6F
OP_2OVER = 0x70
OP_2ROT = 0x71
OP_2SWAP = 0x72
OP_IFDUP = 0x73
OP_DEPTH = 0x74
OP_DROP = 0x75
OP_DUP = 0x76
OP_NIP = 0x77
OP_OVER = 0x78
OP_PICK = 0x79
OP_ROLL = 0x7A
OP_ROT = 0x7B
OP_SWAP = 0x7C
OP_TUCK = 0x7D

# splice ops
OP_CAT = 0x7E
OP_SUBSTR = 0x7F
OP_LEFT = 0x80
OP_RIGHT = 0x81
OP_SIZE = 0x82

# bit logic
OP_INVERT = 0x83
OP_AND = 0x84
OP_OR = 0x85
OP_XOR = 0x86
OP_EQUAL = 0x87
OP_EQUALVERIFY = 0x88
OP_RESERVED1 = 0x89
OP_RESERVED2 = 0x8A

# numeric
OP_1ADD = 0x8B
OP_1SUB = 0x8C
OP_2MUL = 0x8D
OP_2DIV = 0x8E
OP_NEGATE = 0x8F
OP_ABS = 0x90
OP_NOT = 0x91
OP_0NOTEQUAL = 0x92
OP_ADD = 0x93
OP_SUB = 0x94
OP_MUL = 0x95
OP_DIV = 0x96
OP_MOD = 0x97
OP_LSHIFT = 0x98
OP_RSHIFT = 0x99
OP_BOOLAND = 0x9A
OP_BOOLOR = 0x9B
OP_NUMEQUAL = 0x9C
OP_NUMEQUALVERIFY = 0x9D
OP_NUMNOTEQUAL = 0x9E
OP_LESSTHAN = 0x9F
OP_GREATERTHAN = 0xA0
OP_LESSTHANOREQUAL = 0xA1
OP_GREATERTHANOREQUAL = 0xA2
OP_MIN = 0xA3
OP_MAX = 0xA4
OP_WITHIN = 0xA5

# crypto
OP_RIPEMD160 = 0xA6
OP_SHA1 = 0xA7
OP_SHA256 = 0xA8
OP_HASH160 = 0xA9
OP_HASH256 = 0xAA
OP_CODESEPARATOR = 0xAB
OP_CHECKSIG = 0xAC
OP_CHECKSIGVERIFY = 0xAD
OP_CHECKMULTISIG = 0xAE
OP_CHECKMULTISIGVERIFY = 0xAF

# expansion
OP_NOP1 = 0xB0
OP_CHECKLOCKTIMEVERIFY = 0xB1
OP_NOP2 = OP_CHECKLOCKTIMEVERIFY
OP_CHECKSEQUENCEVERIFY = 0xB2
OP_NOP3 = OP_CHECKSEQUENCEVERIFY
OP_NOP4 = 0xB3
OP_NOP5 = 0xB4
OP_NOP6 = 0xB5
OP_NOP7 = 0xB6
OP_NOP8 = 0xB7
OP_NOP9 = 0xB8
OP_NOP10 = 0xB9

# BIP342
OP_CHECKSIGADD = 0xBA

OP_INVALIDOPCODE = 0xFF

class ScriptNumError(Exception):
    """CScriptNum overflow / non-minimal encoding (script.h:227-240 throws)."""


def script_num_decode(
    data: bytes, require_minimal: bool, max_size: int = 4
) -> int:
    """Decode a stack element as CScriptNum (script.h:222-251, 296-330).

    Little-endian sign-magnitude; rejects encodings longer than ``max_size``
    and, when ``require_minimal``, encodings with a redundant leading byte.
    """
    if len(data) > max_size:
        raise ScriptNumError("script number overflow")
    if require_minimal and len(data) > 0:
        # script.h:230-239: top byte must carry information.
        if data[-1] & 0x7F == 0:
            if len(data) <= 1 or not (data[-2] & 0x80):
                raise ScriptNumError("non-minimally encoded script number")
    if not data:
        return 0
    result = int.from_bytes(data, "little")
    if data[-1] & 0x80:
        # Clear the sign bit and negate.
        result &= ~(0x80 << (8 * (len(data) - 1)))
        return -result
    return result


def script_num_encode(n: int) -> bytes:
    """Serialize an int64 as minimal CScriptNum (script.h:332-360)."""
    if n == 0:
        return b""
    negative = n < 0
    absvalue = -n if negative else n
    out = bytearray()
    while absvalue:
        out.append(absvalue & 0xFF)
        absvalue >>= 8
    # If the MSB is set, an extra byte carries the sign; else fold it in.
    if out[-1] & 0x80:
        out.append(0x80 if negative else 0x00)
    elif negative:
        out[-1] |= 0x80
    return bytes(out)


def script_num_to_bool(data: bytes) -> bool:
    """CastToBool (interpreter.cpp:36-48): any nonzero byte → true, except
    negative zero (0x80 in the top position alone)."""
    for i, b in enumerate(data):
        if b != 0:
            return not (i == len(data) - 1 and b == 0x80)
    return False


def decode_op(script: bytes, pos: int) -> Tuple[Optional[int], Optional[bytes], int]:
    """Decode one opcode at ``pos`` → (opcode, pushdata|None, next_pos).

    Mirrors GetScriptOp (script.cpp:283-333): returns opcode=None on a
    truncated push (the interpreter maps that to BAD_OPCODE).
    """
    opcode = script[pos]
    pos += 1
    if opcode > OP_PUSHDATA4:
        return opcode, None, pos

    if opcode < OP_PUSHDATA1:
        size = opcode
    elif opcode == OP_PUSHDATA1:
        if pos + 1 > len(script):
            return None, None, pos
        size = script[pos]
        pos += 1
    elif opcode == OP_PUSHDATA2:
        if pos + 2 > len(script):
            return None, None, pos
        size = int.from_bytes(script[pos : pos + 2], "little")
        pos += 2
    else:  # OP_PUSHDATA4
        if pos + 4 > len(script):
            return None, None, pos
        size = int.from_bytes(script[pos : pos + 4], "little")
        pos += 4
    if pos + size > len(script):
        return None, None, pos
    return opcode, script[pos : pos + size], pos + size


def iter_ops(script: bytes) -> Iterator[Tuple[Optional[int], Optional[bytes]]]:
    """Iterate (opcode, data) pairs; yields (None, None) once on corruption."""
    pos = 0
    while pos < len(script):
        opcode, data, pos = decode_op(script, pos)
        yield opcode, data
        if opcode is None:
            return


def push_data(data: bytes) -> bytes:
    """Encode a data push exactly as CScript::operator<<(vector) does
    (script.h:442-464): direct-push/PUSHDATA only, NO folding into
    OP_0/OP_1..OP_16. FindAndDelete and the P2SH-witness malleability check
    both compare against this exact encoding."""
    n = len(data)
    if n < OP_PUSHDATA1:
        return bytes([n]) + data
    if n <= 0xFF:
        return bytes([OP_PUSHDATA1, n]) + data
    if n <= 0xFFFF:
        return bytes([OP_PUSHDATA2]) + n.to_bytes(2, "little") + data
    return bytes([OP_PUSHDATA4]) + n.to_bytes(4, "little") + data


def check_minimal_push(data: bytes, opcode: int) -> bool:
    """CheckMinimalPush (interpreter.cpp:228-251)."""
    assert 0 <= opcode <= OP_PUSHDATA4
    if len(data) == 0:
        return opcode == OP_0
    if len(data) == 1 and 1 <= data[0] <= 16:
        return False  # should have used OP_1..OP_16
    if len(data) == 1 and data[0] == 0x81:
        return False  # should have used OP_1NEGATE
    if len(data) <= 75:
        return opcode == len(data)
    if len(data) <= 255:
        return opcode == OP_PUSHDATA1
    if len(data) <= 65535:
        return opcode == OP_PUSHDATA2
    return True


# --- pattern predicates (script.cpp:201-256) --------------------------------

def is_p2sh(script: bytes) -> bool:
    return (
        len(script) == 23
        and script[0] == OP_HASH160
        and script[1] == 0x14
        and script[22] == OP_EQUAL
    )


def is_witness_program(script: bytes) -> Optional[Tuple[int, bytes]]:
    """Return (version, program) if the script is a witness program
    (script.cpp:220-234), else None."""
    if len(script) < 4 or len(script) > 42:
        return None
    if script[0] != OP_0 and not (OP_1 <= script[0] <= OP_16):
        return None
    if script[1] + 2 == len(script):
        version = 0 if script[0] == OP_0 else script[0] - OP_1 + 1
        return version, script[2:]
    return None


def is_push_only(script: bytes) -> bool:
    """script.cpp:236-250: every op ≤ OP_16 (push-class)."""
    pos = 0
    while pos < len(script):
        opcode, _, pos = decode_op(script, pos)
        if opcode is None or opcode > OP_16:
            return False
    return True


def is_unspendable(script: bytes) -> bool:
    return (len(script) > 0 and script[0] == OP_RETURN) or len(script) > MAX_SCRIPT_SIZE


def is_op_success(opcode: int) -> bool:
    """Tapscript OP_SUCCESSx set (script.cpp:335-341 / BIP342)."""
    return (
        opcode == 0x50
        or opcode == 0x62
        or 0x7E <= opcode <= 0x81
        or 0x83 <= opcode <= 0x86
        or 0x89 <= opcode <= 0x8A
        or 0x8D <= opcode <= 0x8E
        or 0x95 <= opcode <= 0x99
        or 0xBB <= opcode <= 0xFE
    )


def _decode_op_n(opcode: int) -> int:
    if opcode == OP_0:
        return 0
    assert OP_1 <= opcode <= OP_16
    return opcode - (OP_1 - 1)


def get_sig_op_count(script: bytes, accurate: bool) -> int:
    """Legacy sigop counting (script.cpp:153-177)."""
    n = 0
    last_opcode = OP_INVALIDOPCODE
    pos = 0
    while pos < len(script):
        opcode, _, pos = decode_op(script, pos)
        if opcode is None:
            break
        if opcode in (OP_CHECKSIG, OP_CHECKSIGVERIFY):
            n += 1
        elif opcode in (OP_CHECKMULTISIG, OP_CHECKMULTISIGVERIFY):
            if accurate and OP_1 <= last_opcode <= OP_16:
                n += _decode_op_n(last_opcode)
            else:
                n += MAX_PUBKEYS_PER_MULTISIG
        last_opcode = opcode
    return n


def witness_sig_ops(witness_version: int, witness_program: bytes, witness: List[bytes]) -> int:
    """Witness sigop counting (interpreter.cpp:2058-2103 WitnessSigOps)."""
    if witness_version == 0:
        if len(witness_program) == 20:
            return 1
        if len(witness_program) == 32 and witness:
            return get_sig_op_count(witness[-1], True)
    return 0


def find_and_delete(script: bytes, needle: bytes) -> Tuple[bytes, int]:
    """FindAndDelete (interpreter.cpp:253-279): remove every *opcode-aligned*
    occurrence of the serialized push ``needle`` from ``script``.

    Returns (new_script, n_found). Consensus-critical quirk: matching is on
    raw serialized bytes at opcode boundaries, and overlapping repeats are
    skipped byte-for-byte the way the reference's do/while does.
    """
    if not needle:
        return script, 0
    out = bytearray()
    n_found = 0
    pos = 0
    last = 0
    while pos < len(script):
        # Append the segment before this opcode boundary.
        out += script[last:pos]
        # Skip every consecutive occurrence starting exactly here.
        while script[pos : pos + len(needle)] == needle:
            pos += len(needle)
            n_found += 1
        last = pos
        opcode, _, pos = decode_op(script, pos) if pos < len(script) else (None, None, pos)
        if opcode is None:
            break
    out += script[last:]
    return bytes(out), n_found
