"""Block primitives: header/block codec, merkle roots, context-free checks.

Host-side equivalent of the reference's vendored block layer — the shapes
the block-replay north star needs (SURVEY §2.3, §3.5):

- `BlockHeader`/`Block` wire codec (`primitives/block.h:20-90`),
- `merkle_root` with CVE-2012-2459 mutation detection
  (`consensus/merkle.cpp:45-64`), witness merkle root
  (`consensus/merkle.cpp` BlockWitnessMerkleRoot: coinbase wtxid pinned
  to zero),
- compact-bits target decode + proof-of-work check
  (`arith_uint256.cpp` SetCompact, `pow.cpp` CheckProofOfWork),
- `check_block`: the context-free CheckBlock rules
  (`validation.cpp:3402-3474` — merkle, size limits, coinbase placement,
  per-tx CheckTransaction, legacy-sigop cap),
- witness commitment discovery and validation
  (`consensus/validation.h:161-179` GetWitnessCommitmentIndex,
  `validation.cpp:3385-3428` ContextualCheckBlock witness rules).

Like the reference, all hashes are held in wire byte order.
"""

from __future__ import annotations

import os
import struct
from dataclasses import dataclass
from typing import List, Optional, Tuple

from .script import OP_RETURN, get_sig_op_count
from .serialize import ByteReader, SerializationError, write_compact_size
from .tx import Tx
from .tx_check import MAX_BLOCK_WEIGHT, WITNESS_SCALE_FACTOR, check_transaction
from ..utils.hashes import sha256d

__all__ = [
    "BlockHeader",
    "Block",
    "merkle_root",
    "merkle_root_device",
    "block_merkle_root",
    "block_witness_merkle_root",
    "bits_to_target",
    "check_proof_of_work",
    "check_block",
    "witness_commitment_index",
    "check_witness_commitment",
    "MAX_BLOCK_SIGOPS_COST",
    "POW_LIMIT_MAINNET",
]

MAX_BLOCK_SIGOPS_COST = 80_000  # consensus/consensus.h:17
MIN_WITNESS_COMMITMENT = 38  # consensus/validation.h:19
# chainparams.cpp mainnet powLimit.
POW_LIMIT_MAINNET = 0x00000000FFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFF


@dataclass
class BlockHeader:
    """80-byte block header (primitives/block.h:20-72)."""

    version: int
    prev_hash: bytes  # 32 bytes, wire order
    merkle_root: bytes  # 32 bytes, wire order
    time: int
    bits: int
    nonce: int

    def serialize(self) -> bytes:
        return (
            struct.pack("<i", self.version)
            + self.prev_hash
            + self.merkle_root
            + struct.pack("<III", self.time, self.bits, self.nonce)
        )

    @classmethod
    def deserialize(cls, r: ByteReader) -> "BlockHeader":
        version = r.read_i32()
        prev_hash = r.read(32)
        merkle = r.read(32)
        time = r.read_u32()
        bits = r.read_u32()
        nonce = r.read_u32()
        return cls(version, prev_hash, merkle, time, bits, nonce)

    @property
    def hash(self) -> bytes:
        """Double-SHA256 of the 80-byte header (wire order)."""
        return sha256d(self.serialize())

    @property
    def hash_hex(self) -> str:
        return self.hash[::-1].hex()


class Block:
    """Header + transactions (primitives/block.h:75-90)."""

    __slots__ = ("header", "vtx", "_native")  # _native: cached NativeBlock

    def __init__(self, header: BlockHeader, vtx: List[Tx]):
        self.header = header
        self.vtx = vtx

    def __getstate__(self):
        # The cached native parse is a raw C++ handle — drop it from
        # pickles/copies; models/validate.py re-parses on demand.
        return (self.header, self.vtx)

    def __setstate__(self, state):
        self.header, self.vtx = state

    @classmethod
    def deserialize(cls, data: bytes) -> "Block":
        r = ByteReader(data)
        header = BlockHeader.deserialize(r)
        n = r.read_compact_size()
        vtx = [Tx._deserialize_from(r) for _ in range(n)]
        if r.remaining():
            raise SerializationError("trailing data after block")
        return cls(header, vtx)

    def serialize(self, include_witness: bool = True) -> bytes:
        parts = [self.header.serialize(), write_compact_size(len(self.vtx))]
        for tx in self.vtx:
            parts.append(tx.serialize(include_witness=include_witness))
        return b"".join(parts)

    @property
    def hash(self) -> bytes:
        return self.header.hash


def merkle_root(hashes: List[bytes]) -> Tuple[bytes, bool]:
    """(root, mutated) over 32-byte leaf hashes (consensus/merkle.cpp:45-64).

    Bitcoin's odd-count duplication rule makes certain duplicate-leaf lists
    collide (CVE-2012-2459); `mutated` flags any level that hashes two
    identical siblings, which callers must treat as an invalid block.
    """
    if not hashes:
        return b"\x00" * 32, False
    level = list(hashes)
    mutated = False
    while len(level) > 1:
        for pos in range(0, len(level) - 1, 2):
            if level[pos] == level[pos + 1]:
                mutated = True
        if len(level) & 1:
            level.append(level[-1])
        level = [
            sha256d(level[i] + level[i + 1]) for i in range(0, len(level), 2)
        ]
    return level[0], mutated


def merkle_root_device(hashes: List[bytes]) -> Tuple[bytes, bool]:
    """`merkle_root` computed on device via the batched SHA-256 kernel
    (`ops/sha256.sha256d_fixed`): every level is one lane-parallel
    double-SHA over (n/2, 64)-byte pairs, levels chained device-side with
    a single readback at the root. Bit-identical to the host version
    (asserted by tests/test_ops_sha256.py), including the CVE-2012-2459
    `mutated` flag with the host's exact don't-count-the-odd-duplicate
    semantics.

    When to use which: each level's shape compiles once, so this pays off
    for recurring block sizes on co-located chips where dispatch is ~µs;
    over a high-RTT tunnel the single readback still costs one link
    round-trip, which exceeds the ~1 ms the native/host path needs for a
    whole mainnet block. `check_block(device_merkle=True)` or
    BITCOINCONSENSUS_TPU_DEVICE_MERKLE=1 selects it.
    """
    import numpy as np
    import jax
    import jax.numpy as jnp

    from ..ops.sha256 import sha256d_fixed

    if not hashes:
        return b"\x00" * 32, False
    level = jnp.asarray(
        np.frombuffer(b"".join(hashes), dtype=np.uint8).reshape(len(hashes), 32)
    )
    mutated = jnp.zeros((), dtype=bool)
    while level.shape[0] > 1:
        n = level.shape[0]
        # merkle.cpp:45-64 checks sibling equality BEFORE duplicating the
        # odd tail, so the synthetic last pair never counts as mutation.
        n_even = n & ~1
        eq = jnp.all(
            level[0:n_even:2] == level[1:n_even:2], axis=1
        )
        mutated = mutated | jnp.any(eq)
        if n & 1:
            level = jnp.concatenate([level, level[-1:]], axis=0)
            n += 1
        level = sha256d_fixed(level.reshape(n // 2, 64))
    # ONE readback for root + flag (a second blocking fetch would double
    # the link-latency cost this path exists to amortize).
    root_np, mut_np = jax.device_get((level[0], mutated))
    return bytes(root_np), bool(mut_np)


def block_merkle_root(block: Block) -> Tuple[bytes, bool]:
    """BlockMerkleRoot: txid leaves (consensus/merkle.cpp:66-73)."""
    return merkle_root([tx.txid for tx in block.vtx])


def block_witness_merkle_root(block: Block) -> Tuple[bytes, bool]:
    """BlockWitnessMerkleRoot: wtxid leaves with the coinbase pinned to
    zero (consensus/merkle.cpp:75-84)."""
    leaves = [b"\x00" * 32] + [tx.wtxid for tx in block.vtx[1:]]
    return merkle_root(leaves)


def bits_to_target(bits: int) -> Tuple[int, bool, bool]:
    """Compact encoding -> (target, negative, overflow)
    (arith_uint256.cpp SetCompact)."""
    size = bits >> 24
    word = bits & 0x007FFFFF
    if size <= 3:
        target = word >> (8 * (3 - size))
    else:
        target = word << (8 * (size - 3))
    negative = word != 0 and (bits & 0x00800000) != 0
    overflow = word != 0 and (
        size > 34 or (word > 0xFF and size > 33) or (word > 0xFFFF and size > 32)
    )
    return target, negative, overflow


def check_proof_of_work(
    header_hash: bytes, bits: int, pow_limit: int = POW_LIMIT_MAINNET
) -> bool:
    """CheckProofOfWork (pow.cpp:74-90); hash in wire order."""
    target, negative, overflow = bits_to_target(bits)
    if negative or target == 0 or overflow or target > pow_limit:
        return False
    return int.from_bytes(header_hash, "little") <= target


def witness_commitment_index(block: Block) -> int:
    """Last coinbase output carrying the BIP141 commitment header, or -1
    (consensus/validation.h:161-179)."""
    commitpos = -1
    if block.vtx:
        for o, txout in enumerate(block.vtx[0].vout):
            spk = txout.script_pubkey
            if (
                len(spk) >= MIN_WITNESS_COMMITMENT
                and spk[0] == OP_RETURN
                and spk[1:6] == b"\x24\xaa\x21\xa9\xed"
            ):
                commitpos = o
    return commitpos


def check_witness_commitment(block: Block) -> Tuple[bool, Optional[str]]:
    """BIP141 witness-commitment rules from ContextualCheckBlock
    (validation.cpp:3385-3428): if a commitment output exists, the coinbase
    witness must be exactly one 32-byte reserved value and
    SHA256d(witness_root || reserved) must equal the committed bytes; with
    no commitment, no transaction may carry witness data."""
    commitpos = witness_commitment_index(block)
    if commitpos != -1:
        coinbase = block.vtx[0]
        if not coinbase.vin:
            # Standalone callers may skip CheckBlock's CheckTransaction
            # (which guarantees a coinbase input exists).
            return False, "bad-witness-nonce-size"
        witness = coinbase.vin[0].witness
        if len(witness) != 1 or len(witness[0]) != 32:
            return False, "bad-witness-nonce-size"
        root, _ = block_witness_merkle_root(block)
        expect = sha256d(root + witness[0])
        commit = block.vtx[0].vout[commitpos].script_pubkey[6:38]
        if expect != commit:
            return False, "bad-witness-merkle-match"
        return True, None
    for tx in block.vtx:
        if tx.has_witness():
            return False, "unexpected-witness"
    return True, None


def check_block(
    block: Block,
    check_pow: bool = True,
    check_merkle: bool = True,
    pow_limit: int = POW_LIMIT_MAINNET,
    device_merkle: Optional[bool] = None,
) -> Tuple[bool, Optional[str]]:
    """Context-free CheckBlock (validation.cpp:3402-3474).

    Returns (ok, reject-reason); reasons match the reference's strings.
    Witness rules are contextual in the reference (segwit activation); use
    `check_witness_commitment` alongside for post-segwit blocks.
    `device_merkle` selects the batched device SHA-256 merkle backend
    (default: BITCOINCONSENSUS_TPU_DEVICE_MERKLE env; see
    `merkle_root_device` for when it pays off).
    """
    if check_pow and not check_proof_of_work(block.hash, block.header.bits, pow_limit):
        return False, "high-hash"

    if check_merkle:
        if device_merkle is None:
            device_merkle = os.environ.get(
                "BITCOINCONSENSUS_TPU_DEVICE_MERKLE", ""
            ) in ("1", "on")
        root_fn = merkle_root_device if device_merkle else merkle_root
        root, mutated = root_fn([tx.txid for tx in block.vtx])
        if block.header.merkle_root != root:
            return False, "bad-txnmrklroot"
        if mutated:
            return False, "bad-txns-duplicate"

    if (
        not block.vtx
        or len(block.vtx) * WITNESS_SCALE_FACTOR > MAX_BLOCK_WEIGHT
        or len(block.serialize(include_witness=False)) * WITNESS_SCALE_FACTOR
        > MAX_BLOCK_WEIGHT
    ):
        return False, "bad-blk-length"

    if not block.vtx[0].is_coinbase():
        return False, "bad-cb-missing"
    for tx in block.vtx[1:]:
        if tx.is_coinbase():
            return False, "bad-cb-multiple"

    for tx in block.vtx:
        ok, reason = check_transaction(tx)
        if not ok:
            return False, reason

    sigops = 0
    for tx in block.vtx:
        for txin in tx.vin:
            sigops += get_sig_op_count(txin.script_sig, accurate=False)
        for txout in tx.vout:
            sigops += get_sig_op_count(txout.script_pubkey, accurate=False)
    if sigops * WITNESS_SCALE_FACTOR > MAX_BLOCK_SIGOPS_COST:
        return False, "bad-blk-sigops"

    return True, None
